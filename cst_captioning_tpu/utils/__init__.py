"""Utilities: structured logging, profiling (reference ``utils.py``, row 13).

Step timing lives in :mod:`cst_captioning_tpu.obs.metrics` (``StepMeter``).
"""

from cst_captioning_tpu.utils.logging import EventLogger

__all__ = ["EventLogger"]
