"""jax.profiler trace capture around a window of training steps.

The reference had no profiling at all (SURVEY.md §5 row 1: ad-hoc
``time.time()`` prints); here a trace of N post-compile steps can be captured
to a directory viewable in TensorBoard/Perfetto, wired through
``TrainConfig.profile_dir`` / ``--profile``.

Observability wiring (obs/ package): the capture window opens an obs span on
the ``profiler`` virtual track, so the window shows up on the run timeline
next to the step spans it overlaps, and completion is announced as a
structured ``profiler_trace_written`` event (through the caller's event
logger when given one, and into the obs stream) instead of a stderr print.
"""

from __future__ import annotations

from typing import Callable

import jax

from cst_captioning_tpu import obs


class StepProfiler:
    """Start a trace at step ``skip`` (0-based), stop after ``steps`` more.

    ``tick()`` is called once per finished training step; the first ``skip``
    steps are excluded so jit compilation doesn't dominate the trace. Safe to
    leave in hot loops when disabled (``out_dir=""`` -> every tick is a no-op).

    ``log(event, **fields)`` (an ``EventLogger.log`` works as-is) receives
    the ``profiler_trace_written`` completion event; the obs stream gets a
    copy regardless, so run reports can count capture windows.
    """

    def __init__(self, out_dir: str, steps: int = 10, skip: int = 1,
                 log: Callable[..., None] | None = None):
        self.out_dir = out_dir
        self.steps = steps
        self.skip = skip
        self._log = log
        self._count = 0
        self._active = False
        self._done = not out_dir
        self._span: obs.Span | None = None

    def tick(self) -> None:
        if self._done:
            return
        self._count += 1
        if not self._active and self._count > self.skip:
            jax.profiler.start_trace(self.out_dir)
            self._active = True
            # virtual track: the window spans several steps, so it must not
            # join the caller thread's (properly nested) span stack
            self._span = obs.span(
                "profile.window", track="profiler", dir=self.out_dir
            ).begin()
            self._stop_at = self._count + self.steps
        elif self._active and self._count >= self._stop_at:
            self.stop()

    def stop(self) -> None:
        """Finalize the trace (also called when an epoch ends mid-window)."""
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            if self._span is not None:
                self._span.end()
                self._span = None
            fields = {"dir": self.out_dir, "steps": self.steps}
            obs.event("profiler_trace_written", **fields)
            if self._log is not None:
                self._log("profiler_trace_written", **fields)
        self._done = True
