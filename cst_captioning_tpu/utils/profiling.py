"""jax.profiler trace capture around a window of training steps.

The reference had no profiling at all (SURVEY.md §5 row 1: ad-hoc
``time.time()`` prints); here a trace of N post-compile steps can be captured
to a directory viewable in TensorBoard/Perfetto, wired through
``TrainConfig.profile_dir`` / ``--profile``.
"""

from __future__ import annotations

import sys

import jax


class StepProfiler:
    """Start a trace at step ``skip`` (0-based), stop after ``steps`` more.

    ``tick()`` is called once per finished training step; the first ``skip``
    steps are excluded so jit compilation doesn't dominate the trace. Safe to
    leave in hot loops when disabled (``out_dir=""`` -> every tick is a no-op).
    """

    def __init__(self, out_dir: str, steps: int = 10, skip: int = 1):
        self.out_dir = out_dir
        self.steps = steps
        self.skip = skip
        self._count = 0
        self._active = False
        self._done = not out_dir

    def tick(self) -> None:
        if self._done:
            return
        self._count += 1
        if not self._active and self._count > self.skip:
            jax.profiler.start_trace(self.out_dir)
            self._active = True
            self._stop_at = self._count + self.steps
        elif self._active and self._count >= self._stop_at:
            self.stop()

    def stop(self) -> None:
        """Finalize the trace (also called when an epoch ends mid-window)."""
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            print(f"[profile] trace written to {self.out_dir}", file=sys.stderr)
        self._done = True
