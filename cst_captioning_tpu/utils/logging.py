"""Structured JSONL event log + console echo (SURVEY.md §5 observability row).

The reference logs via stdout prints and a history pickle; here every event is
one JSON line (step, phase, loss, reward stats, CIDEr, clips/sec/chip) so runs
are machine-parseable, plus a human-readable console echo.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
from typing import Any, IO

from cst_captioning_tpu.obs import wall_time


class EventLogger:
    """JSONL event sink, safe to lose power on.

    Line-buffered writes, an :func:`atexit`-registered close (so an
    interpreter teardown — including one triggered by SIGTERM's default
    disposition — never strands buffered events), an explicit fsync'ing
    :meth:`flush` for preemption-save paths, and a context-manager protocol
    that records a final ``crash`` event (exception type + message) when the
    governed block dies on an unhandled error."""

    def __init__(self, path: str = "", echo: bool = True):
        self._fh: IO | None = None
        self._atexit_close = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
            # bound method in a local so unregister() matches register()
            self._atexit_close = self.close
            atexit.register(self._atexit_close)
        self.echo = echo

    def log(self, event: str, **fields: Any) -> None:
        # obs.wall_time is the one sanctioned wall-clock read (GL010): the
        # JSONL log and the obs event stream stamp through the same spelling
        rec = {"ts": wall_time(), "event": event, **fields}
        if self._fh:
            self._fh.write(json.dumps(rec, default=float) + "\n")
        if self.echo:
            kv = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items()
            )
            sys.stderr.write(f"[{event}] {kv}\n")

    def flush(self) -> None:
        """Push buffered events to the OS and fsync them to disk — called on
        the preemption path, where the process dies moments later."""
        if self._fh:
            self._fh.flush()
            try:
                os.fsync(self._fh.fileno())
            except OSError:
                pass  # non-seekable sink (pipe/pty): flush() already did it

    def close(self) -> None:
        if self._atexit_close is not None:
            atexit.unregister(self._atexit_close)
            self._atexit_close = None
        if self._fh:
            self.flush()
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "EventLogger":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.log("crash", error=exc_type.__name__, detail=str(exc))
        self.close()
        return False


# StepTimer (the old private clips/sec meter) is gone: both trainer phases
# meter through obs.metrics.StepMeter — one latency histogram + throughput
# counter per phase on the process-wide registry, so XE and RL epochs report
# identically and the run report sees the same numbers the log does.
