"""Structured JSONL event log + console echo (SURVEY.md §5 observability row).

The reference logs via stdout prints and a history pickle; here every event is
one JSON line (step, phase, loss, reward stats, CIDEr, clips/sec/chip) so runs
are machine-parseable, plus a human-readable console echo.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, IO


class EventLogger:
    def __init__(self, path: str = "", echo: bool = True):
        self._fh: IO | None = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
        self.echo = echo

    def log(self, event: str, **fields: Any) -> None:
        rec = {"ts": time.time(), "event": event, **fields}
        if self._fh:
            self._fh.write(json.dumps(rec, default=float) + "\n")
        if self.echo:
            kv = " ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in fields.items()
            )
            print(f"[{event}] {kv}", file=sys.stderr)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None


class StepTimer:
    """Running clips/sec meter (the north-star throughput counter)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = time.perf_counter()
        self._clips = 0

    def tick(self, clips: int):
        self._clips += clips

    @property
    def clips_per_sec(self) -> float:
        dt = time.perf_counter() - self._t0
        return self._clips / dt if dt > 0 else 0.0
