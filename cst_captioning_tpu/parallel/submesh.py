"""Actor/learner submesh partitioning for decoupled SCST.

``train.rl_topology="decoupled"`` (rl/async_scst.py) splits the 1-D data
mesh into two disjoint submeshes: ACTOR devices run the fused rollout
decode continuously, LEARNER devices consume the rollout ring with the
REINFORCE update. Each submesh is a real ``Mesh`` over the same axis name,
so the existing shard_map decode/update factories work on either side
unchanged — the factories only see "a mesh with a 'data' axis".

Two constraints shape the split:

- both submeshes need >= 1 device (a 1-device mesh degenerates to a SHARED
  plan: the one device plays both roles, which is also the mesh=None and
  strict-replay layout);
- each side's device count must divide the global batch (batch rows shard
  over the submesh axis), so counts are clamped DOWN to the largest divisor
  of the batch size — the same rule reclamps survivors after an
  ``actor_preempt`` fault shrinks the actor side, and reclamps the grown
  set when a ``host_rejoin`` re-admits a shed device (:func:`grow_actors`).

Cross-submesh movement (finished rollouts actor->learner, fresh params
learner->actor) is a plain ``jax.device_put`` onto the other submesh's
``NamedSharding`` — resharding between device sets, no collectives.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
from jax.sharding import Mesh


def largest_divisor(batch: int, upper: int) -> int:
    """Largest d with 1 <= d <= upper and batch % d == 0 (1 always works)."""
    upper = max(1, upper)
    if batch <= 0:
        return upper
    for d in range(min(upper, batch), 0, -1):
        if batch % d == 0:
            return d
    return 1


@dataclass(frozen=True)
class SubmeshPlan:
    """The actor/learner split of a data mesh.

    ``shared`` marks the degenerate layout where one submesh IS the full
    mesh and both roles run on the same devices (1-device meshes, and the
    strict replay mode which pins bit-identity by decoding on the full
    mesh exactly like the sync loop).
    """

    actor: Mesh
    learner: Mesh
    actor_devices: tuple
    learner_devices: tuple
    shared: bool

    @property
    def n_actors(self) -> int:
        return len(self.actor_devices)

    @property
    def n_learners(self) -> int:
        return len(self.learner_devices)


def _submesh(devices, axis: str) -> Mesh:
    return Mesh(np.asarray(devices), (axis,))


def shared_plan(mesh: Mesh, axis: str = "data") -> SubmeshPlan:
    """Both roles on the full mesh (strict replay / 1-device layout)."""
    devs = tuple(mesh.devices.reshape(-1))
    return SubmeshPlan(mesh, mesh, devs, devs, shared=True)


def plan_submesh(
    mesh: Mesh,
    actor_fraction: float = 0.5,
    axis: str = "data",
    batch_size: int = 0,
) -> SubmeshPlan:
    """Partition ``mesh`` into disjoint actor/learner submeshes.

    The actor side takes ``round(n * actor_fraction)`` devices clamped so
    both sides keep >= 1, then each side clamps down to the largest
    divisor of ``batch_size`` (0 = no batch constraint). A mesh with a
    single device returns the shared plan.

    A 2-D ``(data, mp)`` mesh (flagship-XL, train/mesh.make_mesh with
    ``mp_devices>1``) splits along its DATA rows: each side keeps every mp
    column, so both submeshes stay 2-D and the mp-sharded decode/update
    factories run on either side unchanged — the dp x mp composition seam.
    ``axis`` is ignored on that path (axis names come from the mesh).
    """
    if len(mesh.axis_names) == 2:
        return _plan_submesh_2d(mesh, actor_fraction, batch_size)
    if len(mesh.axis_names) != 1:
        raise ValueError(
            f"plan_submesh needs a 1-D or 2-D mesh, got axes "
            f"{mesh.axis_names!r}"
        )
    devices = list(mesh.devices.reshape(-1))
    n = len(devices)
    if n < 2:
        return shared_plan(mesh, axis=axis)
    n_actor = max(1, min(n - 1, round(n * actor_fraction)))
    n_actor = largest_divisor(batch_size, n_actor)
    n_learner = largest_divisor(batch_size, n - n_actor)
    actors = tuple(devices[:n_actor])
    learners = tuple(devices[n_actor:n_actor + n_learner])
    return SubmeshPlan(
        actor=_submesh(actors, axis),
        learner=_submesh(learners, axis),
        actor_devices=actors,
        learner_devices=learners,
        shared=False,
    )


def _plan_submesh_2d(
    mesh: Mesh, actor_fraction: float, batch_size: int
) -> SubmeshPlan:
    """Row split of a (data, mp) grid: whole mp columns move together."""
    grid = np.asarray(mesh.devices)
    rows = grid.shape[0]
    if rows < 2:
        return shared_plan(mesh)
    n_actor = max(1, min(rows - 1, round(rows * actor_fraction)))
    n_actor = largest_divisor(batch_size, n_actor)
    n_learner = largest_divisor(batch_size, rows - n_actor)
    actor_grid = grid[:n_actor]
    learner_grid = grid[n_actor:n_actor + n_learner]
    return SubmeshPlan(
        actor=Mesh(actor_grid, mesh.axis_names),
        learner=Mesh(learner_grid, mesh.axis_names),
        actor_devices=tuple(actor_grid.reshape(-1)),
        learner_devices=tuple(learner_grid.reshape(-1)),
        shared=False,
    )


def shrink_actors(
    plan: SubmeshPlan,
    drop_index: int,
    axis: str = "data",
    batch_size: int = 0,
) -> SubmeshPlan | None:
    """Remove one actor device (an ``actor_preempt`` casualty) from the plan.

    ``drop_index`` indexes the CURRENT actor device list modulo its length,
    mirroring how chaos faults address phantom hosts. Survivors reclamp to
    the largest batch divisor. Returns ``None`` when no actor survives —
    the caller falls back to the sync schedule on the learner submesh.
    """
    if plan.shared or plan.n_actors <= 1:
        return None
    if len(plan.actor.axis_names) != 1:
        raise ValueError(
            "shrink_actors only handles 1-D plans: dropping one device from "
            "a (data, mp) grid would break the mp columns — shed a whole "
            "data row by re-planning instead"
        )
    survivors = list(plan.actor_devices)
    del survivors[drop_index % len(survivors)]
    keep = largest_divisor(batch_size, len(survivors))
    survivors = tuple(survivors[:keep])
    return SubmeshPlan(
        actor=_submesh(survivors, axis),
        learner=plan.learner,
        actor_devices=survivors,
        learner_devices=plan.learner_devices,
        shared=False,
    )


def grow_actors(
    plan: SubmeshPlan | None,
    device,
    initial: SubmeshPlan,
    axis: str = "data",
    batch_size: int = 0,
    dead=(),
) -> SubmeshPlan | None:
    """Re-admit one actor device (the inverse of :func:`shrink_actors`).

    ``initial`` is the pristine pre-fault plan: membership AND order come
    from it, so a shrink→grow round trip restores the exact original device
    order — and with it the per-shard RNG folds, which is what makes
    post-regrow rollouts bit-identical to a never-degraded run. ``dead``
    names devices still known lost; everything else from the initial plan
    is healthy and returns with the rejoiner (including devices the shrink
    direction clamped away for batch divisibility — they were shed, not
    preempted). ``plan`` is the current (possibly shrunk) plan, or ``None``
    when the caller fell back to the sync schedule with no live actor. The
    grown set reclamps to the largest batch divisor, like the shrink
    direction. Returns ``None`` when the membership would not change
    (shared initial plan, or a duplicate rejoin the clamp swallows);
    raises if ``device`` was never part of the initial plan.
    """
    if initial.shared:
        return None
    if len(initial.actor.axis_names) != 1:
        raise ValueError(
            "grow_actors only handles 1-D plans: re-admission into a "
            "(data, mp) grid re-plans a whole data row instead"
        )
    if device not in initial.actor_devices:
        raise ValueError(
            f"grow_actors device {device} was never in the initial actor "
            f"plan ({initial.actor_devices})"
        )
    current = set() if plan is None or plan.shared else set(plan.actor_devices)
    gone = set(dead) - {device}
    members = tuple(d for d in initial.actor_devices if d not in gone)
    keep = largest_divisor(batch_size, len(members))
    members = members[:keep]
    if set(members) == current:
        return None
    return SubmeshPlan(
        actor=_submesh(members, axis),
        learner=initial.learner,
        actor_devices=members,
        learner_devices=initial.learner_devices,
        shared=False,
    )
