"""Unified compile layer: one seam where step/decode programs meet jit.

Every jitted program in the repo used to spell its own compilation —
``jax.jit(fn)`` here, ``jax.jit(shard_map(fn, ...))`` there — which meant
the flagship-XL dp x mp refactor would have touched a dozen call sites with
conflicting axis bookkeeping. This module centralizes the choice behind a
:class:`CompilePlan` (the Titanax/SNIPPETS [3] idiom: a plan object picks
jit / shard_map / pjit, the factories just describe their specs):

- ``mesh=None``                      -> plain ``jax.jit`` (single device);
- ``mesh`` + ``in_specs``/``out_specs`` -> ``jax.jit(shard_map(fn, ...))``
  (the explicit-collectives spelling every factory uses today);
- ``how="pjit"``                     -> ``jax.jit`` with NamedSharding
  in/out shardings derived from the same specs (compiler-inserted
  collectives — the escape hatch for programs whose collectives are not
  hand-spelled, e.g. the mp=1 parameter-sharded eval path).

The emitted composition for the first two modes is byte-for-byte the
spelling the factories used before this layer existed, so the default
(mp=1) path stays bit-identical by construction — pinned in
tests/test_mp.py. dp x mp composes with ``parallel/submesh.py``'s
actor/learner split because both sides hand their (sub)mesh through the
same plan: a submesh of a 2-D ('data', 'mp') mesh is itself a 2-D mesh,
and the factories never inspect axis counts.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from cst_captioning_tpu.compat import shard_map

_MODES = ("auto", "jit", "shard_map", "pjit")


class CompileError(ValueError):
    """A CompilePlan that cannot be compiled as requested (missing mesh,
    one-sided specs, unknown mode) — raised at factory-build time, never
    from inside a traced program."""


@dataclass(frozen=True)
class CompilePlan:
    """How to compile one program.

    ``mesh``           — target mesh, or None for single-device jit.
    ``in_specs``       — PartitionSpec pytree for the inputs (shard_map /
                         pjit modes; None with ``mesh=None``).
    ``out_specs``      — PartitionSpec pytree for the outputs.
    ``donate_argnums`` — forwarded to ``jax.jit`` unchanged.
    ``how``            — "auto" (jit without a mesh, shard_map with one),
                         or an explicit "jit" / "shard_map" / "pjit".
    """

    mesh: Mesh | None = None
    in_specs: Any = None
    out_specs: Any = None
    donate_argnums: tuple[int, ...] = ()
    how: str = "auto"

    def __post_init__(self):
        if self.how not in _MODES:
            raise CompileError(
                f"unknown compile mode {self.how!r} (expected one of "
                f"{_MODES})"
            )
        if (self.in_specs is None) != (self.out_specs is None):
            raise CompileError(
                "CompilePlan needs BOTH in_specs and out_specs (or "
                "neither): one-sided specs silently replicate the other "
                "side"
            )

    def resolve(self) -> str:
        """The concrete mode "auto" lands on, with plan validation."""
        how = self.how
        if how == "auto":
            how = "jit" if self.mesh is None else "shard_map"
        if how == "jit":
            if self.in_specs is not None:
                raise CompileError(
                    "mode 'jit' ignores partition specs — drop them or "
                    "pick shard_map/pjit"
                )
            return how
        if self.mesh is None:
            raise CompileError(f"mode {how!r} needs a mesh")
        if self.in_specs is None:
            raise CompileError(
                f"mode {how!r} needs in_specs and out_specs"
            )
        return how


def partition(fn: Callable, plan: CompilePlan) -> Callable:
    """The shard_map half only — for factories whose ``jax.jit`` sits at a
    different level than the mesh program (the seq-parallel factories take
    grads OUTSIDE their shard_map)."""
    how = plan.resolve()
    if how != "shard_map":
        raise CompileError(
            f"partition() only builds shard_map programs, plan resolved to "
            f"{how!r}"
        )
    return shard_map(
        fn, mesh=plan.mesh, in_specs=plan.in_specs, out_specs=plan.out_specs
    )


def _shardings(mesh: Mesh, specs):
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda s: isinstance(s, PartitionSpec),
    )


def compile_fn(fn: Callable, plan: CompilePlan) -> Callable:
    """Compile ``fn`` per ``plan`` — the single seam all step/update
    factories, the evaluator, and CaptionService compile through."""
    how = plan.resolve()
    if how == "jit":
        return jax.jit(fn, donate_argnums=plan.donate_argnums)
    if how == "shard_map":
        return jax.jit(
            partition(fn, plan), donate_argnums=plan.donate_argnums
        )
    # pjit: same jit, compiler-inserted collectives from the sharding trees
    return jax.jit(
        fn,
        in_shardings=_shardings(plan.mesh, plan.in_specs),
        out_shardings=_shardings(plan.mesh, plan.out_specs),
        donate_argnums=plan.donate_argnums,
    )
