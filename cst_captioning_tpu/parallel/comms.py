"""Gradient communication: bucketed / compressed / overlapped allreduce.

The RL update is the bandwidth-bound program of the step (BENCH_r05:
bw_util 0.45 at MFU 0.20) and its allreduce is spelled per-leaf — one
``psum`` per parameter array, dozens of small messages per update. This
module centralizes the cross-device gradient reduction behind one knob
surface (``train.comm_*``), applying the *Densifying Assumed-sparse
Tensors* insight (PAPERS.md, arXiv 1905.04035):

- **Bucketing** (``comm_bucket_mb``): the grad tree flattens into
  size-targeted contiguous buckets, ordered by parameter FAMILY
  (``train/mesh.py PARAM_PARTITION_RULES`` order) so the effectively-sparse
  embedding/vocab-projection rows coalesce into dense payloads, and ONE
  ``psum`` runs per bucket instead of per leaf. Elementwise the sum over
  devices is unchanged, so bucketed f32 is BIT-IDENTICAL to the per-leaf
  spelling (pinned in tests/test_comms.py).
- **bf16 on the wire** (``comm_dtype="bf16"``): grads cast to bfloat16
  before the collective and back after, halving bytes-on-wire; parameters
  and Adam moments stay f32 (master accumulation), so per-step rounding
  does not compound in the state. Tolerance-pinned, off by default — the
  f32 path remains the bit-exact reference.
- **Overlap** (``comm_overlap``, rides ``rl.update_chunks``): each chunk's
  grads start their psum while the next chunk's backward runs (the
  double-buffered carry lives in ``rl/scst._chunked_loss_grads``). The
  bit-exact reference is the EAGER per-chunk-reduce spelling (identical
  float order, no double buffer); note overlap reduces every chunk's full
  param-shaped tree, so its wire volume is (chunks+1)x the unoverlapped
  payload — a latency-hiding trade the ``bench_comms.py`` ledger reports
  honestly.

``reduce_tree`` is the single entry point the six step/update factories
call inside their shard_map bodies; ``comm=None`` keeps the exact pre-PR
per-leaf spelling. The bucket plan is built host-side at TRACE time (it
depends only on leaf shapes/dtypes), which is also where the per-update
``comm.*`` gauges are set — zero device work is added for observability.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from cst_captioning_tpu import obs
from cst_captioning_tpu.train.mesh import PARAM_PARTITION_RULES, param_path_names

_WIRE_DTYPES = ("f32", "bf16")
_OVERLAP_MODES = ("off", "defer", "eager")

# bytes-on-wire histogram buckets: 64 KiB .. 64 MiB per message
_BUCKET_BYTES_BUCKETS = tuple(float(1 << s) for s in range(16, 27))


@dataclass(frozen=True)
class CommConfig:
    """How the step/update factories reduce gradients across the mesh.

    ``bucket_mb``  — target payload per collective, in MiB of WIRE bytes;
                     ``0`` disables coalescing (one psum per leaf, still in
                     the wire dtype).
    ``dtype``      — "f32" (bit-exact default) or "bf16" (half the wire
                     bytes; f32 master accumulation in the optimizer).
    ``overlap``    — "off" | "defer" (double-buffered per-chunk reduce,
                     the production overlap) | "eager" (per-chunk reduce
                     with no buffering: defer's bit-exact float-order
                     reference). Only the chunked RL update consumes it.
    """

    bucket_mb: float = 4.0
    dtype: str = "f32"
    overlap: str = "off"

    def __post_init__(self):
        if self.dtype not in _WIRE_DTYPES:
            raise ValueError(
                f"unknown comm dtype {self.dtype!r} "
                f"(expected one of {_WIRE_DTYPES})"
            )
        if self.overlap not in _OVERLAP_MODES:
            raise ValueError(
                f"unknown comm overlap mode {self.overlap!r} "
                f"(expected one of {_OVERLAP_MODES})"
            )
        if self.bucket_mb < 0:
            raise ValueError(f"comm bucket_mb {self.bucket_mb} must be >= 0")

    @classmethod
    def from_train(cls, train) -> "CommConfig":
        """Build from a ``TrainConfig`` (the ``train.comm_*`` knobs)."""
        return cls(
            bucket_mb=train.comm_bucket_mb,
            dtype=train.comm_dtype,
            overlap="defer" if train.comm_overlap else "off",
        )


@dataclass(frozen=True)
class Bucket:
    indices: tuple[int, ...]      # flat-leaf indices (family-ordered)
    wire_dtype: str               # dtype name on the wire
    bytes_on_wire: int            # payload bytes of ONE psum of this bucket


@dataclass(frozen=True)
class BucketPlan:
    buckets: tuple[Bucket, ...]
    n_leaves: int
    bytes_on_wire: int            # total payload bytes per reduction


def _family_rank(path: str) -> int:
    """Position of a param path's family in PARAM_PARTITION_RULES; paths
    matching no rule sort last (stably, by original leaf order)."""
    for rank, (_, pattern, _spec) in enumerate(PARAM_PARTITION_RULES):
        if re.fullmatch(pattern, path):
            return rank
    return len(PARAM_PARTITION_RULES)


def _mp_sharded(path: str) -> bool:
    """Whether MP_PARAM_PARTITION_RULES puts this param on the 'mp' axis
    (flagship-XL: the vocab/out-projection and LSTM gate families)."""
    from cst_captioning_tpu.train.mesh import MP_PARAM_PARTITION_RULES

    for _family, pattern, spec in MP_PARAM_PARTITION_RULES:
        if re.fullmatch(pattern, path):
            return any(a == "mp" for a in spec if a is not None)
    return False


def mp_shard_view(tree, mp_devices: int):
    """The dp-allreduce payload shape under mp sharding, as a ShapeDtype
    pytree: every mp-sharded leaf carries 1/mp of its elements per device
    (the embedding gradient under a row-sharded table stays DENSE — each
    shard reduces its own [V/mp, E] block, never a scatter of sparse
    rows — so it buckets exactly like any other leaf). Host-side analytic
    view for :func:`ledger`; identity at ``mp_devices<=1``."""
    import jax

    if mp_devices <= 1:
        return tree
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    paths = param_path_names(tree)
    out = []
    for path, leaf in zip(paths, leaves):
        if _mp_sharded(path):
            out.append(jax.ShapeDtypeStruct(
                (-(-leaf.size // mp_devices),), leaf.dtype
            ))
        else:
            out.append(jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


def _wire_dtype_of(leaf, comm: CommConfig):
    """The on-wire dtype for one leaf (host-side; works on tracers and
    ShapeDtypeStructs alike — only ``.dtype`` is read)."""
    import jax.numpy as jnp

    if comm.dtype == "bf16" and jnp.issubdtype(leaf.dtype, jnp.floating):
        return jnp.dtype(jnp.bfloat16)
    return jnp.dtype(leaf.dtype)


def plan_buckets(tree, comm: CommConfig) -> BucketPlan:
    """Family-ordered, size-targeted bucket plan for a grad pytree.

    Host-side and trace-safe: only leaf shapes/dtypes and key paths are
    read. Leaves sort by (family rank, flatten order) — the embedding /
    vocab-projection families coalesce — then pack greedily into buckets of
    at most ``bucket_mb`` MiB of wire bytes; a single leaf larger than the
    target gets its own bucket; only same-wire-dtype leaves share one.
    """
    import jax

    leaves = jax.tree_util.tree_leaves(tree)
    paths = param_path_names(tree)
    order = sorted(
        range(len(leaves)), key=lambda i: (_family_rank(paths[i]), i)
    )
    target = int(comm.bucket_mb * (1 << 20))
    buckets: list[Bucket] = []
    cur: list[int] = []
    cur_bytes = 0
    cur_dtype = None

    def flush():
        nonlocal cur, cur_bytes, cur_dtype
        if cur:
            buckets.append(Bucket(
                indices=tuple(cur), wire_dtype=str(cur_dtype),
                bytes_on_wire=cur_bytes,
            ))
        cur, cur_bytes, cur_dtype = [], 0, None

    for i in order:
        wd = _wire_dtype_of(leaves[i], comm)
        nbytes = leaves[i].size * wd.itemsize
        same = cur_dtype is None or str(wd) == cur_dtype
        fits = target <= 0 or not cur or cur_bytes + nbytes <= target
        if not (same and fits):
            flush()
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = str(wd)
        if target <= 0:
            flush()  # bucket_mb=0: one message per leaf
    flush()
    return BucketPlan(
        buckets=tuple(buckets),
        n_leaves=len(leaves),
        bytes_on_wire=sum(b.bytes_on_wire for b in buckets),
    )


def per_leaf_f32_bytes(tree) -> int:
    """Analytic bytes-on-wire of the pre-PR spelling: one f32-sized psum
    per leaf (the baseline the BENCH_COMMS ratio is taken against)."""
    import jax

    return sum(
        leaf.size * 4 for leaf in jax.tree_util.tree_leaves(tree)
    )


def _observe_plan(plan: BucketPlan) -> None:
    """Trace-time observability: the per-update comm shape as gauges plus
    a per-message payload histogram. Host-side only — nothing reaches the
    compiled program. Dispatch-level wall-clock spans ride
    ``resilience.health.collective_span`` (wrapped around the update call
    by SCSTTrainer and bench_comms)."""
    obs.gauge("comm.buckets").set(float(len(plan.buckets)))
    obs.gauge("comm.bytes_on_wire").set(float(plan.bytes_on_wire))
    hist = obs.histogram("comm.bucket_bytes", _BUCKET_BYTES_BUCKETS)
    for b in plan.buckets:
        hist.observe(float(b.bytes_on_wire))


def reduce_tree(grads, axis: str, comm: CommConfig | None):
    """Allreduce a gradient pytree over mesh axis ``axis`` (call INSIDE a
    shard_map body).

    ``comm=None`` is the exact pre-PR spelling: one ``psum`` per leaf, no
    cast — kept callable so parity tests can pin the new paths against it.
    Otherwise leaves are packed per :func:`plan_buckets`, each bucket is
    raveled/concatenated into one contiguous buffer in the wire dtype,
    psum'd once, and split back; results cast back to each leaf's dtype.
    psum is elementwise, so at f32 this is bit-identical to per-leaf.
    """
    import jax
    import jax.numpy as jnp

    if comm is None:
        return jax.tree.map(lambda g: jax.lax.psum(g, axis), grads)

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    plan = plan_buckets(grads, comm)
    _observe_plan(plan)
    out: list = [None] * len(leaves)
    for bucket in plan.buckets:
        wd = jnp.dtype(bucket.wire_dtype)
        parts = [leaves[i].reshape(-1).astype(wd) for i in bucket.indices]
        buf = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        buf = jax.lax.psum(buf, axis)
        offset = 0
        for i in bucket.indices:
            leaf = leaves[i]
            piece = jax.lax.dynamic_slice_in_dim(buf, offset, leaf.size)
            out[i] = piece.reshape(leaf.shape).astype(leaf.dtype)
            offset += leaf.size
    return jax.tree_util.tree_unflatten(treedef, out)


def ledger(tree, comm: CommConfig | None, reductions: int = 1,
           mp_devices: int = 1) -> dict:
    """Host-side bytes-on-wire accounting for one update that reduces a
    ``tree``-shaped payload ``reductions`` times (1 for the fused/chunked
    unoverlapped update; chunks+1 for the overlapped chunked update, which
    reduces every chunk's param-shaped grads plus the encoder cotangent
    fold) — the BENCH_COMMS.json row shape.

    ``mp_devices>1`` accounts the flagship-XL dp-allreduce: mp-sharded
    leaves (embedding, vocab projection, LSTM gates) reduce only their
    local 1/mp block per device (:func:`mp_shard_view`) — the mp=1 numbers
    are bit-identical to the pre-mp ledger."""
    tree = mp_shard_view(tree, mp_devices)
    if comm is None:
        import jax

        n = len(jax.tree_util.tree_leaves(tree))
        total = per_leaf_f32_bytes(tree)
        return {
            "buckets": n, "messages_per_update": n * reductions,
            "bytes_on_wire_per_update": total * reductions,
        }
    plan = plan_buckets(tree, comm)
    return {
        "buckets": len(plan.buckets),
        "messages_per_update": len(plan.buckets) * reductions,
        "bytes_on_wire_per_update": plan.bytes_on_wire * reductions,
    }
