"""Sequence/context parallelism (SURVEY.md §5 long-context row).

The reference never needed SP — captions are ~30 tokens and clips ~60 frames.
This package makes the frame axis shardable anyway, so videos 100x longer
than one chip's HBM budget still encode, train, and decode: the memory bank
lives frame-sharded across the mesh and the only frame-crossing reductions
(attention softmax, pooled carry init) run as XLA collectives over ICI.
"""

from cst_captioning_tpu.parallel.compile import (
    CompileError,
    CompilePlan,
    compile_fn,
    partition,
)
from cst_captioning_tpu.parallel.comms import (
    Bucket,
    BucketPlan,
    CommConfig,
    ledger,
    mp_shard_view,
    per_leaf_f32_bytes,
    plan_buckets,
    reduce_tree,
)
from cst_captioning_tpu.parallel.submesh import (
    SubmeshPlan,
    grow_actors,
    largest_divisor,
    plan_submesh,
    shared_plan,
    shrink_actors,
)
from cst_captioning_tpu.parallel.seq_parallel import (
    make_sp_decode,
    make_sp_forward,
    make_sp_rl_update,
    make_sp_xe_step,
    sp_batch_shardings,
    sp_batch_specs,
    sp_model,
)

__all__ = [
    "Bucket",
    "BucketPlan",
    "CommConfig",
    "CompileError",
    "CompilePlan",
    "compile_fn",
    "partition",
    "SubmeshPlan",
    "grow_actors",
    "largest_divisor",
    "ledger",
    "plan_submesh",
    "shared_plan",
    "shrink_actors",
    "make_sp_decode",
    "mp_shard_view",
    "per_leaf_f32_bytes",
    "plan_buckets",
    "reduce_tree",
    "make_sp_forward",
    "make_sp_rl_update",
    "make_sp_xe_step",
    "sp_batch_shardings",
    "sp_batch_specs",
    "sp_model",
]
