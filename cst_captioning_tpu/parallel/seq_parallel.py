"""Frame-axis sequence parallelism: shard the video, psum the attention.

Design (SURVEY.md §5 long-context row, "one-step ring"): every op in the
caption model is frame-local EXCEPT the attention softmax and the carry-init
pooling. With ``ModelConfig.seq_axis`` set, those two become collective
(``pmax`` + ``psum`` over the mesh axis — see ``models/attention.py``), so the
model body runs unchanged inside ``shard_map`` with ``feats``/``masks``
sharded on their frame axis. Everything downstream of the psums is
device-invariant, which means:

- decode (greedy / K-rollout sampling / beam) works as-is — every device
  steps the same replicated LSTM against its own frame shard;
- training gradients are taken OUTSIDE the shard_map: JAX's varying-axis
  machinery (check_vma) transposes the collectives correctly, producing
  global grads — frame-sharded params (encoder embeds, attention memory
  projection) get their partial contributions summed, replicated-path params
  (LSTM, output projection) stay exact. Pinned against single-device grads
  in tests/test_seq_parallel.py.

Composition with data parallelism: a 2-D ``Mesh(('data', 'seq'))`` shards the
batch over 'data' and frames over 'seq'; the XE step psums the loss over
'data' exactly like train/steps.py does.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cst_captioning_tpu.config.config import ModelConfig
from cst_captioning_tpu.parallel.compile import CompilePlan, compile_fn, partition
from cst_captioning_tpu.decoding import fused_decode, greedy_decode, sample_decode
from cst_captioning_tpu.losses import masked_cross_entropy
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.train.steps import _apply
from cst_captioning_tpu.train.state import TrainState


def sp_model(cfg: ModelConfig, seq_axis: str = "seq") -> CaptionModel:
    """A CaptionModel whose frame-axis reductions are collective over ``seq_axis``.

    Parameters are layout-identical to the unsharded model — checkpoints
    trained one way load the other way.
    """
    return CaptionModel(dataclasses.replace(cfg, seq_axis=seq_axis))


def sp_batch_specs(cfg: ModelConfig, data_axis: str = "",
                   seq_axis: str = "seq"):
    """(feats_specs, masks_specs): frame axis on ``seq_axis``, batch axis on
    ``data_axis`` (or replicated when empty)."""
    b = data_axis if data_axis else None
    feats = {name: P(b, seq_axis) for name, _ in cfg.modalities}
    masks = {name: P(b, seq_axis) for name, _ in cfg.modalities}
    return feats, masks


def make_sp_forward(model: CaptionModel, mesh: Mesh, data_axis: str = "",
                    seq_axis: str = "seq") -> Callable:
    """Jitted teacher-forced forward: (params, feats, masks, labels) -> logits.

    Logits replicate over 'seq' (they sit downstream of the attention psum)
    and shard over ``data_axis`` when given.
    """
    f_spec, m_spec = sp_batch_specs(model.cfg, data_axis, seq_axis)
    b = data_axis if data_axis else None

    def fwd(params, feats, masks, labels):
        return model.apply(params, feats, masks, labels)

    return compile_fn(fwd, CompilePlan(
        mesh=mesh,
        in_specs=(P(), f_spec, m_spec, P(b)),
        out_specs=P(b),
    ))


def make_sp_decode(model: CaptionModel, mesh: Mesh, num_rollouts: int = 0,
                   temperature: float = 1.0, max_len: int | None = None,
                   seq_axis: str = "seq", data_axis: str = "",
                   with_greedy: bool = True, fused: bool = True) -> Callable:
    """Jitted SP decode: (params, feats, masks, rng) -> (greedy, samples|None).

    The long-video RL/eval decode: frames sharded over ``seq_axis``; the
    batch replicates, or shards over ``data_axis`` when given (DP x SP —
    the product layout for ``MeshConfig.seq_devices > 1``). With
    ``num_rollouts=0`` only the greedy decode runs (eval path);
    ``with_greedy=False`` skips the greedy rollout (greedy is None — the
    scb/none baselines never consume it, see make_rl_decode). When both run,
    ``fused=True`` (default) folds the greedy baseline in as lane 0 of the
    rollout scan — one loop, one encoder pass (decoding/fused.py), pinned
    bit-exact against the two-loop ``fused=False`` reference.

    The fused loop's stride/compaction knobs (``model.decode_stride`` /
    ``decode_compact``) compose with SP: the compaction permutation is
    derived from ``finished``, which sits downstream of the attention psum
    and is therefore 'seq'-invariant — every frame shard gathers the same
    batch columns, and the frame-sharded memory follows the gather
    unchanged. Under DP x SP the permutation varies over 'data' only (each
    batch shard compacts its own columns) and the early-exit count psums
    over 'data', exactly like the 1-D path. ``decode_impl="pallas"``
    remains excluded here (config validation): the stride kernel's
    in-kernel softmax cannot express the collective 'seq' reduction.
    """
    f_spec, m_spec = sp_batch_specs(model.cfg, data_axis, seq_axis)
    b = data_axis if data_axis else None
    if not num_rollouts and not with_greedy:
        raise ValueError("nothing to decode: num_rollouts=0 and no greedy")

    # batch varying over 'data' when DP x SP; the decode loops pcast their
    # invariant inits over it and psum the early-exit count, so check_vma
    # stays ON and verifies the 'seq' attention collectives against the
    # per-shard batch loop (VERDICT r4 weak #3 closed)
    bx = (data_axis,) if data_axis else ()

    def dec(params, feats, masks, rng):
        if data_axis:
            # independent sampling streams per batch shard
            rng = jax.random.fold_in(rng, jax.lax.axis_index(data_axis))
        if with_greedy and num_rollouts and fused:
            greedy, _, samples, _ = fused_decode(
                model, params, feats, masks, rng,
                num_rollouts=num_rollouts, temperature=temperature,
                max_len=max_len, batch_axes=bx,
            )
            return greedy, samples
        greedy = None
        if with_greedy:
            greedy, _ = greedy_decode(
                model, params, feats, masks, max_len=max_len, batch_axes=bx
            )
        if num_rollouts:
            samples, _ = sample_decode(
                model, params, feats, masks, rng,
                num_rollouts=num_rollouts, temperature=temperature,
                max_len=max_len, batch_axes=bx,
            )
        else:
            samples = greedy  # stable output structure for jit
        return greedy, samples

    return compile_fn(dec, CompilePlan(
        mesh=mesh,
        in_specs=(P(), f_spec, m_spec, P()),
        out_specs=(P(b), P(None, b) if num_rollouts else P(b)),
    ))


def make_sp_xe_step(model: CaptionModel, mesh: Mesh,
                    label_smoothing: float = 0.0, data_axis: str = "",
                    seq_axis: str = "seq", donate: bool = False,
                    guard: bool = False, comm=None,
                    stats: bool = False) -> Callable:
    """Jitted SP (optionally DP x SP) XE train step.

    The loss is computed inside shard_map (loss psum'd over ``data_axis``
    when sharded); ``value_and_grad`` wraps the WHOLE sharded computation, so
    the collective transposes produce exact global gradients.

    ``comm`` (parallel/comms.CommConfig) is accepted for factory-signature
    symmetry and IGNORED: gradients here are taken outside shard_map — the
    collective transposes already yield global grads, so there is no grad
    allreduce to bucket, compress, or overlap (ExperimentConfig rejects
    bf16/overlap knobs on the seq-parallel path for the same reason).

    ``stats=True`` adds the flight recorder's per-family update-ratio
    metrics (train/steps._update_ratios) — extra outputs only, params
    bit-identical.
    """
    del comm  # no grad allreduce on this path — see docstring
    f_spec, m_spec = sp_batch_specs(model.cfg, data_axis, seq_axis)
    b = data_axis if data_axis else None

    def sharded_loss(params, feats, masks, labels, mask, weights, drng):
        if data_axis:
            drng = jax.random.fold_in(drng, jax.lax.axis_index(data_axis))
        # the seq index is deliberately NOT folded in (ADVICE r2 reviewed and
        # declined): every dropout site sits on the REPLICATED path (the
        # decoder input/hidden, downstream of the attention psum — there is
        # no frame-sharded dropout in this model), so identical masks across
        # 'seq' devices are what keep the replicated activations replicated;
        # folding the seq index would desynchronize them and break the
        # out_specs invariance.
        logits = model.apply(
            params, feats, masks, labels, train=True, rngs={"dropout": drng}
        )
        w_mask = mask * weights[:, None]
        den = jnp.sum(w_mask)
        num = masked_cross_entropy(
            logits, labels, mask, weights=weights,
            label_smoothing=label_smoothing,
        ) * den
        if data_axis:
            num = jax.lax.psum(num, data_axis)
            den = jax.lax.psum(den, data_axis)
        return num / jnp.maximum(den, 1.0)

    sm = partition(sharded_loss, CompilePlan(
        mesh=mesh,
        in_specs=(P(), f_spec, m_spec, P(b), P(b), P(b), P()),
        out_specs=P(),
    ))

    def step(state: TrainState, feats, masks, labels, mask, weights):
        drng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(p):
            return sm(p, feats, masks, labels, mask, weights, drng)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        gnorm = optax.global_norm(grads)
        return _apply(state, grads, loss, gnorm, guard, stats=stats)

    return compile_fn(
        step, CompilePlan(donate_argnums=(0,) if donate else ())
    )


def make_sp_rl_update(model: CaptionModel, mesh: Mesh, data_axis: str = "data",
                      seq_axis: str = "seq", chunks: int = 1,
                      donate: bool = False, guard: bool = False,
                      comm=None, stats: bool = False) -> Callable:
    """Jitted DP x SP REINFORCE update (the SCST update on a 2-D mesh).

    Same structure as :func:`make_sp_xe_step`: the (numerator, denominator)
    sums of the teacher-forced REINFORCE loss are computed inside shard_map
    (psum'd over ``data_axis``); ``value_and_grad`` wraps the whole sharded
    computation so the 'seq' attention collectives transpose to exact global
    gradients. Mirrors rl/scst.py's ``make_parallel_rl_update`` semantics
    (valid-row exclusion included). ``chunks > 1`` scans over slices of the
    rollout axis at the jit level — one value_and_grad per chunk, gradients
    accumulated, normalized once by the global token count — producing the
    same total gradient in K/chunks of the activation memory (the same
    HBM-ceiling lever as ``rl.update_chunks`` on the 1-D mesh).

    ``comm`` is accepted for factory-signature symmetry and IGNORED — same
    reason as :func:`make_sp_xe_step`: grads are taken outside shard_map,
    there is no grad allreduce to shape.
    """
    del comm  # no grad allreduce on this path — see docstring
    from cst_captioning_tpu.models.captioner import EncoderOutput

    f_spec, m_spec = sp_batch_specs(model.cfg, data_axis, seq_axis)
    b = data_axis if data_axis else None
    # EncoderOutput partition specs: memory/proj/mask keep their frame shard,
    # the carry ((c, h) per LSTM layer, downstream of the attention psum)
    # shards over the batch only — structure is static given the config
    enc_spec = EncoderOutput(
        P(b, seq_axis), P(b, seq_axis), P(b, seq_axis),
        tuple((P(b), P(b)) for _ in range(model.cfg.num_layers)),
    )

    def sharded_encode(params, feats, masks):
        # one sharded encoder program: memory/proj/mask keep their frame
        # shard, the carry (downstream of the attention psum) shards over the
        # batch only. Frame-axis leaves that don't depend on the sharded
        # feats (e.g. an all-ones memory_mask) are device-invariant and would
        # violate their varying out_specs — the varying-zero trick from
        # rl/scst._chunked_loss_grads makes those three leaves uniformly
        # varying (zv carries exactly the feats' vma = the f_spec axes); its
        # transpose lands in the discarded feats cotangent. The carry is NOT
        # touched: its out_spec is batch-only (it sits downstream of the
        # 'seq' attention psum) and zv would wrongly make it frame-varying.
        enc = model.apply(params, feats, masks, method=CaptionModel.encode)
        zv = jnp.sum(jax.tree.leaves(feats)[0]) * 0.0
        return type(enc)(
            enc.memory + zv.astype(enc.memory.dtype),
            enc.memory_proj + zv.astype(enc.memory_proj.dtype),
            enc.memory_mask + zv.astype(enc.memory_mask.dtype),
            enc.carry,
        )

    def sharded_sums(params, enc, samples, advantage, valid):
        # the single source of truth for tiling + REINFORCE loss sums lives
        # in rl/scst.py (import here: scst's own parallel import is lazy, so
        # there is no module-level cycle). Same shape as the DP update: tile
        # the ENCODED memory over rollouts and compute target logps inside
        # the teacher-forcing scan — the [K*Bl, T, V] logits stack never
        # materializes, which matters most here (long-context SP exists
        # because memory is tight). The encoder runs OUTSIDE this program
        # (sharded_encode + jax.vjp below), so with chunks>1 its forward AND
        # backward run once instead of once per chunk (ADVICE r4: the
        # per-chunk encoder backward could not be hoisted by XLA — the
        # cotangents differ per chunk — but summing the enc cotangents first
        # and running one backward is the same linear algebra).
        from cst_captioning_tpu.rl.scst import _decode_loss_sums, _tile_enc

        K, Bl, T = samples.shape
        num, den = _decode_loss_sums(
            model, params, _tile_enc(enc, K),
            samples.reshape(K * Bl, T),
            advantage.reshape(K * Bl),
            jnp.tile(valid, (K,)),
        )
        if data_axis:
            num = jax.lax.psum(num, data_axis)
            den = jax.lax.psum(den, data_axis)
        return num, den

    def update(state: TrainState, feats, masks, samples, advantage, valid):
        K = samples.shape[0]

        # gradients are taken OUTSIDE the shard_maps (module docstring): the
        # collective transposes produce exact global grads — frame-sharded
        # params sum their partials, replicated-path params stay exact
        def enc_fn(p):
            return partition(sharded_encode, CompilePlan(
                mesh=mesh,
                in_specs=(P(), f_spec, m_spec), out_specs=enc_spec,
            ))(p, feats, masks)

        def sums(p, e, sam_c, adv_c):
            return partition(sharded_sums, CompilePlan(
                mesh=mesh,
                in_specs=(P(), enc_spec, P(None, b), P(None, b), P(b)),
                out_specs=(P(), P()),
            ))(p, e, sam_c, adv_c, valid)

        if chunks > 1:
            if K % chunks:
                raise ValueError(
                    f"update_chunks {chunks} must divide K={K} rollouts"
                )
            kc = K // chunks
            sam = samples.reshape((chunks, kc) + samples.shape[1:])
            adv = advantage.reshape((chunks, kc) + advantage.shape[1:])
            enc, enc_vjp = jax.vjp(enc_fn, state.params)

            def body(acc, x):
                gp_acc, ge_acc, num_acc, den_acc = acc
                (num, den), (gp, ge) = jax.value_and_grad(
                    sums, argnums=(0, 1), has_aux=True
                )(state.params, enc, *x)
                return (
                    jax.tree.map(jnp.add, gp_acc, gp),
                    # f32 accumulation of the (possibly bf16) enc cotangents
                    jax.tree.map(
                        lambda a_, g: a_ + g.astype(a_.dtype), ge_acc, ge
                    ),
                    num_acc + num,
                    den_acc + den,
                ), None

            init = (
                jax.tree.map(jnp.zeros_like, state.params),
                jax.tree.map(
                    lambda x: jnp.zeros(
                        x.shape, jnp.promote_types(x.dtype, jnp.float32)
                    ),
                    enc,
                ),
                jnp.zeros(()),
                jnp.zeros(()),
            )
            (gp, ge, num, den), _ = jax.lax.scan(body, init, (sam, adv))
            ge = jax.tree.map(lambda g, x: g.astype(x.dtype), ge, enc)
            (g_enc,) = enc_vjp(ge)
            g_sum = jax.tree.map(jnp.add, gp, g_enc)
            den = jnp.maximum(den, 1.0)
            loss = num / den
            grads = jax.tree.map(lambda g: g / den, g_sum)
        else:
            def loss_fn(p):
                num, den = sums(p, enc_fn(p), samples, advantage)
                return num / jnp.maximum(den, 1.0)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
        gnorm = optax.global_norm(grads)
        return _apply(state, grads, loss, gnorm, guard, key="rl_loss",
                      stats=stats)

    return compile_fn(
        update, CompilePlan(donate_argnums=(0,) if donate else ())
    )


def sp_batch_shardings(mesh: Mesh, cfg: ModelConfig, data_axis: str = "data",
                       seq_axis: str = "seq") -> tuple:
    """``jax.device_put`` shardings for the XE batch tuple
    ``(feats, masks, labels, mask, weights, valid)`` on a 2-D mesh:
    frame axis over ``seq_axis``, batch axis over ``data_axis``."""
    f_spec, m_spec = sp_batch_specs(cfg, data_axis, seq_axis)
    d = NamedSharding(mesh, P(data_axis))
    return (
        {k: NamedSharding(mesh, s) for k, s in f_spec.items()},
        {k: NamedSharding(mesh, s) for k, s in m_spec.items()},
        d, d, d, d,
    )
