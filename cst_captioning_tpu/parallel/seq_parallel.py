"""Frame-axis sequence parallelism: shard the video, psum the attention.

Design (SURVEY.md §5 long-context row, "one-step ring"): every op in the
caption model is frame-local EXCEPT the attention softmax and the carry-init
pooling. With ``ModelConfig.seq_axis`` set, those two become collective
(``pmax`` + ``psum`` over the mesh axis — see ``models/attention.py``), so the
model body runs unchanged inside ``shard_map`` with ``feats``/``masks``
sharded on their frame axis. Everything downstream of the psums is
device-invariant, which means:

- decode (greedy / K-rollout sampling / beam) works as-is — every device
  steps the same replicated LSTM against its own frame shard;
- training gradients are taken OUTSIDE the shard_map: JAX's varying-axis
  machinery (check_vma) transposes the collectives correctly, producing
  global grads — frame-sharded params (encoder embeds, attention memory
  projection) get their partial contributions summed, replicated-path params
  (LSTM, output projection) stay exact. Pinned against single-device grads
  in tests/test_seq_parallel.py.

Composition with data parallelism: a 2-D ``Mesh(('data', 'seq'))`` shards the
batch over 'data' and frames over 'seq'; the XE step psums the loss over
'data' exactly like train/steps.py does.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from cst_captioning_tpu.config.config import ModelConfig
from cst_captioning_tpu.decoding import greedy_decode, sample_decode
from cst_captioning_tpu.losses import masked_cross_entropy
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.train.state import TrainState


def sp_model(cfg: ModelConfig, seq_axis: str = "seq") -> CaptionModel:
    """A CaptionModel whose frame-axis reductions are collective over ``seq_axis``.

    Parameters are layout-identical to the unsharded model — checkpoints
    trained one way load the other way.
    """
    return CaptionModel(dataclasses.replace(cfg, seq_axis=seq_axis))


def sp_batch_specs(cfg: ModelConfig, data_axis: str = "",
                   seq_axis: str = "seq"):
    """(feats_specs, masks_specs): frame axis on ``seq_axis``, batch axis on
    ``data_axis`` (or replicated when empty)."""
    b = data_axis if data_axis else None
    feats = {name: P(b, seq_axis) for name, _ in cfg.modalities}
    masks = {name: P(b, seq_axis) for name, _ in cfg.modalities}
    return feats, masks


def make_sp_forward(model: CaptionModel, mesh: Mesh, data_axis: str = "",
                    seq_axis: str = "seq") -> Callable:
    """Jitted teacher-forced forward: (params, feats, masks, labels) -> logits.

    Logits replicate over 'seq' (they sit downstream of the attention psum)
    and shard over ``data_axis`` when given.
    """
    f_spec, m_spec = sp_batch_specs(model.cfg, data_axis, seq_axis)
    b = data_axis if data_axis else None

    def fwd(params, feats, masks, labels):
        return model.apply(params, feats, masks, labels)

    sharded = jax.shard_map(
        fwd,
        mesh=mesh,
        in_specs=(P(), f_spec, m_spec, P(b)),
        out_specs=P(b),
    )
    return jax.jit(sharded)


def make_sp_decode(model: CaptionModel, mesh: Mesh, num_rollouts: int = 0,
                   temperature: float = 1.0, max_len: int | None = None,
                   seq_axis: str = "seq", data_axis: str = "",
                   with_greedy: bool = True) -> Callable:
    """Jitted SP decode: (params, feats, masks, rng) -> (greedy, samples|None).

    The long-video RL/eval decode: frames sharded over ``seq_axis``; the
    batch replicates, or shards over ``data_axis`` when given (DP x SP —
    the product layout for ``MeshConfig.seq_devices > 1``). With
    ``num_rollouts=0`` only the greedy decode runs (eval path);
    ``with_greedy=False`` skips the greedy rollout (greedy is None — the
    scb/none baselines never consume it, see make_rl_decode).
    """
    f_spec, m_spec = sp_batch_specs(model.cfg, data_axis, seq_axis)
    b = data_axis if data_axis else None
    if not num_rollouts and not with_greedy:
        raise ValueError("nothing to decode: num_rollouts=0 and no greedy")

    def dec(params, feats, masks, rng):
        if data_axis:
            # independent sampling streams per batch shard
            rng = jax.random.fold_in(rng, jax.lax.axis_index(data_axis))
        greedy = None
        if with_greedy:
            greedy, _ = greedy_decode(
                model, params, feats, masks, max_len=max_len
            )
        if num_rollouts:
            samples, _ = sample_decode(
                model, params, feats, masks, rng,
                num_rollouts=num_rollouts, temperature=temperature,
                max_len=max_len,
            )
        else:
            samples = greedy  # stable output structure for jit
        return greedy, samples

    extra = {}
    if data_axis:
        # INVARIANT (see make_parallel_rl_decode): with the batch sharded the
        # scan carry varies over 'data' while its BOS init does not, so the
        # varying-axis check must be off. The 'seq' collectives inside the
        # attention still execute correctly — check_vma only disables the
        # type-level invariance analysis, not the psums.
        extra["check_vma"] = False
    sharded = jax.shard_map(
        dec,
        mesh=mesh,
        in_specs=(P(), f_spec, m_spec, P()),
        out_specs=(P(b), P(None, b) if num_rollouts else P(b)),
        **extra,
    )
    return jax.jit(sharded)


def make_sp_xe_step(model: CaptionModel, mesh: Mesh,
                    label_smoothing: float = 0.0, data_axis: str = "",
                    seq_axis: str = "seq") -> Callable:
    """Jitted SP (optionally DP x SP) XE train step.

    The loss is computed inside shard_map (loss psum'd over ``data_axis``
    when sharded); ``value_and_grad`` wraps the WHOLE sharded computation, so
    the collective transposes produce exact global gradients.
    """
    f_spec, m_spec = sp_batch_specs(model.cfg, data_axis, seq_axis)
    b = data_axis if data_axis else None

    def sharded_loss(params, feats, masks, labels, mask, weights, drng):
        if data_axis:
            drng = jax.random.fold_in(drng, jax.lax.axis_index(data_axis))
        # the seq index is deliberately NOT folded in (ADVICE r2 reviewed and
        # declined): every dropout site sits on the REPLICATED path (the
        # decoder input/hidden, downstream of the attention psum — there is
        # no frame-sharded dropout in this model), so identical masks across
        # 'seq' devices are what keep the replicated activations replicated;
        # folding the seq index would desynchronize them and break the
        # out_specs invariance.
        logits = model.apply(
            params, feats, masks, labels, train=True, rngs={"dropout": drng}
        )
        w_mask = mask * weights[:, None]
        den = jnp.sum(w_mask)
        num = masked_cross_entropy(
            logits, labels, mask, weights=weights,
            label_smoothing=label_smoothing,
        ) * den
        if data_axis:
            num = jax.lax.psum(num, data_axis)
            den = jax.lax.psum(den, data_axis)
        return num / jnp.maximum(den, 1.0)

    sm = jax.shard_map(
        sharded_loss,
        mesh=mesh,
        in_specs=(P(), f_spec, m_spec, P(b), P(b), P(b), P()),
        out_specs=P(),
    )

    @jax.jit
    def step(state: TrainState, feats, masks, labels, mask, weights):
        drng = jax.random.fold_in(state.rng, state.step)

        def loss_fn(p):
            return sm(p, feats, masks, labels, mask, weights, drng)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        gnorm = optax.global_norm(grads)
        state = state.apply_gradients(grads)
        return state, {"loss": loss, "grad_norm": gnorm}

    return step


def make_sp_rl_update(model: CaptionModel, mesh: Mesh, data_axis: str = "data",
                      seq_axis: str = "seq", chunks: int = 1) -> Callable:
    """Jitted DP x SP REINFORCE update (the SCST update on a 2-D mesh).

    Same structure as :func:`make_sp_xe_step`: the (numerator, denominator)
    sums of the teacher-forced REINFORCE loss are computed inside shard_map
    (psum'd over ``data_axis``); ``value_and_grad`` wraps the whole sharded
    computation so the 'seq' attention collectives transpose to exact global
    gradients. Mirrors rl/scst.py's ``make_parallel_rl_update`` semantics
    (valid-row exclusion included). ``chunks > 1`` scans over slices of the
    rollout axis at the jit level — one value_and_grad per chunk, gradients
    accumulated, normalized once by the global token count — producing the
    same total gradient in K/chunks of the activation memory (the same
    HBM-ceiling lever as ``rl.update_chunks`` on the 1-D mesh).
    """
    f_spec, m_spec = sp_batch_specs(model.cfg, data_axis, seq_axis)
    b = data_axis if data_axis else None

    def sharded_sums(params, feats, masks, samples, advantage, valid):
        # the single source of truth for tiling + REINFORCE loss sums lives
        # in rl/scst.py (import here: scst's own parallel import is lazy, so
        # there is no module-level cycle). Same shape as the DP update:
        # encode the clip rows, tile the ENCODED memory over rollouts, and
        # compute target logps inside the teacher-forcing scan — the
        # [K*Bl, T, V] logits stack never materializes, which matters most
        # here (long-context SP exists because memory is tight). With
        # chunks>1 this function runs once per chunk, so the encode is
        # repeated per chunk at the jaxpr level (XLA's loop-invariant
        # hoisting dedups it in practice; the DP path's _chunked_loss_grads
        # makes the sharing explicit via jax.vjp instead)
        from cst_captioning_tpu.rl.scst import _decode_loss_sums, _tile_enc

        K, Bl, T = samples.shape
        enc = model.apply(params, feats, masks, method=CaptionModel.encode)
        num, den = _decode_loss_sums(
            model, params, _tile_enc(enc, K),
            samples.reshape(K * Bl, T),
            advantage.reshape(K * Bl),
            jnp.tile(valid, (K,)),
        )
        if data_axis:
            num = jax.lax.psum(num, data_axis)
            den = jax.lax.psum(den, data_axis)
        return num, den

    sm = jax.shard_map(
        sharded_sums,
        mesh=mesh,
        in_specs=(P(), f_spec, m_spec, P(None, b), P(None, b), P(b)),
        out_specs=(P(), P()),
    )

    @jax.jit
    def update(state: TrainState, feats, masks, samples, advantage, valid):
        K = samples.shape[0]
        if chunks > 1:
            from cst_captioning_tpu.rl.scst import accumulate_chunk_grads

            if K % chunks:
                raise ValueError(
                    f"update_chunks {chunks} must divide K={K} rollouts"
                )
            kc = K // chunks
            sam = samples.reshape((chunks, kc) + samples.shape[1:])
            adv = advantage.reshape((chunks, kc) + advantage.shape[1:])
            # this scan sits OUTSIDE the shard_map (global arrays), so no
            # vary_axis is needed on the carry
            num, den, g_sum = accumulate_chunk_grads(
                lambda p, sam_c, adv_c: sm(p, feats, masks, sam_c, adv_c, valid),
                state.params, (sam, adv),
            )
            den = jnp.maximum(den, 1.0)
            loss = num / den
            grads = jax.tree.map(lambda g: g / den, g_sum)
        else:
            def loss_fn(p):
                num, den = sm(p, feats, masks, samples, advantage, valid)
                return num / jnp.maximum(den, 1.0)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
        gnorm = optax.global_norm(grads)
        state = state.apply_gradients(grads)
        return state, {"rl_loss": loss, "grad_norm": gnorm}

    return update


def sp_batch_shardings(mesh: Mesh, cfg: ModelConfig, data_axis: str = "data",
                       seq_axis: str = "seq") -> tuple:
    """``jax.device_put`` shardings for the XE batch tuple
    ``(feats, masks, labels, mask, weights, valid)`` on a 2-D mesh:
    frame axis over ``seq_axis``, batch axis over ``data_axis``."""
    f_spec, m_spec = sp_batch_specs(cfg, data_axis, seq_axis)
    d = NamedSharding(mesh, P(data_axis))
    return (
        {k: NamedSharding(mesh, s) for k, s in f_spec.items()},
        {k: NamedSharding(mesh, s) for k, s in m_spec.items()},
        d, d, d, d,
    )
