"""Fused one-loop RL decode: greedy baseline + K rollouts in ONE scan.

The SCST decode program used to run ``greedy_decode`` then ``sample_decode``
as two *sequential* ``scan_until_finished`` loops inside one jitted program
(rl/scst.py pre-PR 4) — two encoder passes, two T-step while loops, and per
step two separate attention/LSTM dispatches over the same memory bank.
Round-5 bench put that program at 85.1% of sequential RL step time at MFU
0.010: the loop is latency-bound, so its cost scales with *steps
dispatched*, not FLOPs.

Here the greedy baseline is folded in as lane 0 of a single (1+K)-lane
scan: lane 0 takes the argmax of its untempered logits, lanes 1..K sample
``categorical(fold_in(fold_in(rng, k), t), logits/temperature)`` — exactly
``sample_decode``'s key stream, so the sampled lanes are bit-identical to
the two-loop reference by construction (vmap lane results do not depend on
the lane count), and the greedy lane is bit-identical to ``greedy_decode``
(which runs the same lane-batched step at G=1). One encoder pass feeds all
lanes; the loop exits once EVERY lane of every clip has emitted EOS.
Pinned bit-exact against the two-loop reference in tests/test_decoding.py
and tests/test_rl.py (sharded ``batch_axes`` variant included).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, PAD_ID
from cst_captioning_tpu.decoding.common import (
    apply_min_len,
    forbid_special,
    lane_decode_step,
    rollout_step_keys,
    scan_until_finished,
    selected_logprob,
    step_outputs,
)
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput


def fused_decode(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    rng: jax.Array,
    num_rollouts: int = 1,
    temperature: float = 1.0,
    max_len: int | None = None,
    min_len: int = 0,
    batch_axes: tuple[str, ...] = (),
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """-> (greedy [B,T], greedy_lp [B,T], tokens [K,B,T], logprobs [K,B,T]).

    Lane 0 is the greedy baseline (argmax of untempered logits, no RNG
    consumed); lanes 1..K are the Monte-Carlo rollouts on ``sample_decode``'s
    exact key stream. ``logprobs`` are untempered model logprobs of the
    chosen tokens (``selected_logprob``); PAD/0 after EOS on every lane.
    """
    T = max_len or model.cfg.max_len
    K = num_rollouts
    enc: EncoderOutput = model.apply(params, feats, masks, method=CaptionModel.encode)
    B = enc.memory.shape[0]
    step_keys = rollout_step_keys(rng, K, T)  # [T, K] — lane 0 never draws

    def step(state, t):
        carry, token, finished = state  # carry leaves [1+K, B, ...]; [1+K, B]
        carry, logits = lane_decode_step(model, params, carry, token, enc)
        logits = apply_min_len(forbid_special(logits), t, min_len)  # [1+K,B,V]
        g_nxt = jnp.argmax(logits[0], axis=-1)
        s_nxt = jax.vmap(
            lambda k_, l_: jax.random.categorical(k_, l_ / temperature, axis=-1)
        )(step_keys[t], logits[1:])
        nxt = jnp.concatenate([g_nxt[None], s_nxt], axis=0).astype(jnp.int32)
        lp = selected_logprob(logits, nxt)
        nxt, lp, finished = step_outputs(nxt, lp, finished)
        return (carry, nxt, finished), (nxt, lp)

    init = (
        jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (1 + K,) + x.shape), enc.carry
        ),
        jnp.full((1 + K, B), BOS_ID, jnp.int32),
        jnp.zeros((1 + K, B), bool),
    )
    _, (tokens, logprobs) = scan_until_finished(
        step, init, T, lambda s: s[2], (PAD_ID, 0.0), batch_axes
    )
    # ys stack on axis 0: [T, 1+K, B] -> [1+K, B, T]
    tokens = tokens.transpose(1, 2, 0)
    logprobs = logprobs.transpose(1, 2, 0)
    return tokens[0], logprobs[0], tokens[1:], logprobs[1:]
