"""Fused one-loop RL decode: greedy baseline + K rollouts in ONE scan.

The SCST decode program used to run ``greedy_decode`` then ``sample_decode``
as two *sequential* ``scan_until_finished`` loops inside one jitted program
(rl/scst.py pre-PR 4) — two encoder passes, two T-step while loops, and per
step two separate attention/LSTM dispatches over the same memory bank.
Round-5 bench put that program at 85.1% of sequential RL step time at MFU
0.010: the loop is latency-bound, so its cost scales with *steps
dispatched*, not FLOPs.

Here the greedy baseline is folded in as lane 0 of a single (1+K)-lane
scan: lane 0 takes the argmax of its untempered logits, lanes 1..K sample
``categorical(fold_in(fold_in(rng, k), t), logits/temperature)`` — exactly
``sample_decode``'s key stream, spelled in its bit-identical Gumbel-max
form (``gumbel_step_noise``) so the same streams drive every path below.
One encoder pass feeds all lanes; the loop exits once EVERY lane of every
clip has emitted EOS. Pinned bit-exact against the two-loop reference in
tests/test_decoding.py and tests/test_rl.py.

On top of the one-loop structure sit the two decode-endgame levers
(``ModelConfig.decode_stride`` / ``decode_compact``):

- **stride**: the driving while loop advances ``S`` time steps per
  iteration instead of one. On the XLA path that is an inner ``lax.scan``
  chunk (the early-exit check amortizes over S steps); with
  ``decode_impl="pallas"`` each chunk is ONE launch of the multi-step
  stride kernel (ops/decode_pallas.py: token selection + next-token embed
  lookup in-kernel, decoder weights VMEM-resident across the whole
  stride).
- **compaction**: between strides, batch columns whose every lane has
  finished are permuted out of a dense still-active prefix
  (``jnp.argsort`` stable: active columns keep their order), the stride
  steps the permuted state, and outputs scatter back through the inverse
  permutation. Per-row math is position-independent, so the round trip is
  token- and logprob-exact (pinned in tests/test_decoding.py); the
  compute win is the stride kernel's, which skips whole blocks past the
  ``n_active`` prefix. The while loop's all-finished exit replaces the
  fixed budget either way.

Every (stride, compact) combination is token- and logprob-exact vs the
stride-1 uncompacted loop under a fixed rng — selection noise is always
drawn in ORIGINAL batch order and gathered through the compaction
permutation, so a row's RNG stream follows it through the shuffle.

The serving engine (cst_captioning_tpu/serving/engine.py) drives the SAME
stride machinery as an always-on service: its admission loop re-packs the
active prefix between strides exactly like the compaction here, but with
per-REQUEST RNG streams and a paged encoder bank gathered per stride —
``fused_decode_stride``'s ``mem_lens`` argument carries the per-row ragged
lengths; the offline paths below pass none (uniform M), which compiles to
the identical program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.decoding.common import (
    apply_min_len,
    forbid_special,
    gumbel_step_noise,
    lane_decode_step,
    npad_best_lane,
    pcast_varying,
    rollout_step_keys,
    scan_until_finished,
    selected_logprob,
    step_outputs,
)
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput


def _sel_step(model, params, enc_c, step_keys, B, V, temperature, min_len,
              perm):
    """The (1+K)-lane decode step with fused token selection.

    ``perm`` (compaction permutation, or None) maps the state's column
    order back to original batch order: Gumbel noise is drawn for ORIGINAL
    columns and gathered through it, so a clip's sampling stream is
    independent of where compaction moved it.
    """

    def step(state, t):
        carry, token, finished = state  # carry leaves [1+K, B, ...]; [1+K, B]
        carry, logits = lane_decode_step(model, params, carry, token, enc_c)
        logits = apply_min_len(forbid_special(logits), t, min_len)  # [1+K,B,V]
        g_nxt = jnp.argmax(logits[0], axis=-1)
        tl = logits[1:] / temperature
        noise = gumbel_step_noise(step_keys[t], (B, V), tl.dtype)
        if perm is not None:
            noise = noise[:, perm, :]
        s_nxt = jnp.argmax(tl + noise, axis=-1)
        nxt = jnp.concatenate([g_nxt[None], s_nxt], axis=0).astype(jnp.int32)
        lp = selected_logprob(logits, nxt)
        nxt, lp, finished = step_outputs(nxt, lp, finished)
        return (carry, nxt, finished), (nxt, lp)

    return step


def _kernel_stride(model, params, state_c, enc_c, noise, t, S, n_active,
                   temperature, min_len):
    """One stride via the multi-step Pallas kernel -> (state', toks, lps)."""
    from cst_captioning_tpu.ops.decode_pallas import fused_decode_stride

    carry, token, finished = state_c
    new_carry, toks, lps = fused_decode_stride(
        params["params"]["cell"], carry, token, finished,
        enc_c.memory, enc_c.memory_proj, enc_c.memory_mask,
        noise, t, n_active, steps=S, temperature=temperature,
        min_len=min_len, num_layers=model.cfg.num_layers,
    )
    # the kernel emits the frozen-token stream; the carried token is the
    # last emission and finished accumulates any EOS in the chunk — the
    # exact state the XLA step chain would carry
    finished = finished | jnp.any(toks == EOS_ID, axis=0)
    return (new_carry, toks[-1], finished), toks, lps


def _stride_decode(model, params, enc: EncoderOutput, step_keys, B, T, S, K,
                   temperature, min_len, compact, batch_axes):
    """The strided driving loop (module docstring): while over S-step
    chunks, optional finished-column compaction between chunks, all-
    finished early exit. Returns (tokens [P,1+K,B], logprobs [P,1+K,B])
    already sliced to the T budget."""
    G = 1 + K
    V = model.cfg.vocab_size
    padded = -(-T // S) * S
    use_kernel = getattr(model.cfg, "decode_impl", "xla") == "pallas"

    init = (
        jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (G,) + x.shape), enc.carry
        ),
        jnp.full((G, B), BOS_ID, jnp.int32),
        jnp.zeros((G, B), bool),
    )
    bufs = (
        jnp.full((padded, G, B), PAD_ID, jnp.int32),
        jnp.zeros((padded, G, B), jnp.float32),
    )
    init = pcast_varying(init, batch_axes)
    bufs = pcast_varying(bufs, batch_axes)

    def count_unfinished(finished):
        n = jnp.sum(jnp.logical_not(finished).astype(jnp.int32))
        for ax in batch_axes:
            n = jax.lax.psum(n, ax)
        return n

    def cond(loop):
        t, _, _, unfinished = loop
        return (t < T) & (unfinished > 0)

    def body(loop):
        t, state, (tok_buf, lp_buf), _ = loop
        carry, token, finished = state
        if compact:
            # stable sort keeps active columns in original relative order,
            # so the prefix is a gather, not a shuffle
            col_done = jnp.all(finished, axis=0)                    # [B]
            perm = jnp.argsort(col_done, stable=True)
            inv = jnp.argsort(perm, stable=True)
            n_active = B - jnp.sum(col_done.astype(jnp.int32))
            carry = jax.tree.map(lambda x: jnp.take(x, perm, axis=1), carry)
            token = jnp.take(token, perm, axis=1)
            finished = jnp.take(finished, perm, axis=1)
            enc_c = enc.take_batch(perm)
            # materialize the gathered operands: without the barrier XLA
            # fuses the gather into the step's consumers, changing the
            # generated code and drifting logits by ULPs vs the uncompacted
            # loop — with it, the step body sees plain arrays and compiles
            # to the exact same program, which is what makes compaction
            # bit-exact rather than merely close (a gather is a copy
            # anyway, so the barrier costs nothing extra)
            carry, token, finished, enc_c = jax.lax.optimization_barrier(
                (carry, token, finished, enc_c)
            )
        else:
            perm = None
            n_active = jnp.int32(B)
            enc_c = enc
        state_c = (carry, token, finished)

        if use_kernel:
            # the kernel's whole-stride noise, drawn in original column
            # order from the exact rollout_step_keys streams (overhang rows
            # past T clamp to row T-1; their emissions never leave the
            # sliced-off buffer tail)
            keys_chunk = step_keys[t + jnp.arange(S)]               # [S, K]
            noise = jax.vmap(
                lambda ks: gumbel_step_noise(ks, (B, V), jnp.float32)
            )(keys_chunk)
            if compact:
                noise = noise[:, :, perm, :]
            state_c, tok_chunk, lp_chunk = _kernel_stride(
                model, params, state_c, enc_c, noise, t, S, n_active,
                temperature, min_len,
            )
        else:
            step = _sel_step(
                model, params, enc_c, step_keys, B, V, temperature, min_len,
                perm,
            )
            state_c, (tok_chunk, lp_chunk) = jax.lax.scan(
                step, state_c, t + jnp.arange(S)
            )

        carry, token, finished = state_c
        if compact:
            carry = jax.tree.map(lambda x: jnp.take(x, inv, axis=1), carry)
            token = jnp.take(token, inv, axis=1)
            finished = jnp.take(finished, inv, axis=1)
            tok_chunk = jnp.take(tok_chunk, inv, axis=2)
            lp_chunk = jnp.take(lp_chunk, inv, axis=2)
        tok_buf = jax.lax.dynamic_update_slice_in_dim(tok_buf, tok_chunk, t, 0)
        lp_buf = jax.lax.dynamic_update_slice_in_dim(lp_buf, lp_chunk, t, 0)
        return (
            t + S,
            (carry, token, finished),
            (tok_buf, lp_buf),
            count_unfinished(finished),
        )

    # overhang steps past T (S not dividing T, final chunk only) need no
    # state freeze: finished is monotonic, the loop cond exits on t >= T
    # regardless, and the final state is discarded — only the buffer rows
    # below T survive
    _, _, (tok_buf, lp_buf), _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), init, bufs, count_unfinished(init[2]))
    )
    return tok_buf[:T], lp_buf[:T]


def fused_decode(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    rng: jax.Array,
    num_rollouts: int = 1,
    temperature: float = 1.0,
    max_len: int | None = None,
    min_len: int = 0,
    batch_axes: tuple[str, ...] = (),
    decode_stride: int | None = None,
    compact: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """-> (greedy [B,T], greedy_lp [B,T], tokens [K,B,T], logprobs [K,B,T]).

    Lane 0 is the greedy baseline (argmax of untempered logits, no RNG
    consumed); lanes 1..K are the Monte-Carlo rollouts on ``sample_decode``'s
    exact key stream. ``logprobs`` are untempered model logprobs of the
    chosen tokens (``selected_logprob``); PAD/0 after EOS on every lane.

    ``decode_stride`` / ``compact`` default from ``model.cfg``
    (``decode_stride`` / ``decode_compact``); pass explicit values to
    override per call (the parity tests and bench sweep do). Stride 1
    without compaction is the per-step loop every other combination is
    pinned token/logprob-exact against.
    """
    T = max_len or model.cfg.max_len
    K = num_rollouts
    S = (
        decode_stride if decode_stride is not None
        else getattr(model.cfg, "decode_stride", 1)
    )
    S = max(1, min(int(S), T))
    if compact is None:
        compact = bool(getattr(model.cfg, "decode_compact", False))
    if S == 1:
        # compaction only pays between strides (the per-step kernel takes
        # no active-prefix, and permuting between every step buys nothing);
        # stride 1 therefore always means the plain per-step loop
        compact = False
    enc: EncoderOutput = model.apply(
        params, feats, masks, method=CaptionModel.encode
    )
    B = enc.memory.shape[0]
    step_keys = rollout_step_keys(rng, K, T)  # [T, K] — lane 0 never draws

    if S == 1 and not compact:
        # the per-step loop: scan_until_finished's fine-grained early exit
        # (exit check every ~5 steps), the exactness baseline
        step = _sel_step(
            model, params, enc, step_keys, B, model.cfg.vocab_size,
            temperature, min_len, None,
        )
        init = (
            jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (1 + K,) + x.shape),
                enc.carry,
            ),
            jnp.full((1 + K, B), BOS_ID, jnp.int32),
            jnp.zeros((1 + K, B), bool),
        )
        _, (tokens, logprobs) = scan_until_finished(
            step, init, T, lambda s: s[2], (PAD_ID, 0.0), batch_axes
        )
    else:
        tokens, logprobs = _stride_decode(
            model, params, enc, step_keys, B, T, S, K, temperature, min_len,
            compact, batch_axes,
        )
    # ys stack on axis 0: [T, 1+K, B] -> [1+K, B, T]
    tokens = tokens.transpose(1, 2, 0)
    logprobs = logprobs.transpose(1, 2, 0)
    return tokens[0], logprobs[0], tokens[1:], logprobs[1:]


def npad_decode(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    rng: jax.Array,
    num_lanes: int = 4,
    temperature: float = 1.0,
    max_len: int | None = None,
    min_len: int = 0,
    batch_axes: tuple[str, ...] = (),
    decode_stride: int | None = None,
    compact: bool | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Noisy Parallel Approximate Decoding -> (tokens [B, T], scores [B]).

    arXiv 1605.03835: decode the greedy lane plus ``num_lanes`` noise-
    perturbed lanes IN PARALLEL (they drop into the fused loop's (1+K)-lane
    pool, so the marginal cost over greedy is one wider lane axis, not M
    sequential decodes), then answer with the highest-sum-logprob lane.
    The anytime property the evaluator's NPAD mode leans on: lane 0 is the
    unperturbed greedy lane and argmax ties break toward it, so the answer
    is >= greedy by construction (pinned in tests/test_decoding.py) at a
    latency near greedy's — the budget-friendly stand-in for beam search.
    ``scores`` are the winning lane's sum-logprobs (PAD rows contribute
    0.0, so it is exactly the sequence logprob, the beam ranking scale).
    """
    g_tok, g_lp, s_tok, s_lp = fused_decode(
        model, params, feats, masks, rng, num_rollouts=num_lanes,
        temperature=temperature, max_len=max_len, min_len=min_len,
        batch_axes=batch_axes, decode_stride=decode_stride, compact=compact,
    )
    tokens = jnp.concatenate([g_tok[None], s_tok], axis=0)     # [1+M, B, T]
    logprobs = jnp.concatenate([g_lp[None], s_lp], axis=0)
    return npad_best_lane(tokens, logprobs)
