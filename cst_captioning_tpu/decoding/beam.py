"""Batched fixed-shape beam search under ``lax.scan`` (eval config 5).

Reference behavior: ``model.sample(feats, beam_size=5)`` per-step topk over
beam×vocab (SURVEY.md §3.3). The classic tricky kernel (§7 "hard parts"):
everything is static-shape —

- finished beams may only "continue" with PAD at logprob 0, so their score is
  frozen while still participating in top-k,
- beam 0 alone is live at t=0 (others start at -1e9) so the first expansion
  doesn't pick W copies of the same token,
- one ``top_k`` over the flattened ``W*V`` axis per step; parent beams are
  gathered with ``take_along_axis`` over every carry leaf.

Two implementations share that candidate math (``_topk_expand``):

- ``beam_impl="reference"`` — the original sequential spelling: beams are
  flattened into the batch (state carry ``[B*W, ...]``) and every step runs
  one ``model.decode_step`` over the tiled batch. Kept verbatim as the
  bit-parity oracle.
- ``beam_impl="lanes"`` (default) — beams ride the shared (1+K)-lane decode
  step from decoding/fused.py (``lane_decode_step``): state carry is lane-
  major ``[W, B, ...]``, one lane per beam, so beam search reuses the exact
  step program the fused RL loop and the serving engine compile — including
  the fused Pallas step kernel when ``model.cfg.decode_impl == "pallas"``,
  where the per-step top-k itself moves in-kernel
  (``ops.decode_pallas.fused_beam_step``: blocked online logsumexp + blocked
  top-W over (lane, vocab-block)). Beam-hypothesis reordering is a cross-
  lane gather and therefore happens OUTSIDE the kernel, between launches —
  the seam where decoding/fused.py compacts finished columns.

Lane-vs-reference token- and score-bit-exactness at beam∈{1,3,5} is pinned
in tests/test_decoding.py and re-asserted in every bench_eval.py run (the
parity block in BENCH_EVAL_E2E.json). The guarantee rests on per-row bit-
stability of the decode step across batch layouts (vmap lanes over [B] vs
one flat [B*W] batch) — the same property that makes the fused loop's
greedy lane bit-exact against the two-loop reference.

Correctness is also pinned by tests: beam=1 ≡ greedy, and a brute-force
enumeration oracle on a tiny vocab (SURVEY.md §4 item 2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.decoding.common import (
    apply_min_len,
    forbid_special,
    lane_decode_step,
    row_logprobs,
    scan_until_finished,
)
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput

_NEG = -1.0e9

BEAM_IMPLS = ("lanes", "reference")


def _tile_beam(tree, beam: int):
    """[B, ...] leaves -> [B*beam, ...] (each row repeated beam times)."""
    return jax.tree.map(
        lambda x: jnp.repeat(x, beam, axis=0), tree
    )


def _gather_beams(tree, parent: jnp.ndarray, batch: int, beam: int):
    """Select parent beams: leaves [B*W, ...] indexed by parent [B, W]."""
    flat_idx = (jnp.arange(batch)[:, None] * beam + parent).reshape(-1)  # [B*W]
    return jax.tree.map(lambda x: x[flat_idx], tree)


def _gather_lanes(tree, parent: jnp.ndarray):
    """Select parent beams on LANE-major leaves: [W, B, ...] by parent [B, W].

    ``out[w, b] = leaf[parent[b, w], b]`` — the beam-hypothesis reorder as a
    cross-lane gather, the lane layout's spelling of ``_gather_beams``.
    """
    pT = parent.T  # [W, B]
    return jax.tree.map(
        lambda x: jnp.take_along_axis(
            x, pT.reshape(pT.shape + (1,) * (x.ndim - 2)), axis=0
        ),
        tree,
    )


def _pad_row(V: int) -> jnp.ndarray:
    """Continuation row for finished beams: logp 0 at PAD, -1e9 else."""
    return jnp.full((V,), _NEG).at[PAD_ID].set(0.0)


def _topk_expand(scores, finished, logp, pad_row, B: int, W: int, V: int):
    """The per-step beam expansion both impls share.

    (scores [B,W], finished [B,W], logp [B,W,V]) ->
    (top_scores [B,W], parent [B,W], tok [B,W]) — finished beams continue
    with the PAD-only row, one ``top_k`` over the flattened W*V candidates
    (ties break toward the lower flat index = lower beam, then lower token).
    """
    cont = jnp.where(finished[:, :, None], pad_row[None, None, :], logp)
    total = scores[:, :, None] + cont                      # [B, W, V]
    top_scores, flat = jax.lax.top_k(total.reshape(B, W * V), W)
    parent = flat // V                                     # [B, W]
    tok = (flat % V).astype(jnp.int32)
    return top_scores, parent, tok


def _state0(carry0, B: int, W: int, T: int):
    """(carry, tokens, scores, finished, last): beam 0 alone live at t=0."""
    return (
        carry0,
        jnp.full((B, W, T), PAD_ID, jnp.int32),
        jnp.concatenate([jnp.zeros((B, 1)), jnp.full((B, W - 1), _NEG)], axis=1),
        jnp.zeros((B, W), bool),
        jnp.full((B, W), BOS_ID, jnp.int32),
    )


def _run_reference(model, params, enc, B, V, W, T, min_len, batch_axes):
    """The sequential spelling: beams flattened into the batch ([B*W] rows)."""
    enc_tiled = _tile_beam(enc, W)          # leaves [B*W, ...]
    carry0 = enc_tiled.carry
    enc_tiled = EncoderOutput(
        enc_tiled.memory, enc_tiled.memory_proj, enc_tiled.memory_mask, carry=()
    )
    pad_row = _pad_row(V)

    def step(state, t):
        carry, tokens, scores, finished, last = state
        carry, logits = model.apply(
            params,
            carry,
            last.reshape(B * W),
            enc_tiled,
            method=CaptionModel.decode_step,
        )
        logits = apply_min_len(forbid_special(logits), t, min_len)
        logp = row_logprobs(logits).reshape(B, W, V)
        top_scores, parent, tok = _topk_expand(
            scores, finished, logp, pad_row, B, W, V
        )

        carry = _gather_beams(carry, parent, B, W)
        tokens = jnp.take_along_axis(tokens, parent[:, :, None], axis=1)
        finished = jnp.take_along_axis(finished, parent, axis=1)
        tok = jnp.where(finished, jnp.full_like(tok, PAD_ID), tok)
        tokens = tokens.at[:, :, t].set(tok)
        finished = finished | (tok == EOS_ID)
        return (carry, tokens, top_scores, finished, tok), None

    # Early exit once every beam of every row is finished — bit-identical to
    # the full T-step unroll: with all beams finished, every continuation row
    # is the PAD-only ``pad_row``, so the per-beam top candidate is its own
    # frozen score at PAD, and since top_k returned ``scores`` DESCENDING on
    # the step that finished the last beam (ties broken toward lower flat
    # index = lower beam), the next top_k re-selects the beams in their
    # current order: parent is the identity, tok is PAD everywhere, and the
    # whole state is a fixed point of ``step``.
    (_, tokens, scores, _, _), _ = scan_until_finished(
        step, _state0(carry0, B, W, T), T, lambda s: s[3], None, batch_axes
    )
    return tokens, scores


def _run_lanes(model, params, enc, B, V, W, T, min_len, batch_axes):
    """Beams on decode lanes: carry [W, B, ...], one shared-step lane per beam."""
    carry0 = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (W,) + x.shape), enc.carry
    )
    pad_row = _pad_row(V)
    use_kernel = getattr(model.cfg, "decode_impl", "xla") == "pallas"

    def step(state, t):
        carry, tokens, scores, finished, last = state  # carry [W, B, ...]
        if use_kernel:
            from cst_captioning_tpu.ops.decode_pallas import fused_beam_step

            # step + candidate selection in ONE launch: blocked online
            # logsumexp and blocked top-W per (lane, vocab-block), cross-
            # lane merge in-kernel; only the hypothesis reorder (a cross-
            # lane gather) stays out here at the seam between launches
            carry, top_scores, flat = fused_beam_step(
                params["params"]["cell"], carry, last, finished.T,
                scores.T.astype(jnp.float32), enc.memory, enc.memory_proj,
                enc.memory_mask, t=t, min_len=min_len,
                num_layers=model.cfg.num_layers,
            )
            parent = flat // V
            tok = (flat % V).astype(jnp.int32)
            top_scores = top_scores.astype(scores.dtype)
        else:
            carry, logits = lane_decode_step(model, params, carry, last, enc)
            logits = apply_min_len(forbid_special(logits), t, min_len)
            logp = row_logprobs(logits).transpose(1, 0, 2)   # [B, W, V]
            top_scores, parent, tok = _topk_expand(
                scores, finished, logp, pad_row, B, W, V
            )

        carry = _gather_lanes(carry, parent)
        tokens = jnp.take_along_axis(tokens, parent[:, :, None], axis=1)
        finished = jnp.take_along_axis(finished, parent, axis=1)
        tok = jnp.where(finished, jnp.full_like(tok, PAD_ID), tok)
        tokens = tokens.at[:, :, t].set(tok)
        finished = finished | (tok == EOS_ID)
        return (carry, tokens, top_scores, finished, tok.T), None

    # the lane-major state0: last tokens live as [W, B]
    carry, tokens, scores, finished, last = _state0(carry0, B, W, T)
    state0 = (carry, tokens, scores, finished, last.T)
    # same all-finished fixed point as the reference (see _run_reference)
    (_, tokens, scores, _, _), _ = scan_until_finished(
        step, state0, T, lambda s: s[3], None, batch_axes
    )
    return tokens, scores


def beam_search(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    beam_size: int = 5,
    max_len: int | None = None,
    min_len: int = 0,
    length_penalty: float = 0.0,
    return_all: bool = False,
    batch_axes: tuple[str, ...] = (),
    beam_impl: str = "lanes",
):
    """-> (tokens [B, T], scores [B]) — or [B, W, T] / [B, W] if return_all.

    ``length_penalty`` α rescales final scores by ``1/len^α`` (α=0 matches the
    reference's pure sum-logprob ranking). ``beam_impl`` picks the lane-
    batched fast path ("lanes", default) or the sequential bit-parity
    reference ("reference") — token- and score-bit-exact against each other
    (module docstring).
    """
    if beam_impl not in BEAM_IMPLS:
        raise ValueError(
            f"beam_impl must be one of {BEAM_IMPLS}, got {beam_impl!r}"
        )
    W = beam_size
    T = max_len or model.cfg.max_len
    enc: EncoderOutput = model.apply(params, feats, masks, method=CaptionModel.encode)
    B = enc.memory.shape[0]
    V = model.cfg.vocab_size

    run = _run_lanes if beam_impl == "lanes" else _run_reference
    tokens, scores = run(model, params, enc, B, V, W, T, min_len, batch_axes)

    if length_penalty > 0.0:
        lengths = jnp.maximum((tokens != PAD_ID).sum(axis=-1), 1).astype(jnp.float32)
        ranked = scores / (lengths**length_penalty)
    else:
        ranked = scores
    if return_all:
        order = jnp.argsort(-ranked, axis=1)
        return (
            jnp.take_along_axis(tokens, order[:, :, None], axis=1),
            jnp.take_along_axis(ranked, order, axis=1),
        )
    best = jnp.argmax(ranked, axis=1)                           # [B]
    best_tokens = jnp.take_along_axis(tokens, best[:, None, None], axis=1)[:, 0]
    best_scores = jnp.take_along_axis(ranked, best[:, None], axis=1)[:, 0]
    return best_tokens, best_scores
