"""Batched fixed-shape beam search under ``lax.scan`` (eval config 5).

Reference behavior: ``model.sample(feats, beam_size=5)`` per-step topk over
beam×vocab (SURVEY.md §3.3). The classic tricky kernel (§7 "hard parts"):
everything is static-shape —

- state is ``(carry[B*W], tokens[B, W, T], scores[B, W], finished[B, W])``,
- finished beams may only "continue" with PAD at logprob 0, so their score is
  frozen while still participating in top-k,
- beam 0 alone is live at t=0 (others start at -1e9) so the first expansion
  doesn't pick W copies of the same token,
- one ``top_k`` over the flattened ``W*V`` axis per step; parent beams are
  gathered with ``take_along_axis`` over every carry leaf.

Correctness is pinned by tests: beam=1 ≡ greedy, and a brute-force
enumeration oracle on a tiny vocab (SURVEY.md §4 item 2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.decoding.common import (
    apply_min_len,
    forbid_special,
    scan_until_finished,
)
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput

_NEG = -1.0e9


def _tile_beam(tree, beam: int):
    """[B, ...] leaves -> [B*beam, ...] (each row repeated beam times)."""
    return jax.tree.map(
        lambda x: jnp.repeat(x, beam, axis=0), tree
    )


def _gather_beams(tree, parent: jnp.ndarray, batch: int, beam: int):
    """Select parent beams: leaves [B*W, ...] indexed by parent [B, W]."""
    flat_idx = (jnp.arange(batch)[:, None] * beam + parent).reshape(-1)  # [B*W]
    return jax.tree.map(lambda x: x[flat_idx], tree)


def beam_search(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    beam_size: int = 5,
    max_len: int | None = None,
    min_len: int = 0,
    length_penalty: float = 0.0,
    return_all: bool = False,
    batch_axes: tuple[str, ...] = (),
):
    """-> (tokens [B, T], scores [B]) — or [B, W, T] / [B, W] if return_all.

    ``length_penalty`` α rescales final scores by ``1/len^α`` (α=0 matches the
    reference's pure sum-logprob ranking).
    """
    W = beam_size
    T = max_len or model.cfg.max_len
    enc: EncoderOutput = model.apply(params, feats, masks, method=CaptionModel.encode)
    B = enc.memory.shape[0]
    V = model.cfg.vocab_size

    enc_tiled = _tile_beam(enc, W)          # leaves [B*W, ...]
    carry0 = enc_tiled.carry
    enc_tiled = EncoderOutput(
        enc_tiled.memory, enc_tiled.memory_proj, enc_tiled.memory_mask, carry=()
    )

    # PAD-only continuation row for finished beams: logp 0 at PAD, -inf else
    pad_row = jnp.full((V,), _NEG).at[PAD_ID].set(0.0)

    def step(state, t):
        carry, tokens, scores, finished, last = state
        carry, logits = model.apply(
            params,
            carry,
            last.reshape(B * W),
            enc_tiled,
            method=CaptionModel.decode_step,
        )
        logits = apply_min_len(forbid_special(logits), t, min_len)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, W, V)
        cont = jnp.where(finished[:, :, None], pad_row[None, None, :], logp)
        total = scores[:, :, None] + cont                      # [B, W, V]
        top_scores, flat = jax.lax.top_k(total.reshape(B, W * V), W)
        parent = flat // V                                     # [B, W]
        tok = (flat % V).astype(jnp.int32)

        carry = _gather_beams(carry, parent, B, W)
        tokens = jnp.take_along_axis(tokens, parent[:, :, None], axis=1)
        finished = jnp.take_along_axis(finished, parent, axis=1)
        tok = jnp.where(finished, jnp.full_like(tok, PAD_ID), tok)
        tokens = tokens.at[:, :, t].set(tok)
        finished = finished | (tok == EOS_ID)
        return (carry, tokens, top_scores, finished, tok), None

    state0 = (
        carry0,
        jnp.full((B, W, T), PAD_ID, jnp.int32),
        jnp.concatenate([jnp.zeros((B, 1)), jnp.full((B, W - 1), _NEG)], axis=1),
        jnp.zeros((B, W), bool),
        jnp.full((B, W), BOS_ID, jnp.int32),
    )
    # Early exit once every beam of every row is finished — bit-identical to
    # the full T-step unroll: with all beams finished, every continuation row
    # is the PAD-only ``pad_row``, so the per-beam top candidate is its own
    # frozen score at PAD, and since top_k returned ``scores`` DESCENDING on
    # the step that finished the last beam (ties broken toward lower flat
    # index = lower beam), the next top_k re-selects the beams in their
    # current order: parent is the identity, tok is PAD everywhere, and the
    # whole state is a fixed point of ``step``.
    (_, tokens, scores, _, _), _ = scan_until_finished(
        step, state0, T, lambda s: s[3], None, batch_axes
    )

    if length_penalty > 0.0:
        lengths = jnp.maximum((tokens != PAD_ID).sum(axis=-1), 1).astype(jnp.float32)
        ranked = scores / (lengths**length_penalty)
    else:
        ranked = scores
    if return_all:
        order = jnp.argsort(-ranked, axis=1)
        return (
            jnp.take_along_axis(tokens, order[:, :, None], axis=1),
            jnp.take_along_axis(ranked, order, axis=1),
        )
    best = jnp.argmax(ranked, axis=1)                           # [B]
    best_tokens = jnp.take_along_axis(tokens, best[:, None, None], axis=1)[:, 0]
    best_scores = jnp.take_along_axis(ranked, best[:, None], axis=1)[:, 0]
    return best_tokens, best_scores
