"""Greedy decoding under ``lax.scan`` (the SCST baseline decode).

Reference behavior: ``model.sample(feats, greedy)`` — argmax token per step,
stop at EOS (SURVEY.md §3.2). Runs the shared lane-batched decode step as a
single lane (G=1), so the step numerics are lane-for-lane identical to the
sampling and fused RL loops (vmap lane results are independent of the lane
count — what makes the fused loop's greedy row bit-exact against this one,
pinned in tests/test_decoding.py). One compiled program per (batch,
max_len) shape; ``model.cfg.decode_impl`` selects the XLA composite step or
the fused Pallas kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, PAD_ID
from cst_captioning_tpu.decoding.common import (
    apply_min_len,
    forbid_special,
    lane_decode_step,
    scan_until_finished,
    selected_logprob,
    step_outputs,
)
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput


def greedy_decode(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    max_len: int | None = None,
    min_len: int = 0,
    batch_axes: tuple[str, ...] = (),
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (tokens [B, T], logprobs [B, T]); PAD/0 after EOS.

    The step loop exits as soon as every row has emitted EOS (psum'd over
    ``batch_axes`` when the batch is sharded) — bit-identical to the full
    unroll because post-EOS steps emit exactly (PAD, 0.0), which is what the
    output buffers are pre-filled with.
    """
    T = max_len or model.cfg.max_len
    enc: EncoderOutput = model.apply(params, feats, masks, method=CaptionModel.encode)
    B = enc.memory.shape[0]

    def step(state, t):
        carry, token, finished = state  # carry leaves [1, B, ...]; [1, B]
        carry, logits = lane_decode_step(model, params, carry, token, enc)
        logits = apply_min_len(forbid_special(logits), t, min_len)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lp = selected_logprob(logits, nxt)
        nxt, lp, finished = step_outputs(nxt, lp, finished)
        return (carry, nxt, finished), (nxt, lp)

    init = (
        jax.tree.map(lambda x: x[None], enc.carry),
        jnp.full((1, B), BOS_ID, jnp.int32),
        jnp.zeros((1, B), bool),
    )
    _, (tokens, logprobs) = scan_until_finished(
        step, init, T, lambda s: s[2], (PAD_ID, 0.0), batch_axes
    )
    # ys stack on axis 0: [T, 1, B] -> [B, T]
    return tokens[:, 0].T, logprobs[:, 0].T
