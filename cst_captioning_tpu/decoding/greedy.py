"""Greedy decoding under ``lax.scan`` (the SCST baseline decode).

Reference behavior: ``model.sample(feats, greedy)`` — argmax token per step,
stop at EOS (SURVEY.md §3.2). Runs the shared ``decode_step``; one compiled
program per (batch, max_len) shape.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, PAD_ID
from cst_captioning_tpu.decoding.common import (
    apply_min_len,
    forbid_special,
    scan_until_finished,
    step_outputs,
)
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput


def greedy_decode(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    max_len: int | None = None,
    min_len: int = 0,
    batch_axes: tuple[str, ...] = (),
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (tokens [B, T], logprobs [B, T]); PAD/0 after EOS.

    The step loop exits as soon as every row has emitted EOS (psum'd over
    ``batch_axes`` when the batch is sharded) — bit-identical to the full
    unroll because post-EOS steps emit exactly (PAD, 0.0), which is what the
    output buffers are pre-filled with.
    """
    T = max_len or model.cfg.max_len
    enc: EncoderOutput = model.apply(params, feats, masks, method=CaptionModel.encode)
    B = enc.memory.shape[0]

    def step(state, t):
        carry, token, finished = state
        carry, logits = model.apply(
            params, carry, token, enc, method=CaptionModel.decode_step
        )
        logits = apply_min_len(forbid_special(logits), t, min_len)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
        nxt, lp, finished = step_outputs(nxt, lp, finished)
        return (carry, nxt, finished), (nxt, lp)

    init = (enc.carry, jnp.full((B,), BOS_ID, jnp.int32), jnp.zeros((B,), bool))
    _, (tokens, logprobs) = scan_until_finished(
        step, init, T, lambda s: s[2], (PAD_ID, 0.0), batch_axes
    )
    return tokens.T, logprobs.T  # ys stack on axis 0 -> [B, T]
