"""Decoding strategies: greedy, multinomial K-rollout sampling, beam search.

Rebuilds the reference's ``CaptionModel.sample`` modes (SURVEY.md §2 row 4,
§7 step 4) as pure jittable functions over ``CaptionModel``'s ``encode`` /
``decode_step``. All loops are ``lax.scan`` with static shapes — no Python
per-step dispatch, so a whole decode is one XLA program.
"""

from cst_captioning_tpu.decoding.greedy import greedy_decode
from cst_captioning_tpu.decoding.sample import sample_decode
from cst_captioning_tpu.decoding.fused import fused_decode, npad_decode
from cst_captioning_tpu.decoding.beam import beam_search

__all__ = [
    "greedy_decode", "sample_decode", "fused_decode", "npad_decode",
    "beam_search",
]
