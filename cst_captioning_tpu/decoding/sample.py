"""Multinomial sampling with K Monte-Carlo rollouts per clip.

Reference behavior: ``model.sample(feats, multinomial × K)`` — temperature
sampling, K rollouts per video for the consensus reward (SURVEY.md §3.2,
BASELINE config 4). The encoder pass is shared across rollouts (computed
once); the decode loop is vmapped over K rollout RNGs, so all K×B sequences
decode in one XLA program — the fused "one launch" design of §7 step 5.

RNG discipline: rollout k at step t uses ``fold_in(fold_in(key, k), t)`` —
reproducible regardless of batch sharding or rollout count.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID
from cst_captioning_tpu.decoding.common import apply_min_len, forbid_special, step_outputs
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput


def sample_decode(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    rng: jax.Array,
    num_rollouts: int = 1,
    temperature: float = 1.0,
    max_len: int | None = None,
    min_len: int = 0,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (tokens [K, B, T], logprobs [K, B, T]); PAD/0 after EOS.

    ``logprobs`` are the *untempered* model logprobs of the sampled tokens
    (the REINFORCE estimator needs log p_model, not log p_temperature).
    """
    T = max_len or model.cfg.max_len
    enc: EncoderOutput = model.apply(params, feats, masks, method=CaptionModel.encode)
    B = enc.memory.shape[0]

    def rollout(k_rng):
        def step(state, t):
            carry, token, finished = state
            carry, logits = model.apply(
                params, carry, token, enc, method=CaptionModel.decode_step
            )
            logits = apply_min_len(forbid_special(logits), t, min_len)
            step_rng = jax.random.fold_in(k_rng, t)
            nxt = jax.random.categorical(step_rng, logits / temperature, axis=-1)
            nxt = nxt.astype(jnp.int32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            lp = jnp.take_along_axis(logp, nxt[:, None], axis=-1)[:, 0]
            nxt, lp, finished = step_outputs(nxt, lp, finished)
            return (carry, nxt, finished), (nxt, lp)

        init = (enc.carry, jnp.full((B,), BOS_ID, jnp.int32), jnp.zeros((B,), bool))
        _, (tokens, logprobs) = jax.lax.scan(step, init, jnp.arange(T))
        return tokens.T, logprobs.T

    keys = jax.vmap(lambda k: jax.random.fold_in(rng, k))(jnp.arange(num_rollouts))
    tokens, logprobs = jax.vmap(rollout)(keys)
    return tokens, logprobs
