"""Multinomial sampling with K Monte-Carlo rollouts per clip.

Reference behavior: ``model.sample(feats, multinomial × K)`` — temperature
sampling, K rollouts per video for the consensus reward (SURVEY.md §3.2,
BASELINE config 4). The encoder pass is shared across rollouts (computed
once, closed over by the rollout-vmapped decode step); all K×B sequences
decode in ONE XLA program — the fused "one launch" design of §7 step 5 —
whose loop exits as soon as every rollout of every clip has emitted EOS.

RNG discipline: rollout k at step t uses ``fold_in(fold_in(key, k), t)``,
drawn per-rollout over its [B, V] logits block — reproducible regardless of
batch sharding or rollout count. The whole [T, K] key array is precomputed
outside the scan (``rollout_step_keys``); the step body gathers row ``t``
instead of re-folding K keys per iteration — the same stream bit-for-bit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, PAD_ID
from cst_captioning_tpu.decoding.common import (
    apply_min_len,
    forbid_special,
    gumbel_step_noise,
    lane_decode_step,
    rollout_step_keys,
    scan_until_finished,
    selected_logprob,
    step_outputs,
)
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput


def sample_decode(
    model: CaptionModel,
    params,
    feats: dict[str, jnp.ndarray],
    masks: dict[str, jnp.ndarray],
    rng: jax.Array,
    num_rollouts: int = 1,
    temperature: float = 1.0,
    max_len: int | None = None,
    min_len: int = 0,
    batch_axes: tuple[str, ...] = (),
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """-> (tokens [K, B, T], logprobs [K, B, T]); PAD/0 after EOS.

    ``logprobs`` are the *untempered* model logprobs of the sampled tokens
    (the REINFORCE estimator needs log p_model, not log p_temperature).
    """
    T = max_len or model.cfg.max_len
    K = num_rollouts
    enc: EncoderOutput = model.apply(params, feats, masks, method=CaptionModel.encode)
    B = enc.memory.shape[0]

    # the decode step is lane-batched over the rollout axis with the encoder
    # output CLOSED OVER (unbatched): XLA reads the memory bank once per
    # step and fuses the additive-attention broadcast across rollouts. (A
    # flat [K*B]-row layout with tiled memory was measured 80% slower at the
    # flagship dims, round 5 — the tile defeats that fusion.)
    step_keys = rollout_step_keys(rng, K, T)  # [T, K]

    def step(state, t):
        carry, token, finished = state  # carry leaves [K, B, ...]; [K, B]
        carry, logits = lane_decode_step(model, params, carry, token, enc)
        logits = apply_min_len(forbid_special(logits), t, min_len)  # [K,B,V]
        # Gumbel-max form of ``categorical(key, logits / temperature)`` —
        # bit-identical (gumbel_step_noise docstring), and the same selection
        # the fused stride paths run, so every sampler shares one spelling
        tl = logits / temperature
        noise = gumbel_step_noise(step_keys[t], tl.shape[1:], tl.dtype)
        nxt = jnp.argmax(tl + noise, axis=-1).astype(jnp.int32)
        lp = selected_logprob(logits, nxt)
        nxt, lp, finished = step_outputs(nxt, lp, finished)
        return (carry, nxt, finished), (nxt, lp)

    init = (
        # broadcast (no reshape): stays a view for the vmapped step
        jax.tree.map(
            lambda x: jnp.broadcast_to(x[None], (K,) + x.shape), enc.carry
        ),
        jnp.full((K, B), BOS_ID, jnp.int32),
        jnp.zeros((K, B), bool),
    )
    _, (tokens, logprobs) = scan_until_finished(
        step, init, T, lambda s: s[2], (PAD_ID, 0.0), batch_axes
    )
    # ys stack on axis 0: [T, K, B] -> [K, B, T]
    return tokens.transpose(1, 2, 0), logprobs.transpose(1, 2, 0)
