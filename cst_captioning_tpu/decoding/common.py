"""Shared decode-loop plumbing."""

from __future__ import annotations

import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID


def forbid_special(logits: jnp.ndarray) -> jnp.ndarray:
    """Mask PAD/BOS columns to -inf for decoding.

    The reference's vocab overloads id 0 as its pad/end token, so sampling it
    means "stop"; here PAD and EOS are distinct ids, so decoders must never
    *emit* PAD or BOS — EOS is the only way to end a caption.
    """
    neg = jnp.full_like(logits[..., :1], -1e9)
    return logits.at[..., PAD_ID].set(neg[..., 0]).at[..., BOS_ID].set(neg[..., 0])


def step_outputs(
    token: jnp.ndarray,      # [B] token chosen this step
    logprob: jnp.ndarray,    # [B] its logprob
    finished: jnp.ndarray,   # [B] bool: sequence already emitted EOS
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Force PAD / zero-logprob after EOS; returns (token, logprob, finished')."""
    token = jnp.where(finished, jnp.full_like(token, PAD_ID), token)
    logprob = jnp.where(finished, jnp.zeros_like(logprob), logprob)
    finished = finished | (token == EOS_ID)
    return token, logprob, finished


def mask_from_tokens(tokens: jnp.ndarray) -> jnp.ndarray:
    """[.., T] decoded tokens -> float mask counting real tokens incl. EOS."""
    return (tokens != PAD_ID).astype(jnp.float32)


def apply_min_len(logits: jnp.ndarray, t, min_len: int) -> jnp.ndarray:
    """Suppress EOS while step ``t`` < ``min_len`` (prevents empty captions).

    The reference ranks beams by pure sum-logprob, which lets EOS-first beams
    win on weak models; a min caption length is the standard guard. No-op for
    ``min_len`` 0 (reference behavior).
    """
    if min_len <= 0:
        return logits
    blocked = logits.at[..., EOS_ID].set(-1.0e9)
    return jnp.where(t < min_len, blocked, logits)
