"""Shared decode-loop plumbing."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from cst_captioning_tpu.compat import pcast, vma_of
from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID


def selected_logprob(logits: jnp.ndarray, token: jnp.ndarray) -> jnp.ndarray:
    """Logprob of ``token`` under softmax(logits) — [..., V], [...] -> [...].

    ``logit - logsumexp(logits)`` on the selected row only: one [.., V]
    reduction plus a gather, instead of materializing the full ``[.., V]``
    ``log_softmax`` output just to gather one column from it (one fewer
    full-vocab pass per decode step). Matches ``log_softmax`` + gather to
    float association order.
    """
    lse = jax.nn.logsumexp(logits, axis=-1)
    sel = jnp.take_along_axis(logits, token[..., None], axis=-1)[..., 0]
    return sel - lse


def row_logprobs(logits: jnp.ndarray) -> jnp.ndarray:
    """Full log-softmax row in the :func:`selected_logprob` association.

    ``logits - logsumexp(logits, keepdims=True)`` — gathering a column of
    this row is BITWISE equal to ``selected_logprob(logits, token)`` (same
    subtraction, same operand order), which is what lets the beam loops
    score whole rows while the greedy/sampling loops score one token, with
    one shared primitive. Note this differs from ``jax.nn.log_softmax`` by
    float association (``x - (max + log s)`` vs ``(x - max) - log s``), so
    every decoder that wants cross-impl bit-parity must route through here.
    """
    return logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)


def npad_best_lane_index(logprobs) -> jnp.ndarray:
    """[G, .., T] per-token logprobs -> [..] best lane per row (NPAD pick).

    Noisy Parallel Approximate Decoding (arXiv 1605.03835): run the greedy
    lane plus M noise-perturbed lanes, answer with the highest-sum-logprob
    lane. Post-EOS emissions carry logprob 0.0 (``step_outputs``), so the
    sum is exactly the sequence logprob. argmax ties break toward the
    LOWEST lane — lane 0 is the unperturbed greedy lane, so the anytime
    answer degrades to greedy, never below it. Backend-agnostic on purpose
    (pure array methods): the serving engine calls it on host numpy
    tickets ([G, T] -> scalar), the evaluator on device arrays
    ([G, B, T] -> [B]).
    """
    return logprobs.sum(axis=-1).argmax(axis=0)


def npad_best_lane(tokens: jnp.ndarray, logprobs: jnp.ndarray):
    """Select the NPAD answer: ([G, B, T], [G, B, T]) -> ([B, T], [B]).

    Returns the best lane's token rows and their sum-logprob scores,
    gathered with ``take_along_axis`` so the whole selection stays on
    device (one scalar readback for the caller, not G of them).
    """
    best = npad_best_lane_index(logprobs)                       # [B]
    idx = best[None, :, None]                                   # [1, B, 1]
    best_tokens = jnp.take_along_axis(tokens, idx, axis=0)[0]   # [B, T]
    best_scores = jnp.take_along_axis(
        logprobs.sum(axis=-1), best[None, :], axis=0
    )[0]                                                        # [B]
    return best_tokens, best_scores


def rollout_step_keys(rng: jax.Array, num_rollouts: int, length: int) -> jax.Array:
    """[T, K] typed key array with ``keys[t, k] == fold_in(fold_in(rng, k), t)``.

    The sampling loops' per-step RNG discipline, precomputed OUTSIDE the
    scan: the step body gathers row ``t`` (one dynamic slice of K keys)
    instead of re-folding K keys every iteration — bit-identical streams by
    construction (same fold chain), asserted in tests/test_decoding.py.
    Steps past ``length`` (the early-exit loop's overhang, see
    :func:`scan_until_finished`) clamp to row T-1; their draws are
    select-frozen out of the outputs, so the clamped reuse is unobservable.
    """
    keys = jax.vmap(lambda k: jax.random.fold_in(rng, k))(
        jnp.arange(num_rollouts)
    )
    return jax.vmap(
        lambda t: jax.vmap(lambda key: jax.random.fold_in(key, t))(keys)
    )(jnp.arange(length))


def gumbel_step_noise(step_keys_t: jax.Array, shape: tuple[int, ...],
                      dtype) -> jax.Array:
    """[K] keys -> [K, *shape] Gumbel noise — ``jax.random.categorical``'s
    internals, reified.

    ``categorical(key, logits)`` is by definition
    ``argmax(logits + gumbel(key, logits.shape, logits.dtype))`` (the Gumbel
    -max trick; jax implements it literally), and IEEE addition is
    commutative, so selecting via this precomputed noise is BIT-IDENTICAL
    to the categorical call it replaces (pinned in tests/test_decoding.py).
    Reifying the noise is what lets (a) the compacted decode draw in
    ORIGINAL batch order and gather rows through the compaction permutation
    — drawing after the gather would pair rows with different streams — and
    (b) the Pallas stride kernel select tokens in-kernel on the exact same
    RNG streams (the noise is data; the argmax moves inside).
    """
    return jax.vmap(lambda k: jax.random.gumbel(k, shape, dtype))(step_keys_t)


def lane_decode_step(model, params, carry, token, enc):
    """One decoder step over a LANE-batched state: [G, B, ...] -> [G, B, V].

    The shared step of every decode loop (greedy runs G=1, K-rollout
    sampling G=K, the fused RL loop G=1+K — all lanes share the encoder
    output, closed over unbatched so XLA reads the memory bank once per
    step). Dispatches on ``model.cfg.decode_impl``: "xla" vmaps
    ``CaptionModel.decode_step``; "pallas" calls the fused decode-step
    kernel (ops/decode_pallas.py — attention + LSTM stack + out_proj in one
    launch, weights resident in VMEM across the row grid). Decode is
    inference-only, so the kernel needs no VJP.
    """
    if getattr(model.cfg, "decode_impl", "xla") == "pallas":
        from cst_captioning_tpu.ops.decode_pallas import fused_decode_step

        return fused_decode_step(
            params["params"]["cell"], carry, token,
            enc.memory, enc.memory_proj, enc.memory_mask,
            num_layers=model.cfg.num_layers,
        )

    from cst_captioning_tpu.models.captioner import CaptionModel

    def one_lane(carry_k, token_k):
        return model.apply(
            params, carry_k, token_k, enc, method=CaptionModel.decode_step
        )

    return jax.vmap(one_lane)(carry, token)


def pcast_varying(tree, axes: tuple[str, ...]):
    """pcast every leaf to "varying" over ``axes`` it isn't already varying on.

    Inside ``shard_map(..., check_vma=True)`` loop-carried state must keep one
    varying-axis type across iterations; decode inits mix device-invariant
    constants (BOS tokens, zero buffers) with already-varying encoder state,
    so only the missing axes are cast (pcast of an already-varying leaf would
    be rejected). No-op outside shard_map (``axes`` empty).
    """
    if not axes:
        return tree

    def cast(x):
        vma = vma_of(x)
        for a in axes:
            if a not in vma:
                x = pcast(x, a, to="varying")
        return x

    return jax.tree.map(cast, tree)


def _exit_stride(length: int) -> int:
    """Steps per exit check: a divisor of ``length`` near 5 when one exists.

    The while condition forces a scalar-core sync per iteration (~0.2-0.3ms
    pipeline bubble on TPU, measured round 5); checking every ~5 steps
    amortizes it to noise while keeping the exit granularity fine enough
    that converged policies (captions well under T) still skip most of the
    tail. A divisor avoids overhang steps in the never-finishing case.
    """
    for c in (5, 6, 4, 3, 7, 2):
        if length % c == 0:
            return c
    return min(4, length)


def scan_until_finished(step, init, length: int, get_finished, y_fills,
                        batch_axes: tuple[str, ...] = ()):
    """``lax.scan(step, init, jnp.arange(length))`` with EOS early exit.

    Runs ``step`` in stride-sized ``lax.scan`` chunks under a
    ``lax.while_loop`` that stops once every row has finished (or ``length``
    steps ran) — the decode loops spend most of a T=30 budget emitting
    post-EOS padding on converged policies, and the while loop skips exactly
    that tail while keeping every shape static.

    Bit-exactness contract (the caller's to uphold): once
    ``get_finished(state)`` is all-True, ``step`` must be an identity on
    the OUTPUT-RELEVANT state components (whatever ``get_finished`` and
    the emitted ys read — finished flags, tokens, beam bookkeeping) and
    emit exactly ``y_fills`` — true for the EOS-frozen decode loops here
    (PAD token / 0.0 logprob emission; the beam step degenerates to the
    identity permutation, see beam.py). Under that contract the early exit
    returns ``ys`` bit-identical to the full scan: the y-buffers are
    pre-filled with the post-finish emission, and any overhang step past
    ``length`` (non-divisor stride only) is select-frozen out of the state.

    The returned ``final_state`` is NOT covered by that guarantee: the
    decode steps keep evolving their LSTM carries on post-finish steps, so
    under early exit the carry differs from the full scan's (every caller
    here discards it). A future caller wanting the final carry must either
    freeze it in ``step`` once finished or decode without early exit.

    ``batch_axes`` names the mesh axes the batch dim is sharded over (when
    called inside ``shard_map``). The unfinished-row count is psum'd over
    them in the loop BODY, so (a) every shard exits on the same step —
    uniform control flow — and (b) the while condition reads an invariant
    carried scalar, keeping ``check_vma=True`` sound (collectives stay out
    of the cond computation). The rest of the carry is pcast to varying over
    the same axes so its type is loop-invariant.

    ``y_fills``: pytree of scalars matching the step's y output structure.
    Returns ``(final_state, ys)`` with ys stacked on axis 0, like scan.
    """
    stride = _exit_stride(length)
    padded = -(-length // stride) * stride

    def count_unfinished(state):
        n = jnp.sum(jnp.logical_not(get_finished(state)).astype(jnp.int32))
        for ax in batch_axes:
            n = jax.lax.psum(n, ax)
        return n

    y_aval = jax.eval_shape(lambda s: step(s, jnp.int32(0))[1], init)
    ys0 = jax.tree.map(
        lambda av, fill: jnp.full((padded,) + av.shape, fill, av.dtype),
        y_aval, y_fills,
    )
    init = pcast_varying(init, batch_axes)
    ys0 = pcast_varying(ys0, batch_axes)

    def cond(loop):
        t, _, _, unfinished = loop
        return (t < length) & (unfinished > 0)

    def inner(state, t):
        state2, y = step(state, t)
        if padded != length:
            # overhang steps past `length` must not mutate the state (the
            # beam carry IS the result) — freeze them; their y rows are
            # sliced off below, the select just keeps dtypes aligned
            live = t < length
            state2 = jax.tree.map(
                lambda a, b: jnp.where(live, a, b), state2, state
            )
        return state2, y

    def body(loop):
        t, state, ys, _ = loop
        state, chunk = jax.lax.scan(inner, state, t + jnp.arange(stride))
        ys = jax.tree.map(
            lambda buf, c: jax.lax.dynamic_update_slice_in_dim(buf, c, t, 0),
            ys, chunk,
        )
        return t + stride, state, ys, count_unfinished(state)

    _, state, ys, _ = jax.lax.while_loop(
        cond, body, (jnp.int32(0), init, ys0, count_unfinished(init))
    )
    if padded != length:
        ys = jax.tree.map(lambda buf: buf[:length], ys)
    return state, ys


def forbid_special(logits: jnp.ndarray) -> jnp.ndarray:
    """Mask PAD/BOS columns to -inf for decoding.

    The reference's vocab overloads id 0 as its pad/end token, so sampling it
    means "stop"; here PAD and EOS are distinct ids, so decoders must never
    *emit* PAD or BOS — EOS is the only way to end a caption.
    """
    neg = jnp.full_like(logits[..., :1], -1e9)
    return logits.at[..., PAD_ID].set(neg[..., 0]).at[..., BOS_ID].set(neg[..., 0])


def step_outputs(
    token: jnp.ndarray,      # [B] token chosen this step
    logprob: jnp.ndarray,    # [B] its logprob
    finished: jnp.ndarray,   # [B] bool: sequence already emitted EOS
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Force PAD / zero-logprob after EOS; returns (token, logprob, finished')."""
    token = jnp.where(finished, jnp.full_like(token, PAD_ID), token)
    logprob = jnp.where(finished, jnp.zeros_like(logprob), logprob)
    finished = finished | (token == EOS_ID)
    return token, logprob, finished


def mask_from_tokens(tokens: jnp.ndarray) -> jnp.ndarray:
    """[.., T] decoded tokens -> float mask counting real tokens incl. EOS."""
    return (tokens != PAD_ID).astype(jnp.float32)


def apply_min_len(logits: jnp.ndarray, t, min_len: int) -> jnp.ndarray:
    """Suppress EOS while step ``t`` < ``min_len`` (prevents empty captions).

    The reference ranks beams by pure sum-logprob, which lets EOS-first beams
    win on weak models; a min caption length is the standard guard. No-op for
    ``min_len`` 0 (reference behavior).
    """
    if min_len <= 0:
        return logits
    blocked = logits.at[..., EOS_ID].set(-1.0e9)
    return jnp.where(t < min_len, blocked, logits)
