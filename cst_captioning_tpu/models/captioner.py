"""CaptionModel: encoder + LSTM decoder with shared single-step semantics.

The reference's ``CaptionModel`` couples ``forward`` (teacher forcing) and
``sample`` (greedy/multinomial/beam) in one torch module (SURVEY.md §2 row 4).
Here the same capability is split TPU-style:

- :meth:`encode`       — one pass building the memory bank + initial carry,
- :meth:`decode_step`  — one decoder step (used by every decoding strategy),
- :meth:`__call__`     — teacher-forced unroll of ``decode_step`` via
  ``nn.scan`` (compiled to a single fused XLA while loop; no per-step Python).

Teacher forcing and all samplers therefore share parameters *and* code, which
is what makes the unroll-consistency test (SURVEY.md §4 item 2) meaningful.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import BOS_ID, ModelConfig
from cst_captioning_tpu.models.decoder import Carry, DecoderCell
from cst_captioning_tpu.models.encoders import (
    MeanPoolEncoder,
    TemporalAttentionEncoder,
    masked_mean,
)


@flax.struct.dataclass
class EncoderOutput:
    memory: jnp.ndarray       # [B, M, E]
    memory_proj: jnp.ndarray  # [B, M, d_att] attention key projection
    memory_mask: jnp.ndarray  # [B, M]
    carry: Carry              # initial LSTM carry

    def take_batch(self, idx: jnp.ndarray) -> "EncoderOutput":
        """Gather batch rows ``idx`` from every leaf (all are batch-major).

        The fused decode's finished-lane compaction permutes still-active
        batch columns into a dense prefix between strides
        (decoding/fused.py); the encoder output must follow the same
        permutation so each row keeps attending over its own memory bank.
        """
        return jax.tree.map(lambda x: jnp.take(x, idx, axis=0), self)


def shift_right(labels: jnp.ndarray) -> jnp.ndarray:
    """[B, T] target tokens -> decoder inputs [B, T] starting with BOS."""
    bos = jnp.full((labels.shape[0], 1), BOS_ID, dtype=labels.dtype)
    return jnp.concatenate([bos, labels[:, :-1]], axis=1)


def _scan_step(mdl, carry, token, memory, memory_proj, memory_mask, deterministic):
    return mdl.cell(carry, token, memory, memory_proj, memory_mask, deterministic)


def _scan_step_logp(mdl, carry, tokens, memory, memory_proj, memory_mask,
                    deterministic):
    """One teacher-forced step emitting only the TARGET token's logprob.

    The per-step ``[B, V]`` logits are consumed immediately (logsumexp +
    gather fuse into the step), so the ``[B, T, V]`` stack never reaches
    HBM — the point of :meth:`CaptionModel.teacher_force_logps`. Shares
    ``selected_logprob`` with the decode loops: the REINFORCE logprobs and
    the decode-time logprobs are the same association order by construction.
    """
    from cst_captioning_tpu.decoding.common import selected_logprob

    token_in, token_tgt = tokens
    carry, logits = mdl.cell(
        carry, token_in, memory, memory_proj, memory_mask, deterministic
    )
    return carry, selected_logprob(logits.astype(jnp.float32), token_tgt)


class CaptionModel(nn.Module):
    cfg: ModelConfig

    def setup(self):
        cfg = self.cfg
        if cfg.encoder == "meanpool":
            self.encoder = MeanPoolEncoder(cfg, name="encoder")
        else:
            self.encoder = TemporalAttentionEncoder(cfg, name="encoder")
        self.cell = DecoderCell(cfg, name="cell")
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        # LSTM carry is initialized from the pooled memory (the reference
        # instead feeds the video feature at step 0 — same information path,
        # but this keeps step 0 identical to every other step for the scan)
        self.init_c = [
            nn.Dense(cfg.d_hidden, name=f"init_c{i}", dtype=dtype, param_dtype=pdtype)
            for i in range(cfg.num_layers)
        ]
        self.init_h = [
            nn.Dense(cfg.d_hidden, name=f"init_h{i}", dtype=dtype, param_dtype=pdtype)
            for i in range(cfg.num_layers)
        ]

    # ---- encoding ----------------------------------------------------------

    def encode(
        self, feats: dict[str, jnp.ndarray], masks: dict[str, jnp.ndarray]
    ) -> EncoderOutput:
        memory, mmask = self.encoder(feats, masks)
        memory_proj = self.cell.project_memory(memory)
        ctx0 = masked_mean(memory, mmask, axis=1, axis_name=self.cfg.seq_axis)
        carry = tuple(
            (jnp.tanh(self.init_c[i](ctx0)), jnp.tanh(self.init_h[i](ctx0)))
            for i in range(self.cfg.num_layers)
        )
        return EncoderOutput(memory, memory_proj, mmask, carry)

    # ---- single step (greedy / sampling / beam all call this) ---------------

    def decode_step(
        self,
        carry: Carry,
        token: jnp.ndarray,
        enc: EncoderOutput,
        deterministic: bool = True,
    ) -> tuple[Carry, jnp.ndarray]:
        return self.cell(
            carry, token, enc.memory, enc.memory_proj, enc.memory_mask, deterministic
        )

    # ---- teacher forcing -----------------------------------------------------

    def decode_logits(
        self,
        enc: EncoderOutput,
        labels: jnp.ndarray,
        train: bool = False,
    ) -> jnp.ndarray:
        """Teacher-forced unroll from an already-built :class:`EncoderOutput`.

        Split from :meth:`__call__` so callers that reuse one encoder pass
        for many label rows (the REINFORCE update teacher-forces K rollouts
        per clip against TILED memory — rl/scst.py) pay the encoder once
        instead of per row."""
        inputs = shift_right(labels)
        scan = nn.scan(
            functools.partial(_scan_step, deterministic=not train),
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=(1, nn.broadcast, nn.broadcast, nn.broadcast),
            out_axes=1,
        )
        _, logits = scan(
            self, enc.carry, inputs, enc.memory, enc.memory_proj, enc.memory_mask
        )
        return logits

    def teacher_force_logps(
        self,
        enc: EncoderOutput,
        labels: jnp.ndarray,
        train: bool = False,
    ) -> jnp.ndarray:
        """Per-position logprob of ``labels`` under teacher forcing: [B, T].

        Equals ``sequence_log_probs(decode_logits(enc, labels), labels)``
        (pinned by test) but never materializes the ``[B, T, V]`` logits
        stack — at the flagship dims that array is ~2 GB of f32 per REINFORCE
        chunk whose only use is a gather + logsumexp, pure HBM traffic the
        in-scan form avoids (rl/scst.py's update path)."""
        inputs = shift_right(labels)
        scan = nn.scan(
            functools.partial(_scan_step_logp, deterministic=not train),
            variable_broadcast="params",
            split_rngs={"params": False, "dropout": True},
            in_axes=((1, 1), nn.broadcast, nn.broadcast, nn.broadcast),
            out_axes=1,
        )
        _, logps = scan(
            self, enc.carry, (inputs, labels), enc.memory, enc.memory_proj,
            enc.memory_mask,
        )
        return logps

    def __call__(
        self,
        feats: dict[str, jnp.ndarray],
        masks: dict[str, jnp.ndarray],
        labels: jnp.ndarray,
        train: bool = False,
    ) -> jnp.ndarray:
        """-> logits [B, T, V] (f32); logits[:, t] predicts labels[:, t]."""
        return self.decode_logits(self.encode(feats, masks), labels, train)
