"""Model layer: Flax caption models (encoders + LSTM decoder).

Rebuilds the capability of the reference's ``model.py::CaptionModel``
(SURVEY.md §2 row 4) as jit-compiled Flax modules with one unifying design
decision: every encoder produces a *memory* — a ``[B, M, E]`` bank of slots
plus a validity mask — and a single decoder cell attends over that memory at
each step:

- mean-pool encoder  -> one slot per modality (M = #modalities),
- temporal-attention -> one slot per frame, all modalities concatenated along
  the frame axis (M = sum of frame counts).

This gives one decode path for every config (greedy / sampling / beam reuse
the same ``decode_step``), static shapes throughout, and attention that maps
onto a single batched matmul per step for the MXU.
"""

from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput
from cst_captioning_tpu.models.encoders import MeanPoolEncoder, TemporalAttentionEncoder
from cst_captioning_tpu.models.attention import AdditiveAttention
from cst_captioning_tpu.models.decoder import DecoderCell

__all__ = [
    "CaptionModel",
    "EncoderOutput",
    "MeanPoolEncoder",
    "TemporalAttentionEncoder",
    "AdditiveAttention",
    "DecoderCell",
]
