"""LSTM decoder cell with input-feed attention context.

One step of the caption decoder (reference ``model.py`` decode loop,
SURVEY.md §2 row 4): embed the previous token, attend over the encoder memory
with the previous top-layer hidden state, feed ``[word_emb, context]`` through
the LSTM stack, project to vocab logits. Written as a single-step module so
teacher forcing (``nn.scan``), greedy/multinomial sampling and beam search all
share the exact same parameters and code path.

``ops/decode_pallas.py`` reimplements exactly this step (minus dropout —
decode is deterministic) as one fused TPU kernel over this module's
parameter tree, selected by ``ModelConfig.decode_impl``; any change to the
math here must be mirrored there (the parity sweep in
tests/test_ops_decode_pallas.py pins the two together).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from cst_captioning_tpu.config.config import ModelConfig
from cst_captioning_tpu.models.attention import AdditiveAttention

# carry: tuple over layers of LSTM (c, h) pairs
Carry = tuple[tuple[jnp.ndarray, jnp.ndarray], ...]

# flax OptimizedLSTMCell parameter families, in the order its concatenated
# gate matmul splits them: i (input), f (forget), g (cell), o (output).
# ops/decode_pallas.py concatenates the per-gate kernels in EXACTLY this
# order when it rebuilds the cell's gate matmul inside the fused decode-step
# kernel — keep the two in lockstep.
LSTM_GATE_ORDER = ("i", "f", "g", "o")


class DecoderCell(nn.Module):
    cfg: ModelConfig

    def setup(self):
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        pdtype = jnp.dtype(cfg.param_dtype)
        self.word_embed = nn.Embed(
            cfg.vocab_size, cfg.d_embed, name="word_embed",
            dtype=dtype, param_dtype=pdtype,
        )
        self.attention = AdditiveAttention(
            d_att=cfg.d_att, dtype=dtype, param_dtype=pdtype, name="attention",
            seq_axis=cfg.seq_axis, impl=cfg.attention_impl,
        )
        self.lstm = [
            nn.OptimizedLSTMCell(
                cfg.d_hidden, dtype=dtype, param_dtype=pdtype, name=f"lstm{i}"
            )
            for i in range(cfg.num_layers)
        ]
        self.out_proj = nn.Dense(
            cfg.vocab_size, name="out_proj", dtype=dtype, param_dtype=pdtype
        )
        self.dropout = nn.Dropout(rate=cfg.dropout)

    def project_memory(self, memory: jnp.ndarray) -> jnp.ndarray:
        return self.attention.project_memory(memory)

    def __call__(
        self,
        carry: Carry,
        token: jnp.ndarray,        # [B] int32 previous token
        memory: jnp.ndarray,       # [B, M, E]
        memory_proj: jnp.ndarray,  # [B, M, d_att]
        memory_mask: jnp.ndarray,  # [B, M]
        deterministic: bool = True,
    ) -> tuple[Carry, jnp.ndarray]:
        """One decode step -> (new carry, logits [B, V] float32)."""
        h_top = carry[-1][1]
        ctx = self.attention(h_top, memory, memory_proj, memory_mask)
        x = jnp.concatenate([self.word_embed(token), ctx], axis=-1)
        x = self.dropout(x, deterministic=deterministic)
        new_carry = []
        for i, cell in enumerate(self.lstm):
            c_i, x = cell(carry[i], x)
            new_carry.append(c_i)
            if i + 1 < len(self.lstm):
                x = self.dropout(x, deterministic=deterministic)
        x = self.dropout(x, deterministic=deterministic)
        # logits in f32: softmax/loss stability is worth the cast
        logits = self.out_proj(x).astype(jnp.float32)
        return tuple(new_carry), logits
