"""Feature encoders: every encoder yields a memory bank [B, M, E] + mask.

Capability map to the reference (SURVEY.md §2 row 4):

- :class:`MeanPoolEncoder` — config 1 (MSVD mean-pool): masked mean over
  frames per modality, one memory slot per modality. The decoder's attention
  over modality slots subsumes the reference's concat-and-project fusion.
- :class:`TemporalAttentionEncoder` — config 2 (MSR-VTT temporal attention):
  per-frame embeddings, all modalities concatenated along the frame axis, so
  one attention pass spans every frame of every modality. Modalities with
  different frame counts/rates need no alignment.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from cst_captioning_tpu.config.config import ModelConfig


def masked_mean(
    x: jnp.ndarray, mask: jnp.ndarray, axis: int, axis_name: str = ""
) -> jnp.ndarray:
    """Mean over ``axis`` counting only mask==1 positions.

    ``axis_name``: mesh axis ``axis`` is additionally sharded over (sequence
    parallelism) — numerator and count are psum'd before the divide, so the
    result equals the unsharded mean. Also correct when the input is merely
    REPLICATED over that axis: both sums scale by the device count and the
    ratio cancels.
    """
    mask = mask.astype(x.dtype)
    num = jnp.sum(x * jnp.expand_dims(mask, -1), axis=axis)
    den = jnp.sum(mask, axis=axis)
    if axis_name:
        num = jax.lax.psum(num, axis_name)
        den = jax.lax.psum(den, axis_name)
    return num / jnp.maximum(den, 1.0)[..., None]


class MeanPoolEncoder(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(
        self, feats: dict[str, jnp.ndarray], masks: dict[str, jnp.ndarray]
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """feats[name]: [B, F, D_name] -> (memory [B, n_mod, E], mask [B, n_mod])."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        slots = []
        for name, _ in cfg.modalities:
            pooled = masked_mean(
                feats[name].astype(dtype), masks[name], axis=1,
                axis_name=cfg.seq_axis,
            )
            emb = nn.Dense(
                cfg.d_embed, name=f"embed_{name}",
                dtype=dtype, param_dtype=jnp.dtype(cfg.param_dtype),
            )(pooled)
            slots.append(jnp.tanh(emb))
        memory = jnp.stack(slots, axis=1)                        # [B, n_mod, E]
        # masks are float32 framework-wide (loss/metric denominators sum
        # them exactly); this is not compute-path data
        mmask = jnp.ones(memory.shape[:2], dtype=jnp.float32)  # graftlint: disable=GL005
        return memory, mmask


class TemporalAttentionEncoder(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(
        self, feats: dict[str, jnp.ndarray], masks: dict[str, jnp.ndarray]
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """-> (memory [B, sum_F, E], mask [B, sum_F]): frame slots, all modalities."""
        cfg = self.cfg
        dtype = jnp.dtype(cfg.dtype)
        banks, bmasks = [], []
        for name, _ in cfg.modalities:
            emb = nn.Dense(
                cfg.d_embed, name=f"embed_{name}",
                dtype=dtype, param_dtype=jnp.dtype(cfg.param_dtype),
            )(feats[name].astype(dtype))                         # [B, F, E]
            banks.append(jnp.tanh(emb))
            bmasks.append(masks[name])
        memory = jnp.concatenate(banks, axis=1)
        mmask = jnp.concatenate(bmasks, axis=1).astype(jnp.float32)
        # zero padded slots so masked positions can't leak through the
        # value-sum even if a downstream consumer forgets the mask
        memory = memory * mmask[..., None].astype(memory.dtype)
        return memory, mmask
