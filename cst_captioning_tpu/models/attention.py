"""Additive (Bahdanau) temporal attention over a memory bank.

The reference's temporal attention scores each frame against the decoder
state with ``v^T tanh(W_f f + W_h h)`` (CST paper §3.1 / SURVEY.md §5). Here
the memory projection ``W_f f`` is precomputed once per sequence by the
encoder (it does not depend on the step), so the per-step cost is one small
matmul + a masked softmax — XLA fuses the whole step into a couple of kernels.

Sequence parallelism (``seq_axis`` set): the memory bank arrives FRAME-SHARDED
across the mesh axis and the softmax becomes a two-pass distributed reduction
— ``pmax`` of the local score maxima, then one ``psum`` of the (numerator,
denominator) pair, the "one-step ring" of SURVEY.md §5's long-context row.
Attention is permutation-invariant over memory slots, so sharded results
equal the single-device softmax exactly (up to f32 summation order).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class AdditiveAttention(nn.Module):
    d_att: int
    dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    # mesh axis the frame dimension is sharded over ("" = not sharded)
    seq_axis: str = ""
    # "xla" composite (default) or the "pallas" blockwise kernel
    # (ops/attention_pallas.py); the collective seq_axis path overrides
    impl: str = "xla"

    def setup(self):
        self.mem_proj = nn.Dense(
            self.d_att, name="mem_proj", use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype,
        )
        self.query_proj = nn.Dense(
            self.d_att, name="query_proj", use_bias=True,
            dtype=self.dtype, param_dtype=self.param_dtype,
        )
        self.score = nn.Dense(
            1, name="score", use_bias=False,
            dtype=self.dtype, param_dtype=self.param_dtype,
        )

    def project_memory(self, memory: jnp.ndarray) -> jnp.ndarray:
        """[B, M, E] -> [B, M, d_att]; hoisted out of the decode loop."""
        return self.mem_proj(memory)

    def __call__(
        self,
        query: jnp.ndarray,        # [B, H] decoder state
        memory: jnp.ndarray,       # [B, M, E] value bank
        memory_proj: jnp.ndarray,  # [B, M, d_att] = project_memory(memory)
        memory_mask: jnp.ndarray,  # [B, M] 1/0
    ) -> jnp.ndarray:
        """-> context [B, E]: mask-weighted sum of memory slots."""
        q = self.query_proj(query)
        if self.impl == "pallas" and not self.seq_axis:
            from cst_captioning_tpu.ops import fused_additive_attention

            # the score kernel vector, read by pushing the identity through
            # the Dense (also creates the param during init, keeping the
            # parameter tree identical to the XLA path's)
            v = self.score(jnp.eye(self.d_att, dtype=self.dtype))[:, 0]
            return fused_additive_attention(
                q, v, memory, memory_proj, memory_mask
            )
        scores = self.score(jnp.tanh(memory_proj + q[:, None, :]))[..., 0]  # [B, M]
        # -1e9, not -inf: a row with zero valid slots must yield a finite
        # (uniform) softmax over zeroed memory, not NaNs that poison the step
        scores = jnp.where(memory_mask > 0, scores, -1.0e9)
        if self.seq_axis:
            return self._sharded_softmax_attend(scores, memory)
        # softmax in f32 for stability regardless of compute dtype
        weights = nn.softmax(scores.astype(jnp.float32), axis=-1).astype(memory.dtype)
        return jnp.einsum("bm,bme->be", weights, memory)

    def _sharded_softmax_attend(
        self, scores: jnp.ndarray, memory: jnp.ndarray
    ) -> jnp.ndarray:
        """Distributed masked softmax over the frame-sharded memory axis."""
        s = scores.astype(jnp.float32)                         # [B, M_local]
        # global max is a constant shift for softmax — stop_gradient both
        # keeps the math exact and sidesteps pmax's missing diff rule
        m = jax.lax.pmax(
            jax.lax.stop_gradient(jnp.max(s, axis=-1)), self.seq_axis
        )                                                      # [B] global max
        w = jnp.exp(s - m[:, None])
        den = jax.lax.psum(jnp.sum(w, axis=-1), self.seq_axis)              # [B]
        num = jax.lax.psum(
            jnp.einsum("bm,bme->be", w.astype(memory.dtype), memory)
            .astype(jnp.float32),
            self.seq_axis,
        )                                                      # [B, E]
        return (num / den[:, None]).astype(memory.dtype)
