"""CaptionService: always-on caption serving with continuous batching.

The admission/batch-former loop runs the PR 5 stride machinery as a
*service*: a fixed pool of ``capacity`` decode lanes steps S-step strides
forever, and between strides — exactly where finished-lane compaction
already re-packs columns — finished requests leave their lanes and queued
requests slot in. The stride program never learns about requests: like the
offline loop, it sees a dense active prefix (host-built permutation +
``n_active``), gathered encoder pages, and per-row noise. Continuous
batching is therefore *structurally* the offline decode with a different
column occupancy per stride, which is what makes the parity pin possible:

**Per-request determinism.** Every request decodes on its OWN RNG streams
— ``fold_in(fold_in(key(seed), k), t)`` with the request's *local* step t —
and its encoder output comes from a batched admission-group encode whose
rows it owns alone. Per-row encoder AND decode math is batch-composition
independent (each row's matmul/softmax reads only its own row) and
padding-width independent (masked memory slots contribute exact-zero
softmax weight), so a request admitted mid-flight into an arbitrary lane
emits token- and logprob-BIT-identical output to
the same clip decoded offline through ``decoding.fused.fused_decode``
(pinned by tests/test_serving.py). K sampled lanes ride along as *Noisy
Parallel Approximate Decoding* (arXiv:1605.03835): the served caption is
the best-scoring lane (greedy included), an anytime quality knob that
costs only lane width.

**Zero-sync loop discipline (GL001-clean).** All device work is dispatched
through jitted closures; every host<->device crossing is explicit — one
``jax.device_put`` batch per stride for the small host-built inputs (page
table, permutation, lens) and ONE explicit ``jax.device_get`` per stride
for the emissions the host must act on (tokens/logprobs/finished — the
admission decision and the response payload ARE host data; serving's
per-stride readback is the deliberate, amortized sync point, not an
accident). Nothing else crosses implicitly: the loop body holds under
``jax.transfer_guard("disallow")`` (tests/test_serving.py sanitize test).

**Drain.** SIGTERM, a detected peer loss (resilience/health.py), or the
seeded ``serving_preempt`` chaos fault stop the loop at the next stride
boundary: in-flight strides finish, new admissions are refused, and the
queue (pending + in-flight request payloads) plus the page-table snapshot
persist to the snapshot dir. :func:`load_snapshot` replays the drained
queue through a fresh service and — per-request determinism again — yields
bit-identical tokens (pinned by the recovery test).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Iterable

import jax
import jax.numpy as jnp
import numpy as np

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID
from cst_captioning_tpu.decoding.common import (
    forbid_special,
    gumbel_step_noise,
    lane_decode_step,
    npad_best_lane_index,
    selected_logprob,
    step_outputs,
)
from cst_captioning_tpu.models.captioner import CaptionModel, EncoderOutput
from cst_captioning_tpu.parallel.compile import CompilePlan, compile_fn
from cst_captioning_tpu import obs
from cst_captioning_tpu.obs import anomaly as obs_anomaly
from cst_captioning_tpu.obs import recorder as obs_recorder
from cst_captioning_tpu.obs.flops import (
    enc_and_per_tok_flops,
    serving_bank_bytes_per_stride,
)
from cst_captioning_tpu.resilience import chaos
from cst_captioning_tpu.resilience.preempt import PreemptionHandler
from cst_captioning_tpu.serving.pages import (
    OutOfPages,
    PageBank,
    gather_bank,
)


@dataclass(frozen=True)
class ClipRequest:
    """One caption request: unbatched features ``[F, D]`` per modality,
    per-frame masks ``[F]``, and the request's OWN rng seed (the whole
    decode is a deterministic function of this payload — replay = rerun)."""

    req_id: str
    feats: dict[str, np.ndarray]
    masks: dict[str, np.ndarray]
    seed: int = 0
    arrival_s: float = 0.0

    @property
    def num_frames(self) -> int:
        return int(next(iter(self.feats.values())).shape[0])


@dataclass
class CaptionResult:
    req_id: str
    tokens: np.ndarray        # [1+K, T] int32 — lane 0 greedy, like fused.py
    logprobs: np.ndarray      # [1+K, T] f32 untempered model logprobs
    best_lane: int            # NPAD pick: argmax sum-logprob over lanes
    caption_ids: list[int]    # best lane up to (excluding) EOS
    caption: str | None       # detokenized when the service has a vocab
    latency_s: float          # arrival -> completion (queue wait included)
    phases: dict[str, float]  # queue_wait / encode / decode / detok seconds
    param_version: int = 0    # admission-pinned version this decode ran under


@dataclass
class ServeReport:
    results: dict[str, CaptionResult] = field(default_factory=dict)
    drained: bool = False
    drain_reason: str = ""
    snapshot_dir: str | None = None
    wall_s: float = 0.0
    submitted: int = 0
    completed: int = 0
    strides: int = 0


@dataclass
class _Ticket:
    req: ClipRequest
    slot: int = -1
    t: int = 0                      # local decode step (host mirror)
    tok: np.ndarray | None = None   # [G, T] accumulation buffers
    lp: np.ndarray | None = None
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_encoded: float = 0.0
    # the param version active at admission: every stride of this request
    # decodes under THIS version's params even after a hot swap (per-lane
    # version pinning — the request is bit-identical to its offline decode
    # under the admission version). Staged (encoded, lane-less) requests
    # pin at ENCODE time: the encoder already ran under that version.
    param_version: int = 0
    # encode-ahead staging: the encoder carry parked on device until a
    # lane frees (tiny: L x 2 x [1, H] leaves); dropped at lane bind
    enc_carry: object = None


class SloMonitor:
    """Rolling-window SLO attainment + multi-window burn-rate alerting.

    One completion at a time: ``observe(latency_s, now)`` marks the request
    ok iff ``latency_s <= target_s``, then for every rolling window (default
    1-min fast / 10-min slow) computes

    - attainment  = ok / total over the window,
    - burn rate   = (1 - attainment) / (1 - objective) — how many times
      faster than sustainable the error budget is burning (1.0 = exactly
      on budget, 14.4 = a 30-day budget gone in ~2 days),

    published as ``serving.slo.attainment.<w>s`` / ``serving.slo.burn_rate.
    <w>s`` gauges. An alert trips only when the FAST window burns above
    ``fast_burn`` AND the SLOW window above ``slow_burn`` (the classic
    multi-window rule: the slow window filters blips, the fast window makes
    the page recent) — edge-triggered into the ``serving.slo.alerts``
    counter and the shared ``obs.anomaly.slo_burn`` spelling
    (obs/anomaly.py), so the serving report and the training postmortem
    timeline name SLO pain the same way. ``now`` comes from the service's
    injectable clock: tests drive the windows with a fake clock."""

    def __init__(
        self,
        target_s: float,
        objective: float = 0.99,
        windows: tuple[float, float] = (60.0, 600.0),
        fast_burn: float = 14.4,
        slow_burn: float = 6.0,
    ):
        if target_s <= 0:
            raise ValueError(f"slo target_s {target_s} must be > 0")
        if not 0.0 < objective < 1.0:
            raise ValueError(f"slo objective {objective} must be in (0, 1)")
        if len(windows) != 2 or windows[0] >= windows[1]:
            raise ValueError(
                f"slo windows {windows} must be (fast, slow) with fast < slow"
            )
        self.target_s = float(target_s)
        self.objective = float(objective)
        self.windows = tuple(float(w) for w in windows)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self._samples: dict[float, deque] = {
            w: deque() for w in self.windows
        }
        self._alerting = False
        self.alerts = 0

    def burn_rate(self, window: float, now: float) -> float:
        """Current burn rate over ``window`` (0.0 when no samples)."""
        dq = self._samples[window]
        while dq and dq[0][0] < now - window:
            dq.popleft()
        if not dq:
            return 0.0
        att = sum(ok for _, ok in dq) / len(dq)
        return (1.0 - att) / (1.0 - self.objective)

    def observe(self, latency_s: float, now: float) -> None:
        ok = latency_s <= self.target_s
        if not ok:
            obs.counter("serving.slo.breaches").inc()
        burns = {}
        for w in self.windows:
            dq = self._samples[w]
            dq.append((now, ok))
            while dq and dq[0][0] < now - w:
                dq.popleft()
            att = sum(o for _, o in dq) / len(dq)
            burns[w] = (1.0 - att) / (1.0 - self.objective)
            obs.gauge(f"serving.slo.attainment.{int(w)}s").set(att)
            obs.gauge(f"serving.slo.burn_rate.{int(w)}s").set(burns[w])
        fast, slow = self.windows
        firing = burns[fast] >= self.fast_burn and burns[slow] >= self.slow_burn
        if firing and not self._alerting:
            # edge-triggered: one alert per excursion, not one per request
            self.alerts += 1
            obs.counter("serving.slo.alerts").inc()
            obs_anomaly.record_anomaly(
                "slo_burn",
                target_s=self.target_s,
                fast_burn=burns[fast],
                slow_burn=burns[slow],
            )
        self._alerting = firing


# the active service (drain target of the serving_preempt chaos fault and
# the module-level request_drain() entry point)
_ACTIVE: "CaptionService | None" = None
_ACTIVE_LOCK = threading.Lock()


def request_drain(reason: str = "requested") -> None:
    """Ask the active service to drain (chaos ``serving_preempt`` hook)."""
    with _ACTIVE_LOCK:
        svc = _ACTIVE
    if svc is None:
        raise RuntimeError(
            "serving_preempt fired with no active CaptionService — the "
            "fault models a preemption of the serving loop"
        )
    svc.drain(reason)


class CaptionService:
    """Continuous-batching caption service over one model + params.

    ``capacity`` decode lanes, ``num_rollouts`` K sampled lanes per request
    (lane 0 is always the greedy lane), ``stride`` steps per dispatched
    chunk (defaults to ``model.cfg.decode_stride``). The paged encoder bank
    holds ``num_pages`` pages of ``page_size`` memory slots; admission
    backpressures on page exhaustion. ``frame_bucket`` pads each clip's
    frame axis up to the next bucket multiple (<= ``cfg.max_frames``) so
    ragged clips hold fewer pages — decode output is padding-width
    invariant (module docstring), so the bucket is a pure memory knob.
    """

    def __init__(
        self,
        model: CaptionModel,
        params,
        vocab=None,
        *,
        capacity: int = 8,
        num_rollouts: int = 2,
        temperature: float = 1.0,
        max_len: int | None = None,
        min_len: int = 0,
        stride: int | None = None,
        page_size: int | None = None,
        num_pages: int | None = None,
        frame_bucket: int | None = None,
        kernel_block_b: int = 1,
        admit_group: int = 1,
        paged: bool | None = None,
        clock: Callable[[], float] = time.monotonic,
        slo_target_s: float = 0.0,
        slo_objective: float = 0.99,
        slo_fast_burn: float = 14.4,
        slo_slow_burn: float = 6.0,
        feedback: Callable[[ClipRequest, CaptionResult, int], None] | None = None,
    ):
        cfg = model.cfg
        self.model = model
        self.params = params
        self.vocab = vocab
        self.B = int(capacity)
        self.K = int(num_rollouts)
        self.G = 1 + self.K
        self.T = int(max_len or cfg.max_len)
        self.temperature = float(temperature)
        self.min_len = int(min_len)
        self.S = max(1, min(
            int(stride if stride is not None
                else getattr(cfg, "decode_stride", 8)),
            self.T,
        ))
        self.use_kernel = getattr(cfg, "decode_impl", "xla") == "pallas"
        if self.use_kernel and self.min_len > 0:
            raise ValueError(
                "decode_impl='pallas' serving does not support min_len > 0 "
                "(the stride kernel's min-len mask is stride-global, not "
                "per-row) — use the XLA decode path"
            )
        if self.use_kernel and self.K < 1:
            raise ValueError(
                "decode_impl='pallas' serving needs num_rollouts >= 1 "
                "(the stride kernel requires the (1+K)-lane layout)"
            )
        if self.B < 1:
            raise ValueError(f"capacity {capacity} must be >= 1")
        self.n_mod = len(cfg.modalities)
        self.frame_bucket = int(frame_bucket or cfg.max_frames)
        if not (1 <= self.frame_bucket <= cfg.max_frames):
            raise ValueError(
                f"frame_bucket {self.frame_bucket} must be in "
                f"[1, max_frames={cfg.max_frames}]"
            )
        m_max = self.n_mod * cfg.max_frames
        page = int(page_size or max(self.n_mod * self.frame_bucket, 1))
        pages_per_row = -(-m_max // page)
        if num_pages is None:
            # default pool: every lane can hold a max-length clip (the
            # padded-slab equivalent); size it DOWN to see backpressure
            num_pages = self.B * pages_per_row
        # paged in-kernel attention (default wherever the stride kernel
        # runs): the stride reads pages straight from the pool by table
        # lookup — no dense [B, W, E] bank per stride, and the pool may
        # exceed one batch's dense footprint (encode-ahead staging below
        # fills the surplus). paged=False forces the dense-gather path
        # (the XLA decode always gathers).
        self.paged = self.use_kernel if paged is None else bool(paged)
        if self.paged and not self.use_kernel:
            raise ValueError(
                "paged=True needs decode_impl='pallas' — the XLA decode "
                "path has no in-kernel page reader (it runs the "
                "gather_bank fallback); leave paged unset or False"
            )
        if not self.paged and int(num_pages) > self.B * pages_per_row:
            raise ValueError(
                f"num_pages {num_pages} exceeds one batch's dense-bank "
                f"footprint ({self.B} lanes x {pages_per_row} pages) — "
                "the dense-gather path re-materializes every lane's full "
                "window per stride, so surplus pages can never be "
                "admitted; use decode_impl='pallas' with paged=True "
                "(the in-kernel page reader) to grow the pool past it"
            )
        self.bank = PageBank(num_pages, page)
        self.table_width = pages_per_row
        self.W = pages_per_row * page     # gathered memory width per row
        # device-resident per-lane page table: bound/cleared at admission
        # and completion, consumed directly by every stride dispatch
        self.bank.init_rows(self.B, self.table_width)

        # admission-group encode width. 1 (default) = one encoder pass per
        # request, which is what makes a served request bit-identical to
        # its offline B=1 decode at EVERY dtype. >1 batches same-bucket
        # admission encodes into one pass (less admission wall under
        # arrival waves) — bit-exact where the encoder gemm is row-stable
        # (f32, pinned by test). bf16 encoder gemms are batch-shape
        # sensitive, so at any non-f32 model dtype a requested group width
        # > 1 FALLS BACK to per-request encode until a bf16 row-stability
        # story exists (bench_serving ledgers the measured grouped-vs-solo
        # bf16 drift behind the documented promotion gate)
        self.requested_admit_group = max(int(admit_group), 1)
        self.admit_group = self.requested_admit_group
        if (self.admit_group > 1
                and str(getattr(cfg, "dtype", "float32")) != "float32"):
            self.admit_group = 1
            obs.counter("serving.admit_group_bf16_fallback").inc()
            obs.event(
                "serving_admit_group_fallback",
                requested=self.requested_admit_group,
                dtype=str(getattr(cfg, "dtype", "float32")),
            )
        # kernel batch-block width. 1 (default) = every lane is its own
        # block: the kernel's block-granular skips become PER-ROW skips
        # (finished rows and the compaction prefix die row by row), and each
        # row computes in exactly the [1, ..] block shape an offline B=1
        # decode uses — which is what makes serving-pallas bit-identical to
        # offline-pallas per request (wider blocks change the matmul
        # accumulation shape; on TPU raise this toward the sublane width
        # and accept fraction-grade parity, like the offline kernel)
        self.kernel_block_b = int(kernel_block_b)
        self._queue: deque[ClipRequest] = deque()
        self._tickets: dict[str, _Ticket] = {}
        self._inflight: dict[int, _Ticket] = {}   # slot -> ticket
        # encode-ahead staging (paged only): requests encoded and paged in
        # while every lane is busy — they bind a lane with NO encoder pass
        # the moment one frees. This is what makes a pool larger than one
        # batch's dense footprint USEFUL: staged pages are bounded by the
        # pool, not by lane count. FIFO order: staged requests came off
        # the queue front, and bind before any new admission.
        self._staged: deque[str] = deque()
        self._free_slots: deque[int] = deque(range(self.B))
        self._state = None                        # lazy device lane state
        self._drain = threading.Event()
        self._drain_reason = ""
        self.clock = clock
        self._encode_fns: dict[int, Callable] = {}
        self._admit_fn = None
        self._stride_fn = self._build_stride_fn()
        # seed -> raw key data, jitted: `jax.random.key(seed)` EAGER would
        # stage the seed scalar implicitly (the transfer-guard test's whole
        # point); inside jit the seed arrives as an explicit device_put arg
        self._key_fn = compile_fn(
            lambda s: jax.random.key_data(jax.random.key(s)), CompilePlan()
        )
        # SLO burn-rate monitor (SloMonitor docstring): off until a target
        # exists (slo_target_s=0.0 default, or set_slo after calibration)
        self._slo_kw = dict(
            objective=slo_objective,
            fast_burn=slo_fast_burn,
            slow_burn=slo_slow_burn,
        )
        self._slo: SloMonitor | None = (
            SloMonitor(slo_target_s, **self._slo_kw)
            if slo_target_s > 0 else None
        )
        # ---- drain-free hot param swap state (README "Online RL from
        # served traffic"). The ACTIVE version admits new requests; every
        # in-flight request decodes under its admission-pinned version's
        # params, kept in _old_params until its last lane completes. A
        # publish is STAGED here and applied only at a stride boundary
        # (_apply_pending_swap) — never mid-stride, never torn.
        self.param_version = 0
        self._old_params: dict[int, object] = {}
        self._pending_publish: tuple[int, object] | None = None
        self._swap_history: list[dict] = []
        # serving-as-actor capture: called per completed request with
        # (req, result, admission param version) — tok/lp are already host
        # arrays at completion, so the capture is zero extra dispatch
        self._feedback = feedback
        obs.gauge("serving.param_version").set(0.0)
        # analytic per-token / encode FLOPs for the obs MFU counters
        feat_dims = tuple(d for _, d in cfg.modalities)
        self._enc_flops, self._tok_flops = enc_and_per_tok_flops(
            cfg.max_frames, cfg.d_embed, cfg.d_hidden, cfg.d_att,
            cfg.vocab_size, feat_dims, cfg.num_layers,
        )

    # ---- public API ---------------------------------------------------------

    def submit(self, req: ClipRequest) -> None:
        if req.req_id in self._tickets:
            raise ValueError(f"duplicate req_id {req.req_id!r}")
        if req.num_frames < 1 or req.num_frames > self.model.cfg.max_frames:
            raise ValueError(
                f"request {req.req_id!r} has {req.num_frames} frames "
                f"(need 1..{self.model.cfg.max_frames})"
            )
        if not 0 <= req.seed < 2**31:
            # the seed travels as an int32 scalar; out-of-range values
            # would silently change the request's RNG streams vs the
            # offline `jax.random.key(seed)` spelling
            raise ValueError(
                f"request {req.req_id!r} seed {req.seed} outside [0, 2^31)"
            )
        self._tickets[req.req_id] = _Ticket(req=req)
        self._queue.append(req)
        obs.counter("serving.requests_submitted").inc()

    def drain(self, reason: str = "requested") -> None:
        """Stop at the next stride boundary: finish in-flight strides,
        refuse new admissions, snapshot the queue (thread/signal-safe)."""
        self._drain_reason = self._drain_reason or reason
        self._drain.set()

    @property
    def draining(self) -> bool:
        return self._drain.is_set()

    def grow_capacity(self, new_capacity: int) -> None:
        """Grow the lane pool at a stride seam (the elastic regrow
        direction: a rejoined node re-admits a drained shard's work at
        full width). Call between :meth:`serve` calls — never mid-stride.

        Only grows: the free-slot list gains the new lane ids, the page
        bank grows proportionally (``table_width`` pages per new lane, the
        same per-lane share the constructor defaults to), the stride
        closure rebuilds for the new ``B``, and — when lane state already
        exists — every state leaf pads along the lane axis with lanes born
        FINISHED and empty, exactly like :meth:`_ensure_state` births
        them. Existing lanes' slots, pages, and in-flight decodes are
        untouched, so growing mid-service never perturbs a running
        request's stream. Shrinking is drain-and-rebuild, never in place.
        """
        new_b = int(new_capacity)
        if new_b < self.B:
            raise ValueError(
                f"grow_capacity({new_capacity}) below current capacity "
                f"{self.B} — the lane pool only grows (shrink = drain and "
                "rebuild)"
            )
        if new_b == self.B:
            return
        old_b = self.B
        grown = new_b - old_b
        self.B = new_b
        self._free_slots.extend(range(old_b, new_b))
        self.bank.grow(self.bank.num_pages + grown * self.table_width)
        self.bank.grow_rows(new_b)
        self._stride_fn = self._build_stride_fn()
        if self._state is not None:
            carry, token, finished, t_local, keys = self._state

            def pad(x, fill, axis):
                widths = [(0, 0)] * x.ndim
                widths[axis] = (0, grown)
                return jnp.pad(x, widths, constant_values=fill)

            self._state = (
                tuple((pad(c, 0, 1), pad(h, 0, 1)) for c, h in carry),
                pad(token, BOS_ID, 1),
                pad(finished, True, 1),   # new lanes are born finished
                pad(t_local, 0, 0),
                pad(keys, 0, 0),
            )
        obs.counter("serving.lanes_regrown").inc(grown)
        obs.event("serving_regrow", capacity=new_b, grown=grown)

    def set_slo(self, target_s: float) -> None:
        """(Re)arm the SLO monitor with a latency target — the bench calls
        this after calibrating a target from solo-request latency. Window
        history restarts; ``target_s <= 0`` disarms."""
        self._slo = (
            SloMonitor(target_s, **self._slo_kw) if target_s > 0 else None
        )
        if self._slo is not None:
            obs.gauge("serving.slo.target_s").set(float(target_s))

    def slo_snapshot(self) -> dict | None:
        """Current SLO-monitor state for reports (``None`` when disarmed):
        target, objective, and per-window burn rate as of now."""
        mon = self._slo
        if mon is None:
            return None
        now = self.clock()
        return {
            "target_s": mon.target_s,
            "objective": mon.objective,
            "burn_rate": {
                f"{int(w)}s": round(mon.burn_rate(w, now), 4)
                for w in mon.windows
            },
            "breach_alerts": mon.alerts,
            "param_version": self.param_version,
        }

    # ---- drain-free hot param swap ------------------------------------------

    def publish_params(self, params, version: int | None = None) -> bool:
        """Stage a new param tree for a drain-free hot swap into the live
        service. The swap applies at the NEXT stride boundary
        (:meth:`_apply_pending_swap`) — in-flight requests keep decoding
        under their admission-pinned version, new admissions pick up the
        published one; nothing drains, nothing tears.

        Version-gated: ``version`` (default: one past the newest known)
        must be strictly newer than both the active version and any
        still-pending publish — a stale or duplicate publish (e.g. one
        replayed after a preemption) is REFUSED, counted, and returns
        False. A newer publish supersedes a pending unapplied one."""
        floor = self.param_version
        if self._pending_publish is not None:
            floor = max(floor, self._pending_publish[0])
        version = floor + 1 if version is None else int(version)
        if version <= floor:
            obs.counter("serving.param_swaps_refused").inc()
            obs.event(
                "serving_param_swap_refused", version=version,
                active=self.param_version, reason="stale_version",
            )
            return False
        self._pending_publish = (version, params)
        obs.event("serving_param_publish", version=version)
        return True

    def _apply_pending_swap(self) -> bool:
        """Apply a staged publish at the stride boundary — the ONLY place
        the active version ever changes, so a swap is atomic with respect
        to strides: every stride runs entirely under whole versions.

        The ``serving.param_swap`` chaos seam fires BEFORE any state
        mutates: a preemption landing exactly mid-swap requests a drain,
        the check below refuses the swap, and the drained snapshot replays
        entirely under the OLD version — the swap is fully applied or
        fully refused, never torn."""
        if self._pending_publish is None:
            return False
        version, params = self._pending_publish
        chaos.visit("serving.param_swap")
        if self.draining:
            self._pending_publish = None
            obs.counter("serving.param_swaps_refused").inc()
            obs.event(
                "serving_param_swap_refused", version=version,
                active=self.param_version, reason="draining",
            )
            return False
        self._pending_publish = None
        prev = self.param_version
        if self._inflight or self._staged:
            # in-flight lanes AND staged (encoded, lane-less) requests pin
            # the outgoing version until they complete
            self._old_params[prev] = self.params
        self.params = params
        self.param_version = version
        self._swap_history.append({
            "version": version, "from": prev,
            "inflight_pinned": len(self._inflight),
        })
        obs.counter("serving.param_swaps").inc()
        obs.gauge("serving.param_version").set(float(version))
        obs.event(
            "serving_param_swap", version=version, prev=prev,
            inflight_pinned=len(self._inflight),
        )
        return True

    def _params_for(self, version: int):
        """The param tree a stride for ``version``-pinned lanes decodes
        under: the live tree for the active version, else the retained
        tree the swap parked for still-in-flight lanes."""
        if version == self.param_version:
            return self.params
        return self._old_params[version]

    def _retire_versions(self) -> None:
        """Drop retained old-param trees no in-flight lane pins anymore
        (called after completions, so a swap's old version lives exactly
        as long as its last admitted request)."""
        if not self._old_params:
            return
        live = {t.param_version for t in self._inflight.values()}
        live |= {
            self._tickets[r].param_version
            for r in self._staged if r in self._tickets
        }
        for v in [v for v in self._old_params if v not in live]:
            del self._old_params[v]
            obs.counter("serving.param_versions_retired").inc()

    def serve(
        self,
        requests: Iterable[ClipRequest] = (),
        *,
        snapshot_dir: str | None = None,
        realtime: bool = False,
        idle_wait_s: float = 0.002,
    ) -> ServeReport:
        """Run the admission/decode loop until the queue drains (or a drain
        is requested). ``realtime=True`` honors each request's ``arrival_s``
        against the wall clock (the bench's open-loop mode); otherwise every
        submitted request is immediately admissible."""
        global _ACTIVE
        for req in sorted(requests, key=lambda r: r.arrival_s):
            self.submit(req)
        report = ServeReport(submitted=len(self._tickets))
        t0 = self.clock()
        now = lambda: self.clock() - t0  # noqa: E731
        with _ACTIVE_LOCK:
            prev_active = _ACTIVE
            _ACTIVE = self
        pre = PreemptionHandler().install()
        try:
            while True:
                chaos.visit("serving.step")
                if pre.requested:
                    self.drain("sigterm")
                mon = _health_monitor()
                if mon is not None and mon.peer_lost:
                    self.drain("peer_loss")
                if self.draining:
                    # stride-boundary drain: the dispatched stride already
                    # finished (we only reach here between strides); both
                    # in-flight AND pending requests persist to the
                    # snapshot and replay from scratch bit-identically
                    break
                # stride-boundary hot swap: a staged publish lands here,
                # BEFORE admission, so every request admitted this
                # iteration pins the post-swap version
                self._apply_pending_swap()
                if self.draining:
                    continue  # a swap-seam preempt: drain at the loop top
                self._admit_arrived(now, realtime)
                if not self._inflight:
                    if not self._queue:
                        break
                    # queued work not yet arrived (realtime) or blocked on
                    # pages freed only by completions that cannot come —
                    # the former waits, the latter is a sizing error
                    if not realtime:
                        raise OutOfPages(
                            "queue is non-empty but nothing can be "
                            "admitted: a single request needs more pages "
                            "than the whole pool"
                        )
                    time.sleep(idle_wait_s)
                    continue
                self._run_stride(report, now)
            report.drained = self.draining
            report.drain_reason = self._drain_reason
            if self.draining:
                report.snapshot_dir = self._write_snapshot(snapshot_dir)
                obs.event(
                    "serving_drain", reason=self._drain_reason,
                    pending=len(self._queue), inflight=len(self._inflight),
                    snapshot=report.snapshot_dir,
                )
                obs.counter("serving.drains").inc()
                # postmortem bundle BEFORE the working set is released: the
                # pending/inflight census below is still live evidence
                self._drain_postmortem(report)
                # release the drained working set AFTER the snapshot
                # captured the page table (the object stays reusable)
                for slot in sorted(self._inflight):
                    ticket = self._inflight.pop(slot)
                    self.bank.free(ticket.req.req_id)
                    self._free_slots.append(slot)
                    self._tickets.pop(ticket.req.req_id, None)
                for rid in self._staged:
                    self.bank.free(rid)
                    self._tickets.pop(rid, None)
                self._staged.clear()
                for req in self._queue:
                    self._tickets.pop(req.req_id, None)
                self._queue.clear()
        finally:
            pre.uninstall()
            with _ACTIVE_LOCK:
                _ACTIVE = prev_active
        report.wall_s = now()
        report.completed = len(report.results)
        return report

    def _drain_postmortem(self, report: ServeReport) -> None:
        """A drained service leaves the same forensic a dying trainer does:
        a flight-recorder postmortem bundle whose registry carries the SLO
        snapshot, so ``cli.obs_report --postmortem`` diagnoses a SIGTERM /
        peer-loss / chaos drain with the training tooling. Dumps through the
        process-global recorder when one is configured (serving inside a
        training run); otherwise an ephemeral recorder dropping the bundle
        next to the obs event stream, or into the drain snapshot as a last
        resort. Best-effort — a failed dump must never break the drain."""
        extra = {
            "serving": {
                "drain_reason": self._drain_reason,
                "pending": len(self._queue),
                "inflight": len(self._inflight),
                "slo": self.slo_snapshot(),
                # param-version attribution: which version was serving at
                # the drain, and the recent swap arcs — the fleet merge
                # (obs/fleet.py) pins a reward/SLO regression to these
                "param_version": self.param_version,
                "param_swaps": len(self._swap_history),
                "swap_history": self._swap_history[-8:],
            }
        }
        fields = dict(
            drain_reason=self._drain_reason,
            pending=len(self._queue),
            inflight=len(self._inflight),
        )
        reason = f"serving_drain_{self._drain_reason or 'request'}"
        try:
            fr = obs_recorder.active()
            if fr is not None:
                fr.postmortem(reason, registry_extra=extra, **fields)
                return
            span_rec = obs.active()
            out_dir = (
                span_rec.out_dir if span_rec is not None
                else report.snapshot_dir
            )
            if not out_dir:
                return  # no obs, no snapshot: nowhere durable to dump
            fr = obs_recorder.FlightRecorder(
                1, out_dir, run="serving", max_dumps=1
            )
            try:
                fr.postmortem(reason, registry_extra=extra, **fields)
            finally:
                fr.close()
        except Exception:
            # counted, not raised: drains run on the unwind path
            obs.counter("serving.drain_postmortem_error").inc()

    def stride_cost(self) -> dict | None:
        """XLA HLO cost analysis of ONE compiled stride program
        (``obs/flops.compiled_cost``) — the serving MFU ledger's
        compiled-program FLOPs source, analytic fallback when None.
        Available once the service has admitted at least one request (the
        pools and lane state exist then)."""
        from cst_captioning_tpu.obs.flops import compiled_cost

        if self._state is None or self.bank.mem is None:
            return None
        B = self.B
        perm = np.arange(B, dtype=np.int32)
        return compiled_cost(
            self._stride_fn, self.params,
            (self.bank.mem, self.bank.proj, self.bank.mask),
            self.bank.row_table, self.bank.row_lens,
            perm, perm, np.int32(B), self._state,
            np.ones((B,), bool),
        )

    # ---- admission ----------------------------------------------------------

    def _admit_arrived(self, now, realtime: bool) -> None:
        # staged requests bind freed lanes FIRST (they left the queue front
        # earlier, so FIFO holds) — binding is encode-free: the pages and
        # the parked encoder carry already exist
        while self._staged and self._free_slots:
            rid = self._staged.popleft()
            ticket = self._tickets[rid]
            with obs.span("serving.bind_staged", req=rid):
                self._bind_lane(ticket)
            obs.counter("serving.staged_bound").inc()
        # collect every currently-admissible request (a free lane AND
        # enough free pages), grouped by frame bucket — each group encodes
        # as ONE batched pass. Per-row encoder math is batch-composition
        # independent (module docstring), so batching the admission encode
        # changes no bits, only the wall clock a serialized-B=1 admission
        # loop would burn (the static policy amortizes its encoder over the
        # batch; the continuous former must too, or it spots the comparison
        # an encoder pass per request)
        groups: dict[int, list[ClipRequest]] = {}
        free = len(self._free_slots)
        reserved = 0
        while self._queue and free:
            req = self._queue[0]
            if realtime and req.arrival_s > now():
                break
            n_pages = self.bank.pages_for(self.n_mod * self._padded_frames(req))
            if self.bank.free_pages - reserved < n_pages:
                obs.counter("serving.admission_blocked_pages").inc()
                break
            self._queue.popleft()
            groups.setdefault(self._padded_frames(req), []).append(req)
            reserved += n_pages
            free -= 1
        for F, reqs in groups.items():
            for i in range(0, len(reqs), self.admit_group):
                chunk = reqs[i:i + self.admit_group]
                with obs.span("serving.admit", requests=len(chunk)):
                    self._admit_group(F, chunk, now)
        # encode-ahead staging (paged path only): every lane is busy but
        # pages are free — encode queue-front requests NOW and park their
        # pages, so (a) a freed lane binds with zero encode on its critical
        # path and (b) the pool's surplus past one batch's dense footprint
        # actually fills. The dense-gather path cannot do this: its pool is
        # constructor-capped at the dense footprint.
        sgroups: dict[int, list[ClipRequest]] = {}
        if self.paged and not self._free_slots:
            reserved = 0
            while self._queue:
                req = self._queue[0]
                if realtime and req.arrival_s > now():
                    break
                n_pages = self.bank.pages_for(
                    self.n_mod * self._padded_frames(req)
                )
                if self.bank.free_pages - reserved < n_pages:
                    break
                self._queue.popleft()
                sgroups.setdefault(self._padded_frames(req), []).append(req)
                reserved += n_pages
            for F, reqs in sgroups.items():
                for i in range(0, len(reqs), self.admit_group):
                    chunk = reqs[i:i + self.admit_group]
                    with obs.span("serving.stage", requests=len(chunk)):
                        self._admit_group(F, chunk, now, stage=True)
            obs.gauge("serving.staged").set(len(self._staged))
        if groups or sgroups or self._queue:
            obs.gauge("serving.queue_depth").set(len(self._queue))

    def _padded_frames(self, req: ClipRequest) -> int:
        b = self.frame_bucket
        return min(-(-req.num_frames // b) * b, self.model.cfg.max_frames)

    def _admit_group(self, F: int, reqs: list[ClipRequest], now,
                     stage: bool = False) -> None:
        t_admit = now()
        t_enc0 = time.perf_counter()
        with obs.span("serving.encode", requests=len(reqs)):
            enc = self._encode_batch(reqs, F)
        enc_s = (time.perf_counter() - t_enc0) / len(reqs)
        m_len = self.n_mod * F
        for i, req in enumerate(reqs):
            ticket = self._tickets[req.req_id]
            ticket.t_submit = ticket.t_submit or req.arrival_s
            ticket.t_admit = t_admit
            ticket.param_version = self.param_version
            enc_i = jax.tree.map(lambda x: x[i:i + 1], enc)
            pages = self.bank.alloc(req.req_id, m_len)
            self.bank.store(
                pages, enc_i.memory, enc_i.memory_proj, enc_i.memory_mask
            )
            ticket.t_encoded = now()
            ticket.enc_carry = enc_i.carry
            self._ensure_state(enc_i.carry)
            if stage:
                self._staged.append(req.req_id)
                obs.counter("serving.requests_staged").inc()
            else:
                self._bind_lane(ticket)
            obs.counter("serving.requests_admitted").inc()
            obs.counter("flops.serving.encode").inc(self._enc_flops)
            obs.histogram("serving.queue_wait_seconds").observe(
                max(ticket.t_admit - ticket.t_submit, 0.0)
            )
            obs.histogram("serving.encode_seconds").observe(enc_s)
        obs.gauge("serving.slots_in_use").set(len(self._inflight))
        obs.gauge("serving.pages_in_use").set(self.bank.pages_in_use)

    def _bind_lane(self, ticket: _Ticket) -> None:
        """Bind an encoded request (fresh or staged) to a free lane: set
        the device page-table row, seed the lane state from the parked
        encoder carry, arm the request's own RNG stream. No encoder work —
        the encode happened at admission/staging time."""
        slot = self._free_slots.popleft()
        ticket.slot = slot
        ticket.tok = np.full((self.G, self.T), PAD_ID, np.int32)
        ticket.lp = np.zeros((self.G, self.T), np.float32)
        self._inflight[slot] = ticket
        self.bank.bind_row(slot, ticket.req.req_id)
        key_raw = self._key_fn(jax.device_put(np.int32(ticket.req.seed)))
        self._state = self._admit_fn(
            self._state, jax.device_put(np.int32(slot)), ticket.enc_carry,
            key_raw,
        )
        ticket.enc_carry = None  # the lane state owns the carry now

    def _encode_batch(self, reqs: list[ClipRequest], F: int) -> EncoderOutput:
        """One batched encoder pass for an admission group. The batch dim
        pads to the next power of two (repeating row 0; surplus rows are
        discarded) so compile count stays O(log capacity) per frame bucket
        instead of one program per group size."""
        n = len(reqs)
        npad = 1
        while npad < n:
            npad *= 2
        fn = self._encode_fns.get((F, npad))
        if fn is None:
            model = self.model
            fn = compile_fn(
                lambda p, f, m: model.apply(
                    p, f, m, method=CaptionModel.encode
                ),
                CompilePlan(),
            )
            self._encode_fns[(F, npad)] = fn
        feats, masks = {}, {}
        for name, _ in self.model.cfg.modalities:
            rows, mrows = [], []
            for req in reqs:
                x = np.asarray(req.feats[name], np.float32)
                mk = np.asarray(req.masks[name], np.float32)
                pad = F - x.shape[0]
                rows.append(np.pad(x, ((0, pad), (0, 0))))
                mrows.append(np.pad(mk, ((0, pad),)))
            rows += rows[:1] * (npad - n)
            mrows += mrows[:1] * (npad - n)
            feats[name] = jax.device_put(np.stack(rows))
            masks[name] = jax.device_put(np.stack(mrows))
        return fn(self.params, feats, masks)

    # ---- device lane state --------------------------------------------------

    def _ensure_state(self, enc_carry) -> None:
        if self._state is not None:
            return
        G, B = self.G, self.B
        carry = tuple(
            (
                jnp.zeros((G, B) + c.shape[1:], c.dtype),
                jnp.zeros((G, B) + h.shape[1:], h.dtype),
            )
            for c, h in enc_carry
        )
        # key-data layout probed abstractly (eval_shape: no device values,
        # no transfers — the impl-dependent raw width is all we need)
        key_aval = jax.eval_shape(
            lambda: jax.random.key_data(jax.random.key(0))
        )
        self._state = (
            carry,
            jnp.full((G, B), BOS_ID, jnp.int32),
            jnp.ones((G, B), bool),        # empty lanes are born finished
            jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,) + key_aval.shape, key_aval.dtype),
        )
        def admit(state, col, enc_carry, key_raw):
            carry, token, finished, t_local, keys = state
            new_carry = tuple(
                (
                    c.at[:, col].set(
                        jnp.broadcast_to(ec[0], (G,) + ec.shape[1:])
                    ),
                    h.at[:, col].set(
                        jnp.broadcast_to(eh[0], (G,) + eh.shape[1:])
                    ),
                )
                for (c, h), (ec, eh) in zip(carry, enc_carry)
            )
            return (
                new_carry,
                token.at[:, col].set(BOS_ID),
                finished.at[:, col].set(False),
                t_local.at[col].set(0),
                keys.at[col].set(key_raw),
            )

        L = len(enc_carry)
        assert L == len(carry)
        self._admit_fn = compile_fn(
            admit, CompilePlan(donate_argnums=(0,))
        )

    # ---- the stride ---------------------------------------------------------

    def _build_stride_fn(self):
        model, params_model = self.model, None  # params passed per call
        B, G, K, S, T, W = self.B, self.G, self.K, self.S, self.T, self.W
        V = model.cfg.vocab_size
        temp, min_len = self.temperature, self.min_len
        use_kernel = self.use_kernel
        paged = self.paged
        num_layers = model.cfg.num_layers
        kernel_block_b = self.kernel_block_b

        def row_noise(key_raw, t_b):
            """[S, K, V] Gumbel noise on THIS request's offline streams:
            ``gumbel(fold_in(fold_in(key, k), t), (1, V))`` — the exact
            call shape ``fused_decode`` makes for a B=1 batch, so the bits
            match the offline decode draw for draw. Steps past T clamp to
            T-1 like ``rollout_step_keys`` (the overhang draws only ever
            feed discarded emissions)."""
            key = jax.random.wrap_key_data(key_raw)
            ks = jax.vmap(lambda k: jax.random.fold_in(key, k))(
                jnp.arange(K)
            )

            def step_noise(t):
                ks_t = jax.vmap(lambda kk: jax.random.fold_in(kk, t))(ks)
                return gumbel_step_noise(ks_t, (1, V), jnp.float32)[:, 0]

            ts = jnp.minimum(t_b + jnp.arange(S), T - 1)
            return jax.vmap(step_noise)(ts)

        def stride(params, pools, table, lens, perm, inv, n_active, state,
                   step_mask):
            """One S-step stride over the lanes ``step_mask`` selects.

            ``step_mask`` [B] (slot order) freezes the lanes it excludes:
            they are treated as finished for the decode, their state leaves
            select back to the pre-stride values, and their ``t_local``
            does not advance — so their RNG streams resume exactly where
            they paused. A hot param swap runs one stride per live version
            with that version's lanes masked in; the all-True mask is the
            single-version case and computes bit-identically to an unmasked
            stride (``where(True, new, old) == new``)."""
            carry, token, finished, t_local, keys = state
            take1 = lambda x: jnp.take(x, perm, axis=1)  # noqa: E731
            carry_c = jax.tree.map(take1, carry)
            token_c, fin_c = take1(token), take1(finished)
            mask_c = jnp.take(step_mask, perm)
            carry_c0, token_c0, fin_c0 = carry_c, token_c, fin_c
            fin_c = fin_c | ~mask_c[None, :]
            t_c = jnp.take(t_local, perm)
            keys_c = jnp.take(keys, perm, axis=0)
            # compaction permutes TABLE ROWS, never pages: the permuted
            # [B, width] table is all the decode needs on either path
            table_c = jnp.take(table, perm, axis=0)
            lens_c = jnp.take(lens, perm)
            if K:
                noise = jnp.transpose(
                    jax.vmap(row_noise)(keys_c, t_c), (1, 2, 0, 3)
                )  # [S, K, B, V]
            else:
                noise = jnp.zeros((S, 0, B, V), jnp.float32)

            if use_kernel and paged:
                from cst_captioning_tpu.ops.decode_pallas import (
                    fused_decode_stride_paged,
                )

                # pool + table pass straight through: the kernel resolves
                # pages by table lookup — no dense bank this stride
                carry_c, toks, lps = fused_decode_stride_paged(
                    params["params"]["cell"], carry_c, token_c, fin_c,
                    *pools, table_c, noise, jnp.int32(0), n_active,
                    steps=S, temperature=temp, min_len=0,
                    num_layers=num_layers, mem_lens=lens_c,
                    block_b=kernel_block_b,
                )
                fin_c = fin_c | jnp.any(toks == EOS_ID, axis=0)
                token_c = toks[-1]
            elif use_kernel:
                from cst_captioning_tpu.ops.decode_pallas import (
                    fused_decode_stride,
                )

                mem, proj, mask = gather_bank(pools, table_c)
                carry_c, toks, lps = fused_decode_stride(
                    params["params"]["cell"], carry_c, token_c, fin_c,
                    mem, proj, mask,
                    noise, jnp.int32(0), n_active, steps=S,
                    temperature=temp, min_len=0, num_layers=num_layers,
                    mem_lens=lens_c, block_b=kernel_block_b,
                )
                fin_c = fin_c | jnp.any(toks == EOS_ID, axis=0)
                token_c = toks[-1]
            else:
                mem, proj, mask = gather_bank(pools, table_c)
                enc_c = EncoderOutput(mem, proj, mask, ())
                def step(st, s):
                    carry_s, token_s, fin_s = st
                    carry_s, logits = lane_decode_step(
                        model, params, carry_s, token_s, enc_c
                    )
                    logits = forbid_special(logits)
                    if min_len > 0:
                        blocked = logits.at[..., EOS_ID].set(-1.0e9)
                        logits = jnp.where(
                            ((t_c + s) < min_len)[None, :, None],
                            blocked, logits,
                        )
                    g_nxt = jnp.argmax(logits[0], axis=-1)
                    tl = logits[1:] / temp
                    s_nxt = jnp.argmax(tl + noise[s], axis=-1)
                    nxt = jnp.concatenate(
                        [g_nxt[None], s_nxt], axis=0
                    ).astype(jnp.int32)
                    lp = selected_logprob(logits, nxt)
                    nxt, lp, fin_s = step_outputs(nxt, lp, fin_s)
                    return (carry_s, nxt, fin_s), (nxt, lp)

                (carry_c, token_c, fin_c), (toks, lps) = jax.lax.scan(
                    step, (carry_c, token_c, fin_c), jnp.arange(S)
                )

            # frozen-lane select-back: both decode paths advance carry for
            # rows they treat as finished (the scan's lane step computes
            # every column), so lanes outside the mask restore their
            # pre-stride state bit-exactly — a masked-out lane's stream is
            # untouched, not merely ignored
            def sel(new, old):
                m = mask_c.reshape((1, -1) + (1,) * (new.ndim - 2))
                return jnp.where(m, new, old)

            carry_c = jax.tree.map(sel, carry_c, carry_c0)
            token_c = sel(token_c, token_c0)
            fin_c = sel(fin_c, fin_c0)
            back1 = lambda x: jnp.take(x, inv, axis=1)  # noqa: E731
            new_state = (
                jax.tree.map(back1, carry_c),
                back1(token_c),
                back1(fin_c),
                t_local + S * step_mask.astype(jnp.int32),
                keys,
            )
            return new_state, jnp.take(toks, inv, axis=2), jnp.take(
                lps, inv, axis=2
            )

        return compile_fn(stride, CompilePlan(donate_argnums=(7,)))

    def _run_stride(self, report: ServeReport, now) -> None:
        active = sorted(self._inflight)
        perm = np.fromiter(
            (s for s in active), np.int32, len(active)
        )
        rest = np.fromiter(
            (s for s in range(self.B) if s not in self._inflight),
            np.int32, self.B - len(active),
        )
        perm = np.concatenate([perm, rest])
        inv = np.argsort(perm, kind="stable").astype(np.int32)
        # the page table and per-lane lengths are DEVICE-resident (bound at
        # lane bind, cleared at completion) — nothing per-stride to build
        # or upload for them; only the permutation/masks cross per stride
        # group active lanes by admission-pinned param version: one stride
        # dispatch per LIVE version, each under that version's params with
        # the other versions' lanes frozen (step_mask). The common single-
        # version case is exactly the old one-dispatch stride (all-True
        # mask); across a hot swap the groups share the lane state and the
        # per-lane RNG streams stay untouched, so every request remains
        # bit-identical to its offline decode under its pinned version.
        by_ver: dict[int, list[int]] = {}
        for slot in active:
            by_ver.setdefault(
                self._inflight[slot].param_version, []
            ).append(slot)
        versions = sorted(by_ver)
        if len(versions) <= 1:
            masks = [np.ones((self.B,), bool)]
        else:
            masks = []
            for v in versions:
                m = np.zeros((self.B,), bool)
                m[by_ver[v]] = True
                masks.append(m)
        with obs.span(
            "serving.stride", active=len(active), versions=len(versions)
        ):
            dev = jax.device_put(
                (perm, inv, np.int32(len(active)), tuple(masks))
            )
            perm_d, inv_d, n_d, masks_d = dev
            outs = []
            for v, mask_d in zip(versions, masks_d):
                self._state, toks, lps = self._stride_fn(
                    self._params_for(v),
                    (self.bank.mem, self.bank.proj, self.bank.mask),
                    self.bank.row_table, self.bank.row_lens,
                    perm_d, inv_d, n_d, self._state, mask_d,
                )
                outs.append((toks, lps))
            # the per-stride sync point: ONE explicit readback of the small
            # host-facing outputs (module docstring)
            outs_np, fin_np = jax.device_get(
                (tuple(outs), self._state[2])
            )
        report.strides += 1
        obs.counter("serving.strides").inc()
        obs.counter("flops.serving.stride").inc(
            len(active) * self.G * self.S * self._tok_flops
        )
        obs.gauge("serving.pages.in_use").set(self.bank.pages_in_use)
        obs.gauge("serving.pages.free").set(self.bank.free_pages)
        obs.gauge("serving.pages.table_rows").set(self.B)
        if self.paged and self.bank.mem is not None:
            # the dense-gather path would have paid 3x the bank bytes per
            # dispatch (pool read + bank write + kernel read); the paged
            # kernel pays 1x — count the 2x saved, per version dispatch
            E = int(self.bank.mem.shape[-1])
            A = int(self.bank.proj.shape[-1])
            nbytes = int(self.bank.mem.dtype.itemsize)
            dense = serving_bank_bytes_per_stride(
                self.B, self.W, E, A, nbytes, paged=False
            )
            paged = serving_bank_bytes_per_stride(
                self.B, self.W, E, A, nbytes, paged=True
            )
            obs.counter("serving.gather_bytes_avoided").inc(
                len(versions) * (dense - paged)
            )
        for v, (toks_np, lps_np) in zip(versions, outs_np):
            for slot in by_ver[v]:
                ticket = self._inflight[slot]
                n = min(self.S, self.T - ticket.t)
                ticket.tok[:, ticket.t:ticket.t + n] = toks_np[:n, :, slot].T
                ticket.lp[:, ticket.t:ticket.t + n] = lps_np[:n, :, slot].T
                ticket.t += n
                if bool(fin_np[:, slot].all()) or ticket.t >= self.T:
                    self._complete(ticket, report, now)
        self._retire_versions()

    def _complete(self, ticket: _Ticket, report: ServeReport, now) -> None:
        with obs.span("serving.detok", req=ticket.req.req_id):
            t_det0 = time.perf_counter()
            best = int(npad_best_lane_index(ticket.lp))
            row = ticket.tok[best]
            ids: list[int] = []
            for tok in row:
                tok = int(tok)
                if tok in (EOS_ID, PAD_ID):
                    break
                ids.append(tok)
            caption = self.vocab.decode(row) if self.vocab is not None else None
            detok_s = time.perf_counter() - t_det0
        t_done = now()
        self._inflight.pop(ticket.slot)
        self._free_slots.append(ticket.slot)
        self.bank.clear_row(ticket.slot)
        self.bank.free(ticket.req.req_id)
        # evict the ticket: an always-on service must not grow state per
        # served request (and a later request may legitimately reuse an id)
        self._tickets.pop(ticket.req.req_id, None)
        phases = {
            "queue_wait": max(ticket.t_admit - ticket.t_submit, 0.0),
            "encode": max(ticket.t_encoded - ticket.t_admit, 0.0),
            "decode": max(t_done - ticket.t_encoded, 0.0),
            "detok": detok_s,
        }
        latency = max(t_done - ticket.t_submit, 0.0)
        result = CaptionResult(
            req_id=ticket.req.req_id,
            tokens=ticket.tok,
            logprobs=ticket.lp,
            best_lane=best,
            caption_ids=ids,
            caption=caption,
            latency_s=latency,
            phases=phases,
            param_version=ticket.param_version,
        )
        report.results[ticket.req.req_id] = result
        obs.counter("serving.requests_completed").inc()
        obs.gauge("serving.slots_in_use").set(len(self._inflight))
        obs.gauge("serving.pages_in_use").set(self.bank.pages_in_use)
        obs.histogram("serving.decode_seconds").observe(
            phases["decode"]
        )
        obs.histogram("serving.detok_seconds").observe(detok_s)
        obs.histogram("serving.latency_seconds").observe(latency)
        if self._slo is not None:
            # t_done is the service's monotone clock (injectable): the SLO
            # windows slide on the same timeline the latencies came from
            self._slo.observe(latency, t_done)
        obs.event(
            "serving_request", req=ticket.req.req_id, latency_s=latency,
            best_lane=best, steps=ticket.t,
            param_version=ticket.param_version, **{
                f"{k}_s": v for k, v in phases.items()
            },
        )
        if self._feedback is not None:
            # serving-as-actor feedback capture: the completed request's
            # (greedy + sampled lanes, logprobs, seed, pinned version) go
            # to the online learner — tok/lp are already host arrays, so
            # this dispatches nothing on device
            self._feedback(ticket.req, result, ticket.param_version)

    # ---- drain persistence --------------------------------------------------

    def _write_snapshot(self, snapshot_dir: str | None) -> str | None:
        if snapshot_dir is None:
            return None
        os.makedirs(snapshot_dir, exist_ok=True)
        # in-flight first (they were admitted earlier), then staged (encoded
        # but not yet bound to a lane), then queue order — replay preserves
        # the service order
        drained: list[ClipRequest] = [
            self._inflight[s].req for s in sorted(
                self._inflight, key=lambda s: self._inflight[s].t_admit
            )
        ] + [
            self._tickets[r].req for r in self._staged if r in self._tickets
        ] + list(self._queue)
        arrays: dict[str, np.ndarray] = {}
        manifest = {
            "requests": [],
            "page_table": self.bank.snapshot(),
            "in_flight_steps": {
                t.req.req_id: t.t for t in self._inflight.values()
            },
            "staged": list(self._staged),
            "drain_reason": self._drain_reason,
        }
        for i, req in enumerate(drained):
            manifest["requests"].append({
                "req_id": req.req_id,
                "seed": req.seed,
                "arrival_s": req.arrival_s,
                "modalities": sorted(req.feats),
            })
            for name in req.feats:
                arrays[f"{i}.feats.{name}"] = np.asarray(
                    req.feats[name], np.float32
                )
                arrays[f"{i}.masks.{name}"] = np.asarray(
                    req.masks[name], np.float32
                )
        for req in drained:
            self._tickets.pop(req.req_id, None)
        np.savez(os.path.join(snapshot_dir, "queue.npz"), **arrays)
        tmp = os.path.join(snapshot_dir, ".manifest.json.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(manifest, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(snapshot_dir, "manifest.json"))
        return snapshot_dir


def load_snapshot(
    snapshot_dir: str,
    service: "CaptionService | None" = None,
    grow_to: int | None = None,
) -> list[ClipRequest]:
    """Drained queue -> requests, in the order the service would have run
    them. Re-serving them through a fresh CaptionService yields bit-identical
    tokens (per-request determinism; in-flight requests restart from step 0).

    The regrow direction: pass ``service`` to replay the snapshot onto a
    rejoined node — the drained requests resubmit in their drain order so
    admissions resume where the outage cut them off. ``grow_to`` first
    grows the service's lane pool to that capacity at a stride seam
    (:meth:`CaptionService.grow_capacity`), covering the shard that rode
    out the outage at reduced width. The bare one-argument call keeps the
    old read-only contract and just returns the requests."""
    with open(os.path.join(snapshot_dir, "manifest.json"),
              encoding="utf-8") as f:
        manifest = json.load(f)
    data = np.load(os.path.join(snapshot_dir, "queue.npz"))
    out: list[ClipRequest] = []
    for i, rec in enumerate(manifest["requests"]):
        feats = {m: data[f"{i}.feats.{m}"] for m in rec["modalities"]}
        masks = {m: data[f"{i}.masks.{m}"] for m in rec["modalities"]}
        out.append(ClipRequest(
            req_id=rec["req_id"], feats=feats, masks=masks,
            seed=int(rec["seed"]), arrival_s=float(rec["arrival_s"]),
        ))
    if service is not None:
        if grow_to is not None:
            service.grow_capacity(grow_to)
        for req in out:
            service.submit(req)
        obs.counter("serving.requests_replayed").inc(len(out))
        obs.event(
            "serving_replay", requests=len(out), capacity=service.B,
            drain_reason=manifest.get("drain_reason", ""),
        )
    return out


def _health_monitor():
    """The active elastic-health monitor, if resilience wiring started one
    (lazy import: serving must not drag the health stack in by default)."""
    from cst_captioning_tpu.resilience import health

    return health.active_monitor()


# ---- the static-batching reference policy -----------------------------------


def static_batch_serve(
    model: CaptionModel,
    params,
    requests: list[ClipRequest],
    *,
    capacity: int = 8,
    num_rollouts: int = 2,
    temperature: float = 1.0,
    max_len: int | None = None,
    min_len: int = 0,
    vocab=None,
    service_seed: int = 0,
    realtime: bool = False,
    clock: Callable[[], float] = time.monotonic,
    idle_wait_s: float = 0.002,
    decode_fn=None,
) -> ServeReport:
    """The policy continuous batching is benchmarked against: wait until
    ``capacity`` requests are queued (or no more are coming), decode the
    whole batch offline through ``fused_decode``, return everyone together.

    Every request pays batch-formation wait plus the full batch's decode
    (the slowest member gates all), which is exactly the latency-tail cost
    the continuous engine removes. Same hardware, same model, same K lanes,
    same NPAD best-lane selection — only the batching policy differs. The
    batch shares one rng (requests are NOT per-request deterministic here;
    this is the throughput baseline, not the parity subject).

    Batches are FIXED-SHAPE: a final partial batch pads with repeats of its
    first row (outputs discarded), so the whole run is one compiled program
    — static batch servers run fixed shapes, that is the point of the
    policy. ``decode_fn`` lets the bench pass a pre-warmed jitted decode so
    neither policy's measurements pay compile time.
    """
    from cst_captioning_tpu.decoding.fused import fused_decode

    T = int(max_len or model.cfg.max_len)
    F = model.cfg.max_frames
    pending = deque(sorted(requests, key=lambda r: r.arrival_s))
    report = ServeReport(submitted=len(pending))
    t0 = clock()
    now = lambda: clock() - t0  # noqa: E731
    decode = decode_fn or compile_fn(
        lambda p, f, m, r: fused_decode(
            model, p, f, m, r, num_rollouts=num_rollouts,
            temperature=temperature, max_len=T, min_len=min_len,
        ),
        CompilePlan(),
    )
    batch_idx = 0
    service_key = jax.random.key(service_seed)
    while pending:
        arrived = [r for r in pending if (not realtime)
                   or r.arrival_s <= now()]
        if len(arrived) < min(capacity, len(pending)):
            # batch former: wait for a full batch while more is coming
            time.sleep(idle_wait_s)
            continue
        batch = [pending.popleft() for _ in range(min(capacity,
                                                      len(pending)))]
        rows_pad = capacity - len(batch)
        feats = {}
        masks = {}
        for name, _ in model.cfg.modalities:
            rows, mrows = [], []
            for req in batch:
                x = np.asarray(req.feats[name], np.float32)
                mk = np.asarray(req.masks[name], np.float32)
                pad = F - x.shape[0]
                rows.append(np.pad(x, ((0, pad), (0, 0))))
                mrows.append(np.pad(mk, ((0, pad),)))
            rows += rows[:1] * rows_pad
            mrows += mrows[:1] * rows_pad
            feats[name] = jax.device_put(np.stack(rows))
            masks[name] = jax.device_put(np.stack(mrows))
        rng = jax.random.fold_in(service_key, batch_idx)
        batch_idx += 1
        g, gl, s, sl = jax.device_get(
            decode(params, feats, masks, rng)
        )
        t_done = now()
        for i, req in enumerate(batch):
            tok = np.concatenate([g[i][None], s[:, i]], axis=0)
            lp = np.concatenate([gl[i][None], sl[:, i]], axis=0)
            best = int(npad_best_lane_index(lp))
            ids: list[int] = []
            for t in tok[best]:
                t = int(t)
                if t in (EOS_ID, PAD_ID):
                    break
                ids.append(t)
            latency = max(t_done - (req.arrival_s if realtime else 0.0), 0.0)
            report.results[req.req_id] = CaptionResult(
                req_id=req.req_id, tokens=tok, logprobs=lp, best_lane=best,
                caption_ids=ids,
                caption=vocab.decode(tok[best]) if vocab is not None else None,
                latency_s=latency,
                phases={"queue_wait": 0.0, "encode": 0.0,
                        "decode": latency, "detok": 0.0},
            )
    report.wall_s = now()
    report.completed = len(report.results)
    return report
