"""Always-on caption serving: continuous batching over the decode stack.

The decode endgame (PRs 4-5) built a fast offline rollout program: fused
(1+K)-lane scan, multi-step stride kernel, finished-lane compaction. This
package productionizes it into a request-serving layer (README "Serving"):

- :mod:`serving.pages`   — paged HBM bank for ragged encoder outputs
  (fixed-size pages + host free-list + device page table, replacing
  per-request padded slabs — the Ragged Paged Attention memory layout);
- :mod:`serving.engine`  — :class:`CaptionService`: request queue +
  admission/batch-former loop slotting new clips into decode lanes freed
  between strides (continuous batching), with drain/snapshot/restore for
  preemption and a static-batching reference policy for the bench;
- :mod:`serving.traffic` — seeded, replayable Poisson/bursty traffic traces
  (the bench_serving.py workload generator).

Every request decodes on its OWN fold_in RNG stream, so a request admitted
mid-flight is token- and logprob-bit-identical to the same clip decoded
offline through decoding/fused.py (pinned by tests/test_serving.py).

The engine is also the ONLINE RL actor (README "Online RL from served
traffic"): a ``feedback`` hook hands every completed request's lanes to
:class:`~cst_captioning_tpu.rl.online.OnlineSCSTTrainer`, and
:meth:`CaptionService.publish_params` hot-swaps learner params back in at
a stride boundary — drain-free, with in-flight requests pinned to their
admission-time param version (still bit-identical to the offline decode
under that version).
"""

from cst_captioning_tpu.serving.engine import (
    CaptionResult,
    CaptionService,
    ClipRequest,
    ServeReport,
    load_snapshot,
    request_drain,
    static_batch_serve,
)
from cst_captioning_tpu.serving.pages import OutOfPages, PageBank
from cst_captioning_tpu.serving.traffic import Trace, TrafficSpec, make_trace

__all__ = [
    "CaptionResult",
    "CaptionService",
    "ClipRequest",
    "OutOfPages",
    "PageBank",
    "ServeReport",
    "Trace",
    "TrafficSpec",
    "load_snapshot",
    "make_trace",
    "request_drain",
    "static_batch_serve",
]
