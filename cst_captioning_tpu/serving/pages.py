"""Paged HBM bank for ragged in-flight encoder outputs.

Offline decode pads every clip's memory bank to ``[B, M_max, E]`` — fine
when the batch lives for one program, wasteful when requests of wildly
different lengths coexist for many strides: a 1-frame clip would pin the
same HBM as a max-frame one for its whole lifetime. Here encoder outputs
live in fixed-size **pages** (the Ragged Paged Attention memory layout,
arXiv:2604.15464):

- three device pools — ``mem [N, P, E]``, ``proj [N, P, A]``,
  ``mask [N, P]`` — hold N pages of P memory slots each;
- a **host-side free-list** hands pages out at admission and takes them
  back at completion (allocation is pure Python — no device traffic);
- a **device-resident page table** (int32 ``[slots, pages_per_row]`` +
  per-row lengths, updated by one jitted donated row-set per lane
  bind/clear) maps each decode lane to its pages. The paged stride kernel
  (``ops/decode_pallas.fused_decode_stride_paged``) reads pages straight
  out of the pools by table lookup IN-kernel — no dense bank is ever
  materialized, so the pool may exceed one batch's dense footprint. The
  XLA decode path (and the parity oracle) instead runs
  :func:`gather_bank`, the old dense ``[B, W, E]`` gather (one
  ``jnp.take`` per pool — a device-side copy, no host sync);
- **page 0 is the shared zero page**: mask 0 everywhere, so table padding
  gathers slots the attention softmax excludes exactly (masked scores hit
  ``-1e9`` and underflow to an exact 0 weight — the bit-exactness argument
  in decoding/fused.py's compaction applies unchanged).

A request holds ``ceil(M_r / P)`` pages for exactly its in-flight window,
so the pool capacity bounds the *sum of active lengths*, not
``slots * M_max`` — the admission loop backpressures on ``OutOfPages``
instead of overcommitting HBM.

Writes are one jitted donated scatter per admission (``pool.at[idx].set``);
frees touch no device state (a freed page's stale floats are unobservable:
nothing points at it until it is re-allocated and overwritten).
"""

from __future__ import annotations

from collections import deque
from typing import Hashable

import jax
import jax.numpy as jnp
import numpy as np


class OutOfPages(RuntimeError):
    """The pool cannot hold another request's pages right now (backpressure:
    the admission loop keeps the request queued until completions free
    pages — it must NOT treat this as a permanent rejection)."""


def gather_bank(pools, table):
    """Dense ``[B, W, *]`` bank from ``(mem, proj, mask)`` pools + a
    ``[B, width]`` page table — the XLA decode path's fallback and the
    parity oracle the paged stride kernel is pinned bit-exact against.
    Page 0 is the shared zero page, so table padding gathers slots the
    attention softmax excludes exactly."""
    mem_pool, proj_pool, mask_pool = pools
    B, width = table.shape
    P = mem_pool.shape[1]
    flat = table.reshape(-1)
    mem = jnp.take(mem_pool, flat, axis=0).reshape(B, width * P, -1)
    proj = jnp.take(proj_pool, flat, axis=0).reshape(B, width * P, -1)
    mask = jnp.take(mask_pool, flat, axis=0).reshape(B, width * P)
    return mem, proj, mask


def _bind(table, lens, row, rowv, ln):
    return table.at[row].set(rowv), lens.at[row].set(ln)


# one jitted donated row-set shared by every bank: the device table updates
# in place at lane bind/clear instead of re-uploading the whole table per
# stride (the old host-built-table convention)
_BIND_FN = jax.jit(_bind, donate_argnums=(0, 1))


class PageBank:
    """Fixed-size page pool with host free-list + host page table.

    ``num_pages`` counts usable pages EXCLUDING the reserved zero page
    (page id 0); ``page_size`` is P, the memory slots per page. Device
    pools allocate lazily at the first :meth:`store` (dims/dtypes come
    from the first encoder output), so constructing a bank costs nothing.
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages < 1 or page_size < 1:
            raise ValueError(
                f"need num_pages >= 1 and page_size >= 1, got "
                f"{num_pages}, {page_size}"
            )
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self._free: deque[int] = deque(range(1, self.num_pages + 1))
        self._owned: dict[Hashable, list[int]] = {}
        self._lens: dict[Hashable, int] = {}
        self.mem = None    # [N+1, P, E]
        self.proj = None   # [N+1, P, A]
        self.mask = None   # [N+1, P]
        self.row_table = None   # device [rows, width] int32 (init_rows)
        self.row_lens = None    # device [rows] int32 memory lengths
        self._store_fns: dict[tuple[int, int], object] = {}
        self.pages_hwm = 0  # high-water mark, for the obs gauge

    # ---- host-side accounting ----------------------------------------------

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_for(self, m_len: int) -> int:
        return -(-int(m_len) // self.page_size)

    def can_fit(self, m_len: int) -> bool:
        return self.pages_for(m_len) <= len(self._free)

    def alloc(self, owner: Hashable, m_len: int) -> list[int]:
        """Reserve pages for ``m_len`` memory slots; raises OutOfPages."""
        if owner in self._owned:
            raise ValueError(f"owner {owner!r} already holds pages")
        n = self.pages_for(m_len)
        if n > len(self._free):
            raise OutOfPages(
                f"{n} page(s) requested, {len(self._free)} free "
                f"(pool {self.num_pages} x {self.page_size} slots)"
            )
        pages = [self._free.popleft() for _ in range(n)]
        self._owned[owner] = pages
        self._lens[owner] = int(m_len)
        self.pages_hwm = max(self.pages_hwm, self.pages_in_use)
        return pages

    def free(self, owner: Hashable) -> None:
        """Return an owner's pages to the free list (no device writes: stale
        page contents are unreachable until re-allocation overwrites them)."""
        for p in self._owned.pop(owner, ()):
            self._free.append(p)
        self._lens.pop(owner, None)

    def owned(self, owner: Hashable) -> list[int]:
        return list(self._owned.get(owner, ()))

    def length(self, owner: Hashable) -> int:
        return self._lens.get(owner, 0)

    def table(self, owners: list[Hashable | None], width: int) -> np.ndarray:
        """Page table rows for ``owners`` (None/unknown -> all zero pages),
        padded to ``width`` pages with the zero page."""
        out = np.zeros((len(owners), width), np.int32)
        for i, owner in enumerate(owners):
            pages = self._owned.get(owner, ()) if owner is not None else ()
            if len(pages) > width:
                raise ValueError(
                    f"owner {owner!r} holds {len(pages)} pages > table "
                    f"width {width}"
                )
            out[i, : len(pages)] = pages
        return out

    # ---- device-resident per-lane page table --------------------------------

    def init_rows(self, rows: int, width: int) -> None:
        """Materialize the device-resident page table: ``row_table``
        [rows, width] int32 (row = decode lane, zero-page padded) and
        ``row_lens`` [rows] int32 per-lane memory lengths. The serving
        stride passes BOTH straight into the decode program — the paged
        kernel reads pages by table lookup, the XLA path feeds them to
        :func:`gather_bank` — so per-stride host uploads shrink to the
        permutation/masks only."""
        self.row_table = jnp.zeros((int(rows), int(width)), jnp.int32)
        self.row_lens = jnp.zeros((int(rows),), jnp.int32)

    def bind_row(self, row: int, owner: Hashable) -> None:
        """Point table row ``row`` at ``owner``'s pages (one jitted donated
        row-set; explicit uploads keep the serving loop transfer-guard
        clean)."""
        pages = self._owned.get(owner, ())
        width = self.row_table.shape[1]
        if len(pages) > width:
            raise ValueError(
                f"owner {owner!r} holds {len(pages)} pages > table "
                f"width {width}"
            )
        rowv = np.zeros((width,), np.int32)
        rowv[: len(pages)] = pages
        self.row_table, self.row_lens = _BIND_FN(
            self.row_table, self.row_lens,
            jax.device_put(np.int32(row)), jax.device_put(rowv),
            jax.device_put(np.int32(self._lens.get(owner, 0))),
        )

    def clear_row(self, row: int) -> None:
        """Reset table row ``row`` to the shared zero page (lane freed)."""
        width = self.row_table.shape[1]
        self.row_table, self.row_lens = _BIND_FN(
            self.row_table, self.row_lens,
            jax.device_put(np.int32(row)),
            jax.device_put(np.zeros((width,), np.int32)),
            jax.device_put(np.int32(0)),
        )

    def grow_rows(self, rows: int) -> None:
        """Grow the device table's row count (the lane-pool regrow seam);
        new rows are born pointing at the zero page."""
        cur = self.row_table.shape[0]
        new_r = int(rows)
        if new_r < cur:
            raise ValueError(
                f"grow_rows({rows}) below current row count {cur} — rows "
                "only grow (shrink = drain and rebuild)"
            )
        if new_r == cur:
            return
        self.row_table = jnp.pad(self.row_table, ((0, new_r - cur), (0, 0)))
        self.row_lens = jnp.pad(self.row_lens, ((0, new_r - cur),))

    def grow(self, num_pages: int) -> None:
        """Grow the page pool in place (the elastic regrow direction: a
        shard rebuilt at reduced width re-admits its drained work at full
        capacity). Only grows — the free-list gains the new page ids and
        the device pools (when already materialized) zero-pad along the
        page axis, so existing page contents, the page table, and the
        shared zero page are untouched. Shrinking is drain-and-rebuild,
        never in place."""
        new_n = int(num_pages)
        if new_n < self.num_pages:
            raise ValueError(
                f"PageBank.grow({num_pages}) below current pool size "
                f"{self.num_pages} — the pool only grows (shrink = drain "
                "and rebuild)"
            )
        if new_n == self.num_pages:
            return
        extra = new_n - self.num_pages
        self._free.extend(range(self.num_pages + 1, new_n + 1))
        self.num_pages = new_n
        if self.mem is not None:
            def pad(x):
                return jnp.pad(x, [(0, extra)] + [(0, 0)] * (x.ndim - 1))

            self.mem = pad(self.mem)
            self.proj = pad(self.proj)
            self.mask = pad(self.mask)

    def snapshot(self) -> dict:
        """JSON-ready accounting snapshot (the drain persistence payload)."""
        return {
            "num_pages": self.num_pages,
            "page_size": self.page_size,
            "free": list(self._free),
            "owned": {str(k): list(v) for k, v in self._owned.items()},
            "lengths": {str(k): v for k, v in self._lens.items()},
            "pages_hwm": self.pages_hwm,
        }

    # ---- device pools -------------------------------------------------------

    def _ensure_pools(self, memory: jnp.ndarray, proj: jnp.ndarray) -> None:
        if self.mem is not None:
            return
        P = self.page_size
        E, A = memory.shape[-1], proj.shape[-1]
        # +1 row: page 0, the shared always-zero page table-padding gathers
        self.mem = jnp.zeros((self.num_pages + 1, P, E), memory.dtype)
        self.proj = jnp.zeros((self.num_pages + 1, P, A), proj.dtype)
        self.mask = jnp.zeros((self.num_pages + 1, P), jnp.float32)

    def store(self, pages: list[int], memory: jnp.ndarray, proj: jnp.ndarray,
              mask: jnp.ndarray) -> None:
        """Scatter one encoder output (``[1, M, *]`` leaves) into ``pages``.

        One jitted donated scatter per distinct (n_pages, M) shape — the
        pools update in place instead of double-buffering. The M -> n*P
        pad rides inside the same program (mask pads with 0, so padded
        slots are excluded from every later softmax).
        """
        self._ensure_pools(memory, proj)
        n = len(pages)
        M = int(memory.shape[1])
        if n != self.pages_for(M):
            raise ValueError(
                f"{n} page(s) passed for M={M} (need {self.pages_for(M)})"
            )
        fn = self._store_fns.get((n, M))
        if fn is None:
            fn = jax.jit(
                lambda pools, idx, mem1, proj1, mask1: _scatter(
                    pools, idx, mem1, proj1, mask1, self.page_size, n
                ),
                donate_argnums=(0,),
            )
            self._store_fns[(n, M)] = fn
        # explicit upload: the serving loop runs under transfer_guard
        idx = jax.device_put(np.asarray(pages, np.int32))
        self.mem, self.proj, self.mask = fn(
            (self.mem, self.proj, self.mask), idx, memory, proj, mask
        )


def _scatter(pools, idx, memory, proj, mask, page_size: int, n: int):
    mem_pool, proj_pool, mask_pool = pools
    M = memory.shape[1]
    pad = n * page_size - M
    memp = jnp.pad(memory[0], ((0, pad), (0, 0)))
    projp = jnp.pad(proj[0], ((0, pad), (0, 0)))
    maskp = jnp.pad(mask[0].astype(jnp.float32), ((0, pad),))
    return (
        mem_pool.at[idx].set(memp.reshape(n, page_size, -1)),
        proj_pool.at[idx].set(projp.reshape(n, page_size, -1)),
        mask_pool.at[idx].set(maskp.reshape(n, page_size)),
    )
