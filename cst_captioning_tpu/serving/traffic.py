"""Seeded, replayable request traffic for the serving bench and tests.

A :class:`Trace` is a plain list of arrival records — ``(arrival_s,
req_id, num_frames, seed)`` — generated deterministically from a
:class:`TrafficSpec`, so the same spec always produces the same workload
(bench runs are comparable across machines and the drain-recovery test can
replay an identical stream). Two arrival processes:

- ``poisson`` — homogeneous Poisson arrivals at ``rate_rps`` (i.i.d.
  exponential gaps), the steady-state load model;
- ``bursty``  — a two-state modulated Poisson process: ``burst_len_s``
  windows at ``rate_rps * burst_factor`` alternating with quiet windows at
  ``rate_rps / burst_factor`` — the tail-latency stressor (admission
  backpressure + queue growth is exactly what continuous batching must
  absorb better than static batching).

Clip lengths draw uniformly from ``frame_choices`` so traces exercise the
paged bank's raggedness (mix 1-frame and max-frame clips for the
adversarial case). Feature payloads are NOT stored in the trace — they
regenerate deterministically from each record's seed via
:func:`synth_request_features`, keeping traces tiny and replayable.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

import numpy as np


@dataclass(frozen=True)
class TrafficSpec:
    kind: str = "poisson"                 # "poisson" | "bursty"
    rate_rps: float = 4.0                 # mean arrival rate (requests/s)
    num_requests: int = 32
    seed: int = 0
    burst_factor: float = 4.0             # bursty: rate multiplier in bursts
    burst_len_s: float = 1.0              # bursty: burst/quiet window length
    frame_choices: tuple[int, ...] = (4,)  # clip lengths (frames) to mix

    def __post_init__(self):
        if self.kind not in ("poisson", "bursty"):
            raise ValueError(f"unknown traffic kind {self.kind!r}")
        if self.rate_rps <= 0 or self.num_requests < 1:
            raise ValueError(
                f"need rate_rps > 0 and num_requests >= 1, got "
                f"{self.rate_rps}, {self.num_requests}"
            )
        if self.kind == "bursty" and (
            self.burst_factor < 1.0 or self.burst_len_s <= 0
        ):
            raise ValueError(
                "bursty traffic needs burst_factor >= 1 and burst_len_s > 0"
            )


@dataclass(frozen=True)
class TraceItem:
    arrival_s: float
    req_id: str
    num_frames: int
    seed: int


@dataclass
class Trace:
    spec: TrafficSpec
    items: list[TraceItem] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.items)

    @property
    def duration_s(self) -> float:
        return self.items[-1].arrival_s if self.items else 0.0

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                {"spec": asdict(self.spec),
                 "items": [asdict(i) for i in self.items]},
                f, indent=2,
            )

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path, encoding="utf-8") as f:
            d = json.load(f)
        spec = d["spec"]
        spec["frame_choices"] = tuple(spec["frame_choices"])
        return cls(
            spec=TrafficSpec(**spec),
            items=[TraceItem(**i) for i in d["items"]],
        )


def make_trace(spec: TrafficSpec) -> Trace:
    """Deterministic trace from a spec (same spec -> identical trace)."""
    rng = np.random.default_rng(spec.seed)
    items: list[TraceItem] = []
    t = 0.0
    for i in range(spec.num_requests):
        if spec.kind == "poisson":
            rate = spec.rate_rps
        else:
            # two-state modulation keyed off the CURRENT arrival time, so
            # the process is stationary and replayable without extra state
            window = int(t / spec.burst_len_s)
            rate = (
                spec.rate_rps * spec.burst_factor if window % 2 == 0
                else spec.rate_rps / spec.burst_factor
            )
        t += float(rng.exponential(1.0 / rate))
        frames = int(spec.frame_choices[
            int(rng.integers(0, len(spec.frame_choices)))
        ])
        seed = int(rng.integers(0, 2**31 - 1))
        items.append(TraceItem(
            arrival_s=round(t, 6),
            req_id=f"{spec.kind}-{spec.seed}-{i:04d}",
            num_frames=frames,
            seed=seed,
        ))
    return Trace(spec=spec, items=items)


def synth_request_features(
    item: TraceItem, modalities: tuple[tuple[str, int], ...]
) -> tuple[dict[str, np.ndarray], dict[str, np.ndarray]]:
    """(feats, masks) for a trace item — unbatched ``[F, D]`` / ``[F]``
    arrays, regenerated bit-identically from the item's seed (traces carry
    no payloads; replay = regenerate)."""
    rng = np.random.default_rng(item.seed)
    F = item.num_frames
    feats = {
        name: rng.normal(size=(F, dim)).astype(np.float32)
        for name, dim in modalities
    }
    masks = {name: np.ones((F,), np.float32) for name, _ in modalities}
    return feats, masks
