"""COCOScorer-style wrapper: one call, full metric table.

Replaces the reference's eval wrapper that adapts {video_id: [captions]} dicts
into the vendored scorers (SURVEY.md §2 row 11). Used both for validation-time
CIDEr during training and for the final test.py-style metric table.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Optional, Sequence

import numpy as np

from cst_captioning_tpu.metrics.bleu import Bleu
from cst_captioning_tpu.metrics.cider import Cider, CiderD, CorpusDF
from cst_captioning_tpu.metrics.meteor import MeteorApprox
from cst_captioning_tpu.metrics.rouge import RougeL
from cst_captioning_tpu.metrics.tokenizer import ptb_tokenize


class CaptionScorer:
    """Scores {id: [caption strings]} hypotheses against reference pools.

    ``metrics`` selects which scorers run; validation-time callers typically
    ask only for CIDEr-D (cheap, the model-selection metric), the final eval
    runs everything (BASELINE.json config 5).
    """

    KNOWN = ("Bleu", "ROUGE_L", "METEOR_approx", "CIDEr", "CIDEr-D")

    def __init__(
        self,
        metrics: Sequence[str] = KNOWN,
        cider_df: "CorpusDF | str" = "corpus",
        pre_tokenized: bool = False,
        use_native: bool = True,
    ):
        unknown = [m for m in metrics if m not in self.KNOWN]
        if unknown:
            # a misspelled selector silently producing an empty/partial table
            # would fake a metric regression (or hide one) downstream
            raise ValueError(
                f"unknown metric selector(s) {unknown}; known: {list(self.KNOWN)}"
            )
        self.metrics = tuple(metrics)
        self.cider_df = cider_df
        self.pre_tokenized = pre_tokenized
        # CIDEr-D via the C++ merge-join kernel (metrics/native_cider.py):
        # the prepared reference pool is cached on the instance, so repeated
        # scoring of the same split — per-epoch validation, the eval bench —
        # pays the pool build once and ~µs/row after. Python oracle fallback
        # when the library is unavailable or the pool changes per call.
        self.use_native = use_native
        self._native_cider = None

    def _tok(self, table: Mapping[str, Sequence]) -> Dict[str, List[List[str]]]:
        if self.pre_tokenized:
            return {k: [list(c) for c in v] for k, v in table.items()}
        return {k: [ptb_tokenize(c) for c in v] for k, v in table.items()}

    def score(
        self,
        gts: Mapping[str, Sequence],
        res: Mapping[str, Sequence],
    ) -> Dict[str, float]:
        """Returns the metric table; per-id scores via score_with_details."""
        table, _ = self.score_with_details(gts, res)
        return table

    def score_with_details(
        self,
        gts: Mapping[str, Sequence],
        res: Mapping[str, Sequence],
    ):
        gts_t = self._tok(gts)
        res_t = self._tok(res)
        table: Dict[str, float] = {}
        per_id: Dict[str, np.ndarray] = {}
        if "Bleu" in self.metrics:
            corpus, per_order = Bleu(4).compute_score(gts_t, res_t)
            for n in range(4):
                table[f"Bleu_{n+1}"] = corpus[n]
                per_id[f"Bleu_{n+1}"] = per_order[n]
        if "ROUGE_L" in self.metrics:
            table["ROUGE_L"], per_id["ROUGE_L"] = RougeL().compute_score(gts_t, res_t)
        if "METEOR_approx" in self.metrics:
            table["METEOR_approx"], per_id["METEOR_approx"] = MeteorApprox().compute_score(
                gts_t, res_t
            )
        if "CIDEr" in self.metrics:
            table["CIDEr"], per_id["CIDEr"] = Cider(df="corpus").compute_score(
                gts_t, res_t
            )
        if "CIDEr-D" in self.metrics:
            scored = None
            if self.use_native:
                nc = self._native_cider
                if nc is None or not nc.covers(gts_t):
                    from cst_captioning_tpu.metrics.native_cider import NativeCiderD

                    nc = NativeCiderD.build(gts_t, self.cider_df)
                    self._native_cider = nc
                if nc is not None:
                    scored = nc.compute_score(res_t)  # None on id mismatch
            if scored is None:
                scored = CiderD(df=self.cider_df).compute_score(gts_t, res_t)
            table["CIDEr-D"], per_id["CIDEr-D"] = scored
        return table, per_id


def score_captions(
    gts: Mapping[str, Sequence],
    res: Mapping[str, Sequence],
    **kwargs,
) -> Dict[str, float]:
    """One-shot convenience wrapper."""
    return CaptionScorer(**kwargs).score(gts, res)
