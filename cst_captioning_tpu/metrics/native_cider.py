"""CIDEr-D through the C++ merge-join kernel, for the EVAL scorer.

The RL reward already scores CIDEr-D at ~6.5 µs/row via ``native/creward.cpp``
(flat-array merge joins, parity-pinned against the Python ``metrics.CiderD``
oracle in tests/test_rl.py). The eval path ran the pure-Python scorer — and
round-5's end-to-end eval measurement (`BENCH_EVAL_E2E.json`) put host metric
scoring at 71% of the whole config-5 pipeline, with CIDEr/CIDEr-D the largest
single shares. This adapter lets :class:`metrics.scorer.CaptionScorer` route
its CIDEr-D column through the same kernel:

- scoring stays in *string space*: reference and hypothesis words are
  interned into a private id table (ids start above the special tokens, so
  the kernel's PAD/BOS/EOS handling is untouched);
- the reference pools + df are loaded into the kernel ONCE per gts pool
  (the expensive part), so per-epoch validation re-scores at merge-join
  speed — the scorer caches one instance per pool;
- df="corpus" reproduces the Python scorer's eval-mode semantics exactly
  (df over the pools of the ids being scored); a :class:`CorpusDF` is
  forwarded as-is.

Falls back cleanly: :meth:`NativeCiderD.build` returns None when the native
library is unavailable, and :meth:`compute_score` refuses pools it wasn't
prepared for (the caller then uses the Python oracle). Parity with the
Python scorer is pinned in tests/test_metrics_cider.py.
"""

from __future__ import annotations

import ctypes
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from cst_captioning_tpu.config.config import (
    BOS_ID,
    EOS_ID,
    NUM_SPECIAL_TOKENS,
    PAD_ID,
)
from cst_captioning_tpu.metrics.cider import CorpusDF

_SIGMA = 6.0  # CIDEr-D length-penalty sigma (matches metrics.cider / kernel)


class NativeCiderD:
    """Kernel-backed ``CiderD.compute_score`` for one fixed reference pool."""

    def __init__(self, lib, gts: Dict[str, Sequence[Sequence[str]]],
                 df: "CorpusDF | str"):
        self._lib = lib
        self._gts = gts
        self._intern: dict[str, int] = {}

        ids = list(gts.keys())
        if isinstance(df, CorpusDF):
            table, ndoc = df.df, df.num_docs
        else:  # "corpus": df over the pools being scored (eval mode)
            df_obj = CorpusDF.from_refs([gts[i] for i in ids])
            table, ndoc = df_obj.df, df_obj.num_docs
        log_ndoc = math.log(max(float(ndoc), math.e))

        self._handle = lib.crw_create(
            ctypes.c_double(log_ndoc), ctypes.c_double(_SIGMA),
            PAD_ID, BOS_ID, EOS_ID,
        )
        gram_tokens: list[int] = []
        gram_lens: list[int] = []
        gram_counts: list[float] = []
        for gram, count in table.items():
            gram_tokens.extend(self._iid(w) for w in gram)
            gram_lens.append(len(gram))
            gram_counts.append(float(count))
        if gram_lens:
            gt = np.asarray(gram_tokens, np.int32)
            gl = np.asarray(gram_lens, np.int32)
            gc = np.asarray(gram_counts, np.float64)
            lib.crw_set_df(
                self._handle,
                gt.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                gl.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                gc.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                ctypes.c_int64(len(gram_lens)),
            )
        self._video_index: dict[str, int] = {}
        for vid, pool in gts.items():
            toks = np.asarray(
                [self._iid(w) for ref in pool for w in ref], np.int32
            )
            lens = np.asarray([len(ref) for ref in pool], np.int32)
            idx = lib.crw_add_video(
                self._handle,
                toks.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ctypes.c_int32(len(pool)),
            )
            self._video_index[vid] = int(idx)

    def _iid(self, word: str) -> int:
        i = self._intern.get(word)
        if i is None:
            i = len(self._intern) + NUM_SPECIAL_TOKENS
            self._intern[word] = i
        return i

    def __del__(self):
        if getattr(self, "_handle", None):
            try:
                self._lib.crw_free(self._handle)
            except Exception:
                pass

    @classmethod
    def build(cls, gts: Dict[str, Sequence[Sequence[str]]],
              df: "CorpusDF | str") -> Optional["NativeCiderD"]:
        """None when the native library can't be loaded/built."""
        from cst_captioning_tpu.native import load_creward

        lib = load_creward()
        if lib is None:
            return None
        return cls(lib, gts, df)

    def covers(self, gts: Dict[str, Sequence[Sequence[str]]]) -> bool:
        """True when this instance was prepared for exactly this pool."""
        return self._gts == gts

    def compute_score(
        self, res: Dict[str, Sequence[Sequence[str]]]
    ) -> Optional[Tuple[float, np.ndarray]]:
        """(corpus mean, per-id array) in res-key order — the Python
        ``CiderD.compute_score`` contract. None when ``res`` ids don't match
        the prepared pool (df="corpus" semantics depend on the id set; the
        caller falls back to the Python oracle).

        Precision contract: the kernel computes per-id scores in double
        but returns them through a float32 ABI (``creward.cpp``'s
        ``out[i] = (float)r``), so results differ from the float64 Python
        oracle by up to ~1e-7 relative (~1e-8 typical). Consumers
        comparing native and fallback paths — best-checkpoint selection
        ties included — must treat scores within that band as equal; the
        band is pinned by the parity tests in tests/test_metrics_cider.py.
        """
        ids = list(res.keys())
        if set(ids) != set(self._video_index):
            return None
        hyps: List[List[str]] = []
        for i in ids:
            assert len(res[i]) == 1, "one hypothesis per id"
            hyps.append(list(res[i][0]))
        width = max((len(h) for h in hyps), default=0) or 1
        rows = np.full((len(ids), width), PAD_ID, np.int32)
        for r, hyp in enumerate(hyps):
            rows[r, : len(hyp)] = [self._iid(w) for w in hyp]
        vidx = np.asarray([self._video_index[i] for i in ids], np.int32)
        out = np.zeros(len(ids), np.float32)
        self._lib.crw_score(
            self._handle,
            vidx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            np.ascontiguousarray(rows).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int32)
            ),
            ctypes.c_int64(len(ids)),
            ctypes.c_int32(width),
            ctypes.c_double(1.0),   # pure CIDEr-D
            ctypes.c_double(0.0),   # no BLEU term
            ctypes.c_int32(os.cpu_count() or 1),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        scores = out.astype(np.float64)
        return (float(np.mean(scores)) if len(scores) else 0.0), scores
