"""PTB-style caption tokenizer (pure Python).

The reference pipes captions through the Stanford CoreNLP ``PTBTokenizer`` jar
before scoring (coco-caption's ``PTBTokenizer`` wrapper; SURVEY.md §2 row 10).
On caption text — short, lowercase-ish English sentences — the jar's observable
behavior is: split on whitespace, separate punctuation into its own tokens,
lowercase, then DROP a fixed punctuation list from the token stream.

This module reproduces that contract with regexes. It is the single tokenizer
used everywhere (vocab build, df precompute, reward, eval), which keeps
CIDEr-D self-consistent even if it differs from the jar on exotic inputs
(SURVEY.md §7 "CIDEr-D parity" mitigation).
"""

from __future__ import annotations

import re
from typing import Dict, Iterable, List

# The punctuation list removed by coco-caption's PTBTokenizer wrapper after
# tokenization (its PUNCTUATIONS constant, reproduced by spec not by copy).
_PUNCTUATIONS = frozenset(
    {
        "''", "'", "``", "`", "(", ")", "[", "]", "{", "}",
        ".", "?", "!", ",", ":", "-", "--", "...", ";",
    }
)

# Contractions and possessives the PTB tokenizer splits off the preceding word.
_CONTRACTION_RE = re.compile(r"(?i)(n't|'s|'re|'ve|'ll|'d|'m)$")

# One token = a run of word chars (incl. digits, unicode letters), or a single
# non-space non-word char (punctuation split into its own token).
_TOKEN_RE = re.compile(r"[\w]+|[^\w\s]", re.UNICODE)


def _split_contractions(word: str) -> List[str]:
    """Split PTB contractions off a word: "don't" -> ["do", "n't"]."""
    m = _CONTRACTION_RE.search(word)
    if m and m.start() > 0:
        return [word[: m.start()], m.group(0)]
    return [word]


def ptb_tokenize(sentence: str, *, keep_punct: bool = False) -> List[str]:
    """Tokenize one caption PTB-style and lowercase it.

    Punctuation tokens are dropped (matching the reference eval pipeline)
    unless ``keep_punct`` is True.
    """
    raw = _TOKEN_RE.findall(sentence.replace("\n", " "))
    out: List[str] = []
    # Re-attach apostrophes to following letters so "don ' t" patterns from the
    # naive split become PTB contractions, then split them properly.
    merged: List[str] = []
    i = 0
    while i < len(raw):
        tok = raw[i]
        if (
            tok == "'"
            and merged
            and i + 1 < len(raw)
            and re.fullmatch(r"[A-Za-z]+", raw[i + 1])
        ):
            # word ' suffix  -> word'suffix, handled by contraction splitter
            merged[-1] = merged[-1] + "'" + raw[i + 1]
            i += 2
            continue
        merged.append(tok)
        i += 1
    for tok in merged:
        for piece in _split_contractions(tok):
            piece = piece.lower()
            if not keep_punct and piece in _PUNCTUATIONS:
                continue
            if piece:
                out.append(piece)
    return out


def ptb_tokenize_corpus(
    corpus: Dict[str, Iterable[str]], *, keep_punct: bool = False
) -> Dict[str, List[List[str]]]:
    """Tokenize a {video_id: [caption, ...]} mapping.

    Mirrors the reference's PTBTokenizer.tokenize() batch interface, returning
    token lists rather than joined strings (callers join if they need strings).
    """
    return {
        vid: [ptb_tokenize(c, keep_punct=keep_punct) for c in caps]
        for vid, caps in corpus.items()
    }
