"""METEOR (approximate, pure Python) — replaces the METEOR 1.5 jar.

The reference shells out to the METEOR 1.5 jar over a stdin/stdout line
protocol (SURVEY.md §2 "native components" table). No JVM exists here, so this
is an explicitly-labeled approximation implementing the METEOR scoring formula
(Denkowski & Lavie 2014) with the *exact* and *stem* matcher stages only —
synonym/paraphrase stages need WordNet/paraphrase tables that are unavailable
offline. Results are reported as ``METEOR_approx`` so they are never confused
with jar numbers. METEOR is never used as an RL reward in the reference's
recipes, only in final eval tables, so the approximation does not affect
training parity.

Parameters are METEOR 1.5's English defaults: alpha=0.85, beta=0.2, gamma=0.6.
"""

from __future__ import annotations

import re
from typing import Dict, List, Sequence, Tuple

import numpy as np

_VOWELS = "aeiou"


def _porter_stem(word: str) -> str:
    """Compact Porter stemmer (1980 algorithm, steps 1a/1b/1c/2-5 abridged).

    Adequate for METEOR's stem-stage matching on caption vocabulary; not a
    full linguistic stemmer.
    """
    w = word
    if len(w) <= 2:
        return w
    # step 1a
    if w.endswith("sses"):
        w = w[:-2]
    elif w.endswith("ies"):
        w = w[:-2]
    elif w.endswith("ss"):
        pass
    elif w.endswith("s"):
        w = w[:-1]
    # step 1b (simplified): -ed / -ing with a vowel in the stem
    for suf in ("ing", "ed"):
        if w.endswith(suf):
            stem = w[: -len(suf)]
            if any(c in _VOWELS for c in stem):
                w = stem
                if w.endswith(("at", "bl", "iz")):
                    w += "e"
                elif len(w) >= 2 and w[-1] == w[-2] and w[-1] not in "lsz":
                    w = w[:-1]
            break
    # step 1c
    if w.endswith("y") and any(c in _VOWELS for c in w[:-1]):
        w = w[:-1] + "i"
    return w


def _align(hyp: Sequence[str], ref: Sequence[str]) -> Tuple[int, int]:
    """Greedy two-stage alignment: exact first, then stem matches.

    Returns (num matches, num chunks). Chunks = maximal runs of matched hyp
    positions mapped to contiguous increasing ref positions.
    """
    ref_used = [False] * len(ref)
    match_to: List[int] = [-1] * len(hyp)  # hyp idx -> ref idx
    # stage 1: exact
    for i, h in enumerate(hyp):
        for j, r in enumerate(ref):
            if not ref_used[j] and h == r:
                ref_used[j] = True
                match_to[i] = j
                break
    # stage 2: stem
    ref_stems = [_porter_stem(r) for r in ref]
    for i, h in enumerate(hyp):
        if match_to[i] >= 0:
            continue
        hs = _porter_stem(h)
        for j in range(len(ref)):
            if not ref_used[j] and hs == ref_stems[j]:
                ref_used[j] = True
                match_to[i] = j
                break
    matches = sum(1 for m in match_to if m >= 0)
    # chunk counting over the matched subsequence
    chunks = 0
    prev_ref = None
    for i in range(len(hyp)):
        j = match_to[i]
        if j < 0:
            prev_ref = None
            continue
        if prev_ref is None or j != prev_ref + 1:
            chunks += 1
        prev_ref = j
    return matches, chunks


class MeteorApprox:
    method = "METEOR_approx"

    def __init__(self, alpha: float = 0.85, beta: float = 0.2, gamma: float = 0.6):
        self.alpha, self.beta, self.gamma = alpha, beta, gamma

    def sentence_score(
        self, hyp: Sequence[str], refs: Sequence[Sequence[str]]
    ) -> float:
        """Max METEOR over the reference pool (the jar's multi-ref behavior)."""
        best = 0.0
        for ref in refs:
            if not len(hyp) or not len(ref):
                continue
            m, chunks = _align(hyp, ref)
            if m == 0:
                continue
            p = m / len(hyp)
            r = m / len(ref)
            f = p * r / (self.alpha * p + (1 - self.alpha) * r)
            frag = chunks / m
            penalty = self.gamma * (frag**3)  # beta exponent = 3 in 1.5
            best = max(best, f * (1 - penalty))
        return best

    def compute_score(
        self,
        gts: Dict[str, Sequence[Sequence[str]]],
        res: Dict[str, Sequence[Sequence[str]]],
    ) -> Tuple[float, np.ndarray]:
        ids = list(res.keys())
        scores = np.array([self.sentence_score(res[i][0], gts[i]) for i in ids])
        return float(np.mean(scores)) if len(scores) else 0.0, scores
