"""CIDEr and CIDEr-D scorers with pluggable document frequency.

Reimplements the semantics of the reference's vendored ``cider/`` package
(SURVEY.md §2 row 9) from the CIDEr paper (Vedantam et al., CVPR 2015) and the
CST paper's usage (arXiv:1712.09532):

- tf-idf vectors over n-grams n=1..4; idf from a document-frequency table,
- CIDEr  : plain cosine similarity averaged over refs and n, ×10,
- CIDEr-D: hypothesis counts clipped to the reference's, multiplied by a
  gaussian length penalty exp(-(l_h - l_r)^2 / (2 σ^2)), σ = 6, ×10.

Document frequency is pluggable exactly like the reference's ``CiderD(df=...)``:
``df="corpus"`` computes df from the refs being scored (eval mode); a
``CorpusDF`` precomputed over the *train* split is what the RL reward uses —
both for speed and to match the paper's numbers (SURVEY.md §2 row 3).
"""

from __future__ import annotations

import math
import pickle
from collections import Counter, defaultdict
from typing import Dict, List, Sequence, Tuple

import numpy as np

from cst_captioning_tpu.metrics.ngram import NGram, precook


class CorpusDF:
    """Precomputed document frequency over a caption corpus.

    ``df[ngram]`` = number of *videos* (documents) in whose reference pool the
    n-gram appears at least once; ``num_docs`` = number of videos. This matches
    the reference's train-split df pickle used by the RL reward.
    """

    def __init__(self, df: Dict[NGram, float], num_docs: int):
        self.df = df
        self.num_docs = num_docs

    @classmethod
    def from_refs(cls, refs_per_doc: Sequence[Sequence[Sequence[str]]],
                  max_n: int = 4) -> "CorpusDF":
        """Build df from an iterable of per-video reference token lists."""
        df: Dict[NGram, float] = defaultdict(float)
        ndoc = 0
        for refs in refs_per_doc:
            ndoc += 1
            seen = set()
            for ref in refs:
                seen.update(precook(ref, max_n).keys())
            for g in seen:
                df[g] += 1.0
        return cls(dict(df), ndoc)

    def save(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump({"df": self.df, "num_docs": self.num_docs}, f)

    @classmethod
    def load(cls, path: str) -> "CorpusDF":
        with open(path, "rb") as f:
            d = pickle.load(f)
        return cls(d["df"], d["num_docs"])


def _counts_to_vec(
    counts: Counter, df: Dict[NGram, float], log_ndoc: float, max_n: int
) -> Tuple[List[Dict[NGram, float]], np.ndarray, int]:
    """tf-idf vector per n-gram order, its L2 norms, and the unigram length."""
    vec: List[Dict[NGram, float]] = [dict() for _ in range(max_n)]
    norm = np.zeros(max_n)
    length = 0
    for ngram, tf in counts.items():
        n_idx = len(ngram) - 1
        idf = log_ndoc - math.log(max(1.0, df.get(ngram, 0.0)))
        w = float(tf) * idf
        vec[n_idx][ngram] = w
        norm[n_idx] += w * w
        if n_idx == 0:
            length += tf
    return vec, np.sqrt(norm), length


class _CiderBase:
    """Shared machinery for CIDEr and CIDEr-D."""

    def __init__(self, df: "CorpusDF | str" = "corpus", max_n: int = 4,
                 sigma: float = 6.0):
        self.max_n = max_n
        self.sigma = sigma
        self._df_source = df

    # -- subclass hooks -------------------------------------------------------
    def _pair_sim(self, hvec, rvec, hnorm, rnorm, hlen, rlen) -> np.ndarray:
        raise NotImplementedError

    # -- public API (compute_score mirrors the reference scorers) -------------
    def compute_score(
        self,
        gts: Dict[str, Sequence[Sequence[str]]],
        res: Dict[str, Sequence[Sequence[str]]],
    ) -> Tuple[float, np.ndarray]:
        """Score hypotheses against reference pools.

        gts: {id: [ref tokens, ...]}; res: {id: [hyp tokens]} (one hyp per id,
        as in the reference's scorers). Returns (corpus mean, per-id array) —
        the per-id array is the RL reward vector.
        """
        ids = list(res.keys())
        assert all(i in gts for i in ids), "every hypothesis needs references"

        if isinstance(self._df_source, CorpusDF):
            df, ndoc = self._df_source.df, self._df_source.num_docs
        else:  # "corpus": df over the refs being scored, like eval-mode cider
            df_obj = CorpusDF.from_refs([gts[i] for i in ids], self.max_n)
            df, ndoc = df_obj.df, df_obj.num_docs
        # The reference clips num_docs to >= e so idf stays >= 0 on tiny sets.
        log_ndoc = math.log(max(float(ndoc), math.e))

        scores = np.zeros(len(ids))
        for k, i in enumerate(ids):
            hyps = res[i]
            assert len(hyps) == 1, "one hypothesis per id"
            hvec, hnorm, hlen = _counts_to_vec(
                precook(hyps[0], self.max_n), df, log_ndoc, self.max_n
            )
            per_ref = np.zeros(self.max_n)
            for ref in gts[i]:
                rvec, rnorm, rlen = _counts_to_vec(
                    precook(ref, self.max_n), df, log_ndoc, self.max_n
                )
                per_ref += self._pair_sim(hvec, rvec, hnorm, rnorm, hlen, rlen)
            per_ref /= max(1, len(gts[i]))
            scores[k] = float(np.mean(per_ref)) * 10.0
        return float(np.mean(scores)) if len(scores) else 0.0, scores


class Cider(_CiderBase):
    """Plain CIDEr: average tf-idf cosine over n-gram orders."""

    method = "CIDEr"

    def _pair_sim(self, hvec, rvec, hnorm, rnorm, hlen, rlen) -> np.ndarray:
        val = np.zeros(self.max_n)
        for n_idx in range(self.max_n):
            dot = 0.0
            hv, rv = hvec[n_idx], rvec[n_idx]
            small = hv if len(hv) <= len(rv) else rv
            other = rv if small is hv else hv
            for g, w in small.items():
                ow = other.get(g)
                if ow is not None:
                    dot += w * ow
            denom = hnorm[n_idx] * rnorm[n_idx]
            if denom > 0:
                val[n_idx] = dot / denom
        return val


class CiderD(_CiderBase):
    """CIDEr-D: clipped counts + gaussian length penalty (the RL reward)."""

    method = "CIDEr-D"

    def _pair_sim(self, hvec, rvec, hnorm, rnorm, hlen, rlen) -> np.ndarray:
        val = np.zeros(self.max_n)
        for n_idx in range(self.max_n):
            dot = 0.0
            for g, hw in hvec[n_idx].items():
                rw = rvec[n_idx].get(g)
                if rw is not None:
                    # clip hypothesis tf-idf weight to the reference's
                    dot += min(hw, rw) * rw
            denom = hnorm[n_idx] * rnorm[n_idx]
            if denom > 0:
                val[n_idx] = dot / denom
        delta = float(hlen - rlen)
        val *= math.exp(-(delta**2) / (2.0 * self.sigma**2))
        return val
