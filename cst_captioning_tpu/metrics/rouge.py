"""ROUGE-L — replaces coco-caption's Rouge (SURVEY.md §2 row 10).

LCS-based F-measure with beta = 1.2, taking the max precision and max recall
over the reference pool per instance (the coco-caption convention).
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np


def _lcs_len(a: Sequence[str], b: Sequence[str]) -> int:
    """Classic O(len(a)*len(b)) LCS length with a rolling row."""
    if not a or not b:
        return 0
    prev = [0] * (len(b) + 1)
    for x in a:
        cur = [0] * (len(b) + 1)
        for j, y in enumerate(b, 1):
            cur[j] = prev[j - 1] + 1 if x == y else max(prev[j], cur[j - 1])
        prev = cur
    return prev[-1]


class RougeL:
    method = "ROUGE_L"

    def __init__(self, beta: float = 1.2):
        self.beta = beta

    def sentence_score(
        self, hyp: Sequence[str], refs: Sequence[Sequence[str]]
    ) -> float:
        if not len(hyp):
            return 0.0
        precs: List[float] = []
        recs: List[float] = []
        for ref in refs:
            lcs = _lcs_len(hyp, ref)
            precs.append(lcs / len(hyp))
            recs.append(lcs / len(ref) if len(ref) else 0.0)
        p, r = max(precs), max(recs)
        if p == 0.0 or r == 0.0:
            return 0.0
        b2 = self.beta**2
        return (1 + b2) * p * r / (r + b2 * p)

    def compute_score(
        self,
        gts: Dict[str, Sequence[Sequence[str]]],
        res: Dict[str, Sequence[Sequence[str]]],
    ) -> Tuple[float, np.ndarray]:
        ids = list(res.keys())
        scores = np.array([self.sentence_score(res[i][0], gts[i]) for i in ids])
        return float(np.mean(scores)) if len(scores) else 0.0, scores
