"""N-gram counting shared by BLEU and CIDEr (reference: cider/'s precook).

Hot host path during the RL phase: every sampled caption is cooked per step.
A C++ fast path lives in ``cst_captioning_tpu.native``; this module is the
always-available pure-Python implementation and the correctness oracle.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Sequence, Tuple

NGram = Tuple[str, ...]


def ngram_counts(tokens: Sequence[str], n: int) -> Counter:
    """Counter of n-grams of a single order ``n``."""
    return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))


def precook(tokens: Sequence[str], max_n: int = 4) -> Counter:
    """Counter over all n-grams of orders 1..max_n (the cider 'precook')."""
    counts: Counter = Counter()
    toks = tuple(tokens)
    L = len(toks)
    for n in range(1, max_n + 1):
        for i in range(L - n + 1):
            counts[toks[i : i + n]] += 1
    return counts


def cook_refs(refs: Sequence[Sequence[str]], max_n: int = 4) -> List[Counter]:
    """Precook each reference caption of one video."""
    return [precook(r, max_n) for r in refs]
