"""Caption metrics: tokenizer, BLEU, ROUGE-L, CIDEr, CIDEr-D, METEOR (approx).

Pure Python/numpy replacements for the reference's vendored ``cider/`` and
``coco-caption/`` packages (SURVEY.md §2 rows 9-11). No JVM: the PTBTokenizer,
METEOR and SPICE jars of the reference are replaced by a regex PTB-style
tokenizer, an exact+stem METEOR variant (clearly labeled approximate), and
SPICE is out of scope (never used as a reward in the reference's recipes).

CIDEr-D is the RL reward (BASELINE.json configs 3-4) and the model-selection
metric, so it supports a precomputed corpus document-frequency table exactly
like the reference's ``CiderD(df='...')``.
"""

from cst_captioning_tpu.metrics.tokenizer import ptb_tokenize, ptb_tokenize_corpus
from cst_captioning_tpu.metrics.ngram import ngram_counts, precook
from cst_captioning_tpu.metrics.bleu import Bleu
from cst_captioning_tpu.metrics.rouge import RougeL
from cst_captioning_tpu.metrics.cider import Cider, CiderD, CorpusDF
from cst_captioning_tpu.metrics.meteor import MeteorApprox
from cst_captioning_tpu.metrics.scorer import CaptionScorer, score_captions

__all__ = [
    "ptb_tokenize",
    "ptb_tokenize_corpus",
    "ngram_counts",
    "precook",
    "Bleu",
    "RougeL",
    "Cider",
    "CiderD",
    "CorpusDF",
    "MeteorApprox",
    "CaptionScorer",
    "score_captions",
]
