"""BLEU-1..4 (corpus and per-sentence) — replaces coco-caption's Bleu.

Semantics per Papineni et al. 2002 with the coco-caption conventions the
reference relies on (SURVEY.md §2 row 10): clipped n-gram precision against
the max count over references, "closest" reference length for the brevity
penalty, geometric mean over orders. Per-sentence scores (used when BLEU4 is
mixed into the consensus reward, BASELINE.json config 4) use +1 smoothing on
orders > 1 so single short captions don't collapse to 0.
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Sequence, Tuple

import numpy as np

from cst_captioning_tpu.metrics.ngram import ngram_counts


def _closest_ref_len(hyp_len: int, ref_lens: Sequence[int]) -> int:
    return min(ref_lens, key=lambda rl: (abs(rl - hyp_len), rl))


def _clipped_matches(
    hyp: Sequence[str], refs: Sequence[Sequence[str]], n: int
) -> Tuple[int, int]:
    """(clipped match count, total hyp n-gram count) for one order."""
    hyp_counts = ngram_counts(hyp, n)
    total = sum(hyp_counts.values())
    if not total:
        return 0, 0
    max_ref: Counter = Counter()
    for ref in refs:
        for g, c in ngram_counts(ref, n).items():
            if c > max_ref[g]:
                max_ref[g] = c
    matched = sum(min(c, max_ref[g]) for g, c in hyp_counts.items())
    return matched, total


class Bleu:
    """BLEU with up to ``max_n`` orders; compute_score mirrors the reference."""

    def __init__(self, max_n: int = 4):
        self.max_n = max_n

    @property
    def method(self) -> List[str]:
        return [f"Bleu_{n}" for n in range(1, self.max_n + 1)]

    def sentence_bleu(
        self, hyp: Sequence[str], refs: Sequence[Sequence[str]]
    ) -> np.ndarray:
        """Smoothed per-sentence BLEU-1..max_n (the reward-side entry point)."""
        scores = np.zeros(self.max_n)
        if not len(hyp):
            return scores
        bp = self._brevity(len(hyp), [len(r) for r in refs])
        log_p = 0.0
        for n in range(1, self.max_n + 1):
            matched, total = _clipped_matches(hyp, refs, n)
            if n == 1:
                p = matched / total if total else 0.0
            else:  # +1 smoothing beyond unigrams
                p = (matched + 1.0) / (total + 1.0) if total else 0.0
            if p == 0.0:
                break  # zero precision zeroes this and all higher orders
            log_p += np.log(p)
            scores[n - 1] = bp * np.exp(log_p / n)
        return scores

    @staticmethod
    def _brevity(hyp_len: int, ref_lens: Sequence[int]) -> float:
        r = _closest_ref_len(hyp_len, ref_lens)
        return 1.0 if hyp_len >= r else float(np.exp(1.0 - r / hyp_len))

    def compute_score(
        self,
        gts: Dict[str, Sequence[Sequence[str]]],
        res: Dict[str, Sequence[Sequence[str]]],
    ) -> Tuple[List[float], List[np.ndarray]]:
        """Corpus BLEU list + per-sentence score arrays, coco-caption style."""
        ids = list(res.keys())
        matched = np.zeros(self.max_n)
        total = np.zeros(self.max_n)
        hyp_len_sum = 0
        ref_len_sum = 0
        per_sentence: List[np.ndarray] = []
        for i in ids:
            hyp = res[i][0]
            refs = gts[i]
            hyp_len_sum += len(hyp)
            ref_len_sum += _closest_ref_len(len(hyp), [len(r) for r in refs])
            for n in range(1, self.max_n + 1):
                m, t = _clipped_matches(hyp, refs, n)
                matched[n - 1] += m
                total[n - 1] += t
            per_sentence.append(self.sentence_bleu(hyp, refs))
        bp = (
            1.0
            if hyp_len_sum >= ref_len_sum
            else float(np.exp(1.0 - ref_len_sum / max(1, hyp_len_sum)))
        )
        corpus: List[float] = []
        log_p = 0.0
        dead = False
        for n in range(self.max_n):
            p = matched[n] / total[n] if total[n] else 0.0
            if p == 0.0:
                dead = True
            if dead:
                corpus.append(0.0)
            else:
                log_p += np.log(p)
                corpus.append(float(bp * np.exp(log_p / (n + 1))))
        # transpose per-sentence to a list of arrays per order, like coco bleu
        per_order = [np.array([s[n] for s in per_sentence]) for n in range(self.max_n)]
        return corpus, per_order
