"""Version-compatibility shims for the installed JAX.

The codebase is written against the current jax API surface — ``jax.shard_map``
with the varying-manual-axes (vma) type checker, ``jax.lax.pcast``, and
``jax.typeof`` — but must also run on 0.4.x installs where shard_map still
lives in ``jax.experimental`` and the vma type system does not exist. Every
call site imports the one spelling below; the shim resolves to the native API
when present and to the closest 0.4.x equivalent otherwise.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    # jax >= 0.6: native shard_map, vma checker on by default
    shard_map = jax.shard_map
    pcast = jax.lax.pcast

    def vma_of(x) -> frozenset:
        """Mesh axes ``x`` is typed as varying over (empty when untyped)."""
        return getattr(jax.typeof(x), "vma", frozenset())

else:
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs):
        # check_rep=False: the call sites annotate for the vma checker
        # (pcast device-invariant values to varying), which the 0.4.x
        # replication checker predates — run unchecked rather than
        # half-checked against the older, stricter-in-the-wrong-places rules
        return _shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )

    def pcast(x, axis_name, *, to):
        # no vma type system: values carry no varying-axes type, the cast
        # is a no-op (the collectives it guards still run identically)
        del axis_name, to
        return x

    def vma_of(x) -> frozenset:
        del x
        return frozenset()


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()``, which 0.4.x doesn't export —
    there, the coordination client on the private global state is the
    initialized marker."""
    if hasattr(jax.distributed, "is_initialized"):
        return bool(jax.distributed.is_initialized())
    from jax._src import distributed as _distributed

    return getattr(_distributed.global_state, "client", None) is not None
