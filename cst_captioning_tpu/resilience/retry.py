"""Budgeted, jittered exponential backoff for host-side fallible I/O.

Applied to checkpoint writes (shared-filesystem hiccups under preemption
storms) and the RL reward scorer (a remote service in production deployments;
in-process numpy here, but the call site is the same). Deterministic: the
jitter stream is seeded by the policy, so a retried run is reproducible.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass
from typing import Any, Callable

from cst_captioning_tpu.obs import metrics as obs_metrics


@dataclass(frozen=True)
class RetryPolicy:
    """``max_attempts`` total tries; sleeps grow ``base_delay * factor**i``
    capped at ``max_delay``, each scaled by a ±``jitter`` fraction; the sum
    of sleeps never exceeds ``budget`` seconds (a preempting host has a grace
    window — better to fail over to the next checkpoint than to burn it
    retrying)."""

    max_attempts: int = 4
    base_delay: float = 0.05
    max_delay: float = 2.0
    factor: float = 2.0
    jitter: float = 0.5
    budget: float = 30.0
    retry_on: tuple = (OSError,)
    seed: int = 0

    def delays(self) -> "list[float]":
        """The full (pre-budget) backoff schedule, for logging/tests."""
        rng = random.Random(self.seed)
        out = []
        for i in range(self.max_attempts - 1):
            d = min(self.max_delay, self.base_delay * self.factor ** i)
            out.append(d * (1.0 + self.jitter * (2.0 * rng.random() - 1.0)))
        return out


def retry_call(
    fn: Callable[..., Any],
    *args: Any,
    policy: RetryPolicy = RetryPolicy(),
    on_retry: Callable[[dict], None] | None = None,
    sleep: Callable[[float], None] = time.sleep,
    **kwargs: Any,
) -> Any:
    """Call ``fn`` with retries per ``policy``.

    Only ``policy.retry_on`` exceptions are retried — anything else (and a
    :class:`~cst_captioning_tpu.resilience.chaos.SimulatedKill`, which is a
    ``BaseException``) propagates immediately. ``on_retry`` receives a
    structured dict per retry, ready for ``EventLogger.log(**info)``.
    """
    delays = policy.delays()
    slept = 0.0
    for attempt in range(policy.max_attempts):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            # attempts vs give-ups feed the run report's resilience summary
            # (obs satellite: retries were previously visible only to the
            # caller's on_retry log)
            if attempt >= len(delays):
                obs_metrics.counter("resilience.retry.give_up").inc()
                raise
            delay = delays[attempt]
            if slept + delay > policy.budget:
                obs_metrics.counter("resilience.retry.give_up").inc()
                raise
            obs_metrics.counter("resilience.retry.attempt").inc()
            if on_retry is not None:
                on_retry({
                    "attempt": attempt + 1,
                    "delay": round(delay, 4),
                    "error": type(e).__name__,
                    "detail": str(e),
                })
            sleep(delay)
            slept += delay
    raise AssertionError("unreachable")  # pragma: no cover
