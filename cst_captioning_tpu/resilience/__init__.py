"""Fault-tolerance layer: durable checkpoints, divergence sentinel, retry,
preemption handling, and a deterministic chaos (fault-injection) harness.

The ROADMAP north-star is a production-scale system; at that scale TPU
preemptions, NaN batches, and flaky filesystem/reward-service I/O are routine
events, not exceptional ones (Podracer arXiv:2104.06272 and RLAX
arXiv:2512.06392 both treat them as first-class design inputs). This package
makes each of them a *tested* code path:

- :mod:`durable`  — fsync'd atomic checkpoint writes + a sidecar manifest of
  per-file checksums, verified on load (a truncated ``state.msgpack`` is
  detected, not deserialized into garbage).
- :mod:`sentinel` — NaN/inf + loss-spike detection over the step loops with a
  configurable policy: ``skip_batch`` (the device-side guard already excluded
  the update), ``rollback`` (restore last-good checkpoint, re-randomize the
  data order), or ``abort``.
- :mod:`guard`    — the on-device finite-update guard shared by every jitted
  step (`jnp.where(ok, new, old)` over params/opt_state/step).
- :mod:`retry`    — budgeted, jittered exponential backoff for host-side
  fallible I/O (checkpoint writes, the RL reward scorer).
- :mod:`preempt`  — SIGTERM handling: set a flag, let the step loop save a
  mid-epoch checkpoint recording the exact batch index, and exit cleanly.
- :mod:`health`   — elastic multi-host layer: per-host heartbeats + a
  peer-loss watchdog (timeout/backoff), survivor rendezvous for the
  degraded-mesh continuation, the validated rejoin path that grows the
  mesh back when a lost host recovers, and the DCN-stall span around
  cross-host collectives.
- :mod:`chaos`    — seeded fault plans (NaN-poisoned batches, kill-mid-save,
  transient I/O errors, slow/failing reward calls, preemption signals,
  partial preemption of one host, host rejoin after recovery — including
  the flaky rejoiner that dies mid-rendezvous — slow/partial H2D
  transfers, wedged prefetch threads, ENOSPC mid-rotation) driven by the
  tests through named injection points compiled into the hot paths.
"""

from cst_captioning_tpu.resilience.chaos import (
    Fault,
    FaultPlan,
    PartialTransferError,
    SimulatedKill,
)
from cst_captioning_tpu.resilience.health import (
    HealthMonitor,
    HostRejoin,
    PeerLost,
    RejoinRefused,
    RendezvousTimeout,
    attempt_rejoin,
    collective_span,
    rendezvous,
    simulate_rejoin,
)
from cst_captioning_tpu.resilience.durable import (
    CorruptCheckpointError,
    verify_manifest,
    write_manifest,
)
from cst_captioning_tpu.resilience.guard import guarded_apply_gradients
from cst_captioning_tpu.resilience.preempt import Preempted, PreemptionHandler
from cst_captioning_tpu.resilience.retry import RetryPolicy, retry_call
from cst_captioning_tpu.resilience.sentinel import (
    DivergenceSentinel,
    RollbackRequested,
    TrainingDiverged,
)

__all__ = [
    "CorruptCheckpointError",
    "DivergenceSentinel",
    "Fault",
    "FaultPlan",
    "HealthMonitor",
    "HostRejoin",
    "PartialTransferError",
    "PeerLost",
    "Preempted",
    "PreemptionHandler",
    "RejoinRefused",
    "RendezvousTimeout",
    "RetryPolicy",
    "RollbackRequested",
    "SimulatedKill",
    "TrainingDiverged",
    "attempt_rejoin",
    "collective_span",
    "guarded_apply_gradients",
    "rendezvous",
    "retry_call",
    "simulate_rejoin",
    "verify_manifest",
    "write_manifest",
]
