"""On-device finite-update guard for jitted train steps.

``jax_debug_nans`` is the debugging tool; this is the production one: when a
step's loss or grad-norm is NaN/inf, the parameter/optimizer/step update is
suppressed *inside the XLA program* (``jnp.where`` select against the old
state) — no host sync, no poisoned Adam moments, and the step counter does
not advance, so the batch is cleanly excluded. The ``nonfinite`` metric
(device scalar, 0/1) lets the host-side
:class:`~cst_captioning_tpu.resilience.sentinel.DivergenceSentinel` log and
apply policy on its own (amortized) readback schedule.

When every input is finite the select picks the new leaves bit-for-bit, so a
guarded healthy run is numerically identical to an unguarded one.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def guarded_apply_gradients(state, grads, loss, grad_norm):
    """-> (new_state, nonfinite) with the update suppressed when non-finite.

    ``loss`` and ``grad_norm`` jointly witness divergence: any NaN/inf in
    any gradient leaf makes the global norm non-finite, so per-leaf isfinite
    scans are unnecessary. Only ``step``/``params``/``opt_state`` are
    selected (the PRNG key and static fields are untouched by the update, and
    ``where`` over typed key dtypes is not portable to the 0.4.x floor).
    """
    ok = jnp.isfinite(loss) & jnp.isfinite(grad_norm)
    new = state.apply_gradients(grads)

    def sel(n, o):
        return jnp.where(ok, n, o)

    guarded = new.replace(
        step=sel(new.step, state.step),
        params=jax.tree.map(sel, new.params, state.params),
        opt_state=jax.tree.map(sel, new.opt_state, state.opt_state),
    )
    return guarded, 1.0 - ok.astype(jnp.float32)
