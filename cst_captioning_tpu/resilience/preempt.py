"""SIGTERM/preemption handling for the train loops.

TPU preemption arrives as SIGTERM with a short grace window. The handler
only sets a flag (signal handlers must not run arbitrary Python against
half-updated trainer state); the step loop checks the flag once per step,
saves a mid-epoch checkpoint recording the exact batch index, and raises
:class:`Preempted` so drivers exit nonzero and the next run resumes the
remainder of the epoch.

The save itself runs in DRAIN-AWARE order (Trainer._preempt_save /
SCSTTrainer.train_epoch): the pipelined RL loop first applies its in-flight
updates in schedule order, then decodes the seam batch at its exact
pipeline position and persists the tokens (``seam.npz``) inside the same
atomic checkpoint swap — so a pipelined mid-epoch resume replays the seam
instead of re-decoding it against params one update fresher, and both
``rl.pipelined`` modes resume bit-identically. Partial preemption (one
host of a multi-host cluster, detected by :mod:`resilience.health`) drains
through the same path before :class:`~.health.PeerLost` unwinds.
"""

from __future__ import annotations

import signal
import threading
from typing import Iterable


class Preempted(RuntimeError):
    """Raised by a train loop after a preemption-triggered save completed."""


class PreemptionHandler:
    """Installable SIGTERM latch; context manager restores prior handlers.

    Installation is best-effort: ``signal.signal`` only works in the main
    thread, so a Trainer driven from a worker thread simply runs without
    preemption handling (``installed`` is False) instead of crashing.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,)):
        self.signals = tuple(signals)
        self.installed = False
        self._requested = threading.Event()
        self._prev: dict[int, object] = {}

    @property
    def requested(self) -> bool:
        return self._requested.is_set()

    def _on_signal(self, signum, frame) -> None:
        self._requested.set()
        prev = self._prev.get(signum)
        # chain a pre-existing Python-level handler (e.g. an outer harness's
        # own latch); never re-invoke SIG_DFL/SIG_IGN — default SIGTERM
        # disposition would kill the process before the save runs
        if callable(prev) and prev not in (signal.SIG_DFL, signal.SIG_IGN):
            prev(signum, frame)

    def install(self) -> "PreemptionHandler":
        if self.installed:
            return self
        try:
            for s in self.signals:
                self._prev[s] = signal.signal(s, self._on_signal)
            self.installed = True
        except ValueError:  # not the main thread
            self._prev.clear()
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()
        self.installed = False

    def __enter__(self) -> "PreemptionHandler":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
