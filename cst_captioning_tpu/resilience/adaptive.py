"""Anomaly-adaptive sentinel thresholds: the detector's moments become the
spike bound.

The divergence sentinel's fixed policy — trip when
``loss > spike_factor * median(recent)`` — needs a factor loose enough to
survive healthy noise, which makes it blind to a *slow ramp*: a loss that
creeps up a few percent per step drags the median along with it, so
``loss / median`` never reaches the factor and the run burns hours before
the nonfinite check finally fires. The anomaly detector already maintains
exactly the statistic that catches this: an EWMA mean/variance of the loss
stream whose memory (``~2/alpha`` steps) is long enough that early ramp
steps sit many EW-standard-deviations above the healthy-phase mean *before*
the moments re-converge.

:class:`AdaptiveThresholds` maps those moments onto the sentinel's bound::

    bound = clamp(mean + z * std,  spike_factor_min * median,
                                   spike_factor     * median)

- The **upper clamp** keeps adaptive mode at least as sensitive as the fixed
  factor (everything the fixed policy would trip, adaptive trips too).
- The **lower clamp** keeps a freakishly-quiet healthy phase (tiny variance)
  from turning ordinary noise into trips.
- **Warmup gating**: until the EWMA has ``warmup`` observations and nonzero
  variance the fixed bound is used verbatim — cold-start moments are noise.

When an :class:`obs.anomaly.AnomalyDetector` is live, its ``loss``
:class:`~obs.anomaly.Ewma` is shared (the detector updates it on the flight
recorder's flush cadence; this class only *reads*). Without a detector the
instance owns a private ``Ewma`` and folds in every loss the sentinel
flushes. Either way all arithmetic runs host-side on values the sentinel's
ONE batched ``device_get`` already produced — the hot path stays zero-sync
(GL001-clean), and ``spike_mode="fixed"`` never constructs this class at
all, so the default policy is bit-identical to before.

Pure stdlib + :mod:`obs.anomaly` (itself stdlib): importable jax-free.
"""

from __future__ import annotations

import math

from cst_captioning_tpu.obs.anomaly import Ewma


class AdaptiveThresholds:
    """EWMA-moment spike bound for :class:`resilience.sentinel.DivergenceSentinel`.

    Parameters
    ----------
    factor_max:
        The config's ``spike_factor`` — ceiling clamp, so adaptive mode is
        never *looser* than the fixed policy it replaces.
    factor_min:
        The config's ``spike_factor_min`` — floor clamp against noise trips
        when the healthy variance is near zero.
    z:
        How many EW-standard-deviations above the EW-mean the bound sits.
        The default (3.0) is deliberately tighter than the anomaly
        detector's z_threshold (4.0): a ramp must trip at ONSET, before the
        shared moments chase it — once the EWMA converges onto a ramp its
        variance inflates with the tracking lag and ``mean + 4*std`` never
        falls below the current loss again. The ``factor_min`` floor, not a
        large z, is what keeps healthy noise from tripping.
    ewma:
        A live :class:`~obs.anomaly.Ewma` to share (the anomaly detector's
        ``loss`` stream); when ``None`` a private one is created and fed by
        :meth:`observe`.
    """

    def __init__(self, factor_max: float, factor_min: float = 1.5,
                 z: float = 3.0, ewma: Ewma | None = None,
                 alpha: float = 0.1, warmup: int = 8):
        if factor_max <= 0.0:
            raise ValueError(f"factor_max {factor_max} must be > 0")
        if not 0.0 < factor_min <= factor_max:
            raise ValueError(
                f"factor_min {factor_min} must be in (0, factor_max="
                f"{factor_max}]")
        if z <= 0.0:
            raise ValueError(f"z {z} must be > 0")
        self.factor_max = factor_max
        self.factor_min = factor_min
        self.z = z
        self._shared = ewma is not None
        self.ewma = ewma if ewma is not None else Ewma(alpha=alpha,
                                                       warmup=warmup)

    @property
    def warmed(self) -> bool:
        """Moments trustworthy enough to override the fixed bound."""
        ew = self.ewma
        return ew.n >= max(ew.warmup, 2) and ew.var > 0.0

    def observe(self, loss: float) -> None:
        """Fold one flushed (host-side, finite) loss into the moments —
        no-op in shared mode, where the anomaly detector owns the updates
        and double-counting would halve the effective memory."""
        if not self._shared and math.isfinite(loss):
            self.ewma.update(loss)

    def bound(self, median: float, fixed_bound: float) -> float:
        """The spike bound to compare this flush's loss against.

        ``median`` is the sentinel's recent-loss median, ``fixed_bound`` the
        fixed-policy bound (``spike_factor * median``). Falls back to
        ``fixed_bound`` until warmed; the clamps only apply while the median
        is positive (an RL loss can legitimately go negative, where
        factor-of-median semantics stop meaning anything — there the bound
        is the raw EWMA one, still capped at ``fixed_bound``)."""
        if not self.warmed:
            return fixed_bound
        b = self.ewma.mean + self.z * math.sqrt(self.ewma.var)
        if median > 0.0:
            b = max(b, self.factor_min * median)
        return min(b, fixed_bound)
