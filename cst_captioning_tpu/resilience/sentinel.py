"""Host-side divergence sentinel over the step loops.

The on-device guard (:mod:`resilience.guard`) already *excludes* a non-finite
update; the sentinel is the policy layer on top: it buffers each step's
``(loss, nonfinite)`` device scalars and reads them back in batched chunks
(one ``device_get`` per flush — never a per-step sync, per graftlint GL001),
then

- logs every divergence as a structured ``divergence`` event,
- under ``policy="skip_batch"`` carries on (the guard did the work),
- under ``policy="rollback"`` raises :class:`RollbackRequested` so the
  trainer restores the last-good checkpoint and re-randomizes the data order,
- under ``policy="abort"`` raises :class:`TrainingDiverged`.

Loss *spikes* (finite but ``spike_factor``× the recent median) are detected
on the same readback. A spiked update is already applied by the time the
host sees it, so under ``skip_batch`` a spike is logged but not acted on;
``rollback``/``abort`` treat it like a NaN.

``check_every=None`` defers all checks to explicit :meth:`flush` calls (the
trainer flushes at epoch ends and before checkpoint saves) — zero extra
syncs for the default ``skip_batch`` policy. ``rollback``/``abort`` set a
mid-epoch cadence so a diverged run stops within ``check_every`` steps.
"""

from __future__ import annotations

import math
import statistics
from collections import deque
from typing import Any, Callable

import jax

from cst_captioning_tpu.obs import anomaly as obs_anomaly
from cst_captioning_tpu.obs import metrics as obs_metrics
from cst_captioning_tpu.obs import recorder as obs_recorder

POLICIES = ("off", "skip_batch", "rollback", "abort")


class TrainingDiverged(RuntimeError):
    """Raised under ``policy="abort"`` or when the rollback budget runs out."""


class RollbackRequested(RuntimeError):
    """Control-flow escape: the trainer catches this and restores the
    last-good checkpoint with a re-randomized data order."""

    def __init__(self, message: str, step: int = -1, kind: str = ""):
        super().__init__(message)
        self.step = step
        self.kind = kind


class DivergenceSentinel:
    def __init__(
        self,
        policy: str = "skip_batch",
        phase: str = "xe",
        log: Callable[..., None] | None = None,
        spike_factor: float = 0.0,
        window: int = 32,
        warmup: int = 8,
        check_every: int | None = None,
        adaptive: Any = None,
    ):
        if policy not in POLICIES:
            raise ValueError(f"unknown divergence policy {policy!r}")
        self.policy = policy
        self.phase = phase
        self.log = log or (lambda event, **fields: None)
        self.spike_factor = spike_factor
        self.warmup = warmup
        self.check_every = check_every
        # spike_mode="adaptive": an AdaptiveThresholds (resilience/adaptive.py)
        # tightens the spike bound from the anomaly detector's EWMA moments.
        # None (spike_mode="fixed") keeps the median-factor policy untouched.
        self.adaptive = adaptive
        self._recent: deque[float] = deque(maxlen=window)
        self._buf: list[tuple[int, Any, Any]] = []
        self.skipped = 0

    def push(self, step: int, loss: Any, nonfinite: Any = None) -> None:
        """Record one step's (device) scalars; flushes on the cadence."""
        if self.policy == "off":
            return
        self._buf.append((step, loss, nonfinite))
        if self.check_every is not None and len(self._buf) >= self.check_every:
            self.flush()

    def flush(self) -> None:
        """ONE host readback for everything buffered, then per-step checks."""
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        for step, loss, nonfinite in jax.device_get(buf):
            self._check(int(step), float(loss), nonfinite)

    def reset(self) -> None:
        """Drop buffered scalars and spike history (rollback/epoch restart)."""
        self._buf.clear()
        self._recent.clear()

    # ---- internals ----------------------------------------------------------

    def _check(self, step: int, loss: float, nonfinite: Any) -> None:
        bad = bool(nonfinite) if nonfinite is not None else False
        if bad or not math.isfinite(loss):
            self._diverged(step, loss, "nonfinite")
            return
        if self.spike_factor and len(self._recent) >= self.warmup:
            med = statistics.median(self._recent)
            bound = self.spike_factor * med
            if self.adaptive is not None:
                bound = self.adaptive.bound(med, bound)
            if loss > bound:
                self._diverged(step, loss, "spike", bound=bound)
                return
        self._recent.append(loss)
        if self.adaptive is not None:
            self.adaptive.observe(loss)

    def _diverged(self, step: int, loss: float, kind: str,
                  bound: float | None = None) -> None:
        # skip_batch cannot un-apply a finite-but-spiked update — log only
        action = self.policy
        if kind == "spike" and self.policy == "skip_batch":
            action = "logged"
        # every verdict counts, so a run report aggregates divergences even
        # when the per-event log rotated away (obs satellite: log-only ->
        # counted)
        obs_metrics.counter(f"resilience.divergence.{kind}").inc()
        # the sentinel's verdict and the online detector (obs/anomaly.py)
        # share ONE spelling: the same obs.anomaly.<kind> counter + anomaly
        # event, whoever saw it first — dashboards and the postmortem
        # timeline never disagree on what a divergence is called
        # a spike verdict carries the bound it crossed — under adaptive mode
        # that is the evidence for *why* this loss tripped when factor-of-
        # median would not have
        detail = {} if bound is None else {"bound": bound}
        obs_anomaly.record_anomaly(
            kind, phase=self.phase, step=step, value=loss, source="sentinel",
            **detail,
        )
        # flight-recorder postmortem: capture the ring around the diverged
        # step before any policy action (rollback restore, abort unwind)
        obs_recorder.postmortem(
            f"divergence_{kind}", phase=self.phase, step=step, loss=loss,
            action=action, **detail,
        )
        self.log(
            "divergence",
            phase=self.phase, step=step, loss=loss, kind=kind, action=action,
            **detail,
        )
        if self.policy == "skip_batch":
            if kind == "nonfinite":
                self.skipped += 1
                obs_metrics.counter("resilience.nan_skip").inc()
            return
        msg = f"{self.phase} step {step}: {kind} loss {loss!r}"
        if self.policy == "rollback":
            raise RollbackRequested(msg, step=step, kind=kind)
        raise TrainingDiverged(msg)
