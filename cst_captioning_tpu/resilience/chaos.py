"""Deterministic fault injection: seeded plans fired at named code points.

Production hot paths call :func:`visit` at a handful of named injection
points (``"xe.step"``, ``"ckpt.save"``, ``"reward.call"``, ...). With no
active plan that is one module-global ``None`` check — free. Tests activate a
:class:`FaultPlan` and every listed :class:`Fault` fires at an exact visit
index of its point, so a chaos run is bit-reproducible: the same plan always
kills the same save, poisons the same batch, fails the same reward call.

Fault kinds:

- ``"kill"``     — raise :class:`SimulatedKill` (a ``BaseException``: it
  models a process death, so ``except Exception`` recovery paths must NOT
  swallow it).
- ``"preempt"``  — deliver a real ``SIGTERM`` to this process (exercises the
  actual :class:`~cst_captioning_tpu.resilience.preempt.PreemptionHandler`
  signal path, not a shortcut flag).
- ``"io_error"`` — raise :class:`TransientIOError` (an ``OSError``) for
  ``times`` consecutive visits starting at ``at`` — the retry-helper fodder.
- ``"nan"``      — poison the visited payload (a ``data.batcher.Batch``):
  every feature array becomes NaN, so the forward pass diverges on device.
- ``"slow"``     — ``time.sleep(delay)``, modelling a stalled reward service.
- ``"slow_h2d"`` — ``time.sleep(delay)`` at the host->device staging point,
  modelling a degraded PCIe/DMA transfer (fire at ``prefetch.h2d``).
- ``"partial_h2d"`` — raise :class:`PartialTransferError` (a transient,
  retryable transfer failure): the staged batch never fully landed in HBM.
  The prefetch stage retries the placement under a small budget.
- ``"wedged_prefetch"`` — ``time.sleep(delay)`` on the prefetch WORKER
  thread (fire at ``prefetch.stage``): the staging thread wedges while the
  consumer's stall watchdog detects and reports the starvation.
- ``"enospc_rotation"`` — raise ``OSError(ENOSPC)``: the filesystem filled
  up mid-checkpoint; rotation reclaims the oldest generation and retries.
- ``"partial_preempt"`` — mark host ``host`` dead on the active
  :class:`~cst_captioning_tpu.resilience.health.HealthMonitor` (tombstone +
  synchronous loss flag): one host of the cluster was preempted while this
  one survived — the elastic drain/degraded-continuation trigger.
- ``"serving_preempt"`` — request a drain of the active
  :class:`~cst_captioning_tpu.serving.engine.CaptionService` (fire at
  ``serving.step``): the serving loop finishes in-flight strides, refuses
  new admissions, and persists the queue + page-table snapshot — the
  SIGTERM/peer-loss path, triggered deterministically. The recovery test
  replays the drained queue and pins bit-identical tokens.
- ``"actor_preempt"`` — preempt one device of the decoupled RL actor
  submesh (fire at ``rl.actor.step``; ``host`` indexes the victim device
  in the actor plan). The running
  :class:`~cst_captioning_tpu.rl.async_scst.AsyncSCSTTrainer` epoch sheds
  the device, recounts the in-flight rollout ring on the survivors, and
  falls back to the sync schedule when no actor remains.
- ``"host_rejoin"`` — the grow-back companion to ``partial_preempt``: a
  previously-lost host recovers NOW. Fired at ``health.rejoin`` it acts on
  the phantom's behalf via
  :func:`~cst_captioning_tpu.resilience.health.simulate_rejoin` (tombstone
  cleared, fresh heartbeat, generation-stamped rejoin marker, regrow
  rendezvous check-in) and the degraded trainer re-admits it at the next
  batch boundary. Fired at ``rl.actor.step`` it instead re-admits one
  previously-shed actor device (``host`` indexes into the initial actor
  plan) via
  :func:`~cst_captioning_tpu.rl.async_scst.request_actor_rejoin`.
- ``"host_rejoin_flaky"`` — the flaky rejoiner: the host announces itself
  (marker + heartbeat land) and then dies mid-rendezvous, so the
  survivors' regrow rendezvous times out and the run continues degraded —
  a failed rejoin must never become a second outage.
- ``"param_swap"`` — preempt the serving loop exactly at the hot
  param-swap seam (fire at ``serving.param_swap``: the visit sits between
  a staged publish and its application). The swap machinery refuses the
  publish and drains under the OLD version, so the snapshot replays
  bit-identically — the swap is fully applied or fully refused, never a
  torn version (tests/test_serving.py pins both arms).

Injection points currently compiled in:

=================  =========================================================
``xe.step``        XE train loop, once per dispatched step (main thread)
``xe.batch``       XE host batch prep, payload = the ``Batch`` (prefetch thread)
``rl.step``        RL train loop, once per completed step (main thread)
``rl.batch``       RL host batch prep, payload = the ``Batch`` (prefetch thread)
``prefetch.stage`` prefetch worker, once per staged batch (worker thread)
``prefetch.h2d``   inside the (retried) host->device placement of a batch
``ckpt.save``      entry of ``save_state`` (before any file is written)
``ckpt.state_written``  after ``state.msgpack`` hits the tmp dir
``ckpt.pre_replace``    tmp dir complete + fsync'd, final rename not yet done
``reward.call``    inside the retried RL reward invocation
``serving.step``   serving admission loop, once per iteration (main thread)
``serving.param_swap``  between a staged param publish and its application
``rl.actor.step``  decoupled RL actor loop, once per decoded batch
``health.rejoin``  degraded trainer's rejoin poll, once per batch boundary
=================  =========================================================
"""

from __future__ import annotations

import errno
import os
import signal
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any

import numpy as np

from cst_captioning_tpu.obs import metrics as obs_metrics


class SimulatedKill(BaseException):
    """A chaos-injected process death. BaseException on purpose: recovery
    code that catches ``Exception`` must not accidentally 'survive' a kill."""


class TransientIOError(OSError):
    """A chaos-injected transient I/O failure (retryable)."""


class PartialTransferError(TransientIOError):
    """A chaos-injected partial host->device transfer (retryable): the
    destination buffer is torn, the placement must be redone."""


@dataclass
class Fault:
    """One scheduled fault.

    ``at`` is the 0-based visit index of ``point`` that triggers; pass
    ``("rand", lo, hi)`` to have :class:`FaultPlan` draw it from the plan
    seed (deterministic per seed). ``times`` widens io_error/nan/slow faults
    to that many consecutive visits. ``host`` names the victim host of a
    ``partial_preempt`` — and, symmetrically, the rejoiner of a
    ``host_rejoin``/``host_rejoin_flaky``.
    """

    point: str
    kind: str  # see _KINDS / module docstring
    at: Any = 0
    times: int = 1
    delay: float = 0.0
    host: int = 0

    _KINDS = ("kill", "preempt", "io_error", "nan", "slow", "slow_h2d",
              "partial_h2d", "wedged_prefetch", "enospc_rotation",
              "partial_preempt", "serving_preempt", "actor_preempt",
              "host_rejoin", "host_rejoin_flaky", "param_swap")

    def __post_init__(self):
        if self.kind not in self._KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.times < 1:
            raise ValueError(f"fault times {self.times} must be >= 1")

    def window(self) -> range:
        return range(self.at, self.at + self.times)


class FaultPlan:
    """A seeded, activatable schedule of faults.

    Use as a context manager::

        plan = FaultPlan([Fault("xe.step", "preempt", at=7)], seed=3)
        with plan.activate():
            trainer.train_xe()
        assert plan.fired  # [{"point": "xe.step", "kind": "preempt", ...}]

    Only one plan can be active per process at a time (they model
    process-level failures). ``plan.fired`` records every triggered fault in
    order for test assertions.
    """

    def __init__(self, faults: list[Fault], seed: int = 0):
        rng = np.random.default_rng(seed)
        self.seed = seed
        self.faults: list[Fault] = []
        for f in faults:
            if isinstance(f.at, tuple):
                tag, lo, hi = f.at
                if tag != "rand":
                    raise ValueError(f"bad fault at-spec {f.at!r}")
                f = replace(f, at=int(rng.integers(lo, hi)))
            self.faults.append(f)
        self.fired: list[dict] = []
        self._visits: dict[str, int] = {}
        self._lock = threading.Lock()

    def activate(self) -> "_Activation":
        return _Activation(self)

    def visits(self, point: str) -> int:
        with self._lock:
            return self._visits.get(point, 0)

    def _visit(self, point: str, payload: Any) -> Any:
        with self._lock:
            idx = self._visits.get(point, 0)
            self._visits[point] = idx + 1
            due = [f for f in self.faults
                   if f.point == point and idx in f.window()]
            for f in due:
                self.fired.append(
                    {"point": point, "kind": f.kind, "visit": idx}
                )
                # chaos activations count like real faults so a chaos-run
                # report shows exactly what was injected
                obs_metrics.counter("resilience.chaos_fault").inc()
                obs_metrics.counter(f"resilience.chaos_fault.{f.kind}").inc()
        if due:
            # flight-recorder postmortem BEFORE the fault fires: a kill or
            # preempt unwinds past any later dump site. Lazy import keeps
            # this module importable from jax-free contexts; the recorder's
            # module import is jax-free by design (obs/recorder.py)
            from cst_captioning_tpu.obs import recorder as obs_recorder

            for f in due:
                # the victim host rides in the bundle meta so the fleet
                # merge can attribute a partial preemption to a named host
                # (victim_host, not host — meta's `host` is the identity of
                # the RECORDING process, set by the recorder itself)
                if f.kind == "partial_preempt":
                    extra = {"victim_host": f.host}
                elif f.kind in ("host_rejoin", "host_rejoin_flaky"):
                    extra = {"rejoiner_host": f.host}
                else:
                    extra = {}
                obs_recorder.note_fault(point, f.kind, visit=idx, **extra)
        # fire outside the lock: handlers/sleeps must not serialize threads
        for f in due:
            if f.kind == "kill":
                raise SimulatedKill(f"chaos kill at {point}#{idx}")
            if f.kind == "io_error":
                raise TransientIOError(f"chaos io_error at {point}#{idx}")
            if f.kind == "partial_h2d":
                raise PartialTransferError(
                    f"chaos partial_h2d at {point}#{idx}"
                )
            if f.kind == "enospc_rotation":
                raise OSError(
                    errno.ENOSPC,
                    f"chaos enospc at {point}#{idx}: No space left on device",
                )
            if f.kind == "preempt":
                os.kill(os.getpid(), signal.SIGTERM)
            elif f.kind == "partial_preempt":
                # lazy import: health is a consumer of chaos-adjacent obs
                # plumbing; binding it at module import would cycle through
                # the resilience package init
                from cst_captioning_tpu.resilience import health

                health.simulate_peer_loss(f.host)
            elif f.kind == "serving_preempt":
                # lazy import: serving pulls jax in; chaos must stay
                # importable from jax-free contexts (cli.obs_report)
                from cst_captioning_tpu.serving import engine as serving

                serving.request_drain("chaos_serving_preempt")
            elif f.kind == "param_swap":
                # a preemption landing exactly mid-swap: the service's
                # swap seam sees the drain request before mutating and
                # refuses the publish (fully applied or fully refused)
                from cst_captioning_tpu.serving import engine as serving

                serving.request_drain("chaos_param_swap")
            elif f.kind == "actor_preempt":
                # lazy import: rl pulls jax in, same contract as serving
                from cst_captioning_tpu.rl import async_scst

                async_scst.request_actor_preempt(f.host)
            elif f.kind in ("host_rejoin", "host_rejoin_flaky"):
                if point == "rl.actor.step":
                    # actor-fleet direction: re-admit a shed actor device
                    from cst_captioning_tpu.rl import async_scst

                    async_scst.request_actor_rejoin(f.host)
                else:
                    from cst_captioning_tpu.resilience import health

                    health.simulate_rejoin(
                        f.host, flaky=(f.kind == "host_rejoin_flaky")
                    )
            elif f.kind in ("slow", "slow_h2d", "wedged_prefetch"):
                time.sleep(f.delay)
            elif f.kind == "nan":
                payload = _poison(payload)
        return payload


@dataclass
class _Activation:
    plan: FaultPlan
    _token: Any = field(default=None, repr=False)

    def __enter__(self) -> FaultPlan:
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("another FaultPlan is already active")
        _ACTIVE = self.plan
        return self.plan

    def __exit__(self, *exc) -> None:
        global _ACTIVE
        _ACTIVE = None


_ACTIVE: FaultPlan | None = None


def _poison(payload: Any) -> Any:
    """NaN-poison a batch payload in place (features only: labels stay valid
    so the loss itself, not the int pipeline, is what diverges)."""
    if payload is None:
        raise ValueError("nan fault fired at a point with no batch payload")
    feats = getattr(payload, "feats", payload)
    for arr in feats.values() if hasattr(feats, "values") else [feats]:
        arr[:] = np.nan
    return payload


def visit(point: str, payload: Any = None) -> Any:
    """Injection point: no-op (returning ``payload``) unless a plan is
    active and schedules a fault at this visit of ``point``."""
    if _ACTIVE is None:
        return payload
    return _ACTIVE._visit(point, payload)
