"""Durable file primitives: fsync'd writes + a checksum manifest sidecar.

``os.replace`` alone only orders the rename against other *metadata*
operations; after a host crash the freshly renamed checkpoint can still read
back as zeros/truncated unless the data files AND the directories were
fsync'd first. The manifest (``manifest.json``) records a sha256 + size per
checkpoint file so a torn write is *detected at load time* instead of being
deserialized into garbage params.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Mapping

MANIFEST_FILE = "manifest.json"


class CorruptCheckpointError(RuntimeError):
    """A checkpoint file failed its manifest checksum/size verification."""


def fsync_dir(path: str) -> None:
    """fsync a directory so entry renames/creates survive a crash. Some
    filesystems refuse O_RDONLY dir fsync — treat that as best-effort."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        return
    finally:
        os.close(fd)


def write_bytes_durable(path: str, data: bytes) -> None:
    """Write ``data`` to ``path`` and fsync the file before returning."""
    with open(path, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())


def write_manifest(dirpath: str, blobs: Mapping[str, bytes]) -> None:
    """Write ``manifest.json`` for files already written under ``dirpath``.

    Checksums come from the in-memory ``blobs`` (name -> bytes), not a
    re-read of disk, so the manifest attests what the writer *meant* to
    persist.
    """
    manifest = {
        "version": 1,
        "files": {
            name: {
                "sha256": hashlib.sha256(blob).hexdigest(),
                "size": len(blob),
            }
            for name, blob in blobs.items()
        },
    }
    write_bytes_durable(
        os.path.join(dirpath, MANIFEST_FILE),
        json.dumps(manifest, indent=2).encode(),
    )


def verify_manifest(dirpath: str) -> bool:
    """Verify every file listed in ``dirpath``'s manifest.

    Returns ``False`` when no manifest exists (a pre-manifest legacy
    checkpoint: loadable, just unverifiable). Raises
    :class:`CorruptCheckpointError` naming every mismatching file otherwise.
    """
    mpath = os.path.join(dirpath, MANIFEST_FILE)
    if not os.path.exists(mpath):
        return False
    try:
        with open(mpath, encoding="utf-8") as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (ValueError, KeyError, OSError) as e:
        raise CorruptCheckpointError(f"{mpath}: unreadable manifest: {e}") from e
    bad: list[str] = []
    for name, meta in files.items():
        fpath = os.path.join(dirpath, name)
        if not os.path.exists(fpath):
            bad.append(f"{name}: missing")
            continue
        size = os.path.getsize(fpath)
        if size != int(meta["size"]):
            bad.append(f"{name}: size {size} != {meta['size']}")
            continue
        h = hashlib.sha256()
        with open(fpath, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != meta["sha256"]:
            bad.append(f"{name}: sha256 mismatch")
    if bad:
        raise CorruptCheckpointError(
            f"{dirpath}: manifest verification failed: " + "; ".join(bad)
        )
    return True
