"""Cluster health: per-host heartbeats, a peer-loss watchdog, rendezvous.

Multi-host training dies today if *any* host drops out: the collectives hang
until a connect timeout and every survivor crashes. This module makes partial
failure an *observable, recoverable* event:

- :class:`HealthMonitor` — each host writes a tiny heartbeat file into a
  shared directory on a watchdog thread (configurable interval) and watches
  its peers' files. Liveness is stamped with the LOCAL monotonic clock at
  *receipt* of a new heartbeat (never the peer's wall clock), so clock skew
  between hosts cannot fake a death. A peer whose heartbeat goes stale past
  ``timeout_s`` for ``misses`` consecutive polls (the debounce/backoff) — or
  that left an explicit tombstone — is declared lost: a structured
  ``peer_lost`` obs event fires and :attr:`HealthMonitor.peer_lost` flips,
  which the train loops poll once per step (a plain Python bool read: no
  device transfer, no syscall — GL001-clean by construction).
- **Piggybacked liveness** — every completed cross-host collective proves all
  peers were alive moments ago, so the multihost helpers call
  :func:`record_collective` and refresh every peer's last-seen stamp for
  free; the file heartbeat only has to cover the gaps between collectives.
- :func:`rendezvous` — survivors agree on the new membership after a loss:
  each writes a marker into a generation-numbered directory and polls (with
  exponential backoff) until every expected host checked in or the timeout
  expires. Deterministic and injectable (``clock``/``sleep``) for tests.
- **Rejoin rendezvous (the grow-back direction)** — a recovered host
  announces itself with a generation-stamped rejoin marker next to its
  heartbeat (:meth:`HealthMonitor.announce_rejoin`); the surviving
  coordinator validates liveness with ``misses`` consecutive fresh-heartbeat
  reads (:meth:`HealthMonitor.validate_rejoin`, run under the budgeted
  retry policy via :func:`attempt_rejoin`), bumps the mesh generation, and
  re-admits the host (:meth:`HealthMonitor.readmit`). A refused or
  timed-out rejoin raises :class:`RejoinRefused` and leaves the degraded
  membership untouched — graceful degradation, never a second outage.

All marker files here (heartbeats, tombstones, rejoin markers, rendezvous
check-ins) are published tmp-then-rename and read torn-read-tolerantly: a
poller racing a writer sees the previous marker or nothing, never a
truncated file.
- :func:`collective_span` — the DCN-stall probe: wraps a cross-host
  barrier/broadcast in an obs span and emits a ``dcn_stall`` event + counter
  when the collective exceeds the stall threshold, closing the "span around
  the multihost barrier/broadcast" obs item.

Everything here is host-side stdlib (no jax import): the monitor can run in
tests, CLIs, and subprocesses without touching a backend.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Iterable

from cst_captioning_tpu import obs


class PeerLost(RuntimeError):
    """Raised by a train loop after a peer-loss-triggered drain+save
    completed. ``hosts`` names the lost host ids; the caller decides between
    degraded-mesh continuation and the strict abort-and-full-restart."""

    def __init__(self, hosts: Iterable[int], message: str):
        self.hosts = sorted(int(h) for h in hosts)
        super().__init__(message)


class RendezvousTimeout(RuntimeError):
    """A degraded-mesh rendezvous expired before every survivor checked in."""


class RejoinRefused(RuntimeError):
    """A host's rejoin attempt was refused (marker absent or corrupt, stale
    generation, no fresh heartbeats). The degraded run continues untouched."""


class HostRejoin(RuntimeError):
    """Raised by a train loop after a validated rejoin drained the pipeline
    at a batch boundary. ``host`` names the rejoiner; the caller runs the
    regrow rendezvous and rebuilds the full mesh — or, if that rendezvous
    times out, keeps the degraded mesh and continues."""

    def __init__(self, host: int, message: str):
        self.host = int(host)
        super().__init__(message)


# default threshold for the DCN-stall probe; overridden per run from
# train.dcn_stall_s via set_dcn_stall_threshold
_DCN_STALL_S = 2.0


def set_dcn_stall_threshold(seconds: float) -> None:
    global _DCN_STALL_S
    _DCN_STALL_S = float(seconds)


def _publish_json(path: str, rec: dict) -> None:
    """Atomic marker publish: write a sibling tmp file, then rename into
    place. A reader polling mid-write sees the previous marker or nothing —
    never a truncated/torn file."""
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(rec, f)
    os.replace(tmp, path)


@contextmanager
def collective_span(op: str, stall_threshold_s: float | None = None):
    """Span + stall probe around one cross-host collective.

    Emits the ``dcn.collective`` span (op attribute), feeds the
    ``dcn.collective_seconds`` histogram, and — when the collective took
    longer than the stall threshold — a structured ``dcn_stall`` event plus
    the ``health.dcn_stall`` counter. A completed collective also refreshes
    every peer's liveness on the active monitor (piggybacked heartbeat)."""
    t0 = time.perf_counter()
    with obs.span("dcn.collective", op=op):
        yield
    dur = time.perf_counter() - t0
    obs.histogram("dcn.collective_seconds").observe(dur)
    threshold = _DCN_STALL_S if stall_threshold_s is None else stall_threshold_s
    if dur > threshold:
        obs.counter("health.dcn_stall").inc()
        obs.event("dcn_stall", op=op, dur_s=round(dur, 6),
                  threshold_s=threshold)
    mon = _ACTIVE
    if mon is not None:
        mon.record_collective()


class HealthMonitor:
    """File-heartbeat cluster monitor with a watchdog thread.

    One instance per process. ``num_hosts`` may exceed the real process count
    (simulated hosts for chaos tests — this process is ``host_id`` and the
    phantom peers are only ever killed via :meth:`simulate_loss`): a peer
    that NEVER heartbeated is not declared dead by staleness alone, only a
    peer that went silent after being seen, or one with a tombstone.

    ``clock`` is injectable (defaults to ``time.monotonic``) so loss
    detection is testable without sleeping through real timeouts.
    """

    def __init__(
        self,
        dir: str,
        host_id: int = 0,
        num_hosts: int = 1,
        interval_s: float = 0.5,
        timeout_s: float = 5.0,
        misses: int = 2,
        log: Callable[..., None] | None = None,
        clock: Callable[[], float] = time.monotonic,
        start_thread: bool = True,
    ):
        if num_hosts < 1 or not 0 <= host_id < num_hosts:
            raise ValueError(
                f"host_id {host_id} not in [0, num_hosts={num_hosts})"
            )
        if interval_s <= 0 or timeout_s <= 0 or misses < 1:
            raise ValueError(
                "health knobs out of range: interval_s > 0, timeout_s > 0, "
                f"misses >= 1 required (got {interval_s}, {timeout_s}, "
                f"{misses})"
            )
        self.dir = dir
        self.host_id = host_id
        self.num_hosts = num_hosts
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.misses = misses
        self.log = log or (lambda event, **fields: None)
        self.clock = clock
        self._start_thread = start_thread
        self.peers: set[int] = set(range(num_hosts)) - {host_id}
        self.lost_hosts: set[int] = set()
        # mesh generation: bumped by the trainer on every membership change
        # (shrink or regrow); rejoin markers are stamped with generation+1
        # so a marker from a previous regrow round is refused as stale
        self.generation = 0
        self._seq = 0
        self._step = 0
        self._seen_seq: dict[int, int] = {}
        self._last_seen: dict[int, float] = {}
        self._strikes: dict[int, int] = {}
        self._loss_event = threading.Event()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        os.makedirs(dir, exist_ok=True)

    # ---- lifecycle ----------------------------------------------------------

    def start(self) -> "HealthMonitor":
        global _ACTIVE
        now = self.clock()
        with self._lock:
            # grace period: peers have a full timeout from start to appear
            for p in self.peers:
                self._last_seen.setdefault(p, now)
        # a restarted host announces itself alive: its own tombstone (left
        # by the survivors of a previous incarnation) is stale by definition
        try:
            os.unlink(self._tombstone(self.host_id))
        except FileNotFoundError:
            pass
        self.beat()
        _ACTIVE = self
        if self._start_thread and self._thread is None:
            self._thread = threading.Thread(
                target=self._watchdog, daemon=True, name="health-watchdog"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        global _ACTIVE
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if _ACTIVE is self:
            _ACTIVE = None

    def __enter__(self) -> "HealthMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _watchdog(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()
            self.poll()

    # ---- heartbeats ---------------------------------------------------------

    def _hb_path(self, host: int) -> str:
        return os.path.join(self.dir, f"host{host}.hb")

    def _tombstone(self, host: int) -> str:
        return os.path.join(self.dir, f"host{host}.dead")

    def note_step(self, step: int) -> None:
        """Record train progress for the next heartbeat payload. A plain
        attribute store — safe (and free) once per step in the hot loop."""
        self._step = int(step)

    def beat(self, step: int | None = None) -> None:
        """Write this host's heartbeat file (atomic replace)."""
        if step is not None:
            self._step = int(step)
        with self._lock:
            self._seq += 1
            rec = {"host": self.host_id, "seq": self._seq,
                   "step": self._step, "ts": time.time()}  # graftlint: disable=GL010 (heartbeat wall-clock payload, read by humans/other hosts)
        try:
            _publish_json(self._hb_path(self.host_id), rec)
        except OSError as e:
            # a missed beat is survivable (peers debounce); losing the run
            # to a transient shared-fs error is not
            self.log("heartbeat_write_failed", error=type(e).__name__,
                     detail=str(e))
            return
        obs.counter("health.heartbeats").inc()

    def _read_hb(self, host: int) -> dict | None:
        try:
            with open(self._hb_path(host), encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None  # absent / torn mid-replace: treated as "no news"
        return rec if isinstance(rec, dict) else None

    # ---- peer-loss detection ------------------------------------------------

    def poll(self, now: float | None = None) -> list[int]:
        """One watchdog pass: refresh last-seen stamps from peer heartbeat
        files, detect tombstones and stale peers, update gauges. Returns the
        hosts newly declared lost by this pass."""
        now = self.clock() if now is None else now
        newly_lost: list[int] = []
        max_age = 0.0
        with self._lock:
            peers = sorted(self.peers - self.lost_hosts)
        for p in peers:
            if os.path.exists(self._tombstone(p)):
                if self._mark_lost(p, reason="tombstone"):
                    newly_lost.append(p)
                continue
            rec = self._read_hb(p)
            with self._lock:
                if rec is not None and rec.get("seq") != self._seen_seq.get(p):
                    # NEW heartbeat: stamp receipt with OUR clock (clock skew
                    # between hosts can never fake a death)
                    self._seen_seq[p] = rec.get("seq")
                    self._last_seen[p] = now
                    self._strikes[p] = 0
                    age = 0.0
                else:
                    age = now - self._last_seen.get(p, now)
            max_age = max(max_age, age)
            if age > self.timeout_s:
                with self._lock:
                    self._strikes[p] = self._strikes.get(p, 0) + 1
                    strikes = self._strikes[p]
                if strikes >= self.misses and self._seen_seq.get(p) is not None:
                    if self._mark_lost(p, reason="heartbeat_timeout",
                                       age_s=round(age, 3)):
                        newly_lost.append(p)
        obs.gauge("health.peers_alive").set(
            float(len(self.survivors()) - 1)
        )
        obs.gauge("health.peer_age_max_s").set(max_age)
        return newly_lost

    def _mark_lost(self, host: int, **info) -> bool:
        with self._lock:
            if host in self.lost_hosts or host not in self.peers:
                return False
            self.lost_hosts.add(host)
        obs.counter("health.peer_lost").inc()
        obs.event("peer_lost", host=host, **info)
        self.log("peer_lost", host=host, **info)
        self._loss_event.set()
        return True

    def record_collective(self) -> None:
        """A cross-host collective completed: every non-lost peer was alive
        to participate — refresh all their last-seen stamps (the piggybacked
        heartbeat)."""
        now = self.clock()
        with self._lock:
            for p in self.peers - self.lost_hosts:
                self._last_seen[p] = now
                self._strikes[p] = 0

    def simulate_loss(self, host: int) -> None:
        """Chaos hook (``partial_preempt`` fault): kill a (possibly
        simulated) peer NOW — tombstone on disk for other real processes,
        synchronous mark for deterministic single-process tests."""
        if host == self.host_id:
            raise ValueError(
                f"partial_preempt host {host} is this host; use the "
                "'preempt' fault kind for whole-process preemption"
            )
        if host not in self.peers:
            raise ValueError(
                f"partial_preempt host {host} not a peer of host "
                f"{self.host_id} (peers: {sorted(self.peers)})"
            )
        try:
            _publish_json(self._tombstone(host),
                          {"host": host, "by": self.host_id})
        except OSError as e:
            # the synchronous mark below still lands; peers of a REAL fleet
            # would fall back to heartbeat-timeout detection
            self.log("tombstone_write_failed", host=host,
                     error=type(e).__name__, detail=str(e))
        self._mark_lost(host, reason="partial_preempt")

    def simulate_recovery(self, host: int, flaky: bool = False) -> None:
        """Chaos hook (``host_rejoin`` fault): a lost — possibly simulated —
        peer recovers NOW. Acts on the phantom's behalf, mirroring what a
        really-restarted process does in :meth:`start` +
        :meth:`announce_rejoin`: clear its tombstone, publish the recovered
        incarnation's first heartbeat (a fresh seq stream), write a rejoin
        marker stamped with the NEXT generation, and — unless ``flaky`` —
        pre-check into the regrow rendezvous. A flaky rejoiner announces
        itself and then dies mid-rendezvous: marker and heartbeat land, the
        rendezvous check-in never does, so the survivors' regrow rendezvous
        times out and the run continues degraded."""
        if host == self.host_id:
            raise ValueError(
                f"host_rejoin host {host} is this host; it never left"
            )
        with self._lock:
            if host not in self.lost_hosts:
                raise ValueError(
                    f"host_rejoin host {host} is not a lost host "
                    f"(lost: {sorted(self.lost_hosts)})"
                )
            fresh_seq = int(self._seen_seq.get(host) or 0) + 1
        try:
            os.unlink(self._tombstone(host))
        except FileNotFoundError:
            pass
        gen = int(self.generation) + 1
        _publish_json(self._hb_path(host), {
            "host": host, "seq": fresh_seq, "step": 0,
            "ts": time.time(),  # graftlint: disable=GL010 (heartbeat wall-clock payload, read by humans/other hosts)
        })
        self.announce_rejoin(gen, host=host)
        if not flaky:
            _write_rendezvous_marker(self.dir, gen, host)
        self.log("host_rejoin_simulated", host=host, generation=gen,
                 flaky=flaky)

    # ---- rejoin rendezvous (grow-back) --------------------------------------

    def _rejoin_path(self, host: int) -> str:
        return os.path.join(self.dir, f"host{host}.rejoin")

    def announce_rejoin(self, generation: int, host: int | None = None) -> None:
        """Publish a generation-stamped rejoin marker next to the heartbeat
        (tmp-then-rename, like every marker here). A recovered host calls
        this with the generation it wants to join — current + 1, learned
        from the coordinator's latest rendezvous directory or config."""
        host = self.host_id if host is None else int(host)
        rec = {"host": host, "generation": int(generation),
               "ts": time.time()}  # graftlint: disable=GL010 (rejoin marker wall-clock payload)
        try:
            _publish_json(self._rejoin_path(host), rec)
        except OSError as e:
            self.log("rejoin_write_failed", host=host,
                     error=type(e).__name__, detail=str(e))
            return
        obs.counter("health.rejoin_announced").inc()

    def read_rejoin(self, host: int) -> dict | None:
        """Torn-read-tolerant rejoin marker read (absent/corrupt → None)."""
        try:
            with open(self._rejoin_path(host), encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None
        return rec if isinstance(rec, dict) else None

    def pending_rejoins(self) -> dict[int, dict]:
        """Lost hosts that have published a readable rejoin marker, keyed by
        host id. The train loops poll this at batch boundaries (one stat()
        per lost host — only ever on an already-degraded run)."""
        out: dict[int, dict] = {}
        for h in self.lost():
            rec = self.read_rejoin(h)
            if rec is not None:
                out[h] = rec
        return out

    def clear_rejoin(self, host: int) -> None:
        """Consume a rejoin marker — after admission, or after a refusal so
        the run does not re-litigate the same dead marker every batch."""
        try:
            os.unlink(self._rejoin_path(host))
        except FileNotFoundError:
            pass

    def validate_rejoin(
        self,
        host: int,
        generation: int,
        sleep: Callable[[float], None] | None = None,
    ) -> dict:
        """Coordinator-side admission check for one announced rejoiner.

        Read-only (membership is only mutated by :meth:`readmit`): the
        rejoin marker must parse and carry exactly ``generation`` (a marker
        from an earlier regrow round is stale — the host must re-announce),
        and liveness is proven with ``misses`` consecutive heartbeat reads,
        each of which must return a parseable heartbeat whose seq differs
        from the last seq seen before the loss (a restarted process begins a
        new seq stream; the dead incarnation's stale file never passes).
        Pass ``sleep`` (spaced by ``interval_s``) when polling a real remote
        host. Raises :class:`RejoinRefused`; returns the marker on success.
        """
        rec = self.read_rejoin(host)
        if rec is None:
            raise RejoinRefused(
                f"host {host}: rejoin marker absent or unreadable"
            )
        marker_gen = rec.get("generation")
        if marker_gen != int(generation):
            raise RejoinRefused(
                f"host {host}: stale rejoin generation {marker_gen!r} "
                f"(current regrow generation is {int(generation)})"
            )
        with self._lock:
            if host not in self.lost_hosts:
                raise RejoinRefused(
                    f"host {host} is not in the lost set "
                    f"({sorted(self.lost_hosts)}); nothing to re-admit"
                )
            stale_seq = self._seen_seq.get(host)
        for i in range(self.misses):
            if i and sleep is not None:
                sleep(self.interval_s)
            hb = self._read_hb(host)
            if hb is None:
                raise RejoinRefused(
                    f"host {host}: no readable heartbeat on poll "
                    f"{i + 1}/{self.misses} — announced, then went silent"
                )
            if hb.get("seq") == stale_seq:
                raise RejoinRefused(
                    f"host {host}: heartbeat seq {stale_seq} predates the "
                    f"loss (poll {i + 1}/{self.misses}) — the dead "
                    "incarnation's file, not a recovery"
                )
        return rec

    def readmit(self, host: int) -> None:
        """Admit a validated rejoiner back into the membership (the inverse
        of the loss mark): clear the lost record, re-arm liveness tracking
        with a fresh grace stamp, and consume the tombstone + rejoin
        marker + recovery heartbeat. Consuming the heartbeat returns the
        host to the never-seen state — tombstone-only loss detection —
        until its NEW incarnation's beat stream is observed, so a
        simulated phantom that cannot keep beating is not immediately
        re-declared lost by staleness (a real host re-publishes within one
        beat interval and staleness protection resumes). Call only after
        :meth:`validate_rejoin` (or :func:`attempt_rejoin`) and a
        successful regrow rendezvous."""
        now = self.clock()
        with self._lock:
            if host not in self.lost_hosts:
                raise ValueError(
                    f"host {host} is not lost; nothing to readmit"
                )
            self.lost_hosts.discard(host)
            self.peers.add(host)
            self._seen_seq.pop(host, None)
            self._strikes[host] = 0
            self._last_seen[host] = now
        for path in (self._tombstone(host), self._rejoin_path(host),
                     self._hb_path(host)):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass
        obs.counter("health.peer_readmitted").inc()
        obs.event("peer_readmitted", host=host)
        self.log("peer_readmitted", host=host)

    # ---- membership ---------------------------------------------------------

    @property
    def peer_lost(self) -> bool:
        """True when at least one unacknowledged peer loss is pending. A
        lock-free Event read — the once-per-step poll in the train loops."""
        return self._loss_event.is_set()

    def lost(self) -> list[int]:
        with self._lock:
            return sorted(self.lost_hosts)

    def survivors(self) -> list[int]:
        with self._lock:
            return sorted(
                ({self.host_id} | self.peers) - self.lost_hosts
            )

    def acknowledge(self) -> None:
        """Clear the pending loss flag (the drain+continuation handled it);
        the lost set stays recorded so a dead host is never re-admitted *by
        accident* — re-admission happens only through the validated rejoin
        path (:meth:`validate_rejoin` → regrow rendezvous →
        :meth:`readmit`)."""
        self._loss_event.clear()

    def set_membership(self, hosts: Iterable[int]) -> None:
        """Adopt the post-rendezvous membership: only these hosts are peers
        from now on (the lost record is kept for reporting)."""
        hosts = set(int(h) for h in hosts)
        with self._lock:
            self.peers = hosts - {self.host_id}


_ACTIVE: HealthMonitor | None = None


def active_monitor() -> HealthMonitor | None:
    return _ACTIVE


def simulate_peer_loss(host: int) -> None:
    """Module-level chaos entry point for the ``partial_preempt`` fault."""
    mon = _ACTIVE
    if mon is None:
        raise RuntimeError(
            "partial_preempt fault fired with no active HealthMonitor — "
            "enable train.health (the fault models a peer loss the monitor "
            "must detect)"
        )
    mon.simulate_loss(host)


def simulate_rejoin(host: int, flaky: bool = False) -> None:
    """Module-level chaos entry point for the ``host_rejoin`` (and, with
    ``flaky=True``, ``host_rejoin_flaky``) fault kinds."""
    mon = _ACTIVE
    if mon is None:
        raise RuntimeError(
            "host_rejoin fault fired with no active HealthMonitor — enable "
            "train.health (the fault models a recovered host the monitor "
            "must re-admit)"
        )
    mon.simulate_recovery(host, flaky=flaky)


def attempt_rejoin(
    monitor: HealthMonitor,
    host: int,
    generation: int,
    policy=None,
    sleep: Callable[[float], None] = time.sleep,
) -> dict:
    """Validate one announced rejoiner under the budgeted-retry policy.

    A refusal is often transient (the recovered host's first heartbeat may
    land a beat after its marker), so validation retries under the same
    seeded/budgeted backoff used for checkpoint I/O. Returns the validated
    marker on success; once the policy's attempts or sleep budget are
    exhausted the final :class:`RejoinRefused` propagates and the caller
    keeps the degraded membership untouched — never a second outage.
    Feeds ``resilience.regrow.{attempts,refused}``.
    """
    from cst_captioning_tpu.resilience.retry import RetryPolicy, retry_call

    obs.counter("resilience.regrow.attempts").inc()
    if policy is None:
        policy = RetryPolicy(
            max_attempts=2,
            base_delay=monitor.interval_s,
            max_delay=monitor.timeout_s,
            budget=monitor.timeout_s,
            retry_on=(RejoinRefused, OSError),
        )

    def on_retry(info: dict) -> None:
        monitor.log("rejoin_retry", host=host, attempt=info["attempt"],
                    delay=info["delay"], error=info["error"])

    try:
        return retry_call(monitor.validate_rejoin, host, generation,
                          policy=policy, on_retry=on_retry, sleep=sleep)
    except RejoinRefused:
        obs.counter("resilience.regrow.refused").inc()
        raise


def _write_rendezvous_marker(dir: str, generation: int, host_id: int) -> str:
    """Check one host into a generation directory (atomic publish). Returns
    the directory path. Shared by :func:`rendezvous` and the ``host_rejoin``
    chaos hook (which checks in on a recovered phantom's behalf)."""
    rdir = os.path.join(dir, f"rendezvous_{int(generation):04d}")
    os.makedirs(rdir, exist_ok=True)
    _publish_json(
        os.path.join(rdir, f"host{host_id}.json"),
        {"host": host_id, "ts": time.time()},  # graftlint: disable=GL010 (rendezvous marker wall-clock payload)
    )
    return rdir


def rendezvous(
    dir: str,
    host_id: int,
    hosts: Iterable[int],
    generation: int = 0,
    timeout_s: float = 30.0,
    poll_s: float = 0.05,
    backoff: float = 1.5,
    max_poll_s: float = 1.0,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
) -> list[int]:
    """Survivor rendezvous: block until every host in ``hosts`` checked into
    the generation directory, with exponential-backoff polling.

    Each caller writes ``<dir>/rendezvous_<generation>/host<k>.json`` and
    polls for the others. Returns the sorted membership on success; raises
    :class:`RendezvousTimeout` naming the missing hosts otherwise (the
    caller's strict fallback: abort and full-restart).
    """
    expected = sorted(int(h) for h in hosts)
    rdir = _write_rendezvous_marker(dir, generation, host_id)
    t0 = clock()
    delay = poll_s
    while True:
        present = [
            h for h in expected
            if os.path.exists(os.path.join(rdir, f"host{h}.json"))
        ]
        if len(present) == len(expected):
            obs.event("rendezvous", generation=generation, hosts=present)
            return present
        if clock() - t0 > timeout_s:
            missing = sorted(set(expected) - set(present))
            raise RendezvousTimeout(
                f"rendezvous generation {generation} timed out after "
                f"{timeout_s}s: hosts {missing} never checked in "
                f"(present: {present})"
            )
        sleep(delay)
        delay = min(delay * backoff, max_poll_s)
