import numpy as np

from cst_captioning_tpu.metrics.meteor import MeteorApprox, _porter_stem
from cst_captioning_tpu.metrics.scorer import CaptionScorer, score_captions


def toks(s):
    return s.split()


def test_stemmer_basics():
    assert _porter_stem("running") == "run"
    assert _porter_stem("plays") == "plai"  # y->i after step 1c on "play"
    assert _porter_stem("played") == "plai"
    assert _porter_stem("cats") == "cat"


def test_meteor_perfect_match_is_high():
    m = MeteorApprox()
    s = m.sentence_score(toks("a man rides a horse"), [toks("a man rides a horse")])
    # perfect alignment: P=R=1 -> F=1, one chunk over 5 matches -> small penalty
    frag = 1.0 / 5.0
    expected = 1.0 - 0.6 * frag**3
    np.testing.assert_allclose(s, expected, atol=1e-9)


def test_meteor_stem_stage_matches():
    m = MeteorApprox()
    s_exact = m.sentence_score(toks("dog runs"), [toks("dog runs")])
    s_stem = m.sentence_score(toks("dog running"), [toks("dog runs")])
    assert 0 < s_stem <= s_exact


def test_meteor_disjoint_zero():
    assert MeteorApprox().sentence_score(toks("a b"), [toks("x y")]) == 0.0


def test_scorer_full_table():
    gts = {
        "v1": ["a man is playing a guitar", "someone plays guitar"],
        "v2": ["a cat sits on a mat"],
    }
    res = {"v1": ["a man is playing a guitar"], "v2": ["a dog runs"]}
    table = score_captions(gts, res)
    for k in ("Bleu_1", "Bleu_4", "ROUGE_L", "METEOR_approx", "CIDEr", "CIDEr-D"):
        assert k in table, k
    assert table["Bleu_1"] > 0.5
    assert 0 <= table["CIDEr-D"] <= 10


def test_scorer_pre_tokenized():
    gts = {"v": [["a", "dog", "runs", "fast"]]}
    res = {"v": [["a", "dog", "runs", "fast"]]}
    table = CaptionScorer(metrics=("CIDEr-D",), pre_tokenized=True).score(gts, res)
    np.testing.assert_allclose(table["CIDEr-D"], 10.0, atol=1e-9)


def test_scorer_details_per_id():
    gts = {"v1": ["a b c d"], "v2": ["a b c d"]}
    res = {"v1": ["a b c d"], "v2": ["x y z w"]}
    table, per_id = CaptionScorer(metrics=("CIDEr-D",)).score_with_details(gts, res)
    np.testing.assert_allclose(per_id["CIDEr-D"], [10.0, 0.0], atol=1e-9)
