"""Pallas fused additive-attention parity (ops/attention_pallas.py).

Off-TPU these run the kernel in Pallas interpret mode — the same kernel
code path the TPU compiles through Mosaic (compiled parity at B=64/M=4096
was verified on a real v5e chip).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config.config import ModelConfig
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.ops.attention_pallas import (
    _reference,
    fused_additive_attention,
)


def _inputs(B, M, E, D, seed=0, full_mask_row=None):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(D,)), jnp.float32)
    memory = jnp.asarray(rng.normal(size=(B, M, E)), jnp.float32)
    proj = jnp.asarray(rng.normal(size=(B, M, D)), jnp.float32)
    mask = jnp.asarray(
        np.arange(M)[None, :] < rng.integers(1, M + 1, size=(B, 1)),
        jnp.float32,
    )
    if full_mask_row is not None:
        mask = mask.at[full_mask_row].set(0.0)
    # dataset semantics: padded frames carry zero features
    memory = memory * mask[:, :, None]
    return q, v, memory, proj, mask


@pytest.mark.parametrize(
    "B,M", [(5, 200), (8, 128), (3, 7), (16, 300)],
)
def test_fused_attention_matches_composite(B, M):
    """Odd shapes spanning block boundaries, ragged masks, and a
    fully-masked row (which must yield the same uniform-softmax result,
    not NaN)."""
    args = _inputs(B, M, E=24, D=16, full_mask_row=min(2, B - 1))
    want = _reference(*args)
    got = fused_additive_attention(*args)
    assert not np.isnan(np.asarray(got)).any()
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
    )


def test_fused_attention_gradients_match():
    """The custom-vjp backward (XLA recompute) produces the composite's
    gradients for every differentiable input."""
    args = _inputs(6, 150, E=20, D=12, seed=3)

    def loss(f):
        return lambda *a: jnp.sum(f(*a) ** 2)

    g_ref = jax.grad(loss(_reference), argnums=(0, 1, 2, 3))(*args)
    g_ker = jax.grad(loss(fused_additive_attention), argnums=(0, 1, 2, 3))(*args)
    for a, b in zip(g_ref, g_ker):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5
        )


def test_model_attention_impl_pallas_matches_xla():
    """ModelConfig.attention_impl='pallas' produces the same teacher-forced
    logits and greedy captions as the XLA composite, sharing one parameter
    tree (the score/query/mem_proj params are layout-identical)."""
    from cst_captioning_tpu.decoding import greedy_decode

    V, B, F, T = 20, 4, 12, 6
    base = ModelConfig(
        vocab_size=V, modalities=(("resnet", 10),), d_embed=12, d_hidden=12,
        d_att=8, encoder="temporal_attention", dropout=0.0, max_len=T,
        max_frames=F, dtype="float32",
    )
    rng = np.random.default_rng(1)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 10)), jnp.float32)}
    masks = {
        "resnet": jnp.asarray(
            np.arange(F)[None, :] < rng.integers(3, F + 1, size=(B, 1)),
            jnp.float32,
        )
    }
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)

    m_xla = CaptionModel(base)
    m_pal = CaptionModel(dataclasses.replace(base, attention_impl="pallas"))
    params = m_xla.init(jax.random.key(0), feats, masks, labels)
    # identical parameter trees: the pallas path creates the same params
    params2 = m_pal.init(jax.random.key(0), feats, masks, labels)
    assert jax.tree_util.tree_structure(params) == jax.tree_util.tree_structure(params2)

    logits_x = m_xla.apply(params, feats, masks, labels)
    logits_p = m_pal.apply(params, feats, masks, labels)
    np.testing.assert_allclose(
        np.asarray(logits_p), np.asarray(logits_x), rtol=2e-4, atol=2e-5
    )
    tok_x, _ = greedy_decode(m_xla, params, feats, masks, max_len=T)
    tok_p, _ = greedy_decode(m_pal, params, feats, masks, max_len=T)
    np.testing.assert_array_equal(np.asarray(tok_p), np.asarray(tok_x))
