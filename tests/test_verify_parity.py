"""Smoke test: the parity runbook's dry-run path executes end-to-end.

scripts/verify_parity.py is the one-command resolution of the #1
environmental blocker (absolute parity vs the reference — VERDICT r4 next
#6); this pins that the runbook itself works TODAY on the synthetic corpus,
so the day the reference/data appear only the inputs change.
"""

import importlib.util
import json
import os
import sys

import pytest

_SCRIPT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "scripts", "verify_parity.py",
)


def _load():
    spec = importlib.util.spec_from_file_location("verify_parity", _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_dry_run_end_to_end(tmp_path):
    vp = _load()
    report_path = tmp_path / "report.json"
    rc = vp.main([
        "--dry-run",
        "--reference", str(tmp_path / "empty_ref"),
        "--workdir", str(tmp_path / "work"),
        "--xe-epochs", "2", "--rl-epochs", "1",
        "--json", str(report_path),
    ])
    # rc 1 only means the tiny run missed the internal gate, not a failure
    assert rc in (0, 1)
    report = json.loads(report_path.read_text())
    assert "unreadable" in report["reference"]["status"] \
        or "EMPTY" in report["reference"]["status"]
    pipe = report["pipeline"]
    assert pipe["mode"] == "dry_run_synthetic"
    for stage in ("xe_test_metrics", "cst_test_metrics"):
        assert "CIDEr-D" in pipe[stage]
    assert "internal_gate_cst_beats_xe" in report["verdict"]


def test_reference_readout_on_populated_tree(tmp_path):
    """A fake 'reference' tree: LoC counted (tests excluded), metric rows
    greppable, BASELINE.md untouched without --update-baseline."""
    vp = _load()
    ref = tmp_path / "ref"
    (ref / "tests").mkdir(parents=True)
    (ref / "model.py").write_text("import torch\n" * 40)
    (ref / "tests" / "test_model.py").write_text("assert True\n" * 99)
    (ref / "README.md").write_text(
        "# results\n\n| model | CIDEr |\n|---|---|\n| CST | 0.542 |\n"
    )
    out = vp.read_reference(str(ref), update_baseline=False)
    assert out["status"] == "readable"
    assert out["loc_non_test"] == 40
    assert any("0.542" in r["line"] for r in out["metric_rows"])
