"""Checkpoint + evaluator tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ckpt import CheckpointManager, load_params, load_state, save_state
from cst_captioning_tpu.config.config import EvalConfig, ModelConfig, TrainConfig
from cst_captioning_tpu.data import CaptionDataset, make_synthetic_dataset
from cst_captioning_tpu.eval import Evaluator
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.train import create_train_state, make_optimizer


@pytest.fixture(scope="module")
def state_setup():
    cfg = ModelConfig(
        vocab_size=12, modalities=(("resnet", 6),), d_embed=8, d_hidden=8,
        d_att=4, encoder="meanpool", max_len=5, max_frames=3, dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(2, 3, 6)), jnp.float32)}
    masks = {"resnet": jnp.ones((2, 3), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, 12, size=(2, 5)), jnp.int32)
    tx = make_optimizer(TrainConfig(lr=1e-3), 10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=0)
    return model, state


def _params_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_save_load_roundtrip(state_setup, tmp_path):
    model, state = state_setup
    save_state(str(tmp_path), "latest", state, {"epoch": 3})
    restored, infos = load_state(str(tmp_path), "latest", state)
    assert infos["epoch"] == 3
    _params_equal(state.params, restored.params)
    assert int(restored.step) == int(state.step)


def test_load_params_only(state_setup, tmp_path):
    model, state = state_setup
    save_state(str(tmp_path), "best", state)
    params = load_params(str(tmp_path), "best", jax.device_get(state.params))
    _params_equal(state.params, params)


def test_checkpoint_manager_best_policy(state_setup, tmp_path):
    model, state = state_setup
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.save(state, value=0.30) is True      # first -> best
    assert mgr.save(state, value=0.20) is False     # worse
    assert mgr.save(state, value=0.45) is True      # better
    assert mgr.save(state, value=None) is False     # no metric -> latest only
    # fresh manager recovers best_value from disk
    mgr2 = CheckpointManager(str(tmp_path))
    assert mgr2.best_value == pytest.approx(0.45)
    assert mgr2.save(state, value=0.40) is False
    restored = mgr2.restore_latest(jax.device_get(state))
    assert restored is not None


def test_checkpoint_manager_recovers_from_corrupt_latest(state_setup, tmp_path):
    import os

    model, state = state_setup
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(state, value=0.5)
    # corrupt 'latest'; restore must fall back to 'best'
    with open(os.path.join(str(tmp_path), "latest", "state.msgpack"), "wb") as f:
        f.write(b"garbage")
    restored = mgr.restore_latest(jax.device_get(state))
    assert restored is not None
    _params_equal(state.params, restored[0].params)


@pytest.fixture(scope="module")
def eval_setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("evalsynth")
    paths = make_synthetic_dataset(
        str(out), num_videos=12, modalities={"resnet": 16}, max_frames=4, seed=2
    )
    ds = CaptionDataset(paths["info_json"], {"resnet": paths["resnet"]}, "test", 4)
    cfg = ModelConfig(
        vocab_size=len(ds.vocab), modalities=(("resnet", 16),), d_embed=12,
        d_hidden=12, d_att=6, encoder="temporal_attention", max_len=8,
        max_frames=4, dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(1)
    feats = {"resnet": jnp.asarray(rng.normal(size=(2, 4, 16)), jnp.float32)}
    masks = {"resnet": jnp.ones((2, 4), jnp.float32)}
    labels = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.key(0), feats, masks, labels)
    return model, params, ds


def test_evaluator_generates_all_videos(eval_setup):
    model, params, ds = eval_setup
    ev = Evaluator(model, ds, EvalConfig(beam_size=3, max_len=8), batch_size=5)
    caps = ev.generate(params)
    assert sorted(caps) == sorted(r.video_id for r in ds.records)
    assert all(isinstance(c, str) for c in caps.values())


def test_evaluator_full_metric_table(eval_setup, tmp_path):
    model, params, ds = eval_setup
    ev = Evaluator(model, ds, EvalConfig(beam_size=2, max_len=8), batch_size=5)
    result = ev.evaluate(params, results_json=str(tmp_path / "res.json"))
    m = result["metrics"]
    for key in ("Bleu_4", "ROUGE_L", "METEOR_approx", "CIDEr", "CIDEr-D"):
        assert key in m, f"missing metric {key}"
        assert np.isfinite(m[key])
    assert (tmp_path / "res.json").exists()
    # untrained model on synthetic data: scores exist but are low
    assert 0.0 <= m["Bleu_4"] <= 1.0


@pytest.mark.parametrize("beam", [1, 3])
def test_evaluator_mesh_matches_single_device(eval_setup, beam):
    """Sharded eval (8 fake devices) must produce the exact same captions."""
    from cst_captioning_tpu.train import make_mesh, replicate

    model, params, ds = eval_setup
    cfg = EvalConfig(beam_size=beam, max_len=8)
    single = Evaluator(model, ds, cfg, batch_size=8).generate(params)
    mesh = make_mesh()
    sharded = Evaluator(model, ds, cfg, batch_size=8, mesh=mesh).generate(
        replicate(mesh, params)
    )
    assert sharded == single


def test_evaluator_mesh_pads_indivisible_batch(eval_setup):
    """batch_size=5 on 8 devices wrap-pads to 8 and still produces the EXACT
    single-device captions (VERDICT r2 next #5: no error, no silent
    single-chip fallback)."""
    from cst_captioning_tpu.train import make_mesh, replicate

    model, params, ds = eval_setup
    cfg = EvalConfig(beam_size=1, max_len=8)
    single = Evaluator(model, ds, cfg, batch_size=5).generate(params)
    mesh = make_mesh()
    ev = Evaluator(model, ds, cfg, batch_size=5, mesh=mesh)
    assert ev.batcher.batch_size == 8  # rounded up to the device multiple
    assert ev.generate(replicate(mesh, params)) == single
