"""Eval fast-path tests: the two-stage decode/score pipeline is bit-identical
to the serial evaluator (metric table AND captions), the overlap ledger is
recorded, and the NPAD eval mode runs end to end."""

import json

import jax
import numpy as np
import pytest

from cst_captioning_tpu import obs
from cst_captioning_tpu.config.config import EvalConfig, ModelConfig
from cst_captioning_tpu.data.batcher import Batcher
from cst_captioning_tpu.data.dataset import CaptionDataset
from cst_captioning_tpu.data.synthetic import make_synthetic_dataset
from cst_captioning_tpu.eval.evaluator import Evaluator
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.train.steps import batch_arrays


@pytest.fixture(scope="module")
def eval_setup(tmp_path_factory):
    out = tmp_path_factory.mktemp("evalpipe")
    paths = make_synthetic_dataset(
        str(out), num_videos=12, modalities={"resnet": 16}, max_frames=4,
        seed=2,
    )
    ds = CaptionDataset(
        paths["info_json"], {"resnet": paths["resnet"]}, "test", 4
    )
    cfg = ModelConfig(
        vocab_size=len(ds.vocab), modalities=(("resnet", 16),), d_embed=12,
        d_hidden=12, d_att=6, encoder="temporal_attention", max_len=8,
        max_frames=4, dtype="float32",
    )
    model = CaptionModel(cfg)
    train_ds = CaptionDataset(
        paths["info_json"], {"resnet": paths["resnet"]}, "train", 4
    )
    batch = next(iter(
        Batcher(train_ds, batch_size=4, max_len=8).epoch(shuffle=False)
    ))
    feats, masks, labels, *_ = batch_arrays(batch)
    params = model.init(jax.random.key(0), feats, masks, labels)
    return model, params, ds


def test_pipelined_matches_serial_bit_identical(eval_setup):
    """The tentpole contract: the pipelined evaluator's captions (content
    AND dict order) and metric table are bit-identical to the serial
    path's — overlap changes WHEN tokenization runs, never its result.
    Compared through json.dumps so any float drift in any metric fails."""
    model, params, ds = eval_setup
    serial = Evaluator(
        model, ds, EvalConfig(beam_size=3, max_len=8, pipelined=False),
        batch_size=5,
    ).evaluate(params)
    piped = Evaluator(
        model, ds,
        EvalConfig(beam_size=3, max_len=8, pipelined=True, score_workers=3),
        batch_size=5,
    ).evaluate(params)
    assert list(piped["captions"]) == list(serial["captions"])
    assert piped["captions"] == serial["captions"]
    assert json.dumps(piped["metrics"], sort_keys=True) == json.dumps(
        serial["metrics"], sort_keys=True
    )


def test_pipelined_beam_reference_impl_matches_lanes(eval_setup):
    """cfg.beam_impl="reference" routes the sequential oracle through the
    same evaluator — identical captions (the lane/reference bit-parity
    contract, observed at the eval surface)."""
    model, params, ds = eval_setup
    lanes = Evaluator(
        model, ds, EvalConfig(beam_size=3, max_len=8), batch_size=5
    ).evaluate(params)
    ref = Evaluator(
        model, ds,
        EvalConfig(beam_size=3, max_len=8, beam_impl="reference"),
        batch_size=5,
    ).evaluate(params)
    assert ref["captions"] == lanes["captions"]


def test_pipelined_records_overlap_ledger(eval_setup, tmp_path):
    """A pipelined eval leaves the obs ledger behind: stage histograms,
    overlap gauges, fill/drain spans — and cli.obs_report's builder
    surfaces them as the eval section."""
    from cst_captioning_tpu.obs.report import build_report, load_events

    model, params, ds = eval_setup
    run_dir = str(tmp_path / "run")
    obs.REGISTRY.reset()  # counters are cumulative; isolate this run
    obs.configure(run_dir, run="evalpipe")
    try:
        Evaluator(
            model, ds, EvalConfig(beam_size=2, max_len=8), batch_size=5
        ).evaluate(params)
    finally:
        obs.shutdown()
        obs.REGISTRY.reset()
    rep = build_report(load_events(run_dir))
    ev = rep["eval"]
    assert ev is not None
    assert ev["batches"] >= 1
    assert ev["captions"] == len(ds.records)
    assert ev["decode_total_s"] > 0.0 and ev["score_total_s"] > 0.0
    assert 0.0 <= ev["overlap_fraction"] <= 1.0
    assert 0.0 <= ev["overlap_efficiency"] <= 1.0
    names = {p["phase"] for p in rep["phases"]} | {
        p["phase"] for p in rep["overlap"]
    }
    assert "eval.pipeline.fill" in names
    assert "eval.pipeline.drain" in names


def test_npad_eval_mode_end_to_end(eval_setup):
    """cfg.npad_lanes switches the evaluator to NPAD anytime decoding:
    every split video still gets a caption and the metric table is
    finite — and the run is deterministic (the per-batch rng is
    fold_in(key(npad_seed), batch_index), carrying no mutable state, so
    a repeat evaluate — pipeline thread timing and all — reproduces the
    captions exactly)."""
    model, params, ds = eval_setup
    cfg = EvalConfig(
        beam_size=1, max_len=8, npad_lanes=3, npad_temperature=1.0,
        npad_seed=7,
    )
    ev = Evaluator(model, ds, cfg, batch_size=5)
    r1 = ev.evaluate(params)
    r2 = ev.evaluate(params)
    assert set(r1["captions"]) == {r.video_id for r in ds.records}
    assert r1["captions"] == r2["captions"]
    assert all(np.isfinite(v) for v in r1["metrics"].values())


def test_eval_config_validation():
    with pytest.raises(ValueError, match="beam_impl"):
        EvalConfig(beam_impl="bogus")
    with pytest.raises(ValueError, match="npad_lanes"):
        EvalConfig(npad_lanes=-1)
    with pytest.raises(ValueError, match="npad_temperature"):
        EvalConfig(npad_lanes=2, npad_temperature=0.0)
    with pytest.raises(ValueError, match="score_workers"):
        EvalConfig(score_workers=0)
