"""The paper's two-stage recipe through the REAL CLIs (SURVEY.md §3.5):

    stage 1: WXE (consensus-weighted cross-entropy) training
    stage 2: CST fine-tune from the stage-1 checkpoint (rl.init_from)
    then:    beam eval of the fine-tuned checkpoint

Covers the two paths nothing else exercises end-to-end: ``train.loss='wxe'``
through the Trainer and the ``--skip-xe`` + ``rl__init_from`` handoff.
"""

import json
import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def recipe_data(tmp_path_factory):
    from cst_captioning_tpu.data import make_synthetic_dataset
    from cst_captioning_tpu.data.preprocess import compute_consensus_weights

    root = tmp_path_factory.mktemp("recipe")
    paths = make_synthetic_dataset(
        str(root), num_videos=16, num_topics=3, vocab_words=20,
        modalities={"resnet": 12}, max_frames=4, seed=13,
    )
    info = json.load(open(paths["info_json"]))
    tok = {
        v["id"]: [c.split() for c in v["captions"]]
        for v in info["videos"] if v["split"] == "train"
    }
    weights = compute_consensus_weights(tok)
    w_path = str(root / "consensus_weights.npz")
    np.savez(w_path, **weights)
    paths["consensus_weights"] = w_path
    # info['vocab'] already includes the 4 special tokens
    paths["vocab_size"] = len(info["vocab"])
    return paths


def _common(paths):
    return [
        "--info-json", paths["info_json"],
        "--feature", f"resnet={paths['resnet']}",
        "--set", f"model__vocab_size={paths['vocab_size']}",
        "--set", "model__modalities=(('resnet',12),)",
        "--set", "model__d_embed=12", "--set", "model__d_hidden=12",
        "--set", "model__d_att=8", "--set", "model__max_len=8",
        "--set", "model__max_frames=4", "--set", "model__dtype='float32'",
        "--set", "data__batch_size=8", "--set", "data__seq_per_vid=3",
    ]


def test_two_stage_recipe_via_clis(recipe_data, tmp_path):
    from cst_captioning_tpu.cli.eval import main as eval_main
    from cst_captioning_tpu.cli.train import main as train_main

    xe_ckpt = str(tmp_path / "xe")
    rl_ckpt = str(tmp_path / "rl")
    log1 = str(tmp_path / "stage1.jsonl")
    log2 = str(tmp_path / "stage2.jsonl")

    # stage 1: consensus-weighted XE
    train_main([
        "--preset", "msrvtt_xe_attention", *_common(recipe_data),
        "--set", "train__loss='wxe'", "--set", "train__lr=5e-3",
        "--set", f"data__consensus_weights='{recipe_data['consensus_weights']}'",
        "--set", "train__epochs=3", "--set", "train__eval_every_epochs=3",
        "--log-jsonl", log1,
        "--set", f"train__ckpt_dir='{xe_ckpt}'",
    ])
    ev1 = [json.loads(l) for l in open(log1)]
    xe_losses = [e["loss"] for e in ev1 if e["event"] == "xe_epoch"]
    assert len(xe_losses) == 3 and xe_losses[-1] < xe_losses[0]
    assert os.path.exists(os.path.join(xe_ckpt, "best", "state.msgpack"))

    # stage 2: CST fine-tune FROM the stage-1 best checkpoint, RL only
    train_main([
        "--preset", "msrvtt_scst", *_common(recipe_data), "--skip-xe",
        "--set", f"rl__init_from='{xe_ckpt}'",
        "--set", "rl__epochs=2", "--set", "rl__num_rollouts=3",
        "--set", "train__eval_every_epochs=1",
        "--log-jsonl", log2,
        "--set", f"train__ckpt_dir='{rl_ckpt}'",
    ])
    ev2 = [json.loads(l) for l in open(log2)]
    assert [e for e in ev2 if e["event"] == "handoff"], "no XE->RL handoff"
    rl = [e for e in ev2 if e["event"] == "rl_epoch"]
    assert len(rl) == 2 and all(np.isfinite(e["reward"]) for e in rl)
    assert os.path.exists(os.path.join(rl_ckpt, "latest", "state.msgpack"))

    # eval the fine-tuned checkpoint with beam search
    res = str(tmp_path / "results.json")
    eval_main([
        "--preset", "msrvtt_eval_beam5", *_common(recipe_data),
        "--ckpt-dir", rl_ckpt, "--ckpt-name", "latest", "--split", "test",
        "--set", "eval__beam_size=3", "--set", "eval__max_len=8",
        "--results-json", res,
    ])
    result = json.load(open(res))
    assert result["captions"] and np.isfinite(result["metrics"]["CIDEr-D"])
