"""Training-layer tests, incl. the 8-fake-device DP equivalence (SURVEY §4.4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config.config import ModelConfig, TrainConfig
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.train import (
    create_train_state,
    make_mesh,
    make_optimizer,
    make_parallel_xe_step,
    make_xe_step,
    replicate,
    shard_batch,
)

B, F, T, V = 16, 4, 6, 17  # B divisible by 8 fake devices


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig(
        vocab_size=V,
        modalities=(("resnet", 8),),
        d_embed=12,
        d_hidden=12,
        d_att=6,
        encoder="temporal_attention",
        dropout=0.0,  # determinism for the DP-equivalence check
        max_len=T,
        max_frames=F,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 8)), jnp.float32)}
    masks = {"resnet": jnp.ones((B, F), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)
    mask = jnp.ones((B, T), jnp.float32)
    # ragged masks: rows end at different lengths (exercises normalization)
    mask_np = np.ones((B, T), np.float32)
    for i in range(B):
        mask_np[i, 2 + (i % 4):] = 0.0
    mask = jnp.asarray(mask_np)
    weights = jnp.asarray(rng.uniform(0.5, 1.5, size=(B,)), jnp.float32)
    tx = make_optimizer(TrainConfig(lr=1e-3, grad_clip=1.0), steps_per_epoch=10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=0)
    return model, state, (feats, masks, labels, mask, weights)


def test_single_device_step_decreases_loss(setup):
    model, state, batch = setup
    step = make_xe_step(model)
    losses = []
    for _ in range(8):
        state, m = step(state, *batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 8
    assert np.isfinite(losses).all()


def test_parallel_step_matches_single_device(setup):
    """psum-DP grads over 8 devices == single-device grads on the full batch."""
    model, state0, batch = setup
    assert len(jax.devices()) == 8, "conftest must provide 8 fake CPU devices"
    mesh = make_mesh()

    single = make_xe_step(model)
    parallel = make_parallel_xe_step(model, mesh)

    s_state, s_metrics = single(state0, *batch)

    p_state = replicate(mesh, state0)
    p_batch = shard_batch(mesh, batch)
    p_state, p_metrics = parallel(p_state, *p_batch)

    np.testing.assert_allclose(
        float(s_metrics["loss"]), float(p_metrics["loss"]), rtol=1e-5
    )
    # updated params identical (up to float assoc in psum ordering)
    flat_s = jax.tree_util.tree_leaves(s_state.params)
    flat_p = jax.tree_util.tree_leaves(p_state.params)
    # psum reassociation perturbs grads at float32 eps; Adam's rsqrt amplifies
    # that on near-zero second moments, so compare at 1e-3 not exact-bit level
    for a, b in zip(flat_s, flat_p):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5)


def test_parallel_step_runs_multiple_steps(setup):
    model, state0, batch = setup
    mesh = make_mesh()
    parallel = make_parallel_xe_step(model, mesh)
    state = replicate(mesh, state0)
    pb = shard_batch(mesh, batch)
    losses = []
    for _ in range(5):
        state, m = parallel(state, *pb)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_lr_schedule_decay():
    from cst_captioning_tpu.train import make_lr_schedule

    cfg = TrainConfig(lr=1e-2, lr_decay=0.5, lr_decay_every=2)
    sched = make_lr_schedule(cfg, steps_per_epoch=10)
    assert float(sched(0)) == pytest.approx(1e-2)
    assert float(sched(19)) == pytest.approx(1e-2)
    assert float(sched(20)) == pytest.approx(5e-3)
    assert float(sched(40)) == pytest.approx(2.5e-3)
    const = make_lr_schedule(TrainConfig(lr=1e-3, lr_decay_every=0), 10)
    assert float(const(1000)) == pytest.approx(1e-3)


def test_make_optimizer_rejects_unknown():
    with pytest.raises(ValueError, match="unknown optimizer"):
        make_optimizer(TrainConfig(optimizer="adagrad"), 1)


def test_weighted_step_uses_weights(setup):
    """Zeroing a row's weight must change the computed loss."""
    model, state, (feats, masks, labels, mask, weights) = setup
    step = make_xe_step(model)
    _, m1 = step(state, feats, masks, labels, mask, weights)
    w2 = weights.at[0].set(0.0)
    _, m2 = step(state, feats, masks, labels, mask, w2)
    assert float(m1["loss"]) != float(m2["loss"])
