"""Model layer tests: shapes, unroll consistency, dtype, losses."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config.config import BOS_ID, ModelConfig
from cst_captioning_tpu.losses import (
    masked_cross_entropy,
    reinforce_loss,
    sequence_log_probs,
)
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.models.captioner import shift_right

B, F1, F2, T, V = 3, 5, 4, 7, 23


def tiny_cfg(encoder="temporal_attention", num_layers=1, dtype="float32"):
    return ModelConfig(
        vocab_size=V,
        modalities=(("resnet", 12), ("c3d", 6)),
        d_embed=16,
        d_hidden=16,
        d_att=8,
        encoder=encoder,
        num_layers=num_layers,
        dropout=0.3,
        max_len=T,
        max_frames=F1,
        dtype=dtype,
    )


def make_batch(seed=0):
    rng = np.random.default_rng(seed)
    feats = {
        "resnet": jnp.asarray(rng.normal(size=(B, F1, 12)), jnp.float32),
        "c3d": jnp.asarray(rng.normal(size=(B, F2, 6)), jnp.float32),
    }
    masks = {"c3d": jnp.ones((B, F2), jnp.float32)}
    # per-row frame masks with differing lengths
    m = np.zeros((B, F1), np.float32)
    for i, n in enumerate([3, 5, 2][:B]):
        m[i, :n] = 1
    masks["resnet"] = jnp.asarray(m)
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)
    return feats, masks, labels


@pytest.mark.parametrize("encoder", ["meanpool", "temporal_attention"])
@pytest.mark.parametrize("num_layers", [1, 2])
def test_forward_shapes(encoder, num_layers):
    cfg = tiny_cfg(encoder, num_layers)
    model = CaptionModel(cfg)
    feats, masks, labels = make_batch()
    params = model.init(jax.random.key(0), feats, masks, labels)
    logits = model.apply(params, feats, masks, labels)
    assert logits.shape == (B, T, V)
    assert logits.dtype == jnp.float32
    enc = model.apply(params, feats, masks, method=CaptionModel.encode)
    expected_M = 2 if encoder == "meanpool" else F1 + F2
    assert enc.memory.shape == (B, expected_M, cfg.d_embed)
    assert len(enc.carry) == num_layers


@pytest.mark.parametrize("encoder", ["meanpool", "temporal_attention"])
def test_unroll_consistency(encoder):
    """Teacher-forced scan logits == step-by-step decode_step logits."""
    cfg = tiny_cfg(encoder)
    model = CaptionModel(cfg)
    feats, masks, labels = make_batch(1)
    params = model.init(jax.random.key(0), feats, masks, labels)
    logits_scan = model.apply(params, feats, masks, labels)

    enc = model.apply(params, feats, masks, method=CaptionModel.encode)
    inputs = shift_right(labels)
    carry = enc.carry
    per_step = []
    for t in range(T):
        carry, lg = model.apply(
            params, carry, inputs[:, t], enc, method=CaptionModel.decode_step
        )
        per_step.append(lg)
    logits_step = jnp.stack(per_step, axis=1)
    np.testing.assert_allclose(logits_scan, logits_step, rtol=1e-5, atol=1e-5)


def test_memory_mask_blocks_padded_frames():
    """Changing features under masked-out frames must not change logits."""
    cfg = tiny_cfg("temporal_attention")
    model = CaptionModel(cfg)
    feats, masks, labels = make_batch(2)
    params = model.init(jax.random.key(0), feats, masks, labels)
    out1 = model.apply(params, feats, masks, labels)
    feats2 = dict(feats)
    noise = np.array(feats["resnet"])
    noise[np.array(masks["resnet"]) == 0] = 99.0
    feats2["resnet"] = jnp.asarray(noise)
    out2 = model.apply(params, feats2, masks, labels)
    np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-5)


def test_dropout_rng_and_determinism():
    cfg = tiny_cfg()
    model = CaptionModel(cfg)
    feats, masks, labels = make_batch(3)
    params = model.init(jax.random.key(0), feats, masks, labels)
    d1 = model.apply(params, feats, masks, labels, train=True,
                     rngs={"dropout": jax.random.key(1)})
    d2 = model.apply(params, feats, masks, labels, train=True,
                     rngs={"dropout": jax.random.key(2)})
    assert not np.allclose(d1, d2)  # dropout active and rng-dependent
    e1 = model.apply(params, feats, masks, labels)
    e2 = model.apply(params, feats, masks, labels)
    np.testing.assert_array_equal(e1, e2)  # eval mode deterministic


def test_bfloat16_compute_path():
    cfg = tiny_cfg(dtype="bfloat16")
    model = CaptionModel(cfg)
    feats, masks, labels = make_batch(4)
    params = model.init(jax.random.key(0), feats, masks, labels)
    # params stay f32, logits come back f32, no NaNs
    flat = jax.tree_util.tree_leaves(params)
    assert all(p.dtype == jnp.float32 for p in flat)
    logits = model.apply(params, feats, masks, labels)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.array(logits)).all()


def test_shift_right():
    labels = jnp.asarray([[5, 6, 2, 0]], jnp.int32)
    np.testing.assert_array_equal(shift_right(labels), [[BOS_ID, 5, 6, 2]])


# ---- losses ----------------------------------------------------------------


def test_masked_xe_matches_manual():
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(2, 3, 5)), jnp.float32)
    labels = jnp.asarray([[1, 2, 0], [3, 2, 4]], jnp.int32)
    mask = jnp.asarray([[1, 1, 0], [1, 1, 1]], jnp.float32)
    got = masked_cross_entropy(logits, labels, mask)
    logp = np.asarray(jax.nn.log_softmax(logits, -1))
    manual = 0.0
    for b in range(2):
        for t in range(3):
            if mask[b, t]:
                manual -= logp[b, t, labels[b, t]]
    np.testing.assert_allclose(got, manual / 5.0, rtol=1e-6)


def test_weighted_xe_scales_rows():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, 3, 5)), jnp.float32)
    labels = jnp.asarray([[1, 2, 2], [3, 2, 4]], jnp.int32)
    mask = jnp.ones((2, 3), jnp.float32)
    w = jnp.asarray([2.0, 0.0])
    got = masked_cross_entropy(logits, labels, mask, weights=w)
    # only row 0 contributes; weight cancels in numerator/denominator scaling
    row0 = masked_cross_entropy(logits[:1], labels[:1], mask[:1])
    np.testing.assert_allclose(got, row0, rtol=1e-6)


def test_reinforce_loss_sign_and_grad():
    """Positive advantage must push sampled-token logprobs up."""
    logits = jnp.zeros((1, 2, 4), jnp.float32)
    tokens = jnp.asarray([[1, 2]], jnp.int32)
    mask = jnp.ones((1, 2), jnp.float32)

    def loss_fn(lg):
        lp = sequence_log_probs(lg, tokens)
        return reinforce_loss(lp, mask, jnp.asarray([1.0]))

    g = jax.grad(loss_fn)(logits)
    # gradient descent direction increases logprob of sampled tokens
    assert g[0, 0, 1] < 0 and g[0, 1, 2] < 0
    # advantage 0 -> zero gradient
    g0 = jax.grad(
        lambda lg: reinforce_loss(sequence_log_probs(lg, tokens), mask, jnp.asarray([0.0]))
    )(logits)
    np.testing.assert_allclose(g0, 0.0, atol=1e-7)


def test_sequence_log_probs_gather():
    logits = jnp.log(jnp.asarray([[[0.1, 0.2, 0.7]]], jnp.float32))
    lp = sequence_log_probs(logits, jnp.asarray([[2]], jnp.int32))
    np.testing.assert_allclose(lp, np.log(0.7), rtol=1e-4)


@pytest.mark.parametrize("encoder", ["temporal_attention", "meanpool"])
def test_teacher_force_logps_matches_full_logits(encoder):
    """The in-scan target-logp path (the RL update's memory-lean form) must
    equal gather(log_softmax(decode_logits)) exactly — same math, the [B,T,V]
    stack just never materializes."""
    cfg = tiny_cfg(encoder=encoder)
    model = CaptionModel(cfg)
    feats, masks, labels = make_batch(3)
    params = model.init(jax.random.key(0), feats, masks, labels)
    enc = model.apply(params, feats, masks, method=CaptionModel.encode)
    full = sequence_log_probs(
        model.apply(params, enc, labels, method=CaptionModel.decode_logits),
        labels,
    )
    lean = model.apply(
        params, enc, labels, method=CaptionModel.teacher_force_logps
    )
    np.testing.assert_allclose(np.asarray(lean), np.asarray(full),
                               rtol=1e-6, atol=1e-6)
