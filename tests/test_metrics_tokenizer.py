from cst_captioning_tpu.metrics.tokenizer import ptb_tokenize, ptb_tokenize_corpus


def test_basic_lowercase_and_punct_drop():
    assert ptb_tokenize("A man is playing a Guitar.") == [
        "a", "man", "is", "playing", "a", "guitar",
    ]


def test_contractions_split():
    assert ptb_tokenize("don't") == ["do", "n't"]
    assert ptb_tokenize("He's running") == ["he", "'s", "running"]
    assert ptb_tokenize("they'll win, won't they?") == [
        "they", "'ll", "win", "wo", "n't", "they",
    ]


def test_punctuation_tokens_dropped():
    assert ptb_tokenize("wait -- no, really...") == ["wait", "no", "really"]
    assert ptb_tokenize("a (small) dog") == ["a", "small", "dog"]


def test_keep_punct_mode():
    assert ptb_tokenize("a dog.", keep_punct=True) == ["a", "dog", "."]


def test_numbers_and_hyphens():
    # hyphen splits words; the bare hyphen token is punctuation and dropped
    assert ptb_tokenize("a 2-year-old child") == ["a", "2", "year", "old", "child"]


def test_empty_and_whitespace():
    assert ptb_tokenize("") == []
    assert ptb_tokenize("   \n  ") == []


def test_corpus_tokenize():
    out = ptb_tokenize_corpus({"v1": ["A dog runs.", "The dog ran!"]})
    assert out == {"v1": [["a", "dog", "runs"], ["the", "dog", "ran"]]}
