"""Decoupled actor/learner SCST tests: submesh planning, strict-mode
bit-identity against the sync loop, staleness drop/recount determinism,
drain/resume of the in-flight rollout ring, and the zero-actor fallback."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config.config import ModelConfig, RLConfig, TrainConfig
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.parallel import (
    grow_actors,
    largest_divisor,
    plan_submesh,
    shared_plan,
    shrink_actors,
)
from cst_captioning_tpu.rl import AsyncSCSTTrainer, SCSTTrainer
from cst_captioning_tpu.train import (
    create_train_state,
    make_mesh,
    make_optimizer,
    replicate,
    shard_batch,
)

V = 14
B, F, T = 8, 3, 5


@pytest.fixture(scope="module")
def model_setup():
    cfg = ModelConfig(
        vocab_size=V,
        modalities=(("resnet", 6),),
        d_embed=12,
        d_hidden=12,
        d_att=6,
        encoder="meanpool",
        dropout=0.0,
        max_len=T,
        max_frames=F,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 6)), jnp.float32)}
    masks = {"resnet": jnp.ones((B, F), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)
    tx = make_optimizer(TrainConfig(lr=5e-2, grad_clip=5.0), 10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=1)
    return model, state, feats, masks


class TokenReward:
    """Rigged reward: +1 per occurrence of a target token. ``calls``
    records every scored row batch so tests can pin token bit-identity
    between two schedules without reaching into the decode."""

    def __init__(self, target: int):
        self.target = target
        self.calls: list[np.ndarray] = []

    def __call__(self, video_ids, rows):
        rows = np.asarray(rows)
        self.calls.append(rows.copy())
        return (rows == self.target).sum(axis=1).astype(np.float32)


VIDS = [f"v{i}" for i in range(B)]


def _batches(feats, masks, n):
    return [(feats, masks, VIDS, None)] * n


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---- submesh planning -------------------------------------------------------


def test_largest_divisor():
    assert largest_divisor(8, 3) == 2
    assert largest_divisor(8, 4) == 4
    assert largest_divisor(6, 4) == 3
    assert largest_divisor(7, 4) == 1
    assert largest_divisor(0, 5) == 5  # no batch constraint
    assert largest_divisor(8, 0) == 1


def test_plan_submesh_halves_and_clamps():
    mesh = make_mesh()
    n = mesh.devices.size
    plan = plan_submesh(mesh, 0.5, batch_size=8)
    assert not plan.shared
    assert plan.n_actors + plan.n_learners <= n
    assert plan.n_actors >= 1 and plan.n_learners >= 1
    assert 8 % plan.n_actors == 0 and 8 % plan.n_learners == 0
    assert set(plan.actor_devices).isdisjoint(plan.learner_devices)
    # each side is a real 1-axis mesh over the same axis name
    assert plan.actor.axis_names == plan.learner.axis_names == ("data",)


def test_plan_submesh_single_device_is_shared():
    dev = jax.devices()[0]
    from jax.sharding import Mesh

    mesh = Mesh(np.asarray([dev]), ("data",))
    plan = plan_submesh(mesh, 0.5, batch_size=8)
    assert plan.shared and plan.n_actors == plan.n_learners == 1


def test_shared_plan_spans_full_mesh():
    mesh = make_mesh()
    plan = shared_plan(mesh)
    assert plan.shared
    assert plan.n_actors == plan.n_learners == mesh.devices.size


def test_shrink_actors_reclamps_and_exhausts():
    mesh = make_mesh()
    plan = plan_submesh(mesh, 0.5, batch_size=8)
    learners = plan.learner_devices
    while plan is not None and plan.n_actors > 1:
        smaller = shrink_actors(plan, 0, batch_size=8)
        assert smaller is not None
        assert smaller.n_actors < plan.n_actors
        assert 8 % smaller.n_actors == 0
        assert smaller.learner_devices == learners  # learner side untouched
        plan = smaller
    # the last actor cannot be shed: the caller falls back to sync
    assert shrink_actors(plan, 0, batch_size=8) is None


def test_grow_actors_round_trip_restores_initial_plan():
    mesh = make_mesh()
    initial = plan_submesh(mesh, 0.5, batch_size=8)
    victim = initial.actor_devices[0]
    shrunk = shrink_actors(initial, 0, batch_size=8)
    assert victim not in shrunk.actor_devices
    # one rejoin restores every healthy device, including any the shrink
    # clamped away for batch divisibility — in the original order
    grown = grow_actors(shrunk, victim, initial, batch_size=8, dead=set())
    assert grown is not None
    assert grown.actor_devices == initial.actor_devices
    assert grown.learner_devices == initial.learner_devices
    # a duplicate rejoin changes nothing
    assert grow_actors(grown, victim, initial, batch_size=8, dead=set()) is None
    # still-dead peers stay out of the grown membership
    others = [d for d in initial.actor_devices if d != victim]
    if others:
        partial = grow_actors(
            shrunk, victim, initial, batch_size=8, dead={others[0]},
        )
        assert partial is None or others[0] not in partial.actor_devices
    # a device that never belonged to the actor side is refused
    with pytest.raises(ValueError):
        grow_actors(shrunk, initial.learner_devices[0], initial, batch_size=8)
    # growing out of the sync fallback (no surviving plan) also works
    from_fallback = grow_actors(None, victim, initial, batch_size=8)
    assert from_fallback is not None
    assert from_fallback.actor_devices == initial.actor_devices


# ---- strict-mode bit-identity ----------------------------------------------


@pytest.mark.parametrize(
    "pipelined",
    [True, pytest.param(False, marks=pytest.mark.slow)],
    ids=["pipelined", "sequential"],
)
@pytest.mark.slow
def test_strict_matches_sync_no_mesh(model_setup, pipelined):
    """strict=True replays the sync schedule (its 1-deep pipeline, or the
    sequential loop under pipelined=False) bit-for-bit with mesh=None:
    decoded tokens, per-step metrics, params, and opt_state all match."""
    model, state, feats, masks = model_setup
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   pipelined=pipelined)
    batches = _batches(feats, masks, 3)

    r_sync = TokenReward(7)
    sync = SCSTTrainer(model, r_sync, cfg)
    s_sync, m_sync = sync.train_epoch(
        state, iter(batches), jax.random.key(9), pipelined=pipelined
    )

    r_async = TokenReward(7)
    a = AsyncSCSTTrainer(model, r_async, cfg, strict=True)
    s_async, m_async = a.train_epoch(state, iter(batches), jax.random.key(9))

    assert len(m_sync) == len(m_async) == 3
    for ms, ma in zip(m_sync, m_async):
        assert float(ms["rl_loss"]) == float(ma["rl_loss"])
        assert ms["reward_mean"] == ma["reward_mean"]
    # the reward computer saw the exact same token rows in the same order
    assert len(r_sync.calls) == len(r_async.calls)
    for rs, ra in zip(r_sync.calls, r_async.calls):
        np.testing.assert_array_equal(rs, ra)
    _assert_tree_equal(s_sync.params, s_async.params)
    _assert_tree_equal(s_sync.opt_state, s_async.opt_state)


@pytest.mark.slow
def test_strict_matches_sync_on_mesh(model_setup):
    """Mesh twin of the strict pin: both roles run the FULL mesh so the
    shard_map decode's axis_index rng folds match the sync loop's."""
    model, state, feats, masks = model_setup
    mesh = make_mesh()
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy")
    state_m = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    batches = [(f_s, m_s, VIDS, None)] * 3

    r_sync = TokenReward(7)
    sync = SCSTTrainer(model, r_sync, cfg, mesh=mesh)
    s_sync, m_sync = sync.train_epoch(state_m, iter(batches), jax.random.key(9))

    r_async = TokenReward(7)
    a = AsyncSCSTTrainer(model, r_async, cfg, mesh=mesh, strict=True,
                         batch_size=B)
    assert a._plan.shared  # strict pins the full-mesh shared layout
    s_async, m_async = a.train_epoch(state_m, iter(batches), jax.random.key(9))

    for ms, ma in zip(m_sync, m_async):
        assert float(ms["rl_loss"]) == float(ma["rl_loss"])
    for rs, ra in zip(r_sync.calls, r_async.calls):
        np.testing.assert_array_equal(rs, ra)
    _assert_tree_equal(s_sync.params, s_async.params)
    _assert_tree_equal(s_sync.opt_state, s_async.opt_state)


@pytest.mark.slow
def test_depth1_bound0_is_implicitly_strict(model_setup):
    """rollout_depth=1 + staleness_bound=0 IS the sequential sync schedule:
    no strict flag needed (the config-driven strict mode)."""
    model, state, feats, masks = model_setup
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   pipelined=False, rollout_depth=1, staleness_bound=0)
    batches = _batches(feats, masks, 2)

    sync = SCSTTrainer(model, TokenReward(7), cfg)
    s_sync, _ = sync.train_epoch(
        state, iter(batches), jax.random.key(3), pipelined=False
    )
    a = AsyncSCSTTrainer(model, TokenReward(7), cfg)
    assert a._strict
    s_async, _ = a.train_epoch(state, iter(batches), jax.random.key(3))
    _assert_tree_equal(s_sync.params, s_async.params)


# ---- the genuinely decoupled schedule ---------------------------------------


@pytest.mark.slow
def test_decoupled_runs_and_reports_occupancy(model_setup):
    model, state, feats, masks = model_setup
    mesh = make_mesh()
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   rollout_depth=2, staleness_bound=1)
    state_m = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    batches = [(f_s, m_s, VIDS, None)] * 6

    a = AsyncSCSTTrainer(model, TokenReward(7), cfg, mesh=mesh, batch_size=B)
    assert not a._plan.shared
    s, metrics = a.train_epoch(state_m, iter(batches), jax.random.key(9))
    assert len(metrics) == 6  # every batch got exactly one applied update
    # defaults depth=2/bound=1: steady-state staleness 1, nothing dropped
    assert a.last_dropped == 0
    assert set(a.last_staleness) <= {0, 1}
    assert 0.0 < a.last_occupancy["actor"] <= 1.0
    assert 0.0 < a.last_occupancy["learner"] <= 1.0
    # the returned state is back on the caller's full-mesh layout
    dev_ids = {
        d.id for leaf in jax.tree_util.tree_leaves(s.params)
        for d in leaf.sharding.device_set
    }
    assert dev_ids == {d.id for d in mesh.devices.reshape(-1)}


@pytest.mark.slow
def test_staleness_drops_are_deterministic(model_setup):
    """depth 3 / bound 1: steady-state staleness 2 exceeds the bound, so
    batches are dropped and recounted — identically across two runs
    (the recount re-decodes under refreshed params with the entry's own
    stored rng key)."""
    model, state, feats, masks = model_setup
    mesh = make_mesh()
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   rollout_depth=3, staleness_bound=1)
    state_m = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    batches = [(f_s, m_s, VIDS, None)] * 6

    runs = []
    for _ in range(2):
        a = AsyncSCSTTrainer(model, TokenReward(7), cfg, mesh=mesh,
                             batch_size=B)
        s, m = a.train_epoch(state_m, iter(batches), jax.random.key(9))
        runs.append((
            a.last_dropped,
            dict(a.last_staleness),
            [float(x["rl_loss"]) for x in m],
            s.params,
        ))
    assert runs[0][0] > 0  # the bound genuinely fired
    assert runs[0][0] == runs[1][0]
    assert runs[0][1] == runs[1][1]
    assert runs[0][2] == runs[1][2]
    _assert_tree_equal(runs[0][3], runs[1][3])
    # recounted batches land at staleness 0 <= bound: nothing over the bound
    assert all(k <= 1 for k in runs[0][1])


@pytest.mark.slow
def test_drain_persists_ring_and_resume_replays(model_setup):
    """should_stop mid-epoch persists the in-flight ring into seam_sink;
    a resumed epoch replays those exact tokens (replay-consistent: the
    reward computer sees the SAME rows the pre-drain decode produced)."""
    model, state, feats, masks = model_setup
    mesh = make_mesh()
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   rollout_depth=2, staleness_bound=1)
    state_m = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    batches = [(f_s, m_s, VIDS, None)] * 6

    calls = {"n": 0}

    def stop_after_4():
        calls["n"] += 1
        return calls["n"] > 4

    sink: dict = {}
    a = AsyncSCSTTrainer(model, TokenReward(7), cfg, mesh=mesh, batch_size=B)
    s_half, m_half = a.train_epoch(
        state_m, iter(batches), jax.random.key(9),
        should_stop=stop_after_4, seam_sink=sink,
    )
    assert sink.get("ring"), "expected in-flight entries in the seam sink"
    ring_tokens = [e["samples"].copy() for e in sink["ring"]]

    # resume: skip the consumed batches, advance the rng chain past every
    # batch the first run decoded (consumed + in-flight), replay the seam
    done = len(m_half) + len(sink["ring"])
    rest = batches[len(m_half):]
    rng = jax.random.key(9)
    for _ in range(done):
        rng = jax.random.split(rng)[0]
    r2 = TokenReward(7)
    a2 = AsyncSCSTTrainer(model, r2, cfg, mesh=mesh, batch_size=B)
    s_res, m_res = a2.train_epoch(s_half, iter(rest), rng, seam=sink)
    assert len(m_half) + len(m_res) == 6
    # the first consumed rows of the resumed run are the persisted tokens
    # (reward sees the K*B sample rows first, then the greedy rows: the
    # replayed batches' sample calls sit at stride 2)
    for i, tok in enumerate(ring_tokens):
        np.testing.assert_array_equal(
            tok.reshape(-1, tok.shape[-1]), r2.calls[2 * i]
        )


@pytest.mark.slow
def test_seam_ring_discarded_on_changed_batch_order(model_setup):
    """A replay whose video ids don't match the incoming batch is discarded
    (never marry old tokens to new features) and decoding goes live."""
    model, state, feats, masks = model_setup
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   rollout_depth=2, staleness_bound=1)
    events = []
    a = AsyncSCSTTrainer(
        model, TokenReward(7), cfg,
        on_event=lambda e, **kw: events.append(e),
    )
    stale_seam = {"ring": [{
        "samples": np.zeros((2, B, T), np.int32),
        "lps": np.zeros((2, B, T), np.float32),
        "video_ids": ["other%d" % i for i in range(B)],
        "valid": np.ones((B,), np.float32),
        "rng": np.asarray(jax.random.key_data(jax.random.key(0))),
        "batch_index": 0,
    }]}
    s, m = a.train_epoch(
        state, iter(_batches(feats, masks, 2)), jax.random.key(9),
        seam=stale_seam,
    )
    assert len(m) == 2
    assert "seam_ring_discarded" in events


# ---- chaos: actor preemption ------------------------------------------------


@pytest.mark.slow
def test_actor_preempt_degrades_to_survivors(model_setup):
    from cst_captioning_tpu.resilience.chaos import Fault, FaultPlan

    model, state, feats, masks = model_setup
    mesh = make_mesh()
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   rollout_depth=2, staleness_bound=1)
    state_m = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    batches = [(f_s, m_s, VIDS, None)] * 6

    events = []
    a = AsyncSCSTTrainer(model, TokenReward(7), cfg, mesh=mesh, batch_size=B,
                         on_event=lambda e, **kw: events.append((e, kw)))
    n_actors = a._plan.n_actors
    plan = FaultPlan([Fault("rl.actor.step", "actor_preempt", at=2)], seed=0)
    with plan.activate():
        s, m = a.train_epoch(state_m, iter(batches), jax.random.key(9))
    assert len(m) == 6  # every batch still got exactly one update
    assert plan.fired and plan.fired[0]["kind"] == "actor_preempt"
    degraded = [kw for e, kw in events if e == "rl_actor_degraded"]
    assert degraded and degraded[0]["survivors"] < n_actors
    assert not a._fallback_sync


@pytest.mark.slow
def test_actor_preempt_exhaustion_falls_back_to_sync(model_setup):
    from cst_captioning_tpu.resilience.chaos import Fault, FaultPlan

    model, state, feats, masks = model_setup
    mesh = make_mesh()
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   rollout_depth=2, staleness_bound=1)
    state_m = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    batches = [(f_s, m_s, VIDS, None)] * 6

    events = []
    a = AsyncSCSTTrainer(model, TokenReward(7), cfg, mesh=mesh, batch_size=B,
                         on_event=lambda e, **kw: events.append((e, kw)))
    plan = FaultPlan(
        [Fault("rl.actor.step", "actor_preempt", at=1, times=8)], seed=0
    )
    with plan.activate():
        s, m = a.train_epoch(state_m, iter(batches), jax.random.key(9))
    assert len(m) == 6
    assert a._fallback_sync
    assert any(e == "rl_actor_fallback_sync" for e, _ in events)
    # metrics stay finite through the degradation chain
    assert all(np.isfinite(float(x["rl_loss"])) for x in m)


@pytest.mark.slow
def test_actor_preempt_then_rejoin_is_deterministic(model_setup):
    """actor_preempt followed by host_rejoin shrinks then regrows the
    actor fleet mid-epoch; in-flight rollouts orphaned at the grow
    boundary are recounted in order, and two seeded runs produce
    identical staleness histograms, token rows, losses, and params."""
    from cst_captioning_tpu.resilience.chaos import Fault, FaultPlan

    model, state, feats, masks = model_setup
    mesh = make_mesh()
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   rollout_depth=2, staleness_bound=1)
    state_m = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    batches = [(f_s, m_s, VIDS, None)] * 6

    runs = []
    for _ in range(2):
        events = []
        reward = TokenReward(7)
        a = AsyncSCSTTrainer(model, reward, cfg, mesh=mesh, batch_size=B,
                             on_event=lambda e, **kw: events.append((e, kw)))
        n_actors = a._plan.n_actors
        plan = FaultPlan([
            Fault("rl.actor.step", "actor_preempt", at=1),
            Fault("rl.actor.step", "host_rejoin", at=3),
        ], seed=0)
        with plan.activate():
            s, m = a.train_epoch(state_m, iter(batches), jax.random.key(9))
        assert len(m) == 6  # every batch still got exactly one update
        assert [f["kind"] for f in plan.fired] == [
            "actor_preempt", "host_rejoin",
        ]
        degraded = [kw for e, kw in events if e == "rl_actor_degraded"]
        regrown = [kw for e, kw in events if e == "rl_actor_regrown"]
        assert degraded and degraded[0]["survivors"] < n_actors
        assert regrown and regrown[0]["actors"] == n_actors
        assert a.last_rejoined == 1
        assert a._plan.n_actors == n_actors
        assert not a._fallback_sync
        runs.append((
            dict(a.last_staleness),
            [c.copy() for c in reward.calls],
            [float(x["rl_loss"]) for x in m],
            s.params,
        ))
    assert runs[0][0] == runs[1][0]  # identical staleness histograms
    assert len(runs[0][1]) == len(runs[1][1])
    for r0, r1 in zip(runs[0][1], runs[1][1]):
        np.testing.assert_array_equal(r0, r1)  # identical token rows
    assert runs[0][2] == runs[1][2]
    _assert_tree_equal(runs[0][3], runs[1][3])


# ---- trainer seam serialization --------------------------------------------


def test_seam_ring_npz_roundtrip(tmp_path):
    """Trainer._seam_bytes/_load_seam carry the ring format losslessly."""
    import types

    from cst_captioning_tpu.train.trainer import Trainer

    rng = np.random.default_rng(1)
    ring = [
        {
            "samples": rng.integers(0, V, size=(2, B, T)).astype(np.int32),
            "lps": rng.normal(size=(2, B, T)).astype(np.float32),
            "video_ids": [f"v{i}" for i in range(B)],
            "valid": np.ones((B,), np.float32),
            "rng": np.asarray(
                jax.random.key_data(jax.random.key(7)), np.uint32
            ),
            "batch_index": 3 + k,
            "greedy": rng.integers(0, V, size=(B, T)).astype(np.int32),
        }
        for k in range(2)
    ]
    blob = Trainer._seam_bytes({"ring": ring}, epoch=2, batch_index=3)
    ckpt = tmp_path / "step_000123"
    ckpt.mkdir()
    (ckpt / "seam.npz").write_bytes(blob)

    logged = []
    fake = types.SimpleNamespace(
        log=types.SimpleNamespace(log=lambda ev, **kw: logged.append(ev))
    )
    seam = Trainer._load_seam(
        fake, str(tmp_path),
        {"ckpt_name": "step_000123", "phase": "rl", "batch_index": 3},
    )
    assert seam is not None and "seam_loaded" in logged
    assert seam["epoch"] == 2 and seam["batch_index"] == 3
    assert len(seam["ring"]) == 2
    for orig, back in zip(ring, seam["ring"]):
        np.testing.assert_array_equal(orig["samples"], back["samples"])
        np.testing.assert_array_equal(orig["lps"], back["lps"])
        np.testing.assert_array_equal(orig["greedy"], back["greedy"])
        np.testing.assert_array_equal(orig["rng"], back["rng"])
        assert orig["video_ids"] == back["video_ids"]
        assert orig["batch_index"] == back["batch_index"]
