"""Multi-host (DCN) support: host-sharded feeding + 2-process parity.

SURVEY.md §5 dist-comm row reserved a multi-host extension of the data
parallelism; train/multihost.py implements it. These tests pin:

1. Batcher ``host_shard`` slicing: the union of every host's local batches
   is exactly the unsharded global batch stream (same order, same rows).
2. The single-process degradations of every multihost helper are the plain
   device_put / np.asarray paths.
3. A REAL 2-process jax.distributed cluster (Gloo collectives on CPU,
   4 fake devices per process = 8 global) trains XE + RL through the
   Trainer and evaluates, matching the single-process 8-device run:
   bit-comparable params and identical captions.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- shared recipe (also imported by tests/_multihost_child.py) -------------


def build_cfg(vocab_size: int, ckpt_dir: str):
    import dataclasses

    from cst_captioning_tpu.config.config import (
        DataConfig, EvalConfig, ExperimentConfig, ModelConfig, RLConfig,
        TrainConfig,
    )

    return ExperimentConfig(
        name="mh",
        model=ModelConfig(
            vocab_size=vocab_size,
            modalities=(("resnet", 12),),
            d_embed=16, d_hidden=16, d_att=8,
            encoder="temporal_attention", dropout=0.0,
            max_len=8, max_frames=4, dtype="float32",
        ),
        data=DataConfig(batch_size=8, seq_per_vid=2),
        train=TrainConfig(
            lr=5e-3, epochs=1, grad_clip=5.0, ckpt_dir=ckpt_dir,
            eval_every_epochs=100, seed=0,
        ),
        rl=RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                    lr=1e-3, epochs=1),
        eval=EvalConfig(beam_size=2, max_len=8),
    )


def run_training(data_dir: str, ckpt_dir: str) -> dict:
    """Train 1 XE + 1 RL epoch and beam-eval the test split; return parity
    artifacts (per-leaf param sums + captions). Works single- OR
    multi-process: the Trainer/Evaluator multihost wiring adapts."""
    import jax

    from cst_captioning_tpu.config.config import EvalConfig
    from cst_captioning_tpu.data import CaptionDataset
    from cst_captioning_tpu.eval.evaluator import Evaluator
    from cst_captioning_tpu.train.trainer import Trainer

    paths = {
        "info_json": os.path.join(data_dir, "info.json"),
        "resnet": os.path.join(data_dir, "resnet.h5"),
    }
    train_ds = CaptionDataset(paths["info_json"], {"resnet": paths["resnet"]},
                              "train", 4)
    test_ds = CaptionDataset(paths["info_json"], {"resnet": paths["resnet"]},
                             "test", 4)
    cfg = build_cfg(len(train_ds.vocab), ckpt_dir)
    tr = Trainer(cfg, train_ds, None, use_mesh=True)
    tr.train_xe()
    tr.train_rl()
    ev = Evaluator(tr.model, test_ds,
                   EvalConfig(beam_size=2, max_len=8,
                              metrics=("CIDEr-D", "Bleu")),
                   batch_size=8, mesh=tr.mesh)
    result = ev.evaluate(tr.state.params)
    leaf_sums = [
        float(np.asarray(x, np.float64).sum())
        for x in jax.tree_util.tree_leaves(jax.device_get(tr.state.params))
    ]
    train_ds.close()
    test_ds.close()
    return {
        "leaf_sums": leaf_sums,
        "captions": result["captions"],
        "metrics": result["metrics"],
        # evidence the eval host work is actually sharded: the per-process
        # collate width (multi-process: global batch / process count)
        "eval_local_batch": ev.batcher.local_batch_size,
    }


@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    from cst_captioning_tpu.data import make_synthetic_dataset

    out = tmp_path_factory.mktemp("mhsynth")
    paths = make_synthetic_dataset(
        str(out), num_videos=16, num_topics=3, vocab_words=20,
        modalities={"resnet": 12}, max_frames=4, seed=9,
    )
    return os.path.dirname(paths["info_json"])


# ---- 1. host-sharded batcher ------------------------------------------------


@pytest.mark.parametrize("mode", ["caption", "video"])
def test_host_shard_slices_reassemble_global_stream(synth, mode):
    from cst_captioning_tpu.data import Batcher, CaptionDataset

    ds = CaptionDataset(os.path.join(synth, "info.json"),
                        {"resnet": os.path.join(synth, "resnet.h5")},
                        "train", 4)
    kw = dict(batch_size=6, max_len=8, mode=mode, seq_per_vid=2, seed=3)
    whole = Batcher(ds, **kw)
    parts = [Batcher(ds, **kw, host_shard=(i, 2)) for i in range(2)]
    for b_all, b0, b1 in zip(whole.epoch(), parts[0].epoch(), parts[1].epoch()):
        assert b0.labels.shape[0] == 3 and b1.labels.shape[0] == 3
        assert b_all.video_ids == b0.video_ids + b1.video_ids
        np.testing.assert_array_equal(
            b_all.labels, np.concatenate([b0.labels, b1.labels])
        )
        np.testing.assert_array_equal(
            b_all.valid, np.concatenate([b0.valid, b1.valid])
        )
        np.testing.assert_array_equal(
            b_all.feats["resnet"],
            np.concatenate([b0.feats["resnet"], b1.feats["resnet"]]),
        )
    ds.close()


def test_host_shard_validation(synth):
    from cst_captioning_tpu.data import Batcher, CaptionDataset

    ds = CaptionDataset(os.path.join(synth, "info.json"),
                        {"resnet": os.path.join(synth, "resnet.h5")},
                        "train", 4)
    with pytest.raises(ValueError, match="divisible"):
        Batcher(ds, batch_size=5, max_len=8, host_shard=(0, 2))
    with pytest.raises(ValueError, match="index"):
        Batcher(ds, batch_size=4, max_len=8, host_shard=(2, 2))
    ds.close()


# ---- 2. single-process helper degradations ---------------------------------


def test_helpers_single_process_identity():
    import jax
    from jax.sharding import PartitionSpec as P

    from cst_captioning_tpu.train import multihost
    from cst_captioning_tpu.train.mesh import batch_sharding, make_mesh

    assert not multihost.is_multiprocess()
    assert multihost.host_shard() == (0, 1)
    mesh = make_mesh()
    s = batch_sharding(mesh)
    tree = ({"a": np.ones((8, 3), np.float32)}, np.arange(8, dtype=np.int32))
    placed = multihost.put_global((s, s), tree)
    np.testing.assert_array_equal(np.asarray(placed[0]["a"]), tree[0]["a"])
    placed2 = multihost.put_full_global(s, np.ones((8, 2), np.float32))
    assert placed2.sharding == s
    arr = placed[1]
    np.testing.assert_array_equal(
        multihost.to_host_local(arr, mesh, P("data")), tree[1]
    )
    assert multihost.from_host_local(arr, mesh, P("data")) is arr
    np.testing.assert_array_equal(multihost.allgather_to_host(arr), tree[1])
    assert multihost.global_scalar_mean(2.5) == 2.5
    # weighted mean: local ratio, zero-weight guarded
    assert multihost.global_weighted_mean(6.0, 4.0) == pytest.approx(1.5)
    assert multihost.global_weighted_mean(0.0, 0.0) == 0.0


def test_pyobj_helpers_single_process():
    from cst_captioning_tpu.train import multihost

    obj = {"a": [1, 2], "b": "caption text"}
    assert multihost.allgather_pyobj(obj) == [obj]
    assert multihost.broadcast_pyobj(obj) is obj


# ---- 3. the real thing: 2-process cluster == single-process ----------------


def test_two_process_cluster_matches_single_process(synth, tmp_path):
    """Full XE + RL + beam-eval parity: a 2-process jax.distributed cluster
    (Gloo over localhost, 8 global fake devices) produces the same params
    and the exact same captions as the single-process 8-device run."""
    single = run_training(synth, str(tmp_path / "ckpt_single"))

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    out_json = str(tmp_path / "mh.json")
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests", "_multihost_child.py"),
             str(i), "2", str(port), synth, out_json, str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    for i, p in enumerate(procs):
        out, err = p.communicate(timeout=600)
        assert p.returncode == 0, f"process {i} failed:\n{err[-4000:]}"

    multi = json.load(open(out_json))
    assert multi["captions"] == single["captions"]
    # per-process eval host work is HALVED (host-sharded collate), yet the
    # process-0-scored + broadcast metrics match the single-process ones
    assert multi["eval_local_batch"] == single["eval_local_batch"] // 2
    assert set(multi["metrics"]) == set(single["metrics"])
    for k, v in single["metrics"].items():
        assert multi["metrics"][k] == pytest.approx(v), k
    np.testing.assert_allclose(
        multi["leaf_sums"], single["leaf_sums"], rtol=1e-4, atol=1e-5
    )


# ---- 4. partial kill: one REAL process dies, the survivor drains ------------


def test_partial_kill_survivor_drains(synth, tmp_path):
    """The PR 6 elastic path on REAL processes, not sim-hosts: a 2-process
    jax.distributed cluster shares one heartbeat dir; process 1 hard-dies
    mid-epoch (seeded chaos kill -> os._exit), and process 0's
    HealthMonitor must declare the loss from heartbeat staleness, drain
    (peer-loss checkpoint), and raise PeerLost (strict elastic). Trainers
    are per-process (no cross-process computations — this CPU backend
    cannot run them; the elastic machinery under test is entirely
    file-and-signal based and identical on a TPU pod)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    out_json = str(tmp_path / "pk.json")
    env = {
        k: v for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable,
             os.path.join(REPO, "tests", "_multihost_child.py"),
             str(i), "2", str(port), synth, out_json, str(tmp_path),
             "partial_kill"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        for i in range(2)
    ]
    errs = []
    for i, p in enumerate(procs):
        _, err = p.communicate(timeout=300)
        errs.append(err)
    reports = {}
    for i in range(2):
        path = f"{out_json}.proc{i}"
        if os.path.exists(path):
            reports[i] = json.load(open(path))
    if not reports or not all(r.get("initialized") for r in reports.values()):
        pytest.skip(
            "2-process jax.distributed cluster unavailable here: "
            f"{reports or errs}"
        )
    victim, survivor = reports[1], reports[0]
    assert victim.get("died") == "SimulatedKill", victim
    assert survivor.get("peer_lost") == [1], (survivor, errs[0][-2000:])
    # the drain saved a mid-epoch step checkpoint before PeerLost unwound
    assert survivor.get("drained_ckpts"), survivor
