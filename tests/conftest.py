"""Test environment: force JAX onto 8 virtual CPU devices.

Per SURVEY.md §4 item 4: distributed paths (shard_map/pmap grad allreduce,
per-device RNG) are exercised on fake CPU devices so the suite runs anywhere;
the real TPU is reserved for bench.py.

This environment preloads jax at interpreter start (a sitecustomize on
PYTHONPATH registers the ``axon`` TPU backend and sets JAX_PLATFORMS=axon), so
setting env vars here is too late for jax's config — but the *backend* is not
initialized until first use, so ``jax.config.update`` + XLA_FLAGS (read at
backend init) still take effect. Keep this module free of any call that
touches devices.
"""

import os

# XLA_FLAGS is read by the CPU client at backend-init time, so mutating the
# env here (pre-init) works even though jax itself is already imported.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests may spawn

import jax

jax.config.update("jax_platforms", "cpu")

try:
    _backends = jax._src.xla_bridge._backends  # private; best-effort probe
except AttributeError:
    _backends = None
assert not _backends, (
    "a JAX backend was initialized before conftest ran; CPU forcing is too late"
)


def pytest_configure(config):
    # tier-1 filters with `-m "not slow"`; register the marker so strict
    # marker modes and --markers stay accurate (graftlint GL008 enforces it
    # on TPU-only test imports)
    config.addinivalue_line(
        "markers", "slow: needs real TPU hardware or long wall-clock; "
        "excluded from tier-1 (-m 'not slow')"
    )
    config.addinivalue_line(
        "markers", "no_sanitize: opted out of the --sanitize transfer "
        "guard (the test's PURPOSE is an implicit transfer or a NaN path)"
    )


def pytest_addoption(parser):
    parser.addoption(
        "--sanitize", action="store_true", default=False,
        help="run every test under jax.transfer_guard('disallow') + "
        "jax.debug_nans: the runtime cross-check of graftlint's "
        "GL001/GL013 zero-implicit-transfer claim (scripts/sanitize.sh "
        "drives this over the hot-path tier-1 subset)",
    )


import pytest  # noqa: E402  (after the backend-forcing block above)


@pytest.fixture(autouse=True)
def _sanitizer_gate(request):
    """With --sanitize, fail any test that performs an implicit host<->
    device transfer (explicit device_put/device_get stay allowed — the
    whole point is that every transfer must be a visible decision) or
    produces a NaN. graftlint proves the claim lexically; this proves it
    at runtime."""
    if not request.config.getoption("--sanitize") or \
            request.node.get_closest_marker("no_sanitize"):
        yield
        return
    with jax.transfer_guard("disallow"), jax.debug_nans(True):
        yield
