"""Test environment: force JAX onto 8 virtual CPU devices.

Per SURVEY.md §4 item 4: distributed paths (shard_map/pmap grad allreduce,
per-device RNG) are exercised on fake CPU devices so the suite runs anywhere;
the real TPU is reserved for bench.py.

This environment preloads jax at interpreter start (a sitecustomize on
PYTHONPATH registers the ``axon`` TPU backend and sets JAX_PLATFORMS=axon), so
setting env vars here is too late for jax's config — but the *backend* is not
initialized until first use, so ``jax.config.update`` + XLA_FLAGS (read at
backend init) still take effect. Keep this module free of any call that
touches devices.
"""

import os

# XLA_FLAGS is read by the CPU client at backend-init time, so mutating the
# env here (pre-init) works even though jax itself is already imported.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests may spawn

import jax

jax.config.update("jax_platforms", "cpu")

try:
    _backends = jax._src.xla_bridge._backends  # private; best-effort probe
except AttributeError:
    _backends = None
assert not _backends, (
    "a JAX backend was initialized before conftest ran; CPU forcing is too late"
)


def pytest_configure(config):
    # tier-1 filters with `-m "not slow"`; register the marker so strict
    # marker modes and --markers stay accurate (graftlint GL008 enforces it
    # on TPU-only test imports)
    config.addinivalue_line(
        "markers", "slow: needs real TPU hardware or long wall-clock; "
        "excluded from tier-1 (-m 'not slow')"
    )
