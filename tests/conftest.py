"""Test environment: force JAX onto 8 virtual CPU devices.

Per SURVEY.md §4 item 4: distributed paths (shard_map/pmap grad allreduce,
per-device RNG) are exercised on fake CPU devices so the suite runs anywhere;
the real TPU is reserved for bench.py. Must run before the first jax import.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
