"""GL014 cross-file fixture — callers reusing keys a callee already spent.

``double_draw`` and ``transitive`` must be flagged when linted together
with ``keys_lib.py``; alone, this file must lint clean (the consumption
fact lives in the other module).
"""

import jax

from cst_captioning_tpu.keys_lib import sample_rollout, splitter, wrapped


def double_draw(key):
    a = sample_rollout(key, (2,))
    b = jax.random.uniform(key, (2,))  # GL014: key spent by sample_rollout
    return a + b


def transitive(key):
    a = wrapped(key, (2,))
    b = wrapped(key, (2,))  # GL014: both consumptions happen via callees
    return a + b


def fresh(key):
    k1, k2 = jax.random.split(key)
    a = sample_rollout(k1, (2,))
    b = jax.random.uniform(k2, (2,))
    return a + b


def split_then_use(key):
    # splitter does not consume: reuse after it is fine
    k1, k2 = splitter(key)
    a = sample_rollout(k1, (2,))
    return a, k2


def suppressed(key):
    a = sample_rollout(key, (2,))
    b = jax.random.uniform(key, (2,))  # graftlint: disable=GL014 (fixture: deliberate correlated draw)
    return a + b
