"""GL014 cross-file fixture — the CONSUMING callees.

``sample_rollout`` spends its ``key`` parameter directly;
``wrapped`` spends it one call deeper (the summary fixpoint sees through
the hop). Callers in ``caller.py`` must not reuse a key after passing it
here — a fact no per-file engine can know from the caller alone.
"""

import jax


def sample_rollout(key, shape):
    return jax.random.normal(key, shape)


def wrapped(key, shape):
    return sample_rollout(key, shape)


def splitter(key):
    # does NOT consume: callers may keep using their key afterwards
    return jax.random.split(key)
