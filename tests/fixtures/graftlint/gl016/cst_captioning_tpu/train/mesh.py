"""GL016 cross-file fixture — the mesh DECLARATION side.

Declares axes 'model' and 'pipeline' (the string defaults of *axis
parameters, same scrape as the real train/mesh.py). BOTH axes are
declared, so GL012's literal-vs-mesh check passes everywhere in this
fixture — only the axis-ENVIRONMENT analysis can tell that the
shard_map call path binds just 'model'.
"""


def make_mesh(num_devices=0, axis="model", seq_axis="pipeline"):
    return None
