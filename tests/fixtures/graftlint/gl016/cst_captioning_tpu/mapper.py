"""GL016 cross-file fixture — the shard_map APPLICATION side.

``run``'s nested ``body`` is shard_mapped with ``axis_names=('model',)``
(a partial manual-axes mapping: 'pipeline' stays automatic), then calls
the helpers in ``collectives.py`` — so their axis environment is
{'model'}, a fact that lives entirely in THIS module. ``vmapped`` shows
the vmap(axis_name=) seeding path for a NON-mesh axis: 'rollout' is not
declared by mesh.py, yet psum over it is legitimate because the call
path visibly binds it (GL012 consults the same environment).
"""

import jax
from jax.experimental.shard_map import shard_map

from cst_captioning_tpu.collectives import (
    reduce_model,
    reduce_pipeline,
    reduce_pipeline_suppressed,
)


def run(mesh, xs, in_specs, out_specs):
    def body(x):
        a = reduce_model(x)
        b = reduce_pipeline(x)
        c = reduce_pipeline_suppressed(x)
        return a + b + c

    step = shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        axis_names=("model",),
    )
    return step(xs)


def lane_sum(x):
    # 'rollout' is not a mesh axis; bound only by vmapped() below
    return jax.lax.psum(x, "rollout")


def vmapped(xs):
    return jax.vmap(lane_sum, axis_name="rollout")(xs)
