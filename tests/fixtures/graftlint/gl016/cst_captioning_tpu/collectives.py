"""GL016 cross-file fixture — helper functions with collectives.

Every helper here is called from inside the shard_map body in
``mapper.py``, which binds ONLY the 'model' axis (``axis_names=``).
``reduce_pipeline`` reduces over 'pipeline' — a mesh axis train/mesh.py
declares, so GL012 provably cannot flag it — but no reachable calling
context binds it: GL016's finding. Linting this file ALONE must find
nothing (no caller is known, so the runtime context is unknowable).

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

import jax


def reduce_model(x):
    # 'model' is bound by mapper.py's shard_map(axis_names=('model',))
    return jax.lax.psum(x, "model")


def reduce_pipeline(x):
    # declared by mesh.py, NEVER bound on any call path -> GL016
    return jax.lax.pmean(x, "pipeline")


def reduce_pipeline_suppressed(x):
    return jax.lax.pmean(x, "pipeline")  # graftlint: disable=GL016 (fixture: axis bound by an external caller)


def unreached(x):
    # no in-tree caller at all: context unknowable, GL016 stays quiet
    return jax.lax.psum(x, "pipeline")
