"""GL019 cross-file fixture — collective operand drift in a replica of
the seed module (every collective here is a cross-host rendezvous).

Positives: an operand whose leading dim is ``len(jax.local_devices())``,
an operand shaped differently under a ``process_index()`` branch, and an
operand returned by a helper whose summary says ``returns_host_shape``
(the cross-module fixpoint fact). Negatives prove the rule never
guesses: param-shaped operands, literal shapes, and the canonical
gather-lengths-then-pad pattern are all provably host-invariant.

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

import jax
import jax.numpy as jnp
from jax.experimental import multihost_utils

from cst_captioning_tpu.parallel.helpers import local_block, sync_ragged


def drift_local_devices():
    ragged = jnp.zeros((len(jax.local_devices()), 128), jnp.float32)
    return jax.lax.psum(ragged, "data")  # GL019: per-host leading dim


def drift_branch():
    if jax.process_index() == 0:
        buf = jnp.zeros((4, 128), jnp.float32)
    else:
        buf = jnp.zeros((2, 128), jnp.float32)
    return jax.lax.psum(buf, "data")  # GL019: branch-dependent shape


def drift_cross_module():
    return jax.lax.psum(local_block(), "data")  # GL019: callee fact


def drift_suppressed():
    ragged = jnp.zeros((len(jax.local_devices()), 128), jnp.float32)
    return jax.lax.psum(ragged, "data")  # graftlint: disable=GL019 (fixture: single-host harness pins one process)


def quiet_param(x):
    # operand shape comes from the caller: unknown, never guess
    return jax.lax.psum(x, "data")


def quiet_literal():
    return jax.lax.psum(jnp.zeros((8, 128), jnp.float32), "data")


def quiet_gathered_pad(data):
    # the canonical fix: gather the per-host lengths FIRST, then pad to
    # the gathered max — provably host-invariant
    lengths = multihost_utils.process_allgather(data.size)
    padded = jnp.zeros((int(lengths.max()),), jnp.uint8)
    return multihost_utils.process_allgather(padded)


def reach_helper(x):
    # pulls helpers.sync_ragged into the multihost reachability closure
    return sync_ragged(x)
