"""GL019 helpers. ``local_block`` returns a per-host shape — the pass-1
summary fact (``returns_host_shape``) that taints its results at call
sites in other modules. ``sync_ragged`` holds a drifting collective that
is only a finding because ``train/multihost.py`` calls it (reachability
closure): linting THIS file alone must find nothing — with the seed
module absent, nothing proves the site is a cross-host rendezvous."""

import jax
import jax.numpy as jnp


def local_block():
    return jnp.zeros((jax.local_device_count(), 128), jnp.float32)


def sync_ragged(x):
    tail = jnp.zeros((jax.local_device_count(),), jnp.float32)
    return jax.lax.psum(tail, "data")  # GL019 only via reachability
