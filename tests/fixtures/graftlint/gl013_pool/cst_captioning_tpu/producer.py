"""GL013 worker-pool fixture — the DEVICE side.

Same shape as the gl013 pair's producer: ``decode`` returns a device value
through the jitted ``encode``. The consumer hands it to a thread-pool
worker that reads it back EXPLICITLY with ``jax.device_get`` — the eval
pipeline's pattern (eval/evaluator.py) — which must produce ZERO findings.

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

import jax
import jax.numpy as jnp


@jax.jit
def encode(x):
    return jnp.tanh(x)


def decode(feats):
    return encode(feats) * 2
