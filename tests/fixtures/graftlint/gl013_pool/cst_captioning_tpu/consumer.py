"""GL013 worker-pool fixture — the HOST side.

The pipelined evaluator's cross-thread readback: device tokens are
submitted to a worker pool whose worker reads them back through the
EXPLICIT ``jax.device_get`` before any numpy conversion. The explicit
readback is the sanctioned host-transfer spelling, and provenance through
``pool.submit(...)`` into a function parameter is unknown, not device —
neither half may trip GL013 (zero-findings pin in tests/test_graftlint.py).
"""

from concurrent.futures import ThreadPoolExecutor

import jax
import numpy as np

from cst_captioning_tpu.producer import decode


def _readback(tokens):
    host = jax.device_get(tokens)  # explicit transfer: the sanctioned spelling
    return np.asarray(host)


def pipeline(batches):
    out = []
    with ThreadPoolExecutor(max_workers=2) as pool:
        futs = [pool.submit(_readback, decode(b)) for b in batches]
        out = [f.result() for f in futs]
    return out
