"""GL015 cross-file fixture — PartitionSpec literals that must resolve
against the axes ``train/mesh.py`` (a different module) declares.

``drifted`` spells 'data', which THIS fixture's mesh does not declare —
a per-file engine has no way to know that.
"""

from jax.sharding import NamedSharding, PartitionSpec as P


def good(mesh):
    return NamedSharding(mesh, P("model", "pipeline"))


def drifted(mesh):
    return NamedSharding(mesh, P("data"))  # GL015: not an axis of THIS mesh


def suppressed(mesh):
    return NamedSharding(mesh, P("data"))  # graftlint: disable=GL015 (fixture)


def dynamic(mesh, axis):
    # dynamic axis expressions are out of scope
    return NamedSharding(mesh, P(axis))
