"""GL015 cross-file fixture — the mesh DECLARATION side of the pair.

Declares axes 'model' and 'pipeline' (the string defaults of *axis
parameters, same scrape as the real train/mesh.py). ``shard_use.py``'s
spec literals must resolve against THESE axes, not a hardcoded list.
"""


def make_mesh(num_devices=0, axis="model", seq_axis="pipeline"):
    return None
