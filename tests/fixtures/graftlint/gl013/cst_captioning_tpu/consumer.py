"""GL013 cross-file fixture — the HOST side of the pair.

Every conversion below operates on a value whose device provenance is
declared in ``producer.py`` (a different module): linting this file ALONE
must find nothing, linting the pair must flag ``to_host`` and ``loop``.
"""

import numpy as np

from cst_captioning_tpu.producer import decode, prefetched


def to_host(feats):
    tokens = decode(feats)
    return np.asarray(tokens)  # GL013: device provenance lives in producer.py


def to_host_suppressed(feats):
    tokens = decode(feats)
    return np.asarray(tokens)  # graftlint: disable=GL013 (fixture: intentional readback)


def loop(batches, out):
    for batch in prefetched(batches):
        out.append(batch.tolist())  # GL013: prefetched batches are device-resident


def host_only(rows):
    # no device provenance anywhere: must stay quiet
    return np.asarray([len(r) for r in rows])
