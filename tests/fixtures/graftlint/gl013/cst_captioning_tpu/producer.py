"""GL013 cross-file fixture — the DEVICE side of the pair.

``decode`` returns a device value two hops deep (through the jitted
``encode``); ``prefetched`` is the device-yielding generator pattern
(stages via ``jax.device_put``, yields through a queue-shaped hop). A
per-file engine reading only ``consumer.py`` cannot know either fact —
that is exactly what this pair proves (see tests/test_graftlint.py).

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

import jax
import jax.numpy as jnp


@jax.jit
def encode(x):
    return jnp.tanh(x)


def decode(feats):
    # un-decorated, but its return provenance traces to the traced encode
    return encode(feats) * 2


def prefetched(batches):
    for b in batches:
        yield jax.device_put(b)
