"""GL020 fixture — ``grid_spec=`` Pallas sites, one contract per def.

``prefetch_ok``: a ``PrefetchScalarGridSpec(num_scalar_prefetch=1)`` site
whose index maps all take grid-rank + 1 arguments, with unblocked
``memory_space=pltpu.ANY`` pool refs and a DMA semaphore in scratch —
quiet (the ANY refs and the semaphore cost no VMEM).
``prefetch_arity_drift``: same site but one index map forgets the
trailing scalar-prefetch ref — GL020.
``gridspec_plain_ok``: a plain ``pltpu.GridSpec`` site (no prefetch)
with grid-rank index maps — quiet.

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _paged_copy_kernel(tbl_ref, x_ref, o_ref, pool_ref, slab, sem):
    @pl.when(pl.program_id(0) == 0)
    def _():
        pltpu.make_async_copy(
            pool_ref.at[tbl_ref[0]], slab.at[...], sem
        ).start()
        pltpu.make_async_copy(
            pool_ref.at[tbl_ref[0]], slab.at[...], sem
        ).wait()
    o_ref[...] = x_ref[...] + slab[...]


def prefetch_ok(x, pool, table, block=128):
    n, d = x.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block, d // block),
        in_specs=[
            pl.BlockSpec((block, block), lambda i, j, tbl: (i, j)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((block, block),
                               lambda i, j, tbl: (i, j)),
        scratch_shapes=[
            pltpu.VMEM((block, block), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        _paged_copy_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(table, x, pool)


def prefetch_arity_drift(x, pool, table, block=128):
    n, d = x.shape
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // block, d // block),
        in_specs=[
            pl.BlockSpec((block, block), lambda i, j: (i, j)),  # GL020
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((block, block),
                               lambda i, j, tbl: (i, j)),
        scratch_shapes=[
            pltpu.VMEM((block, block), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    return pl.pallas_call(
        _paged_copy_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(table, x, pool)


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def gridspec_plain_ok(x, block=128):
    n, d = x.shape
    grid_spec = pltpu.GridSpec(
        grid=(n // block, d // block),
        in_specs=[pl.BlockSpec((block, block), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((block, block), lambda i, j: (i, j)),
    )
    return pl.pallas_call(
        _copy_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
