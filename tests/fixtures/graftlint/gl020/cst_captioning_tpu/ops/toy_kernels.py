"""GL020 fixture — Pallas kernel contract violations, one per def.

``arity_mismatch``: index-map arity drifts from the grid rank.
``stride_mismatch``: a block dim paired with a floor-divided grid dim
uses a different divisor, and the kernel body has no ``pl.when`` guard.
``stride_guarded`` is the same pairing but the kernel visibly guards the
tail — quiet. ``vmem_hog``: fully-resolvable blocks + scratch exceed the
~16 MiB per-core budget (warning).

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _guarded_kernel(x_ref, o_ref):
    @pl.when(pl.program_id(0) == 0)
    def _():
        o_ref[...] = x_ref[...]


def arity_mismatch(x, block=128):
    n, d = x.shape
    return pl.pallas_call(
        _copy_kernel,
        grid=(n // block, d // block),
        in_specs=[pl.BlockSpec((block, block), lambda i: (i, 0))],  # GL020
        out_specs=pl.BlockSpec((block, block), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def stride_mismatch(x, block_n=128, block_k=64):
    n, _ = x.shape
    return pl.pallas_call(
        _copy_kernel,
        grid=(n // block_n,),
        in_specs=[pl.BlockSpec((block_k, 128), lambda i: (i, 0))],  # GL020
        out_specs=pl.BlockSpec((block_n, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def stride_guarded(x, block_n=128, block_k=64):
    n, _ = x.shape
    return pl.pallas_call(
        _guarded_kernel,
        grid=(n // block_n,),
        in_specs=[pl.BlockSpec((block_k, 128), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((block_n, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def arity_suppressed(x, block=128):
    n, d = x.shape
    return pl.pallas_call(
        _copy_kernel,
        grid=(n // block, d // block),
        in_specs=[pl.BlockSpec((block, block), lambda i: (i, 0))],  # graftlint: disable=GL020 (fixture: grid rank is dynamic upstream)
        out_specs=pl.BlockSpec((block, block), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def vmem_hog(x):
    n = 4096
    return pl.pallas_call(
        _guarded_kernel,
        grid=(n // 4096,),
        in_specs=[pl.BlockSpec((4096, 4096), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((4096, 4096), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, 4096), jnp.float32),
        scratch_shapes=[pltpu.VMEM((4096, 128), jnp.float32)],
    )(x)
