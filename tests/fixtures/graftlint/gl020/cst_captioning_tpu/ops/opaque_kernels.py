"""GL020 provably-cannot twin: the grid arrives through an attribute and
the in_specs through a helper call — single-file analysis provably
cannot resolve either, so the rule must stay quiet rather than guess."""

import jax
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def make_specs(block):
    return [pl.BlockSpec((block, block), lambda i: (i, 0))]


def opaque(x, cfg):
    return pl.pallas_call(
        _kernel,
        grid=cfg.grid,
        in_specs=make_specs(cfg.block),
        out_specs=pl.BlockSpec((cfg.block, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)
