"""GL017 cross-file fixture — the DONATING side.

``fused_update`` donates its arg 0 when called (literal
``donate_argnums`` decoration); ``make_step`` is the factory pattern —
calling it RETURNS a donating jit. Callers in ``loop.py`` must treat a
buffer passed through either as deleted — a fact no per-file engine can
know from the caller alone.

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def fused_update(state, batch):
    return state


def _impl(state, batch):
    return state


def make_step():
    return jax.jit(_impl, donate_argnums=(0,))
