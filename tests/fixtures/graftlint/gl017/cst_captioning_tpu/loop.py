"""GL017 cross-file fixture — callers of the donating side.

``bad_factory_use`` re-reads a buffer after a step built by the
``make_step`` factory donated it; ``bad_loop`` never rebinds the carry,
so iteration two reads the buffer iteration one donated. ``local_wrapper``
forwards its param into the donating ``fused_update``, and ``outer_jit``
wraps it in a donation-less ``jax.jit`` — the inner donation is silently
dropped. All three facts live in ``steps_lib.py``: linting this file
ALONE must find nothing.
"""

import jax

from cst_captioning_tpu.steps_lib import fused_update, make_step


def bad_factory_use(state, batch):
    step = make_step()
    new_state = step(state, batch)
    return new_state, state.step  # GL017: `state` was donated to step()


def bad_loop(state, batches):
    out = None
    for b in batches:
        out = fused_update(state, b)  # GL017: donated on iter 1, read on iter 2
    return out


def good_rebind(state, batches):
    for b in batches:
        state = fused_update(state, b)  # rebinding the carry is THE pattern
    return state


def good_read_before(state, batch):
    step_count = state.step
    new_state = fused_update(state, batch)
    return new_state, step_count


def suppressed(state, batch):
    new_state = fused_update(state, batch)
    return new_state, state.step  # graftlint: disable=GL017 (fixture: replay semantics, donation elided at runtime)


def local_wrapper(state, batch):
    # forwards `state` into fused_update's donated position (a cross-
    # module fact the index fixpoint carries back here)
    return fused_update(state, batch)


def outer_jit():
    return jax.jit(local_wrapper)  # GL017: drops local_wrapper's donation
