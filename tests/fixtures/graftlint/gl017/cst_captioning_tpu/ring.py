"""GL017 attribute-rooted fixture — ``self._buf``-style buffers donated
through method calls (the rl/async_scst.py RolloutRing shape).

``Ring._write`` is a donating staticmethod; ``bad_push`` donates
``self._buf`` through it and re-reads the attribute WITHOUT rebinding —
the use-after-donate. ``good_push`` rebinds (donate-and-rebind is THE
pattern) and must stay clean, as must ``good_read_first``.

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

import functools

import jax


class Ring:
    def __init__(self, buf):
        self._buf = buf

    @staticmethod
    @functools.partial(jax.jit, donate_argnums=(0,))
    def _write(buf, update, slot):
        return buf.at[slot].set(update)

    def bad_push(self, update, slot):
        out = self._write(self._buf, update, slot)
        return out, self._buf.shape  # GL017: self._buf donated, not rebound

    def good_push(self, update, slot):
        self._buf = self._write(self._buf, update, slot)
        return self._buf.shape

    def good_read_first(self, update, slot):
        shape = self._buf.shape
        out = self._write(self._buf, update, slot)
        return out, shape
