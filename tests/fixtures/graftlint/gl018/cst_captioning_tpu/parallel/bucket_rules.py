"""GL018 fixture — a NON-canonical regex partition-rule table (the name
ends with ``PARTITION_RULES`` but is not ``PARAM_PARTITION_RULES``, so
GL018 owns coverage here, not GL007).

Three findings: ``dec_again`` is fully shadowed by the earlier ``dec``
rule (first-match-wins dead row — the autofix deletes it), ``lstm_gate``
matches no contract param, and ``params/head/w`` is matched by no rule.

Deliberately lint-dirty directory: skipped by the repo-wide walk
(``fixtures`` is in core._SKIP_DIRS), linted explicitly by the tests.
"""

SHARDING_CONTRACT = "scripts/shardings_contract.json"

P = tuple  # stand-in spec type: GL018 only reads the (family, regex) prefix

COMM_PARTITION_RULES = (
    ("enc", r"params/enc/.*", P()),
    ("dec", r"params/dec/.*", P()),
    ("dec_again", r"params/dec/[wb]", P()),
    ("lstm_gate", r"params/lstm\d+/.*", P()),
)
