"""GL018 provably-cannot twin: the table is BUILT dynamically, so its
rows carry no literal (family, regex) prefix. Single-file analysis
provably cannot check coverage or shadowing here — the rule must stay
quiet rather than guess (a partially-parseable table is treated the
same way: all rows literal, or nothing is claimed)."""

SHARDING_CONTRACT = "scripts/shardings_contract.json"

_BASE = [("enc", r"params/enc/.*"), ("dec", r"params/dec/.*")]

DYN_PARTITION_RULES = tuple((f, p, ()) for f, p in _BASE)
