"""GL018 suppressed twin: ``enc_dup`` is fully shadowed by the
catch-all ``all`` rule, but the inline suppression keeps it quiet (and
the catch-all leaves no uncovered params, so nothing else fires)."""

SHARDING_CONTRACT = "scripts/shardings_contract.json"

P = tuple

ALT_PARTITION_RULES = (
    ("all", r"params/.*", P()),
    ("enc_dup", r"params/enc/w", P()),  # graftlint: disable=GL018 (fixture: kept as documentation of the enc family)
)
