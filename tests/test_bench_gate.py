"""scripts/bench_gate.py negative tests: the gate must actually FAIL on
the violations it promises to catch — a parity bool silently flipped
false, a bench emitting a new schema without its required blocks, a torn
file from a killed run. (lint.sh runs the gate on the committed tree,
which only proves the green path; these prove the red path.)

No jax import — the gate is plain-JSON tooling and must stay runnable on
a box with nothing but the repo.
"""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench_gate", os.path.join(REPO, "scripts", "bench_gate.py")
)
bench_gate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_gate)


def _good_rl_online() -> dict:
    """A minimal BENCH_RL_ONLINE.json the gate accepts — mirrors the
    schema bench_rl_online.py writes."""
    return {
        "metric": "online_rl_requests_per_s",
        "device_kind": "cpu",
        "note": "non-TPU run: rerun on TPU for throughput acceptance",
        "rungs": {
            "frozen": {"requests_per_s": 10.0, "reward_mean": 1.0},
            "online": {
                "requests_per_s": 5.0,
                "learner_updates": 8,
                "param_swaps": 4,
                "dropped_stale": 0,
                "staleness_histogram": {"0": 1, "1": 4},
                "reward_trend": [0.5, 0.75],
            },
        },
        "parity": {
            "swap_parity_tokens_bit_exact": True,
            "swap_parity_replay_bit_exact": True,
            "swap_parity_logprobs_ulp_bounded_vs_fused": True,
            "swap_straddled_live_traffic": True,
            "two_runs_bit_identical_params": True,
            "versions_straddled": 2,
            "requests_checked": 16,
        },
        "parity_ok": True,
    }


def _run(tmp_path, data) -> int:
    if not isinstance(data, str):
        data = json.dumps(data)
    (tmp_path / "BENCH_RL_ONLINE.json").write_text(data)
    return bench_gate.main(["bench_gate", str(tmp_path)])


def test_gate_accepts_good_rl_online_ledger(tmp_path):
    assert _run(tmp_path, _good_rl_online()) == 0


def test_gate_rejects_false_parity_bool(tmp_path):
    bad = _good_rl_online()
    bad["parity"]["swap_parity_replay_bit_exact"] = False
    assert _run(tmp_path, bad) == 1


def test_gate_rejects_missing_swap_parity_pin(tmp_path):
    bad = _good_rl_online()
    del bad["parity"]["two_runs_bit_identical_params"]
    assert _run(tmp_path, bad) == 1


def test_gate_rejects_missing_online_rung_evidence(tmp_path):
    for field in ("learner_updates", "dropped_stale",
                  "staleness_histogram", "reward_trend"):
        bad = _good_rl_online()
        del bad["rungs"]["online"][field]
        assert _run(tmp_path, bad) == 1, field


def test_gate_rejects_missing_online_rung(tmp_path):
    bad = _good_rl_online()
    del bad["rungs"]["online"]
    assert _run(tmp_path, bad) == 1


def _good_serving() -> dict:
    """A minimal BENCH_SERVING.json the gate accepts — mirrors the schema
    bench_serving.py writes (the paged_inkernel rung portion)."""
    stats = {"completed": 4, "p50_s": 0.1, "p99_s": 0.2, "goodput_rps": 8.0}
    return {
        "metric": "serving_request_latency_and_slo_goodput",
        "device_kind": "cpu",
        "note": "non-TPU run: rerun on TPU for the flagship numbers",
        "traces": {
            "poisson": {"continuous": dict(stats), "static": dict(stats)},
        },
        "parity": {"continuous_vs_offline_bit_exact": True},
        "paged": {
            "requests_per_trace": 4,
            "traces": {
                "poisson": {
                    "paged_inkernel": dict(stats),
                    "dense_gather": dict(stats),
                },
                "bursty": {
                    "paged_inkernel": dict(stats),
                    "dense_gather": dict(stats),
                },
            },
            "per_stride_bank_bytes": {
                "paged_inkernel": 1000.0,
                "dense_gather": 3000.0,
                "bytes_avoided_frac": 0.6667,
            },
            "parity": {
                "paged_vs_gather_bit_exact": True,
                "checked_requests": 8,
            },
            "stress": {
                "pool_pages": 24,
                "dense_footprint_pages": 12,
                "pages_hwm": 20,
                "completed": 6,
                "requests": 6,
            },
        },
        "acceptance": {
            "continuous_beats_static_goodput": {"poisson": True},
            "paged_matches_dense_gather_bit_exact": True,
            "paged_pool_exceeds_dense_footprint": True,
            "gather_path_refuses_oversized_pool": True,
        },
    }


def _run_serving(tmp_path, data) -> int:
    (tmp_path / "BENCH_SERVING.json").write_text(json.dumps(data))
    return bench_gate.main(["bench_gate", str(tmp_path)])


def test_gate_accepts_good_serving_ledger(tmp_path):
    assert _run_serving(tmp_path, _good_serving()) == 0


def test_gate_rejects_missing_paged_rung(tmp_path):
    bad = _good_serving()
    del bad["paged"]
    assert _run_serving(tmp_path, bad) == 1


def test_gate_rejects_false_paged_parity(tmp_path):
    bad = _good_serving()
    bad["paged"]["parity"]["paged_vs_gather_bit_exact"] = False
    assert _run_serving(tmp_path, bad) == 1


def test_gate_rejects_missing_paged_evidence(tmp_path):
    # each required sub-block missing is a violation on its own
    for field in ("traces", "parity", "per_stride_bank_bytes", "stress"):
        bad = _good_serving()
        del bad["paged"][field]
        assert _run_serving(tmp_path, bad) == 1, field
    # a dense_gather leg dropped from a trace
    bad = _good_serving()
    del bad["paged"]["traces"]["bursty"]["dense_gather"]
    assert _run_serving(tmp_path, bad) == 1


def test_gate_rejects_paged_not_cheaper_than_gather(tmp_path):
    bad = _good_serving()
    bad["paged"]["per_stride_bank_bytes"]["paged_inkernel"] = 3000.0
    assert _run_serving(tmp_path, bad) == 1


def test_gate_rejects_stress_hwm_within_dense_footprint(tmp_path):
    bad = _good_serving()
    bad["paged"]["stress"]["pages_hwm"] = 12
    assert _run_serving(tmp_path, bad) == 1


def _good_scaling() -> dict:
    """A minimal BENCH_SCALING.json the gate accepts — mirrors the schema
    bench_scaling.py writes (dp points preserved + the flagship-XL mp
    block)."""
    return {
        "points": [{
            "metric": "rl_clips_per_sec_per_chip_cpu_mesh",
            "value": 1.0, "devices": 1,
        }],
        "summary": {
            "metric": "rl_weak_scaling_efficiency",
            "note": "weak scaling on forced-CPU virtual devices",
        },
        "mp": {
            "metric": "mp_stride_seconds_per_stride_cpu_mesh",
            "rungs": [
                {"mp": 1, "seconds_per_stride": 0.004},
                {"mp": 2, "seconds_per_stride": 0.012,
                 "merge_bytes_per_step_per_device": {
                     "emb_psum": 20480, "lse_and_select": 960,
                     "argmax_all_gather": 1280, "total": 22720,
                 }},
            ],
            "parity": {
                "stride_tokens_bit_exact": True,
                "beam_candidates_bit_exact": True,
                "stride_logprob_max_abs_diff": 4.8e-07,
            },
            "embedding_grad_ledger": {
                "mp1_bytes_on_wire_per_update": 100000,
                "mp2_bytes_on_wire_per_update": 60000,
            },
            "device_kind": "cpu",
            "note": "mp weak scaling on forced-CPU virtual devices",
        },
    }


def _run_scaling(tmp_path, data) -> int:
    (tmp_path / "BENCH_SCALING.json").write_text(json.dumps(data))
    return bench_gate.main(["bench_gate", str(tmp_path)])


def test_gate_accepts_good_scaling_ledger(tmp_path):
    assert _run_scaling(tmp_path, _good_scaling()) == 0


def test_gate_rejects_dropped_dp_points(tmp_path):
    # bench_scaling.py merges into the committed file — losing the dp
    # weak-scaling ladder would mean it started overwriting
    bad = _good_scaling()
    bad["points"] = []
    assert _run_scaling(tmp_path, bad) == 1


def test_gate_rejects_missing_mp_block(tmp_path):
    bad = _good_scaling()
    del bad["mp"]
    assert _run_scaling(tmp_path, bad) == 1


def test_gate_rejects_mp_block_without_sharded_rung(tmp_path):
    bad = _good_scaling()
    bad["mp"]["rungs"] = [{"mp": 1, "seconds_per_stride": 0.004}]
    assert _run_scaling(tmp_path, bad) == 1


def test_gate_rejects_mp_rung_without_merge_bytes(tmp_path):
    bad = _good_scaling()
    del bad["mp"]["rungs"][1]["merge_bytes_per_step_per_device"]
    assert _run_scaling(tmp_path, bad) == 1


def test_gate_rejects_false_mp_parity(tmp_path):
    bad = _good_scaling()
    bad["mp"]["parity"]["stride_tokens_bit_exact"] = False
    assert _run_scaling(tmp_path, bad) == 1


def test_gate_rejects_missing_mp_parity_pin(tmp_path):
    for pin in ("stride_tokens_bit_exact", "beam_candidates_bit_exact"):
        bad = _good_scaling()
        del bad["mp"]["parity"][pin]
        assert _run_scaling(tmp_path, bad) == 1, pin


def test_gate_rejects_mp_ledger_not_below_replicated(tmp_path):
    # the whole point of the mp dp-allreduce accounting: the sharded
    # payload must be strictly smaller
    bad = _good_scaling()
    bad["mp"]["embedding_grad_ledger"]["mp2_bytes_on_wire_per_update"] = \
        100000
    assert _run_scaling(tmp_path, bad) == 1


def test_gate_rejects_mp_block_without_note(tmp_path):
    bad = _good_scaling()
    bad["mp"]["note"] = ""
    assert _run_scaling(tmp_path, bad) == 1


def test_gate_rejects_nontpu_without_note(tmp_path):
    bad = _good_rl_online()
    bad["note"] = None
    assert _run(tmp_path, bad) == 1


def test_gate_rejects_torn_json(tmp_path):
    assert _run(tmp_path, '{"metric": "online_rl_requests_per_s", "par') == 1


def test_gate_on_committed_tree_is_clean():
    """The committed BENCH_*.json set keeps its own promises — the exact
    invocation scripts/lint.sh runs."""
    assert bench_gate.main(["bench_gate", REPO]) == 0
