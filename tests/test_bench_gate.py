"""scripts/bench_gate.py negative tests: the gate must actually FAIL on
the violations it promises to catch — a parity bool silently flipped
false, a bench emitting a new schema without its required blocks, a torn
file from a killed run. (lint.sh runs the gate on the committed tree,
which only proves the green path; these prove the red path.)

No jax import — the gate is plain-JSON tooling and must stay runnable on
a box with nothing but the repo.
"""

import importlib.util
import json
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "bench_gate", os.path.join(REPO, "scripts", "bench_gate.py")
)
bench_gate = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench_gate)


def _good_rl_online() -> dict:
    """A minimal BENCH_RL_ONLINE.json the gate accepts — mirrors the
    schema bench_rl_online.py writes."""
    return {
        "metric": "online_rl_requests_per_s",
        "device_kind": "cpu",
        "note": "non-TPU run: rerun on TPU for throughput acceptance",
        "rungs": {
            "frozen": {"requests_per_s": 10.0, "reward_mean": 1.0},
            "online": {
                "requests_per_s": 5.0,
                "learner_updates": 8,
                "param_swaps": 4,
                "dropped_stale": 0,
                "staleness_histogram": {"0": 1, "1": 4},
                "reward_trend": [0.5, 0.75],
            },
        },
        "parity": {
            "swap_parity_tokens_bit_exact": True,
            "swap_parity_replay_bit_exact": True,
            "swap_parity_logprobs_ulp_bounded_vs_fused": True,
            "swap_straddled_live_traffic": True,
            "two_runs_bit_identical_params": True,
            "versions_straddled": 2,
            "requests_checked": 16,
        },
        "parity_ok": True,
    }


def _run(tmp_path, data) -> int:
    if not isinstance(data, str):
        data = json.dumps(data)
    (tmp_path / "BENCH_RL_ONLINE.json").write_text(data)
    return bench_gate.main(["bench_gate", str(tmp_path)])


def test_gate_accepts_good_rl_online_ledger(tmp_path):
    assert _run(tmp_path, _good_rl_online()) == 0


def test_gate_rejects_false_parity_bool(tmp_path):
    bad = _good_rl_online()
    bad["parity"]["swap_parity_replay_bit_exact"] = False
    assert _run(tmp_path, bad) == 1


def test_gate_rejects_missing_swap_parity_pin(tmp_path):
    bad = _good_rl_online()
    del bad["parity"]["two_runs_bit_identical_params"]
    assert _run(tmp_path, bad) == 1


def test_gate_rejects_missing_online_rung_evidence(tmp_path):
    for field in ("learner_updates", "dropped_stale",
                  "staleness_histogram", "reward_trend"):
        bad = _good_rl_online()
        del bad["rungs"]["online"][field]
        assert _run(tmp_path, bad) == 1, field


def test_gate_rejects_missing_online_rung(tmp_path):
    bad = _good_rl_online()
    del bad["rungs"]["online"]
    assert _run(tmp_path, bad) == 1


def test_gate_rejects_nontpu_without_note(tmp_path):
    bad = _good_rl_online()
    bad["note"] = None
    assert _run(tmp_path, bad) == 1


def test_gate_rejects_torn_json(tmp_path):
    assert _run(tmp_path, '{"metric": "online_rl_requests_per_s", "par') == 1


def test_gate_on_committed_tree_is_clean():
    """The committed BENCH_*.json set keeps its own promises — the exact
    invocation scripts/lint.sh runs."""
    assert bench_gate.main(["bench_gate", REPO]) == 0
