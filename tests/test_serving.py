"""Serving subsystem: continuous batching, paged bank, drain, parity.

The load-bearing pin is ACCEPTANCE PARITY: a request admitted mid-flight
into the continuous-batching engine must emit token- AND logprob-bit-
identical output to the same clip decoded offline through
``decoding.fused.fused_decode`` — the admission/compaction seam must not
perturb RNG streams or attention reads. Everything else (pages, traffic,
drain/restore, NPAD selection, obs) hangs off the same tiny model.
"""

import dataclasses
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config.config import EOS_ID, PAD_ID, ModelConfig
from cst_captioning_tpu.decoding.fused import fused_decode
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.resilience.chaos import Fault, FaultPlan
from cst_captioning_tpu.serving import (
    CaptionService,
    ClipRequest,
    OutOfPages,
    PageBank,
    Trace,
    TrafficSpec,
    load_snapshot,
    make_trace,
    static_batch_serve,
)
from cst_captioning_tpu.serving.traffic import synth_request_features

MODAL = (("resnet", 16),)
T = 12
MAX_F = 8


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig(
        vocab_size=97, modalities=MODAL, d_embed=16, d_hidden=16, d_att=8,
        encoder="temporal_attention", dropout=0.0, max_len=T,
        max_frames=MAX_F, dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats0 = {"resnet": jnp.asarray(rng.normal(size=(1, MAX_F, 16)),
                                    jnp.float32)}
    masks0 = {"resnet": jnp.ones((1, MAX_F), jnp.float32)}
    params = model.init(
        jax.random.key(0), feats0, masks0, jnp.zeros((1, T), jnp.int32)
    )
    # EOS-biased so caption lengths vary (the continuous-batching regime);
    # shared by every path, so parity comparisons are unaffected
    bias = params["params"]["cell"]["out_proj"]["bias"]
    params["params"]["cell"]["out_proj"]["bias"] = bias.at[EOS_ID].add(2.0)
    return model, params


def _requests(frames=(1, 8, 3, 8, 2, 5), seed0=1000):
    out = []
    for i, F in enumerate(frames):
        rng = np.random.default_rng(100 + i)
        out.append(ClipRequest(
            req_id=f"r{i}",
            feats={"resnet": rng.normal(size=(F, 16)).astype(np.float32)},
            masks={"resnet": np.ones((F,), np.float32)},
            seed=seed0 + i,
        ))
    return out


def _offline(model, params, req, K=2, min_len=0):
    """The parity oracle: the clip decoded alone through fused.py, padded
    to max_frames like every offline caller pads."""
    pad = model.cfg.max_frames - req.num_frames
    f1 = {"resnet": jnp.asarray(
        np.pad(np.asarray(req.feats["resnet"], np.float32),
               ((0, pad), (0, 0)))[None]
    )}
    m1 = {"resnet": jnp.asarray(
        np.pad(np.asarray(req.masks["resnet"], np.float32), ((0, pad),))[None]
    )}
    g, gl, s, sl = jax.tree.map(np.asarray, fused_decode(
        model, params, f1, m1, jax.random.key(req.seed), num_rollouts=K,
        min_len=min_len,
    ))
    return (np.concatenate([g, s[:, 0]], axis=0),
            np.concatenate([gl, sl[:, 0]], axis=0))


def _assert_parity(model, params, report, reqs, K=2, min_len=0):
    for req in reqs:
        tok, lp = _offline(model, params, req, K=K, min_len=min_len)
        res = report.results[req.req_id]
        np.testing.assert_array_equal(res.tokens, tok, err_msg=req.req_id)
        np.testing.assert_array_equal(res.logprobs, lp, err_msg=req.req_id)


# ---- traffic ----------------------------------------------------------------


def test_trace_is_deterministic_and_replayable(tmp_path):
    spec = TrafficSpec(kind="poisson", rate_rps=5.0, num_requests=16,
                       seed=3, frame_choices=(1, 4, 8))
    a, b = make_trace(spec), make_trace(spec)
    assert a.items == b.items and len(a) == 16
    assert all(
        x.arrival_s <= y.arrival_s for x, y in zip(a.items, a.items[1:])
    )
    path = str(tmp_path / "trace.json")
    a.save(path)
    assert Trace.load(path).items == a.items
    # feature payloads regenerate bit-identically from the item seed
    f1, m1 = synth_request_features(a.items[0], MODAL)
    f2, _ = synth_request_features(a.items[0], MODAL)
    np.testing.assert_array_equal(f1["resnet"], f2["resnet"])
    assert m1["resnet"].shape == (a.items[0].num_frames,)


def test_bursty_trace_modulates_rate():
    spec = TrafficSpec(kind="bursty", rate_rps=10.0, num_requests=64,
                       seed=1, burst_factor=8.0, burst_len_s=1.0)
    t = make_trace(spec)
    # burst windows (even seconds) hold far more arrivals than quiet ones
    burst = sum(1 for i in t.items if int(i.arrival_s) % 2 == 0)
    assert burst > len(t) * 0.7


def test_traffic_spec_validation():
    with pytest.raises(ValueError, match="kind"):
        TrafficSpec(kind="steady")
    with pytest.raises(ValueError, match="rate"):
        TrafficSpec(rate_rps=0.0)
    with pytest.raises(ValueError, match="burst"):
        TrafficSpec(kind="bursty", burst_factor=0.5)


# ---- page bank --------------------------------------------------------------


def test_page_bank_alloc_free_accounting():
    bank = PageBank(num_pages=6, page_size=4)
    p1 = bank.alloc("a", 9)     # ceil(9/4) = 3 pages
    assert len(p1) == 3 and bank.pages_in_use == 3
    assert 0 not in p1          # page 0 is the reserved zero page
    p2 = bank.alloc("b", 4)
    assert len(p2) == 1 and bank.pages_in_use == 4
    with pytest.raises(OutOfPages):
        bank.alloc("c", 12)     # 3 pages needed, 2 free
    with pytest.raises(ValueError, match="already holds"):
        bank.alloc("a", 4)
    table = bank.table(["a", "b", None], width=3)
    assert table.shape == (3, 3)
    np.testing.assert_array_equal(table[0], p1)
    assert table[1, 0] == p2[0] and (table[1, 1:] == 0).all()
    assert (table[2] == 0).all()
    bank.free("a")
    assert bank.pages_in_use == 1 and bank.free_pages == 5
    assert bank.alloc("c", 12) and bank.pages_in_use == 4
    snap = bank.snapshot()
    assert snap["page_size"] == 4 and set(snap["owned"]) == {"b", "c"}


# ---- the acceptance pin: mid-flight admission parity ------------------------


def test_midflight_admission_is_bit_identical_to_offline(setup):
    """capacity 2 << 6 ragged requests: most requests are admitted into
    lanes freed mid-flight while other requests sit at arbitrary local
    steps. Token AND logprob parity with the offline B=1 fused decode pins
    that the admission/compaction seam perturbs nothing."""
    model, params = setup
    reqs = _requests()
    svc = CaptionService(model, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    report = svc.serve(reqs)
    assert report.completed == len(reqs) and not report.drained
    # continuous batching actually happened: more strides than one batch
    # of 2 would need, and every slot was reused
    assert report.strides > (T // 4)
    _assert_parity(model, params, report, reqs)
    # all pages and slots returned
    assert svc.bank.pages_in_use == 0 and len(svc._free_slots) == 2


def test_serving_parity_with_min_len(setup):
    """min_len rides per-ROW in the serving step (each request's own local
    t), matching offline ``apply_min_len`` bit-for-bit."""
    model, params = setup
    reqs = _requests(frames=(2, 8, 5))
    report = CaptionService(
        model, params, capacity=2, num_rollouts=2, stride=4, min_len=3,
    ).serve(reqs)
    for res in report.results.values():
        lens = (res.tokens != PAD_ID).sum(axis=1)
        assert (lens >= 3).all()
    _assert_parity(model, params, report, reqs, min_len=3)


def test_serving_pallas_kernel_parity(setup):
    """The stride-kernel path (per-row mem_lens raggedness, in-kernel
    selection, kernel_block_b=1) is bit-identical to the same clips decoded
    offline through the pallas stride path."""
    model, params = setup
    m_pal = CaptionModel(dataclasses.replace(
        model.cfg, decode_impl="pallas", decode_stride=4,
    ))
    reqs = _requests()
    report = CaptionService(
        m_pal, params, capacity=2, num_rollouts=2, stride=4, frame_bucket=2,
    ).serve(reqs)
    _assert_parity(m_pal, params, report, reqs)


def test_page_table_stress_adversarial_ragged(setup):
    """1-frame and max-frame clips interleaved through a pool deliberately
    too small to hold the working set: admission backpressures on pages,
    every request still completes with bit-exact output, and the bank
    drains back to empty."""
    model, params = setup
    frames = [1, 8, 1, 8, 1, 8, 1, 8, 1, 8]
    reqs = _requests(frames=frames, seed0=7000)
    svc = CaptionService(
        model, params, capacity=4, num_rollouts=1, stride=4, frame_bucket=1,
        page_size=2, num_pages=6,  # 12 slots: < 2 max-frame clips' worth
    )
    report = svc.serve(reqs)
    assert report.completed == len(reqs)
    _assert_parity(model, params, report, reqs, K=1)
    assert svc.bank.pages_in_use == 0
    assert svc.bank.pages_hwm <= 6


def test_single_request_larger_than_pool_raises(setup):
    model, params = setup
    svc = CaptionService(model, params, capacity=2, num_rollouts=1,
                         page_size=2, num_pages=2)
    with pytest.raises(OutOfPages, match="more pages than the whole pool"):
        svc.serve(_requests(frames=(8,)))


# ---- paged in-kernel attention: pool past the dense footprint ---------------


def test_paged_pool_exceeds_dense_footprint_with_staging(setup):
    """THE paged acceptance pin: a pool of 20 pages over 2 lanes x 4
    pages/row (dense footprint 8) actually FILLS — encode-ahead staging
    parks encoded requests' pages while lanes are busy, the high-water
    mark exceeds what any dense [B, W, E] bank could hold, and every
    request is still token- and logprob-bit-identical to its offline
    decode. The dense-gather path refuses the same pool at construction
    — the in-kernel page reader is what makes the surplus admissible."""
    model, params = setup
    m_pal = CaptionModel(dataclasses.replace(
        model.cfg, decode_impl="pallas", decode_stride=4,
    ))
    reqs = _requests(frames=(8,) * 8, seed0=9000)
    svc = CaptionService(
        m_pal, params, capacity=2, num_rollouts=1, stride=4, frame_bucket=1,
        page_size=2, num_pages=20,
    )
    assert svc.paged and svc.B * svc.table_width == 8
    report = svc.serve(reqs)
    assert report.completed == len(reqs) and not report.drained
    # the pool genuinely held more than one batch's dense-bank worth
    assert svc.bank.pages_hwm > svc.B * svc.table_width
    _assert_parity(m_pal, params, report, reqs, K=1)
    assert svc.bank.pages_in_use == 0 and len(svc._free_slots) == 2
    # the same pool is impossible on the gather path: it re-materializes
    # every lane's full window per stride, so surplus pages never admit
    with pytest.raises(ValueError, match="dense-bank footprint"):
        CaptionService(model, params, capacity=2, num_rollouts=1,
                       stride=4, frame_bucket=1, page_size=2, num_pages=20)


def test_paged_requires_kernel_path(setup):
    """paged=True without the stride kernel is a loud constructor error —
    the XLA decode has no in-kernel page reader to honor it."""
    model, params = setup
    with pytest.raises(ValueError, match="decode_impl='pallas'"):
        CaptionService(model, params, capacity=2, num_rollouts=1,
                       paged=True)


def test_paged_hot_swap_midflight_parity(setup):
    """Cross-version strides on the paged path: a publish straddling live
    traffic dispatches one masked paged stride per live version, and every
    request stays bit-identical to its offline decode under its
    admission-pinned params."""
    model, params = setup
    m_pal = CaptionModel(dataclasses.replace(
        model.cfg, decode_impl="pallas", decode_stride=4,
    ))
    p2 = _perturbed(params)
    reqs = _requests()
    svc = CaptionService(m_pal, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    assert svc.paged
    published = []

    def feedback(req, result, version):
        if not published:
            published.append(svc.publish_params(p2, version=1))

    svc._feedback = feedback
    report = svc.serve(reqs)
    assert published == [True]
    assert report.completed == len(reqs) and not report.drained
    by_ver = {0: [], 1: []}
    for req in reqs:
        by_ver[report.results[req.req_id].param_version].append(req)
    assert by_ver[0] and by_ver[1]
    _assert_parity(m_pal, params, report, by_ver[0])
    _assert_parity(m_pal, p2, report, by_ver[1])
    assert svc._old_params == {}


def test_npad_best_lane_selection(setup):
    """NPAD anytime-quality: the served caption is the best-scoring lane,
    so its total logprob is >= the greedy lane's by construction, and the
    caption ids are that lane's tokens up to EOS."""
    model, params = setup
    reqs = _requests(frames=(4, 8, 6), seed0=4000)
    report = CaptionService(
        model, params, capacity=3, num_rollouts=3, temperature=1.3,
    ).serve(reqs)
    for res in report.results.values():
        sums = res.logprobs.sum(axis=1)
        assert sums[res.best_lane] == sums.max()
        assert sums[res.best_lane] >= sums[0]
        row = res.tokens[res.best_lane]
        expect = []
        for tok in row:
            if tok in (EOS_ID, PAD_ID):
                break
            expect.append(int(tok))
        assert res.caption_ids == expect
        assert set(res.phases) == {"queue_wait", "encode", "decode", "detok"}


def test_batched_admission_encode_group_parity(setup):
    """admit_group > 1 batches same-bucket admission encodes into one pass;
    at f32 the encoder gemm is row-stable, so parity must hold bit-for-bit
    (the knob's contract — bf16-on-CPU is documented out)."""
    model, params = setup
    reqs = _requests(frames=(8, 8, 8, 8), seed0=5000)
    report = CaptionService(
        model, params, capacity=4, num_rollouts=2, admit_group=4,
    ).serve(reqs)
    _assert_parity(model, params, report, reqs)


# ---- drain / snapshot / recovery --------------------------------------------


def test_serving_preempt_chaos_drains_and_recovers_bit_identical(
    setup, tmp_path
):
    """The seeded ``serving_preempt`` fault drains the loop mid-flight:
    in-flight strides finish, admissions stop, queue + page table persist.
    Replaying the drained queue through a fresh service completes the
    remaining requests BIT-identically to the undrained run."""
    model, params = setup
    reqs = _requests()
    base = CaptionService(model, params, capacity=2, num_rollouts=2,
                          stride=4, frame_bucket=2).serve(reqs)

    snap = str(tmp_path / "drain")
    plan = FaultPlan([Fault("serving.step", "serving_preempt", at=3)])
    svc = CaptionService(model, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    with plan.activate():
        drained = svc.serve(_requests(), snapshot_dir=snap)
    assert plan.fired and plan.fired[0]["kind"] == "serving_preempt"
    assert drained.drained and drained.drain_reason == "chaos_serving_preempt"
    assert drained.completed < len(reqs)
    assert os.path.exists(os.path.join(snap, "manifest.json"))
    assert os.path.exists(os.path.join(snap, "queue.npz"))

    restored = load_snapshot(snap)
    assert len(restored) == len(reqs) - drained.completed
    replay = CaptionService(model, params, capacity=2, num_rollouts=2,
                            stride=4, frame_bucket=2).serve(restored)
    union = dict(drained.results)
    union.update(replay.results)
    assert set(union) == set(base.results)
    for rid, res in base.results.items():
        np.testing.assert_array_equal(union[rid].tokens, res.tokens, rid)
        np.testing.assert_array_equal(union[rid].logprobs, res.logprobs, rid)


def test_snapshot_records_page_table_and_order(setup, tmp_path):
    model, params = setup
    import json

    snap = str(tmp_path / "drain2")
    plan = FaultPlan([Fault("serving.step", "serving_preempt", at=2)])
    svc = CaptionService(model, params, capacity=2, num_rollouts=1,
                         stride=4, frame_bucket=2)
    with plan.activate():
        svc.serve(_requests(), snapshot_dir=snap)
    manifest = json.load(open(os.path.join(snap, "manifest.json")))
    assert manifest["drain_reason"] == "chaos_serving_preempt"
    pt = manifest["page_table"]
    assert pt["num_pages"] == svc.bank.num_pages
    # stride-boundary drain: requests were genuinely IN FLIGHT at the cut
    assert manifest["in_flight_steps"]
    # in-flight requests lead the persisted order (admitted earlier)
    inflight = set(manifest["in_flight_steps"])
    lead = [r["req_id"] for r in manifest["requests"][:len(inflight)]]
    assert set(lead) == inflight


def test_snapshot_replays_onto_regrown_service(setup, tmp_path):
    """ISSUE 17 serving arc: a drained shard's queue+page snapshot replays
    onto a rejoined node — the replacement service comes up at the reduced
    width the outage left it, grows its lane pool back at a stride seam
    (pages added to the bank, lanes born finished), and completes the
    drained requests bit-identically to an undrained full-width run."""
    model, params = setup
    reqs = _requests()
    base = CaptionService(model, params, capacity=4, num_rollouts=2,
                          stride=4, frame_bucket=2).serve(reqs)

    snap = str(tmp_path / "regrow")
    plan = FaultPlan([Fault("serving.step", "serving_preempt", at=3)])
    svc = CaptionService(model, params, capacity=4, num_rollouts=2,
                         stride=4, frame_bucket=2)
    with plan.activate():
        drained = svc.serve(_requests(), snapshot_dir=snap)
    assert drained.drained and drained.completed < len(reqs)

    # the rejoined node starts at the degraded width, then grows back to
    # full width before admissions resume
    regrown = CaptionService(model, params, capacity=2, num_rollouts=2,
                             stride=4, frame_bucket=2)
    pages_before = regrown.bank.num_pages
    restored = load_snapshot(snap, service=regrown, grow_to=4)
    assert len(restored) == len(reqs) - drained.completed
    assert regrown.B == 4 and len(regrown._free_slots) == 4
    assert (regrown.bank.num_pages
            == pages_before + 2 * regrown.table_width)
    replay = regrown.serve(())  # the replayed queue is already submitted
    union = dict(drained.results)
    union.update(replay.results)
    assert set(union) == set(base.results)
    for rid, res in base.results.items():
        np.testing.assert_array_equal(union[rid].tokens, res.tokens, rid)
        np.testing.assert_array_equal(
            union[rid].logprobs, res.logprobs, rid
        )


def test_grow_capacity_with_live_state_preserves_parity(setup):
    """Growing the lane pool between serve calls (live lane state present)
    pads every lane-axis leaf with finished, empty lanes: later requests
    admitted at the grown width still decode bit-identically to the
    offline oracle, and shrinking in place is refused."""
    model, params = setup
    svc = CaptionService(model, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    first = _requests(frames=(1, 8, 3), seed0=1000)
    r1 = svc.serve(first)
    assert r1.completed == 3 and svc._state is not None
    svc.grow_capacity(5)
    assert svc.B == 5 and len(svc._free_slots) == 5
    second = [
        dataclasses.replace(r, req_id="g" + r.req_id)
        for r in _requests(frames=(8, 2, 5, 4, 6), seed0=2000)
    ]
    r2 = svc.serve(second)
    assert set(r2.results) >= {r.req_id for r in second}
    _assert_parity(model, params, r2, second)
    with pytest.raises(ValueError, match="only grows"):
        svc.grow_capacity(2)


def test_sigterm_drains_the_loop(setup, tmp_path):
    """A real SIGTERM mid-serve stops at the next stride boundary via the
    PreemptionHandler path (drain_reason='sigterm')."""
    model, params = setup
    import signal

    snap = str(tmp_path / "sig")
    svc = CaptionService(model, params, capacity=1, num_rollouts=1, stride=4)
    # many requests through one lane: plenty of stride boundaries
    reqs = _requests(frames=(8,) * 6, seed0=6000)
    timer = threading.Timer(
        0.05, lambda: os.kill(os.getpid(), signal.SIGTERM)
    )
    timer.start()
    try:
        report = svc.serve(reqs, snapshot_dir=snap)
    finally:
        timer.cancel()
    assert report.drained and report.drain_reason == "sigterm"
    assert report.completed < len(reqs)
    assert len(load_snapshot(snap)) == len(reqs) - report.completed


def test_static_batch_serve_completes_all(setup):
    model, params = setup
    reqs = _requests()
    report = static_batch_serve(model, params, reqs, capacity=2,
                                num_rollouts=2)
    assert report.completed == len(reqs)
    for res in report.results.values():
        assert res.tokens.shape == (3, T)


# ---- zero-sync discipline ---------------------------------------------------


def test_serving_loop_is_transfer_guard_clean(setup):
    """The warmed admission/decode loop holds under
    ``jax.transfer_guard("disallow")``: every host<->device crossing in the
    serving loop is explicit (device_put up, one device_get down per
    stride) — the empirical half of the GL001-clean claim."""
    model, params = setup
    svc = CaptionService(model, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    svc.serve(_requests(frames=(2, 8)))          # warm: compiles stage eagerly
    with jax.transfer_guard("disallow"):
        report = svc.serve(_requests(frames=(1, 8, 3), seed0=9000))
    assert report.completed == 3


# ---- obs --------------------------------------------------------------------


def test_serving_obs_events_and_report(setup, tmp_path):
    """A served run under obs leaves per-request phase histograms and
    span/request events that cli.obs_report aggregates into the serving
    section."""
    from cst_captioning_tpu import obs
    from cst_captioning_tpu.obs import metrics as obs_metrics
    from cst_captioning_tpu.obs.report import report_run, render_report

    model, params = setup
    obs_metrics.REGISTRY.reset()
    run_dir = str(tmp_path / "obsrun")
    obs.configure(run_dir, run="serve-test")
    try:
        CaptionService(model, params, capacity=2, num_rollouts=1,
                       stride=4).serve(_requests(frames=(2, 8, 5)))
        obs.snapshot_metrics()
    finally:
        obs.shutdown()
    rep = report_run(run_dir)
    sv = rep["serving"]
    assert sv is not None
    assert sv["submitted"] == 3 and sv["completed"] == 3
    assert sv["strides"] >= 1
    assert sv["phases"]["decode"]["count"] == 3
    assert sv["phases"]["queue_wait"]["count"] == 3
    text = render_report(rep)
    assert "serving: 3 submitted" in text
    # engine-loop spans land in the phase table
    names = {p["phase"] for p in rep["phases"]}
    assert {"serving.stride", "serving.encode"} <= names


def test_serving_drain_dumps_postmortem_with_slo_snapshot(setup, tmp_path):
    """PR 13 satellite: a drained service leaves a flight-recorder
    postmortem bundle whose registry carries the SLO snapshot, next to the
    obs event stream, renderable by cli.obs_report --postmortem."""
    from cst_captioning_tpu import obs
    from cst_captioning_tpu.obs import metrics as obs_metrics
    from cst_captioning_tpu.obs.report import load_postmortem

    model, params = setup
    obs_metrics.REGISTRY.reset()
    run_dir = str(tmp_path / "obsrun")
    obs.configure(run_dir, run="serve-drain")
    svc = CaptionService(model, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    svc.set_slo(30.0)
    plan = FaultPlan([Fault("serving.step", "serving_preempt", at=3)])
    try:
        with plan.activate():
            report = svc.serve(_requests(),
                               snapshot_dir=str(tmp_path / "drain"))
    finally:
        obs.shutdown()
    assert report.drained

    (bundle,) = [
        n for n in os.listdir(run_dir) if n.startswith("postmortem_")
    ]
    assert bundle.endswith("serving_drain_chaos_serving_preempt")
    pm = load_postmortem(os.path.join(run_dir, bundle))
    assert pm["verified"], pm["problems"]
    meta = pm["meta"]
    assert meta["drain_reason"] == "chaos_serving_preempt"
    assert meta["pending"] + meta["inflight"] > 0  # drained mid-flight
    sv = pm["registry"]["serving"]
    assert sv["drain_reason"] == "chaos_serving_preempt"
    assert sv["slo"] is not None and sv["slo"]["target_s"] == 30.0
    # the bundle names the param version that served (fleet attribution)
    assert sv["param_version"] == 0 and sv["param_swaps"] == 0
    snap = obs_metrics.snapshot()
    assert snap["counters"].get("serving.drain_postmortem_error") is None


# ---- SLO burn-rate monitor (Obs v2) -----------------------------------------


def test_slo_monitor_burn_rates_and_edge_triggered_alerts():
    """Multi-window burn-rate math on a fake clock: attainment/burn gauges,
    the breach counter, and the edge-triggered alert (fires once per
    excursion when BOTH windows burn hot; re-fires for a new excursion)."""
    from cst_captioning_tpu.obs import metrics as obs_metrics
    from cst_captioning_tpu.serving.engine import SloMonitor

    obs_metrics.REGISTRY.reset()
    mon = SloMonitor(0.1, objective=0.9, windows=(10.0, 100.0),
                     fast_burn=2.0, slow_burn=1.5)
    # 9 ok + 1 breach: attainment 0.9 == objective -> burning exactly at
    # budget (1.0x), no alert
    for i in range(9):
        mon.observe(0.05, now=float(i))
    mon.observe(0.5, now=9.0)
    snap = obs_metrics.snapshot()
    assert snap["gauges"]["serving.slo.attainment.10s"] == pytest.approx(0.9)
    assert snap["gauges"]["serving.slo.burn_rate.10s"] == pytest.approx(1.0)
    assert snap["counters"]["serving.slo.breaches"] == 1
    assert mon.alerts == 0

    # sustained breaches push BOTH windows over threshold: ONE alert for
    # the excursion, counted through the shared anomaly spelling
    for i in range(10, 16):
        mon.observe(0.5, now=float(i))
    snap = obs_metrics.snapshot()
    assert mon.alerts == 1
    assert snap["counters"]["serving.slo.alerts"] == 1
    assert snap["counters"]["obs.anomaly.slo_burn"] == 1

    # recovery clears the latch; a fresh excursion re-alerts
    for i in range(16, 40):
        mon.observe(0.01, now=float(i))
    assert mon.alerts == 1
    for i in range(40, 52):
        mon.observe(0.5, now=float(i))
    assert mon.alerts == 2

    # window expiry: 200s of silence ages everything out of both windows
    assert mon.burn_rate(10.0, now=260.0) == 0.0
    assert mon.burn_rate(100.0, now=260.0) == 0.0


def test_slo_monitor_validates_parameters():
    from cst_captioning_tpu.serving.engine import SloMonitor

    with pytest.raises(ValueError):
        SloMonitor(0.0)
    with pytest.raises(ValueError):
        SloMonitor(0.1, objective=1.0)
    with pytest.raises(ValueError):
        SloMonitor(0.1, windows=(600.0, 60.0))  # fast must be < slow


def test_service_set_slo_gauges_and_snapshot(setup):
    """set_slo arms the monitor after calibration (bench_serving's flow):
    served completions populate the target/attainment/burn gauges and
    slo_snapshot(); target <= 0 disarms."""
    from cst_captioning_tpu.obs import metrics as obs_metrics

    model, params = setup
    obs_metrics.REGISTRY.reset()
    svc = CaptionService(model, params, capacity=2, num_rollouts=1, stride=4)
    assert svc.slo_snapshot() is None  # disarmed by default
    svc.set_slo(30.0)  # generous target: every request lands within
    svc.serve(_requests(frames=(2, 8, 5)))
    snap = obs_metrics.snapshot()
    assert snap["gauges"]["serving.slo.target_s"] == 30.0
    assert snap["gauges"]["serving.slo.attainment.60s"] == 1.0
    assert snap["gauges"]["serving.slo.burn_rate.60s"] == 0.0
    assert snap["counters"].get("serving.slo.breaches") is None
    s = svc.slo_snapshot()
    assert s["target_s"] == 30.0 and s["breach_alerts"] == 0
    assert s["burn_rate"] == {"60s": 0.0, "600s": 0.0}
    svc.set_slo(0.0)
    assert svc.slo_snapshot() is None


# ---- drain-free hot param swap (online RL feedback loop) --------------------


def _perturbed(params, tok=5, delta=3.0):
    """A second param version whose captions visibly differ: copy the tree
    containers (leaves shared) and raise one output-bias logit."""
    p2 = jax.tree.map(lambda x: x, params)
    bias = p2["params"]["cell"]["out_proj"]["bias"]
    p2["params"]["cell"]["out_proj"]["bias"] = bias.at[tok].add(delta)
    return p2


def test_hot_param_swap_midflight_parity(setup):
    """THE swap acceptance pin: a publish landing while requests are in
    flight applies at a stride boundary; every request — admitted before OR
    after the swap — is token- and logprob-bit-identical to the offline
    fused decode under its admission-pinned params. The straddle window
    exercises mixed-version strides (one masked dispatch per live
    version)."""
    model, params = setup
    p2 = _perturbed(params)
    reqs = _requests()
    svc = CaptionService(model, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    svc.set_slo(30.0)
    published = []

    def feedback(req, result, version):
        if not published:
            published.append(svc.publish_params(p2, version=1))

    svc._feedback = feedback
    report = svc.serve(reqs)
    assert published == [True]
    assert report.completed == len(reqs) and not report.drained
    assert svc.param_version == 1
    assert len(svc._swap_history) == 1
    by_ver = {0: [], 1: []}
    for req in reqs:
        by_ver[report.results[req.req_id].param_version].append(req)
    # the swap genuinely straddled live traffic
    assert by_ver[0] and by_ver[1]
    _assert_parity(model, params, report, by_ver[0])
    _assert_parity(model, p2, report, by_ver[1])
    # the two versions really produce different captions (non-vacuous)
    assert any(
        not np.array_equal(_offline(model, params, r)[0],
                           _offline(model, p2, r)[0])
        for r in by_ver[1]
    )
    # the outgoing tree was retired once its last pinned lane completed
    assert svc._old_params == {}
    # slo snapshot names the active version
    assert svc.slo_snapshot()["param_version"] == 1
    # a replayed/stale publish is refused, not applied
    assert not svc.publish_params(params, version=1)
    assert svc.param_version == 1 and svc._pending_publish is None


def test_param_swap_chaos_preempt_refuses_never_tears(setup, tmp_path):
    """The seeded ``param_swap`` fault preempts EXACTLY mid-swap (after the
    publish staged, before application): the swap must be fully refused —
    active version unchanged, pending publish cleared — and the drained
    queue replays bit-identically under the OLD params."""
    model, params = setup
    p2 = _perturbed(params)
    reqs = _requests()
    base = CaptionService(model, params, capacity=2, num_rollouts=2,
                          stride=4, frame_bucket=2).serve(reqs)

    snap = str(tmp_path / "swapdrain")
    plan = FaultPlan([Fault("serving.param_swap", "param_swap", at=0)])
    svc = CaptionService(model, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    published = []

    def feedback(req, result, version):
        if not published:
            published.append(svc.publish_params(p2, version=1))

    svc._feedback = feedback
    with plan.activate():
        drained = svc.serve(_requests(), snapshot_dir=snap)
    assert plan.fired and plan.fired[0]["kind"] == "param_swap"
    assert drained.drained and drained.drain_reason == "chaos_param_swap"
    # fully refused: no version change, no torn half-applied state
    assert svc.param_version == 0 and svc._pending_publish is None
    assert svc._swap_history == [] and svc._old_params == {}
    # everything served (before and during the drain) ran under v0
    assert all(
        r.param_version == 0 for r in drained.results.values()
    )
    restored = load_snapshot(snap)
    replay = CaptionService(model, params, capacity=2, num_rollouts=2,
                            stride=4, frame_bucket=2).serve(restored)
    union = dict(drained.results)
    union.update(replay.results)
    assert set(union) == set(base.results)
    for rid, res in base.results.items():
        np.testing.assert_array_equal(union[rid].tokens, res.tokens, rid)
        np.testing.assert_array_equal(union[rid].logprobs, res.logprobs, rid)


def test_param_swap_obs_report_renders_versions(setup, tmp_path):
    """An applied swap lands in the run report's serving section (version
    gauge + swap counter) and the text rendering."""
    from cst_captioning_tpu import obs
    from cst_captioning_tpu.obs import metrics as obs_metrics
    from cst_captioning_tpu.obs.report import report_run, render_report

    model, params = setup
    p2 = _perturbed(params)
    obs_metrics.REGISTRY.reset()
    run_dir = str(tmp_path / "obsswap")
    obs.configure(run_dir, run="serve-swap")
    try:
        svc = CaptionService(model, params, capacity=2, num_rollouts=1,
                             stride=4)
        published = []

        def feedback(req, result, version):
            if not published:
                published.append(svc.publish_params(p2))

        svc._feedback = feedback
        svc.serve(_requests(frames=(2, 8, 5)))
        obs.snapshot_metrics()
    finally:
        obs.shutdown()
    rep = report_run(run_dir)
    sv = rep["serving"]
    assert sv["param_swaps"] == 1 and sv["param_swaps_refused"] == 0
    assert sv["param_version"] == 1.0
    assert "param swaps: 1 applied (active v1)" in render_report(rep)


# ---- bf16 batched-admission fallback ----------------------------------------


def test_bf16_admission_group_falls_back_to_per_request(setup):
    """admit_group > 1 promises row-stable grouped encodes; bf16 gemms are
    not row-stable, so a bf16 service demotes to per-request admission
    encode (the parity-preserving path) and counts the fallback. f32 keeps
    the grouped path (bit-exactness pinned above)."""
    model, params = setup
    m_bf16 = CaptionModel(dataclasses.replace(model.cfg, dtype="bfloat16"))
    svc = CaptionService(m_bf16, params, capacity=4, num_rollouts=1,
                         admit_group=4)
    assert svc.requested_admit_group == 4 and svc.admit_group == 1
    report = svc.serve(_requests(frames=(8, 8, 8, 8), seed0=5000))
    assert report.completed == 4
    svc32 = CaptionService(model, params, capacity=4, num_rollouts=1,
                           admit_group=4)
    assert svc32.requested_admit_group == 4 and svc32.admit_group == 4


# ---- pallas stride-kernel path: grow / snapshot-regrow ----------------------


def test_pallas_grow_capacity_with_live_state_preserves_parity(setup):
    """grow_capacity with live lane state on the pallas stride-kernel path
    (kernel_block_b=1 per-row raggedness): requests admitted at the grown
    width still decode bit-identically to the offline pallas oracle."""
    model, params = setup
    m_pal = CaptionModel(dataclasses.replace(
        model.cfg, decode_impl="pallas", decode_stride=4,
    ))
    svc = CaptionService(m_pal, params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=2)
    r1 = svc.serve(_requests(frames=(1, 8, 3), seed0=1000))
    assert r1.completed == 3 and svc._state is not None
    svc.grow_capacity(4)
    assert svc.B == 4 and len(svc._free_slots) == 4
    second = [
        dataclasses.replace(r, req_id="g" + r.req_id)
        for r in _requests(frames=(8, 2, 5, 4), seed0=2000)
    ]
    r2 = svc.serve(second)
    assert set(r2.results) >= {r.req_id for r in second}
    _assert_parity(m_pal, params, r2, second)


@pytest.mark.slow  # heaviest pallas compile chain; the grow-parity test
#                    above keeps the pallas grow seam in tier-1
def test_pallas_snapshot_replays_onto_regrown_service(setup, tmp_path):
    """load_snapshot(grow_to=) on the pallas stride-kernel path: a drained
    shard's queue replays onto a degraded-width pallas service grown back
    to full width, bit-identical to the undrained full-width run."""
    model, params = setup
    m_pal = CaptionModel(dataclasses.replace(
        model.cfg, decode_impl="pallas", decode_stride=4,
    ))
    reqs = _requests()
    base = CaptionService(m_pal, params, capacity=4, num_rollouts=2,
                          stride=4, frame_bucket=2).serve(reqs)

    snap = str(tmp_path / "palregrow")
    plan = FaultPlan([Fault("serving.step", "serving_preempt", at=3)])
    svc = CaptionService(m_pal, params, capacity=4, num_rollouts=2,
                         stride=4, frame_bucket=2)
    with plan.activate():
        drained = svc.serve(_requests(), snapshot_dir=snap)
    assert drained.drained and drained.completed < len(reqs)

    regrown = CaptionService(m_pal, params, capacity=2, num_rollouts=2,
                             stride=4, frame_bucket=2)
    restored = load_snapshot(snap, service=regrown, grow_to=4)
    assert len(restored) == len(reqs) - drained.completed
    assert regrown.B == 4 and len(regrown._free_slots) == 4
    replay = regrown.serve(())
    union = dict(drained.results)
    union.update(replay.results)
    assert set(union) == set(base.results)
    for rid, res in base.results.items():
        np.testing.assert_array_equal(union[rid].tokens, res.tokens, rid)
        np.testing.assert_array_equal(union[rid].logprobs, res.logprobs, rid)
