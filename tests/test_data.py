"""Data layer tests: vocab, synthetic fixtures, dataset, batcher, preprocess."""

import numpy as np
import pytest

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID, UNK_ID
from cst_captioning_tpu.data import (
    Batcher,
    CaptionDataset,
    Vocab,
    build_vocab,
    compute_cider_df,
    compute_consensus_weights,
    make_synthetic_dataset,
    tokenize_captions,
)


@pytest.fixture(scope="module")
def synth(tmp_path_factory):
    out = tmp_path_factory.mktemp("synth")
    paths = make_synthetic_dataset(
        str(out),
        num_videos=16,
        modalities={"resnet": 32, "c3d": 16},
        max_frames=6,
        seed=7,
    )
    return paths


def test_vocab_roundtrip():
    v = Vocab.from_corpus_words(["cat", "dog", "runs"])
    assert len(v) == 7
    ids = v.encode(["dog", "runs", "zebra"])
    assert ids == [v.encode(["dog"])[0], v.encode(["runs"])[0], UNK_ID]
    assert v.decode([BOS_ID] + v.encode(["cat", "runs"]) + [EOS_ID, PAD_ID]) == "cat runs"
    v2 = Vocab.from_json(v.to_json())
    assert v2.words == v.words


def test_synthetic_dataset_loads(synth):
    ds = CaptionDataset(
        synth["info_json"],
        {"resnet": synth["resnet"], "c3d": synth["c3d"]},
        split="train",
        max_frames=6,
    )
    assert len(ds) == 12  # 16 * 0.75
    feats = ds.features_for(ds.records[0].video_id)
    f, m = feats["resnet"]
    assert f.shape == (6, 32) and m.shape == (6,)
    assert m.sum() >= 2
    # masked-out frames are zero
    assert np.all(f[m == 0] == 0)
    pool = ds.gts_pool()
    assert all(len(caps) == 5 for caps in pool.values())
    ds.close()


def test_batcher_caption_mode_shapes(synth):
    ds = CaptionDataset(synth["info_json"], {"resnet": synth["resnet"]}, "train", 6)
    b = Batcher(ds, batch_size=5, max_len=12, mode="caption", seq_per_vid=2, seed=1)
    batches = list(b.epoch())
    assert len(batches) == b.num_batches()
    for batch in batches:
        assert batch.labels.shape == (5, 12)
        assert batch.mask.shape == (5, 12)
        assert batch.feats["resnet"].shape == (5, 6, 32)
        # every valid row ends with EOS at the last masked position
        for r in range(5):
            n = int(batch.mask[r].sum())
            assert n >= 1
            assert batch.labels[r, n - 1] == EOS_ID
            assert np.all(batch.labels[r, n:] == PAD_ID)
    # wrap-padding marks invalid rows
    total_valid = sum(b2.size for b2 in batches)
    assert total_valid == 12 * 2
    ds.close()


def test_batcher_video_mode_unique_ids(synth):
    ds = CaptionDataset(synth["info_json"], {"resnet": synth["resnet"]}, "test", 6)
    b = Batcher(ds, batch_size=3, max_len=12, mode="video")
    seen = []
    for batch in b.epoch(shuffle=False):
        seen.extend(v for v, ok in zip(batch.video_ids, batch.valid) if ok)
    assert sorted(seen) == sorted(r.video_id for r in ds.records)
    ds.close()


def test_preprocess_consensus_weights():
    raw = {
        "v1": ["a cat runs fast", "a cat runs", "a dog sleeps here now"],
        "v2": ["the sun is bright", "the sun is very bright"],
    }
    tok = tokenize_captions(raw)
    v = build_vocab(tok, min_count=1)
    assert "<unk>" in v.words and "cat" in v.words
    w = compute_consensus_weights(tok)
    assert set(w) == {"v1", "v2"}
    # the outlier caption ("a dog sleeps...") gets the lowest consensus weight
    assert np.argmin(w["v1"]) == 2
    # mean-1 normalization per video
    for arr in w.values():
        assert arr.mean() == pytest.approx(1.0, abs=1e-5)
    df = compute_cider_df(tok)
    assert df.num_docs == 2
    assert df.df  # non-empty


def test_prefetch_to_device(synth):
    import jax

    from cst_captioning_tpu.data.prefetch import prefetch_to_device

    ds = CaptionDataset(synth["info_json"], {"resnet": synth["resnet"]}, "train", 6)
    b = Batcher(ds, batch_size=4, max_len=10, mode="caption")
    out = list(
        prefetch_to_device(
            b.epoch(shuffle=False),
            size=2,
            transform=lambda batch: {"labels": batch.labels, "mask": batch.mask},
        )
    )
    assert len(out) == b.num_batches()
    assert isinstance(out[0]["labels"], jax.Array)
    ds.close()


def test_prefetch_propagates_errors():
    from cst_captioning_tpu.data.prefetch import prefetch_to_device

    def bad_iter():
        yield np.zeros(3)
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        list(prefetch_to_device(bad_iter(), size=2))


def test_prefetch_early_abandon_does_not_leak_worker():
    import threading

    from cst_captioning_tpu.data.prefetch import prefetch_to_device

    n_before = threading.active_count()

    def src():
        for i in range(100):
            yield np.full((2,), i)

    it = prefetch_to_device(src(), size=2)
    next(it)
    it.close()  # abandon early -> generator finally must retire the worker
    # worker must exit promptly rather than blocking on a full queue
    for _ in range(50):
        if threading.active_count() <= n_before:
            break
        import time

        time.sleep(0.05)
    assert threading.active_count() <= n_before


def test_dataset_rejects_missing_weights_and_empty_captions(synth, tmp_path):
    import json

    with pytest.raises(FileNotFoundError):
        CaptionDataset(
            synth["info_json"],
            {"resnet": synth["resnet"]},
            "train",
            6,
            consensus_weights=str(tmp_path / "nope.npz"),
        )
    with open(synth["info_json"]) as f:
        info = json.load(f)
    info["videos"][0]["caption_ids"] = []
    bad = tmp_path / "bad_info.json"
    bad.write_text(json.dumps(info))
    with pytest.raises(ValueError, match="no captions"):
        CaptionDataset(str(bad), {"resnet": synth["resnet"]}, "train", 6)


def test_synthetic_template_style(tmp_path):
    """caption_style="template": same-topic videos share consensus n-gram
    structure (noisy realizations of the topic's canonical phrases) while
    different topics share none — the precondition bench_recipe.py's
    XE-vs-CST comparison rests on. feature_noise scales the per-video
    fingerprint amplitude."""
    import collections
    import json as _json

    paths = make_synthetic_dataset(
        str(tmp_path),
        num_videos=24,
        num_topics=2,
        vocab_words=80,
        captions_per_video=10,
        caption_len=(5, 9),
        modalities={"resnet": 16},
        max_frames=4,
        seed=11,
        caption_style="template",
        template_noise=0.2,
        feature_noise=0.01,
    )
    info = _json.load(open(paths["info_json"]))
    by_topic = collections.defaultdict(list)
    for v in info["videos"]:
        by_topic[v["topic"]].append(v)

    def bigrams(video):
        s = set()
        for c in video["captions"]:
            w = c.split()
            s |= set(zip(w, w[1:]))
        return s

    t0, t1 = by_topic[0], by_topic[1]
    same = bigrams(t0[0]) & bigrams(t0[1])
    cross = bigrams(t0[0]) & bigrams(t1[0])
    assert len(same) > 3       # consensus transfers across same-topic videos
    assert len(cross) == 0     # disjoint word pools -> no cross-topic overlap

    # low feature_noise: same-topic features nearly identical frame-to-frame
    import h5py

    with h5py.File(paths["resnet"], "r") as f:
        a = np.asarray(f[t0[0]["id"]])
        b = np.asarray(f[t0[1]["id"]])
        x = np.asarray(f[t1[0]["id"]])
    assert np.abs(a.mean(0) - b.mean(0)).max() < 0.1     # same topic: close
    assert np.abs(a.mean(0) - x.mean(0)).max() > 0.5     # cross topic: far

    with pytest.raises(ValueError, match="caption_style"):
        make_synthetic_dataset(str(tmp_path / "bad"), caption_style="nope")


def test_feature_cache_serves_without_h5(synth):
    """cache_features=True: after a warm pass, features come from host RAM —
    identical to the uncached reads, and served even once the h5 stores are
    closed (proving repeat epochs do zero h5 IO)."""
    cold = CaptionDataset(
        synth["info_json"], {"resnet": synth["resnet"]}, "train", 6
    )
    warm = CaptionDataset(
        synth["info_json"], {"resnet": synth["resnet"]}, "train", 6,
        cache_features=True,
    )
    ids = [r.video_id for r in warm.records]
    baseline = {v: cold.features_for(v) for v in ids}
    for v in ids:
        warm.features_for(v)
    for s in warm.stores.values():
        s.close()                      # h5 gone; cache must stand alone
    for v in ids:
        f, m = warm.features_for(v)["resnet"]
        np.testing.assert_array_equal(f, baseline[v]["resnet"][0])
        np.testing.assert_array_equal(m, baseline[v]["resnet"][1])
    cold.close()
