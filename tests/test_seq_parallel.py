"""Sequence-parallelism parity: frame-sharded model == single-device model.

SURVEY.md §5 long-context row: shard the frame axis over the mesh, psum the
attention numerator/denominator pair. These tests pin the collective softmax,
the pooled carry init, decode, beam, and training gradients against the
unsharded implementation on 8 fake CPU devices.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.compat import shard_map
from cst_captioning_tpu.config.config import ModelConfig, TrainConfig
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.parallel import (
    make_sp_decode,
    make_sp_forward,
    make_sp_rl_update,
    make_sp_xe_step,
    sp_batch_specs,
    sp_model,
)
from cst_captioning_tpu.train import create_train_state, make_optimizer
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

V, B, F, T = 20, 4, 16, 6   # F=16 shards 8 ways (2 frames/device)


def mesh_1d(axis="seq"):
    return Mesh(np.asarray(jax.devices()), (axis,))


def mesh_2d(data=2, seq=4):
    return Mesh(np.asarray(jax.devices()).reshape(data, seq), ("data", "seq"))


@pytest.fixture(scope="module", params=["temporal_attention", "meanpool"])
def setup(request):
    cfg = ModelConfig(
        vocab_size=V,
        modalities=(("resnet", 10), ("c3d", 6)),
        d_embed=12,
        d_hidden=12,
        d_att=8,
        encoder=request.param,
        dropout=0.0,
        max_len=T,
        max_frames=F,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {
        "resnet": jnp.asarray(rng.normal(size=(B, F, 10)), jnp.float32),
        "c3d": jnp.asarray(rng.normal(size=(B, F, 6)), jnp.float32),
    }
    # ragged frame validity to exercise the masked collective softmax,
    # including one device's shard being fully masked for some rows
    masks = {
        k: jnp.asarray(
            (np.arange(F)[None, :] < rng.integers(3, F + 1, size=(B, 1))),
            jnp.float32,
        )
        for k in feats
    }
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)
    params = model.init(jax.random.key(0), feats, masks, labels)
    return cfg, model, params, feats, masks, labels


def _place(mesh, cfg, feats, masks, data_axis=""):
    f_spec, m_spec = sp_batch_specs(cfg, data_axis)
    f = {k: jax.device_put(v, NamedSharding(mesh, f_spec[k])) for k, v in feats.items()}
    m = {k: jax.device_put(v, NamedSharding(mesh, m_spec[k])) for k, v in masks.items()}
    return f, m


def test_sp_forward_matches_single_device(setup):
    cfg, model, params, feats, masks, labels = setup
    want = model.apply(params, feats, masks, labels)

    mesh = mesh_1d()
    spm = sp_model(cfg)
    f, m = _place(mesh, cfg, feats, masks)
    got = make_sp_forward(spm, mesh)(params, f, m, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=1e-5)


def test_sp_greedy_decode_matches_single_device(setup):
    from cst_captioning_tpu.decoding import greedy_decode

    cfg, model, params, feats, masks, _ = setup
    want, _ = greedy_decode(model, params, feats, masks, max_len=T)

    mesh = mesh_1d()
    spm = sp_model(cfg)
    f, m = _place(mesh, cfg, feats, masks)
    got, samples = make_sp_decode(spm, mesh, num_rollouts=2, max_len=T)(
        params, f, m, jax.random.key(1)
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert samples.shape == (2, B, T)
    s = np.asarray(samples)
    assert (s >= 0).all() and (s < V).all()


def test_sp_beam_search_matches_single_device(setup):
    from cst_captioning_tpu.decoding import beam_search

    cfg, model, params, feats, masks, _ = setup
    want, _ = beam_search(model, params, feats, masks, beam_size=3, max_len=T)

    mesh = mesh_1d()
    spm = sp_model(cfg)
    f, m = _place(mesh, cfg, feats, masks)
    sharded = jax.jit(shard_map(
        lambda p, fe, ma: beam_search(spm, p, fe, ma, beam_size=3, max_len=T)[0],
        mesh=mesh,
        in_specs=(P(),) + sp_batch_specs(cfg),
        out_specs=P(),
    ))
    got = sharded(params, f, m)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("data_axis", ["", "data"])
def test_sp_xe_step_matches_single_device(setup, data_axis):
    """SP (and DP x SP) gradients through the collective softmax are exact."""
    from cst_captioning_tpu.train.steps import make_xe_step

    cfg, model, params, feats, masks, labels = setup
    mask = jnp.ones((B, T), jnp.float32)
    weights = jnp.ones((B,), jnp.float32)
    tx = make_optimizer(TrainConfig(lr=1e-2, grad_clip=5.0), 10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=3)

    s_state, s_m = make_xe_step(model)(state, feats, masks, labels, mask, weights)

    mesh = mesh_2d() if data_axis else mesh_1d()
    spm = sp_model(cfg)
    f, m = _place(mesh, cfg, feats, masks, data_axis)
    step = make_sp_xe_step(spm, mesh, data_axis=data_axis)
    b_shard = (
        NamedSharding(mesh, P("data")) if data_axis
        else NamedSharding(mesh, P())
    )
    p_state, p_m = step(
        state,
        f,
        m,
        jax.device_put(labels, b_shard),
        jax.device_put(mask, b_shard),
        jax.device_put(weights, b_shard),
    )
    np.testing.assert_allclose(float(s_m["loss"]), float(p_m["loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_state.params),
        jax.tree_util.tree_leaves(p_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
        )


def test_sp_dp_greedy_decode_matches_single_device(setup):
    """make_sp_decode with a data axis (the product DP x SP layout): greedy
    tokens on a 2x4 mesh == the single-device decode."""
    from cst_captioning_tpu.decoding import greedy_decode

    cfg, model, params, feats, masks, _ = setup
    want, _ = greedy_decode(model, params, feats, masks, max_len=T)

    mesh = mesh_2d()
    spm = sp_model(cfg)
    f, m = _place(mesh, cfg, feats, masks, "data")
    got, samples = make_sp_decode(
        spm, mesh, num_rollouts=2, max_len=T, data_axis="data"
    )(params, f, m, jax.random.key(1))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert samples.shape == (2, B, T)
    s = np.asarray(samples)
    assert (s >= 0).all() and (s < V).all()


def test_sp_rl_update_matches_single_device(setup):
    """make_sp_rl_update on a 2x4 mesh: same rollouts + advantages produce
    the same post-update params as the single-device REINFORCE update
    (gradients through the 'seq' attention collectives are exact)."""
    from jax.sharding import NamedSharding
    from cst_captioning_tpu.rl.scst import make_rl_update

    cfg, model, params, feats, masks, labels = setup
    K = 3
    rng = np.random.default_rng(5)
    samples = jnp.asarray(rng.integers(2, V, size=(K, B, T)), jnp.int32)
    advantage = jnp.asarray(rng.normal(size=(K, B)), jnp.float32)
    valid = jnp.asarray([1, 1, 1, 0], jnp.float32)  # one wrap-padded row

    tx = make_optimizer(TrainConfig(lr=1e-2, grad_clip=5.0), 10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=3)
    s_state, s_m = make_rl_update(model)(
        state, feats, masks, samples, advantage, valid
    )

    mesh = mesh_2d()
    spm = sp_model(cfg)
    f, m = _place(mesh, cfg, feats, masks, "data")
    bshard = NamedSharding(mesh, P("data"))
    kb_shard = NamedSharding(mesh, P(None, "data"))
    for chunks in (1, 3):  # fused + rollout-axis gradient accumulation
        p_state, p_m = make_sp_rl_update(spm, mesh, chunks=chunks)(
            state, f, m,
            jax.device_put(samples, kb_shard),
            jax.device_put(advantage, kb_shard),
            jax.device_put(valid, bshard),
        )
        np.testing.assert_allclose(
            float(s_m["rl_loss"]), float(p_m["rl_loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(s_state.params),
            jax.tree_util.tree_leaves(p_state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            )


def test_sp_handles_very_long_frame_axis(setup):
    """The SP design point: a frame axis far beyond one batch's usual size
    still decodes (each device holds 1/8th of the frames)."""
    cfg, model, params, feats, masks, _ = setup
    if cfg.encoder != "temporal_attention":
        pytest.skip("long-frame point test only needs one encoder")
    LONG = 512
    rng = np.random.default_rng(7)
    lf = {
        "resnet": jnp.asarray(rng.normal(size=(2, LONG, 10)), jnp.float32),
        "c3d": jnp.asarray(rng.normal(size=(2, LONG, 6)), jnp.float32),
    }
    lm = {k: jnp.ones((2, LONG), jnp.float32) for k in lf}
    want, _ = __import__("cst_captioning_tpu.decoding", fromlist=["greedy_decode"]).greedy_decode(
        model, params, lf, lm, max_len=T
    )
    mesh = mesh_1d()
    spm = sp_model(cfg)
    f, m = _place(mesh, cfg, lf, lm)
    got, _ = make_sp_decode(spm, mesh, max_len=T)(params, f, m, jax.random.key(0))
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
