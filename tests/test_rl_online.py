"""Online RL from served traffic (rl/online.py): feedback capture, the
drop-and-COUNT staleness gate, the closed serve->update->publish loop, and
the acceptance pin — two seeded online runs over the same trace and swap
schedule produce BIT-identical learner params.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config.config import (
    EOS_ID,
    ModelConfig,
    RLConfig,
    TrainConfig,
)
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.rl import OnlineSCSTTrainer
from cst_captioning_tpu.serving import CaptionService, ClipRequest
from cst_captioning_tpu.train import create_train_state, make_optimizer

MODAL = (("resnet", 8),)
T = 8
MAX_F = 5


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig(
        vocab_size=31, modalities=MODAL, d_embed=12, d_hidden=12, d_att=6,
        encoder="temporal_attention", dropout=0.0, max_len=T,
        max_frames=MAX_F,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(2, MAX_F, 8)),
                                   jnp.float32)}
    masks = {"resnet": jnp.ones((2, MAX_F), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, 31, size=(2, T)), jnp.int32)
    tx = make_optimizer(TrainConfig(lr=5e-2, grad_clip=5.0), 10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=1)
    # EOS-biased params so caption lengths vary (continuous batching, and
    # lanes freeing at different strides straddle the swaps)
    p = jax.tree.map(lambda x: x, state.params)
    bias = p["params"]["cell"]["out_proj"]["bias"]
    p["params"]["cell"]["out_proj"]["bias"] = bias.at[EOS_ID].add(2.0)
    return model, state.replace(params=p)


def _rl_cfg(**kw):
    base = dict(
        enabled=True, num_rollouts=2, baseline="greedy", lr=5e-2,
        rollout_depth=1, staleness_bound=8, online_batch_size=2,
        swap_every=1,
    )
    base.update(kw)
    return RLConfig(**base)


class TokenReward:
    """Rigged consensus scorer: +1 per occurrence of a target token."""

    def __init__(self, target: int):
        self.target = target
        self.calls = 0

    def __call__(self, video_ids, rows):
        self.calls += 1
        rows = np.asarray(rows)
        return (rows == self.target).sum(axis=1).astype(np.float32)


def _requests(n=6, seed0=1000):
    out = []
    frames = (1, 5, 3, 5, 2, 4, 1, 5)
    for i in range(n):
        rng = np.random.default_rng(100 + i)
        F = frames[i % len(frames)]
        out.append(ClipRequest(
            req_id=f"r{i}",
            feats={"resnet": rng.normal(size=(F, 8)).astype(np.float32)},
            masks={"resnet": np.ones((F,), np.float32)},
            seed=seed0 + i,
        ))
    return out


def _run_loop(model, state0, cfg, n=6):
    """One seeded online run: serve a fixed trace with the learner attached;
    returns (trainer, service, report)."""
    trainer = OnlineSCSTTrainer(model, TokenReward(3), cfg, state0)
    svc = CaptionService(model, state0.params, capacity=2, num_rollouts=2,
                         stride=4, frame_bucket=1)
    trainer.attach(svc)
    report = svc.serve(_requests(n))
    trainer.flush()
    return trainer, svc, report


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---- the closed loop --------------------------------------------------------


def test_online_loop_captures_updates_and_publishes(setup):
    """6 served requests at online_batch_size 2 become 3 learner updates;
    each update publishes (swap_every=1) and the service's active version
    tracks the learner counter. The reward-trend ledger carries one row
    per applied update."""
    model, state0 = setup
    trainer, svc, report = _run_loop(model, state0, _rl_cfg())
    assert report.completed == 6 and not report.drained
    assert trainer.version == 3 == trainer.last_applied
    assert trainer.last_dropped == 0
    assert trainer.pending_captures == 0
    # the final publish applied at the loop's last stride boundary
    assert svc.param_version == 3
    assert len(svc._swap_history) == 3
    assert [h["version"] for h in svc._swap_history] == [1, 2, 3]
    # reward trend: one metrics row per update, version-stamped
    assert [m["param_version"] for m in trainer.history] == [1, 2, 3]
    assert all("reward_mean" in m for m in trainer.history)
    # the learner actually moved the params
    assert any(
        not np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(state0.params),
                        jax.tree_util.tree_leaves(trainer.state.params))
    )
    # served results pin the version that decoded them, monotonically
    versions = [report.results[f"r{i}"].param_version for i in range(6)]
    assert versions[0] == 0 and max(versions) >= 1
    assert versions == sorted(versions)


def test_online_partial_buffer_waits(setup):
    """A trailing capture short of online_batch_size stays buffered (batch
    shapes through the ring are constant) and is visible via
    pending_captures; flush() does not fabricate a short batch."""
    model, state0 = setup
    trainer, svc, report = _run_loop(model, state0, _rl_cfg(), n=5)
    assert report.completed == 5
    assert trainer.version == 2 and trainer.pending_captures == 1
    assert trainer.flush() == 0
    assert trainer.pending_captures == 1


def _capture(trainer, version, rid, seed=0):
    """Feed one synthetic completed request through the feedback hook:
    exactly what the service hands over at the stride seam (1+K host
    token/logprob rows), with a chosen admission-pinned version."""
    from types import SimpleNamespace

    rng = np.random.default_rng(seed)
    req = ClipRequest(
        req_id=rid,
        feats={"resnet": rng.normal(size=(3, 8)).astype(np.float32)},
        masks={"resnet": np.ones((3,), np.float32)},
        seed=seed,
    )
    result = SimpleNamespace(
        tokens=rng.integers(4, 31, size=(3, T)).astype(np.int32),
        logprobs=rng.normal(size=(3, T)).astype(np.float32) - 2.0,
    )
    trainer.on_result(req, result, version)


def test_online_staleness_drop_and_count(setup):
    """Captures admitted under a version the learner has since advanced
    past staleness_bound are DROPPED and counted — never re-decoded (served
    tokens are ground truth). Applied + dropped accounts for every consumed
    batch, and the staleness ledger matches."""
    model, state0 = setup
    trainer = OnlineSCSTTrainer(
        model, TokenReward(3), _rl_cfg(staleness_bound=0), state0
    )
    # batch 1: two v0 captures at learner v0 -> stale 0 -> applied, v1
    _capture(trainer, 0, "a0", seed=1)
    _capture(trainer, 0, "a1", seed=2)
    assert trainer.version == 1 and trainer.last_dropped == 0
    # batch 2: two captures SERVED before that swap (still stamped v0)
    # -> stale 1 > bound 0 -> dropped-and-counted, learner unchanged
    _capture(trainer, 0, "b0", seed=3)
    _capture(trainer, 0, "b1", seed=4)
    assert trainer.version == 1 and trainer.last_dropped == 1
    # batch 3: post-swap traffic (v1) applies again
    _capture(trainer, 1, "c0", seed=5)
    _capture(trainer, 1, "c1", seed=6)
    assert trainer.version == 2
    assert trainer.last_applied == 2 and trainer.last_dropped == 1
    assert trainer.last_staleness == {0: 2, 1: 1}
    # a mixed-version batch is as stale as its OLDEST capture
    _capture(trainer, 0, "d0", seed=7)
    _capture(trainer, 2, "d1", seed=8)
    assert trainer.last_dropped == 2 and trainer.version == 2
    assert trainer.last_staleness == {0: 2, 1: 1, 2: 1}


def test_online_two_runs_bit_identical(setup):
    """THE determinism pin: the whole loop (capture order, batch forming,
    staleness drops, updates, publishes) runs on the serving thread as a
    deterministic function of (trace, swap schedule) — two seeded runs end
    with bit-identical learner params and identical ledgers."""
    model, state0 = setup
    cfg = _rl_cfg(staleness_bound=1)
    t1, s1, _ = _run_loop(model, state0, cfg)
    t2, s2, _ = _run_loop(model, state0, cfg)
    assert t1.version == t2.version
    assert t1.last_applied == t2.last_applied
    assert t1.last_dropped == t2.last_dropped
    assert t1.last_staleness == t2.last_staleness
    assert s1.param_version == s2.param_version
    _assert_tree_equal(t1.state.params, t2.state.params)
    _assert_tree_equal(t1.state.opt_state, t2.state.opt_state)


# ---- wiring guards ----------------------------------------------------------


def test_attach_rejects_donating_learner(setup):
    model, state0 = setup
    trainer = OnlineSCSTTrainer(
        model, TokenReward(3), _rl_cfg(), state0, donate=True
    )
    svc = CaptionService(model, state0.params, capacity=2, num_rollouts=2)
    with pytest.raises(ValueError, match="donate"):
        trainer.attach(svc)


def test_attach_rejects_version_mismatch(setup):
    model, state0 = setup
    trainer = OnlineSCSTTrainer(model, TokenReward(3), _rl_cfg(), state0)
    trainer.version = 2  # a learner mid-run against a fresh service
    svc = CaptionService(model, state0.params, capacity=2, num_rollouts=2)
    with pytest.raises(ValueError, match="version"):
        trainer.attach(svc)


def test_capture_rejects_lane_mismatch(setup):
    """A service decoding a different 1+K than the learner's K is a wiring
    error the first capture rejects loudly."""
    model, state0 = setup
    trainer = OnlineSCSTTrainer(model, TokenReward(3), _rl_cfg(), state0)
    svc = CaptionService(model, state0.params, capacity=2, num_rollouts=1)
    trainer.attach(svc)
    with pytest.raises(ValueError, match="lanes"):
        svc.serve(_requests(1))


def test_online_config_validation():
    from cst_captioning_tpu.config.config import ExperimentConfig

    with pytest.raises(ValueError, match="online_batch_size"):
        ExperimentConfig(rl=RLConfig(online_batch_size=0))
    with pytest.raises(ValueError, match="swap_every"):
        ExperimentConfig(rl=RLConfig(swap_every=0))
