"""graftlint: per-rule positive/negative fixtures, baseline round-trip,
--json schema, and the tier-1 self-check that keeps the repo lint-clean.

Pure AST analysis — nothing here touches a JAX backend except the
import-cleanliness subprocess test at the bottom (which exists to PROVE no
backend comes up).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from cst_captioning_tpu.tools.graftlint import Baseline, all_rules, lint_paths
from cst_captioning_tpu.tools.graftlint.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every lintable top-level target of the repo (scripts/lint.sh mirrors this)
REPO_LINT_PATHS = [
    os.path.join(REPO, p)
    for p in ("cst_captioning_tpu", "tests", "scripts", "bench.py",
              "bench_attention.py", "bench_recipe.py")
]


# deliberately lint-dirty cross-file fixture pairs (skipped by the repo
# walk — "fixtures" is in core._SKIP_DIRS — and linted explicitly here)
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")


def _lint(tmp_path, relname: str, source: str, rules=None):
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    # cache_path="": unit fixtures rewrite files faster than mtime
    # granularity; the cache has its own dedicated tests
    result = lint_paths([str(path)], str(tmp_path), rule_ids=rules,
                        cache_path="")
    return result.findings


def _lint_fixture(sub: str, rules, only: str | None = None):
    root = os.path.join(FIXTURES, sub)
    paths = [os.path.join(root, only)] if only else [root]
    return lint_paths(paths, root, rule_ids=rules, cache_path="").findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ---- GL001: host sync -------------------------------------------------------

def test_gl001_positive_sync_in_traced_function(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)\n"
    ), rules=["GL001"])
    assert _rules_of(findings) == ["GL001"]
    assert findings[0].severity == "error"


def test_gl001_positive_sync_in_scan_body(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        return c, float(x)\n"
        "    return jax.lax.scan(body, 0, xs)\n"
    ), rules=["GL001"])
    assert _rules_of(findings) == ["GL001"]


def test_gl001_negative_sync_outside_trace(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x * 2\n"
        "def host(x):\n"
        "    return np.asarray(step(x))\n"
    ), rules=["GL001"])
    assert findings == []


def test_gl001_positive_per_step_loop_sync(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake_loop.py", (
            "import jax\n"
            "def epoch(step, batches, log):\n"
            "    for b in batches:\n"
            "        state, m = step(b)\n"
            "        log.append(float(m['loss']))\n"
        ), rules=["GL001"],
    )
    assert _rules_of(findings) == ["GL001"]
    assert findings[0].severity == "warning"


def test_gl001_negative_gated_loop_sync(tmp_path):
    # a sync inside a log-every-N `if` body is amortized — not flagged
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake_loop.py", (
            "import jax\n"
            "def epoch(step, batches, log, every):\n"
            "    n = 0\n"
            "    for b in batches:\n"
            "        state, m = step(b)\n"
            "        n += 1\n"
            "        if every and n % every == 0:\n"
            "            log.append(float(m['loss']))\n"
        ), rules=["GL001"],
    )
    assert findings == []


def test_gl001_negative_loop_sync_outside_hot_packages(tmp_path):
    # same loop in a host-side package: scoring IS a readback, not flagged
    findings = _lint(
        tmp_path, "cst_captioning_tpu/metrics/fake.py", (
            "import jax\n"
            "def score(rows):\n"
            "    out = []\n"
            "    for r in rows:\n"
            "        out.append(float(r))\n"
            "    return out\n"
        ), rules=["GL001"],
    )
    assert findings == []


# ---- GL002: PRNG key reuse --------------------------------------------------

def test_gl002_positive_key_reuse(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "def rollout(key):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.uniform(key, (2,))\n"
        "    return a + b\n"
    ), rules=["GL002"])
    assert _rules_of(findings) == ["GL002"]
    assert "line 3" in findings[0].message


def test_gl002_negative_split_between_consumers(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "def rollout(key):\n"
        "    k1, k2 = jax.random.split(key)\n"
        "    a = jax.random.normal(k1, (2,))\n"
        "    key, sub = jax.random.split(k2)\n"
        "    b = jax.random.uniform(sub, (2,))\n"
        "    c = jax.random.normal(key, (2,))\n"
        "    return a + b + c\n"
    ), rules=["GL002"])
    assert findings == []


def test_gl002_negative_rebound_key(tmp_path):
    # consuming, REBINDING, then consuming again is the canonical pattern
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "def loop(key, n):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    key = jax.random.fold_in(key, 1)\n"
        "    b = jax.random.normal(key, (2,))\n"
        "    return a + b\n"
    ), rules=["GL002"])
    assert findings == []


def test_gl002_not_applied_in_tests(tmp_path):
    # determinism assertions reuse keys on purpose
    findings = _lint(tmp_path, "tests/test_fake.py", (
        "import jax\n"
        "def test_deterministic(key):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.normal(key, (2,))\n"
        "    assert (a == b).all()\n"
    ), rules=["GL002"])
    assert findings == []


# ---- GL003: Python branch on traced value -----------------------------------

def test_gl003_positive_if_on_jnp_value(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    s = jnp.sum(x)\n"
        "    if s > 0:\n"
        "        return x\n"
        "    return -x\n"
    ), rules=["GL003"])
    assert _rules_of(findings) == ["GL003"]


def test_gl003_positive_while_on_lax_value(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    while jax.lax.reduce_max(x) > 0:\n"
        "        x = x - 1\n"
        "    return x\n"
    ), rules=["GL003"])
    assert _rules_of(findings) == ["GL003"]


def test_gl003_negative_static_branch(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def make(with_greedy):\n"
        "    @jax.jit\n"
        "    def f(x):\n"
        "        if with_greedy:\n"
        "            return jnp.sum(x)\n"
        "        return x\n"
        "    return f\n"
    ), rules=["GL003"])
    assert findings == []


# ---- GL004: jit step without donation ---------------------------------------

def test_gl004_positive_undonated_train_step(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def train_step(state, batch):\n"
        "    return state\n"
    ), rules=["GL004"])
    assert _rules_of(findings) == ["GL004"]


def test_gl004_negative_explicit_donation(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def train_step(state, batch):\n"
        "    return state\n"
        "def make_update(fn, donate):\n"
        "    return jax.jit(fn, donate_argnums=(0,) if donate else ())\n"
    ), rules=["GL004"])
    assert findings == []


def test_gl004_negative_stateless_decode_step(tmp_path):
    # a decode 'step' carries no train state: donation buys nothing
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def step(params, feats):\n"
        "    return feats\n"
    ), rules=["GL004"])
    assert findings == []


# ---- GL005: f32 literal in bf16 module --------------------------------------

def test_gl005_positive_f32_literal_in_models(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/models/fake.py", (
            "import jax.numpy as jnp\n"
            "def forward(x):\n"
            "    bias = jnp.zeros((4,), jnp.float32)\n"
            "    return x + bias\n"
        ), rules=["GL005"],
    )
    assert _rules_of(findings) == ["GL005"]


def test_gl005_negative_config_dtype_and_out_of_scope(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/models/fake.py", (
            "import jax.numpy as jnp\n"
            "def forward(x, cfg):\n"
            "    bias = jnp.zeros((4,), jnp.dtype(cfg.dtype))\n"
            "    return x + bias\n"
        ), rules=["GL005"],
    )
    assert findings == []
    # f32 input data built in tests/benches is fine (the model casts)
    findings = _lint(
        tmp_path, "tests/test_fake.py", (
            "import jax.numpy as jnp\n"
            "x = jnp.zeros((4,), jnp.float32)\n"
        ), rules=["GL005"],
    )
    assert findings == []


# ---- GL006: heavy imports / import-time device work -------------------------

def test_gl006_positive_torch_import(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake.py",
        "import torch\n", rules=["GL006"],
    )
    assert _rules_of(findings) == ["GL006"]


def test_gl006_positive_module_level_device_work(tmp_path):
    findings = _lint(tmp_path, "bench_fake.py", (
        "import jax\n"
        "N = len(jax.devices())\n"
    ), rules=["GL006"])
    assert _rules_of(findings) == ["GL006"]


def test_gl006_negative_guarded_and_function_scoped(tmp_path):
    findings = _lint(tmp_path, "bench_fake.py", (
        "import jax\n"
        "import numpy as np\n"
        "def main():\n"
        "    return len(jax.devices())\n"
        "if __name__ == '__main__':\n"
        "    print(jax.devices())\n"
    ), rules=["GL006"])
    assert findings == []


# ---- GL007: partition-rule coverage -----------------------------------------

_CONTRACT = {"params": ["params/lstm0/kernel", "params/orphan/bias"]}


def _write_contract(tmp_path, params):
    p = tmp_path / "scripts" / "shardings_contract.json"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps({"params": params}))


def test_gl007_positive_unmatched_rule_and_unruled_param(tmp_path):
    _write_contract(tmp_path, _CONTRACT["params"])
    findings = _lint(tmp_path, "mesh_fake.py", (
        "PARAM_PARTITION_RULES = (\n"
        "    ('lstm', r'params/lstm\\d+/.*', None),\n"
        "    ('ghost', r'params/ghost/.*', None),\n"
        ")\n"
        "SHARDING_CONTRACT = 'scripts/shardings_contract.json'\n"
    ), rules=["GL007"])
    messages = " | ".join(f.message for f in findings)
    assert len(findings) == 2
    assert "ghost" in messages and "params/orphan/bias" in messages


def test_gl007_negative_full_coverage(tmp_path):
    _write_contract(tmp_path, ["params/lstm0/kernel", "params/out/bias"])
    findings = _lint(tmp_path, "mesh_fake.py", (
        "PARAM_PARTITION_RULES = (\n"
        "    ('lstm', r'params/lstm\\d+/.*', None),\n"
        "    ('head', r'params/out/.*', None),\n"
        ")\n"
        "SHARDING_CONTRACT = 'scripts/shardings_contract.json'\n"
    ), rules=["GL007"])
    assert findings == []


def test_gl007_missing_contract_is_info_not_gate(tmp_path):
    findings = _lint(tmp_path, "mesh_fake.py", (
        "PARAM_PARTITION_RULES = (('lstm', r'.*', None),)\n"
        "SHARDING_CONTRACT = 'scripts/shardings_contract.json'\n"
    ), rules=["GL007"])
    assert [f.severity for f in findings] == ["info"]


# ---- GL008: TPU-only test imports without slow marker -----------------------

def test_gl008_positive_unmarked_tpu_test(tmp_path):
    findings = _lint(tmp_path, "tests/test_fake_pallas.py", (
        "from jax.experimental.pallas import tpu as pltpu\n"
        "def test_kernel():\n"
        "    pass\n"
    ), rules=["GL008"])
    assert _rules_of(findings) == ["GL008"]


def test_gl008_negative_slow_marked(tmp_path):
    findings = _lint(tmp_path, "tests/test_fake_pallas.py", (
        "import pytest\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "pytestmark = pytest.mark.slow\n"
        "def test_kernel():\n"
        "    pass\n"
    ), rules=["GL008"])
    assert findings == []
    findings = _lint(tmp_path, "tests/test_fake_pallas2.py", (
        "import pytest\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "@pytest.mark.slow\n"
        "def test_kernel():\n"
        "    pass\n"
    ), rules=["GL008"])
    assert findings == []


# ---- GL009: silently swallowed broad exceptions -----------------------------

def test_gl009_positive_swallowed_continue(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/ckpt/fake.py", (
            "def restore(candidates):\n"
            "    for c in candidates:\n"
            "        try:\n"
            "            return load(c)\n"
            "        except Exception:\n"
            "            continue\n"
        ), rules=["GL009"],
    )
    assert _rules_of(findings) == ["GL009"]
    assert findings[0].severity == "warning"


def test_gl009_positive_bare_except_pass(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/utils/fake.py", (
            "def close(fh):\n"
            "    try:\n"
            "        fh.close()\n"
            "    except:\n"
            "        pass\n"
        ), rules=["GL009"],
    )
    assert _rules_of(findings) == ["GL009"]


def test_gl009_positive_tuple_containing_exception(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/data/fake.py", (
            "def read(path):\n"
            "    try:\n"
            "        return open(path).read()\n"
            "    except (OSError, Exception):\n"
            "        pass\n"
        ), rules=["GL009"],
    )
    assert _rules_of(findings) == ["GL009"]


def test_gl009_negative_logged_fallback_and_narrow_types(tmp_path):
    # logging before falling back is exactly the prescribed fix
    findings = _lint(
        tmp_path, "cst_captioning_tpu/ckpt/fake.py", (
            "def restore(candidates, log):\n"
            "    for c in candidates:\n"
            "        try:\n"
            "            return load(c)\n"
            "        except Exception as e:\n"
            "            log('ckpt_corrupt', name=c, error=str(e))\n"
            "            continue\n"
        ), rules=["GL009"],
    )
    assert findings == []
    # a narrow exception type is a deliberate contract, even when silent
    findings = _lint(
        tmp_path, "cst_captioning_tpu/data/fake.py", (
            "import queue\n"
            "def drain(q):\n"
            "    try:\n"
            "        q.get_nowait()\n"
            "    except queue.Empty:\n"
            "        pass\n"
        ), rules=["GL009"],
    )
    assert findings == []


def test_gl009_not_applied_outside_package(tmp_path):
    # tests/benches swallow on purpose when asserting failure modes
    findings = _lint(
        tmp_path, "tests/test_fake.py", (
            "def test_x():\n"
            "    try:\n"
            "        boom()\n"
            "    except Exception:\n"
            "        pass\n"
        ), rules=["GL009"],
    )
    assert findings == []


# ---- GL010: ad-hoc timing / bare print in package hot paths -----------------

def test_gl010_positive_time_time_in_package(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake.py", (
            "import time\n"
            "def epoch(step, batches):\n"
            "    t0 = time.time()\n"
            "    for b in batches:\n"
            "        step(b)\n"
            "    return time.time() - t0\n"
        ), rules=["GL010"],
    )
    assert _rules_of(findings) == ["GL010"]
    assert len(findings) == 2 and findings[0].severity == "warning"
    assert "obs.span" in findings[0].message


def test_gl010_positive_bare_print_in_package(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/rl/fake.py", (
            "def score(rows):\n"
            "    print('scored', len(rows))\n"
        ), rules=["GL010"],
    )
    assert _rules_of(findings) == ["GL010"]
    assert "EventLogger" in findings[0].message


def test_gl010_negative_perf_counter_and_obs_span(tmp_path):
    # the prescribed replacements never trip the rule
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake.py", (
            "import time\n"
            "from cst_captioning_tpu import obs\n"
            "def epoch(step, batches):\n"
            "    t0 = time.perf_counter()\n"
            "    with obs.span('xe.epoch'):\n"
            "        for b in batches:\n"
            "            step(b)\n"
            "    obs.event('done', dur=time.perf_counter() - t0)\n"
        ), rules=["GL010"],
    )
    assert findings == []


def test_gl010_not_applied_to_clis_tools_tests(tmp_path):
    # user-facing stdout surfaces and tests print/measure on purpose
    for rel in ("cst_captioning_tpu/cli/fake.py",
                "cst_captioning_tpu/tools/graftlint/fake.py",
                "tests/test_fake.py", "scripts/fake.py", "bench_fake.py"):
        findings = _lint(
            tmp_path, rel, (
                "import time\n"
                "def main():\n"
                "    print(time.time())\n"
            ), rules=["GL010"],
        )
        assert findings == [], rel


# ---- suppressions -----------------------------------------------------------

def test_inline_suppression_same_line(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)  # graftlint: disable=GL001 (fixture)\n"
    ), rules=["GL001"])
    assert findings == []


def test_inline_suppression_next_line(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    # graftlint: disable-next-line=GL001\n"
        "    return np.asarray(x)\n"
    ), rules=["GL001"])
    assert findings == []


def test_suppression_of_other_rule_does_not_hide(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)  # graftlint: disable=GL999\n"
    ), rules=["GL001"])
    assert _rules_of(findings) == ["GL001"]


# ---- baseline round-trip ----------------------------------------------------

def test_baseline_round_trip(tmp_path):
    src = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)\n"
    )
    path = tmp_path / "mod.py"
    path.write_text(src)
    first = lint_paths([str(path)], str(tmp_path))
    assert len(first.findings) == 1 and not first.findings[0].baselined

    bl_path = tmp_path / "graftlint.baseline"
    bl = Baseline.from_findings(first.findings)
    bl.save(str(bl_path))
    reloaded = Baseline.load(str(bl_path))

    second = lint_paths([str(path)], str(tmp_path), baseline=reloaded)
    assert len(second.findings) == 1
    assert second.findings[0].baselined
    assert second.gating == []

    # a NEW finding on top of the baselined one still gates
    path.write_text(src + (
        "@jax.jit\n"
        "def step2(x):\n"
        "    return np.asarray(x)\n"
    ))
    third = lint_paths(
        [str(path)], str(tmp_path), baseline=Baseline.load(str(bl_path))
    )
    assert len(third.gating) == 1


def test_baseline_preserves_reasons_on_rewrite(tmp_path):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    result = lint_paths([str(path)], str(tmp_path))
    bl = Baseline.from_findings(result.findings)
    bl.entries[0]["reason"] = "intentional: fixture"
    rewritten = Baseline.from_findings(result.findings, old=bl)
    assert rewritten.entries[0]["reason"] == "intentional: fixture"


# ---- CLI / --json schema ----------------------------------------------------

def test_cli_json_schema(tmp_path, capsys):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    rc = cli_main([str(path), "--root", str(tmp_path), "--json",
                   "--no-baseline"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["version"] == 1 and report["tool"] == "graftlint"
    assert report["files_checked"] == 1
    assert report["counts"]["new"] == 1
    assert report["counts"]["by_rule"] == {"GL001": 1}
    (finding,) = report["findings"]
    assert set(finding) == {
        "rule", "severity", "path", "line", "col", "message", "context",
        "baselined", "fix",
    }
    assert finding["rule"] == "GL001" and finding["line"] == 4
    assert finding["fix"] is None  # GL001 has no mechanical repair
    # the two-pass engine's bookkeeping rides along in the report
    assert report["stale_baseline"] == []
    assert report["unused_suppressions"] == []
    # the fixes block: autofixable counts + the stale classes --fix repairs
    assert set(report["fixes"]) == {
        "autofixable", "by_rule", "stale_suppressions", "stale_baseline",
    }
    assert report["fixes"]["autofixable"] == 0
    timings = report["timings"]
    assert {"index_seconds", "rules_seconds"} <= set(timings)
    assert timings["files"] == 1


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    assert cli_main([str(path), "--root", str(tmp_path),
                     "--write-baseline"]) == 0
    capsys.readouterr()
    assert cli_main([str(path), "--root", str(tmp_path)]) == 0


def test_cli_list_rules_names_all_registered(tmp_path, capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("GL001", "GL002", "GL003", "GL004", "GL005", "GL006",
                "GL007", "GL008", "GL009", "GL010", "GL011", "GL012",
                "GL013", "GL014", "GL015", "GL016", "GL017"):
        assert rid in out


def test_rule_registry_has_at_least_seven_rules():
    rules = all_rules()
    assert len(rules) >= 7
    assert all(r.rationale for r in rules.values())


def test_parse_error_is_reported_not_fatal(tmp_path):
    path = tmp_path / "broken.py"
    path.write_text("def oops(:\n")
    result = lint_paths([str(path)], str(tmp_path))
    assert [f.rule for f in result.findings] == ["GL000"]
    assert result.gating  # syntax errors gate


# ---- GL011: scan-carry dtype drift ------------------------------------------

def test_gl011_positive_scan_carry_cast_drift(tmp_path):
    """A scan body that casts the carry to a dtype different from its
    literal init — the stride-carry hazard this rule exists for."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        return (c + x).astype(jnp.bfloat16), x\n"
        "    init = jnp.zeros((4,), jnp.float32)\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert _rules_of(findings) == ["GL011"]
    assert findings[0].severity == "error"
    assert "bfloat16" in findings[0].message and "float32" in findings[0].message


def test_gl011_positive_while_loop_ctor_drift(tmp_path):
    """while_loop body rebuilding the carry in a different dtype than the
    (default-f32) init."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(n):\n"
        "    def body(c):\n"
        "        return jnp.asarray(c + 1, dtype=jnp.int32)\n"
        "    return jax.lax.while_loop(lambda c: c < n, body, jnp.zeros(()))\n"
    ), rules=["GL011"])
    assert _rules_of(findings) == ["GL011"]


def test_gl011_positive_tuple_carry_positional(tmp_path):
    """Tuple carries compare leaf-by-leaf: only the drifting position
    fires, dtype-matching ones stay quiet."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        a, b = c\n"
        "        return (a.astype(jnp.float32), b.astype(jnp.float16)), x\n"
        "    init = (jnp.zeros((2,), jnp.float32), jnp.zeros((2,), jnp.float32))\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert len(findings) == 1 and findings[0].rule == "GL011"
    assert "float16" in findings[0].message


def test_gl011_negative_matching_dtype(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        return (c + x).astype(jnp.float32), x\n"
        "    init = jnp.zeros((4,), jnp.float32)\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert findings == []


def test_gl011_negative_unknown_dtypes_stay_quiet(tmp_path):
    """No literal dtype on either side -> out of scope, no guessing (the
    repo's tree.map-built carries must never false-positive)."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs, init):\n"
        "    def body(c, x):\n"
        "        return jax.tree.map(jnp.add, c, x), None\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert findings == []


def test_gl011_negative_nested_def_returns_ignored(tmp_path):
    """Returns inside helpers nested in the body are not the body's carry."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        def helper(v):\n"
        "            return v.astype(jnp.bfloat16)\n"
        "        return c + helper(x).astype(jnp.float32), x\n"
        "    init = jnp.zeros((4,), jnp.float32)\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert findings == []


# ---- project index: summary cache + provenance fixpoint ---------------------

def test_summary_cache_invalidation(tmp_path):
    """Edit a file (mtime/size change) -> its summary is recomputed; an
    untouched file is served from the on-disk cache."""
    import time as _time

    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    mod = tmp_path / "m.py"
    mod.write_text(
        "import numpy as np\n"
        "def f():\n"
        "    return np.zeros(3)\n"
    )
    cache = tmp_path / "cache.json"
    idx = ProjectIndex.build([str(mod)], str(tmp_path),
                             cache_path=str(cache))
    assert idx.stats.summarized >= 1 and cache.exists()
    assert not idx.functions["m.f"].returns_device

    idx2 = ProjectIndex.build([str(mod)], str(tmp_path),
                              cache_path=str(cache))
    assert idx2.stats.summarized == 0 and idx2.stats.cached >= 1
    assert not idx2.functions["m.f"].returns_device

    mod.write_text(
        "import jax.numpy as jnp\n"
        "def f():\n"
        "    return jnp.zeros(3)\n"
    )
    future = _time.time() + 10
    os.utime(mod, (future, future))
    idx3 = ProjectIndex.build([str(mod)], str(tmp_path),
                              cache_path=str(cache))
    assert idx3.stats.summarized >= 1
    assert idx3.functions["m.f"].returns_device


def test_index_fixpoint_transitive_device_returns(tmp_path):
    """returns-device provenance propagates through the call graph across
    modules (a -> b -> jnp)."""
    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    (tmp_path / "a.py").write_text(
        "import jax.numpy as jnp\n"
        "def leaf(x):\n"
        "    return jnp.tanh(x)\n"
    )
    (tmp_path / "b.py").write_text(
        "from a import leaf\n"
        "def mid(x):\n"
        "    return leaf(x)\n"
        "def top(x):\n"
        "    return mid(x)\n"
    )
    idx = ProjectIndex.build(
        [str(tmp_path / "a.py"), str(tmp_path / "b.py")],
        str(tmp_path), cache_path="",
    )
    assert idx.functions["a.leaf"].returns_device
    assert idx.functions["b.mid"].returns_device
    assert idx.functions["b.top"].returns_device


# ---- --check-stale: dead baseline entries + dead suppressions ---------------

def test_stale_baseline_entries_reported(tmp_path):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    live = lint_paths([str(path)], str(tmp_path), cache_path="")
    bl = Baseline.from_findings(live.findings)
    bl.entries.append({
        "rule": "GL001", "path": "mod.py",
        "context": "return np.asarray(ghost)", "count": 1,
        "reason": "the code site was fixed long ago",
    })
    result = lint_paths([str(path)], str(tmp_path), baseline=bl,
                        cache_path="")
    assert result.gating == []  # the live finding is still covered
    assert [e["context"] for e in result.stale_baseline] == [
        "return np.asarray(ghost)"
    ]


def test_unused_suppressions_reported(tmp_path):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)  # graftlint: disable=GL001 (used)\n"
        "def host(x):\n"
        "    return x  # graftlint: disable=GL003 (nothing ever fires here)\n"
    )
    result = lint_paths([str(path)], str(tmp_path), cache_path="")
    assert [(s["line"], s["rule"]) for s in result.unused_suppressions] == [
        (7, "GL003")
    ]


def test_cli_check_stale_gates(tmp_path, capsys):
    path = tmp_path / "mod.py"
    path.write_text(
        "def f(x):\n"
        "    return x  # graftlint: disable=GL001 (dead)\n"
    )
    (tmp_path / "graftlint.baseline").write_text(json.dumps(
        {"version": 1, "entries": []}
    ))
    assert cli_main([str(path), "--root", str(tmp_path)]) == 0
    capsys.readouterr()
    rc = cli_main([str(path), "--root", str(tmp_path), "--check-stale"])
    err = capsys.readouterr().err
    assert rc == 1 and "unused suppression" in err
    # --check-stale without the full rule set is a usage error
    assert cli_main([str(path), "--root", str(tmp_path), "--check-stale",
                     "--rules", "GL001"]) == 2


def test_cli_timings_and_budget(tmp_path, capsys):
    path = tmp_path / "mod.py"
    path.write_text("def f():\n    return 1\n")
    assert cli_main([str(path), "--root", str(tmp_path), "--timings"]) == 0
    err = capsys.readouterr().err
    assert "index" in err and "rules" in err
    # an absurdly small budget must fail the run
    assert cli_main([str(path), "--root", str(tmp_path),
                     "--budget", "0.000001"]) == 1
    assert "budget" in capsys.readouterr().err


# ---- tier-1 self-check: the repo itself stays lint-clean --------------------

def test_repo_is_graftlint_clean(capsys):
    """The acceptance gate: zero non-baselined findings over the tree."""
    rc = cli_main(REPO_LINT_PATHS + ["--root", REPO])
    out = capsys.readouterr()
    assert rc == 0, f"graftlint found new findings:\n{out.out}"


def test_sharding_contract_matches_model():
    """scripts/check_shardings.py default mode: contract + coverage OK."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_shardings
    finally:
        sys.path.pop(0)
    assert check_shardings.main([]) == 0


# ---- satellite: drivers import side-effect-free under JAX_PLATFORMS=cpu -----

def test_scripts_import_without_backend_init():
    """bench.py / verify_parity.py (and friends) must import without
    initializing a JAX backend — graftlint's AST pass must stay the only
    analysis that needs to read them."""
    code = (
        "import sys\n"
        f"sys.path.insert(0, {os.path.join(REPO, 'scripts')!r})\n"
        "import bench, bench_attention, bench_recipe\n"
        "import verify_parity, check_shardings\n"
        "import jax\n"
        "try:\n"
        "    backends = jax._src.xla_bridge._backends\n"
        "except AttributeError:\n"
        "    backends = None\n"
        "assert not backends, 'importing the drivers initialized a backend'\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr


# ---- GL012: collective-axis-name typos --------------------------------------

def test_gl012_positive_psum_axis_typo(tmp_path):
    """A misspelled mesh axis in a collective is the exact hazard: an
    unbound-axis trace error (or wrong-axis reduction) deep inside
    shard_map."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'dta')\n"
    ), rules=["GL012"])
    assert _rules_of(findings) == ["GL012"]
    assert findings[0].severity == "error"
    assert "'dta'" in findings[0].message


def test_gl012_positive_axis_name_kwarg_and_tuple(tmp_path):
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    a = jax.lax.pmean(x, axis_name='sequ')\n"
        "    b = jax.lax.psum(x, ('data', 'seqq'))\n"
        "    return a, b\n"
    ), rules=["GL012"])
    assert len(findings) == 2
    assert all(f.rule == "GL012" for f in findings)


def test_gl012_negative_declared_axes_and_dynamic_names(tmp_path):
    """Axes declared by train/mesh.py pass; dynamic axis expressions are
    out of scope (not statically checkable)."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x, axis):\n"
        "    a = jax.lax.psum(x, 'data')\n"
        "    b = jax.lax.pmean(x, 'seq')\n"
        "    c = jax.lax.axis_index('data')\n"
        "    d = jax.lax.psum(x, axis)\n"
        "    return a, b, c, d\n"
    ), rules=["GL012"])
    assert findings == []


def test_gl012_axes_extracted_from_mesh_py(tmp_path):
    """The allowed set comes from the *axis-parameter defaults declared by
    train/mesh.py under the lint root, not a hardcoded list."""
    mesh = tmp_path / "cst_captioning_tpu" / "train" / "mesh.py"
    mesh.parent.mkdir(parents=True, exist_ok=True)
    mesh.write_text(
        "def make_mesh(num_devices=0, axis='model', seq_axis='pipeline'):\n"
        "    pass\n"
    )
    good = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'model')\n"
    ), rules=["GL012"])
    assert good == []
    bad = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'data')\n"  # not declared by THIS mesh.py
    ), rules=["GL012"])
    assert _rules_of(bad) == ["GL012"]


def test_gl012_negative_tests_out_of_scope(tmp_path):
    findings = _lint(tmp_path, "tests/test_mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'i')\n"
    ), rules=["GL012"])
    assert findings == []


def test_gl012_mesh_axes_rescrape_within_one_process(tmp_path):
    """The stale-cache fix: editing train/mesh.py between two lint runs in
    the SAME process must change the allowed axis set (the scrape lives on
    the per-run project index now, not a module-level cache)."""
    import time as _time

    mesh = tmp_path / "cst_captioning_tpu" / "train" / "mesh.py"
    mesh.parent.mkdir(parents=True, exist_ok=True)
    mesh.write_text("def make_mesh(num_devices=0, axis='alpha'):\n    pass\n")
    src = (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'alpha')\n"
    )
    assert _lint(tmp_path, "cst_captioning_tpu/mod.py", src,
                 rules=["GL012"]) == []
    mesh.write_text("def make_mesh(num_devices=0, axis='beta'):\n    pass\n")
    future = _time.time() + 10
    os.utime(mesh, (future, future))
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", src,
                     rules=["GL012"])
    assert _rules_of(findings) == ["GL012"] and "'alpha'" in findings[0].message


# ---- GL013: implicit host transfers (interprocedural) -----------------------

def test_gl013_cross_file_device_provenance():
    """The acceptance pair: np.asarray / .tolist() on values whose device
    provenance is declared in ANOTHER module (traced-fn result, device-
    yielding prefetch generator); the suppressed twin stays quiet."""
    findings = _lint_fixture("gl013", ["GL013"])
    assert len(findings) == 2
    assert all(f.rule == "GL013" and f.path.endswith("consumer.py")
               for f in findings)
    by_ctx = {f.context: f for f in findings}
    asarray = next(f for c, f in by_ctx.items() if "np.asarray(tokens)" in c)
    tolist = next(f for c, f in by_ctx.items() if ".tolist()" in c)
    # the finding message carries the interprocedural path
    assert "cst_captioning_tpu.producer.decode" in asarray.message
    assert "jit-traced" in asarray.message
    assert "cst_captioning_tpu.producer.prefetched" in tolist.message


def test_gl013_single_file_engine_provably_cannot():
    """Linting the consumer ALONE must find nothing: the provenance facts
    live in producer.py, out of any per-file engine's reach."""
    assert _lint_fixture(
        "gl013", ["GL013"], only="cst_captioning_tpu/consumer.py"
    ) == []


def test_gl013_worker_pool_explicit_readback_is_clean():
    """The eval pipeline's cross-thread readback (device tokens submitted
    to a pool worker that calls ``jax.device_get`` before numpy) must not
    trip GL013: the explicit transfer is the sanctioned spelling, and
    ``pool.submit`` is not a host-conversion sink — a function parameter's
    provenance is unknown, not device."""
    assert _lint_fixture("gl013_pool", ["GL013"]) == []


def test_gl013_branch_sensitive_no_false_positive(tmp_path):
    """A host rebinding in one branch must not inherit the other branch's
    device provenance (the real scst.py seam pattern)."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def seam(samples, mesh):\n"
        "    if mesh is not None:\n"
        "        samples = jax.device_put(samples)\n"
        "    else:\n"
        "        samples = np.asarray(samples)\n"
        "    return np.asarray(samples)\n"
    ), rules=["GL013"])
    assert findings == []


def test_gl013_local_device_provenance_and_explicit_readback(tmp_path):
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def bad(x):\n"
        "    y = jnp.tanh(x)\n"
        "    return np.asarray(y)\n"
        "def good(x):\n"
        "    y = jnp.tanh(x)\n"
        "    return np.asarray(jax.device_get(y))\n"
    ), rules=["GL013"])
    assert len(findings) == 1 and findings[0].line == 6


def test_gl013_not_applied_outside_package(tmp_path):
    # benches/tests/scripts read back on purpose
    findings = _lint(tmp_path, "tests/helper.py", (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    return np.asarray(jnp.tanh(x))\n"
    ), rules=["GL013"])
    assert findings == []


# ---- GL014: cross-function PRNG key reuse -----------------------------------

def test_gl014_cross_file_key_reuse():
    """The acceptance pair: a key spent by a callee (directly, and through
    one extra call hop) then reused by the caller; split/fold_in and the
    suppressed twin stay quiet."""
    findings = _lint_fixture("gl014", ["GL014"])
    assert len(findings) == 2
    assert all(f.rule == "GL014" and f.path.endswith("caller.py")
               for f in findings)
    direct, transitive = findings
    assert "cst_captioning_tpu.keys_lib.sample_rollout" in direct.message
    assert "jax.random.normal" in direct.message
    assert "cst_captioning_tpu.keys_lib.wrapped" in transitive.message


def test_gl014_single_file_engine_provably_cannot():
    assert _lint_fixture(
        "gl014", ["GL014"], only="cst_captioning_tpu/caller.py"
    ) == []


def test_gl014_local_reuse_stays_gl002(tmp_path):
    """Pure same-function double consumption belongs to GL002 — GL014 only
    owns pairs involving a callee, so the two never double-report."""
    src = (
        "import jax\n"
        "def f(key):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.uniform(key, (2,))\n"
        "    return a + b\n"
    )
    assert _lint(tmp_path, "mod.py", src, rules=["GL014"]) == []
    assert _rules_of(_lint(tmp_path, "mod.py", src, rules=["GL002"])) == [
        "GL002"
    ]


def test_gl014_not_applied_in_tests(tmp_path):
    findings = _lint(tmp_path, "tests/test_fake.py", (
        "import jax\n"
        "def consume(k):\n"
        "    return jax.random.normal(k, (2,))\n"
        "def test_reuse(key):\n"
        "    a = consume(key)\n"
        "    b = jax.random.uniform(key, (2,))\n"
        "    assert (a != b).any()\n"
    ), rules=["GL014"])
    assert findings == []


# ---- GL015: sharding-spec drift ---------------------------------------------

def test_gl015_cross_file_axis_drift():
    """The acceptance pair: a PartitionSpec literal checked against axes
    declared in the OTHER module (train/mesh.py); declared axes, dynamic
    specs, and the suppressed twin stay quiet."""
    findings = _lint_fixture("gl015", ["GL015"])
    assert len(findings) == 1
    (f,) = findings
    assert f.rule == "GL015" and f.path.endswith("shard_use.py")
    assert "'data'" in f.message
    # the allowed set names the axes that only mesh.py declares
    assert "model" in f.message and "pipeline" in f.message


def test_gl015_repo_axes_pass(tmp_path):
    """With no fixture mesh the default data/seq axes apply — the repo's
    own spec literals must lint clean under them."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "from jax.sharding import PartitionSpec as P\n"
        "def f():\n"
        "    return P('data', 'seq'), P(None), P(('data', 'seq'))\n"
    ), rules=["GL015"])
    assert findings == []
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "from jax.sharding import PartitionSpec as P\n"
        "def f():\n"
        "    return P('model')\n"
    ), rules=["GL015"])
    assert _rules_of(findings) == ["GL015"]


def test_gl015_not_applied_in_tests(tmp_path):
    findings = _lint(tmp_path, "tests/test_mod.py", (
        "from jax.sharding import PartitionSpec as P\n"
        "S = P('i')\n"
    ), rules=["GL015"])
    assert findings == []


# ---- GL016: collective over a declared-but-unbound axis ---------------------

def test_gl016_cross_file_unbound_axis_in_shard_map_called_helper():
    """THE acceptance fixture: 'pipeline' is a declared mesh axis (GL012
    provably cannot flag it), but the only call path into the helper goes
    through a shard_map body binding just 'model' (axis_names=) — the
    axis-environment fixpoint sees that across files."""
    findings = _lint_fixture("gl016", ["GL016"])
    assert len(findings) == 1
    (f,) = findings
    assert f.rule == "GL016" and f.severity == "error"
    assert f.path.endswith("collectives.py")
    assert "'pipeline'" in f.message and "reduce_pipeline" in f.message
    # the message names what the callers DO bind
    assert "model" in f.message


def test_gl016_gl012_provably_cannot_see_the_fixture():
    """GL012's literal-vs-mesh check passes on the whole gl016 pair —
    every axis spelled is either declared (pipeline/model) or visibly
    bound (vmap's 'rollout'): only the scoped rule catches the bug."""
    assert _lint_fixture("gl016", ["GL012"]) == []


def test_gl016_single_file_engine_provably_cannot():
    """Linting the helpers ALONE must find nothing: with no known caller
    the runtime context is unknowable (and the binding lives in
    mapper.py)."""
    assert _lint_fixture(
        "gl016", ["GL016"], only="cst_captioning_tpu/collectives.py"
    ) == []


def test_gl016_bound_axis_and_suppressed_twin_quiet():
    findings = _lint_fixture("gl016", ["GL016"])
    lines = {f.line for f in findings}
    # reduce_model (bound via shard_map) and the suppressed twin are quiet
    assert len(findings) == 1 and all(
        "reduce_model" not in f.message for f in findings
    )
    assert lines != set()


def test_gl012_vmap_bound_axis_not_a_typo():
    """The GL016 substrate refines GL012: an axis bound by a reachable
    vmap(axis_name=) is legitimate even though mesh.py never declares
    it (mapper.py's 'rollout' lane axis)."""
    findings = _lint_fixture("gl016", ["GL012"],
                             only="cst_captioning_tpu/mapper.py")
    assert findings == []


def test_gl016_unbound_helper_called_from_plain_context(tmp_path):
    """A helper with a literal mesh-axis collective whose only caller is
    an ordinary function (no binding anywhere) IS a finding — that is
    the runtime unbound-axis error."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def helper(x):\n"
        "    return jax.lax.psum(x, 'data')\n"
        "def epoch(xs):\n"
        "    return [helper(x) for x in xs]\n"
    ), rules=["GL016"])
    assert _rules_of(findings) == ["GL016"]
    assert findings[0].line == 3


def test_gl016_shard_map_without_axis_names_binds_all_mesh_axes(tmp_path):
    """A shard_map with no axis_names= literal binds every declared mesh
    axis (the mesh argument is dynamic): collectives over any declared
    axis under it stay quiet."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def helper(x):\n"
        "    return jax.lax.psum(x, 'seq')\n"
        "def run(mesh, xs):\n"
        "    def body(x):\n"
        "        return helper(x)\n"
        "    return shard_map(body, mesh=mesh, in_specs=None,\n"
        "                     out_specs=None)(xs)\n"
    ), rules=["GL016"])
    assert findings == []


def test_gl016_string_default_axis_param_unbound_is_finding(tmp_path):
    """The ``axis="data"`` factory spelling: an axis routed through a
    string-default parameter resolves like a literal, so a helper whose
    only caller is an ordinary function IS a finding — this is the
    carry-over GL016 previously could not see."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def helper(x, axis='data'):\n"
        "    return jax.lax.psum(x, axis)\n"
        "def epoch(xs):\n"
        "    return [helper(x) for x in xs]\n"
    ), rules=["GL016"])
    assert _rules_of(findings) == ["GL016"]
    assert findings[0].line == 3 and "'data'" in findings[0].message


def test_gl016_string_default_axis_inherited_by_nested_def(tmp_path):
    """The make_*_step closure spelling: the nested device fn inherits
    the factory's ``axis="data"`` default; bound via shard_map the
    collective stays quiet, called plainly it is a finding."""
    src = (
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def make_step(mesh, axis='data'):\n"
        "    def device_step(x):\n"
        "        return jax.lax.psum(x, axis)\n"
        "    return shard_map(device_step, mesh=mesh,\n"
        "                     in_specs=None, out_specs=None)\n"
    )
    assert _lint(tmp_path / "bound", "cst_captioning_tpu/mod.py", src,
                 rules=["GL016"]) == []
    plain = src.replace(
        "    return shard_map(device_step, mesh=mesh,\n"
        "                     in_specs=None, out_specs=None)\n",
        "    return device_step(0)\n"
        "def epoch(mesh, xs):\n"
        "    return [make_step(mesh) for x in xs]\n",
    )
    findings = _lint(tmp_path / "plain", "cst_captioning_tpu/mod.py",
                     plain, rules=["GL016"])
    assert _rules_of(findings) == ["GL016"]
    assert findings[0].line == 5


def test_gl016_empty_string_axis_default_resolves_to_nothing(tmp_path):
    """The SP factories spell ``data_axis: str = ""`` for "no data
    axis"; an empty default must NOT be recorded as an axis (and the
    call site stays unresolvable, hence quiet)."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def helper(x, data_axis=''):\n"
        "    if data_axis:\n"
        "        return jax.lax.psum(x, data_axis)\n"
        "    return x\n"
        "def epoch(xs):\n"
        "    return [helper(x) for x in xs]\n"
    ), rules=["GL016"])
    assert findings == []


def test_gl016_reassigned_axis_param_drops_out_of_env(tmp_path):
    """A rebind of the string-default parameter makes it unresolvable
    again — never guess the default still holds."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def helper(x, axis='data'):\n"
        "    axis = pick_axis(x)\n"
        "    return jax.lax.psum(x, axis)\n"
        "def pick_axis(x):\n"
        "    return 'seq'\n"
        "def epoch(xs):\n"
        "    return [helper(x) for x in xs]\n"
    ), rules=["GL016"])
    assert findings == []


# ---- GL017: interprocedural donation hazards --------------------------------

def test_gl017_cross_file_donation_hazards():
    """The acceptance trio: use-after-donate through the make_step
    factory, the loop-carried un-rebound donation, and the outer jit()
    that silently drops a wrapper's donation — all facts living in
    steps_lib.py."""
    findings = _lint_fixture("gl017", ["GL017"])
    findings = [f for f in findings if f.path.endswith("loop.py")]
    assert len(findings) == 3
    factory, loop, wrapper = sorted(findings, key=lambda f: f.line)
    assert factory.severity == "error"
    assert "donated" in factory.message and "make_step" in factory.message
    assert "fused_update" in loop.message
    assert wrapper.severity == "warning"
    assert "local_wrapper" in wrapper.message
    assert "ignored" in wrapper.message


def test_gl017_single_file_engine_provably_cannot():
    assert _lint_fixture(
        "gl017", ["GL017"], only="cst_captioning_tpu/loop.py"
    ) == []


def test_gl017_rebind_and_read_before_and_suppressed_quiet():
    findings = _lint_fixture("gl017", ["GL017"])
    for f in findings:
        assert "good_rebind" not in f.context
        assert "good_read_before" not in f.context
    # the suppressed twin is the same shape as the factory positive;
    # ring.py contributes exactly its one attribute-rooted positive
    assert len(findings) == 4


def test_gl017_attribute_rooted_donation():
    """``self._buf`` donated through ``self._write`` (an attribute-rooted
    method resolved via the index) flags when re-read un-rebound; the
    donate-and-rebind ring idiom and a read-before stay clean."""
    findings = _lint_fixture("gl017", ["GL017"])
    ring = [f for f in findings if f.path.endswith("ring.py")]
    assert len(ring) == 1
    assert "self._buf" in ring[0].message
    assert "_write" in ring[0].message
    assert "self._buf.shape" in ring[0].context
    for f in findings:
        assert "good_push" not in f.context
        assert "good_read_first" not in f.context


def test_gl017_local_jit_use_after_donate(tmp_path):
    """Single-file form: a locally-built donating jit, buffer re-read
    after the donating call."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def train(state, batch, impl):\n"
        "    step = jax.jit(impl, donate_argnums=(0,))\n"
        "    new_state = step(state, batch)\n"
        "    return new_state, state.loss\n"
    ), rules=["GL017"])
    assert _rules_of(findings) == ["GL017"]
    assert findings[0].line == 5


def test_gl017_dynamic_donation_stays_out_of_scope(tmp_path):
    """`donate_argnums=(0,) if donate else ()` is dynamic: no fact is
    recorded, nothing fires (never guess) — the repo's steps.py
    factories keep linting clean."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def make(impl, donate):\n"
        "    return jax.jit(impl, donate_argnums=(0,) if donate else ())\n"
        "def train(state, batch, impl, donate):\n"
        "    step = make(impl, donate)\n"
        "    new_state = step(state, batch)\n"
        "    return new_state, state.loss\n"
    ), rules=["GL017"])
    assert findings == []


def test_gl017_branch_exclusive_donation_no_false_positive(tmp_path):
    """A donation in one `if` arm must not flag a read in the OTHER arm
    (exclusive paths); a read AFTER the join on the donating path is
    still caught via the may-join."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def train(state, batch, impl, fast):\n"
        "    step = jax.jit(impl, donate_argnums=(0,))\n"
        "    if fast:\n"
        "        out = step(state, batch)\n"
        "    else:\n"
        "        out = state.replace(step=state.step + 1)\n"
        "    return out\n"
    ), rules=["GL017"])
    assert findings == []
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def train(state, batch, impl, fast):\n"
        "    step = jax.jit(impl, donate_argnums=(0,))\n"
        "    if fast:\n"
        "        out = step(state, batch)\n"
        "    else:\n"
        "        out = None\n"
        "    return out, state.loss\n"
    ), rules=["GL017"])
    assert _rules_of(findings) == ["GL017"] and findings[0].line == 8


def test_gl017_not_applied_in_tests(tmp_path):
    findings = _lint(tmp_path, "tests/test_fake.py", (
        "import jax\n"
        "def test_donation_error(state, batch, impl):\n"
        "    step = jax.jit(impl, donate_argnums=(0,))\n"
        "    new_state = step(state, batch)\n"
        "    return new_state, state.loss\n"
    ), rules=["GL017"])
    assert findings == []


# ---- autofix engine ---------------------------------------------------------

def _write_repo(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    (tmp_path / "graftlint.baseline").write_text(
        json.dumps({"version": 1, "entries": []})
    )


_FIXABLE_GL013 = {
    "cst_captioning_tpu/producer.py": (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def encode(x):\n"
        "    return jnp.tanh(x)\n"
        "def decode(feats):\n"
        "    return encode(feats) * 2\n"
    ),
    "cst_captioning_tpu/consumer.py": (
        "import jax\n"
        "import numpy as np\n"
        "from cst_captioning_tpu.producer import decode\n"
        "def to_host(feats):\n"
        "    tokens = decode(feats)\n"
        "    return np.asarray(tokens)\n"
    ),
}


def test_fix_applies_and_is_idempotent(tmp_path, capsys):
    """--fix rewrites np.asarray -> jax.device_get, the tree relints
    clean, and a second --fix is a byte-for-byte no-op (the pinned
    idempotence contract)."""
    _write_repo(tmp_path, _FIXABLE_GL013)
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache"]
    assert cli_main(args + ["--fix"]) == 0
    capsys.readouterr()
    fixed = (tmp_path / "cst_captioning_tpu/consumer.py").read_text()
    assert "jax.device_get(tokens)" in fixed and "np.asarray" not in fixed
    assert cli_main(args) == 0  # tree is lint-clean after the fix
    before = fixed
    assert cli_main(args + ["--fix"]) == 0
    assert (tmp_path / "cst_captioning_tpu/consumer.py").read_text() == before


_FIXABLE_GL013_NO_JAX = {
    "cst_captioning_tpu/producer.py":
        _FIXABLE_GL013["cst_captioning_tpu/producer.py"],
    # no `import jax` anywhere — the fix must insert it (once, despite
    # two findings wanting it)
    "cst_captioning_tpu/consumer.py": (
        "import numpy as np\n"
        "from cst_captioning_tpu.producer import decode\n"
        "def to_host(feats):\n"
        "    tokens = decode(feats)\n"
        "    return np.asarray(tokens)\n"
        "def to_host_twice(feats):\n"
        "    tokens = decode(feats)\n"
        "    return np.asarray(tokens)\n"
    ),
}


def test_fix_inserts_missing_jax_import(tmp_path, capsys):
    """A consumer with NO jax import still gets the mechanical rewrite:
    --fix inserts ``import jax`` exactly once (grouped onto the first
    import), rewrites BOTH sinks, relints clean, and stays idempotent."""
    _write_repo(tmp_path, _FIXABLE_GL013_NO_JAX)
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache"]
    assert cli_main(args + ["--fix"]) == 0
    capsys.readouterr()
    fixed = (tmp_path / "cst_captioning_tpu/consumer.py").read_text()
    lines = fixed.splitlines()
    assert lines[0] == "import jax" and lines[1] == "import numpy as np"
    assert fixed.count("import jax\n") == 1
    assert fixed.count("jax.device_get(tokens)") == 2
    assert "np.asarray" not in fixed
    assert cli_main(args) == 0  # tree is lint-clean after the fix
    before = fixed
    assert cli_main(args + ["--fix"]) == 0
    assert (tmp_path / "cst_captioning_tpu/consumer.py").read_text() == before


def test_fix_import_insertion_respects_future_imports(tmp_path, capsys):
    """``from __future__ import ...`` must stay first in the file: the
    inserted ``import jax`` lands after the last future import (and
    after the module docstring)."""
    files = dict(_FIXABLE_GL013_NO_JAX)
    files["cst_captioning_tpu/consumer.py"] = (
        '"""Reads captions back to host."""\n'
        "from __future__ import annotations\n"
        "import numpy as np\n"
        "from cst_captioning_tpu.producer import decode\n"
        "def to_host(feats):\n"
        "    tokens = decode(feats)\n"
        "    return np.asarray(tokens)\n"
    )
    _write_repo(tmp_path, files)
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache"]
    assert cli_main(args + ["--fix"]) == 0
    capsys.readouterr()
    lines = (
        tmp_path / "cst_captioning_tpu/consumer.py"
    ).read_text().splitlines()
    assert lines[1] == "from __future__ import annotations"
    assert lines[2] == "import jax"
    assert cli_main(args) == 0
    assert cli_main(args + ["--fix"]) == 0  # idempotent


def test_fix_dry_run_prints_diff_and_writes_nothing(tmp_path, capsys):
    _write_repo(tmp_path, _FIXABLE_GL013)
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache", "--fix", "--dry-run"]
    assert cli_main(args) == 0
    out = capsys.readouterr()
    assert "+    return jax.device_get(tokens)" in out.out
    assert "-    return np.asarray(tokens)" in out.out
    assert "would fix" in out.err
    src = (tmp_path / "cst_captioning_tpu/consumer.py").read_text()
    assert "np.asarray(tokens)" in src  # untouched


def test_fix_check_gates_until_fixed(tmp_path, capsys):
    """--fix-check is the CI spelling: exit 1 while an autofixable
    finding is unfixed, 0 after --fix; it never writes."""
    _write_repo(tmp_path, _FIXABLE_GL013)
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache"]
    assert cli_main(args + ["--fix-check"]) == 1
    err = capsys.readouterr().err
    assert "autofixable" in err and "--fix" in err
    src = (tmp_path / "cst_captioning_tpu/consumer.py").read_text()
    assert "np.asarray(tokens)" in src
    assert cli_main(args + ["--fix"]) == 0
    capsys.readouterr()
    assert cli_main(args + ["--fix-check"]) == 0


def test_fix_and_fix_check_are_exclusive(tmp_path, capsys):
    _write_repo(tmp_path, {})
    assert cli_main([str(tmp_path), "--root", str(tmp_path), "--fix",
                     "--fix-check"]) == 2
    assert cli_main([str(tmp_path), "--root", str(tmp_path),
                     "--dry-run"]) == 2


def test_fix_removes_stale_suppressions_and_baseline(tmp_path, capsys):
    """The two repair classes --check-stale only reports: a dead inline
    disable= comment is removed (whole line when alone, trimmed when
    sharing one) and a dead baseline entry is dropped from the file."""
    _write_repo(tmp_path, {
        "cst_captioning_tpu/mod.py": (
            "def f(x):\n"
            "    return x  # graftlint: disable=GL001 (long fixed)\n"
            "def g(x):\n"
            "    # graftlint: disable-next-line=GL003\n"
            "    return x\n"
        ),
    })
    (tmp_path / "graftlint.baseline").write_text(json.dumps({
        "version": 1,
        "entries": [{
            "rule": "GL001", "path": "cst_captioning_tpu/mod.py",
            "context": "return np.asarray(ghost)", "count": 1,
            "reason": "the code site was fixed long ago",
        }],
    }))
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache"]
    assert cli_main(args + ["--check-stale"]) == 1  # stale gates
    capsys.readouterr()
    assert cli_main(args + ["--fix"]) == 0
    src = (tmp_path / "cst_captioning_tpu/mod.py").read_text()
    assert "graftlint" not in src
    assert "return x" in src  # the code lines survived
    bl = json.loads((tmp_path / "graftlint.baseline").read_text())
    assert bl["entries"] == []
    capsys.readouterr()
    assert cli_main(args + ["--check-stale"]) == 0  # now stale-clean


def test_fix_trims_one_dead_id_from_shared_suppression(tmp_path, capsys):
    """A comment disabling two rules where only one still fires keeps the
    live id."""
    _write_repo(tmp_path, {
        "cst_captioning_tpu/train/mod.py": (
            "import jax\n"
            "import numpy as np\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return np.asarray(x)  # graftlint: disable=GL001,GL003\n"
        ),
    })
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache"]
    assert cli_main(args + ["--fix"]) == 0
    src = (tmp_path / "cst_captioning_tpu/train/mod.py").read_text()
    assert "disable=GL001" in src and "GL003" not in src


def test_overlapping_edits_refused():
    """Two fixes claiming the same span: the engine applies the first and
    refuses the second — never merges."""
    from cst_captioning_tpu.tools.graftlint.core import Edit
    from cst_captioning_tpu.tools.graftlint.fixes import (
        OverlappingEditsError,
        apply_edits,
        edits_overlap,
    )

    src = "a = np.asarray(x)\n"
    e1 = Edit(line=1, col=4, end_line=1, end_col=14, replacement="jd")
    e2 = Edit(line=1, col=4, end_line=1, end_col=14, replacement="other")
    e3 = Edit(line=1, col=15, end_line=1, end_col=16, replacement="y")
    with pytest.raises(OverlappingEditsError):
        apply_edits(src, [e1, e2])
    assert edits_overlap(src, [e1], [e2])
    assert not edits_overlap(src, [e1], [e3])
    assert apply_edits(src, [e1, e3]) == "a = jd(y)\n"


def test_fix_gl011_carry_init_dtype(tmp_path, capsys):
    _write_repo(tmp_path, {
        "cst_captioning_tpu/mod.py": (
            "import jax\n"
            "import jax.numpy as jnp\n"
            "def outer(xs):\n"
            "    def body(c, x):\n"
            "        return (c + x).astype(jnp.bfloat16), x\n"
            "    init = jnp.zeros((4,), jnp.float32)\n"
            "    return jax.lax.scan(body, init, xs)\n"
        ),
    })
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache", "--fix"]
    assert cli_main(args) == 0
    src = (tmp_path / "cst_captioning_tpu/mod.py").read_text()
    assert "init = jnp.zeros((4,), jnp.bfloat16)" in src


def test_fix_gl005_routes_through_dtype_param(tmp_path, capsys):
    _write_repo(tmp_path, {
        "cst_captioning_tpu/models/mod.py": (
            "import jax.numpy as jnp\n"
            "def forward(x, dtype):\n"
            "    bias = jnp.zeros((4,), jnp.float32)\n"
            "    return x + bias\n"
        ),
    })
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache", "--fix"]
    assert cli_main(args) == 0
    src = (tmp_path / "cst_captioning_tpu/models/mod.py").read_text()
    assert "bias = jnp.zeros((4,), dtype)" in src


def test_fix_gl005_no_dtype_param_stays_manual(tmp_path, capsys):
    """Without a dtype in scope there is no mechanical spelling: the
    finding still gates, but --fix-check does not claim it."""
    _write_repo(tmp_path, {
        "cst_captioning_tpu/models/mod.py": (
            "import jax.numpy as jnp\n"
            "def forward(x):\n"
            "    bias = jnp.zeros((4,), jnp.float32)\n"
            "    return x + bias\n"
        ),
    })
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache"]
    assert cli_main(args + ["--fix-check"]) == 1  # GL005 still gates...
    err = capsys.readouterr().err
    assert "autofixable" not in err  # ...but not as an unfixed autofix


def test_fix_skips_baselined_findings(tmp_path, capsys):
    """Baselined findings are intentional: --fix must not rewrite them."""
    _write_repo(tmp_path, _FIXABLE_GL013)
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache"]
    assert cli_main(args + ["--write-baseline"]) == 0
    capsys.readouterr()
    assert cli_main(args + ["--fix"]) == 0
    src = (tmp_path / "cst_captioning_tpu/consumer.py").read_text()
    assert "np.asarray(tokens)" in src  # untouched: grandfathered


def test_json_fixes_block_counts_autofixable(tmp_path, capsys):
    _write_repo(tmp_path, _FIXABLE_GL013)
    rc = cli_main([str(tmp_path / "cst_captioning_tpu"), "--root",
                   str(tmp_path), "--no-cache", "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["fixes"]["autofixable"] == 1
    assert report["fixes"]["by_rule"] == {"GL013": 1}
    fixable = [f for f in report["findings"] if f["fix"]]
    assert len(fixable) == 1
    fix = fixable[0]["fix"]
    assert "device_get" in fix["description"]
    assert all(
        set(e) == {"line", "col", "end_line", "end_col", "replacement"}
        for e in fix["edits"]
    )


# ---- summary cache: v3 schema (axis + donation summaries) -------------------

def test_cache_schema_bump_cold_starts_cleanly(tmp_path):
    """A cache written by an OLDER schema version is discarded wholesale:
    the build re-summarizes everything and still computes the new axis/
    donation facts (no half-read of the old schema)."""
    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    mod = tmp_path / "m.py"
    mod.write_text(
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def update(state, batch):\n"
        "    return state\n"
    )
    cache = tmp_path / "cache.json"
    cache.write_text(json.dumps({
        "version": 2,  # the pre-axis/donation schema
        "files": {"m.py": {"mtime": 0.0, "size": 0,
                           "summary": {"bogus": "shape"}}},
    }))
    idx = ProjectIndex.build([str(mod)], str(tmp_path),
                             cache_path=str(cache))
    assert idx.stats.summarized == 1 and idx.stats.cached == 0
    assert idx.functions["m.update"].donated_argnums == [0]
    # the rewritten cache carries the current schema version and
    # round-trips the new fields
    from cst_captioning_tpu.tools.graftlint.project import _CACHE_VERSION
    data = json.loads(cache.read_text())
    assert data["version"] == _CACHE_VERSION
    idx2 = ProjectIndex.build([str(mod)], str(tmp_path),
                              cache_path=str(cache))
    assert idx2.stats.cached == 1
    assert idx2.functions["m.update"].donated_argnums == [0]


def test_cache_round_trips_axis_and_donation_summaries(tmp_path):
    """Warm-cache builds must serve the NEW summary fields (axis tables,
    donation facts) identically to a cold build — the fields are part of
    the cached schema, not recomputed."""
    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    (tmp_path / "lib.py").write_text(
        "import jax\n"
        "def helper(x):\n"
        "    return jax.lax.psum(x, 'data')\n"
        "def make_step(impl):\n"
        "    return jax.jit(impl, donate_argnums=(1,))\n"
    )
    (tmp_path / "use.py").write_text(
        "import jax\n"
        "from lib import helper\n"
        "def run(xs):\n"
        "    return jax.vmap(helper, axis_name='data')(xs)\n"
    )
    files = [str(tmp_path / "lib.py"), str(tmp_path / "use.py")]
    cache = tmp_path / "cache.json"
    cold = ProjectIndex.build(files, str(tmp_path), cache_path=str(cache))
    warm = ProjectIndex.build(files, str(tmp_path), cache_path=str(cache))
    assert warm.stats.cached == 2 and warm.stats.summarized == 0
    for idx in (cold, warm):
        assert idx.functions["lib.make_step"].returns_donating == [1]
        env, has_ctx = idx.axis_env_of("lib", "helper")
        assert has_ctx and "data" in env
        info = idx.modules["lib"].axis_funcs["helper"]
        assert info.collectives == [("psum", "data", 3, 11)]


def test_axis_env_transitive_through_helper_chain(tmp_path):
    """Axis environments propagate through ordinary call edges: bound
    body -> helper -> leaf, the leaf inherits the binding two hops up."""
    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    (tmp_path / "m.py").write_text(
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def leaf(x):\n"
        "    return jax.lax.psum(x, 'data')\n"
        "def mid(x):\n"
        "    return leaf(x)\n"
        "def run(mesh, xs):\n"
        "    def body(x):\n"
        "        return mid(x)\n"
        "    return shard_map(body, mesh=mesh, in_specs=None,\n"
        "                     out_specs=None, axis_names=('data',))(xs)\n"
    )
    idx = ProjectIndex.build([str(tmp_path / "m.py")], str(tmp_path),
                             cache_path="")
    for qual in ("leaf", "mid", "run.body"):
        env, has_ctx = idx.axis_env_of("m", qual)
        assert has_ctx and env == frozenset({"data"}), qual

# ---- GL018: partition-rule table coverage & shadowing -----------------------

def test_gl018_shadowed_no_match_and_uncovered():
    """THE acceptance fixture: a non-canonical regex rule table with a
    fully-shadowed dead row (autofixable), a rule matching no contract
    param, and a contract param matched by no rule — three findings; the
    suppressed twin and the dynamically-built table stay quiet."""
    findings = _lint_fixture("gl018", ["GL018"])
    assert _rules_of(findings) == ["GL018"]
    assert all(f.path.endswith("bucket_rules.py") for f in findings)
    by_line = {f.line: f for f in findings}
    assert set(by_line) == {17, 20, 21}
    # uncovered contract param anchors to the table header
    assert "params/head/w" in by_line[17].message
    assert by_line[17].fix is None
    # dead row: every param it matches is claimed earlier — autofix
    # deletes it (provably behavior-identical under first-match-wins)
    assert "dec_again" in by_line[20].message
    assert "shadowed" in by_line[20].message
    assert by_line[20].fix is not None
    # rule whose family was renamed away: matches nothing
    assert "lstm_gate" in by_line[21].message
    assert by_line[21].fix is None
    for f in findings:
        assert f.severity == "error"


def test_gl018_dynamic_table_provably_cannot():
    """A table built by a comprehension carries no literal (family,
    regex) rows: single-file analysis provably cannot check it, so the
    rule stays quiet rather than guess."""
    assert _lint_fixture(
        "gl018", ["GL018"],
        only="cst_captioning_tpu/parallel/dynamic_rules.py",
    ) == []


def test_gl018_canonical_table_shadowing_only(tmp_path):
    """GL007 owns coverage for the canonical PARAM_PARTITION_RULES —
    GL018 adds only the shadowing check there (no duplicate no-match /
    uncovered findings)."""
    (tmp_path / "scripts").mkdir()
    (tmp_path / "scripts" / "shardings_contract.json").write_text(
        json.dumps({"params": ["params/enc/w", "params/dec/w",
                               "params/orphan/w"]})
    )
    findings = _lint(tmp_path, "cst_captioning_tpu/train/mesh.py", (
        "PARAM_PARTITION_RULES = (\n"
        "    ('enc', r'params/enc/.*', ()),\n"
        "    ('enc_dup', r'params/enc/w', ()),\n"   # shadowed -> GL018
        "    ('no_match', r'params/gone/.*', ()),\n"  # GL007's job, not ours
        ")\n"
    ), rules=["GL018"])
    assert len(findings) == 1
    assert findings[0].line == 3 and "enc_dup" in findings[0].message
    # params/orphan/w is uncovered, but coverage of the canonical table
    # belongs to GL007 — GL018 must not double-report it
    assert all("orphan" not in f.message for f in findings)


def test_gl018_covers_mp_table_next_to_canonical(tmp_path):
    """The flagship-XL layout: MP_PARAM_PARTITION_RULES lives beside the
    canonical table in the same module. GL018 applies the FULL check there
    (coverage + shadowing), so a dead mp row and an mp rule matching no
    contract param are both findings while the canonical twin stays
    GL007's job."""
    (tmp_path / "scripts").mkdir()
    (tmp_path / "scripts" / "shardings_contract.json").write_text(
        json.dumps({"params": ["params/enc/w", "params/dec/w"]})
    )
    findings = _lint(tmp_path, "cst_captioning_tpu/train/mesh.py", (
        "PARAM_PARTITION_RULES = (\n"
        "    ('enc', r'params/enc/.*', ()),\n"
        "    ('dec', r'params/dec/.*', ()),\n"
        ")\n"
        "MP_PARAM_PARTITION_RULES = (\n"
        "    ('enc', r'params/enc/.*', ()),\n"
        "    ('dec', r'params/dec/.*', ()),\n"
        "    ('dec_dead', r'params/dec/w', ()),\n"     # shadowed by 'dec'
        "    ('gate_gone', r'params/gate/.*', ()),\n"  # matches nothing
        ")\n"
    ), rules=["GL018"])
    assert _rules_of(findings) == ["GL018"]
    msgs = {f.line: f for f in findings}
    assert any("dec_dead" in f.message and "shadowed" in f.message
               and f.fix is not None for f in findings)
    assert any("gate_gone" in f.message for f in findings)
    assert all("MP_PARAM_PARTITION_RULES" in f.message for f in findings)
    assert len(msgs) == 2


def _mp_mesh_fixture(tmp_path):
    """A fixture train/mesh.py declaring the flagship-XL axes the way the
    real one does — string defaults of *axis params (the scrape's input)."""
    (tmp_path / "cst_captioning_tpu" / "train").mkdir(parents=True)
    (tmp_path / "cst_captioning_tpu" / "train" / "mesh.py").write_text(
        "def make_mesh(num_devices=0, axis='data', seq_devices=1,\n"
        "              seq_axis='seq', mp_devices=1, mp_axis='mp'):\n"
        "    return None\n"
    )


def test_gl015_learns_mp_axis_from_mesh_scrape(tmp_path):
    """P('data', 'mp') literals lint clean once make_mesh grows the
    mp_axis='mp' default — no rule-table edit, the axis scrape picks it
    up; an undeclared axis still fires and the allowed set names 'mp'."""
    _mp_mesh_fixture(tmp_path)
    (tmp_path / "cst_captioning_tpu" / "use.py").write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "def f():\n"
        "    return P('data', 'mp'), P(None, 'mp')\n"
    )
    assert lint_paths([str(tmp_path)], str(tmp_path), rule_ids=["GL015"],
                      cache_path="").findings == []
    (tmp_path / "cst_captioning_tpu" / "use.py").write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "def f():\n"
        "    return P('tp')\n"
    )
    findings = lint_paths([str(tmp_path)], str(tmp_path),
                          rule_ids=["GL015"], cache_path="").findings
    assert _rules_of(findings) == ["GL015"]
    assert "'tp'" in findings[0].message and "mp" in findings[0].message


def test_gl016_mp_axis_binding_via_shard_map(tmp_path):
    """A psum over 'mp' is quiet when every reachable caller binds it
    (shard_map axis_names including 'mp') and a finding from a plain
    calling context — same fixpoint as 'data'/'seq', new axis."""
    _mp_mesh_fixture(tmp_path)
    (tmp_path / "cst_captioning_tpu" / "merge.py").write_text(
        "import jax\n"
        "from jax.experimental.shard_map import shard_map\n"
        "def merge_lse(x):\n"
        "    return jax.lax.psum(x, 'mp')\n"
        "def run(mesh, xs):\n"
        "    def body(x):\n"
        "        return merge_lse(x)\n"
        "    return shard_map(body, mesh=mesh, in_specs=None,\n"
        "                     out_specs=None, axis_names=('data', 'mp'))(xs)\n"
    )
    assert lint_paths([str(tmp_path)], str(tmp_path), rule_ids=["GL016"],
                      cache_path="").findings == []
    (tmp_path / "cst_captioning_tpu" / "merge.py").write_text(
        "import jax\n"
        "def merge_lse(x):\n"
        "    return jax.lax.psum(x, 'mp')\n"
        "def run(xs):\n"
        "    return [merge_lse(x) for x in xs]\n"
    )
    findings = lint_paths([str(tmp_path)], str(tmp_path),
                          rule_ids=["GL016"], cache_path="").findings
    assert _rules_of(findings) == ["GL016"]
    assert "'mp'" in findings[0].message


def test_gl018_fix_deletes_dead_rule_and_is_idempotent(tmp_path, capsys):
    """--fix removes the provably-dead shadowed row (whole line, trailing
    comma and all), the tree relints clean, and a second --fix is a
    byte-for-byte no-op."""
    _write_repo(tmp_path, {
        "scripts/shardings_contract.json": json.dumps(
            {"params": ["params/enc/w", "params/dec/w"]}
        ),
        "cst_captioning_tpu/parallel/bucket_rules.py": (
            "SHARDING_CONTRACT = 'scripts/shardings_contract.json'\n"
            "COMM_PARTITION_RULES = (\n"
            "    ('all', r'params/.*', ()),\n"
            "    ('dup', r'params/dec/.*', ()),\n"
            ")\n"
        ),
    })
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache", "--rules", "GL018"]
    assert cli_main(args + ["--fix"]) == 0
    capsys.readouterr()
    fixed = (
        tmp_path / "cst_captioning_tpu/parallel/bucket_rules.py"
    ).read_text()
    assert "dup" not in fixed and "('all', r'params/.*', ())," in fixed
    assert cli_main(args) == 0  # clean after the fix
    before = fixed
    assert cli_main(args + ["--fix"]) == 0
    assert (
        tmp_path / "cst_captioning_tpu/parallel/bucket_rules.py"
    ).read_text() == before


# ---- GL019: cross-host collective operand drift -----------------------------

def test_gl019_cross_file_drift():
    """THE acceptance fixture: per-host constructor shape, a
    process_index()-conditional shape, and a callee whose summary says
    returns_host_shape (plus a helper reached only through the seed
    module's call closure) all fire; the param-shaped, literal-shaped,
    and gather-lengths-then-pad negatives stay quiet."""
    findings = _lint_fixture("gl019", ["GL019"])
    assert _rules_of(findings) == ["GL019"]
    sites = {(os.path.basename(f.path), f.line) for f in findings}
    assert sites == {
        ("helpers.py", 18),     # reachability-only finding
        ("multihost.py", 24),   # len(jax.local_devices()) leading dim
        ("multihost.py", 32),   # branch-dependent shape
        ("multihost.py", 36),   # cross-module returns_host_shape fact
    }
    for f in findings:
        assert f.severity == "error"
        # every message names the canonical repair
        assert "process_allgather" in f.message
    by_site = {(os.path.basename(f.path), f.line): f for f in findings}
    assert "local_devices" in by_site[("multihost.py", 24)].message
    assert "branch" in by_site[("multihost.py", 32)].message
    assert "local_block" in by_site[("multihost.py", 36)].message


def test_gl019_single_file_provably_cannot():
    """Linting the helper module ALONE must find nothing: without the
    seed module in the index, nothing proves its psum is a cross-host
    rendezvous (the reachability closure is empty)."""
    assert _lint_fixture(
        "gl019", ["GL019"],
        only="cst_captioning_tpu/parallel/helpers.py",
    ) == []


def test_gl019_host_value_reduction_is_fine(tmp_path):
    """VALUE host-dependence is the point of a reduction — only shape /
    wire-dtype drift deadlocks. A psum OVER a per-host value with a
    host-invariant shape must stay quiet."""
    findings = _lint(tmp_path, "cst_captioning_tpu/train/multihost.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def count_devices():\n"
        "    n = float(jax.local_device_count())\n"
        "    return jax.lax.psum(jnp.float32(n), 'data')\n"
    ), rules=["GL019"])
    assert findings == []


# ---- GL020: Pallas kernel contract ------------------------------------------

def test_gl020_arity_divisibility_and_vmem():
    """THE acceptance fixture: index-map arity vs grid rank (error),
    block dim vs grid divisor without a pl.when guard (error), and a
    fully-resolvable VMEM estimate over the ~16 MiB budget (warning);
    the guarded twin and the suppressed twin stay quiet."""
    findings = _lint_fixture(
        "gl020", ["GL020"],
        only="cst_captioning_tpu/ops/toy_kernels.py",
    )
    assert _rules_of(findings) == ["GL020"]
    assert all(f.path.endswith("toy_kernels.py") for f in findings)
    by_line = {f.line: f for f in findings}
    assert set(by_line) == {35, 46, 76}
    assert "arity" in by_line[35].message or "argument" in by_line[35].message
    assert by_line[35].severity == "error"
    assert "block_k" in by_line[46].message
    assert "block_n" in by_line[46].message
    assert by_line[46].severity == "error"
    assert "VMEM" in by_line[76].message and "MiB" in by_line[76].message
    assert by_line[76].severity == "warning"


def test_gl020_prefetch_grid_spec_sites():
    """grid_spec= sites resolve through PrefetchScalarGridSpec/GridSpec:
    index-map arity must be grid rank + num_scalar_prefetch (the prefetch
    refs trail the grid indices), unblocked memory_space=ANY refs and DMA
    semaphores cost no VMEM, and the clean twins stay quiet."""
    findings = _lint_fixture(
        "gl020", ["GL020"],
        only="cst_captioning_tpu/ops/prefetch_kernels.py",
    )
    assert _rules_of(findings) == ["GL020"]
    assert [f.line for f in findings] == [63]
    assert "scalar-prefetch" in findings[0].message
    assert findings[0].severity == "error"


def test_gl020_opaque_site_provably_cannot():
    """grid through an attribute, in_specs through a helper call:
    single-file analysis provably cannot resolve either — quiet, never
    guess."""
    assert _lint_fixture(
        "gl020", ["GL020"],
        only="cst_captioning_tpu/ops/opaque_kernels.py",
    ) == []


# ---- cache: corruption, v5 fields, submesh scrape ---------------------------

def test_corrupt_cache_falls_back_to_cold(tmp_path):
    """A truncated / garbage cache file (the failure the atomic
    tmp-then-rename write prevents) must cold-start cleanly, then leave
    a valid cache behind."""
    from cst_captioning_tpu.tools.graftlint import ProjectIndex
    from cst_captioning_tpu.tools.graftlint.project import _CACHE_VERSION

    mod = tmp_path / "m.py"
    mod.write_text("def f():\n    return 1\n")
    cache = tmp_path / "cache.json"
    cache.write_text('{"version": 5, "files": {')  # torn mid-write
    idx = ProjectIndex.build([str(mod)], str(tmp_path),
                             cache_path=str(cache))
    assert idx.stats.summarized == 1 and idx.stats.cached == 0
    data = json.loads(cache.read_text())  # rewritten valid
    assert data["version"] == _CACHE_VERSION
    warm = ProjectIndex.build([str(mod)], str(tmp_path),
                              cache_path=str(cache))
    assert warm.stats.cached == 1 and warm.stats.summarized == 0


def test_cache_round_trips_shape_and_host_facts(tmp_path):
    """The v5 summary fields (literal dims, PartitionSpec bindings,
    host-shape provenance) must serve identically from a warm cache."""
    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    (tmp_path / "lib.py").write_text(
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax.sharding import PartitionSpec as P\n"
        "def local_block():\n"
        "    return jnp.zeros((jax.local_device_count(), 128),\n"
        "                     jnp.float32)\n"
        "def buf():\n"
        "    x = jnp.zeros((8, 128), jnp.bfloat16)\n"
        "    spec = P('data', None)\n"
        "    return x\n"
    )
    cache = tmp_path / "cache.json"
    files = [str(tmp_path / "lib.py")]
    cold = ProjectIndex.build(files, str(tmp_path), cache_path=str(cache))
    warm = ProjectIndex.build(files, str(tmp_path), cache_path=str(cache))
    assert warm.stats.cached == 1 and warm.stats.summarized == 0
    for idx in (cold, warm):
        host = idx.functions["lib.local_block"]
        assert host.returns_host_shape
        assert "local_device_count" in host.host_shape_reason
        plain = idx.functions["lib.buf"]
        assert plain.array_dims["x"] == [8, 128]
        assert plain.pspec_vars["spec"] == ["data", None]
        assert plain.return_dims == [8, 128]
        assert not plain.returns_host_shape


def test_submesh_axes_merge_into_mesh_decl(tmp_path):
    """parallel/submesh.py axis declarations join the train/mesh.py
    scrape, so GL012 treats the actor/learner submesh axis as declared."""
    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    mesh = tmp_path / "cst_captioning_tpu" / "train" / "mesh.py"
    mesh.parent.mkdir(parents=True)
    mesh.write_text("def make_mesh(axis='data'):\n    return axis\n")
    sub = tmp_path / "cst_captioning_tpu" / "parallel" / "submesh.py"
    sub.parent.mkdir(parents=True)
    sub.write_text(
        "def plan_submesh(mesh, rollout_axis='actor'):\n"
        "    return rollout_axis\n"
    )
    mod = tmp_path / "cst_captioning_tpu" / "mod.py"
    mod.write_text(
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'actor')\n"
    )
    idx = ProjectIndex.build(
        [str(mesh), str(sub), str(mod)], str(tmp_path), cache_path="",
    )
    assert {"data", "actor"} <= set(idx.mesh.axes)
    result = lint_paths([str(mod)], str(tmp_path), rule_ids=["GL012"],
                        cache_path="")
    assert result.findings == []
    # contrast: without submesh.py the same axis IS a GL012 typo
    sub.unlink()
    result = lint_paths([str(mod)], str(tmp_path), rule_ids=["GL012"],
                        cache_path="")
    assert _rules_of(result.findings) == ["GL012"]


# ---- README drift pin -------------------------------------------------------

def test_readme_rule_table_tracks_registry():
    """Every registered rule id appears in README's Static analysis rule
    table, and every GLxxx the README mentions is a live registered rule
    (no retired ids lingering in the docs)."""
    import re

    readme = open(os.path.join(REPO, "README.md")).read()
    registered = set(all_rules())
    mentioned = set(re.findall(r"\bGL\d{3}\b", readme))
    missing = {
        rid for rid in registered
        if not re.search(rf"\*\*{rid}\b", readme)
    }
    assert not missing, f"rules missing from README table: {sorted(missing)}"
    retired = mentioned - registered
    assert not retired, f"README names unregistered rules: {sorted(retired)}"


# ---- --changed-only: the git-scoped fast path -------------------------------

def _git(tmp_path, *argv):
    subprocess.run(
        ["git", "-C", str(tmp_path), "-c", "user.email=ci@example.com",
         "-c", "user.name=ci", *argv],
        check=True, capture_output=True,
    )


def test_changed_only_scopes_pass_two_to_the_diff(tmp_path, capsys):
    """Pass 1 still indexes the whole tree, but findings come only from
    files git reports changed: a pre-existing finding in an UNTOUCHED
    file stays out of the fast path (the full-tree gate owns it)."""
    files = dict(_FIXABLE_GL013)  # consumer.py holds the GL013 finding
    _write_repo(tmp_path, files)
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    args = [str(tmp_path / "cst_captioning_tpu"), "--root", str(tmp_path),
            "--no-cache", "--changed-only"]
    # clean tree: nothing to lint, exit 0
    assert cli_main(args) == 0
    assert "no changed" in capsys.readouterr().err
    # touch ONLY the clean producer: consumer's finding must not gate
    # the fast path
    prod = tmp_path / "cst_captioning_tpu/producer.py"
    prod.write_text(prod.read_text() + "\n# tuning note\n")
    assert cli_main(args) == 0
    err = capsys.readouterr().err
    assert "1 file(s), 0 finding(s)" in err
    # now dirty the consumer too: its finding rides the fast path
    assert cli_main(args + ["--rules", "GL013"]) == 0  # not changed yet
    capsys.readouterr()
    cons = tmp_path / "cst_captioning_tpu/consumer.py"
    cons.write_text(cons.read_text() + "\n# touched\n")
    assert cli_main(args) == 1
    out = capsys.readouterr()
    assert "GL013" in out.out and "2 file(s)" in out.err


def test_changed_only_excludes_authoritative_gates(tmp_path, capsys):
    _write_repo(tmp_path, {})
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "add", "-A")
    _git(tmp_path, "commit", "-qm", "seed")
    base = [str(tmp_path), "--root", str(tmp_path), "--changed-only"]
    for gate in ("--fix", "--fix-check", "--write-baseline"):
        assert cli_main(base + [gate]) == 2
        assert "exclusive" in capsys.readouterr().err
    assert cli_main(base + ["--check-stale"]) == 2


def test_changed_only_outside_git_is_a_usage_error(tmp_path, capsys):
    _write_repo(tmp_path, {"cst_captioning_tpu/m.py": "X = 1\n"})
    env = dict(os.environ, GIT_DIR=str(tmp_path / "nope" / ".git"),
               GIT_CEILING_DIRECTORIES=str(tmp_path))
    rc = subprocess.run(
        [sys.executable, "-m", "cst_captioning_tpu.tools.graftlint",
         "cst_captioning_tpu", "--root", str(tmp_path), "--changed-only"],
        cwd=REPO, env=env, capture_output=True, text=True,
    )
    assert rc.returncode == 2
    assert "git checkout" in rc.stderr
