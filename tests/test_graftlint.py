"""graftlint: per-rule positive/negative fixtures, baseline round-trip,
--json schema, and the tier-1 self-check that keeps the repo lint-clean.

Pure AST analysis — nothing here touches a JAX backend except the
import-cleanliness subprocess test at the bottom (which exists to PROVE no
backend comes up).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from cst_captioning_tpu.tools.graftlint import Baseline, all_rules, lint_paths
from cst_captioning_tpu.tools.graftlint.cli import main as cli_main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every lintable top-level target of the repo (scripts/lint.sh mirrors this)
REPO_LINT_PATHS = [
    os.path.join(REPO, p)
    for p in ("cst_captioning_tpu", "tests", "scripts", "bench.py",
              "bench_attention.py", "bench_recipe.py")
]


# deliberately lint-dirty cross-file fixture pairs (skipped by the repo
# walk — "fixtures" is in core._SKIP_DIRS — and linted explicitly here)
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")


def _lint(tmp_path, relname: str, source: str, rules=None):
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(source)
    # cache_path="": unit fixtures rewrite files faster than mtime
    # granularity; the cache has its own dedicated tests
    result = lint_paths([str(path)], str(tmp_path), rule_ids=rules,
                        cache_path="")
    return result.findings


def _lint_fixture(sub: str, rules, only: str | None = None):
    root = os.path.join(FIXTURES, sub)
    paths = [os.path.join(root, only)] if only else [root]
    return lint_paths(paths, root, rule_ids=rules, cache_path="").findings


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ---- GL001: host sync -------------------------------------------------------

def test_gl001_positive_sync_in_traced_function(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)\n"
    ), rules=["GL001"])
    assert _rules_of(findings) == ["GL001"]
    assert findings[0].severity == "error"


def test_gl001_positive_sync_in_scan_body(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        return c, float(x)\n"
        "    return jax.lax.scan(body, 0, xs)\n"
    ), rules=["GL001"])
    assert _rules_of(findings) == ["GL001"]


def test_gl001_negative_sync_outside_trace(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return x * 2\n"
        "def host(x):\n"
        "    return np.asarray(step(x))\n"
    ), rules=["GL001"])
    assert findings == []


def test_gl001_positive_per_step_loop_sync(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake_loop.py", (
            "import jax\n"
            "def epoch(step, batches, log):\n"
            "    for b in batches:\n"
            "        state, m = step(b)\n"
            "        log.append(float(m['loss']))\n"
        ), rules=["GL001"],
    )
    assert _rules_of(findings) == ["GL001"]
    assert findings[0].severity == "warning"


def test_gl001_negative_gated_loop_sync(tmp_path):
    # a sync inside a log-every-N `if` body is amortized — not flagged
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake_loop.py", (
            "import jax\n"
            "def epoch(step, batches, log, every):\n"
            "    n = 0\n"
            "    for b in batches:\n"
            "        state, m = step(b)\n"
            "        n += 1\n"
            "        if every and n % every == 0:\n"
            "            log.append(float(m['loss']))\n"
        ), rules=["GL001"],
    )
    assert findings == []


def test_gl001_negative_loop_sync_outside_hot_packages(tmp_path):
    # same loop in a host-side package: scoring IS a readback, not flagged
    findings = _lint(
        tmp_path, "cst_captioning_tpu/metrics/fake.py", (
            "import jax\n"
            "def score(rows):\n"
            "    out = []\n"
            "    for r in rows:\n"
            "        out.append(float(r))\n"
            "    return out\n"
        ), rules=["GL001"],
    )
    assert findings == []


# ---- GL002: PRNG key reuse --------------------------------------------------

def test_gl002_positive_key_reuse(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "def rollout(key):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.uniform(key, (2,))\n"
        "    return a + b\n"
    ), rules=["GL002"])
    assert _rules_of(findings) == ["GL002"]
    assert "line 3" in findings[0].message


def test_gl002_negative_split_between_consumers(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "def rollout(key):\n"
        "    k1, k2 = jax.random.split(key)\n"
        "    a = jax.random.normal(k1, (2,))\n"
        "    key, sub = jax.random.split(k2)\n"
        "    b = jax.random.uniform(sub, (2,))\n"
        "    c = jax.random.normal(key, (2,))\n"
        "    return a + b + c\n"
    ), rules=["GL002"])
    assert findings == []


def test_gl002_negative_rebound_key(tmp_path):
    # consuming, REBINDING, then consuming again is the canonical pattern
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "def loop(key, n):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    key = jax.random.fold_in(key, 1)\n"
        "    b = jax.random.normal(key, (2,))\n"
        "    return a + b\n"
    ), rules=["GL002"])
    assert findings == []


def test_gl002_not_applied_in_tests(tmp_path):
    # determinism assertions reuse keys on purpose
    findings = _lint(tmp_path, "tests/test_fake.py", (
        "import jax\n"
        "def test_deterministic(key):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.normal(key, (2,))\n"
        "    assert (a == b).all()\n"
    ), rules=["GL002"])
    assert findings == []


# ---- GL003: Python branch on traced value -----------------------------------

def test_gl003_positive_if_on_jnp_value(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    s = jnp.sum(x)\n"
        "    if s > 0:\n"
        "        return x\n"
        "    return -x\n"
    ), rules=["GL003"])
    assert _rules_of(findings) == ["GL003"]


def test_gl003_positive_while_on_lax_value(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    while jax.lax.reduce_max(x) > 0:\n"
        "        x = x - 1\n"
        "    return x\n"
    ), rules=["GL003"])
    assert _rules_of(findings) == ["GL003"]


def test_gl003_negative_static_branch(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def make(with_greedy):\n"
        "    @jax.jit\n"
        "    def f(x):\n"
        "        if with_greedy:\n"
        "            return jnp.sum(x)\n"
        "        return x\n"
        "    return f\n"
    ), rules=["GL003"])
    assert findings == []


# ---- GL004: jit step without donation ---------------------------------------

def test_gl004_positive_undonated_train_step(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def train_step(state, batch):\n"
        "    return state\n"
    ), rules=["GL004"])
    assert _rules_of(findings) == ["GL004"]


def test_gl004_negative_explicit_donation(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def train_step(state, batch):\n"
        "    return state\n"
        "def make_update(fn, donate):\n"
        "    return jax.jit(fn, donate_argnums=(0,) if donate else ())\n"
    ), rules=["GL004"])
    assert findings == []


def test_gl004_negative_stateless_decode_step(tmp_path):
    # a decode 'step' carries no train state: donation buys nothing
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "@jax.jit\n"
        "def step(params, feats):\n"
        "    return feats\n"
    ), rules=["GL004"])
    assert findings == []


# ---- GL005: f32 literal in bf16 module --------------------------------------

def test_gl005_positive_f32_literal_in_models(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/models/fake.py", (
            "import jax.numpy as jnp\n"
            "def forward(x):\n"
            "    bias = jnp.zeros((4,), jnp.float32)\n"
            "    return x + bias\n"
        ), rules=["GL005"],
    )
    assert _rules_of(findings) == ["GL005"]


def test_gl005_negative_config_dtype_and_out_of_scope(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/models/fake.py", (
            "import jax.numpy as jnp\n"
            "def forward(x, cfg):\n"
            "    bias = jnp.zeros((4,), jnp.dtype(cfg.dtype))\n"
            "    return x + bias\n"
        ), rules=["GL005"],
    )
    assert findings == []
    # f32 input data built in tests/benches is fine (the model casts)
    findings = _lint(
        tmp_path, "tests/test_fake.py", (
            "import jax.numpy as jnp\n"
            "x = jnp.zeros((4,), jnp.float32)\n"
        ), rules=["GL005"],
    )
    assert findings == []


# ---- GL006: heavy imports / import-time device work -------------------------

def test_gl006_positive_torch_import(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake.py",
        "import torch\n", rules=["GL006"],
    )
    assert _rules_of(findings) == ["GL006"]


def test_gl006_positive_module_level_device_work(tmp_path):
    findings = _lint(tmp_path, "bench_fake.py", (
        "import jax\n"
        "N = len(jax.devices())\n"
    ), rules=["GL006"])
    assert _rules_of(findings) == ["GL006"]


def test_gl006_negative_guarded_and_function_scoped(tmp_path):
    findings = _lint(tmp_path, "bench_fake.py", (
        "import jax\n"
        "import numpy as np\n"
        "def main():\n"
        "    return len(jax.devices())\n"
        "if __name__ == '__main__':\n"
        "    print(jax.devices())\n"
    ), rules=["GL006"])
    assert findings == []


# ---- GL007: partition-rule coverage -----------------------------------------

_CONTRACT = {"params": ["params/lstm0/kernel", "params/orphan/bias"]}


def _write_contract(tmp_path, params):
    p = tmp_path / "scripts" / "shardings_contract.json"
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps({"params": params}))


def test_gl007_positive_unmatched_rule_and_unruled_param(tmp_path):
    _write_contract(tmp_path, _CONTRACT["params"])
    findings = _lint(tmp_path, "mesh_fake.py", (
        "PARAM_PARTITION_RULES = (\n"
        "    ('lstm', r'params/lstm\\d+/.*', None),\n"
        "    ('ghost', r'params/ghost/.*', None),\n"
        ")\n"
        "SHARDING_CONTRACT = 'scripts/shardings_contract.json'\n"
    ), rules=["GL007"])
    messages = " | ".join(f.message for f in findings)
    assert len(findings) == 2
    assert "ghost" in messages and "params/orphan/bias" in messages


def test_gl007_negative_full_coverage(tmp_path):
    _write_contract(tmp_path, ["params/lstm0/kernel", "params/out/bias"])
    findings = _lint(tmp_path, "mesh_fake.py", (
        "PARAM_PARTITION_RULES = (\n"
        "    ('lstm', r'params/lstm\\d+/.*', None),\n"
        "    ('head', r'params/out/.*', None),\n"
        ")\n"
        "SHARDING_CONTRACT = 'scripts/shardings_contract.json'\n"
    ), rules=["GL007"])
    assert findings == []


def test_gl007_missing_contract_is_info_not_gate(tmp_path):
    findings = _lint(tmp_path, "mesh_fake.py", (
        "PARAM_PARTITION_RULES = (('lstm', r'.*', None),)\n"
        "SHARDING_CONTRACT = 'scripts/shardings_contract.json'\n"
    ), rules=["GL007"])
    assert [f.severity for f in findings] == ["info"]


# ---- GL008: TPU-only test imports without slow marker -----------------------

def test_gl008_positive_unmarked_tpu_test(tmp_path):
    findings = _lint(tmp_path, "tests/test_fake_pallas.py", (
        "from jax.experimental.pallas import tpu as pltpu\n"
        "def test_kernel():\n"
        "    pass\n"
    ), rules=["GL008"])
    assert _rules_of(findings) == ["GL008"]


def test_gl008_negative_slow_marked(tmp_path):
    findings = _lint(tmp_path, "tests/test_fake_pallas.py", (
        "import pytest\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "pytestmark = pytest.mark.slow\n"
        "def test_kernel():\n"
        "    pass\n"
    ), rules=["GL008"])
    assert findings == []
    findings = _lint(tmp_path, "tests/test_fake_pallas2.py", (
        "import pytest\n"
        "from jax.experimental.pallas import tpu as pltpu\n"
        "@pytest.mark.slow\n"
        "def test_kernel():\n"
        "    pass\n"
    ), rules=["GL008"])
    assert findings == []


# ---- GL009: silently swallowed broad exceptions -----------------------------

def test_gl009_positive_swallowed_continue(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/ckpt/fake.py", (
            "def restore(candidates):\n"
            "    for c in candidates:\n"
            "        try:\n"
            "            return load(c)\n"
            "        except Exception:\n"
            "            continue\n"
        ), rules=["GL009"],
    )
    assert _rules_of(findings) == ["GL009"]
    assert findings[0].severity == "warning"


def test_gl009_positive_bare_except_pass(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/utils/fake.py", (
            "def close(fh):\n"
            "    try:\n"
            "        fh.close()\n"
            "    except:\n"
            "        pass\n"
        ), rules=["GL009"],
    )
    assert _rules_of(findings) == ["GL009"]


def test_gl009_positive_tuple_containing_exception(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/data/fake.py", (
            "def read(path):\n"
            "    try:\n"
            "        return open(path).read()\n"
            "    except (OSError, Exception):\n"
            "        pass\n"
        ), rules=["GL009"],
    )
    assert _rules_of(findings) == ["GL009"]


def test_gl009_negative_logged_fallback_and_narrow_types(tmp_path):
    # logging before falling back is exactly the prescribed fix
    findings = _lint(
        tmp_path, "cst_captioning_tpu/ckpt/fake.py", (
            "def restore(candidates, log):\n"
            "    for c in candidates:\n"
            "        try:\n"
            "            return load(c)\n"
            "        except Exception as e:\n"
            "            log('ckpt_corrupt', name=c, error=str(e))\n"
            "            continue\n"
        ), rules=["GL009"],
    )
    assert findings == []
    # a narrow exception type is a deliberate contract, even when silent
    findings = _lint(
        tmp_path, "cst_captioning_tpu/data/fake.py", (
            "import queue\n"
            "def drain(q):\n"
            "    try:\n"
            "        q.get_nowait()\n"
            "    except queue.Empty:\n"
            "        pass\n"
        ), rules=["GL009"],
    )
    assert findings == []


def test_gl009_not_applied_outside_package(tmp_path):
    # tests/benches swallow on purpose when asserting failure modes
    findings = _lint(
        tmp_path, "tests/test_fake.py", (
            "def test_x():\n"
            "    try:\n"
            "        boom()\n"
            "    except Exception:\n"
            "        pass\n"
        ), rules=["GL009"],
    )
    assert findings == []


# ---- GL010: ad-hoc timing / bare print in package hot paths -----------------

def test_gl010_positive_time_time_in_package(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake.py", (
            "import time\n"
            "def epoch(step, batches):\n"
            "    t0 = time.time()\n"
            "    for b in batches:\n"
            "        step(b)\n"
            "    return time.time() - t0\n"
        ), rules=["GL010"],
    )
    assert _rules_of(findings) == ["GL010"]
    assert len(findings) == 2 and findings[0].severity == "warning"
    assert "obs.span" in findings[0].message


def test_gl010_positive_bare_print_in_package(tmp_path):
    findings = _lint(
        tmp_path, "cst_captioning_tpu/rl/fake.py", (
            "def score(rows):\n"
            "    print('scored', len(rows))\n"
        ), rules=["GL010"],
    )
    assert _rules_of(findings) == ["GL010"]
    assert "EventLogger" in findings[0].message


def test_gl010_negative_perf_counter_and_obs_span(tmp_path):
    # the prescribed replacements never trip the rule
    findings = _lint(
        tmp_path, "cst_captioning_tpu/train/fake.py", (
            "import time\n"
            "from cst_captioning_tpu import obs\n"
            "def epoch(step, batches):\n"
            "    t0 = time.perf_counter()\n"
            "    with obs.span('xe.epoch'):\n"
            "        for b in batches:\n"
            "            step(b)\n"
            "    obs.event('done', dur=time.perf_counter() - t0)\n"
        ), rules=["GL010"],
    )
    assert findings == []


def test_gl010_not_applied_to_clis_tools_tests(tmp_path):
    # user-facing stdout surfaces and tests print/measure on purpose
    for rel in ("cst_captioning_tpu/cli/fake.py",
                "cst_captioning_tpu/tools/graftlint/fake.py",
                "tests/test_fake.py", "scripts/fake.py", "bench_fake.py"):
        findings = _lint(
            tmp_path, rel, (
                "import time\n"
                "def main():\n"
                "    print(time.time())\n"
            ), rules=["GL010"],
        )
        assert findings == [], rel


# ---- suppressions -----------------------------------------------------------

def test_inline_suppression_same_line(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)  # graftlint: disable=GL001 (fixture)\n"
    ), rules=["GL001"])
    assert findings == []


def test_inline_suppression_next_line(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    # graftlint: disable-next-line=GL001\n"
        "    return np.asarray(x)\n"
    ), rules=["GL001"])
    assert findings == []


def test_suppression_of_other_rule_does_not_hide(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)  # graftlint: disable=GL999\n"
    ), rules=["GL001"])
    assert _rules_of(findings) == ["GL001"]


# ---- baseline round-trip ----------------------------------------------------

def test_baseline_round_trip(tmp_path):
    src = (
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)\n"
    )
    path = tmp_path / "mod.py"
    path.write_text(src)
    first = lint_paths([str(path)], str(tmp_path))
    assert len(first.findings) == 1 and not first.findings[0].baselined

    bl_path = tmp_path / "graftlint.baseline"
    bl = Baseline.from_findings(first.findings)
    bl.save(str(bl_path))
    reloaded = Baseline.load(str(bl_path))

    second = lint_paths([str(path)], str(tmp_path), baseline=reloaded)
    assert len(second.findings) == 1
    assert second.findings[0].baselined
    assert second.gating == []

    # a NEW finding on top of the baselined one still gates
    path.write_text(src + (
        "@jax.jit\n"
        "def step2(x):\n"
        "    return np.asarray(x)\n"
    ))
    third = lint_paths(
        [str(path)], str(tmp_path), baseline=Baseline.load(str(bl_path))
    )
    assert len(third.gating) == 1


def test_baseline_preserves_reasons_on_rewrite(tmp_path):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    result = lint_paths([str(path)], str(tmp_path))
    bl = Baseline.from_findings(result.findings)
    bl.entries[0]["reason"] = "intentional: fixture"
    rewritten = Baseline.from_findings(result.findings, old=bl)
    assert rewritten.entries[0]["reason"] == "intentional: fixture"


# ---- CLI / --json schema ----------------------------------------------------

def test_cli_json_schema(tmp_path, capsys):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    rc = cli_main([str(path), "--root", str(tmp_path), "--json",
                   "--no-baseline"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["version"] == 1 and report["tool"] == "graftlint"
    assert report["files_checked"] == 1
    assert report["counts"]["new"] == 1
    assert report["counts"]["by_rule"] == {"GL001": 1}
    (finding,) = report["findings"]
    assert set(finding) == {
        "rule", "severity", "path", "line", "col", "message", "context",
        "baselined",
    }
    assert finding["rule"] == "GL001" and finding["line"] == 4
    # the two-pass engine's bookkeeping rides along in the report
    assert report["stale_baseline"] == []
    assert report["unused_suppressions"] == []
    timings = report["timings"]
    assert {"index_seconds", "rules_seconds"} <= set(timings)
    assert timings["files"] == 1


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    assert cli_main([str(path), "--root", str(tmp_path),
                     "--write-baseline"]) == 0
    capsys.readouterr()
    assert cli_main([str(path), "--root", str(tmp_path)]) == 0


def test_cli_list_rules_names_all_registered(tmp_path, capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("GL001", "GL002", "GL003", "GL004", "GL005", "GL006",
                "GL007", "GL008", "GL009", "GL010", "GL011", "GL012",
                "GL013", "GL014", "GL015"):
        assert rid in out


def test_rule_registry_has_at_least_seven_rules():
    rules = all_rules()
    assert len(rules) >= 7
    assert all(r.rationale for r in rules.values())


def test_parse_error_is_reported_not_fatal(tmp_path):
    path = tmp_path / "broken.py"
    path.write_text("def oops(:\n")
    result = lint_paths([str(path)], str(tmp_path))
    assert [f.rule for f in result.findings] == ["GL000"]
    assert result.gating  # syntax errors gate


# ---- GL011: scan-carry dtype drift ------------------------------------------

def test_gl011_positive_scan_carry_cast_drift(tmp_path):
    """A scan body that casts the carry to a dtype different from its
    literal init — the stride-carry hazard this rule exists for."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        return (c + x).astype(jnp.bfloat16), x\n"
        "    init = jnp.zeros((4,), jnp.float32)\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert _rules_of(findings) == ["GL011"]
    assert findings[0].severity == "error"
    assert "bfloat16" in findings[0].message and "float32" in findings[0].message


def test_gl011_positive_while_loop_ctor_drift(tmp_path):
    """while_loop body rebuilding the carry in a different dtype than the
    (default-f32) init."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(n):\n"
        "    def body(c):\n"
        "        return jnp.asarray(c + 1, dtype=jnp.int32)\n"
        "    return jax.lax.while_loop(lambda c: c < n, body, jnp.zeros(()))\n"
    ), rules=["GL011"])
    assert _rules_of(findings) == ["GL011"]


def test_gl011_positive_tuple_carry_positional(tmp_path):
    """Tuple carries compare leaf-by-leaf: only the drifting position
    fires, dtype-matching ones stay quiet."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        a, b = c\n"
        "        return (a.astype(jnp.float32), b.astype(jnp.float16)), x\n"
        "    init = (jnp.zeros((2,), jnp.float32), jnp.zeros((2,), jnp.float32))\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert len(findings) == 1 and findings[0].rule == "GL011"
    assert "float16" in findings[0].message


def test_gl011_negative_matching_dtype(tmp_path):
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        return (c + x).astype(jnp.float32), x\n"
        "    init = jnp.zeros((4,), jnp.float32)\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert findings == []


def test_gl011_negative_unknown_dtypes_stay_quiet(tmp_path):
    """No literal dtype on either side -> out of scope, no guessing (the
    repo's tree.map-built carries must never false-positive)."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs, init):\n"
        "    def body(c, x):\n"
        "        return jax.tree.map(jnp.add, c, x), None\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert findings == []


def test_gl011_negative_nested_def_returns_ignored(tmp_path):
    """Returns inside helpers nested in the body are not the body's carry."""
    findings = _lint(tmp_path, "mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def outer(xs):\n"
        "    def body(c, x):\n"
        "        def helper(v):\n"
        "            return v.astype(jnp.bfloat16)\n"
        "        return c + helper(x).astype(jnp.float32), x\n"
        "    init = jnp.zeros((4,), jnp.float32)\n"
        "    return jax.lax.scan(body, init, xs)\n"
    ), rules=["GL011"])
    assert findings == []


# ---- project index: summary cache + provenance fixpoint ---------------------

def test_summary_cache_invalidation(tmp_path):
    """Edit a file (mtime/size change) -> its summary is recomputed; an
    untouched file is served from the on-disk cache."""
    import time as _time

    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    mod = tmp_path / "m.py"
    mod.write_text(
        "import numpy as np\n"
        "def f():\n"
        "    return np.zeros(3)\n"
    )
    cache = tmp_path / "cache.json"
    idx = ProjectIndex.build([str(mod)], str(tmp_path),
                             cache_path=str(cache))
    assert idx.stats.summarized >= 1 and cache.exists()
    assert not idx.functions["m.f"].returns_device

    idx2 = ProjectIndex.build([str(mod)], str(tmp_path),
                              cache_path=str(cache))
    assert idx2.stats.summarized == 0 and idx2.stats.cached >= 1
    assert not idx2.functions["m.f"].returns_device

    mod.write_text(
        "import jax.numpy as jnp\n"
        "def f():\n"
        "    return jnp.zeros(3)\n"
    )
    future = _time.time() + 10
    os.utime(mod, (future, future))
    idx3 = ProjectIndex.build([str(mod)], str(tmp_path),
                              cache_path=str(cache))
    assert idx3.stats.summarized >= 1
    assert idx3.functions["m.f"].returns_device


def test_index_fixpoint_transitive_device_returns(tmp_path):
    """returns-device provenance propagates through the call graph across
    modules (a -> b -> jnp)."""
    from cst_captioning_tpu.tools.graftlint import ProjectIndex

    (tmp_path / "a.py").write_text(
        "import jax.numpy as jnp\n"
        "def leaf(x):\n"
        "    return jnp.tanh(x)\n"
    )
    (tmp_path / "b.py").write_text(
        "from a import leaf\n"
        "def mid(x):\n"
        "    return leaf(x)\n"
        "def top(x):\n"
        "    return mid(x)\n"
    )
    idx = ProjectIndex.build(
        [str(tmp_path / "a.py"), str(tmp_path / "b.py")],
        str(tmp_path), cache_path="",
    )
    assert idx.functions["a.leaf"].returns_device
    assert idx.functions["b.mid"].returns_device
    assert idx.functions["b.top"].returns_device


# ---- --check-stale: dead baseline entries + dead suppressions ---------------

def test_stale_baseline_entries_reported(tmp_path):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return float(x)\n"
    )
    live = lint_paths([str(path)], str(tmp_path), cache_path="")
    bl = Baseline.from_findings(live.findings)
    bl.entries.append({
        "rule": "GL001", "path": "mod.py",
        "context": "return np.asarray(ghost)", "count": 1,
        "reason": "the code site was fixed long ago",
    })
    result = lint_paths([str(path)], str(tmp_path), baseline=bl,
                        cache_path="")
    assert result.gating == []  # the live finding is still covered
    assert [e["context"] for e in result.stale_baseline] == [
        "return np.asarray(ghost)"
    ]


def test_unused_suppressions_reported(tmp_path):
    path = tmp_path / "mod.py"
    path.write_text(
        "import jax\n"
        "import numpy as np\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return np.asarray(x)  # graftlint: disable=GL001 (used)\n"
        "def host(x):\n"
        "    return x  # graftlint: disable=GL003 (nothing ever fires here)\n"
    )
    result = lint_paths([str(path)], str(tmp_path), cache_path="")
    assert [(s["line"], s["rule"]) for s in result.unused_suppressions] == [
        (7, "GL003")
    ]


def test_cli_check_stale_gates(tmp_path, capsys):
    path = tmp_path / "mod.py"
    path.write_text(
        "def f(x):\n"
        "    return x  # graftlint: disable=GL001 (dead)\n"
    )
    (tmp_path / "graftlint.baseline").write_text(json.dumps(
        {"version": 1, "entries": []}
    ))
    assert cli_main([str(path), "--root", str(tmp_path)]) == 0
    capsys.readouterr()
    rc = cli_main([str(path), "--root", str(tmp_path), "--check-stale"])
    err = capsys.readouterr().err
    assert rc == 1 and "unused suppression" in err
    # --check-stale without the full rule set is a usage error
    assert cli_main([str(path), "--root", str(tmp_path), "--check-stale",
                     "--rules", "GL001"]) == 2


def test_cli_timings_and_budget(tmp_path, capsys):
    path = tmp_path / "mod.py"
    path.write_text("def f():\n    return 1\n")
    assert cli_main([str(path), "--root", str(tmp_path), "--timings"]) == 0
    err = capsys.readouterr().err
    assert "index" in err and "rules" in err
    # an absurdly small budget must fail the run
    assert cli_main([str(path), "--root", str(tmp_path),
                     "--budget", "0.000001"]) == 1
    assert "budget" in capsys.readouterr().err


# ---- tier-1 self-check: the repo itself stays lint-clean --------------------

def test_repo_is_graftlint_clean(capsys):
    """The acceptance gate: zero non-baselined findings over the tree."""
    rc = cli_main(REPO_LINT_PATHS + ["--root", REPO])
    out = capsys.readouterr()
    assert rc == 0, f"graftlint found new findings:\n{out.out}"


def test_sharding_contract_matches_model():
    """scripts/check_shardings.py default mode: contract + coverage OK."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import check_shardings
    finally:
        sys.path.pop(0)
    assert check_shardings.main([]) == 0


# ---- satellite: drivers import side-effect-free under JAX_PLATFORMS=cpu -----

def test_scripts_import_without_backend_init():
    """bench.py / verify_parity.py (and friends) must import without
    initializing a JAX backend — graftlint's AST pass must stay the only
    analysis that needs to read them."""
    code = (
        "import sys\n"
        f"sys.path.insert(0, {os.path.join(REPO, 'scripts')!r})\n"
        "import bench, bench_attention, bench_recipe\n"
        "import verify_parity, check_shardings\n"
        "import jax\n"
        "try:\n"
        "    backends = jax._src.xla_bridge._backends\n"
        "except AttributeError:\n"
        "    backends = None\n"
        "assert not backends, 'importing the drivers initialized a backend'\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr


# ---- GL012: collective-axis-name typos --------------------------------------

def test_gl012_positive_psum_axis_typo(tmp_path):
    """A misspelled mesh axis in a collective is the exact hazard: an
    unbound-axis trace error (or wrong-axis reduction) deep inside
    shard_map."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'dta')\n"
    ), rules=["GL012"])
    assert _rules_of(findings) == ["GL012"]
    assert findings[0].severity == "error"
    assert "'dta'" in findings[0].message


def test_gl012_positive_axis_name_kwarg_and_tuple(tmp_path):
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    a = jax.lax.pmean(x, axis_name='sequ')\n"
        "    b = jax.lax.psum(x, ('data', 'seqq'))\n"
        "    return a, b\n"
    ), rules=["GL012"])
    assert len(findings) == 2
    assert all(f.rule == "GL012" for f in findings)


def test_gl012_negative_declared_axes_and_dynamic_names(tmp_path):
    """Axes declared by train/mesh.py pass; dynamic axis expressions are
    out of scope (not statically checkable)."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x, axis):\n"
        "    a = jax.lax.psum(x, 'data')\n"
        "    b = jax.lax.pmean(x, 'seq')\n"
        "    c = jax.lax.axis_index('data')\n"
        "    d = jax.lax.psum(x, axis)\n"
        "    return a, b, c, d\n"
    ), rules=["GL012"])
    assert findings == []


def test_gl012_axes_extracted_from_mesh_py(tmp_path):
    """The allowed set comes from the *axis-parameter defaults declared by
    train/mesh.py under the lint root, not a hardcoded list."""
    mesh = tmp_path / "cst_captioning_tpu" / "train" / "mesh.py"
    mesh.parent.mkdir(parents=True, exist_ok=True)
    mesh.write_text(
        "def make_mesh(num_devices=0, axis='model', seq_axis='pipeline'):\n"
        "    pass\n"
    )
    good = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'model')\n"
    ), rules=["GL012"])
    assert good == []
    bad = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'data')\n"  # not declared by THIS mesh.py
    ), rules=["GL012"])
    assert _rules_of(bad) == ["GL012"]


def test_gl012_negative_tests_out_of_scope(tmp_path):
    findings = _lint(tmp_path, "tests/test_mod.py", (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'i')\n"
    ), rules=["GL012"])
    assert findings == []


def test_gl012_mesh_axes_rescrape_within_one_process(tmp_path):
    """The stale-cache fix: editing train/mesh.py between two lint runs in
    the SAME process must change the allowed axis set (the scrape lives on
    the per-run project index now, not a module-level cache)."""
    import time as _time

    mesh = tmp_path / "cst_captioning_tpu" / "train" / "mesh.py"
    mesh.parent.mkdir(parents=True, exist_ok=True)
    mesh.write_text("def make_mesh(num_devices=0, axis='alpha'):\n    pass\n")
    src = (
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'alpha')\n"
    )
    assert _lint(tmp_path, "cst_captioning_tpu/mod.py", src,
                 rules=["GL012"]) == []
    mesh.write_text("def make_mesh(num_devices=0, axis='beta'):\n    pass\n")
    future = _time.time() + 10
    os.utime(mesh, (future, future))
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", src,
                     rules=["GL012"])
    assert _rules_of(findings) == ["GL012"] and "'alpha'" in findings[0].message


# ---- GL013: implicit host transfers (interprocedural) -----------------------

def test_gl013_cross_file_device_provenance():
    """The acceptance pair: np.asarray / .tolist() on values whose device
    provenance is declared in ANOTHER module (traced-fn result, device-
    yielding prefetch generator); the suppressed twin stays quiet."""
    findings = _lint_fixture("gl013", ["GL013"])
    assert len(findings) == 2
    assert all(f.rule == "GL013" and f.path.endswith("consumer.py")
               for f in findings)
    by_ctx = {f.context: f for f in findings}
    asarray = next(f for c, f in by_ctx.items() if "np.asarray(tokens)" in c)
    tolist = next(f for c, f in by_ctx.items() if ".tolist()" in c)
    # the finding message carries the interprocedural path
    assert "cst_captioning_tpu.producer.decode" in asarray.message
    assert "jit-traced" in asarray.message
    assert "cst_captioning_tpu.producer.prefetched" in tolist.message


def test_gl013_single_file_engine_provably_cannot():
    """Linting the consumer ALONE must find nothing: the provenance facts
    live in producer.py, out of any per-file engine's reach."""
    assert _lint_fixture(
        "gl013", ["GL013"], only="cst_captioning_tpu/consumer.py"
    ) == []


def test_gl013_branch_sensitive_no_false_positive(tmp_path):
    """A host rebinding in one branch must not inherit the other branch's
    device provenance (the real scst.py seam pattern)."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def seam(samples, mesh):\n"
        "    if mesh is not None:\n"
        "        samples = jax.device_put(samples)\n"
        "    else:\n"
        "        samples = np.asarray(samples)\n"
        "    return np.asarray(samples)\n"
    ), rules=["GL013"])
    assert findings == []


def test_gl013_local_device_provenance_and_explicit_readback(tmp_path):
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def bad(x):\n"
        "    y = jnp.tanh(x)\n"
        "    return np.asarray(y)\n"
        "def good(x):\n"
        "    y = jnp.tanh(x)\n"
        "    return np.asarray(jax.device_get(y))\n"
    ), rules=["GL013"])
    assert len(findings) == 1 and findings[0].line == 6


def test_gl013_not_applied_outside_package(tmp_path):
    # benches/tests/scripts read back on purpose
    findings = _lint(tmp_path, "tests/helper.py", (
        "import jax.numpy as jnp\n"
        "import numpy as np\n"
        "def f(x):\n"
        "    return np.asarray(jnp.tanh(x))\n"
    ), rules=["GL013"])
    assert findings == []


# ---- GL014: cross-function PRNG key reuse -----------------------------------

def test_gl014_cross_file_key_reuse():
    """The acceptance pair: a key spent by a callee (directly, and through
    one extra call hop) then reused by the caller; split/fold_in and the
    suppressed twin stay quiet."""
    findings = _lint_fixture("gl014", ["GL014"])
    assert len(findings) == 2
    assert all(f.rule == "GL014" and f.path.endswith("caller.py")
               for f in findings)
    direct, transitive = findings
    assert "cst_captioning_tpu.keys_lib.sample_rollout" in direct.message
    assert "jax.random.normal" in direct.message
    assert "cst_captioning_tpu.keys_lib.wrapped" in transitive.message


def test_gl014_single_file_engine_provably_cannot():
    assert _lint_fixture(
        "gl014", ["GL014"], only="cst_captioning_tpu/caller.py"
    ) == []


def test_gl014_local_reuse_stays_gl002(tmp_path):
    """Pure same-function double consumption belongs to GL002 — GL014 only
    owns pairs involving a callee, so the two never double-report."""
    src = (
        "import jax\n"
        "def f(key):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.uniform(key, (2,))\n"
        "    return a + b\n"
    )
    assert _lint(tmp_path, "mod.py", src, rules=["GL014"]) == []
    assert _rules_of(_lint(tmp_path, "mod.py", src, rules=["GL002"])) == [
        "GL002"
    ]


def test_gl014_not_applied_in_tests(tmp_path):
    findings = _lint(tmp_path, "tests/test_fake.py", (
        "import jax\n"
        "def consume(k):\n"
        "    return jax.random.normal(k, (2,))\n"
        "def test_reuse(key):\n"
        "    a = consume(key)\n"
        "    b = jax.random.uniform(key, (2,))\n"
        "    assert (a != b).any()\n"
    ), rules=["GL014"])
    assert findings == []


# ---- GL015: sharding-spec drift ---------------------------------------------

def test_gl015_cross_file_axis_drift():
    """The acceptance pair: a PartitionSpec literal checked against axes
    declared in the OTHER module (train/mesh.py); declared axes, dynamic
    specs, and the suppressed twin stay quiet."""
    findings = _lint_fixture("gl015", ["GL015"])
    assert len(findings) == 1
    (f,) = findings
    assert f.rule == "GL015" and f.path.endswith("shard_use.py")
    assert "'data'" in f.message
    # the allowed set names the axes that only mesh.py declares
    assert "model" in f.message and "pipeline" in f.message


def test_gl015_repo_axes_pass(tmp_path):
    """With no fixture mesh the default data/seq axes apply — the repo's
    own spec literals must lint clean under them."""
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "from jax.sharding import PartitionSpec as P\n"
        "def f():\n"
        "    return P('data', 'seq'), P(None), P(('data', 'seq'))\n"
    ), rules=["GL015"])
    assert findings == []
    findings = _lint(tmp_path, "cst_captioning_tpu/mod.py", (
        "from jax.sharding import PartitionSpec as P\n"
        "def f():\n"
        "    return P('model')\n"
    ), rules=["GL015"])
    assert _rules_of(findings) == ["GL015"]


def test_gl015_not_applied_in_tests(tmp_path):
    findings = _lint(tmp_path, "tests/test_mod.py", (
        "from jax.sharding import PartitionSpec as P\n"
        "S = P('i')\n"
    ), rules=["GL015"])
    assert findings == []
