"""Gradient-communication parity suite (parallel/comms.py).

Pins the ISSUE-11 acceptance contract on the 8 fake CPU devices:

- bucketed f32 allreduce is BIT-identical to the per-leaf psum spelling
  (psum is elementwise — coalescing cannot change a single bit), and the
  default ``CommConfig()`` path through the real step/update factories is
  bit-identical to the pre-PR ``comm=None`` spelling;
- bf16-on-the-wire stays within the pinned tolerance per reduction, and
  the f32 master accumulation keeps the drift bounded over 50 synthetic
  optimizer steps (rounding must not compound in the state);
- the overlapped ("defer") chunked update is bit-identical to its eager
  per-chunk-reduce reference spelling;
- the bucket planner orders by param family, respects the size target,
  and the config layer rejects the nonsense combinations at build time.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cst_captioning_tpu.compat import shard_map
from cst_captioning_tpu.config.config import (
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    RLConfig,
    TrainConfig,
)
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.parallel.comms import (
    CommConfig,
    ledger,
    per_leaf_f32_bytes,
    plan_buckets,
    reduce_tree,
)
from cst_captioning_tpu.rl import make_parallel_rl_update
from cst_captioning_tpu.train import (
    create_train_state,
    make_mesh,
    make_optimizer,
    make_parallel_xe_step,
    replicate,
    shard_batch,
)

V = 17


def _param_like_tree(rng):
    """A params-shaped pytree whose paths hit the PARAM_PARTITION_RULES
    families (flatten order is alphabetical, deliberately != family order)."""
    shape = {
        "params": {
            "cell": {
                "out_proj": {"kernel": (24, V), "bias": (V,)},
                "word_embed": {"embedding": (V, 24)},
            },
            "encoder": {"embed_resnet": {"kernel": (8, 24), "bias": (24,)}},
            "init_h0": {"kernel": (24, 24)},
        }
    }
    return jax.tree.map(
        lambda s: jnp.asarray(rng.normal(size=s), jnp.float32),
        shape,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def _reduce_on_mesh(tree, comm):
    mesh = make_mesh()
    fn = shard_map(
        lambda t: reduce_tree(t, "data", comm),
        mesh=mesh, in_specs=(P(),), out_specs=P(),
    )
    return jax.jit(fn)(tree)


# ---- planner (host-side) ----------------------------------------------------


def test_plan_buckets_family_order_and_size_target():
    tree = {
        "params": {
            "cell": {"word_embed": {"embedding": jax.ShapeDtypeStruct((4000, 32), jnp.float32)}},
            "encoder": {"embed_resnet": {"kernel": jax.ShapeDtypeStruct((64, 32), jnp.float32)}},
            "stray": jax.ShapeDtypeStruct((7,), jnp.float32),
        }
    }
    plan = plan_buckets(tree, CommConfig(bucket_mb=0.25))
    leaves_paths = [
        "params/cell/word_embed/embedding",   # flatten index 0
        "params/encoder/embed_resnet/kernel", # flatten index 1
        "params/stray",                       # flatten index 2
    ]
    order = [i for b in plan.buckets for i in b.indices]
    # family order: encoder_embed (rank 0) first, word_embed next, the
    # rule-less stray leaf last
    assert [leaves_paths[i] for i in order] == [
        "params/encoder/embed_resnet/kernel",
        "params/cell/word_embed/embedding",
        "params/stray",
    ]
    target = int(0.25 * (1 << 20))
    for b in plan.buckets:
        # a bucket only exceeds the target when a single leaf does
        assert b.bytes_on_wire <= target or len(b.indices) == 1
    # the 512 KB embedding exceeds the 256 KB target -> its own bucket
    [emb_bucket] = [b for b in plan.buckets if 0 in b.indices]
    assert emb_bucket.indices == (0,)
    assert plan.bytes_on_wire == per_leaf_f32_bytes(tree)


def test_plan_buckets_coalesces_small_leaves():
    tree = {f"params/x{i:02d}": jax.ShapeDtypeStruct((10,), jnp.float32)
            for i in range(12)}
    plan = plan_buckets(tree, CommConfig(bucket_mb=4.0))
    assert len(plan.buckets) == 1
    assert plan.buckets[0].bytes_on_wire == 12 * 10 * 4


def test_plan_buckets_zero_mb_is_per_leaf():
    tree = {"a": jax.ShapeDtypeStruct((5,), jnp.float32),
            "b": jax.ShapeDtypeStruct((6,), jnp.float32)}
    plan = plan_buckets(tree, CommConfig(bucket_mb=0.0))
    assert len(plan.buckets) == 2


def test_ledger_bf16_halves_wire_bytes():
    rng = np.random.default_rng(0)
    tree = _param_like_tree(rng)
    base = ledger(tree, None)
    bf16 = ledger(tree, CommConfig(dtype="bf16"))
    assert base["bytes_on_wire_per_update"] == per_leaf_f32_bytes(tree)
    assert base["bytes_on_wire_per_update"] == \
        2 * bf16["bytes_on_wire_per_update"]
    assert bf16["messages_per_update"] < base["messages_per_update"]


# ---- reduction parity on the 8-device mesh ----------------------------------


def test_bucketed_f32_bitexact_vs_per_leaf():
    rng = np.random.default_rng(1)
    tree = _param_like_tree(rng)
    ref = _reduce_on_mesh(tree, None)
    for mb in (4.0, 0.001, 0.0):
        got = _reduce_on_mesh(tree, CommConfig(bucket_mb=mb))
        for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_bf16_wire_within_tolerance():
    rng = np.random.default_rng(2)
    tree = _param_like_tree(rng)
    ref = _reduce_on_mesh(tree, None)
    got = _reduce_on_mesh(tree, CommConfig(dtype="bf16"))
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        a, b = np.asarray(a), np.asarray(b)
        assert b.dtype == a.dtype  # cast back to the leaf dtype
        # bf16 keeps 8 mantissa bits: relative error per element ~2^-8
        np.testing.assert_allclose(b, a, rtol=1.2e-2, atol=1e-6)


def test_bf16_master_accumulation_drift_bounded():
    """50 synthetic SGD steps with bf16-on-the-wire gradients against the
    f32 reference: params (the f32 master copy) must drift only by the
    accumulated per-step rounding, not compound — the pinned bound is ~10x
    the random-walk estimate sqrt(50) * 2^-8 * lr."""
    rng = np.random.default_rng(3)
    params = _param_like_tree(rng)
    lr = 0.01
    comm_bf = CommConfig(dtype="bf16")

    def run(comm):
        p = params
        for step in range(50):
            g = jax.tree.map(
                lambda x: jnp.asarray(
                    np.random.default_rng(step).normal(size=x.shape),
                    jnp.float32,
                ),
                p,
            )
            g = _reduce_on_mesh(g, comm)
            p = jax.tree.map(lambda x, gg: x - lr * gg, p, g)
        return p

    p_ref, p_bf = run(None), run(comm_bf)
    for a, b in zip(jax.tree.leaves(p_ref), jax.tree.leaves(p_bf)):
        # grads are psum'd over 8 devices (|g| ~ 8): per-step wire rounding
        # is ~8 * 2^-8, scaled by lr; 50 steps of it stays ~1e-2, far from
        # the O(1) error a compounding (bf16 state) bug would show
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-2, rtol=0
        )


# ---- the real factories: default-path bit-identity + overlap parity ---------


@pytest.fixture(scope="module")
def model_setup():
    B, F, T = 8, 3, 5
    cfg = ModelConfig(
        vocab_size=V,
        modalities=(("resnet", 6),),
        d_embed=12,
        d_hidden=12,
        d_att=6,
        encoder="meanpool",
        dropout=0.0,
        max_len=T,
        max_frames=F,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 6)), jnp.float32)}
    masks = {"resnet": jnp.ones((B, F), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)
    tx = make_optimizer(TrainConfig(lr=5e-2, grad_clip=5.0), 10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=1)
    return model, state, feats, masks, labels


def _rl_args(mesh, state, feats, masks, K=4, B=8, T=5, seed=5):
    rng = np.random.default_rng(seed)
    samples = jnp.asarray(rng.integers(2, V, size=(K, B, T)), jnp.int32)
    adv = jnp.asarray(rng.normal(size=(K, B)), jnp.float32)
    valid = jnp.ones((B,), jnp.float32)
    kb = jax.sharding.NamedSharding(mesh, P(None, "data"))
    return (
        replicate(mesh, state),
        *shard_batch(mesh, (feats, masks)),
        jax.device_put(samples, kb),
        jax.device_put(adv, kb),
        shard_batch(mesh, valid),
    )


def _assert_trees_bitequal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_default_comm_bit_identical_rl_update(model_setup):
    """Acceptance pin: the default config path (f32, no overlap) through
    the parallel RL update is BIT-identical to the pre-PR per-leaf psum
    spelling (comm=None IS that spelling, kept callable)."""
    model, state, feats, masks, _ = model_setup
    mesh = make_mesh()
    args = _rl_args(mesh, state, feats, masks)
    s0, m0 = make_parallel_rl_update(model, mesh, comm=None)(*args)
    s1, m1 = make_parallel_rl_update(model, mesh, comm=CommConfig())(*args)
    assert float(m0["rl_loss"]) == float(m1["rl_loss"])
    _assert_trees_bitequal(s0.params, s1.params)
    _assert_trees_bitequal(s0.opt_state, s1.opt_state)


def test_default_comm_bit_identical_xe_step(model_setup):
    model, state, feats, masks, labels = model_setup
    B, T = labels.shape
    mesh = make_mesh()
    batch = (feats, masks, labels, jnp.ones((B, T), jnp.float32),
             jnp.ones((B,), jnp.float32))
    args = (replicate(mesh, state), *shard_batch(mesh, batch))
    s0, m0 = make_parallel_xe_step(model, mesh, comm=None)(*args)
    s1, m1 = make_parallel_xe_step(model, mesh, comm=CommConfig())(*args)
    assert float(m0["loss"]) == float(m1["loss"])
    _assert_trees_bitequal(s0.params, s1.params)


def test_bf16_rl_update_within_tolerance(model_setup):
    model, state, feats, masks, _ = model_setup
    mesh = make_mesh()
    args = _rl_args(mesh, state, feats, masks)
    s0, m0 = make_parallel_rl_update(model, mesh, comm=None)(*args)
    s1, m1 = make_parallel_rl_update(
        model, mesh, comm=CommConfig(dtype="bf16")
    )(*args)
    np.testing.assert_allclose(
        float(m0["rl_loss"]), float(m1["rl_loss"]), rtol=1e-6
    )  # the loss never rides the wire — only grads are compressed
    for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s1.params)):
        # one Adam step from identical state: bf16 grad noise moves the
        # update by ~2^-8 of its magnitude (lr 5e-2), nowhere near the
        # O(lr) displacement a broken accumulation would produce
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-3, rtol=0
        )


def test_overlap_defer_bitexact_vs_eager(model_setup):
    """The production overlap ("defer", double-buffered carry) must be
    bit-identical to the eager per-chunk-reduce spelling — same float
    order, the buffer only changes WHEN each psum is issued."""
    model, state, feats, masks, _ = model_setup
    mesh = make_mesh()
    args = _rl_args(mesh, state, feats, masks)
    outs = {}
    for mode in ("eager", "defer"):
        outs[mode] = make_parallel_rl_update(
            model, mesh, chunks=2, comm=CommConfig(overlap=mode)
        )(*args)
    s_e, m_e = outs["eager"]
    s_d, m_d = outs["defer"]
    assert float(m_e["rl_loss"]) == float(m_d["rl_loss"])
    _assert_trees_bitequal(s_e.params, s_d.params)
    _assert_trees_bitequal(s_e.opt_state, s_d.opt_state)


def test_overlap_close_to_unoverlapped(model_setup):
    """Overlap reduces per chunk instead of accumulate-then-reduce: a
    different float summation order, so parity is tolerance-graded (the
    bit-exact pin for overlap is defer-vs-eager above)."""
    model, state, feats, masks, _ = model_setup
    mesh = make_mesh()
    args = _rl_args(mesh, state, feats, masks)
    s0, m0 = make_parallel_rl_update(
        model, mesh, chunks=2, comm=CommConfig()
    )(*args)
    s1, m1 = make_parallel_rl_update(
        model, mesh, chunks=2, comm=CommConfig(overlap="defer")
    )(*args)
    np.testing.assert_allclose(
        float(m0["rl_loss"]), float(m1["rl_loss"]), rtol=1e-6
    )
    for a, b in zip(jax.tree.leaves(s0.params), jax.tree.leaves(s1.params)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5
        )


# ---- construction-time rejection of nonsense combinations -------------------


def test_comm_config_validation():
    with pytest.raises(ValueError, match="comm dtype"):
        CommConfig(dtype="f16")
    with pytest.raises(ValueError, match="overlap"):
        CommConfig(overlap="async")
    with pytest.raises(ValueError, match="bucket_mb"):
        CommConfig(bucket_mb=-1.0)


def test_train_config_validates_comm_knobs():
    with pytest.raises(ValueError, match="comm_dtype"):
        TrainConfig(comm_dtype="f16")
    with pytest.raises(ValueError, match="comm_bucket_mb"):
        TrainConfig(comm_bucket_mb=-2.0)


def test_experiment_config_overlap_needs_chunks():
    with pytest.raises(ValueError, match="update_chunks"):
        ExperimentConfig(train=TrainConfig(comm_overlap=True))
    ExperimentConfig(
        train=TrainConfig(comm_overlap=True),
        rl=RLConfig(update_chunks=5),
    )  # chunks >= 2: fine (5 divides the default num_rollouts=5)


def test_experiment_config_rejects_comm_on_seq_parallel():
    with pytest.raises(ValueError, match="sequence-parallel"):
        ExperimentConfig(
            train=TrainConfig(comm_dtype="bf16"),
            mesh=MeshConfig(seq_devices=2),
        )


def test_factory_rejects_overlap_without_chunks(model_setup):
    model, *_ = model_setup
    with pytest.raises(ValueError, match="chunks"):
        make_parallel_rl_update(
            model, make_mesh(), chunks=1, comm=CommConfig(overlap="defer")
        )


def test_from_train_maps_knobs():
    t = TrainConfig(comm_bucket_mb=2.5, comm_dtype="bf16", comm_overlap=True)
    c = CommConfig.from_train(t)
    assert (c.bucket_mb, c.dtype, c.overlap) == (2.5, "bf16", "defer")
    assert CommConfig.from_train(TrainConfig()).overlap == "off"
