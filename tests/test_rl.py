"""RL tests: consensus rewards, SCB baseline, SCST learning on a rigged reward."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config.config import EOS_ID, ModelConfig, RLConfig, TrainConfig
from cst_captioning_tpu.data.vocab import Vocab
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.rl import (
    RewardComputer,
    SCSTTrainer,
    make_parallel_rl_update,
    make_rl_update,
    scb_baseline,
)
from cst_captioning_tpu.train import create_train_state, make_mesh, make_optimizer, replicate, shard_batch

V = 14
WORDS = [f"w{i}" for i in range(V - 4)]


def make_vocab():
    return Vocab.from_corpus_words(WORDS)


def test_reward_computer_prefers_matching_captions():
    vocab = make_vocab()
    gts = {"v0": ["w0 w1 w2", "w0 w1 w3"], "v1": ["w5 w6", "w5 w6 w7"]}
    rc = RewardComputer(vocab, gts)
    rows = np.asarray(
        [
            vocab.encode("w0 w1 w2".split()) + [EOS_ID],
            vocab.encode("w5 w6 w7".split()) + [EOS_ID],
        ],
        np.int32,
    )
    r = rc(["v0", "v1"], rows)
    assert r.shape == (2,) and (r > 0).all()
    # swapping hyps across videos must tank the reward
    r_swapped = rc(["v1", "v0"], rows)
    assert r_swapped[0] < r[0] and r_swapped[1] < r[1]


def test_reward_computer_rollout_major_cycling():
    vocab = make_vocab()
    gts = {"v0": ["w0 w1"], "v1": ["w5 w6"]}
    rc = RewardComputer(vocab, gts)
    row_v0 = vocab.encode(["w0", "w1"]) + [EOS_ID]
    row_v1 = vocab.encode(["w5", "w6"]) + [EOS_ID]
    # K=2 rollouts, B=2: rows [r0v0, r0v1, r1v0, r1v1]
    rows = np.asarray([row_v0, row_v1, row_v0, row_v1], np.int32)
    r = rc(["v0", "v1"], rows)
    assert r[0] == pytest.approx(r[2]) and r[1] == pytest.approx(r[3])
    assert (r > 0).all()


def test_reward_computer_bleu_mix_changes_scores():
    vocab = make_vocab()
    gts = {"v0": ["w0 w1 w2 w3 w4"]}
    rc_c = RewardComputer(vocab, gts, cider_weight=1.0, bleu_weight=0.0)
    rc_m = RewardComputer(vocab, gts, cider_weight=1.0, bleu_weight=0.5)
    row = np.asarray([vocab.encode("w0 w1 w2 w3 w4".split()) + [EOS_ID]], np.int32)
    assert rc_m(["v0"], row)[0] > rc_c(["v0"], row)[0]


def test_reward_empty_hypothesis_is_zero():
    vocab = make_vocab()
    rc = RewardComputer(vocab, {"v0": ["w0 w1"]})
    r = rc(["v0"], np.zeros((1, 5), np.int32))  # all PAD
    assert r[0] == 0.0


def test_scb_baseline_leave_one_out():
    r = np.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])  # [K=3, B=2]
    b = scb_baseline(r)
    np.testing.assert_allclose(b[0], [(3 + 5) / 2, (4 + 6) / 2])
    np.testing.assert_allclose(b[1], [(1 + 5) / 2, (2 + 6) / 2])
    # K=1 -> zero baseline
    np.testing.assert_allclose(scb_baseline(np.ones((1, 4))), 0.0)


@pytest.fixture(scope="module")
def model_setup():
    B, F, T = 8, 3, 5
    cfg = ModelConfig(
        vocab_size=V,
        modalities=(("resnet", 6),),
        d_embed=12,
        d_hidden=12,
        d_att=6,
        encoder="meanpool",
        dropout=0.0,
        max_len=T,
        max_frames=F,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 6)), jnp.float32)}
    masks = {"resnet": jnp.ones((B, F), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)
    tx = make_optimizer(TrainConfig(lr=5e-2, grad_clip=5.0), 10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=1)
    return model, state, feats, masks


class TokenReward:
    """Rigged reward: +1 per occurrence of a target token (RewardComputer API)."""

    def __init__(self, target: int):
        self.target = target

    def __call__(self, video_ids, rows):
        return (np.asarray(rows) == self.target).sum(axis=1).astype(np.float32)


@pytest.mark.parametrize("baseline", ["greedy", "scb", "none"])
def test_scst_learns_rigged_reward(model_setup, baseline):
    """A few SCST steps must raise the frequency of the rewarded token."""
    model, state, feats, masks = model_setup
    cfg = RLConfig(enabled=True, num_rollouts=4, baseline=baseline, temperature=1.0)
    trainer = SCSTTrainer(model, TokenReward(target=7), cfg)
    vids = [f"v{i}" for i in range(8)]
    rng = jax.random.key(0)
    rewards = []
    for i in range(15):
        rng, step_rng = jax.random.split(rng)
        state, m = trainer.train_step(state, feats, masks, vids, step_rng)
        rewards.append(m["reward_mean"])
    assert rewards[-1] > rewards[0] + 0.5, f"{baseline}: {rewards[0]:.2f}->{rewards[-1]:.2f}"


def test_parallel_rl_update_matches_single(model_setup):
    model, state, feats, masks = model_setup
    mesh = make_mesh()
    K, B, T = 3, 8, 5
    rng = np.random.default_rng(3)
    samples = jnp.asarray(rng.integers(2, V, size=(K, B, T)), jnp.int32)
    adv = jnp.asarray(rng.normal(size=(K, B)), jnp.float32)

    valid = jnp.ones((B,), jnp.float32)
    s_state, s_m = make_rl_update(model)(state, feats, masks, samples, adv, valid)
    p_state, p_m = make_parallel_rl_update(model, mesh)(
        replicate(mesh, state),
        *shard_batch(mesh, (feats, masks)),
        jax.device_put(samples, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(None, "data"))),
        jax.device_put(adv, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec(None, "data"))),
        shard_batch(mesh, valid),
    )
    np.testing.assert_allclose(float(s_m["rl_loss"]), float(p_m["rl_loss"]), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(s_state.params),
        jax.tree_util.tree_leaves(p_state.params),
    ):
        # lr=5e-2 + Adam rsqrt amplifies psum float reassociation; a real
        # normalization bug would be O(1) off, so 1e-2 still discriminates
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-2, atol=2e-3)


@pytest.mark.parametrize("chunks", [3, 1])
def test_chunked_rl_update_matches_fused(model_setup, chunks):
    """Gradient accumulation over the rollout axis (rl.update_chunks — the
    HBM headroom lever, VERDICT r2 next #3) produces the same loss and
    post-update params as the fused update, single-device AND sharded."""
    model, state, feats, masks = model_setup
    K, B, T = 3, 8, 5
    rng = np.random.default_rng(4)
    samples = jnp.asarray(rng.integers(2, V, size=(K, B, T)), jnp.int32)
    adv = jnp.asarray(rng.normal(size=(K, B)), jnp.float32)
    valid = jnp.asarray([1, 1, 1, 1, 1, 1, 0, 0], jnp.float32)

    f_state, f_m = make_rl_update(model)(state, feats, masks, samples, adv, valid)
    c_state, c_m = make_rl_update(model, chunks=chunks)(
        state, feats, masks, samples, adv, valid
    )
    np.testing.assert_allclose(
        float(f_m["rl_loss"]), float(c_m["rl_loss"]), rtol=1e-6
    )
    for a, b in zip(
        jax.tree_util.tree_leaves(f_state.params),
        jax.tree_util.tree_leaves(c_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
        )

    if chunks > 1:
        mesh = make_mesh()
        sp = jax.sharding.PartitionSpec
        kb = jax.sharding.NamedSharding(mesh, sp(None, "data"))
        p_state, p_m = make_parallel_rl_update(model, mesh, chunks=chunks)(
            replicate(mesh, state),
            *shard_batch(mesh, (feats, masks)),
            jax.device_put(samples, kb),
            jax.device_put(adv, kb),
            shard_batch(mesh, valid),
        )
        np.testing.assert_allclose(
            float(f_m["rl_loss"]), float(p_m["rl_loss"]), rtol=1e-5
        )
        for a, b in zip(
            jax.tree_util.tree_leaves(f_state.params),
            jax.tree_util.tree_leaves(p_state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-2, atol=2e-3
            )

    with pytest.raises(ValueError, match="must divide"):
        make_rl_update(model, chunks=2)(state, feats, masks, samples, adv, valid)


def test_train_step_zero_weights_invalid_rows(model_setup):
    """Wrap-padded rows (valid=False) must not change the update."""
    model, state, feats, masks = model_setup
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="none")
    trainer = SCSTTrainer(model, TokenReward(target=7), cfg)
    vids = [f"v{i}" for i in range(8)]
    rng = jax.random.key(5)
    valid = np.asarray([1, 1, 1, 1, 0, 0, 0, 0], np.float32)
    s1, m1 = trainer.train_step(state, feats, masks, vids, rng, valid=valid)
    # metrics only reflect valid rows
    rows_r = TokenReward(7)(vids, np.zeros((16, 5)))
    assert np.isfinite(m1["reward_mean"])
    # gradient from invalid rows is excluded: corrupting their features
    # must not change the resulting params
    feats2 = {k: v.at[4:].set(99.0) for k, v in feats.items()}
    s2, m2 = trainer.train_step(state, feats2, masks, vids, rng, valid=valid)
    for a, b in zip(
        jax.tree_util.tree_leaves(s1.params), jax.tree_util.tree_leaves(s2.params)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def _reward_computer(vocab, gts, native: bool, **kw) -> RewardComputer:
    """Build a RewardComputer pinned to one scoring path.

    ``native=True`` REQUIRES the C++ kernel: if it failed to load the parity
    test would silently compare Python against itself, so skip loudly instead
    (VERDICT r1 weak #4).
    """
    rc = RewardComputer(vocab, gts, use_native=native, **kw)
    if native and rc._native is not True:
        pytest.skip("C++ creward kernel unavailable (no g++?): native parity "
                    "path cannot be exercised")
    if not native:
        assert rc._native is None
    return rc


@pytest.mark.parametrize("native", [False, True], ids=["python", "native"])
def test_fast_reward_matches_cider_oracle(native):
    """Cached-ref reward path must reproduce metrics.cider.CiderD exactly."""
    from cst_captioning_tpu.metrics.cider import CiderD, CorpusDF

    rng = np.random.default_rng(0)
    vocab = make_vocab()
    vids = [f"v{i}" for i in range(6)]
    gts = {
        v: [
            " ".join(rng.choice(WORDS, size=rng.integers(3, 9)))
            for _ in range(4)
        ]
        for v in vids
    }
    refs = {v: [c.split() for c in caps] for v, caps in gts.items()}
    df = CorpusDF.from_refs(list(refs.values()))
    rc = _reward_computer(vocab, gts, native, df=df, cider_weight=1.0,
                          bleu_weight=0.0)

    rows = np.asarray(
        [
            vocab.encode(list(rng.choice(WORDS, size=rng.integers(2, 8)))) + [EOS_ID]
            + [0] * 10
            for _ in range(12)
        ][0:12],
        dtype=object,
    )
    rows = np.stack([np.asarray((list(r) + [0] * 12)[:12], np.int32) for r in rows])
    got = rc(vids, rows)

    oracle = CiderD(df=df)
    hyps = [vocab.decode(r).split() for r in rows]
    o_gts = {str(i): refs[vids[i % 6]] for i in range(12)}
    o_res = {str(i): [hyps[i]] for i in range(12)}
    _, want = oracle.compute_score(o_gts, o_res)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-8)


@pytest.mark.parametrize("native", [False, True], ids=["python", "native"])
def test_fast_reward_matches_bleu_oracle(native):
    from cst_captioning_tpu.metrics.bleu import Bleu
    from cst_captioning_tpu.metrics.cider import CorpusDF

    rng = np.random.default_rng(1)
    vocab = make_vocab()
    vids = ["a", "b"]
    gts = {
        v: [" ".join(rng.choice(WORDS, size=rng.integers(4, 9))) for _ in range(3)]
        for v in vids
    }
    refs = {v: [c.split() for c in caps] for v, caps in gts.items()}
    df = CorpusDF.from_refs(list(refs.values()))
    rc_mixed = _reward_computer(vocab, gts, native, df=df, cider_weight=0.0,
                                bleu_weight=1.0)
    rows = np.stack(
        [
            np.asarray(
                (vocab.encode(list(rng.choice(WORDS, size=6))) + [EOS_ID] + [0] * 10)[:10],
                np.int32,
            )
            for _ in range(8)
        ]
    )
    got = rc_mixed(vids, rows)
    oracle = Bleu(4)
    for i in range(8):
        hyp = vocab.decode(rows[i]).split()
        want = oracle.sentence_bleu(hyp, refs[vids[i % 2]])[3] * 10.0
        np.testing.assert_allclose(got[i], want, rtol=1e-6)


def test_parallel_rl_decode_greedy_matches_single(model_setup):
    """Sharded decode must produce the single-device greedy tokens exactly."""
    from cst_captioning_tpu.rl import make_parallel_rl_decode, make_rl_decode

    model, state, feats, masks = model_setup
    mesh = make_mesh()
    K, T = 3, 5
    rng = jax.random.key(11)
    g_single, s_single = make_rl_decode(model, K, max_len=T)(
        state.params, feats, masks, rng
    )
    pdec = make_parallel_rl_decode(model, mesh, K, max_len=T)
    g_par, s_par = pdec(
        replicate(mesh, state).params, *shard_batch(mesh, (feats, masks)), rng
    )
    # greedy is deterministic: sharded == concatenated single-device decode
    np.testing.assert_array_equal(np.asarray(g_par), np.asarray(g_single))
    # samples: same static shape, valid token range, PAD-after-EOS invariant
    assert s_par.shape == s_single.shape == (K, 8, T)
    s = np.asarray(s_par)
    assert (s >= 0).all() and (s < V).all()
    from cst_captioning_tpu.config.config import PAD_ID

    for row in s.reshape(-1, T):
        eos = np.where(row == EOS_ID)[0]
        if eos.size:
            assert (row[eos[0] + 1 :] == PAD_ID).all()


def test_rl_decode_fused_matches_two_loop(model_setup):
    """make_rl_decode's fused one-loop default is bit-exact vs the two-loop
    reference (the PR-4 acceptance pin): greedy AND samples, fixed rng."""
    from cst_captioning_tpu.rl import make_rl_decode

    model, state, feats, masks = model_setup
    K, T = 3, 5
    rng = jax.random.key(17)
    g_two, s_two = make_rl_decode(model, K, max_len=T, fused=False)(
        state.params, feats, masks, rng
    )
    g_one, s_one = make_rl_decode(model, K, max_len=T, fused=True)(
        state.params, feats, masks, rng
    )
    np.testing.assert_array_equal(np.asarray(g_one), np.asarray(g_two))
    np.testing.assert_array_equal(np.asarray(s_one), np.asarray(s_two))


def test_parallel_rl_decode_fused_matches_two_loop(model_setup):
    """The sharded (batch_axes) fused decode is bit-exact vs the sharded
    two-loop reference — same mesh, same rng, same shard-folded streams."""
    from cst_captioning_tpu.rl import make_parallel_rl_decode

    model, state, feats, masks = model_setup
    mesh = make_mesh()
    K, T = 3, 5
    rng = jax.random.key(19)
    state_r = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    g_two, s_two = make_parallel_rl_decode(model, mesh, K, max_len=T,
                                           fused=False)(
        state_r.params, f_s, m_s, rng
    )
    g_one, s_one = make_parallel_rl_decode(model, mesh, K, max_len=T,
                                           fused=True)(
        state_r.params, f_s, m_s, rng
    )
    np.testing.assert_array_equal(np.asarray(g_one), np.asarray(g_two))
    np.testing.assert_array_equal(np.asarray(s_one), np.asarray(s_two))


def test_train_epoch_pipelined_matches_sequential_at_lr0(model_setup):
    """With lr=0 the one-step-stale pipeline is exactly the sequential loop."""
    model, _, feats, masks = model_setup
    tx = make_optimizer(TrainConfig(lr=0.0, grad_clip=5.0), 10)
    rng_np = np.random.default_rng(0)
    labels = jnp.asarray(rng_np.integers(4, V, size=(8, 5)), jnp.int32)
    state = create_train_state(model, tx, (feats, masks, labels), seed=1)

    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy")
    trainer = SCSTTrainer(model, TokenReward(target=7), cfg)
    vids = [f"v{i}" for i in range(8)]
    batches = [(feats, masks, vids, None)] * 3

    _, pipelined = trainer.train_epoch(state, iter(batches), jax.random.key(9))

    sequential = []
    rng = jax.random.key(9)
    s = state
    for f, m, v, _ in batches:
        rng, srng = jax.random.split(rng)
        s, mt = trainer.train_step(s, f, m, v, srng)
        sequential.append(mt)
    assert len(pipelined) == len(sequential) == 3
    for mp, ms in zip(pipelined, sequential):
        assert mp["reward_mean"] == pytest.approx(ms["reward_mean"])
        assert float(mp["rl_loss"]) == pytest.approx(float(ms["rl_loss"]), rel=1e-5)


def test_scst_trainer_with_mesh_learns(model_setup):
    """Full sharded cycle (decode+update over the mesh) still learns."""
    model, state, feats, masks = model_setup
    mesh = make_mesh()
    cfg = RLConfig(enabled=True, num_rollouts=4, baseline="greedy")
    trainer = SCSTTrainer(model, TokenReward(target=7), cfg, mesh=mesh)
    vids = [f"v{i}" for i in range(8)]
    state = replicate(mesh, state)
    f_s, m_s = shard_batch(mesh, (feats, masks))
    rng = jax.random.key(2)
    rewards = []
    for _ in range(15):
        rng, srng = jax.random.split(rng)
        state, m = trainer.train_step(state, f_s, m_s, vids, srng)
        rewards.append(m["reward_mean"])
    assert rewards[-1] > rewards[0] + 0.5, f"{rewards[0]:.2f}->{rewards[-1]:.2f}"


@pytest.mark.parametrize("native", [False, True], ids=["python", "native"])
def test_mixed_reward_matches_both_oracles(native):
    """w_c*CIDErD + w_b*BLEU4*10 against BOTH oracles at once (config 4)."""
    from cst_captioning_tpu.metrics.bleu import Bleu
    from cst_captioning_tpu.metrics.cider import CiderD, CorpusDF

    rng = np.random.default_rng(4)
    vocab = make_vocab()
    vids = ["a", "b", "c"]
    gts = {
        v: [" ".join(rng.choice(WORDS, size=rng.integers(4, 9))) for _ in range(4)]
        for v in vids
    }
    refs = {v: [c.split() for c in caps] for v, caps in gts.items()}
    df = CorpusDF.from_refs(list(refs.values()))
    w_c, w_b = 0.8, 0.2
    rc = _reward_computer(vocab, gts, native, df=df, cider_weight=w_c,
                          bleu_weight=w_b)
    rows = np.stack(
        [
            np.asarray(
                (vocab.encode(list(rng.choice(WORDS, size=5))) + [EOS_ID] + [0] * 10)[:10],
                np.int32,
            )
            for _ in range(9)
        ]
    )
    got = rc(vids, rows)

    cider = CiderD(df=df)
    bleu = Bleu(4)
    hyps = [vocab.decode(r).split() for r in rows]
    o_gts = {str(i): refs[vids[i % 3]] for i in range(9)}
    o_res = {str(i): [hyps[i]] for i in range(9)}
    _, cider_scores = cider.compute_score(o_gts, o_res)
    for i in range(9):
        b4 = bleu.sentence_bleu(hyps[i], refs[vids[i % 3]])[3]
        want = w_c * cider_scores[i] + w_b * b4 * 10.0
        np.testing.assert_allclose(got[i], want, rtol=1e-5, atol=1e-7)


def test_native_oov_token_ids_match_python_path():
    """Ids >= len(vocab) (model vocab > dataset vocab) score as '<unk>' on
    both paths (ADVICE r1: native used to clip to the LAST vocab word)."""
    vocab = make_vocab()
    gts = {"v0": ["w0 w1 <unk>", "w0 w1 w2"]}
    rc_py = _reward_computer(vocab, gts, native=False)
    rc_nat = _reward_computer(vocab, gts, native=True)
    # row with an in-vocab prefix and a wildly out-of-range id
    row = np.asarray([[vocab.encode(["w0"])[0], vocab.encode(["w1"])[0],
                       len(vocab) + 123, EOS_ID, 0]], np.int32)
    r_py = rc_py(["v0"], row)
    r_nat = rc_nat(["v0"], row)
    np.testing.assert_allclose(r_nat, r_py, rtol=1e-6)
    assert r_py[0] > 0  # the '<unk>' gram genuinely matched a reference


@pytest.mark.parametrize("native", [False, True], ids=["python", "native"])
def test_reward_bleu_scale_knob(native):
    """rl.reward_bleu4_scale scales the BLEU term linearly on both paths
    (ADVICE r3 #4: the x10 convention is an unverified interpretation of the
    reference — the knob lets it be matched without code changes)."""
    vocab = make_vocab()
    gts = {"v0": ["w0 w1 w2 w3 w4", "w0 w1 w2 w5 w6"]}
    row = np.asarray(
        [vocab.encode("w0 w1 w2 w3 w6".split()) + [EOS_ID]], np.int32
    )
    r_cider = _reward_computer(
        vocab, gts, native, cider_weight=1.0, bleu_weight=0.0
    )(["v0"], row)[0]
    r_10 = _reward_computer(
        vocab, gts, native, cider_weight=1.0, bleu_weight=0.5, bleu_scale=10.0
    )(["v0"], row)[0]
    r_2 = _reward_computer(
        vocab, gts, native, cider_weight=1.0, bleu_weight=0.5, bleu_scale=2.0
    )(["v0"], row)[0]
    bleu_term_10 = r_10 - r_cider
    bleu_term_2 = r_2 - r_cider
    assert bleu_term_10 > 0
    np.testing.assert_allclose(bleu_term_2, bleu_term_10 / 5.0, rtol=1e-5)
    # scale folds out entirely at weight 0
    r_w0 = _reward_computer(
        vocab, gts, native, cider_weight=1.0, bleu_weight=0.0, bleu_scale=99.0
    )(["v0"], row)[0]
    np.testing.assert_allclose(r_w0, r_cider, rtol=1e-6)


def test_reward_threads_explicit_matches_default():
    """num_threads is a pure partitioning knob: scores are identical."""
    vocab = make_vocab()
    gts = {f"v{i}": [f"w{i % 9} w{(i + 1) % 9}"] for i in range(16)}
    rc1 = _reward_computer(vocab, gts, native=True, num_threads=1)
    rc4 = _reward_computer(vocab, gts, native=True, num_threads=4)
    assert rc1.num_threads == 1 and rc4.num_threads == 4
    rng = np.random.default_rng(3)
    # enough rows (>=64) to take the threaded path in the kernel
    rows = rng.integers(0, V, size=(96, 6)).astype(np.int32)
    vids = [f"v{i % 16}" for i in range(16)]
    np.testing.assert_array_equal(rc1(vids, rows), rc4(vids, rows))


def test_train_epoch_strict_flag_matches_train_step(model_setup):
    """pipelined=False is exactly the reference's on-policy loop: bit-equal
    params and metrics to calling train_step per batch with the same rng."""
    model, state, feats, masks = model_setup
    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy",
                   pipelined=False)
    trainer = SCSTTrainer(model, TokenReward(target=7), cfg)
    vids = [f"v{i}" for i in range(8)]
    batches = [(feats, masks, vids, None)] * 3

    s_epoch, strict = trainer.train_epoch(
        state, iter(batches), jax.random.key(5), pipelined=cfg.pipelined
    )

    rng = jax.random.key(5)
    s_manual = state
    manual = []
    for f, m, v, _ in batches:
        rng, srng = jax.random.split(rng)
        s_manual, mt = trainer.train_step(s_manual, f, m, v, srng)
        manual.append(mt)
    assert len(strict) == len(manual) == 3
    for mp, ms in zip(strict, manual):
        assert mp["reward_mean"] == pytest.approx(ms["reward_mean"])
        assert float(mp["rl_loss"]) == float(ms["rl_loss"])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        s_epoch.params, s_manual.params,
    )


def test_train_epoch_pipelined_matches_one_deep_schedule_at_lr(model_setup):
    """The update(i-2)->decode(i)->score(i-1) dispatch order is bit-identical
    to the 1-deep decode(i)->score(i-1)->update(i-1) pipeline at a REAL
    learning rate: the update that lands between two decodes is the same one,
    only its dispatch point moved off the host's critical path."""
    model, _, feats, masks = model_setup
    tx = make_optimizer(TrainConfig(lr=5e-2, grad_clip=5.0), 10)
    rng_np = np.random.default_rng(0)
    labels = jnp.asarray(rng_np.integers(4, V, size=(8, 5)), jnp.int32)
    state = create_train_state(model, tx, (feats, masks, labels), seed=1)

    cfg = RLConfig(enabled=True, num_rollouts=2, baseline="greedy")
    trainer = SCSTTrainer(model, TokenReward(target=7), cfg)
    vids = [f"v{i}" for i in range(8)]
    batches = [(feats, masks, vids, None)] * 4

    s_new, new = trainer.train_epoch(state, iter(batches), jax.random.key(9))

    # reference implementation: the round-3 1-deep pipelined loop
    rng = jax.random.key(9)
    s_old = state
    old = []
    pending = None
    for f, m, v, _ in batches:
        rng, srng = jax.random.split(rng)
        decoded = trainer.decode(s_old.params, f, m, srng)
        if pending is not None:
            s_old, mt = trainer._finish(s_old, *pending)
            old.append(mt)
        greedy, samples = decoded
        pending = (greedy, samples, f, m, v, np.ones((8,), np.float32))
    s_old, mt = trainer._finish(s_old, *pending)
    old.append(mt)

    assert len(new) == len(old) == 4
    for mp, ms in zip(new, old):
        assert mp["reward_mean"] == pytest.approx(ms["reward_mean"])
        assert float(mp["rl_loss"]) == float(ms["rl_loss"])
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        s_new.params, s_old.params,
    )
