"""Trainer integration: overfit XE, RL improves reward, resume, handoff.

SURVEY.md §4 item 3: overfit a handful of synthetic clips with XE, then show
the CST phase lifts the consensus reward; plus checkpoint/resume round-trips
through the Trainer.
"""

import dataclasses
import glob
import json

import numpy as np
import pytest

from cst_captioning_tpu.config.config import (
    DataConfig,
    EvalConfig,
    ExperimentConfig,
    ModelConfig,
    RLConfig,
    TrainConfig,
)
from cst_captioning_tpu.data import CaptionDataset, make_synthetic_dataset
from cst_captioning_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def synth_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("trainsynth")
    return make_synthetic_dataset(
        str(out),
        num_videos=16,
        num_topics=3,
        vocab_words=24,
        modalities={"resnet": 24},
        max_frames=4,
        seed=11,
    )


def make_cfg(ckpt_dir: str, vocab_size: int, **rl_kw) -> ExperimentConfig:
    return ExperimentConfig(
        name="itest",
        model=ModelConfig(
            vocab_size=vocab_size,
            modalities=(("resnet", 24),),
            d_embed=24,
            d_hidden=24,
            d_att=12,
            encoder="temporal_attention",
            dropout=0.0,
            max_len=10,
            max_frames=4,
            dtype="float32",
        ),
        data=DataConfig(batch_size=8, seq_per_vid=3),
        train=TrainConfig(
            lr=5e-3, epochs=12, grad_clip=5.0, ckpt_dir=ckpt_dir,
            eval_every_epochs=4, seed=0,
        ),
        rl=RLConfig(enabled=True, num_rollouts=3, lr=1e-3, epochs=4, **rl_kw),
        eval=EvalConfig(beam_size=1, max_len=10),
    )


@pytest.fixture(scope="module")
def datasets(synth_dir):
    train = CaptionDataset(synth_dir["info_json"], {"resnet": synth_dir["resnet"]},
                           "train", 4)
    val = CaptionDataset(synth_dir["info_json"], {"resnet": synth_dir["resnet"]},
                         "val", 4)
    return train, val


def test_xe_overfit_then_rl_improves(datasets, tmp_path_factory):
    train_ds, val_ds = datasets
    ckpt_dir = str(tmp_path_factory.mktemp("ckpt"))
    log_path = ckpt_dir + "/events.jsonl"
    cfg = make_cfg(ckpt_dir, len(train_ds.vocab), baseline="greedy")
    # single-device trainer (mesh path covered by step-level tests)
    tr = Trainer(cfg, train_ds, val_ds, log_path=log_path, use_mesh=False)

    tr.train_xe()
    events = [json.loads(l) for l in open(log_path)]
    xe_losses = [e["loss"] for e in events if e["event"] == "xe_epoch"]
    assert xe_losses[-1] < xe_losses[0] * 0.75, "XE phase did not learn"
    vals = [e["cider_d"] for e in events if e["event"] == "validate"]
    assert vals, "validation never ran"

    rl_val = tr.train_rl()
    events = [json.loads(l) for l in open(log_path)]
    rl_rewards = [e["reward"] for e in events if e["event"] == "rl_epoch"]
    assert len(rl_rewards) == cfg.rl.epochs
    assert rl_rewards[-1] > rl_rewards[0], (
        f"CST reward did not improve: {rl_rewards}"
    )
    # checkpoints on disk
    assert glob.glob(ckpt_dir + "/best/state.msgpack")
    assert glob.glob(ckpt_dir + "/latest/state.msgpack")


def test_trainer_resume_continues_epoch(datasets, tmp_path_factory):
    train_ds, val_ds = datasets
    ckpt_dir = str(tmp_path_factory.mktemp("ckpt2"))
    cfg = make_cfg(ckpt_dir, len(train_ds.vocab))
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, epochs=2))
    tr1 = Trainer(cfg, train_ds, val_ds, use_mesh=False)
    tr1.train_xe()
    step1 = int(tr1.state.step)

    cfg_resume = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, resume="auto")
    )
    tr2 = Trainer(cfg_resume, train_ds, val_ds, use_mesh=False)
    assert tr2.epoch == 2
    assert int(tr2.state.step) == step1


def test_xe_to_rl_handoff_loads_params(datasets, tmp_path_factory):
    train_ds, val_ds = datasets
    src_dir = str(tmp_path_factory.mktemp("ckpt3"))
    cfg = make_cfg(src_dir, len(train_ds.vocab))
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, epochs=1, eval_every_epochs=1),
    )
    tr1 = Trainer(cfg, train_ds, val_ds, use_mesh=False)
    tr1.train_xe()

    dst_dir = str(tmp_path_factory.mktemp("ckpt4"))
    cfg2 = make_cfg(dst_dir, len(train_ds.vocab))
    tr2 = Trainer(cfg2, train_ds, val_ds, use_mesh=False)
    before = jax_leaf_sum(tr2.state.params)
    tr2.load_params_from(src_dir, "best")
    after = jax_leaf_sum(tr2.state.params)
    assert before != after
    np.testing.assert_allclose(after, jax_leaf_sum(tr1.state.params), rtol=1e-6)


def jax_leaf_sum(tree):
    import jax

    return float(sum(np.abs(np.asarray(x)).sum() for x in jax.tree_util.tree_leaves(tree)))


def test_profile_and_debug_nans_flags(datasets, tmp_path_factory):
    """SURVEY.md §5 rows 1-2: jax.profiler trace + jax_debug_nans, wired
    through TrainConfig and smoke-tested end to end."""
    import os

    import jax

    train_ds, val_ds = datasets
    ckpt_dir = str(tmp_path_factory.mktemp("ckptprof"))
    prof_dir = str(tmp_path_factory.mktemp("trace"))
    log_path = ckpt_dir + "/events.jsonl"
    cfg = make_cfg(ckpt_dir, len(train_ds.vocab))
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(
            cfg.train, epochs=1, profile_dir=prof_dir, profile_steps=2,
            debug_nans=True, log_every_steps=1,
        ),
        rl=dataclasses.replace(cfg.rl, epochs=1),
    )
    try:
        tr = Trainer(cfg, train_ds, val_ds, log_path=log_path, use_mesh=False)
        assert jax.config.jax_debug_nans, "debug_nans flag not applied"
        tr.train_xe()
        tr.train_rl()
    finally:
        jax.config.update("jax_debug_nans", False)
    # both phase traces captured something
    for phase in ("xe", "rl"):
        d = os.path.join(prof_dir, phase)
        files = [
            os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs
        ]
        assert files, f"no {phase} profiler trace written under {d}"
    # per-step observability (VERDICT r2 next #6): every step logged its loss
    # AND grad_norm, so a mid-epoch divergence is locatable from the log alone
    events = [json.loads(l) for l in open(log_path)]
    xe_steps = [e for e in events if e["event"] == "xe_step"]
    rl_steps = [e for e in events if e["event"] == "rl_step"]
    assert len(xe_steps) == tr.steps_per_epoch
    assert rl_steps, "no rl_step events"
    for e in xe_steps:
        assert e["phase"] == "xe" and e["step"] > 0
        assert np.isfinite(e["loss"]) and np.isfinite(e["grad_norm"])
    for e in rl_steps:
        assert e["phase"] == "rl" and e["step"] > 0
        assert np.isfinite(e["reward"]) and np.isfinite(e["grad_norm"])
        assert np.isfinite(e["rl_loss"])


def test_cli_observability_flags_map_to_config():
    import argparse

    from cst_captioning_tpu.cli.common import add_common_args, load_config

    p = argparse.ArgumentParser()
    add_common_args(p)
    args = p.parse_args(
        ["--preset", "msvd_xe_meanpool", "--profile", "/tmp/tr", "--debug-nans"]
    )
    cfg = load_config(args)
    assert cfg.train.profile_dir == "/tmp/tr"
    assert cfg.train.debug_nans is True


def test_checkpoint_infos_carry_config_snapshot(datasets, tmp_path_factory):
    """SURVEY.md §5: the reference's infos pickle carried the full opt
    namespace; ours carries the full ExperimentConfig dict."""
    from cst_captioning_tpu.config.config import ExperimentConfig

    train_ds, val_ds = datasets
    ckpt_dir = str(tmp_path_factory.mktemp("ckptcfg"))
    cfg = make_cfg(ckpt_dir, len(train_ds.vocab))
    cfg = dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, epochs=1, eval_every_epochs=1),
    )
    Trainer(cfg, train_ds, val_ds, use_mesh=False).train_xe()
    infos = json.load(open(ckpt_dir + "/latest/infos.json"))
    assert "config" in infos
    # round-trips back into a typed config equal to the original
    assert ExperimentConfig.from_dict(infos["config"]) == cfg
    # latest/ best_value is the post-update value, not the stale one
    best_infos = json.load(open(ckpt_dir + "/best/infos.json"))
    assert infos["best_value"] == best_infos["best_value"]


def test_resume_reproduces_batch_order(datasets, tmp_path_factory):
    """Interrupt + restart with the SAME config (epochs is a total budget)
    must equal the uninterrupted run, bit-identical params."""
    import jax

    train_ds, val_ds = datasets
    base = make_cfg("", len(train_ds.vocab))

    def run(ckpt_dir, total_epochs, resume="", run_epochs=None):
        cfg = dataclasses.replace(
            base,
            train=dataclasses.replace(
                base.train, epochs=total_epochs, ckpt_dir=ckpt_dir,
                resume=resume, eval_every_epochs=100,
            ),
        )
        tr = Trainer(cfg, train_ds, val_ds=None, use_mesh=False)
        tr.train_xe(run_epochs)
        return tr

    d1 = str(tmp_path_factory.mktemp("straight"))
    d2 = str(tmp_path_factory.mktemp("resumed"))
    tr_straight = run(d1, total_epochs=2)
    # "crash" after 1 of the 2 budgeted epochs, then rerun the same command
    run(d2, total_epochs=2, run_epochs=1)
    tr_resumed = run(d2, total_epochs=2, resume="auto")

    assert tr_resumed.xe_epochs == tr_straight.xe_epochs == 2
    assert int(tr_resumed.state.step) == int(tr_straight.state.step)
    for a, b in zip(
        jax.tree_util.tree_leaves(tr_straight.state.params),
        jax.tree_util.tree_leaves(tr_resumed.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # a further resume with the full budget already trained is a no-op
    tr_done = run(d2, total_epochs=2, resume="auto")
    assert int(tr_done.state.step) == int(tr_straight.state.step)


def test_rl_resume_reproduces_stream(datasets, tmp_path_factory):
    """RL twin of test_resume_reproduces_batch_order (VERDICT r2 missing #2):
    crash mid-RL + rerun the same command == the uninterrupted run,
    bit-identical params — optimizer moments, step count, per-epoch sampling
    rng and batch order all continue instead of resetting."""
    import jax

    train_ds, _ = datasets
    base = make_cfg("", len(train_ds.vocab), baseline="greedy")

    def run(ckpt_dir, resume="", rl_run_epochs=None):
        cfg = dataclasses.replace(
            base,
            train=dataclasses.replace(
                base.train, epochs=1, ckpt_dir=ckpt_dir, resume=resume,
                eval_every_epochs=100,
            ),
            rl=dataclasses.replace(base.rl, epochs=2),
        )
        tr = Trainer(cfg, train_ds, val_ds=None, use_mesh=False)
        tr.train_xe()
        tr.train_rl(rl_run_epochs)
        return tr

    d1 = str(tmp_path_factory.mktemp("rl_straight"))
    d2 = str(tmp_path_factory.mktemp("rl_resumed"))
    tr_straight = run(d1)
    # "crash" after 1 of the 2 budgeted RL epochs, then rerun the command
    run(d2, rl_run_epochs=1)
    tr_resumed = run(d2, resume="auto")

    assert tr_resumed.rl_epochs == tr_straight.rl_epochs == 2
    assert int(tr_resumed.state.step) == int(tr_straight.state.step)
    for a, b in zip(
        jax.tree_util.tree_leaves(tr_straight.state.params),
        jax.tree_util.tree_leaves(tr_resumed.state.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the optimizer moments continued too (not re-initialized to zeros)
    for a, b in zip(
        jax.tree_util.tree_leaves(tr_straight.state.opt_state),
        jax.tree_util.tree_leaves(tr_resumed.state.opt_state),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_seq_devices_matches_data_parallel(datasets, tmp_path_factory):
    """MeshConfig.seq_devices wires SP into the product (VERDICT r2 next #4):
    the SAME config trained on a 2x4 ('data','seq') mesh matches the 1-D
    8-device data-parallel run — XE params allclose, validation CIDEr equal —
    and the RL phase runs sharded end to end on the 2-D mesh."""
    import jax

    from cst_captioning_tpu.config.config import MeshConfig

    train_ds, val_ds = datasets
    base = make_cfg("", len(train_ds.vocab), baseline="greedy")

    def run(ckpt_dir, mesh_cfg):
        cfg = dataclasses.replace(
            base,
            mesh=mesh_cfg,
            train=dataclasses.replace(
                base.train, epochs=2, ckpt_dir=ckpt_dir, eval_every_epochs=2,
            ),
            rl=dataclasses.replace(base.rl, epochs=0),
        )
        log = ckpt_dir + "/events.jsonl"
        tr = Trainer(cfg, train_ds, val_ds, log_path=log, use_mesh=True)
        val = tr.train_xe()
        losses = [
            json.loads(l)["loss"] for l in open(log)
            if json.loads(l)["event"] == "xe_epoch"
        ]
        return tr, val, losses

    d1 = str(tmp_path_factory.mktemp("dp1d"))
    d2 = str(tmp_path_factory.mktemp("dpxsp"))
    tr_dp, val_dp, losses_dp = run(d1, MeshConfig())
    tr_sp, val_sp, losses_sp = run(d2, MeshConfig(seq_devices=4))
    assert tr_sp.sp and tr_sp.mesh.shape == {"data": 2, "seq": 4}
    assert val_sp == pytest.approx(val_dp, abs=1e-6)
    # per-epoch mean losses track tightly (per-step exactness is pinned at
    # rtol=1e-4 in test_seq_parallel; Adam amplifies reassociation bit-drift
    # across the 12 steps, so end-of-run params only match loosely)
    np.testing.assert_allclose(losses_sp, losses_dp, rtol=1e-4)
    for a, b in zip(
        jax.tree_util.tree_leaves(tr_dp.state.params),
        jax.tree_util.tree_leaves(tr_sp.state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-2, atol=2e-3
        )

    # the RL phase runs fully sharded on the 2-D mesh (decode + update SP)
    cfg_rl = dataclasses.replace(
        base,
        mesh=MeshConfig(seq_devices=4),
        train=dataclasses.replace(
            base.train, epochs=0, ckpt_dir=d2, eval_every_epochs=100,
        ),
        rl=dataclasses.replace(base.rl, epochs=1),
    )
    tr_rl = Trainer(cfg_rl, train_ds, None, use_mesh=True)
    before = jax_leaf_sum(tr_rl.state.params)
    tr_rl.train_rl()
    assert tr_rl.rl_epochs == 1
    assert jax_leaf_sum(tr_rl.state.params) != before


def test_trainer_seq_devices_rejects_indivisible_frames(datasets):
    from cst_captioning_tpu.config.config import MeshConfig

    train_ds, _ = datasets
    cfg = make_cfg("", len(train_ds.vocab))
    # 8 devices /8 = a pure-SP mesh, but max_frames=4 can't shard 8 ways
    cfg = dataclasses.replace(cfg, mesh=MeshConfig(seq_devices=8))
    with pytest.raises(ValueError, match="max_frames"):
        Trainer(cfg, train_ds, None)


def test_trainer_rejects_seq_axis_spanning_hosts(datasets, monkeypatch, tmp_path):
    """Multi-host + a 'seq' axis wider than one process's devices would psum
    frame shards of DIFFERENT videos (host-sharded feeding partitions 'data'
    by process) — must be rejected, not silently diverge."""
    from types import SimpleNamespace

    import numpy as np

    from cst_captioning_tpu.config.config import MeshConfig
    from cst_captioning_tpu.train import multihost

    # the placement check itself, on a fabricated 2x4 grid whose seq rows
    # mix two processes (device-id order need not be process-contiguous)
    def dev(pid):
        return SimpleNamespace(process_index=pid)

    bad = np.array([[dev(0), dev(0), dev(1), dev(1)]] * 2)
    with pytest.raises(ValueError, match="spans processes"):
        multihost.assert_seq_axis_within_host(bad)
    good = np.array([[dev(0)] * 4, [dev(1)] * 4])
    multihost.assert_seq_axis_within_host(good)  # no raise

    # and the Trainer wires it: with multi-process faked, the single-process
    # test grid (all process_index 0) passes placement and training proceeds
    # to the batcher — so just pin that the check is invoked
    called = []
    monkeypatch.setattr(multihost, "is_multiprocess", lambda: True)
    monkeypatch.setattr(
        multihost, "assert_seq_axis_within_host",
        lambda grid: called.append(grid.shape),
    )
    # host_shard would also see the fake multiprocess: keep it single
    monkeypatch.setattr(multihost, "host_shard", lambda: (0, 1))
    train_ds, _ = datasets
    cfg = make_cfg(str(tmp_path / "ckpt"), len(train_ds.vocab))
    cfg = dataclasses.replace(cfg, mesh=MeshConfig(seq_devices=4))
    Trainer(cfg, train_ds, None)
    assert called == [(2, 4)]


def test_config_rejects_indivisible_update_chunks():
    from cst_captioning_tpu.config.config import ExperimentConfig, RLConfig

    with pytest.raises(ValueError, match="update_chunks"):
        ExperimentConfig(
            rl=RLConfig(enabled=True, num_rollouts=5, update_chunks=4)
        )
    # valid combinations construct fine
    ExperimentConfig(rl=RLConfig(enabled=True, num_rollouts=4, update_chunks=2))


def test_resume_logs_config_drift(datasets, tmp_path_factory):
    train_ds, _ = datasets
    ckpt_dir = str(tmp_path_factory.mktemp("ckptdrift"))
    log1 = ckpt_dir + "/l1.jsonl"
    log2 = ckpt_dir + "/l2.jsonl"
    cfg = make_cfg(ckpt_dir, len(train_ds.vocab))
    cfg = dataclasses.replace(cfg, train=dataclasses.replace(cfg.train, epochs=1))
    Trainer(cfg, train_ds, None, log_path=log1, use_mesh=False).train_xe()

    # identical config (only the volatile resume field differs): NO drift event
    cfg_same = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, resume="auto")
    )
    log_same = ckpt_dir + "/lsame.jsonl"
    Trainer(cfg_same, train_ds, None, log_path=log_same, use_mesh=False)
    events = [json.loads(l) for l in open(log_same)]
    assert not [e for e in events if e["event"] == "resume_config_drift"]

    # a real hyperparameter change IS flagged, by its dotted path
    cfg2 = dataclasses.replace(
        cfg,
        train=dataclasses.replace(cfg.train, resume="auto", lr=9e-9),
    )
    Trainer(cfg2, train_ds, None, log_path=log2, use_mesh=False)
    events = [json.loads(l) for l in open(log2)]
    drift = [e for e in events if e["event"] == "resume_config_drift"]
    assert drift and drift[0]["fields"] == ["train.lr"]
