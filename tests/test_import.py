"""MSR-VTT importer round trip: standard distribution shape -> our schema ->
CaptionDataset -> batches (VERDICT r1 missing #8 / SURVEY.md §3.4)."""

import json
import os

import h5py
import numpy as np
import pytest

from cst_captioning_tpu.data import Batcher, CaptionDataset, import_msrvtt
from cst_captioning_tpu.metrics.cider import CorpusDF


@pytest.fixture(scope="module")
def msrvtt_fixture(tmp_path_factory):
    """A tiny MSR-VTT-shaped distribution: videodatainfo.json + features."""
    root = tmp_path_factory.mktemp("msrvtt_raw")
    rng = np.random.default_rng(0)
    n = 10
    phrases = [
        "a man is playing a guitar",
        "someone plays an acoustic guitar",
        "a woman is cooking in a kitchen",
        "a person slices some vegetables",
        "a dog runs across the yard",
    ]
    videos = []
    sentences = []
    for i in range(n):
        vid = f"video{i}"
        split = "train" if i < 6 else ("validate" if i < 8 else "test")
        videos.append({"video_id": vid, "split": split, "category": i % 3})
        for j in range(3):
            sentences.append(
                {"video_id": vid, "caption": phrases[(i + j) % len(phrases)],
                 "sen_id": i * 3 + j}
            )
    info = {"videos": videos, "sentences": sentences}
    info_path = str(root / "videodatainfo.json")
    with open(info_path, "w") as f:
        json.dump(info, f)

    # modality 1: an h5 keyed by video id (plus an extra key that must be
    # filtered out, not imported)
    h5_path = str(root / "resnet_raw.h5")
    with h5py.File(h5_path, "w") as f:
        for i in range(n):
            f[f"video{i}"] = rng.normal(size=(6, 32)).astype(np.float32)
        f["video_not_in_info"] = np.zeros((6, 32), np.float32)

    # modality 2: a directory of <vid>.npy files (1-D rows -> [1, dim])
    npy_dir = root / "c3d_npy"
    npy_dir.mkdir()
    for i in range(n):
        np.save(str(npy_dir / f"video{i}.npy"),
                rng.normal(size=(16,)).astype(np.float32))

    return {"info": info_path, "h5": h5_path, "npy_dir": str(npy_dir), "n": n}


@pytest.fixture(scope="module")
def imported(msrvtt_fixture, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("msrvtt_imported"))
    return import_msrvtt(
        msrvtt_fixture["info"],
        out,
        features={"resnet": msrvtt_fixture["h5"],
                  "c3d": msrvtt_fixture["npy_dir"]},
        min_word_count=1,
    ), msrvtt_fixture


def test_import_produces_all_files(imported):
    paths, _ = imported
    for key in ("info_json", "resnet", "c3d", "consensus_weights", "cider_df"):
        assert key in paths and os.path.exists(paths[key]), key


def test_imported_dataset_loads_and_batches(imported):
    paths, fx = imported
    for split, want in (("train", 6), ("val", 2), ("test", 2)):
        ds = CaptionDataset(
            paths["info_json"],
            {"resnet": paths["resnet"], "c3d": paths["c3d"]},
            split,
            max_frames=6,
            consensus_weights=paths["consensus_weights"],
        )
        assert len(ds) == want
        batch = next(iter(Batcher(ds, batch_size=4, max_len=12)))
        assert batch.feats["resnet"].shape == (4, 6, 32)
        # 1-D npy features import as single-frame rows
        assert batch.feats["c3d"].shape == (4, 6, 16)
        assert batch.feat_masks["c3d"][0].sum() == 1.0
        assert batch.labels.max() > 3  # real word ids present
        ds.close()


def test_imported_weights_and_df_are_consumable(imported):
    paths, _ = imported
    df = CorpusDF.load(paths["cider_df"])
    assert df.num_docs == 6  # train videos only
    assert len(df.df) > 0
    w = np.load(paths["consensus_weights"])
    assert sorted(w.files) == [f"video{i}" for i in range(6)]
    for vid in w.files:
        assert w[vid].shape == (3,)
        # mean-1 normalization per video
        np.testing.assert_allclose(w[vid].mean(), 1.0, rtol=1e-5)


def test_import_filters_unknown_h5_keys(imported):
    paths, _ = imported
    with h5py.File(paths["resnet"], "r") as f:
        assert "video_not_in_info" not in f
        assert len(f) == 10


def test_import_rejects_bad_split(msrvtt_fixture, tmp_path):
    info = json.load(open(msrvtt_fixture["info"]))
    info["videos"][0]["split"] = "weird"
    with pytest.raises(ValueError, match="unknown MSR-VTT split"):
        import_msrvtt(info, str(tmp_path))


def test_import_rejects_captionless_video(msrvtt_fixture, tmp_path):
    info = json.load(open(msrvtt_fixture["info"]))
    info["videos"].append({"video_id": "video99", "split": "train"})
    with pytest.raises(ValueError, match="without captions"):
        import_msrvtt(info, str(tmp_path))


def test_cli_entry(msrvtt_fixture, tmp_path, capsys):
    from cst_captioning_tpu.cli.import_msrvtt import main

    main([
        "--videodatainfo", msrvtt_fixture["info"],
        "--out-dir", str(tmp_path / "out"),
        "--feature", f"resnet={msrvtt_fixture['h5']}",
        "--min-word-count", "1", "--no-weights",
    ])
    paths = json.loads(capsys.readouterr().out)
    assert os.path.exists(paths["info_json"])
    assert os.path.exists(paths["resnet"])
    assert "consensus_weights" not in paths


def test_import_rejects_3d_features(msrvtt_fixture, tmp_path):
    """Arrays with a leading batch dim must fail loudly at import time."""
    from cst_captioning_tpu.data.importers import pack_features

    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    np.save(str(bad_dir / "video0.npy"),
            np.zeros((1, 6, 32), np.float32))
    with pytest.raises(ValueError, match="leading batch dimension"):
        pack_features(str(bad_dir), str(tmp_path / "out.h5"), ["video0"])
