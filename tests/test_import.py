"""Importer round trips: standard MSR-VTT / MSVD distribution shapes -> our
schema -> CaptionDataset -> batches (VERDICT r1 missing #8, VERDICT r2 missing
#1 / SURVEY.md §3.4; MSVD is BASELINE config 1's ingestion path)."""

import json
import os

import h5py
import numpy as np
import pytest

from cst_captioning_tpu.data import (
    Batcher,
    CaptionDataset,
    import_msrvtt,
    import_msvd,
)
from cst_captioning_tpu.metrics.cider import CorpusDF


@pytest.fixture(scope="module")
def msrvtt_fixture(tmp_path_factory):
    """A tiny MSR-VTT-shaped distribution: videodatainfo.json + features."""
    root = tmp_path_factory.mktemp("msrvtt_raw")
    rng = np.random.default_rng(0)
    n = 10
    phrases = [
        "a man is playing a guitar",
        "someone plays an acoustic guitar",
        "a woman is cooking in a kitchen",
        "a person slices some vegetables",
        "a dog runs across the yard",
    ]
    videos = []
    sentences = []
    for i in range(n):
        vid = f"video{i}"
        split = "train" if i < 6 else ("validate" if i < 8 else "test")
        videos.append({"video_id": vid, "split": split, "category": i % 3})
        for j in range(3):
            sentences.append(
                {"video_id": vid, "caption": phrases[(i + j) % len(phrases)],
                 "sen_id": i * 3 + j}
            )
    info = {"videos": videos, "sentences": sentences}
    info_path = str(root / "videodatainfo.json")
    with open(info_path, "w") as f:
        json.dump(info, f)

    # modality 1: an h5 keyed by video id (plus an extra key that must be
    # filtered out, not imported)
    h5_path = str(root / "resnet_raw.h5")
    with h5py.File(h5_path, "w") as f:
        for i in range(n):
            f[f"video{i}"] = rng.normal(size=(6, 32)).astype(np.float32)
        f["video_not_in_info"] = np.zeros((6, 32), np.float32)

    # modality 2: a directory of <vid>.npy files (1-D rows -> [1, dim])
    npy_dir = root / "c3d_npy"
    npy_dir.mkdir()
    for i in range(n):
        np.save(str(npy_dir / f"video{i}.npy"),
                rng.normal(size=(16,)).astype(np.float32))

    return {"info": info_path, "h5": h5_path, "npy_dir": str(npy_dir), "n": n}


@pytest.fixture(scope="module")
def imported(msrvtt_fixture, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("msrvtt_imported"))
    return import_msrvtt(
        msrvtt_fixture["info"],
        out,
        features={"resnet": msrvtt_fixture["h5"],
                  "c3d": msrvtt_fixture["npy_dir"]},
        min_word_count=1,
    ), msrvtt_fixture


def test_import_produces_all_files(imported):
    paths, _ = imported
    for key in ("info_json", "resnet", "c3d", "consensus_weights", "cider_df"):
        assert key in paths and os.path.exists(paths[key]), key


def test_imported_dataset_loads_and_batches(imported):
    paths, fx = imported
    for split, want in (("train", 6), ("val", 2), ("test", 2)):
        ds = CaptionDataset(
            paths["info_json"],
            {"resnet": paths["resnet"], "c3d": paths["c3d"]},
            split,
            max_frames=6,
            consensus_weights=paths["consensus_weights"],
        )
        assert len(ds) == want
        batch = next(iter(Batcher(ds, batch_size=4, max_len=12)))
        assert batch.feats["resnet"].shape == (4, 6, 32)
        # 1-D npy features import as single-frame rows
        assert batch.feats["c3d"].shape == (4, 6, 16)
        assert batch.feat_masks["c3d"][0].sum() == 1.0
        assert batch.labels.max() > 3  # real word ids present
        ds.close()


def test_imported_weights_and_df_are_consumable(imported):
    paths, _ = imported
    df = CorpusDF.load(paths["cider_df"])
    assert df.num_docs == 6  # train videos only
    assert len(df.df) > 0
    w = np.load(paths["consensus_weights"])
    assert sorted(w.files) == [f"video{i}" for i in range(6)]
    for vid in w.files:
        assert w[vid].shape == (3,)
        # mean-1 normalization per video
        np.testing.assert_allclose(w[vid].mean(), 1.0, rtol=1e-5)


def test_import_filters_unknown_h5_keys(imported):
    paths, _ = imported
    with h5py.File(paths["resnet"], "r") as f:
        assert "video_not_in_info" not in f
        assert len(f) == 10


def test_import_rejects_bad_split(msrvtt_fixture, tmp_path):
    info = json.load(open(msrvtt_fixture["info"]))
    info["videos"][0]["split"] = "weird"
    with pytest.raises(ValueError, match="unknown MSR-VTT split"):
        import_msrvtt(info, str(tmp_path))


def test_import_rejects_captionless_video(msrvtt_fixture, tmp_path):
    info = json.load(open(msrvtt_fixture["info"]))
    info["videos"].append({"video_id": "video99", "split": "train"})
    with pytest.raises(ValueError, match="without captions"):
        import_msrvtt(info, str(tmp_path))


def test_cli_entry(msrvtt_fixture, tmp_path, capsys):
    from cst_captioning_tpu.cli.import_msrvtt import main

    main([
        "--videodatainfo", msrvtt_fixture["info"],
        "--out-dir", str(tmp_path / "out"),
        "--feature", f"resnet={msrvtt_fixture['h5']}",
        "--min-word-count", "1", "--no-weights",
    ])
    paths = json.loads(capsys.readouterr().out)
    assert os.path.exists(paths["info_json"])
    assert os.path.exists(paths["resnet"])
    assert "consensus_weights" not in paths


# ---- MSVD (BASELINE config 1) ----------------------------------------------


MSVD_PHRASES = [
    "a cat chases a ball",
    "the kitten plays with a toy",
    "a man rides a bicycle downhill",
    "someone is riding a bike",
    "a chef stirs a pot of soup",
]


@pytest.fixture(scope="module")
def msvd_fixture(tmp_path_factory):
    """A tiny MSVD-shaped distribution: corpus csv + youtube mapping +
    features, including non-English rows and an unmapped clip that the
    conventional 1970-clip subset drops."""
    root = tmp_path_factory.mktemp("msvd_raw")
    rng = np.random.default_rng(1)
    n = 8
    clips = [f"yt{i:02d}_{i * 10}_{i * 10 + 5}" for i in range(n)]

    csv_path = str(root / "video_corpus.csv")
    with open(csv_path, "w") as f:
        f.write("VideoID,Start,End,WorkerID,Source,AnnotationTime,"
                "Language,Description\n")
        for i, clip in enumerate(clips):
            vid, start, end = f"yt{i:02d}", i * 10, i * 10 + 5
            for j in range(3):
                cap = MSVD_PHRASES[(i + j) % len(MSVD_PHRASES)]
                if i >= 5 and j == 2:
                    # val/test-only word: must NOT reach the vocab
                    cap = f"a rare zzquux{i} appears"
                f.write(f"{vid},{start},{end},w{j},x,1,English,{cap}\n")
            # non-English and empty rows must be skipped
            f.write(f"{vid},{start},{end},w9,x,1,German,eine katze\n")
            f.write(f"{vid},{start},{end},w8,x,1,English,\n")
        # a clip absent from the mapping: dropped by the canonical subset
        f.write("ytXX,0,5,w0,x,1,English,this clip is not in the mapping\n")

    map_path = str(root / "youtube_mapping.txt")
    with open(map_path, "w") as f:
        # deliberately out of file order; vid index fixes the canonical order
        for i in reversed(range(n)):
            f.write(f"{clips[i]} vid{i + 1}\n")

    npy_dir = root / "resnet_npy"
    npy_dir.mkdir()
    for clip in clips:
        np.save(str(npy_dir / f"{clip}.npy"),
                rng.normal(size=(5, 16)).astype(np.float32))
    return {"csv": csv_path, "mapping": map_path, "npy_dir": str(npy_dir),
            "clips": clips, "n": n}


@pytest.fixture(scope="module")
def msvd_imported(msvd_fixture, tmp_path_factory):
    out = str(tmp_path_factory.mktemp("msvd_imported"))
    return import_msvd(
        msvd_fixture["csv"],
        out,
        mapping=msvd_fixture["mapping"],
        features={"resnet": msvd_fixture["npy_dir"]},
        n_train=5,
        n_val=1,
        min_word_count=1,
    ), msvd_fixture


def test_msvd_import_produces_all_files(msvd_imported):
    paths, _ = msvd_imported
    for key in ("info_json", "resnet", "consensus_weights", "cider_df"):
        assert key in paths and os.path.exists(paths[key]), key


def test_msvd_split_and_order_follow_mapping(msvd_imported):
    paths, fx = msvd_imported
    info = json.load(open(paths["info_json"]))
    # canonical order = mapping's vid<N> order; unmapped ytXX dropped
    ids = [v["id"] for v in info["videos"]]
    assert ids == fx["clips"]
    splits = [v["split"] for v in info["videos"]]
    assert splits == ["train"] * 5 + ["val"] + ["test"] * 2
    # non-English / empty rows were skipped: exactly 3 captions per clip
    assert all(len(v["captions"]) == 3 for v in info["videos"])
    assert not any("katze" in c for v in info["videos"] for c in v["captions"])


def test_msvd_imported_dataset_loads_and_batches(msvd_imported):
    paths, _ = msvd_imported
    for split, want in (("train", 5), ("val", 1), ("test", 2)):
        ds = CaptionDataset(
            paths["info_json"],
            {"resnet": paths["resnet"]},
            split,
            max_frames=5,
            consensus_weights=(
                paths["consensus_weights"] if split == "train" else None
            ),
        )
        assert len(ds) == want
        batch = next(iter(Batcher(ds, batch_size=2, max_len=12)))
        assert batch.feats["resnet"].shape == (2, 5, 16)
        assert batch.labels.max() > 3
        ds.close()


def test_msvd_vocab_is_train_only(msvd_imported):
    """val/test-only words must encode to <unk> (ADVICE r2: standard
    train-only preprocessing; the df/weights were already train-restricted)."""
    paths, _ = msvd_imported
    info = json.load(open(paths["info_json"]))
    vocab = set(info["vocab"])
    train_words = {
        w for v in info["videos"] if v["split"] == "train"
        for c in v["captions"] for w in c.split()
    }
    test_only = {
        w for v in info["videos"] if v["split"] != "train"
        for c in v["captions"] for w in c.split()
    } - train_words
    assert test_only, "fixture should exercise unseen test words"
    assert test_only & vocab == set()
    df = CorpusDF.load(paths["cider_df"])
    assert df.num_docs == 5  # train clips only


def test_msvd_txt_corpus_and_no_mapping(msvd_fixture, tmp_path):
    """The AllVideoDescriptions.txt variant, without a mapping: clips order
    by sorted id and split by the given boundaries."""
    from cst_captioning_tpu.data.importers import parse_msvd_corpus

    txt = tmp_path / "AllVideoDescriptions.txt"
    with open(txt, "w") as f:
        for i in range(4):
            f.write(f"clip{i} a short caption number {i}\n")
            f.write(f"clip{i} another sentence about {i}\n")
    raw, splits = parse_msvd_corpus(str(txt), n_train=2, n_val=1)
    assert list(raw) == [f"clip{i}" for i in range(4)]
    assert [splits[c] for c in raw] == ["train", "train", "val", "test"]
    assert raw["clip0"] == ["a short caption number 0",
                            "another sentence about 0"]


def test_msvd_rejects_undersized_corpus(msvd_fixture, tmp_path):
    with pytest.raises(ValueError, match="n_train"):
        import_msvd(msvd_fixture["csv"], str(tmp_path),
                    mapping=msvd_fixture["mapping"], n_train=100)


def test_msvd_cli_entry(msvd_fixture, tmp_path, capsys):
    from cst_captioning_tpu.cli.import_msvd import main

    main([
        "--corpus", msvd_fixture["csv"],
        "--mapping", msvd_fixture["mapping"],
        "--out-dir", str(tmp_path / "out"),
        "--feature", f"resnet={msvd_fixture['npy_dir']}",
        "--n-train", "5", "--n-val", "1",
        "--min-word-count", "1", "--no-weights",
    ])
    paths = json.loads(capsys.readouterr().out)
    assert os.path.exists(paths["info_json"])
    assert os.path.exists(paths["resnet"])
    assert "consensus_weights" not in paths


def test_msvd_config1_trains_end_to_end(msvd_imported, tmp_path):
    """BASELINE config 1 e2e (VERDICT r2 missing #1): the msvd_xe_meanpool
    preset — dims scaled to the fixture — trains one XE epoch on the
    IMPORTED MSVD data and validates on its val split."""
    import dataclasses

    from cst_captioning_tpu.config.presets import get_preset
    from cst_captioning_tpu.train.trainer import Trainer

    paths, _ = msvd_imported
    train_ds = CaptionDataset(paths["info_json"], {"resnet": paths["resnet"]},
                              "train", max_frames=5)
    val_ds = CaptionDataset(paths["info_json"], {"resnet": paths["resnet"]},
                            "val", max_frames=5)
    cfg = get_preset("msvd_xe_meanpool")
    assert cfg.model.encoder == "meanpool" and cfg.data.dataset == "msvd"
    cfg = dataclasses.replace(
        cfg,
        model=dataclasses.replace(
            cfg.model, vocab_size=len(train_ds.vocab),
            modalities=(("resnet", 16),), d_embed=16, d_hidden=16,
            max_len=12, max_frames=5, dtype="float32",
        ),
        data=dataclasses.replace(cfg.data, batch_size=4),
        train=dataclasses.replace(
            cfg.train, epochs=1, eval_every_epochs=1,
            ckpt_dir=str(tmp_path / "ckpt"),
        ),
    )
    tr = Trainer(cfg, train_ds, val_ds, use_mesh=False)
    val = tr.train_xe()
    assert tr.xe_epochs == 1
    assert val is not None and np.isfinite(val)
    train_ds.close()
    val_ds.close()


def test_import_rejects_3d_features(msrvtt_fixture, tmp_path):
    """Arrays with a leading batch dim must fail loudly at import time."""
    from cst_captioning_tpu.data.importers import pack_features

    bad_dir = tmp_path / "bad"
    bad_dir.mkdir()
    np.save(str(bad_dir / "video0.npy"),
            np.zeros((1, 6, 32), np.float32))
    with pytest.raises(ValueError, match="leading batch dimension"):
        pack_features(str(bad_dir), str(tmp_path / "out.h5"), ["video0"])
