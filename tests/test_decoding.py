"""Decoding tests: greedy, sampling, fused one-loop, beam — incl. oracles."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.config.config import BOS_ID, EOS_ID, PAD_ID, ModelConfig
from cst_captioning_tpu.decoding import (
    beam_search,
    fused_decode,
    greedy_decode,
    sample_decode,
)
from cst_captioning_tpu.decoding.common import (
    forbid_special,
    rollout_step_keys,
    selected_logprob,
)
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.models.captioner import CaptionModel as CM

B, F, T, V = 4, 5, 6, 11


@pytest.fixture(scope="module")
def setup():
    cfg = ModelConfig(
        vocab_size=V,
        modalities=(("resnet", 8),),
        d_embed=12,
        d_hidden=12,
        d_att=6,
        encoder="temporal_attention",
        max_len=T,
        max_frames=F,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 8)), jnp.float32)}
    masks = {"resnet": jnp.ones((B, F), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)
    params = model.init(jax.random.key(0), feats, masks, labels)
    return model, params, feats, masks


def _check_pad_after_eos(tokens):
    tokens = np.asarray(tokens)
    for row in tokens.reshape(-1, tokens.shape[-1]):
        seen_eos = False
        for t in row:
            if seen_eos:
                assert t == PAD_ID
            if t == EOS_ID:
                seen_eos = True


def test_greedy_shapes_and_padding(setup):
    model, params, feats, masks = setup
    tokens, logprobs = greedy_decode(model, params, feats, masks)
    assert tokens.shape == (B, T) and logprobs.shape == (B, T)
    _check_pad_after_eos(tokens)
    # PAD positions have zero logprob
    assert np.all(np.asarray(logprobs)[np.asarray(tokens) == PAD_ID] == 0.0)


def test_greedy_matches_manual_argmax(setup):
    model, params, feats, masks = setup
    tokens, _ = greedy_decode(model, params, feats, masks)
    enc = model.apply(params, feats, masks, method=CM.encode)
    carry, tok = enc.carry, jnp.full((B,), BOS_ID, jnp.int32)
    manual = []
    finished = np.zeros(B, bool)
    for _ in range(T):
        carry, logits = model.apply(params, carry, tok, enc, method=CM.decode_step)
        nxt = np.asarray(jnp.argmax(forbid_special(logits), -1)).astype(np.int32)
        nxt[finished] = PAD_ID
        finished |= nxt == EOS_ID
        manual.append(nxt)
        tok = jnp.asarray(nxt)
    np.testing.assert_array_equal(tokens, np.stack(manual, 1))


def test_sample_rollouts_reproducible_and_distinct(setup):
    model, params, feats, masks = setup
    rng = jax.random.key(42)
    t1, lp1 = sample_decode(model, params, feats, masks, rng, num_rollouts=3)
    t2, lp2 = sample_decode(model, params, feats, masks, rng, num_rollouts=3)
    assert t1.shape == (3, B, T)
    np.testing.assert_array_equal(t1, t2)  # same key -> identical
    # different rollouts differ somewhere (tiny chance of collision)
    assert not np.array_equal(np.asarray(t1[0]), np.asarray(t1[1]))
    _check_pad_after_eos(t1)
    assert np.all(np.asarray(lp1)[np.asarray(t1) == PAD_ID] == 0.0)
    # sampled-token logprobs are real logprobs (negative where not PAD)
    assert np.all(np.asarray(lp1)[np.asarray(t1) != PAD_ID] < 0.0)


def test_sample_temperature_zero_limit(setup):
    """Very low temperature sampling ≈ greedy decoding."""
    model, params, feats, masks = setup
    tg, _ = greedy_decode(model, params, feats, masks)
    ts, _ = sample_decode(
        model, params, feats, masks, jax.random.key(0), num_rollouts=1,
        temperature=1e-4,
    )
    np.testing.assert_array_equal(tg, ts[0])


def test_beam1_equals_greedy(setup):
    model, params, feats, masks = setup
    tg, _ = greedy_decode(model, params, feats, masks)
    tb, _ = beam_search(model, params, feats, masks, beam_size=1)
    np.testing.assert_array_equal(tg, tb)


def test_beam_search_improves_or_matches_score(setup):
    """Beam-5 total logprob >= greedy total logprob for every sequence."""
    model, params, feats, masks = setup

    def seq_logprob(tokens_row):
        """Total model logprob of a fixed token row, teacher-forced."""
        labels = tokens_row[None, :]
        # score through model __call__ on a single row
        f1 = {k: v[:1] for k, v in feats.items()}
        m1 = {k: v[:1] for k, v in masks.items()}
        logits = forbid_special(model.apply(params, f1, m1, labels))
        logp = jax.nn.log_softmax(logits, -1)
        lp = jnp.take_along_axis(logp, labels[..., None], -1)[..., 0]
        mask = (labels != PAD_ID).astype(jnp.float32)
        return float((lp * mask).sum())

    tg, _ = greedy_decode(model, params, feats, masks)
    tb, scores = beam_search(model, params, feats, masks, beam_size=5)
    # row 0 only (seq_logprob uses feats[0:1])
    assert seq_logprob(tb[0]) >= seq_logprob(tg[0]) - 1e-4


def test_beam_matches_bruteforce_oracle(setup):
    """Beam=V on a tiny space == exhaustive enumeration of all sequences."""
    model, params, feats, masks = setup
    Tshort = 3
    f1 = {k: v[:1] for k, v in feats.items()}
    m1 = {k: v[:1] for k, v in masks.items()}

    # enumerate canonical sequences (nothing after first EOS), then score
    # them ALL in one batched teacher-forced pass instead of ~2k step calls
    alphabet = list(range(2, V))  # EOS and real words (skip PAD, BOS)
    candidates = []
    for seq in itertools.product(alphabet, repeat=Tshort):
        if EOS_ID in seq:
            k = seq.index(EOS_ID)
            if any(s != EOS_ID for s in seq[k + 1 :]):
                continue  # duplicate of the truncated form
        candidates.append(seq)
    cand = np.asarray(candidates, np.int32)                     # [N, Tshort]
    N = cand.shape[0]
    fN = {k: jnp.broadcast_to(v[:1], (N,) + v.shape[1:]) for k, v in f1.items()}
    mN = {k: jnp.broadcast_to(v[:1], (N,) + v.shape[1:]) for k, v in m1.items()}
    logits = forbid_special(model.apply(params, fN, mN, jnp.asarray(cand)))
    logp = np.asarray(jax.nn.log_softmax(logits, -1))
    tok_lp = np.take_along_axis(logp, cand[..., None], -1)[..., 0]  # [N, T]
    # mask: count tokens up to and including first EOS
    scores_all = np.zeros(N)
    for i, seq in enumerate(candidates):
        L = seq.index(EOS_ID) + 1 if EOS_ID in seq else Tshort
        scores_all[i] = tok_lp[i, :L].sum()
    best = int(np.argmax(scores_all))
    best_score, best_seq = scores_all[best], candidates[best]

    tb, scores = beam_search(
        model, params, f1, m1, beam_size=(V - 2) ** 2, max_len=Tshort
    )
    got = [t for t in np.asarray(tb)[0].tolist() if t != PAD_ID]
    want = list(best_seq[: best_seq.index(EOS_ID) + 1] if EOS_ID in best_seq else best_seq)
    assert got == want, f"beam {got} vs oracle {want}"
    np.testing.assert_allclose(float(scores[0]), best_score, rtol=1e-4)


def test_beam_return_all_sorted(setup):
    model, params, feats, masks = setup
    tokens, scores = beam_search(
        model, params, feats, masks, beam_size=4, return_all=True
    )
    assert tokens.shape == (B, 4, T) and scores.shape == (B, 4)
    s = np.asarray(scores)
    assert np.all(np.diff(s, axis=1) <= 1e-6)  # descending


def test_selected_logprob_matches_log_softmax():
    """The one-pass selected-row logprob (logit - logsumexp) equals the
    full log_softmax + gather it replaced, across shapes and dtypes."""
    rng = np.random.default_rng(7)
    for shape in [(4, 11), (3, 4, 11), (2, 3, 4, 7)]:
        logits = jnp.asarray(rng.normal(size=shape) * 5, jnp.float32)
        token = jnp.asarray(rng.integers(0, shape[-1], size=shape[:-1]), jnp.int32)
        want = jnp.take_along_axis(
            jax.nn.log_softmax(logits, axis=-1), token[..., None], axis=-1
        )[..., 0]
        got = selected_logprob(logits, token)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6
        )


def test_rollout_step_keys_is_the_fold_in_chain():
    """The precomputed [T, K] key array is EXACTLY fold_in(fold_in(rng, k),
    t) — the per-step re-fold it replaced, bit-for-bit (satellite of the
    decode fast path: same sampling streams by construction)."""
    rng = jax.random.key(123)
    K, T = 4, 7
    keys = rollout_step_keys(rng, K, T)
    assert keys.shape == (T, K)
    got = jax.random.key_data(keys)
    for t in range(T):
        for k in range(K):
            want = jax.random.key_data(
                jax.random.fold_in(jax.random.fold_in(rng, k), t)
            )
            np.testing.assert_array_equal(np.asarray(got[t, k]), np.asarray(want))


def test_sample_matches_manual_per_step_folding(setup):
    """sample_decode (precomputed key array) decodes bit-identical tokens to
    a manual loop that re-folds the K keys inside every step body."""
    model, params, feats, masks = setup
    K = 3
    rng = jax.random.key(5)
    tokens, _ = sample_decode(model, params, feats, masks, rng, num_rollouts=K)

    enc = model.apply(params, feats, masks, method=CM.encode)
    keys = jax.vmap(lambda k: jax.random.fold_in(rng, k))(jnp.arange(K))
    carry = jax.tree.map(lambda x: jnp.broadcast_to(x[None], (K,) + x.shape), enc.carry)
    tok = jnp.full((K, B), BOS_ID, jnp.int32)
    finished = np.zeros((K, B), bool)
    manual = []
    for t in range(T):
        carry, logits = jax.vmap(
            lambda c, t_: model.apply(params, c, t_, enc, method=CM.decode_step)
        )(carry, tok)
        logits = forbid_special(logits)
        step_keys = jax.vmap(lambda k_: jax.random.fold_in(k_, t))(keys)
        nxt = np.asarray(jax.vmap(
            lambda k_, l_: jax.random.categorical(k_, l_, axis=-1)
        )(step_keys, logits)).astype(np.int32)
        nxt[finished] = PAD_ID
        finished |= nxt == EOS_ID
        manual.append(nxt)
        tok = jnp.asarray(nxt)
    np.testing.assert_array_equal(np.asarray(tokens), np.stack(manual, -1))


def test_fused_decode_matches_two_loop_bitexact(setup):
    """The fused one-loop decode is BIT-EXACT against the two-loop reference
    under a fixed rng: greedy tokens/logprobs (lane 0 vs greedy_decode) and
    sampled tokens/logprobs (lanes 1..K vs sample_decode)."""
    model, params, feats, masks = setup
    K = 3
    rng = jax.random.key(42)
    tg, lg = greedy_decode(model, params, feats, masks)
    ts, ls = sample_decode(model, params, feats, masks, rng, num_rollouts=K)
    fg, flg, fs, fls = fused_decode(
        model, params, feats, masks, rng, num_rollouts=K
    )
    np.testing.assert_array_equal(np.asarray(fg), np.asarray(tg))
    np.testing.assert_array_equal(np.asarray(flg), np.asarray(lg))
    np.testing.assert_array_equal(np.asarray(fs), np.asarray(ts))
    np.testing.assert_array_equal(np.asarray(fls), np.asarray(ls))
    # and under jit, exactly as make_rl_decode dispatches it
    fg2, _, fs2, _ = jax.jit(
        lambda p, f, m, r: fused_decode(model, p, f, m, r, num_rollouts=K)
    )(params, feats, masks, rng)
    np.testing.assert_array_equal(np.asarray(fg2), np.asarray(tg))
    np.testing.assert_array_equal(np.asarray(fs2), np.asarray(ts))


def test_fused_decode_temperature_and_padding(setup):
    """Temperature reaches the sampled lanes only (greedy lane untempered),
    and every lane honors PAD-after-EOS / zero-logprob padding."""
    model, params, feats, masks = setup
    rng = jax.random.key(3)
    ts, _ = sample_decode(
        model, params, feats, masks, rng, num_rollouts=2, temperature=0.5
    )
    fg, flg, fs, fls = fused_decode(
        model, params, feats, masks, rng, num_rollouts=2, temperature=0.5
    )
    tg, _ = greedy_decode(model, params, feats, masks)
    np.testing.assert_array_equal(np.asarray(fs), np.asarray(ts))
    np.testing.assert_array_equal(np.asarray(fg), np.asarray(tg))
    _check_pad_after_eos(fg)
    _check_pad_after_eos(fs)
    assert np.all(np.asarray(fls)[np.asarray(fs) == PAD_ID] == 0.0)
    assert np.all(np.asarray(flg)[np.asarray(fg) == PAD_ID] == 0.0)


def test_min_len_suppresses_early_eos(setup):
    model, params, feats, masks = setup
    tg, _ = greedy_decode(model, params, feats, masks, min_len=3)
    tb, _ = beam_search(model, params, feats, masks, beam_size=3, min_len=3)
    for tokens in (np.asarray(tg), np.asarray(tb)):
        lengths = (tokens != PAD_ID).sum(axis=1)
        assert (lengths >= 3).all(), tokens
        assert not (tokens[:, :2] == EOS_ID).any()


# ---- stride + compaction (decode endgame) -----------------------------------

def test_gumbel_step_noise_is_categorical_bitwise():
    """The Gumbel-max spelling (noise precomputed via gumbel_step_noise,
    argmax outside) is BIT-IDENTICAL to the vmapped jax.random.categorical
    it replaced — the invariant that lets the fused stride paths (and the
    in-kernel selection) reuse the exact sample_decode RNG streams."""
    from cst_captioning_tpu.decoding.common import gumbel_step_noise

    key = jax.random.key(9)
    keys = jax.vmap(lambda k: jax.random.fold_in(key, k))(jnp.arange(4))
    logits = jnp.asarray(
        np.random.default_rng(3).normal(size=(4, 7, 13)) * 5, jnp.float32
    )
    for temp in (1.0, 0.7):
        want = jax.vmap(
            lambda k_, l_: jax.random.categorical(k_, l_ / temp, axis=-1)
        )(keys, logits)
        tl = logits / temp
        noise = gumbel_step_noise(keys, tl.shape[1:], tl.dtype)
        got = jnp.argmax(tl + noise, axis=-1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.fixture(scope="module")
def eos_setup():
    """Like ``setup`` but with the EOS logit nudged up so lanes finish at
    varied steps — random EOS patterns are what compaction must survive."""
    cfg = ModelConfig(
        vocab_size=V,
        modalities=(("resnet", 8),),
        d_embed=12,
        d_hidden=12,
        d_att=6,
        encoder="temporal_attention",
        max_len=T,
        max_frames=F,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(7)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 8)), jnp.float32)}
    masks = {"resnet": jnp.ones((B, F), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, V, size=(B, T)), jnp.int32)
    params = model.init(jax.random.key(1), feats, masks, labels)
    bias = params["params"]["cell"]["out_proj"]["bias"]
    params["params"]["cell"]["out_proj"]["bias"] = bias.at[EOS_ID].add(1.5)
    return model, params, feats, masks


def test_fused_stride_compaction_token_and_logprob_exact(eos_setup):
    """EVERY (stride, compact) combination is bit-equal — tokens AND
    logprobs, greedy AND sampled lanes — to the stride-1 uncompacted loop
    under a fixed rng, across random EOS patterns (lanes finish at varied
    steps, so the compaction permutation is exercised for real). Covers the
    stride-boundary case (S=4 not dividing T=6) and S > T clamping."""
    model, params, feats, masks = eos_setup
    rng = jax.random.key(42)
    K = 3
    ref = fused_decode(
        model, params, feats, masks, rng, num_rollouts=K,
        decode_stride=1, compact=False,
    )
    # sanity: the EOS nudge produced genuinely ragged finishes
    lens = (np.asarray(ref[2]) != PAD_ID).sum(-1)
    assert lens.min() < T or lens.max() == T
    # stride 1 + compact normalizes to the plain loop (fused_decode), so
    # the compacted combinations all have S >= 2
    for stride, compact in [(1, True), (2, True), (3, True), (4, True),
                            (4, False), (6, True), (16, True), (8, True)]:
        got = fused_decode(
            model, params, feats, masks, rng, num_rollouts=K,
            decode_stride=stride, compact=compact,
        )
        for a, b, what in zip(got, ref, ("g", "glp", "s", "slp")):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b),
                err_msg=f"stride={stride} compact={compact} {what}",
            )


def test_fused_stride_default_knobs_from_config(eos_setup):
    """fused_decode reads decode_stride / decode_compact off the model
    config when not overridden — and the config defaults (stride 8,
    compaction on) stay bit-exact vs the explicit stride-1 call."""
    import dataclasses

    model, params, feats, masks = eos_setup
    assert model.cfg.decode_stride == 8 and model.cfg.decode_compact
    rng = jax.random.key(5)
    ref = fused_decode(
        model, params, feats, masks, rng, num_rollouts=2,
        decode_stride=1, compact=False,
    )
    by_default = fused_decode(
        model, params, feats, masks, rng, num_rollouts=2
    )
    m2 = CaptionModel(
        dataclasses.replace(model.cfg, decode_stride=3, decode_compact=False)
    )
    by_cfg = fused_decode(m2, params, feats, masks, rng, num_rollouts=2)
    for got in (by_default, by_cfg):
        for a, b in zip(got, ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fused_stride_under_jit_and_temperature(eos_setup):
    """The strided+compacted loop jits (one compiled program, traced
    while loop) and keeps temperature semantics: sampled lanes tempered,
    greedy lane untempered — still bit-equal to the stride-1 loop."""
    model, params, feats, masks = eos_setup
    rng = jax.random.key(12)
    ref = fused_decode(
        model, params, feats, masks, rng, num_rollouts=2, temperature=0.6,
        decode_stride=1, compact=False,
    )
    got = jax.jit(
        lambda p, f, m, r: fused_decode(
            model, p, f, m, r, num_rollouts=2, temperature=0.6,
            decode_stride=4, compact=True,
        )
    )(params, feats, masks, rng)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    _check_pad_after_eos(got[0])
    _check_pad_after_eos(got[2])


def test_decode_stride_config_validation():
    with pytest.raises(ValueError, match="decode_stride"):
        ModelConfig(decode_stride=0)


# ---------------------------------------------------------------------------
# lane-batched beam (decoding/beam.py beam_impl="lanes") vs the sequential
# reference — the bit-parity contract the eval fast path rests on
# ---------------------------------------------------------------------------


def test_beam_lanes_matches_reference_bit_exact(setup, eos_setup):
    """The lane-batched beam is token- AND score-BIT-exact vs the kept
    ``beam_impl="reference"`` oracle at f32 — same per-lane float programs
    (vmap over lanes vs flat [B*W] batch), same ``row_logprobs`` spelling,
    same flattened ``top_k`` — across beam widths, both EOS regimes (the
    eos_setup rows finish raggedly), and an S-indivisible horizon
    (max_len=11 exercises the scan boundary T % stride != 0). The tier-1
    sweep pins the acceptance width (W=5) on both fixtures and spends
    the ragged-EOS fixture on the remaining axes (scan boundary at 5 and
    3, the W=1 degenerate beam); the full W x T x fixture product rides
    the slow-marked exhaustive twin below — each combo is a fresh scan
    compile, and the product is compile-bound, not assertion-bound."""
    for fix, combos in (
        (setup, ((5, T), (3, T))),
        (eos_setup, ((5, T), (5, 11), (3, 11), (1, T))),
    ):
        model, params, feats, masks = fix
        for W, max_len in combos:
            ref_tok, ref_sc = beam_search(
                model, params, feats, masks, beam_size=W, max_len=max_len,
                beam_impl="reference",
            )
            lane_tok, lane_sc = beam_search(
                model, params, feats, masks, beam_size=W, max_len=max_len,
                beam_impl="lanes",
            )
            np.testing.assert_array_equal(
                np.asarray(lane_tok), np.asarray(ref_tok)
            )
            assert np.asarray(lane_sc).tobytes() == np.asarray(
                ref_sc
            ).tobytes(), f"scores not bit-equal at W={W} T={max_len}"


@pytest.mark.slow
def test_beam_lanes_matches_reference_exhaustive(setup, eos_setup):
    """The full W x max_len x fixture product of the bit-parity pin
    above (slow: 24 scan compiles)."""
    for fix in (setup, eos_setup):
        model, params, feats, masks = fix
        for W, max_len in itertools.product((1, 3, 5), (T, 11)):
            ref_tok, ref_sc = beam_search(
                model, params, feats, masks, beam_size=W, max_len=max_len,
                beam_impl="reference",
            )
            lane_tok, lane_sc = beam_search(
                model, params, feats, masks, beam_size=W, max_len=max_len,
                beam_impl="lanes",
            )
            np.testing.assert_array_equal(
                np.asarray(lane_tok), np.asarray(ref_tok)
            )
            assert np.asarray(lane_sc).tobytes() == np.asarray(
                ref_sc
            ).tobytes(), f"scores not bit-equal at W={W} T={max_len}"


def test_beam_lanes_return_all_matches_reference(eos_setup):
    """``return_all`` surfaces the same W ranked hypotheses from both
    implementations (tokens exact, scores bit-equal) — the lane layout
    transpose back to [B, W, T] loses nothing."""
    model, params, feats, masks = eos_setup
    ref_tok, ref_sc = beam_search(
        model, params, feats, masks, beam_size=4, return_all=True,
        beam_impl="reference",
    )
    lane_tok, lane_sc = beam_search(
        model, params, feats, masks, beam_size=4, return_all=True,
        beam_impl="lanes",
    )
    np.testing.assert_array_equal(np.asarray(lane_tok), np.asarray(ref_tok))
    assert np.asarray(lane_sc).tobytes() == np.asarray(ref_sc).tobytes()


def test_beam_impl_validation():
    with pytest.raises(ValueError, match="beam_impl"):
        beam_search(None, None, None, None, beam_impl="bogus")


def test_npad_anytime_answer_is_monotone_vs_greedy(setup, eos_setup):
    """NPAD's best-sum-logprob lane is >= greedy by construction: lane 0
    IS the greedy rollout and argmax over lane sums can only improve on
    it (arXiv 1605.03835's anytime property). Pinned on both EOS regimes,
    one noise temperature each (below and above 1 — each temperature is
    a fresh rollout compile)."""
    from cst_captioning_tpu.decoding import npad_decode

    for fix, temps in ((setup, (0.7,)), (eos_setup, (1.3,))):
        model, params, feats, masks = fix
        _, g_lp = greedy_decode(model, params, feats, masks)
        g_sum = np.asarray(g_lp.sum(axis=-1))
        for temperature in temps:
            tok, sc = npad_decode(
                model, params, feats, masks, jax.random.key(3),
                num_lanes=4, temperature=temperature,
            )
            assert tok.shape[0] == B and np.asarray(sc).shape == (B,)
            assert np.all(np.asarray(sc) >= g_sum - 1e-6), (
                f"NPAD worse than greedy at temperature={temperature}"
            )
            _check_pad_after_eos(tok)


def test_npad_low_temperature_collapses_to_greedy(setup):
    """In the temperature->0 limit every noisy lane decodes the greedy
    tokens (the ``test_sample_temperature_zero_limit`` contract), their
    recorded logprob sums coincide with the greedy lane's, and the argmax
    tie breaks to lane 0 — so NPAD returns exactly the greedy tokens and
    score. This is the tie-break contract ``npad_best_lane_index`` (and
    the >=-greedy guarantee) relies on."""
    from cst_captioning_tpu.decoding import npad_decode

    model, params, feats, masks = setup
    g_tok, g_lp = greedy_decode(model, params, feats, masks)
    tok, sc = npad_decode(
        model, params, feats, masks, jax.random.key(5), num_lanes=3,
        temperature=1e-4,
    )
    np.testing.assert_array_equal(np.asarray(tok), np.asarray(g_tok))
    np.testing.assert_array_equal(
        np.asarray(sc), np.asarray(g_lp.sum(axis=-1))
    )
