"""Runtime sanitizer gate: the zero-implicit-transfer hot-path claim.

graftlint GL001/GL013 prove *lexically* that the XE/RL step loops never
smuggle a host↔device transfer; these tests pin the same claim *at
runtime*. Setup (model init, optimizer build, eager constant staging) runs
UNGUARDED — exactly like production, where setup transfers are amortized —
and then the epoch hot loop runs inside ``jax.transfer_guard("disallow")``
+ ``jax.debug_nans``: any batch fed to a jitted step without an explicit
``device_put``, any eager scalar promotion inside the loop, and any NaN
update blows the test up.

``scripts/sanitize.sh`` drives this file (plus the blanket-guarded
``tests/test_data.py`` prefetch staging tests) with ``pytest --sanitize``;
without the flag the guard is a no-op and the tests double as plain
integration smoke, keeping the code path warm in tier-1.

The module is marked ``no_sanitize`` because the ``hot_guard`` fixture
scopes the guard itself: blanket-guarding the whole test would veto the
eager model init that setup legitimately performs.
"""

import contextlib
import json

import pytest

import jax

from cst_captioning_tpu.config.config import (
    DataConfig,
    EvalConfig,
    ExperimentConfig,
    ModelConfig,
    RLConfig,
    TrainConfig,
)
from cst_captioning_tpu.data import CaptionDataset, make_synthetic_dataset
from cst_captioning_tpu.train.trainer import Trainer

pytestmark = pytest.mark.no_sanitize


@pytest.fixture
def hot_guard(request):
    """Context-manager factory: the sanitizer clamp when --sanitize is on,
    a no-op otherwise."""
    if request.config.getoption("--sanitize"):
        @contextlib.contextmanager
        def guard():
            with jax.transfer_guard("disallow"), jax.debug_nans(True):
                yield

        return guard
    return contextlib.nullcontext


@pytest.fixture(scope="module")
def sanitize_datasets(tmp_path_factory):
    out = tmp_path_factory.mktemp("sanitize_synth")
    synth = make_synthetic_dataset(
        str(out), num_videos=8, num_topics=2, vocab_words=18,
        modalities={"resnet": 12}, max_frames=3, seed=7,
    )
    train = CaptionDataset(
        synth["info_json"], {"resnet": synth["resnet"]}, "train", 3
    )
    val = CaptionDataset(
        synth["info_json"], {"resnet": synth["resnet"]}, "val", 3
    )
    return train, val


def _cfg(ckpt_dir: str, vocab_size: int) -> ExperimentConfig:
    return ExperimentConfig(
        name="sanitize",
        model=ModelConfig(
            vocab_size=vocab_size, modalities=(("resnet", 12),),
            d_embed=12, d_hidden=12, d_att=8,
            encoder="temporal_attention", dropout=0.0,
            max_len=8, max_frames=3, dtype="float32",
        ),
        data=DataConfig(batch_size=4, seq_per_vid=2),
        train=TrainConfig(
            lr=5e-3, epochs=2, grad_clip=5.0, ckpt_dir=ckpt_dir,
            eval_every_epochs=0, seed=0,
        ),
        rl=RLConfig(enabled=True, num_rollouts=2, lr=1e-3, epochs=1),
        eval=EvalConfig(beam_size=1, max_len=8),
    )


def test_xe_hot_loop_runs_clean_under_transfer_guard(
    sanitize_datasets, tmp_path_factory, hot_guard
):
    """Two full XE epochs (prefetch → sharded placement → jitted step →
    deferred readback) with zero implicit transfers and zero NaNs."""
    train_ds, _ = sanitize_datasets
    ckpt_dir = str(tmp_path_factory.mktemp("sanitize_xe"))
    log_path = ckpt_dir + "/events.jsonl"
    cfg = _cfg(ckpt_dir, len(train_ds.vocab))
    tr = Trainer(cfg, train_ds, None, log_path=log_path, use_mesh=False)
    with hot_guard():
        tr.train_xe()
    events = [json.loads(l) for l in open(log_path)]
    losses = [e["loss"] for e in events if e["event"] == "xe_epoch"]
    assert len(losses) == cfg.train.epochs
    assert all(l == l for l in losses), "non-finite XE loss"


def test_rl_hot_loop_runs_clean_under_transfer_guard(
    sanitize_datasets, tmp_path_factory, hot_guard
):
    """One SCST epoch (fused rollout decode → host reward → advantage
    upload → jitted update) under the same clamp: the decode→reward seam
    may read back EXPLICITLY, but nothing may transfer implicitly."""
    train_ds, _ = sanitize_datasets
    ckpt_dir = str(tmp_path_factory.mktemp("sanitize_rl"))
    log_path = ckpt_dir + "/events.jsonl"
    cfg = _cfg(ckpt_dir, len(train_ds.vocab))
    tr = Trainer(cfg, train_ds, None, log_path=log_path, use_mesh=False)
    tr.train_xe()  # unguarded warm start: RL resumes from XE params
    with hot_guard():
        tr.train_rl()
    events = [json.loads(l) for l in open(log_path)]
    rewards = [e["reward"] for e in events if e["event"] == "rl_epoch"]
    assert len(rewards) == cfg.rl.epochs
    assert all(r == r for r in rewards), "non-finite RL reward"


def test_mesh_hot_loops_run_clean_under_transfer_guard(
    sanitize_datasets, tmp_path_factory, hot_guard
):
    """The 8-fake-device mesh path: sharded batch placement, replicated
    epoch keys, and the sharded advantage upload must all be EXPLICIT
    placements — a single-device key or advantage would be re-scattered
    device-to-device on every dispatch (the regression this test pins)."""
    import dataclasses

    train_ds, _ = sanitize_datasets
    ckpt_dir = str(tmp_path_factory.mktemp("sanitize_mesh"))
    log_path = ckpt_dir + "/events.jsonl"
    cfg = _cfg(ckpt_dir, len(train_ds.vocab))
    cfg = dataclasses.replace(
        cfg, data=dataclasses.replace(cfg.data, batch_size=8)
    )
    tr = Trainer(cfg, train_ds, None, log_path=log_path, use_mesh=True)
    with hot_guard():
        tr.train_xe()
        tr.train_rl()
    events = [json.loads(l) for l in open(log_path)]
    losses = [e["loss"] for e in events if e["event"] == "xe_epoch"]
    rewards = [e["reward"] for e in events if e["event"] == "rl_epoch"]
    assert len(losses) == cfg.train.epochs and len(rewards) == cfg.rl.epochs
    assert all(x == x for x in losses + rewards)
