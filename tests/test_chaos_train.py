"""Chaos-driven trainer tests: the resilience acceptance criteria.

Every scenario here is a seeded :class:`FaultPlan` driving the injection
points compiled into the trainer/ckpt/rl hot paths (resilience/chaos.py):

- SIGTERM mid-epoch -> mid-epoch save -> resume -> bit-identical to the
  uninterrupted run (params AND per-step losses);
- NaN-poisoned batch under ``skip_batch`` -> epoch completes with the batch
  excluded (step counter excludes it, params stay finite);
- ``rollback`` -> last-good checkpoint restored, data order re-salted, run
  completes; ``abort`` -> TrainingDiverged;
- truncated ``state.msgpack`` -> manifest checksum detects it, the previous
  checkpoint is restored, a ``ckpt_corrupt`` event is logged;
- transient reward-scorer failures -> retried with logged ``reward_retry``.
"""

import dataclasses
import json
import os

import jax
import numpy as np
import pytest

from cst_captioning_tpu.config.config import (
    DataConfig,
    EvalConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    RLConfig,
    TrainConfig,
)
from cst_captioning_tpu.data import CaptionDataset, make_synthetic_dataset
from cst_captioning_tpu.resilience import (
    Fault,
    FaultPlan,
    PeerLost,
    Preempted,
    TrainingDiverged,
)
from cst_captioning_tpu.train.trainer import Trainer


@pytest.fixture(scope="module")
def synth_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("chaossynth")
    return make_synthetic_dataset(
        str(out),
        num_videos=12,
        num_topics=3,
        vocab_words=20,
        modalities={"resnet": 16},
        max_frames=4,
        seed=5,
    )


@pytest.fixture(scope="module")
def datasets(synth_dir):
    train = CaptionDataset(
        synth_dir["info_json"], {"resnet": synth_dir["resnet"]}, "train", 4
    )
    val = CaptionDataset(
        synth_dir["info_json"], {"resnet": synth_dir["resnet"]}, "val", 4
    )
    return train, val


def make_cfg(ckpt_dir: str, vocab_size: int, *, pipelined: bool = False,
             batch_size: int = 8, seq_per_vid: int = 2, num_devices: int = 0,
             rl_epochs: int = 2, **train_kw) -> ExperimentConfig:
    train_kw.setdefault("eval_every_epochs", 100)
    train_kw.setdefault("epochs", 2)
    return ExperimentConfig(
        name="chaos",
        model=ModelConfig(
            vocab_size=vocab_size,
            modalities=(("resnet", 16),),
            d_embed=16,
            d_hidden=16,
            d_att=8,
            encoder="temporal_attention",
            dropout=0.0,
            max_len=8,
            max_frames=4,
            dtype="float32",
        ),
        data=DataConfig(batch_size=batch_size, seq_per_vid=seq_per_vid),
        train=TrainConfig(
            lr=5e-3, grad_clip=5.0, ckpt_dir=ckpt_dir, seed=0,
            log_every_steps=1, **train_kw,
        ),
        rl=RLConfig(
            enabled=True, num_rollouts=2, lr=1e-3, epochs=rl_epochs,
            baseline="greedy", pipelined=pipelined,
        ),
        eval=EvalConfig(beam_size=1, max_len=8),
        mesh=MeshConfig(num_devices=num_devices),
    )


def events_of(log_path, kind):
    return [
        e for e in (json.loads(l) for l in open(log_path))
        if e["event"] == kind
    ]


def params_equal(a, b):
    for x, y in zip(
        jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# 12 videos x seq_per_vid=2 = 24 rows / batch_size 8 = 3 XE batches per epoch
STEPS_PER_EPOCH = 3


def test_sigterm_mid_epoch_resume_is_bit_identical(datasets, tmp_path_factory):
    """ISSUE acceptance #1: kill mid-epoch via chaos plan, resume, per-step
    losses and final params match the uninterrupted run bit-for-bit."""
    train_ds, _ = datasets
    d1 = str(tmp_path_factory.mktemp("straight"))
    d2 = str(tmp_path_factory.mktemp("preempted"))

    cfg1 = make_cfg(d1, len(train_ds.vocab))
    tr_straight = Trainer(cfg1, train_ds, None, log_path=d1 + "/ev.jsonl",
                          use_mesh=False)
    tr_straight.train_xe()

    # SIGTERM lands after step 5 = batch 2 of epoch 2 (0-based visit 4)
    cfg2 = make_cfg(d2, len(train_ds.vocab))
    tr_kill = Trainer(cfg2, train_ds, None, log_path=d2 + "/ev.jsonl",
                      use_mesh=False)
    plan = FaultPlan([Fault("xe.step", "preempt", at=STEPS_PER_EPOCH + 1)])
    with plan.activate():
        with pytest.raises(Preempted):
            tr_kill.train_xe()
    assert plan.fired and plan.fired[0]["kind"] == "preempt"
    assert events_of(d2 + "/ev.jsonl", "preempt")[0]["batch_index"] == 2
    # the mid-epoch checkpoint recorded the exact position
    step_dirs = [n for n in os.listdir(d2) if n.startswith("step_")]
    assert len(step_dirs) == 1
    infos = json.load(open(os.path.join(d2, step_dirs[0], "infos.json")))
    assert infos["phase"] == "xe" and infos["batch_index"] == 2
    assert infos["xe_epochs"] == 1  # one COMPLETED epoch

    # rerun the same command with resume: replays the epoch remainder
    cfg_resume = dataclasses.replace(
        cfg2, train=dataclasses.replace(cfg2.train, resume="auto")
    )
    tr_res = Trainer(cfg_resume, train_ds, None, log_path=d2 + "/ev2.jsonl",
                     use_mesh=False)
    assert tr_res._resume_batch == 2
    tr_res.train_xe()

    assert tr_res.xe_epochs == tr_straight.xe_epochs == 2
    assert int(tr_res.state.step) == int(tr_straight.state.step)
    params_equal(tr_straight.state.params, tr_res.state.params)

    # per-step losses: pre-kill steps 1-5 + resumed step 6 == straight 1-6
    straight = {
        e["step"]: e["loss"] for e in events_of(d1 + "/ev.jsonl", "xe_step")
    }
    chaos_run = {
        e["step"]: e["loss"] for e in events_of(d2 + "/ev.jsonl", "xe_step")
    }
    chaos_run.update({
        e["step"]: e["loss"] for e in events_of(d2 + "/ev2.jsonl", "xe_step")
    })
    assert chaos_run == straight  # bit-for-bit (json round-trips repr floats)


def test_nan_batch_skipped_epoch_completes(datasets, tmp_path_factory):
    """ISSUE acceptance #2: a NaN-poisoned batch under skip_batch completes
    the epoch with the batch excluded."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("nanskip"))
    cfg = make_cfg(d, len(train_ds.vocab), epochs=1)
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl", use_mesh=False)
    plan = FaultPlan([Fault("xe.batch", "nan", at=1)])
    with plan.activate():
        tr.train_xe()
    assert tr.xe_epochs == 1
    # the poisoned batch is EXCLUDED: the device-side guard suppressed its
    # update, so the step counter advanced for 2 of the 3 batches only
    assert int(tr.state.step) == STEPS_PER_EPOCH - 1
    for leaf in jax.tree_util.tree_leaves(tr.state.params):
        assert np.isfinite(np.asarray(leaf)).all()
    div = events_of(d + "/ev.jsonl", "divergence")
    assert len(div) == 1
    assert div[0]["kind"] == "nonfinite" and div[0]["action"] == "skip_batch"
    # the epoch summary excludes the NaN loss scalar too
    (ep,) = events_of(d + "/ev.jsonl", "xe_epoch")
    assert np.isfinite(ep["loss"])


def test_nan_batch_abort_policy_raises(datasets, tmp_path_factory):
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("nanabort"))
    cfg = make_cfg(d, len(train_ds.vocab), epochs=1, on_divergence="abort")
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl", use_mesh=False)
    with FaultPlan([Fault("xe.batch", "nan", at=1)]).activate():
        with pytest.raises(TrainingDiverged):
            tr.train_xe()


def test_nan_batch_rollback_restores_and_resalts(datasets, tmp_path_factory):
    """Divergence in epoch 2 under rollback: restore the epoch-1 checkpoint,
    re-randomize the order (salt), and still finish the full budget."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("nanroll"))
    cfg = make_cfg(d, len(train_ds.vocab), on_divergence="rollback")
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl", use_mesh=False)
    # poison one batch of epoch 2 (visits 3..5); the replayed (salted) epoch
    # uses later visit indices, so the poison does not re-fire
    with FaultPlan([Fault("xe.batch", "nan", at=STEPS_PER_EPOCH + 1)]).activate():
        tr.train_xe()
    assert tr.xe_epochs == 2 and tr.epoch == 2
    assert tr.batcher.salt == 1
    (rb,) = events_of(d + "/ev.jsonl", "rollback")
    assert rb["restored_epoch"] == 1 and rb["salt"] == 1
    for leaf in jax.tree_util.tree_leaves(tr.state.params):
        assert np.isfinite(np.asarray(leaf)).all()


def test_truncated_checkpoint_resume_falls_back(datasets, tmp_path_factory):
    """ISSUE acceptance #3: a truncated state.msgpack is caught by the
    manifest checksum; resume logs ckpt_corrupt and restores the previous
    checkpoint."""
    train_ds, val_ds = datasets
    d = str(tmp_path_factory.mktemp("trunc"))
    cfg = make_cfg(d, len(train_ds.vocab), epochs=1, eval_every_epochs=1)
    Trainer(cfg, train_ds, val_ds, use_mesh=False).train_xe()  # latest + best
    sp = os.path.join(d, "latest", "state.msgpack")
    with open(sp, "r+b") as f:
        f.truncate(os.path.getsize(sp) // 2)

    cfg_resume = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, resume="auto")
    )
    tr = Trainer(cfg_resume, train_ds, None, log_path=d + "/ev.jsonl",
                 use_mesh=False)
    assert tr.epoch == 1  # restored (from 'best') despite the corrupt latest
    (ev,) = events_of(d + "/ev.jsonl", "ckpt_corrupt")
    assert ev["name"] == "latest"
    assert ev["error"] == "CorruptCheckpointError"
    assert "state.msgpack" in ev["detail"]
    assert events_of(d + "/ev.jsonl", "resume")


def test_step_interval_checkpoints_rotate(datasets, tmp_path_factory):
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("interval"))
    cfg = make_cfg(
        d, len(train_ds.vocab), ckpt_every_steps=2, keep_ckpts=2,
    )
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl", use_mesh=False)
    tr.train_xe()  # 6 steps -> saves at 2, 4, 6; rotation keeps the last 2
    assert [s for s, _ in tr.ckpt.step_checkpoints()] == [4, 6]
    saves = events_of(d + "/ev.jsonl", "ckpt_step")
    assert [e["step"] for e in saves] == [2, 4, 6]
    # batch_index recorded relative to the epoch (3 steps per epoch)
    assert [e["batch_index"] for e in saves] == [2, 1, 3]


def test_rl_preemption_strict_resume_is_bit_identical(datasets, tmp_path_factory):
    """RL twin of the SIGTERM parity test, in strict (pipelined=False) mode:
    preempt mid-RL-epoch, resume, final params match the uninterrupted run
    bit-for-bit (batch order, sampling rng chain, and optimizer moments all
    continue mid-epoch)."""
    train_ds, _ = datasets
    d1 = str(tmp_path_factory.mktemp("rlstraight"))
    d2 = str(tmp_path_factory.mktemp("rlpreempt"))

    def run(ckpt_dir, resume=""):
        cfg = make_cfg(ckpt_dir, len(train_ds.vocab), epochs=1, resume=resume)
        tr = Trainer(cfg, train_ds, None, log_path=ckpt_dir + "/ev.jsonl",
                     use_mesh=False)
        tr.train_xe()
        tr.train_rl()
        return tr

    tr_straight = run(d1)

    # 12 videos / batch 8 = 2 RL batches/epoch; preempt in epoch 2 batch 1
    # (0-based visit 2 of rl.step)
    with FaultPlan([Fault("rl.step", "preempt", at=2)]).activate():
        with pytest.raises(Preempted):
            run(d2)
    saves = events_of(d2 + "/ev.jsonl", "ckpt_step")
    assert saves and saves[-1]["phase"] == "rl"
    assert saves[-1]["batch_index"] == 1

    tr_res = run(d2, resume="auto")
    assert tr_res.rl_epochs == tr_straight.rl_epochs == 2
    assert int(tr_res.state.step) == int(tr_straight.state.step)
    params_equal(tr_straight.state.params, tr_res.state.params)


def test_transient_reward_failures_are_retried(datasets, tmp_path_factory):
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("rewardretry"))
    cfg = make_cfg(d, len(train_ds.vocab), epochs=1)
    cfg = dataclasses.replace(cfg, rl=dataclasses.replace(cfg.rl, epochs=1))
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl", use_mesh=False)
    tr.train_xe()
    with FaultPlan([Fault("reward.call", "io_error", at=0, times=1)]).activate():
        tr.train_rl()
    assert tr.rl_epochs == 1
    retries = events_of(d + "/ev.jsonl", "reward_retry")
    assert len(retries) == 1 and retries[0]["error"] == "TransientIOError"


# ---- elastic resilience: seam parity, partial preemption, degraded mesh -----


def test_rl_pipelined_preempt_seam_resume_is_bit_identical(datasets,
                                                           tmp_path_factory):
    """Drain-aware save order (ISSUE 6 satellite #1): preempting the
    PIPELINED RL loop mid-epoch persists the decoded-but-unscored seam batch
    next to the checkpoint; the resumed run replays those tokens, so per-step
    rewards/losses and final params match the uninterrupted pipelined run
    bit-for-bit (previously the seam batch was re-decoded against params one
    update fresher)."""
    train_ds, _ = datasets
    d1 = str(tmp_path_factory.mktemp("seamstraight"))
    d2 = str(tmp_path_factory.mktemp("seampreempt"))

    def run(ckpt_dir, resume=""):
        # batch_size 2 -> 5 RL batches/epoch (10 train videos): deep
        # enough that the stop
        # lands mid-pipeline (2 in flight) instead of at the epoch boundary
        cfg = make_cfg(ckpt_dir, len(train_ds.vocab), pipelined=True,
                       batch_size=2, seq_per_vid=1, epochs=1, resume=resume)
        tr = Trainer(cfg, train_ds, None, log_path=ckpt_dir + "/ev.jsonl",
                     use_mesh=False)
        tr.train_xe()
        tr.train_rl()
        return tr

    tr_straight = run(d1)

    # 5 rl.step visits per epoch; visit 6 = the second update emitted in
    # epoch 2 -> the loop stops at the NEXT iteration top, mid-pipeline
    with FaultPlan([Fault("rl.step", "preempt", at=6)]).activate():
        with pytest.raises(Preempted):
            run(d2)
    saves = events_of(d2 + "/ev.jsonl", "ckpt_step")
    assert saves and saves[-1]["phase"] == "rl"
    assert 0 < saves[-1]["batch_index"] < 5  # genuinely mid-epoch
    assert saves[-1]["seam"] is True
    step_dirs = [n for n in os.listdir(d2) if n.startswith("step_")]
    assert any(
        os.path.exists(os.path.join(d2, s, "seam.npz")) for s in step_dirs
    )

    tr_res = run(d2, resume="auto")
    assert events_of(d2 + "/ev.jsonl", "seam_loaded")
    assert tr_res.rl_epochs == tr_straight.rl_epochs == 2
    assert int(tr_res.state.step) == int(tr_straight.state.step)
    params_equal(tr_straight.state.params, tr_res.state.params)

    # the per-step reward/loss streams agree bit-for-bit across the seam
    def rl_steps(*paths):
        out = {}
        for p in paths:
            if os.path.exists(p):
                for e in events_of(p, "rl_step"):
                    out[e["step"]] = (e["reward"], e["rl_loss"])
        return out

    straight = rl_steps(d1 + "/ev.jsonl")
    chaosrun = rl_steps(d2 + "/ev.jsonl", d2 + "/ev2.jsonl")
    # the resumed process logs into ev.jsonl again (same path): both runs'
    # events are in d2/ev.jsonl; dedup by step keeps the comparison exact
    assert chaosrun == straight


def test_partial_preempt_xe_strict_drains_and_raises(datasets,
                                                     tmp_path_factory):
    """partial_preempt during XE under elastic='strict': drain -> durable
    save -> PeerLost (today's abort-and-full-restart semantics)."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("xepartial"))
    cfg = make_cfg(d, len(train_ds.vocab), epochs=2, health=True,
                   health_sim_hosts=2)
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl",
                 use_mesh=False)
    try:
        plan = FaultPlan(
            [Fault("xe.step", "partial_preempt", at=STEPS_PER_EPOCH, host=1)]
        )
        with plan.activate():
            with pytest.raises(PeerLost) as ei:
                tr.train_xe()
        assert ei.value.hosts == [1]
        (drain,) = events_of(d + "/ev.jsonl", "peer_loss_drain")
        assert drain["phase"] == "xe" and drain["lost"] == [1]
        assert events_of(d + "/ev.jsonl", "peer_lost")
        # the drain saved a restorable mid-epoch checkpoint
        assert [n for n in os.listdir(d) if n.startswith("step_")]
    finally:
        tr.close()


def test_partial_preempt_strict_full_mesh_restart_is_bit_exact(
        datasets, tmp_path_factory):
    """ISSUE 6 acceptance (strict half): losing 1 of 2 simulated hosts
    mid-RL-epoch drains + saves; the strict fallback aborts, and a FULL-mesh
    restart resumes bit-exactly (params match the uninterrupted 2-device
    run)."""
    train_ds, _ = datasets
    d1 = str(tmp_path_factory.mktemp("strictstraight"))
    d2 = str(tmp_path_factory.mktemp("strictpartial"))

    def run(ckpt_dir, resume="", health=True, health_dir=""):
        cfg = make_cfg(ckpt_dir, len(train_ds.vocab), epochs=1,
                       num_devices=2, resume=resume, health=health,
                       health_sim_hosts=2, health_dir=health_dir)
        tr = Trainer(cfg, train_ds, None, log_path=ckpt_dir + "/ev.jsonl")
        try:
            tr.train_xe()
            tr.train_rl()
        finally:
            tr.close()
        return tr

    tr_straight = run(d1)

    # 2 RL batches/epoch -> visit 2 is epoch 2's first step; the strict loop
    # stops at the next batch boundary, drains, saves, raises PeerLost
    with FaultPlan(
        [Fault("rl.step", "partial_preempt", at=2, host=1)]
    ).activate():
        with pytest.raises(PeerLost):
            run(d2)
    (drain,) = events_of(d2 + "/ev.jsonl", "peer_loss_drain")
    assert drain["phase"] == "rl" and drain["batch_index"] == 1

    # full-mesh restart (a fresh health incarnation: the old tombstone
    # belongs to the dead cluster generation)
    tr_res = run(d2, resume="auto",
                 health_dir=str(tmp_path_factory.mktemp("hb2")))
    assert tr_res.rl_epochs == tr_straight.rl_epochs == 2
    assert int(tr_res.state.step) == int(tr_straight.state.step)
    params_equal(tr_straight.state.params, tr_res.state.params)


def test_partial_preempt_degraded_mesh_continuation(datasets,
                                                    tmp_path_factory):
    """ISSUE 6 acceptance (degraded half): killing 1 of 2 simulated hosts
    mid-RL-epoch triggers drain -> durable save -> survivor rendezvous ->
    shrunk 1-device mesh with optimizer state resharded from the drained
    checkpoint -> training continues in the SAME process: reward trajectory
    stays finite, every epoch completes, no epoch is skipped."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("degraded"))
    cfg = make_cfg(d, len(train_ds.vocab), pipelined=True, batch_size=2,
                   seq_per_vid=1, epochs=1, num_devices=2, health=True,
                   health_sim_hosts=2, elastic="degraded")
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl")
    try:
        tr.train_xe()
        assert tr.mesh is not None and tr.mesh.devices.size == 2
        # 5 RL batches/epoch (10 train videos); visit 6 = the second
        # update emitted in epoch 2 -> peer loss lands mid-epoch,
        # mid-pipeline
        plan = FaultPlan(
            [Fault("rl.step", "partial_preempt", at=6, host=1)]
        )
        with plan.activate():
            tr.train_rl()  # survives: drain + degraded continuation inside
        assert [f["kind"] for f in plan.fired] == ["partial_preempt"]

        # the run finished its full budget on the shrunk mesh
        assert tr.rl_epochs == 2
        assert tr.mesh is not None and tr.mesh.devices.size == 1
        assert tr.health.survivors() == [0]

        (drain,) = events_of(d + "/ev.jsonl", "peer_loss_drain")
        assert drain["phase"] == "rl" and 0 < drain["batch_index"] < 5
        (deg,) = events_of(d + "/ev.jsonl", "degraded_mesh")
        assert deg["lost"] == [1] and deg["survivors"] == [0]
        assert deg["devices"] == 1 and deg["resumed_phase"] == "rl"

        # trajectory continues: every RL epoch reports, rewards stay finite,
        # the step clock never rewinds or skips
        rl_eps = events_of(d + "/ev.jsonl", "rl_epoch")
        assert [e["epoch"] for e in rl_eps] == [2, 3]
        assert all(np.isfinite(e["reward"]) for e in rl_eps)
        steps = [e["step"] for e in events_of(d + "/ev.jsonl", "rl_step")]
        assert sorted(set(steps)) == list(range(1, 11))  # 2 epochs x 5 steps
        rewards = [
            e["reward"] for e in events_of(d + "/ev.jsonl", "rl_step")
        ]
        losses = [
            e["rl_loss"] for e in events_of(d + "/ev.jsonl", "rl_step")
        ]
        assert np.isfinite(rewards).all() and np.isfinite(losses).all()
        for leaf in jax.tree_util.tree_leaves(tr.state.params):
            assert np.isfinite(np.asarray(leaf)).all()
        # the drained seam was replayed, not re-decoded
        assert events_of(d + "/ev.jsonl", "seam_loaded")
    finally:
        tr.close()


def test_partial_preempt_then_rejoin_regrows_full_mesh(datasets,
                                                       tmp_path_factory):
    """ISSUE 17 acceptance (grow-back half): after the degraded-mesh
    continuation, the lost host announces recovery, is validated, and is
    re-admitted at the next batch boundary — drain -> durable save -> full
    rendezvous -> rebuilt 2-device mesh with the rejoiner's state
    replicated from the survivors' drained checkpoint (never its stale
    one). The run finishes its full budget on the FULL mesh with a
    contiguous step clock, and the post-regrow step program is
    bit-identical to a never-degraded trainer resumed from the same
    checkpoint (one-epoch params + opt_state comparison)."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("regrown"))
    cfg = make_cfg(d, len(train_ds.vocab), pipelined=True, batch_size=2,
                   seq_per_vid=1, epochs=1, num_devices=2, health=True,
                   health_sim_hosts=2, elastic="degraded")
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl")
    try:
        tr.train_xe()
        # visit 0 of rl.step = the very first RL step, so the pipelined
        # drain lands mid-epoch (seam) and most of the budget runs AFTER
        # the regrow; visit 0 of health.rejoin = the first poll after the
        # degraded continuation announces host 1's recovery
        plan = FaultPlan([
            Fault("rl.step", "partial_preempt", at=0, host=1),
            Fault("health.rejoin", "host_rejoin", at=0, host=1),
        ])
        with plan.activate():
            tr.train_rl()  # shrinks, then regrows, inside
        assert [f["kind"] for f in plan.fired] == [
            "partial_preempt", "host_rejoin",
        ]

        # the run finished its full budget back on the FULL mesh
        assert tr.rl_epochs == 2
        assert tr.mesh is not None and tr.mesh.devices.size == 2
        assert tr.health.survivors() == [0, 1]
        assert tr.health.generation == 2  # shrink bumped to 1, regrow to 2

        (deg,) = events_of(d + "/ev.jsonl", "degraded_mesh")
        assert deg["lost"] == [1]
        (rd,) = events_of(d + "/ev.jsonl", "regrow_drain")
        assert rd["phase"] == "rl" and rd["rejoiner"] == 1
        (rg,) = events_of(d + "/ev.jsonl", "mesh_regrow")
        assert rg["rejoiner"] == 1 and rg["devices"] == 2
        assert rg["hosts"] == [0, 1] and rg["generation"] == 2
        assert not events_of(d + "/ev.jsonl", "regrow_refused")

        # trajectory: every epoch reports, the step clock never rewinds or
        # skips through shrink OR regrow, dynamics stay finite
        rl_eps = events_of(d + "/ev.jsonl", "rl_epoch")
        assert [e["epoch"] for e in rl_eps] == [2, 3]
        steps = [e["step"] for e in events_of(d + "/ev.jsonl", "rl_step")]
        assert sorted(set(steps)) == list(range(1, 11))
        rewards = [
            e["reward"] for e in events_of(d + "/ev.jsonl", "rl_step")
        ]
        assert np.isfinite(rewards).all()
        for leaf in jax.tree_util.tree_leaves(tr.state.params):
            assert np.isfinite(np.asarray(leaf)).all()

        # program-identity pin: a fresh never-degraded trainer resumed from
        # the same checkpoint runs one more epoch bit-identically to the
        # regrown in-memory trainer (params AND opt_state)
        cfg2 = make_cfg(d, len(train_ds.vocab), pipelined=True, batch_size=2,
                        seq_per_vid=1, epochs=1, num_devices=2, resume="auto")
        tr2 = Trainer(cfg2, train_ds, None, log_path=d + "/ev2.jsonl")
        assert tr2.epoch == tr.epoch
        assert int(tr2.state.step) == int(tr.state.step)
        params_equal(tr.state.params, tr2.state.params)
        tr.train_rl(epochs=1)
        tr2.train_rl(epochs=1)
        params_equal(tr.state.params, tr2.state.params)
        params_equal(tr.state.opt_state, tr2.state.opt_state)
    finally:
        tr.close()


def test_flaky_rejoin_leaves_degraded_run_unharmed(datasets,
                                                   tmp_path_factory):
    """A rejoiner that announces recovery and then dies mid-rendezvous
    (``host_rejoin_flaky``) must not damage the degraded run: the
    survivors time out the regrow rendezvous, refuse the admission, and
    continue on the shrunk mesh with params bit-identical to a run where
    no rejoin was ever attempted."""
    train_ds, _ = datasets

    def run(d, extra_faults):
        cfg = make_cfg(d, len(train_ds.vocab), pipelined=True, batch_size=2,
                       seq_per_vid=1, epochs=1, num_devices=2, health=True,
                       health_sim_hosts=2, elastic="degraded",
                       peer_timeout_s=0.2)  # fast rendezvous timeout
        tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl")
        try:
            tr.train_xe()
            plan = FaultPlan(
                [Fault("rl.step", "partial_preempt", at=0, host=1)]
                + extra_faults
            )
            with plan.activate():
                tr.train_rl()
            assert tr.rl_epochs == 2
            return tr, jax.device_get(tr.state.params)
        finally:
            tr.close()

    d_plain = str(tmp_path_factory.mktemp("norejoins"))
    d_flaky = str(tmp_path_factory.mktemp("flakyrejoin"))
    _, params_plain = run(d_plain, [])
    tr_b, params_flaky = run(d_flaky, [
        Fault("health.rejoin", "host_rejoin_flaky", at=0, host=1),
    ])

    # the flaky run drained for the admission, timed out, refused it, and
    # stayed degraded for its whole remaining budget
    assert events_of(d_flaky + "/ev.jsonl", "regrow_drain")
    (ref,) = events_of(d_flaky + "/ev.jsonl", "regrow_refused")
    assert ref["rejoiner"] == 1
    assert not events_of(d_flaky + "/ev.jsonl", "mesh_regrow")
    assert tr_b.mesh is not None and tr_b.mesh.devices.size == 1
    assert tr_b.health.survivors() == [0]
    steps = [e["step"] for e in events_of(d_flaky + "/ev.jsonl", "rl_step")]
    assert sorted(set(steps)) == list(range(1, 11))
    # the failed admission left the trajectory untouched
    params_equal(params_plain, params_flaky)


def test_enospc_during_training_rotation_recovers(datasets, tmp_path_factory):
    """ENOSPC mid-run: the step-interval save reclaims the oldest step_*
    generation, retries, and training never notices."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("enospc"))
    cfg = make_cfg(d, len(train_ds.vocab), ckpt_every_steps=2, keep_ckpts=2)
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl", use_mesh=False)
    # saves land at steps 2, 4, 6 -> ckpt.save visits 0, 1, 2; the disk
    # "fills up" at the third save and recovers by deleting the oldest gen
    with FaultPlan(
        [Fault("ckpt.save", "enospc_rotation", at=2, times=1)]
    ).activate():
        tr.train_xe()
    assert tr.xe_epochs == 2
    (ev,) = events_of(d + "/ev.jsonl", "ckpt_enospc")
    assert ev["freed"] == ["step_00000002"]
    assert [s for s, _ in tr.ckpt.step_checkpoints()] == [4, 6]


# ---- decoupled actor/learner topology ---------------------------------------


@pytest.mark.slow
def test_decoupled_preempt_ring_seam_resume_is_bit_identical(
        datasets, tmp_path_factory):
    """Decoupled-topology twin of the pipelined seam test: preempting the
    actor/learner loop mid-epoch persists the in-flight rollout RING next
    to the checkpoint; the resume replays those exact tokens. With shared
    roles (use_mesh=False) the default depth-2/bound-1 ring IS the sync
    1-deep pipeline, so the whole chain — straight decoupled, preempted +
    resumed decoupled, straight pipelined sync — lands on bit-identical
    params."""
    train_ds, _ = datasets
    d0 = str(tmp_path_factory.mktemp("decsync"))
    d1 = str(tmp_path_factory.mktemp("decstraight"))
    d2 = str(tmp_path_factory.mktemp("decpreempt"))

    def run(ckpt_dir, resume="", topology="decoupled"):
        cfg = make_cfg(ckpt_dir, len(train_ds.vocab), pipelined=True,
                       batch_size=2, seq_per_vid=1, epochs=1, resume=resume,
                       rl_topology=topology)
        tr = Trainer(cfg, train_ds, None, log_path=ckpt_dir + "/ev.jsonl",
                     use_mesh=False)
        tr.train_xe()
        tr.train_rl()
        return tr

    tr_sync = run(d0, topology="sync")
    tr_straight = run(d1)
    # shared roles + depth 2 + bound 1 replays the sync pipelined schedule
    params_equal(tr_sync.state.params, tr_straight.state.params)

    # 5 rl.step visits per epoch; visit 6 = the second update of epoch 2
    # -> the stop lands with a decoded-but-unscored ring entry in flight
    with FaultPlan([Fault("rl.step", "preempt", at=6)]).activate():
        with pytest.raises(Preempted):
            run(d2)
    saves = events_of(d2 + "/ev.jsonl", "ckpt_step")
    assert saves and saves[-1]["phase"] == "rl"
    assert 0 < saves[-1]["batch_index"] < 5
    assert saves[-1]["seam"] is True
    step_dirs = [n for n in os.listdir(d2) if n.startswith("step_")]
    assert any(
        os.path.exists(os.path.join(d2, s, "seam.npz")) for s in step_dirs
    )

    tr_res = run(d2, resume="auto")
    assert events_of(d2 + "/ev.jsonl", "seam_loaded")
    assert tr_res.rl_epochs == tr_straight.rl_epochs == 2
    assert int(tr_res.state.step) == int(tr_straight.state.step)
    params_equal(tr_straight.state.params, tr_res.state.params)


@pytest.mark.slow
def test_decoupled_actor_preempt_degrades_to_survivors(datasets,
                                                       tmp_path_factory):
    """Seeded actor_preempt recovery: losing one actor device mid-epoch
    sheds it, survivors keep decoding, the orphaned in-flight rollouts are
    recounted, and every epoch completes with finite dynamics."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("actorshed"))
    # 4 devices -> 2 actors / 2 learners; one preempt leaves 1 survivor
    cfg = make_cfg(d, len(train_ds.vocab), num_devices=4,
                   rl_topology="decoupled")
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl")
    try:
        tr.train_xe()
        with FaultPlan(
            [Fault("rl.actor.step", "actor_preempt", at=1)]
        ).activate():
            tr.train_rl()
        assert tr.rl_epochs == 2
        (deg,) = events_of(d + "/ev.jsonl", "rl_actor_degraded")
        assert deg["survivors"] == 1
        assert not events_of(d + "/ev.jsonl", "rl_actor_fallback_sync")
        rewards = [
            e["reward"] for e in events_of(d + "/ev.jsonl", "rl_step")
        ]
        assert rewards and np.isfinite(rewards).all()
        for leaf in jax.tree_util.tree_leaves(tr.state.params):
            assert np.isfinite(np.asarray(leaf)).all()
    finally:
        tr.close()


@pytest.mark.slow
def test_decoupled_actor_rejoin_regrows_fleet(datasets, tmp_path_factory):
    """ISSUE 17 actor-fleet arc: an ``actor_preempt`` sheds one actor, a
    later ``host_rejoin`` re-admits it — the rollout ring re-binds to the
    grown submesh, orphaned in-flight rollouts are recounted in order, and
    every epoch completes with finite dynamics on the restored fleet."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("actorregrow"))
    # 4 devices -> 2 actors / 2 learners; preempt actor 0, then rejoin it
    cfg = make_cfg(d, len(train_ds.vocab), num_devices=4,
                   rl_topology="decoupled")
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl")
    try:
        tr.train_xe()
        plan = FaultPlan([
            Fault("rl.actor.step", "actor_preempt", at=1),
            Fault("rl.actor.step", "host_rejoin", at=3),
        ])
        with plan.activate():
            tr.train_rl()
        assert [f["kind"] for f in plan.fired] == [
            "actor_preempt", "host_rejoin",
        ]
        assert tr.rl_epochs == 2
        (deg,) = events_of(d + "/ev.jsonl", "rl_actor_degraded")
        assert deg["survivors"] == 1
        regrown = events_of(d + "/ev.jsonl", "rl_actor_regrown")
        assert regrown and regrown[0]["actors"] == 2  # the initial fleet
        assert not events_of(d + "/ev.jsonl", "rl_actor_fallback_sync")
        rewards = [
            e["reward"] for e in events_of(d + "/ev.jsonl", "rl_step")
        ]
        assert rewards and np.isfinite(rewards).all()
        for leaf in jax.tree_util.tree_leaves(tr.state.params):
            assert np.isfinite(np.asarray(leaf)).all()
    finally:
        tr.close()


@pytest.mark.slow
def test_decoupled_zero_actor_falls_back_to_sync(datasets, tmp_path_factory):
    """When the last actor is preempted the decoupled loop degrades all the
    way to the sync schedule on the learner submesh and training still
    completes — no crash, no lost batches."""
    train_ds, _ = datasets
    d = str(tmp_path_factory.mktemp("actorzero"))
    # 2 devices -> 1 actor / 1 learner; the single preempt exhausts actors
    cfg = make_cfg(d, len(train_ds.vocab), num_devices=2,
                   rl_topology="decoupled")
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl")
    try:
        tr.train_xe()
        with FaultPlan(
            [Fault("rl.actor.step", "actor_preempt", at=1)]
        ).activate():
            tr.train_rl()
        assert tr.rl_epochs == 2
        assert events_of(d + "/ev.jsonl", "rl_actor_fallback_sync")
        # 2 RL batches/epoch x 2 epochs: every batch still produced a step
        steps = {e["step"] for e in events_of(d + "/ev.jsonl", "rl_step")}
        assert len(steps) == 4
        for leaf in jax.tree_util.tree_leaves(tr.state.params):
            assert np.isfinite(np.asarray(leaf)).all()
    finally:
        tr.close()
