"""bench_eval.py harness smoke (slow-marked: subprocess + jax compiles).

scripts/lint.sh runs the same ``--smoke`` invocation as a pre-commit gate;
this test keeps the harness covered from pytest too (``-m slow``) so the
bench cannot rot into tier-1-green-but-unrunnable. The smoke run itself
asserts lane-vs-reference beam bit-parity, NPAD monotonicity, and
pipelined-vs-serial metric bit-identity (it exits nonzero otherwise), so
rc==0 carries real signal.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_eval_smoke_runs_and_reports():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_eval.py"), "--smoke"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    json_lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert json_lines, proc.stdout[-2000:]
    out = json.loads(json_lines[-1])
    assert out["metric"] == "eval_e2e_clips_per_sec_per_chip"
    assert set(out["modes"]) == {
        "serial_reference_beam", "pipelined_lanes", "npad_pipelined",
    }
    for v in out["modes"].values():
        assert v > 0
    assert out["parity"]["lanes_vs_reference_token_exact"] is True
    assert out["parity"]["lanes_vs_reference_score_bit_exact"] is True
    assert out["parity"]["npad_best_monotone"] is True
    assert out["parity"]["pipelined_vs_serial_metrics_bit_identical"] is True
    assert out["parity_ok"] is True
    assert 0.0 <= out["overlap"]["fraction_of_scoring_hidden"] <= 1.0
    # the acceptance field is machine-checkable off-TPU
    assert out["acceptance"]["vs_committed_475_28"].startswith("skipped")
    # smoke must not clobber the committed TPU BENCH_EVAL_E2E.json
    assert "BENCH_EVAL_E2E.json" not in proc.stderr
