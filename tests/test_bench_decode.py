"""bench_decode.py harness smoke (slow-marked: subprocess + jax compiles).

scripts/lint.sh runs the same ``--smoke`` invocation as a pre-commit gate;
this test keeps the harness covered from pytest too (``-m slow``) so the
bench cannot rot into tier-1-green-but-unrunnable. The smoke run itself
asserts the fused one-loop decode is bit-exact vs the two-loop reference
(it exits nonzero otherwise), so rc==0 carries real signal.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_decode_smoke_runs_and_reports():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_decode.py"), "--smoke"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    json_lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert json_lines, proc.stdout[-2000:]
    out = json.loads(json_lines[-1])
    assert out["metric"] == "rl_decode_seconds_per_step"
    assert set(out["impls"]) == {
        "two_loop_xla", "fused_xla", "fused_xla_s4", "fused_pallas",
        "fused_pallas_s4",
    }
    for r in out["impls"].values():
        assert r["seconds_per_step"] > 0
        assert r["flops"] > 0 and r["bytes"] > 0
        assert {"lanes_stepped", "lanes_skipped", "saved_frac"} <= set(
            r["compaction"]
        )
    assert out["parity"]["fused_xla_greedy_bit_exact"] is True
    assert out["parity"]["fused_xla_samples_bit_exact"] is True
    # the stride+compaction row is BIT-exact vs the stride-1 fused loop,
    # and the in-kernel selection parity covers f32 AND bf16
    assert out["parity"]["fused_xla_s4_bit_exact"] is True
    assert out["parity"]["fused_pallas_s4_token_match_frac"] >= 0.9
    assert out["parity"]["in_kernel_selection_bf16_token_match_frac"] >= 0.8
    # the compacted rows actually skip work (EOS-biased bench params)
    assert out["impls"]["fused_xla_s4"]["compaction"]["lanes_skipped"] > 0
    # the acceptance field is machine-checkable off-TPU
    assert out["vs_r05_two_loop"] == "skipped_non_tpu"
    # smoke must not clobber the committed TPU BENCH_DECODE.json
    assert "BENCH_DECODE.json" not in proc.stderr
