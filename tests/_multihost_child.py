"""Child process for tests/test_multihost.py — NOT a pytest module.

Runs one member of a 2-process jax.distributed cluster (4 fake CPU devices
each = 8 global), trains XE + RL through the Trainer with host-sharded data
feeding, evaluates, and (process 0 only) dumps parity artifacts to json.
"""

import json
import os
import sys


def main() -> None:
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    data_dir = sys.argv[4]
    out_json = sys.argv[5]
    tmp = sys.argv[6]

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from cst_captioning_tpu.train import multihost

    multihost.initialize(f"127.0.0.1:{port}", nproc, pid)
    assert jax.process_count() == nproc
    assert len(jax.devices()) == 4 * nproc

    import numpy as np

    from tests.test_multihost import build_cfg, run_training

    result = run_training(
        data_dir, ckpt_dir=os.path.join(tmp, f"ckpt{pid}")
    )
    if pid == 0:
        with open(out_json, "w") as f:
            json.dump(result, f)


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
