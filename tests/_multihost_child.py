"""Child process for tests/test_multihost.py — NOT a pytest module.

Two modes (argv[7], default ``train``):

- ``train``: one member of a 2-process jax.distributed cluster (4 fake CPU
  devices each = 8 global), trains XE + RL through the Trainer with
  host-sharded data feeding, evaluates, and (process 0 only) dumps parity
  artifacts to json.
- ``partial_kill``: the elastic-path partial-kill harness — a REAL
  2-process cluster with per-process trainers (no cross-process
  computations, which the CPU backend cannot run) sharing one heartbeat
  dir. Process 1 (the victim) hard-dies mid-epoch via a seeded chaos kill;
  process 0 (the survivor) sleeps through the death window on a chaos
  ``slow`` fault, so its HealthMonitor declares the peer lost from
  heartbeat staleness BEFORE the next step — the survivor then drains
  (peer-loss save) and raises PeerLost (strict elastic). Each process
  reports its outcome to ``<out_json>.proc<pid>`` and hard-exits
  (``os._exit``) like a really-preempted host would.
"""

import json
import os
import sys


def _report(out_json: str, pid: int, payload: dict) -> None:
    with open(f"{out_json}.proc{pid}", "w") as f:
        json.dump(payload, f)


def partial_kill(pid: int, data_dir: str, out_json: str, tmp: str) -> None:
    import glob

    from cst_captioning_tpu.config.config import (
        DataConfig, ExperimentConfig, ModelConfig, TrainConfig,
    )
    from cst_captioning_tpu.data import CaptionDataset
    from cst_captioning_tpu.resilience.chaos import Fault, FaultPlan
    from cst_captioning_tpu.resilience.health import PeerLost
    from cst_captioning_tpu.train.trainer import Trainer

    ckpt_dir = os.path.join(tmp, f"pk_ckpt{pid}")
    ds = CaptionDataset(
        os.path.join(data_dir, "info.json"),
        {"resnet": os.path.join(data_dir, "resnet.h5")}, "train", 4,
    )
    cfg = ExperimentConfig(
        name="pk",
        model=ModelConfig(
            vocab_size=len(ds.vocab), modalities=(("resnet", 12),),
            d_embed=16, d_hidden=16, d_att=8,
            encoder="temporal_attention", dropout=0.0,
            max_len=8, max_frames=4, dtype="float32",
        ),
        data=DataConfig(batch_size=4, seq_per_vid=2),
        train=TrainConfig(
            lr=5e-3, epochs=2, ckpt_dir=ckpt_dir, eval_every_epochs=100,
            seed=0, health=True,
            health_dir=os.path.join(tmp, "pk_health"),  # SHARED heartbeats
            health_interval_s=0.1, peer_timeout_s=0.5, health_misses=2,
            elastic="strict",
        ),
    )
    # per-process trainer: NO shared mesh, so nothing here runs a
    # cross-process computation — the elastic signal under test is the
    # file-based heartbeat/watchdog/drain machinery, on real processes
    tr = Trainer(cfg, ds, None, use_mesh=False)
    if pid == 1:
        plan = FaultPlan([Fault("xe.step", "kill", at=2)])
    else:
        # sleep through the victim's death window: heartbeat staleness
        # (0.5s timeout, 2 misses, 0.1s polls) resolves well inside 2.5s,
        # so the boundary poll right after the sleep sees the loss
        plan = FaultPlan([Fault("xe.step", "slow", at=2, delay=2.5)])
    outcome: dict = {"initialized": True, "pid": pid}
    try:
        with plan.activate():
            tr.train_xe()
        outcome["finished"] = True
    except PeerLost as e:
        outcome["peer_lost"] = sorted(e.hosts)
        outcome["drained_ckpts"] = sorted(
            os.path.basename(p)
            for p in glob.glob(os.path.join(ckpt_dir, "step_*"))
        )
    except BaseException as e:  # SimulatedKill on the victim
        outcome["died"] = type(e).__name__
    _report(out_json, pid, outcome)
    ds.close()
    # hard exit, like the preempted host this models: no distributed
    # teardown handshaking with a cluster that just lost a member
    os._exit(0)


def main() -> None:
    pid = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    data_dir = sys.argv[4]
    out_json = sys.argv[5]
    tmp = sys.argv[6]
    mode = sys.argv[7] if len(sys.argv) > 7 else "train"

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")

    from cst_captioning_tpu.train import multihost

    try:
        multihost.initialize(f"127.0.0.1:{port}", nproc, pid)
    except Exception as e:
        if mode == "partial_kill":
            _report(out_json, pid, {"initialized": False, "error": repr(e)})
            os._exit(0)
        raise
    assert jax.process_count() == nproc
    assert len(jax.devices()) == 4 * nproc

    if mode == "partial_kill":
        partial_kill(pid, data_dir, out_json, tmp)
        return

    import numpy as np  # noqa: F401 - kept for the train path's imports

    from tests.test_multihost import build_cfg, run_training  # noqa: F401

    result = run_training(
        data_dir, ckpt_dir=os.path.join(tmp, f"ckpt{pid}")
    )
    if pid == 0:
        with open(out_json, "w") as f:
            json.dump(result, f)


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    main()
