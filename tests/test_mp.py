"""Flagship-XL model parallelism: partition rules, the compile layer, and
the sharded-vocab decode kernels (train/mesh.py, parallel/compile.py,
ops/decode_mp.py).

The parity pins run the mp>=2 shard_map programs on the 8 fake CPU devices
(conftest.py) — the per-shard kernel falls back to its jnp composite there
(interpret mode), the exact contract the replicated kernel tests use.
Tokens and beam candidates must be BIT-exact vs the replicated references;
logprobs/scores/carries get a few-f32-ulp allowance (the cross-shard
logsumexp reassociates, and the shard_map program jit-fuses differently
than the eager reference).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from cst_captioning_tpu.config.config import (
    EOS_ID,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
)
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.models.captioner import CaptionModel as CM
from cst_captioning_tpu.ops.decode_mp import (
    mp_beam_step,
    mp_cell_specs,
    mp_decode_stride,
)
from cst_captioning_tpu.ops import decode_mp
from cst_captioning_tpu.ops.decode_pallas import (
    _reference_beam_topk,
    _reference_stride,
)
from cst_captioning_tpu.parallel.comms import ledger, mp_shard_view
from cst_captioning_tpu.parallel.compile import (
    CompileError,
    CompilePlan,
    compile_fn,
    partition,
)
from cst_captioning_tpu.parallel.submesh import (
    grow_actors,
    plan_submesh,
    shrink_actors,
)
from cst_captioning_tpu.train.mesh import (
    MP_PARAM_PARTITION_RULES,
    PARAM_PARTITION_RULES,
    make_mesh,
    match_partition_rules,
    match_rule,
    param_partition_specs,
    param_path_names,
    rule_coverage,
    rule_provenance,
)

ULP = 5e-6  # few-f32-ulp allowance for reassociated logsumexp / jit fusion


def _setup(V, B, d, F, K, dtype="float32", L=1, seed=0):
    cfg = ModelConfig(
        vocab_size=V, modalities=(("resnet", 16),), d_embed=d, d_hidden=d,
        d_att=max(4, d // 2), encoder="temporal_attention", dropout=0.0,
        max_len=8, max_frames=F, dtype=dtype, num_layers=L,
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(seed)
    feats = {"resnet": jnp.asarray(rng.normal(size=(B, F, 16)), jnp.float32)}
    masks = {"resnet": jnp.asarray(
        np.arange(F)[None] < rng.integers(2, F + 1, size=(B, 1)), jnp.float32
    )}
    labels = jnp.asarray(rng.integers(4, V, size=(B, 8)), jnp.int32)
    params = model.init(jax.random.key(0), feats, masks, labels)
    enc = model.apply(params, feats, masks, method=CM.encode)
    G = 1 + K
    carry = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (G,) + x.shape), enc.carry
    )
    token = jnp.asarray(rng.integers(1, V, size=(G, B)), jnp.int32)
    return model, params, enc, carry, token, rng


# ---- partition rule tables ---------------------------------------------------


def test_match_rule_first_match_wins():
    rules = (
        ("specific", r"a/b/kernel", P("mp")),
        ("broad", r"a/.*", P()),
    )
    assert match_rule(rules, "a/b/kernel") == ("specific", P("mp"))
    assert match_rule(rules, "a/b/bias") == ("broad", P())
    # swapped order: the broad family shadows the specific one — order IS
    # the semantics (GL018 flags genuinely dead rows)
    shadowed = (rules[1], rules[0])
    assert match_rule(shadowed, "a/b/kernel") == ("broad", P())


def test_match_rule_requires_fullmatch_and_raises_on_no_match():
    rules = (("fam", r"params/x", P()),)
    with pytest.raises(ValueError, match="matches no partition rule"):
        match_rule(rules, "params/x/kernel")  # prefix is not fullmatch
    with pytest.raises(ValueError, match="matches no partition rule"):
        match_rule(MP_PARAM_PARTITION_RULES, "params/new_head/kernel")


def test_mp_rules_route_real_param_tree():
    """The flagship table puts the vocab head / embedding / gate matrices
    on 'mp' and replicates everything upstream — checked on a REAL
    2-layer param tree, not fixture strings."""
    model, params, *_ = _setup(V=24, B=2, d=8, F=3, K=1, L=2)
    specs = match_partition_rules(MP_PARAM_PARTITION_RULES, params)
    flat = dict(zip(
        param_path_names(params),
        jax.tree_util.tree_leaves(
            specs, is_leaf=lambda s: isinstance(s, P)
        ),
    ))
    assert flat["params/cell/word_embed/embedding"] == P("mp")
    assert flat["params/cell/out_proj/kernel"] == P(None, "mp")
    assert flat["params/cell/out_proj/bias"] == P("mp")
    assert flat["params/cell/lstm0/ii/kernel"] == P(None, "mp")
    assert flat["params/cell/lstm1/hf/kernel"] == P(None, "mp")
    assert flat["params/cell/lstm0/hf/bias"] == P("mp")
    assert flat["params/cell/attention/query_proj/kernel"] == P()
    assert flat["params/init_h0/kernel"] == P()
    # full coverage both ways, both tables, on the live tree
    names = list(flat)
    for rules in (PARAM_PARTITION_RULES, MP_PARAM_PARTITION_RULES):
        unmatched, unruled = rule_coverage(names, rules=rules)
        assert unmatched == [] and unruled == []


def test_dp_table_is_fully_replicated_and_provenance_names_rules():
    model, params, *_ = _setup(V=24, B=2, d=8, F=3, K=1)
    specs = param_partition_specs(params)  # default: the canonical table
    assert all(
        s == P() for s in jax.tree_util.tree_leaves(
            specs, is_leaf=lambda s: isinstance(s, P)
        )
    )
    prov = rule_provenance(
        MP_PARAM_PARTITION_RULES, ["params/cell/out_proj/kernel"]
    )
    assert prov == {"params/cell/out_proj/kernel": "output_head_kernel"}


# ---- make_mesh ---------------------------------------------------------------


def test_make_mesh_mp_grid_and_degenerate():
    mesh = make_mesh(mp_devices=2)
    assert mesh.axis_names == ("data", "mp")
    assert mesh.shape["data"] == 4 and mesh.shape["mp"] == 2
    # mp=1 degenerates to the exact pre-mp 1-D mesh
    flat = make_mesh(mp_devices=1)
    assert flat.axis_names == ("data",)
    assert flat.devices.tolist() == make_mesh().devices.tolist()


def test_make_mesh_rejects_bad_mp():
    with pytest.raises(ValueError, match="must divide"):
        make_mesh(mp_devices=3)
    with pytest.raises(ValueError, match="cannot compose"):
        make_mesh(seq_devices=2, mp_devices=2)


# ---- sharded-vocab stride ----------------------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize(
    "mp",
    # mp=2 is THE tier-1 acceptance pin; the mp=4 twin proves the merges
    # generalize past two shards but costs another 4-way CPU mesh compile,
    # so it rides the slow tier with the other redundant-compile sweeps
    [2, pytest.param(4, marks=pytest.mark.slow)],
)
def test_mp_stride_matches_replicated(dtype, mp):
    """Tokens BIT-exact vs the replicated stride composite across an
    eos-ragged rollout (min_len block + lanes finishing at different
    steps); logprobs/carry within the ulp allowance."""
    V, B, d, F, K, S = 24, 5, 12, 6, 2, 6
    model, params, enc, carry, token, rng = _setup(V, B, d, F, K, dtype)
    cell = params["params"]["cell"]
    finished = jnp.zeros((1 + K, B), bool)
    noise = jnp.asarray(rng.gumbel(size=(S, K, B, V)), jnp.float32)
    t0 = jnp.asarray(0, jnp.int32)

    c_r, tok_r, lp_r = _reference_stride(
        cell, carry, token, finished, enc.memory, enc.memory_proj,
        enc.memory_mask, noise, t0, steps=S, temperature=0.7, min_len=2,
    )
    mesh = make_mesh(mp_devices=mp)
    c_m, tok_m, lp_m = mp_decode_stride(
        cell, carry, token, finished, enc.memory, enc.memory_proj,
        enc.memory_mask, noise, t0, mesh=mesh, steps=S, temperature=0.7,
        min_len=2,
    )
    np.testing.assert_array_equal(np.asarray(tok_m), np.asarray(tok_r))
    np.testing.assert_allclose(
        np.asarray(lp_m), np.asarray(lp_r), atol=ULP, rtol=0
    )
    for a, b in zip(jax.tree.leaves(c_m), jax.tree.leaves(c_r)):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-3 if dtype == "bfloat16" else ULP, rtol=0,
        )
    # the rollout actually went ragged: some lane hit EOS mid-stride,
    # some row kept going (otherwise the freeze path wasn't exercised)
    assert bool((np.asarray(tok_r) == EOS_ID).any())


def test_mp_stride_respects_prefinished_lanes():
    V, B, d, F, K, S = 24, 4, 12, 5, 2, 4
    model, params, enc, carry, token, rng = _setup(V, B, d, F, K)
    cell = params["params"]["cell"]
    finished = jnp.zeros((1 + K, B), bool).at[1, :2].set(True)
    noise = jnp.asarray(rng.gumbel(size=(S, K, B, V)), jnp.float32)
    mesh = make_mesh(mp_devices=2)
    _c, tok, lp = mp_decode_stride(
        cell, carry, token, finished, enc.memory, enc.memory_proj,
        enc.memory_mask, noise, jnp.asarray(3, jnp.int32), mesh=mesh,
        steps=S, temperature=1.0, min_len=0,
    )
    assert (np.asarray(tok)[:, 1, :2] == 0).all()  # PAD forever
    assert (np.asarray(lp)[:, 1, :2] == 0.0).all()


def test_mp_stride_program_cache_reuses_compiled_program():
    """Repeated strides (the serving loop's shape) must NOT rebuild the
    shard_map program — the lru_cache keyed on (mesh, structure, knobs)
    is what keeps the jit cache warm."""
    V, B, d, F, K, S = 24, 3, 8, 4, 1, 2
    model, params, enc, carry, token, rng = _setup(V, B, d, F, K)
    cell = params["params"]["cell"]
    finished = jnp.zeros((1 + K, B), bool)
    noise = jnp.asarray(rng.gumbel(size=(S, K, B, V)), jnp.float32)
    mesh = make_mesh(mp_devices=2)
    before = decode_mp._stride_program.cache_info()
    args = (cell, carry, token, finished, enc.memory, enc.memory_proj,
            enc.memory_mask, noise, jnp.asarray(0, jnp.int32))
    mp_decode_stride(*args, mesh=mesh, steps=S, temperature=1.0)
    mp_decode_stride(*args, mesh=mesh, steps=S, temperature=1.0)
    after = decode_mp._stride_program.cache_info()
    assert after.hits >= before.hits + 1


def test_mp_stride_validates_inputs():
    fake = {"out_proj": {"kernel": np.zeros((4, 23), np.float32)},
            "word_embed": {"embedding": np.zeros((23, 4), np.float32)}}
    mesh = make_mesh(mp_devices=2)
    with pytest.raises(ValueError, match="does not divide"):
        mp_decode_stride(fake, None, None, None, None, None, None,
                         np.zeros((1, 1, 1, 23)), 0, mesh=mesh, steps=1)
    with pytest.raises(ValueError, match=r"no 'mp' axis"):
        mp_decode_stride(fake, None, None, None, None, None, None,
                         np.zeros((1, 1, 1, 24)), 0, mesh=make_mesh(),
                         steps=1)
    fake24 = {"out_proj": {"kernel": np.zeros((4, 24), np.float32)},
              "word_embed": {"embedding": np.zeros((24, 4), np.float32)}}
    with pytest.raises(ValueError, match="noise vocab dim"):
        mp_decode_stride(fake24, None, None, None, None, None, None,
                         np.zeros((1, 1, 1, 23)), 0, mesh=mesh, steps=1)


# ---- sharded-vocab beam ------------------------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
@pytest.mark.parametrize(
    "mp",
    # mp=2 is THE tier-1 acceptance pin; the mp=4 twin proves the merges
    # generalize past two shards but costs another 4-way CPU mesh compile,
    # so it rides the slow tier with the other redundant-compile sweeps
    [2, pytest.param(4, marks=pytest.mark.slow)],
)
def test_mp_beam_step_matches_replicated(dtype, mp):
    """Candidate flat ids BIT-exact (including top_k tie order — the
    finished lane's PAD continuation manufactures exact score ties);
    scores within the ulp allowance."""
    V, B, d, F, W = 24, 4, 12, 5, 3
    model, params, enc, carry0, _tok, rng = _setup(V, B, d, F, K=W - 1,
                                                   dtype=dtype)
    cell = params["params"]["cell"]
    carry = jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (W,) + x.shape), enc.carry
    )
    token = jnp.asarray(rng.integers(1, V, size=(W, B)), jnp.int32)
    finished = jnp.zeros((W, B), bool).at[W - 1].set(True)
    scores = jnp.asarray(rng.normal(size=(W, B)), jnp.float32)

    _cr, ts_r, fl_r = _reference_beam_topk(
        cell, carry, token, finished, scores, enc.memory, enc.memory_proj,
        enc.memory_mask, t=jnp.asarray(1, jnp.int32), min_len=2,
    )
    mesh = make_mesh(mp_devices=mp)
    _cm, ts_m, fl_m = mp_beam_step(
        cell, carry, token, finished, scores, enc.memory, enc.memory_proj,
        enc.memory_mask, mesh=mesh, t=1, min_len=2,
    )
    np.testing.assert_array_equal(np.asarray(fl_m), np.asarray(fl_r))
    np.testing.assert_allclose(
        np.asarray(ts_m), np.asarray(ts_r), atol=ULP, rtol=0
    )


def test_mp_beam_step_rejects_wide_beam():
    fake = {"out_proj": {"kernel": np.zeros((4, 8), np.float32)},
            "word_embed": {"embedding": np.zeros((8, 4), np.float32)}}
    mesh = make_mesh(mp_devices=2)
    with pytest.raises(ValueError, match="beam width"):
        mp_beam_step(fake, None, np.zeros((5, 2), np.int32), None, None,
                     None, None, None, mesh=mesh, t=0)


def test_mp_cell_specs_shard_only_vocab_families():
    model, params, *_ = _setup(V=24, B=2, d=8, F=3, K=1)
    cell = params["params"]["cell"]
    specs = mp_cell_specs(cell)
    assert specs["word_embed"]["embedding"] == P("mp")
    assert specs["out_proj"]["kernel"] == P(None, "mp")
    assert specs["out_proj"]["bias"] == P("mp")
    # the decode kernels consume the recurrent weights whole -> replicated
    # on this path even though the TRAINING table shards the gates
    assert specs["lstm0"]["ii"]["kernel"] == P()
    assert specs["attention"]["query_proj"]["kernel"] == P()


# ---- compile layer -----------------------------------------------------------


def test_compile_fn_jit_mode_is_bit_identical_to_plain_jit():
    def f(x, y):
        return x @ y + jnp.tanh(x).sum()

    x = jnp.asarray(np.random.default_rng(0).normal(size=(8, 8)),
                    jnp.float32)
    a = compile_fn(f, CompilePlan())(x, x)
    b = jax.jit(f)(x, x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_compile_fn_shard_map_is_bit_identical_to_direct_spelling():
    from cst_captioning_tpu.compat import shard_map

    mesh = make_mesh()

    def mean_grad(x):
        return jax.lax.pmean(jnp.sin(x) * x, "data")

    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 4)),
                    jnp.float32)
    plan = CompilePlan(mesh=mesh, in_specs=P("data"), out_specs=P("data"))
    a = compile_fn(mean_grad, plan)(x)
    b = jax.jit(shard_map(mean_grad, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data")))(x)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_compile_plan_error_cases():
    mesh = make_mesh()
    with pytest.raises(CompileError, match="unknown compile mode"):
        CompilePlan(how="vmap")
    with pytest.raises(CompileError, match="one-sided"):
        CompilePlan(mesh=mesh, in_specs=P("data"))
    with pytest.raises(CompileError, match="ignores partition specs"):
        CompilePlan(how="jit", mesh=mesh, in_specs=P("data"),
                    out_specs=P("data")).resolve()
    with pytest.raises(CompileError, match="needs a mesh"):
        CompilePlan(how="shard_map", in_specs=P("data"),
                    out_specs=P("data")).resolve()
    with pytest.raises(CompileError, match="needs in_specs"):
        CompilePlan(how="pjit", mesh=mesh).resolve()
    with pytest.raises(CompileError, match="only builds shard_map"):
        partition(lambda x: x, CompilePlan())


# ---- dp x mp submesh compose -------------------------------------------------


def test_plan_submesh_splits_2d_mesh_along_data_only():
    mesh = make_mesh(mp_devices=2)  # (4, 2) dp x mp
    plan = plan_submesh(mesh, actor_fraction=0.5, batch_size=4)
    assert not plan.shared
    assert plan.actor.axis_names == ("data", "mp")
    assert plan.learner.axis_names == ("data", "mp")
    assert plan.actor.shape["mp"] == 2 and plan.learner.shape["mp"] == 2
    assert plan.actor.shape["data"] + plan.learner.shape["data"] == 4
    # disjoint device sets covering the full mesh
    ad = {d.id for d in plan.actor_devices}
    ld = {d.id for d in plan.learner_devices}
    assert not (ad & ld) and len(ad | ld) == 8


def test_elastic_resize_rejects_2d_plans():
    mesh = make_mesh(mp_devices=2)
    plan = plan_submesh(mesh, actor_fraction=0.5, batch_size=4)
    with pytest.raises(ValueError, match="1-D"):
        shrink_actors(plan, 0, batch_size=4)
    with pytest.raises(ValueError, match="1-D"):
        grow_actors(plan, plan.actor_devices[0], plan, batch_size=4)


# ---- mp comms ledger ---------------------------------------------------------


def test_mp_shard_view_and_ledger_accounting():
    model, params, *_ = _setup(V=24, B=2, d=8, F=3, K=1)
    led1 = ledger(params, None)
    # mp=1 is bit-identical to the pre-mp ledger (the degenerate pin)
    assert ledger(params, None, mp_devices=1) == led1
    led2 = ledger(params, None, mp_devices=2)
    assert led2["bytes_on_wire_per_update"] < led1["bytes_on_wire_per_update"]
    # the saving is exactly half of every mp-sharded leaf's f32 payload
    view = mp_shard_view(params, 2)
    full = dict(zip(
        param_path_names(params), jax.tree_util.tree_leaves(params)
    ))
    sharded_f32 = 0
    for path, leaf in full.items():
        _fam, spec = match_rule(MP_PARAM_PARTITION_RULES, path)
        if any(a == "mp" for a in spec if a is not None):
            sharded_f32 += (leaf.size - -(-leaf.size // 2)) * 4
    assert led1["bytes_on_wire_per_update"] - \
        led2["bytes_on_wire_per_update"] == sharded_f32
    # shapes in the view: sharded leaves shrink, replicated leaves don't
    assert sum(l.size for l in jax.tree_util.tree_leaves(view)) < \
        sum(l.size for l in jax.tree_util.tree_leaves(params))


# ---- config validation -------------------------------------------------------


def test_experiment_config_validates_mp_devices():
    ok = ExperimentConfig(
        model=ModelConfig(vocab_size=512, d_hidden=128),
        mesh=MeshConfig(mp_devices=2, num_devices=8),
    )
    assert ok.mesh.mp_devices == 2
    with pytest.raises(ValueError, match="must be >= 1"):
        ExperimentConfig(mesh=MeshConfig(mp_devices=0))
    with pytest.raises(ValueError, match="pick one second mesh axis"):
        ExperimentConfig(
            model=ModelConfig(vocab_size=512, d_hidden=128),
            mesh=MeshConfig(mp_devices=2, seq_devices=2),
        )
    with pytest.raises(ValueError, match="vocab_size"):
        ExperimentConfig(
            model=ModelConfig(vocab_size=511, d_hidden=128),
            mesh=MeshConfig(mp_devices=2),
        )
    with pytest.raises(ValueError, match="d_hidden"):
        ExperimentConfig(
            model=ModelConfig(vocab_size=512, d_hidden=127),
            mesh=MeshConfig(mp_devices=2),
        )
    with pytest.raises(ValueError, match="num_devices"):
        ExperimentConfig(
            model=ModelConfig(vocab_size=512, d_hidden=128),
            mesh=MeshConfig(mp_devices=2, num_devices=3),
        )
