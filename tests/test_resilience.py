"""Unit tests for the resilience layer: durable checkpoints + manifest
verification, retry/backoff, divergence sentinel, preemption handler, chaos
plan determinism, and the crash-safe EventLogger."""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ckpt import CheckpointManager, load_state, save_state
from cst_captioning_tpu.config.config import ModelConfig, TrainConfig
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.resilience import chaos
from cst_captioning_tpu.resilience.adaptive import AdaptiveThresholds
from cst_captioning_tpu.resilience.chaos import Fault, FaultPlan, SimulatedKill
from cst_captioning_tpu.resilience.durable import (
    CorruptCheckpointError,
    MANIFEST_FILE,
    verify_manifest,
    write_manifest,
)
from cst_captioning_tpu.resilience.preempt import PreemptionHandler
from cst_captioning_tpu.resilience.retry import RetryPolicy, retry_call
from cst_captioning_tpu.resilience.sentinel import (
    DivergenceSentinel,
    RollbackRequested,
    TrainingDiverged,
)
from cst_captioning_tpu.train import create_train_state, make_optimizer
from cst_captioning_tpu.utils.logging import EventLogger


@pytest.fixture(scope="module")
def tiny_state():
    cfg = ModelConfig(
        vocab_size=12, modalities=(("resnet", 6),), d_embed=8, d_hidden=8,
        d_att=4, encoder="meanpool", max_len=5, max_frames=3, dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(2, 3, 6)), jnp.float32)}
    masks = {"resnet": jnp.ones((2, 3), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, 12, size=(2, 5)), jnp.int32)
    tx = make_optimizer(TrainConfig(lr=1e-3), 10)
    return create_train_state(model, tx, (feats, masks, labels), seed=0)


class LogSink:
    """EventLogger.log-compatible callable that records events."""

    def __init__(self):
        self.events = []

    def __call__(self, event, **fields):
        self.events.append({"event": event, **fields})

    def of(self, kind):
        return [e for e in self.events if e["event"] == kind]


# ---- durable.py -------------------------------------------------------------

def test_manifest_roundtrip_and_truncation(tmp_path):
    d = str(tmp_path)
    blob = b"x" * 1000
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(blob)
    write_manifest(d, {"state.msgpack": blob})
    assert verify_manifest(d) is True

    with open(os.path.join(d, "state.msgpack"), "r+b") as f:
        f.truncate(500)
    with pytest.raises(CorruptCheckpointError, match="size"):
        verify_manifest(d)

    # same size, flipped bytes -> checksum catches it
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(b"y" * 1000)
    with pytest.raises(CorruptCheckpointError, match="sha256"):
        verify_manifest(d)


def test_manifest_missing_is_legacy_not_error(tmp_path):
    assert verify_manifest(str(tmp_path)) is False


def test_save_state_writes_verified_manifest(tiny_state, tmp_path):
    path = save_state(str(tmp_path), "latest", tiny_state, {"epoch": 1})
    assert verify_manifest(path) is True
    manifest = json.load(open(os.path.join(path, MANIFEST_FILE)))
    assert set(manifest["files"]) == {"state.msgpack", "infos.json"}


def test_truncated_state_detected_on_load(tiny_state, tmp_path):
    save_state(str(tmp_path), "latest", tiny_state)
    sp = os.path.join(str(tmp_path), "latest", "state.msgpack")
    with open(sp, "r+b") as f:
        f.truncate(os.path.getsize(sp) // 2)
    with pytest.raises(CorruptCheckpointError):
        load_state(str(tmp_path), "latest", tiny_state)


def test_resave_keeps_previous_generation(tiny_state, tmp_path):
    save_state(str(tmp_path), "latest", tiny_state, {"epoch": 1})
    save_state(str(tmp_path), "latest", tiny_state, {"epoch": 2})
    _, infos = load_state(str(tmp_path), "latest", tiny_state)
    assert infos["epoch"] == 2
    # the demoted generation is intact and loadable
    _, prev_infos = load_state(str(tmp_path), "latest.prev", tiny_state)
    assert prev_infos["epoch"] == 1


# ---- chaos.py ---------------------------------------------------------------

def test_chaos_inactive_is_noop():
    payload = object()
    assert chaos.visit("anything", payload) is payload


def test_chaos_kill_fires_at_exact_visit():
    plan = FaultPlan([Fault("pt", "kill", at=2)])
    with plan.activate():
        chaos.visit("pt")
        chaos.visit("pt")
        with pytest.raises(SimulatedKill):
            chaos.visit("pt")
    assert plan.fired == [{"point": "pt", "kind": "kill", "visit": 2}]
    # deactivated again
    chaos.visit("pt")


def test_chaos_io_error_window_then_clean():
    plan = FaultPlan([Fault("io", "io_error", at=0, times=2)])
    with plan.activate():
        for _ in range(2):
            with pytest.raises(OSError):
                chaos.visit("io")
        chaos.visit("io")  # third visit is clean
    assert plan.visits("io") == 3


def test_chaos_seeded_random_at_is_deterministic():
    spec = [Fault("pt", "kill", at=("rand", 5, 50))]
    a = FaultPlan(list(spec), seed=7)
    b = FaultPlan([Fault("pt", "kill", at=("rand", 5, 50))], seed=7)
    c = FaultPlan([Fault("pt", "kill", at=("rand", 5, 50))], seed=8)
    assert a.faults[0].at == b.faults[0].at
    assert 5 <= a.faults[0].at < 50
    assert a.faults[0].at != c.faults[0].at or True  # seeds may collide; just bounds-check c
    assert 5 <= c.faults[0].at < 50


def test_chaos_nan_poisons_batch_features():
    class B:
        feats = {"resnet": np.ones((2, 3), np.float32)}

    plan = FaultPlan([Fault("b", "nan", at=1)])
    with plan.activate():
        clean = B()
        chaos.visit("b", clean)
        assert np.isfinite(clean.feats["resnet"]).all()
        poisoned = B()
        chaos.visit("b", poisoned)
        assert np.isnan(poisoned.feats["resnet"]).all()


def test_chaos_single_active_plan():
    p1, p2 = FaultPlan([]), FaultPlan([])
    with p1.activate():
        with pytest.raises(RuntimeError, match="already active"):
            p2.activate().__enter__()


# ---- retry.py ---------------------------------------------------------------

def test_retry_succeeds_after_transients():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    events = []
    out = retry_call(
        flaky,
        policy=RetryPolicy(max_attempts=4, base_delay=0.01, seed=1),
        on_retry=events.append,
        sleep=sleeps.append,
    )
    assert out == "ok" and calls["n"] == 3
    assert len(events) == 2 and len(sleeps) == 2
    assert events[0]["error"] == "OSError" and events[0]["attempt"] == 1


def test_retry_exhausts_attempts_and_reraises():
    def always():
        raise OSError("down")

    with pytest.raises(OSError, match="down"):
        retry_call(
            always,
            policy=RetryPolicy(max_attempts=3, base_delay=0.001),
            sleep=lambda d: None,
        )


def test_retry_budget_caps_total_sleep():
    def always():
        raise OSError("down")

    sleeps = []
    with pytest.raises(OSError):
        retry_call(
            always,
            policy=RetryPolicy(
                max_attempts=10, base_delay=1.0, factor=1.0, jitter=0.0,
                budget=2.5,
            ),
            sleep=sleeps.append,
        )
    assert len(sleeps) == 2  # third 1s sleep would exceed the 2.5s budget


def test_retry_nonretryable_propagates_immediately():
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry_call(boom, policy=RetryPolicy(max_attempts=5),
                   sleep=lambda d: None)
    assert calls["n"] == 1


def test_retry_jitter_is_seed_deterministic():
    p = RetryPolicy(max_attempts=5, seed=42)
    assert p.delays() == RetryPolicy(max_attempts=5, seed=42).delays()
    assert p.delays() != RetryPolicy(max_attempts=5, seed=43).delays()


def test_simulated_kill_escapes_retry():
    def killed():
        raise SimulatedKill("host died")

    with pytest.raises(SimulatedKill):
        retry_call(killed, policy=RetryPolicy(max_attempts=5),
                   sleep=lambda d: None)


# ---- sentinel.py ------------------------------------------------------------

def test_sentinel_skip_batch_logs_and_continues():
    log = LogSink()
    s = DivergenceSentinel(policy="skip_batch", log=log)
    s.push(1, jnp.float32(1.0), jnp.float32(0.0))
    s.push(2, jnp.float32(float("nan")), jnp.float32(1.0))
    s.push(3, jnp.float32(0.9), jnp.float32(0.0))
    s.flush()
    events = log.of("divergence")
    assert len(events) == 1
    assert events[0]["step"] == 2 and events[0]["kind"] == "nonfinite"
    assert events[0]["action"] == "skip_batch"
    assert s.skipped == 1


def test_sentinel_abort_raises():
    s = DivergenceSentinel(policy="abort")
    s.push(1, jnp.float32(float("inf")), None)
    with pytest.raises(TrainingDiverged):
        s.flush()


def test_sentinel_rollback_raises_with_context():
    s = DivergenceSentinel(policy="rollback", check_every=1)
    with pytest.raises(RollbackRequested) as ei:
        s.push(7, jnp.float32(float("nan")), jnp.float32(1.0))
    assert ei.value.step == 7 and ei.value.kind == "nonfinite"


def test_sentinel_spike_detection_after_warmup():
    log = LogSink()
    s = DivergenceSentinel(
        policy="abort", log=log, spike_factor=5.0, warmup=4,
    )
    for i in range(6):
        s.push(i, jnp.float32(1.0), None)
    s.flush()
    s.push(10, jnp.float32(50.0), None)  # 50x the median
    with pytest.raises(TrainingDiverged):
        s.flush()
    assert log.of("divergence")[0]["kind"] == "spike"
    # under skip_batch a spike is logged, not acted on (update already applied)
    log2 = LogSink()
    s2 = DivergenceSentinel(
        policy="skip_batch", log=log2, spike_factor=5.0, warmup=4,
    )
    for i in range(6):
        s2.push(i, jnp.float32(1.0), None)
    s2.push(10, jnp.float32(50.0), None)
    s2.flush()
    assert log2.of("divergence")[0]["action"] == "logged"


def test_sentinel_off_is_free():
    s = DivergenceSentinel(policy="off")
    s.push(1, jnp.float32(float("nan")), jnp.float32(1.0))
    s.flush()  # no readback, no raise
    assert s._buf == []


# ---- adaptive.py: anomaly-adaptive spike thresholds -------------------------


def _run_sentinel(losses, adaptive=None, spike_factor=10.0):
    """Push a loss stream through a skip_batch sentinel (spikes are logged,
    the stream continues) and return the spike events."""
    log = LogSink()
    s = DivergenceSentinel(policy="skip_batch", log=log,
                           spike_factor=spike_factor, warmup=4,
                           adaptive=adaptive)
    for i, v in enumerate(losses):
        s.push(i, jnp.float32(v), None)
        if i % 8 == 7:
            s.flush()
    s.flush()
    return [e for e in log.of("divergence") if e["kind"] == "spike"]


def test_adaptive_trips_on_slow_ramp_fixed_misses():
    """ISSUE acceptance: a seeded healthy phase followed by a 10%/step loss
    ramp trips spike_mode='adaptive' at ramp ONSET while the fixed factor
    (which the ramp's drifting median never reaches) stays blind."""
    rng = np.random.default_rng(0)
    healthy = list(2.0 + rng.normal(0.0, 0.02, size=40))
    ramp = [2.0 * 1.10 ** k for k in range(1, 25)]
    losses = healthy + ramp

    assert _run_sentinel(losses) == []  # fixed factor 10: never trips

    spikes = _run_sentinel(
        losses,
        adaptive=AdaptiveThresholds(factor_max=10.0, factor_min=1.5),
    )
    assert spikes, "adaptive mode missed the ramp entirely"
    first = spikes[0]
    # tripped within the first handful of ramp steps, bound detail carried
    assert 40 <= first["step"] <= 48
    assert 0.0 < first["bound"] < first["loss"]


def test_adaptive_never_trips_on_seeded_healthy_runs():
    for seed in range(5):
        rng = np.random.default_rng(seed)
        losses = list(2.0 + rng.normal(0.0, 0.05, size=400))
        spikes = _run_sentinel(
            losses,
            adaptive=AdaptiveThresholds(factor_max=10.0, factor_min=1.5),
        )
        assert spikes == [], f"false trip with seed {seed}: {spikes}"


def test_adaptive_unwarmed_uses_fixed_bound_verbatim():
    at = AdaptiveThresholds(factor_max=10.0, factor_min=1.5)
    assert not at.warmed
    assert at.bound(2.0, 20.0) == 20.0
    for _ in range(16):
        at.observe(2.0)  # zero variance: still not trustworthy
    assert not at.warmed and at.bound(2.0, 20.0) == 20.0


def test_adaptive_bound_clamps_to_factor_window():
    at = AdaptiveThresholds(factor_max=10.0, factor_min=1.5,
                            alpha=0.2, warmup=4)
    rng = np.random.default_rng(1)
    for v in 2.0 + rng.normal(0.0, 0.01, size=32):
        at.observe(float(v))
    assert at.warmed
    # tiny variance: mean + 3 std ~ 2.05 -> the floor clamp lifts it
    assert at.bound(2.0, 20.0) == pytest.approx(1.5 * 2.0, rel=1e-6)
    # the ceiling clamp keeps adaptive never looser than fixed
    assert at.bound(2.0, 2.5) == 2.5
    # negative median (legit RL loss): raw EWMA bound, fixed cap only
    b = at.bound(-0.5, 20.0)
    assert 2.0 < b < 2.2


def test_adaptive_shared_ewma_reads_detector_moments():
    from cst_captioning_tpu.obs.anomaly import AnomalyDetector

    det = AnomalyDetector(warmup=4)
    shared = det.ewma("loss")
    at = AdaptiveThresholds(factor_max=10.0, ewma=shared)
    at.observe(5.0)  # shared mode: the detector owns updates; a no-op
    assert shared.n == 0
    for i in range(12):
        det.observe("loss", 2.0 + 0.01 * i)
    assert at.warmed  # detector updates flow straight through
    assert at.bound(2.0, 20.0) < 20.0


def test_adaptive_validation():
    with pytest.raises(ValueError):
        AdaptiveThresholds(factor_max=0.0)
    with pytest.raises(ValueError):
        AdaptiveThresholds(factor_max=10.0, factor_min=0.0)
    with pytest.raises(ValueError):
        AdaptiveThresholds(factor_max=10.0, factor_min=12.0)
    with pytest.raises(ValueError):
        AdaptiveThresholds(factor_max=10.0, z=0.0)


def test_train_config_validates_spike_mode():
    with pytest.raises(ValueError):
        TrainConfig(spike_mode="bogus")
    with pytest.raises(ValueError):
        TrainConfig(spike_mode="adaptive")  # needs spike_factor > 0
    with pytest.raises(ValueError):
        TrainConfig(spike_mode="adaptive", spike_factor=10.0,
                    spike_factor_min=0.0)
    with pytest.raises(ValueError):
        TrainConfig(spike_mode="adaptive", spike_factor=10.0,
                    spike_factor_min=20.0)
    TrainConfig(spike_mode="adaptive", spike_factor=10.0)  # valid


# ---- preempt.py -------------------------------------------------------------

def test_preemption_handler_latches_sigterm():
    with PreemptionHandler() as h:
        assert h.installed and not h.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.requested
    # prior disposition restored
    assert signal.getsignal(signal.SIGTERM) != h._on_signal


def test_preemption_handler_chains_previous_python_handler():
    hits = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
    try:
        with PreemptionHandler() as h:
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.requested and hits == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


# ---- CheckpointManager: rotation, ordering, corrupt fallback ----------------

def test_step_checkpoint_rotation(tiny_state, tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2)
    for step in (10, 20, 30, 40):
        mgr.save_step(tiny_state, step, {"epoch": 0})
    assert [s for s, _ in mgr.step_checkpoints()] == [30, 40]


def test_restore_prefers_newest_by_global_step(tiny_state, tmp_path):
    log = LogSink()
    mgr = CheckpointManager(str(tmp_path), keep=3, log=log)
    mgr.save(tiny_state, value=None, infos={"epoch": 1, "global_step": 6})
    mgr.save_step(tiny_state, 9, {"epoch": 1, "batch_index": 3})
    restored = mgr.restore_latest(tiny_state)
    assert restored is not None
    _, infos = restored
    assert infos["global_step"] == 9 and infos["batch_index"] == 3


def test_corrupt_latest_falls_back_with_logged_event(tiny_state, tmp_path):
    log = LogSink()
    mgr = CheckpointManager(str(tmp_path), log=log)
    mgr.save(tiny_state, value=0.5, infos={"epoch": 1, "global_step": 6})
    sp = os.path.join(str(tmp_path), "latest", "state.msgpack")
    with open(sp, "r+b") as f:
        f.truncate(os.path.getsize(sp) // 2)
    restored = mgr.restore_latest(tiny_state)
    assert restored is not None  # fell back to 'best'
    _, infos = restored
    assert infos["epoch"] == 1
    events = log.of("ckpt_corrupt")
    assert len(events) == 1 and events[0]["name"] == "latest"
    assert events[0]["error"] == "CorruptCheckpointError"


def test_kill_mid_save_previous_generation_survives(tiny_state, tmp_path):
    log = LogSink()
    mgr = CheckpointManager(str(tmp_path), log=log)
    mgr.save(tiny_state, value=None, infos={"epoch": 1, "global_step": 5})
    # the second save dies after writing state.msgpack, before the swap
    plan = FaultPlan([Fault("ckpt.state_written", "kill", at=0)])
    with plan.activate():
        with pytest.raises(SimulatedKill):
            mgr.save(tiny_state, value=None,
                     infos={"epoch": 2, "global_step": 10})
    # previous generation intact, verified, and picked up on restore
    restored = mgr.restore_latest(tiny_state)
    assert restored is not None
    assert restored[1]["epoch"] == 1
    assert log.of("ckpt_corrupt") == []
    # the next save reclaims the stale .tmp and completes
    mgr.save(tiny_state, value=None, infos={"epoch": 3, "global_step": 15})
    assert mgr.restore_latest(tiny_state)[1]["epoch"] == 3


def test_save_retries_transient_io_errors(tiny_state, tmp_path):
    log = LogSink()
    mgr = CheckpointManager(
        str(tmp_path), log=log,
        retry=RetryPolicy(max_attempts=4, base_delay=0.001),
    )
    plan = FaultPlan([Fault("ckpt.save", "io_error", at=0, times=2)])
    with plan.activate():
        mgr.save(tiny_state, value=None, infos={"epoch": 1})
    assert len(log.of("ckpt_retry")) == 2
    assert mgr.restore_latest(tiny_state) is not None


# ---- EventLogger ------------------------------------------------------------

def test_event_logger_context_manager_records_crash(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with pytest.raises(RuntimeError):
        with EventLogger(path, echo=False) as log:
            log.log("step", loss=1.0)
            raise RuntimeError("boom mid-epoch")
    events = [json.loads(l) for l in open(path)]
    assert [e["event"] for e in events] == ["step", "crash"]
    assert events[-1]["error"] == "RuntimeError"
    assert "boom" in events[-1]["detail"]


def test_event_logger_clean_exit_no_crash_event(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLogger(path, echo=False) as log:
        log.log("step", loss=1.0)
    events = [json.loads(l) for l in open(path)]
    assert [e["event"] for e in events] == ["step"]


def test_event_logger_flush_and_double_close(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLogger(path, echo=False)
    log.log("a")
    log.flush()
    assert [json.loads(l)["event"] for l in open(path)] == ["a"]
    log.close()
    log.close()  # idempotent (atexit may race a manual close)


# ---- health.py: heartbeats, watchdog, rendezvous, DCN spans -----------------

def _counter(name):
    from cst_captioning_tpu import obs

    return obs.counter(name).snapshot()


def test_health_monitor_sees_peer_beats_and_detects_silence(tmp_path):
    """Two monitors share a heartbeat dir; B goes silent -> A declares it
    lost after timeout_s x misses (driven with an injected clock, no real
    sleeps)."""
    from cst_captioning_tpu.resilience.health import HealthMonitor

    now = {"t": 0.0}
    clock = lambda: now["t"]
    a = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2, timeout_s=1.0,
                      misses=2, clock=clock, start_thread=False).start()
    b = HealthMonitor(str(tmp_path), host_id=1, num_hosts=2, timeout_s=1.0,
                      misses=2, clock=clock, start_thread=False).start()
    try:
        b.beat()
        assert a.poll() == []
        assert not a.peer_lost and a.survivors() == [0, 1]
        # B beats again later: stays alive
        now["t"] = 0.9
        b.beat()
        assert a.poll() == []
        # then goes silent: first stale poll is a strike, not a death...
        now["t"] = 2.0
        assert a.poll() == []
        assert not a.peer_lost
        # ...the second consecutive stale poll (the debounce) declares loss
        now["t"] = 2.1
        assert a.poll() == [1]
        assert a.peer_lost and a.lost() == [1] and a.survivors() == [0]
        # acknowledge clears the pending flag; the lost record stays
        a.acknowledge()
        assert not a.peer_lost and a.lost() == [1]
    finally:
        a.stop()
        b.stop()


def test_health_monitor_never_seen_peer_is_not_declared_dead(tmp_path):
    """A simulated peer that never heartbeated must not be 'lost' by
    staleness — only a tombstone (partial_preempt) can kill a phantom."""
    from cst_captioning_tpu.resilience.health import HealthMonitor

    now = {"t": 0.0}
    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=3, timeout_s=0.5,
                        misses=1, clock=lambda: now["t"],
                        start_thread=False).start()
    try:
        now["t"] = 100.0
        assert mon.poll() == []
        assert not mon.peer_lost
    finally:
        mon.stop()


def test_health_simulate_loss_is_synchronous_and_leaves_tombstone(tmp_path):
    from cst_captioning_tpu.resilience.health import HealthMonitor

    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2,
                        start_thread=False).start()
    try:
        mon.simulate_loss(1)
        assert mon.peer_lost and mon.lost() == [1]
        assert os.path.exists(str(tmp_path / "host1.dead"))
        with pytest.raises(ValueError):
            mon.simulate_loss(0)  # self-preemption is the 'preempt' kind
    finally:
        mon.stop()


def test_health_record_collective_refreshes_liveness(tmp_path):
    """A completed collective is a piggybacked heartbeat: it resets the
    staleness clock for every peer."""
    from cst_captioning_tpu.resilience.health import HealthMonitor

    now = {"t": 0.0}
    a = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2, timeout_s=1.0,
                      misses=1, clock=lambda: now["t"],
                      start_thread=False).start()
    b = HealthMonitor(str(tmp_path), host_id=1, num_hosts=2,
                      start_thread=False).start()
    try:
        b.beat()
        a.poll()
        now["t"] = 5.0
        a.record_collective()  # the barrier completed at t=5
        now["t"] = 5.5        # < timeout since the collective
        assert a.poll() == []
        assert not a.peer_lost
    finally:
        a.stop()
        b.stop()


def test_health_watchdog_thread_beats_and_detects(tmp_path):
    """Real-thread smoke: the watchdog writes heartbeats on its own and
    detects a tombstoned peer without manual poll() calls."""
    import time as _time

    from cst_captioning_tpu.resilience.health import HealthMonitor

    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2,
                        interval_s=0.02, timeout_s=5.0, misses=2).start()
    try:
        deadline = _time.monotonic() + 5.0
        while not os.path.exists(str(tmp_path / "host0.hb")):
            assert _time.monotonic() < deadline, "watchdog never beat"
            _time.sleep(0.01)
        # kill the phantom peer via tombstone; the thread must notice
        with open(str(tmp_path / "host1.dead"), "w") as f:
            f.write("{}")
        while not mon.peer_lost:
            assert _time.monotonic() < deadline, "watchdog never saw the tombstone"
            _time.sleep(0.01)
        assert mon.lost() == [1]
    finally:
        mon.stop()


def test_rendezvous_completes_when_all_present(tmp_path):
    from cst_captioning_tpu.resilience.health import rendezvous

    # peer 1 already checked in (another process in production)
    gen_dir = tmp_path / "rendezvous_0003"
    gen_dir.mkdir()
    (gen_dir / "host1.json").write_text('{"host": 1}')
    got = rendezvous(str(tmp_path), host_id=0, hosts=[0, 1], generation=3,
                     timeout_s=1.0, sleep=lambda s: None)
    assert got == [0, 1]


def test_rendezvous_times_out_naming_missing_hosts(tmp_path):
    from cst_captioning_tpu.resilience.health import (
        RendezvousTimeout,
        rendezvous,
    )

    now = {"t": 0.0}

    def sleep(s):
        now["t"] += s

    with pytest.raises(RendezvousTimeout, match=r"\[2\]"):
        rendezvous(str(tmp_path), host_id=0, hosts=[0, 2], generation=0,
                   timeout_s=1.0, clock=lambda: now["t"], sleep=sleep)


def test_rendezvous_backoff_grows_poll_interval(tmp_path):
    from cst_captioning_tpu.resilience.health import (
        RendezvousTimeout,
        rendezvous,
    )

    now = {"t": 0.0}
    sleeps = []

    def sleep(s):
        sleeps.append(s)
        now["t"] += s

    with pytest.raises(RendezvousTimeout):
        rendezvous(str(tmp_path), host_id=0, hosts=[0, 1], generation=1,
                   timeout_s=1.0, poll_s=0.1, backoff=2.0, max_poll_s=0.5,
                   clock=lambda: now["t"], sleep=sleep)
    assert sleeps[0] == pytest.approx(0.1)
    assert sleeps[1] == pytest.approx(0.2)
    assert max(sleeps) <= 0.5 + 1e-9  # capped


# ---- health.py: rejoin rendezvous (grow-back) --------------------------------

def test_health_rejoin_validated_readmission_round_trip(tmp_path):
    """Loss -> recovery -> validate -> readmit restores the membership and
    consumes every marker (tombstone + rejoin)."""
    from cst_captioning_tpu.resilience.health import HealthMonitor

    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2, misses=2,
                        start_thread=False).start()
    try:
        mon.simulate_loss(1)
        mon.acknowledge()
        assert mon.survivors() == [0]
        before = _counter("health.peer_readmitted")
        mon.simulate_recovery(1)
        # announced: rejoin marker + fresh heartbeat, tombstone consumed
        assert os.path.exists(str(tmp_path / "host1.rejoin"))
        assert not os.path.exists(str(tmp_path / "host1.dead"))
        assert list(mon.pending_rejoins()) == [1]
        marker = mon.validate_rejoin(1, mon.generation + 1)
        assert marker["host"] == 1
        mon.readmit(1)
        assert mon.survivors() == [0, 1] and mon.lost() == []
        assert not os.path.exists(str(tmp_path / "host1.rejoin"))
        assert _counter("health.peer_readmitted") == before + 1
        with pytest.raises(ValueError):
            mon.readmit(1)  # no longer lost: nothing to readmit
    finally:
        mon.stop()


def test_health_rejoin_stale_generation_refused(tmp_path):
    """A marker from an earlier regrow round never admits: the host must
    re-announce at the current generation."""
    from cst_captioning_tpu.resilience.health import (
        HealthMonitor,
        RejoinRefused,
    )

    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2, misses=1,
                        start_thread=False).start()
    try:
        mon.simulate_loss(1)
        mon.acknowledge()
        mon.announce_rejoin(1, host=1)  # an old round's marker
        with pytest.raises(RejoinRefused, match="stale rejoin generation"):
            mon.validate_rejoin(1, 2)
        # right generation but no recovered heartbeat: still refused
        mon.announce_rejoin(2, host=1)
        with pytest.raises(RejoinRefused, match="went silent"):
            mon.validate_rejoin(1, 2)
        # a refusal leaves the degraded membership untouched
        assert mon.survivors() == [0] and mon.lost() == [1]
    finally:
        mon.stop()


def test_health_rejoin_dead_incarnation_heartbeat_refused(tmp_path):
    """Liveness means a FRESH seq stream: the dead incarnation's stale
    heartbeat file (seq recorded before the loss) never passes."""
    from cst_captioning_tpu.resilience.health import (
        HealthMonitor,
        RejoinRefused,
    )

    now = {"t": 0.0}
    clock = lambda: now["t"]  # noqa: E731
    a = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2, timeout_s=1.0,
                      misses=1, clock=clock, start_thread=False).start()
    b = HealthMonitor(str(tmp_path), host_id=1, num_hosts=2, timeout_s=1.0,
                      misses=1, clock=clock, start_thread=False).start()
    try:
        b.beat()
        a.poll()  # A records B's pre-loss seq
        a.simulate_loss(1)
        a.acknowledge()
        a.announce_rejoin(a.generation + 1, host=1)
        with pytest.raises(RejoinRefused, match="predates the loss"):
            a.validate_rejoin(1, a.generation + 1)
    finally:
        a.stop()
        b.stop()


def test_attempt_rejoin_budget_exhaustion_counts_refusal(tmp_path):
    """attempt_rejoin retries refused validations under the budgeted policy,
    then gives up with the counters telling the story — and the degraded
    membership untouched."""
    from cst_captioning_tpu.resilience.health import (
        HealthMonitor,
        RejoinRefused,
        attempt_rejoin,
    )

    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2,
                        start_thread=False).start()
    try:
        mon.simulate_loss(1)
        mon.acknowledge()
        attempts = _counter("resilience.regrow.attempts")
        refused = _counter("resilience.regrow.refused")
        sleeps = []
        policy = RetryPolicy(max_attempts=3, base_delay=0.01, budget=10.0,
                             retry_on=(RejoinRefused, OSError))
        # no rejoin marker at all: every validation attempt is refused
        with pytest.raises(RejoinRefused, match="marker absent"):
            attempt_rejoin(mon, 1, 1, policy=policy, sleep=sleeps.append)
        assert len(sleeps) == 2  # retried to the attempt cap, then gave up
        assert _counter("resilience.regrow.attempts") == attempts + 1
        assert _counter("resilience.regrow.refused") == refused + 1
        assert mon.survivors() == [0] and mon.lost() == [1]
    finally:
        mon.stop()


def test_health_rejoin_marker_torn_read_tolerated(tmp_path):
    """A torn/corrupt rejoin marker reads as 'no news', never a crash — and
    the monitor's own publishes are tmp-then-rename, so it can't produce
    one itself."""
    from cst_captioning_tpu.resilience.health import (
        HealthMonitor,
        RejoinRefused,
    )

    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2,
                        start_thread=False).start()
    try:
        mon.simulate_loss(1)
        mon.acknowledge()
        (tmp_path / "host1.rejoin").write_text('{"host": 1, "generat')
        assert mon.read_rejoin(1) is None
        assert mon.pending_rejoins() == {}
        with pytest.raises(RejoinRefused, match="absent or unreadable"):
            mon.validate_rejoin(1, 1)
        # a non-dict payload is equally 'no news'
        (tmp_path / "host1.rejoin").write_text('[1, 2]')
        assert mon.read_rejoin(1) is None
        # the monitor's own writes never leave .tmp litter behind
        mon.beat()
        mon.announce_rejoin(1)
        assert not [n for n in os.listdir(str(tmp_path)) if ".tmp" in n]
    finally:
        mon.stop()


def test_chaos_host_rejoin_requires_active_monitor():
    plan = FaultPlan([Fault("health.rejoin", "host_rejoin", at=0, host=1)])
    with plan.activate():
        with pytest.raises(RuntimeError, match="HealthMonitor"):
            chaos.visit("health.rejoin")


def test_chaos_host_rejoin_flaky_announces_without_checkin(tmp_path):
    """The flaky rejoiner announces (marker + fresh heartbeat — validation
    would PASS) and then dies before the rendezvous check-in; the plain
    kind checks in, so only the flaky run's regrow rendezvous times out."""
    from cst_captioning_tpu.resilience.health import HealthMonitor

    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2, misses=1,
                        start_thread=False).start()
    try:
        mon.simulate_loss(1)
        mon.acknowledge()
        gen = mon.generation + 1
        checkin = tmp_path / f"rendezvous_{gen:04d}" / "host1.json"
        plan = FaultPlan(
            [Fault("health.rejoin", "host_rejoin_flaky", at=0, host=1)]
        )
        with plan.activate():
            chaos.visit("health.rejoin")
        assert list(mon.pending_rejoins()) == [1]
        mon.validate_rejoin(1, gen)  # liveness checks out...
        assert not checkin.exists()  # ...but it died mid-rendezvous
        assert [f["kind"] for f in plan.fired] == ["host_rejoin_flaky"]
        assert plan.faults[0].host == 1  # the rejoiner rides the host field
        # the plain kind pre-checks the phantom into the rendezvous
        plan2 = FaultPlan(
            [Fault("health.rejoin", "host_rejoin", at=0, host=1)]
        )
        with plan2.activate():
            chaos.visit("health.rejoin")
        assert checkin.exists()
    finally:
        mon.stop()


def test_collective_span_emits_stall_event_past_threshold():
    from cst_captioning_tpu.resilience.health import collective_span

    before = _counter("health.dcn_stall")
    with collective_span("test_fast", stall_threshold_s=1e9):
        pass
    assert _counter("health.dcn_stall") == before
    with collective_span("test_stalled", stall_threshold_s=0.0):
        pass  # any duration > 0 exceeds a zero threshold
    assert _counter("health.dcn_stall") == before + 1


# ---- chaos.py: new fault kinds ----------------------------------------------

def test_chaos_partial_h2d_is_transient_and_retryable():
    from cst_captioning_tpu.resilience.chaos import PartialTransferError

    plan = FaultPlan([Fault("prefetch.h2d", "partial_h2d", at=0, times=1)])
    with plan.activate():
        with pytest.raises(PartialTransferError):
            chaos.visit("prefetch.h2d")
        chaos.visit("prefetch.h2d")  # next visit is clean
    assert isinstance(PartialTransferError("x"), OSError)
    assert [f["kind"] for f in plan.fired] == ["partial_h2d"]


def test_chaos_enospc_fault_carries_errno():
    import errno

    plan = FaultPlan([Fault("ckpt.save", "enospc_rotation", at=0)])
    with plan.activate():
        with pytest.raises(OSError) as ei:
            chaos.visit("ckpt.save")
    assert ei.value.errno == errno.ENOSPC


def test_chaos_partial_preempt_requires_active_monitor():
    plan = FaultPlan([Fault("rl.step", "partial_preempt", at=0, host=1)])
    with plan.activate():
        with pytest.raises(RuntimeError, match="HealthMonitor"):
            chaos.visit("rl.step")


def test_chaos_partial_preempt_marks_peer_lost(tmp_path):
    from cst_captioning_tpu.resilience.health import HealthMonitor

    mon = HealthMonitor(str(tmp_path), host_id=0, num_hosts=2,
                        start_thread=False).start()
    try:
        plan = FaultPlan([Fault("rl.step", "partial_preempt", at=1, host=1)])
        with plan.activate():
            chaos.visit("rl.step")
            assert not mon.peer_lost  # fires at visit 1, not 0
            chaos.visit("rl.step")
        assert mon.peer_lost and mon.lost() == [1]
    finally:
        mon.stop()


def test_chaos_unknown_kind_rejected():
    with pytest.raises(ValueError):
        Fault("xe.step", "meteor_strike")


def test_chaos_seeded_at_preserves_host_field():
    plan = FaultPlan(
        [Fault("rl.step", "partial_preempt", at=("rand", 2, 5), host=7)],
        seed=11,
    )
    f = plan.faults[0]
    assert 2 <= f.at < 5 and f.host == 7


# ---- prefetch: slow/partial H2D + wedged-thread stall watchdog --------------

def test_prefetch_partial_h2d_retried_and_all_items_arrive():
    from cst_captioning_tpu.data.prefetch import prefetch_to_device

    before = _counter("resilience.h2d_retry")
    plan = FaultPlan([Fault("prefetch.h2d", "partial_h2d", at=1, times=1)])
    with plan.activate():
        got = list(prefetch_to_device(
            iter(range(4)), size=2, transform=lambda x: x * 10, place=False,
        ))
    assert got == [0, 10, 20, 30]
    assert [f["kind"] for f in plan.fired] == ["partial_h2d"]
    assert _counter("resilience.h2d_retry") == before + 1


def test_prefetch_partial_h2d_exhausting_retries_propagates():
    from cst_captioning_tpu.data.prefetch import prefetch_to_device
    from cst_captioning_tpu.resilience.chaos import PartialTransferError

    plan = FaultPlan([Fault("prefetch.h2d", "partial_h2d", at=0, times=10)])
    with plan.activate():
        with pytest.raises(PartialTransferError):
            list(prefetch_to_device(
                iter(range(2)), size=1, place=False,
            ))


def test_prefetch_slow_h2d_delivers_everything():
    from cst_captioning_tpu.data.prefetch import prefetch_to_device

    plan = FaultPlan([Fault("prefetch.h2d", "slow_h2d", at=0, delay=0.05)])
    with plan.activate():
        got = list(prefetch_to_device(iter(range(3)), size=2, place=False))
    assert got == [0, 1, 2]
    assert plan.fired and plan.fired[0]["kind"] == "slow_h2d"


def test_prefetch_wedged_worker_trips_stall_watchdog_then_recovers():
    """A wedged prefetch thread starves the consumer past stall_warn_s: the
    stall counter fires exactly once for the episode and the run RESUMES
    when the thread unwedges — detection + continuation, not a crash."""
    from cst_captioning_tpu.data.prefetch import prefetch_to_device

    before = _counter("resilience.prefetch_stall")
    plan = FaultPlan(
        [Fault("prefetch.stage", "wedged_prefetch", at=1, delay=0.4)]
    )
    with plan.activate():
        got = list(prefetch_to_device(
            iter(range(3)), size=1, place=False, stall_warn_s=0.05,
        ))
    assert got == [0, 1, 2]
    assert _counter("resilience.prefetch_stall") == before + 1
    assert plan.fired and plan.fired[0]["kind"] == "wedged_prefetch"


# ---- ckpt: ENOSPC-tolerant rotation -----------------------------------------

def test_ckpt_enospc_reclaims_oldest_generation_and_retries(tiny_state, tmp_path):
    """A full disk mid-save deletes the oldest step_* generation, logs a
    structured ckpt_enospc event, and the budgeted retry then succeeds."""
    sink = LogSink()
    mgr = CheckpointManager(
        str(tmp_path), keep=3, log=sink,
        retry=RetryPolicy(max_attempts=3, base_delay=0.0, max_delay=0.0),
    )
    mgr.save_step(tiny_state, 100)
    mgr.save_step(tiny_state, 200)
    before = _counter("resilience.ckpt_enospc")
    plan = FaultPlan([Fault("ckpt.save", "enospc_rotation", at=0, times=1)])
    with plan.activate():
        mgr.save_step(tiny_state, 300)
    assert [f["kind"] for f in plan.fired] == ["enospc_rotation"]
    # the save landed, the OLDEST generation paid for it
    assert [s for s, _ in mgr.step_checkpoints()] == [200, 300]
    (ev,) = sink.of("ckpt_enospc")
    assert ev["freed"] == ["step_00000100"]
    assert _counter("resilience.ckpt_enospc") == before + 1
    state, infos = mgr.restore_latest(jax.device_get(tiny_state))
    assert infos["global_step"] == 300


def test_ckpt_enospc_with_nothing_to_reclaim_gives_up(tiny_state, tmp_path):
    sink = LogSink()
    mgr = CheckpointManager(
        str(tmp_path), keep=3, log=sink,
        retry=RetryPolicy(max_attempts=2, base_delay=0.0, max_delay=0.0),
    )
    plan = FaultPlan([Fault("ckpt.save", "enospc_rotation", at=0, times=5)])
    with plan.activate():
        with pytest.raises(OSError):
            mgr.save_step(tiny_state, 100)
    assert all(ev["freed"] == [] for ev in sink.of("ckpt_enospc"))


def test_save_state_extra_files_ride_the_manifest(tiny_state, tmp_path):
    save_state(str(tmp_path), "latest", tiny_state, {"epoch": 1},
               extra_files={"seam.npz": b"not-really-npz"})
    path = tmp_path / "latest"
    assert (path / "seam.npz").read_bytes() == b"not-really-npz"
    assert verify_manifest(str(path))
    # corrupting the sidecar is caught exactly like a torn state file
    (path / "seam.npz").write_bytes(b"torn")
    with pytest.raises(CorruptCheckpointError, match="seam.npz"):
        verify_manifest(str(path))
