"""Unit tests for the resilience layer: durable checkpoints + manifest
verification, retry/backoff, divergence sentinel, preemption handler, chaos
plan determinism, and the crash-safe EventLogger."""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cst_captioning_tpu.ckpt import CheckpointManager, load_state, save_state
from cst_captioning_tpu.config.config import ModelConfig, TrainConfig
from cst_captioning_tpu.models import CaptionModel
from cst_captioning_tpu.resilience import chaos
from cst_captioning_tpu.resilience.chaos import Fault, FaultPlan, SimulatedKill
from cst_captioning_tpu.resilience.durable import (
    CorruptCheckpointError,
    MANIFEST_FILE,
    verify_manifest,
    write_manifest,
)
from cst_captioning_tpu.resilience.preempt import PreemptionHandler
from cst_captioning_tpu.resilience.retry import RetryPolicy, retry_call
from cst_captioning_tpu.resilience.sentinel import (
    DivergenceSentinel,
    RollbackRequested,
    TrainingDiverged,
)
from cst_captioning_tpu.train import create_train_state, make_optimizer
from cst_captioning_tpu.utils.logging import EventLogger


@pytest.fixture(scope="module")
def tiny_state():
    cfg = ModelConfig(
        vocab_size=12, modalities=(("resnet", 6),), d_embed=8, d_hidden=8,
        d_att=4, encoder="meanpool", max_len=5, max_frames=3, dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(2, 3, 6)), jnp.float32)}
    masks = {"resnet": jnp.ones((2, 3), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, 12, size=(2, 5)), jnp.int32)
    tx = make_optimizer(TrainConfig(lr=1e-3), 10)
    return create_train_state(model, tx, (feats, masks, labels), seed=0)


class LogSink:
    """EventLogger.log-compatible callable that records events."""

    def __init__(self):
        self.events = []

    def __call__(self, event, **fields):
        self.events.append({"event": event, **fields})

    def of(self, kind):
        return [e for e in self.events if e["event"] == kind]


# ---- durable.py -------------------------------------------------------------

def test_manifest_roundtrip_and_truncation(tmp_path):
    d = str(tmp_path)
    blob = b"x" * 1000
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(blob)
    write_manifest(d, {"state.msgpack": blob})
    assert verify_manifest(d) is True

    with open(os.path.join(d, "state.msgpack"), "r+b") as f:
        f.truncate(500)
    with pytest.raises(CorruptCheckpointError, match="size"):
        verify_manifest(d)

    # same size, flipped bytes -> checksum catches it
    with open(os.path.join(d, "state.msgpack"), "wb") as f:
        f.write(b"y" * 1000)
    with pytest.raises(CorruptCheckpointError, match="sha256"):
        verify_manifest(d)


def test_manifest_missing_is_legacy_not_error(tmp_path):
    assert verify_manifest(str(tmp_path)) is False


def test_save_state_writes_verified_manifest(tiny_state, tmp_path):
    path = save_state(str(tmp_path), "latest", tiny_state, {"epoch": 1})
    assert verify_manifest(path) is True
    manifest = json.load(open(os.path.join(path, MANIFEST_FILE)))
    assert set(manifest["files"]) == {"state.msgpack", "infos.json"}


def test_truncated_state_detected_on_load(tiny_state, tmp_path):
    save_state(str(tmp_path), "latest", tiny_state)
    sp = os.path.join(str(tmp_path), "latest", "state.msgpack")
    with open(sp, "r+b") as f:
        f.truncate(os.path.getsize(sp) // 2)
    with pytest.raises(CorruptCheckpointError):
        load_state(str(tmp_path), "latest", tiny_state)


def test_resave_keeps_previous_generation(tiny_state, tmp_path):
    save_state(str(tmp_path), "latest", tiny_state, {"epoch": 1})
    save_state(str(tmp_path), "latest", tiny_state, {"epoch": 2})
    _, infos = load_state(str(tmp_path), "latest", tiny_state)
    assert infos["epoch"] == 2
    # the demoted generation is intact and loadable
    _, prev_infos = load_state(str(tmp_path), "latest.prev", tiny_state)
    assert prev_infos["epoch"] == 1


# ---- chaos.py ---------------------------------------------------------------

def test_chaos_inactive_is_noop():
    payload = object()
    assert chaos.visit("anything", payload) is payload


def test_chaos_kill_fires_at_exact_visit():
    plan = FaultPlan([Fault("pt", "kill", at=2)])
    with plan.activate():
        chaos.visit("pt")
        chaos.visit("pt")
        with pytest.raises(SimulatedKill):
            chaos.visit("pt")
    assert plan.fired == [{"point": "pt", "kind": "kill", "visit": 2}]
    # deactivated again
    chaos.visit("pt")


def test_chaos_io_error_window_then_clean():
    plan = FaultPlan([Fault("io", "io_error", at=0, times=2)])
    with plan.activate():
        for _ in range(2):
            with pytest.raises(OSError):
                chaos.visit("io")
        chaos.visit("io")  # third visit is clean
    assert plan.visits("io") == 3


def test_chaos_seeded_random_at_is_deterministic():
    spec = [Fault("pt", "kill", at=("rand", 5, 50))]
    a = FaultPlan(list(spec), seed=7)
    b = FaultPlan([Fault("pt", "kill", at=("rand", 5, 50))], seed=7)
    c = FaultPlan([Fault("pt", "kill", at=("rand", 5, 50))], seed=8)
    assert a.faults[0].at == b.faults[0].at
    assert 5 <= a.faults[0].at < 50
    assert a.faults[0].at != c.faults[0].at or True  # seeds may collide; just bounds-check c
    assert 5 <= c.faults[0].at < 50


def test_chaos_nan_poisons_batch_features():
    class B:
        feats = {"resnet": np.ones((2, 3), np.float32)}

    plan = FaultPlan([Fault("b", "nan", at=1)])
    with plan.activate():
        clean = B()
        chaos.visit("b", clean)
        assert np.isfinite(clean.feats["resnet"]).all()
        poisoned = B()
        chaos.visit("b", poisoned)
        assert np.isnan(poisoned.feats["resnet"]).all()


def test_chaos_single_active_plan():
    p1, p2 = FaultPlan([]), FaultPlan([])
    with p1.activate():
        with pytest.raises(RuntimeError, match="already active"):
            p2.activate().__enter__()


# ---- retry.py ---------------------------------------------------------------

def test_retry_succeeds_after_transients():
    calls = {"n": 0}
    sleeps = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    events = []
    out = retry_call(
        flaky,
        policy=RetryPolicy(max_attempts=4, base_delay=0.01, seed=1),
        on_retry=events.append,
        sleep=sleeps.append,
    )
    assert out == "ok" and calls["n"] == 3
    assert len(events) == 2 and len(sleeps) == 2
    assert events[0]["error"] == "OSError" and events[0]["attempt"] == 1


def test_retry_exhausts_attempts_and_reraises():
    def always():
        raise OSError("down")

    with pytest.raises(OSError, match="down"):
        retry_call(
            always,
            policy=RetryPolicy(max_attempts=3, base_delay=0.001),
            sleep=lambda d: None,
        )


def test_retry_budget_caps_total_sleep():
    def always():
        raise OSError("down")

    sleeps = []
    with pytest.raises(OSError):
        retry_call(
            always,
            policy=RetryPolicy(
                max_attempts=10, base_delay=1.0, factor=1.0, jitter=0.0,
                budget=2.5,
            ),
            sleep=sleeps.append,
        )
    assert len(sleeps) == 2  # third 1s sleep would exceed the 2.5s budget


def test_retry_nonretryable_propagates_immediately():
    calls = {"n": 0}

    def boom():
        calls["n"] += 1
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        retry_call(boom, policy=RetryPolicy(max_attempts=5),
                   sleep=lambda d: None)
    assert calls["n"] == 1


def test_retry_jitter_is_seed_deterministic():
    p = RetryPolicy(max_attempts=5, seed=42)
    assert p.delays() == RetryPolicy(max_attempts=5, seed=42).delays()
    assert p.delays() != RetryPolicy(max_attempts=5, seed=43).delays()


def test_simulated_kill_escapes_retry():
    def killed():
        raise SimulatedKill("host died")

    with pytest.raises(SimulatedKill):
        retry_call(killed, policy=RetryPolicy(max_attempts=5),
                   sleep=lambda d: None)


# ---- sentinel.py ------------------------------------------------------------

def test_sentinel_skip_batch_logs_and_continues():
    log = LogSink()
    s = DivergenceSentinel(policy="skip_batch", log=log)
    s.push(1, jnp.float32(1.0), jnp.float32(0.0))
    s.push(2, jnp.float32(float("nan")), jnp.float32(1.0))
    s.push(3, jnp.float32(0.9), jnp.float32(0.0))
    s.flush()
    events = log.of("divergence")
    assert len(events) == 1
    assert events[0]["step"] == 2 and events[0]["kind"] == "nonfinite"
    assert events[0]["action"] == "skip_batch"
    assert s.skipped == 1


def test_sentinel_abort_raises():
    s = DivergenceSentinel(policy="abort")
    s.push(1, jnp.float32(float("inf")), None)
    with pytest.raises(TrainingDiverged):
        s.flush()


def test_sentinel_rollback_raises_with_context():
    s = DivergenceSentinel(policy="rollback", check_every=1)
    with pytest.raises(RollbackRequested) as ei:
        s.push(7, jnp.float32(float("nan")), jnp.float32(1.0))
    assert ei.value.step == 7 and ei.value.kind == "nonfinite"


def test_sentinel_spike_detection_after_warmup():
    log = LogSink()
    s = DivergenceSentinel(
        policy="abort", log=log, spike_factor=5.0, warmup=4,
    )
    for i in range(6):
        s.push(i, jnp.float32(1.0), None)
    s.flush()
    s.push(10, jnp.float32(50.0), None)  # 50x the median
    with pytest.raises(TrainingDiverged):
        s.flush()
    assert log.of("divergence")[0]["kind"] == "spike"
    # under skip_batch a spike is logged, not acted on (update already applied)
    log2 = LogSink()
    s2 = DivergenceSentinel(
        policy="skip_batch", log=log2, spike_factor=5.0, warmup=4,
    )
    for i in range(6):
        s2.push(i, jnp.float32(1.0), None)
    s2.push(10, jnp.float32(50.0), None)
    s2.flush()
    assert log2.of("divergence")[0]["action"] == "logged"


def test_sentinel_off_is_free():
    s = DivergenceSentinel(policy="off")
    s.push(1, jnp.float32(float("nan")), jnp.float32(1.0))
    s.flush()  # no readback, no raise
    assert s._buf == []


# ---- preempt.py -------------------------------------------------------------

def test_preemption_handler_latches_sigterm():
    with PreemptionHandler() as h:
        assert h.installed and not h.requested
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.requested
    # prior disposition restored
    assert signal.getsignal(signal.SIGTERM) != h._on_signal


def test_preemption_handler_chains_previous_python_handler():
    hits = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: hits.append(s))
    try:
        with PreemptionHandler() as h:
            os.kill(os.getpid(), signal.SIGTERM)
            assert h.requested and hits == [signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


# ---- CheckpointManager: rotation, ordering, corrupt fallback ----------------

def test_step_checkpoint_rotation(tiny_state, tmp_path):
    mgr = CheckpointManager(str(tmp_path), keep=2)
    for step in (10, 20, 30, 40):
        mgr.save_step(tiny_state, step, {"epoch": 0})
    assert [s for s, _ in mgr.step_checkpoints()] == [30, 40]


def test_restore_prefers_newest_by_global_step(tiny_state, tmp_path):
    log = LogSink()
    mgr = CheckpointManager(str(tmp_path), keep=3, log=log)
    mgr.save(tiny_state, value=None, infos={"epoch": 1, "global_step": 6})
    mgr.save_step(tiny_state, 9, {"epoch": 1, "batch_index": 3})
    restored = mgr.restore_latest(tiny_state)
    assert restored is not None
    _, infos = restored
    assert infos["global_step"] == 9 and infos["batch_index"] == 3


def test_corrupt_latest_falls_back_with_logged_event(tiny_state, tmp_path):
    log = LogSink()
    mgr = CheckpointManager(str(tmp_path), log=log)
    mgr.save(tiny_state, value=0.5, infos={"epoch": 1, "global_step": 6})
    sp = os.path.join(str(tmp_path), "latest", "state.msgpack")
    with open(sp, "r+b") as f:
        f.truncate(os.path.getsize(sp) // 2)
    restored = mgr.restore_latest(tiny_state)
    assert restored is not None  # fell back to 'best'
    _, infos = restored
    assert infos["epoch"] == 1
    events = log.of("ckpt_corrupt")
    assert len(events) == 1 and events[0]["name"] == "latest"
    assert events[0]["error"] == "CorruptCheckpointError"


def test_kill_mid_save_previous_generation_survives(tiny_state, tmp_path):
    log = LogSink()
    mgr = CheckpointManager(str(tmp_path), log=log)
    mgr.save(tiny_state, value=None, infos={"epoch": 1, "global_step": 5})
    # the second save dies after writing state.msgpack, before the swap
    plan = FaultPlan([Fault("ckpt.state_written", "kill", at=0)])
    with plan.activate():
        with pytest.raises(SimulatedKill):
            mgr.save(tiny_state, value=None,
                     infos={"epoch": 2, "global_step": 10})
    # previous generation intact, verified, and picked up on restore
    restored = mgr.restore_latest(tiny_state)
    assert restored is not None
    assert restored[1]["epoch"] == 1
    assert log.of("ckpt_corrupt") == []
    # the next save reclaims the stale .tmp and completes
    mgr.save(tiny_state, value=None, infos={"epoch": 3, "global_step": 15})
    assert mgr.restore_latest(tiny_state)[1]["epoch"] == 3


def test_save_retries_transient_io_errors(tiny_state, tmp_path):
    log = LogSink()
    mgr = CheckpointManager(
        str(tmp_path), log=log,
        retry=RetryPolicy(max_attempts=4, base_delay=0.001),
    )
    plan = FaultPlan([Fault("ckpt.save", "io_error", at=0, times=2)])
    with plan.activate():
        mgr.save(tiny_state, value=None, infos={"epoch": 1})
    assert len(log.of("ckpt_retry")) == 2
    assert mgr.restore_latest(tiny_state) is not None


# ---- EventLogger ------------------------------------------------------------

def test_event_logger_context_manager_records_crash(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with pytest.raises(RuntimeError):
        with EventLogger(path, echo=False) as log:
            log.log("step", loss=1.0)
            raise RuntimeError("boom mid-epoch")
    events = [json.loads(l) for l in open(path)]
    assert [e["event"] for e in events] == ["step", "crash"]
    assert events[-1]["error"] == "RuntimeError"
    assert "boom" in events[-1]["detail"]


def test_event_logger_clean_exit_no_crash_event(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with EventLogger(path, echo=False) as log:
        log.log("step", loss=1.0)
    events = [json.loads(l) for l in open(path)]
    assert [e["event"] for e in events] == ["step"]


def test_event_logger_flush_and_double_close(tmp_path):
    path = str(tmp_path / "events.jsonl")
    log = EventLogger(path, echo=False)
    log.log("a")
    log.flush()
    assert [json.loads(l)["event"] for l in open(path)] == ["a"]
    log.close()
    log.close()  # idempotent (atexit may race a manual close)
