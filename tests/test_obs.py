"""Observability subsystem: spans, metrics, Prometheus export, run reports.

Covers the obs/ acceptance surface: span nesting/ordering/self-time, the
thread-local context, histogram bucket math, the Prometheus textfile format,
report aggregation from a synthetic event file (the committed fixture
scripts/lint.sh also smokes), disabled-mode no-op (zero events, zero files),
profiler event routing, and a real chaos run whose report shows the
injected faults.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time

import numpy as np
import pytest

from cst_captioning_tpu import obs
from cst_captioning_tpu.obs.metrics import Histogram, Registry, StepMeter
from cst_captioning_tpu.obs.report import (
    build_report,
    render_report,
    report_run,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_RUN = os.path.join(REPO, "tests", "fixtures", "obs_run")


@pytest.fixture(autouse=True)
def _clean_obs():
    """Obs state is process-global: every test starts and ends detached."""
    obs.shutdown()
    obs.REGISTRY.reset()
    yield
    obs.shutdown()
    obs.REGISTRY.reset()


def read_events(run_dir):
    with open(os.path.join(run_dir, "events.jsonl")) as f:
        return [json.loads(l) for l in f if l.strip()]


def spans_of(events, name=None):
    out = [e for e in events if e["event"] == "span"]
    return [e for e in out if e["name"] == name] if name else out


# ---- spans ------------------------------------------------------------------

def test_span_nesting_ordering_and_self_time(tmp_path):
    obs.configure(str(tmp_path / "run"), run="t")
    with obs.span("outer"):
        time.sleep(0.02)
        with obs.span("inner", tag="a"):
            time.sleep(0.03)
        time.sleep(0.0)
    obs.shutdown()
    events = read_events(str(tmp_path / "run"))
    assert events[0]["event"] == "run_start"
    assert events[-1]["event"] == "run_end"
    sp = spans_of(events)
    # inner finishes (and is therefore emitted) before outer
    assert [s["name"] for s in sp] == ["inner", "outer"]
    inner, outer = sp
    assert inner["depth"] == 1 and inner["parent"] == "outer"
    assert inner["tag"] == "a"
    assert outer["depth"] == 0 and "parent" not in outer
    assert outer["dur"] >= inner["dur"] >= 0.03
    # self time excludes the child exactly (three values each rounded to 1e-6
    # independently, so the identity holds to 1.5e-6 in the worst case)
    assert outer["self_dur"] == pytest.approx(
        outer["dur"] - inner["dur"], abs=2e-6
    )
    assert inner["self_dur"] == pytest.approx(inner["dur"], abs=1e-6)


def test_span_context_fields_attach_and_detach(tmp_path):
    obs.configure(str(tmp_path / "run"), run="t")
    obs.set_context(phase="xe", epoch=3, step=7)
    with obs.span("a"):
        pass
    obs.set_context(step=None)
    obs.event("ping")
    obs.shutdown()
    events = read_events(str(tmp_path / "run"))
    (a,) = spans_of(events, "a")
    assert (a["phase"], a["epoch"], a["step"]) == ("xe", 3, 7)
    (ping,) = [e for e in events if e["event"] == "ping"]
    assert ping["phase"] == "xe" and "step" not in ping


def test_span_attr_never_shadows_schema(tmp_path):
    obs.configure(str(tmp_path / "run"), run="t")
    with obs.span("ckpt.save", name="latest", dur="shadow"):
        pass
    obs.shutdown()
    (s,) = spans_of(read_events(str(tmp_path / "run")), "ckpt.save")
    assert s["name"] == "ckpt.save" and isinstance(s["dur"], float)
    assert s["attr_name"] == "latest" and s["attr_dur"] == "shadow"


def test_trace_json_is_perfetto_compatible(tmp_path):
    obs.configure(str(tmp_path / "run"), run="t")
    with obs.span("outer"):
        with obs.span("inner"):
            pass
    w = obs.span("window", track="mytrack").begin()
    w.end()
    obs.shutdown()
    doc = json.load(open(tmp_path / "run" / "trace.json"))
    evs = doc["traceEvents"]
    assert {e["name"] for e in evs} == {"outer", "inner", "window"}
    for e in evs:
        assert e["ph"] == "X"
        assert set(e) >= {"name", "ph", "ts", "dur", "pid", "tid"}
        assert e["ts"] >= 0 and e["dur"] >= 0
    (win,) = [e for e in evs if e["name"] == "window"]
    assert win["tid"] == "mytrack"  # virtual track, not the thread


def test_disabled_mode_is_a_noop(tmp_path):
    """train.obs off: zero events, zero files, shared no-op span object."""
    assert obs.configure(str(tmp_path / "off"), enabled=False) is None
    assert not obs.enabled()
    s1, s2 = obs.span("a", big=1), obs.span("b")
    assert s1 is s2  # the shared singleton: no allocation per call
    with s1:
        pass
    obs.event("nope", x=1)
    obs.snapshot_metrics()
    obs.maybe_snapshot(100)
    assert not os.path.exists(tmp_path / "off")
    assert list(tmp_path.iterdir()) == []


def test_span_survives_foreign_stack_state(tmp_path):
    """A begin() left open (crash path) degrades accounting, never corrupts."""
    obs.configure(str(tmp_path / "run"), run="t")
    leaked = obs.span("leaked").begin()
    with obs.span("ok"):
        pass
    # ending the outer leaked span pops past the already-finished child
    leaked.end()
    obs.shutdown()
    names = [s["name"] for s in spans_of(read_events(str(tmp_path / "run")))]
    assert names == ["ok", "leaked"]


# ---- metrics ----------------------------------------------------------------

def test_histogram_bucket_math():
    h = Histogram("t", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.5, 3.0, 100.0):
        h.observe(v)
    assert h.counts == [1, 1, 1, 1]
    assert h.count == 4
    assert h.sum == pytest.approx(105.0)
    assert h.max == 100.0
    # boundary lands in the bucket it bounds (le semantics)
    h2 = Histogram("t2", buckets=(1.0, 2.0))
    h2.observe(1.0)
    assert h2.counts == [1, 0, 0]
    # interpolated quantiles: rank 2 of 4 tops out bucket (1, 2]
    assert h.quantile(0.5) == pytest.approx(2.0)
    assert h.quantile(1.0) == 100.0  # overflow bucket reports the exact max
    assert h.quantile(0.0) == pytest.approx(0.5, abs=0.5)
    with pytest.raises(ValueError):
        h.quantile(1.5)
    with pytest.raises(ValueError):
        Histogram("bad", buckets=(2.0, 1.0))


def test_registry_kinds_and_conflicts():
    reg = Registry()
    c = reg.counter("a.b")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    reg.gauge("g").set(7)
    with pytest.raises(TypeError):
        reg.counter("g")  # name already registered as a gauge
    snap = reg.snapshot()
    assert snap["counters"]["a.b"] == 3.5
    assert snap["gauges"]["g"] == 7.0


def test_prometheus_textfile_format():
    reg = Registry()
    reg.counter("resilience.nan_skip").inc(3)
    reg.gauge("prefetch.queue_depth").set(2)
    h = reg.histogram("xe.step_seconds", buckets=(0.1, 0.5))
    for v in (0.05, 0.3, 2.0):
        h.observe(v)
    text = reg.to_prometheus()
    lines = text.splitlines()
    assert "# TYPE resilience_nan_skip counter" in lines
    assert "resilience_nan_skip 3" in lines
    assert "prefetch_queue_depth 2" in lines
    assert "# TYPE xe_step_seconds histogram" in lines
    # cumulative buckets + +Inf == count
    assert 'xe_step_seconds_bucket{le="0.1"} 1' in lines
    assert 'xe_step_seconds_bucket{le="0.5"} 2' in lines
    assert 'xe_step_seconds_bucket{le="+Inf"} 3' in lines
    assert "xe_step_seconds_count 3" in lines
    assert any(l.startswith("xe_step_seconds_sum 2.35") for l in lines)
    assert text.endswith("\n")


def test_step_meter_windows_and_compile_exclusion():
    meter = StepMeter("tmeter")
    meter.begin_epoch()
    meter.tick(8, first=True)   # compile step: excluded from the histogram
    time.sleep(0.01)
    meter.tick(8)
    meter.tick(8)
    s = meter.epoch_summary()
    assert s["steps"] == 2.0
    assert meter.clips.value == 16.0
    assert meter.compile_secs.value > 0.0
    assert meter.hist.count == 2
    assert s["clips_per_sec"] > 0.0
    # the next epoch windows its own deltas
    meter.begin_epoch()
    meter.tick(8)
    assert meter.epoch_summary()["steps"] == 1.0


def test_metrics_snapshot_lands_in_event_stream(tmp_path):
    obs.configure(str(tmp_path / "run"), run="t", snapshot_every=2)
    obs.counter("resilience.rollback").inc()
    obs.maybe_snapshot(1)   # off-cadence: no snapshot
    obs.maybe_snapshot(2)   # on-cadence
    obs.shutdown()          # final snapshot
    events = read_events(str(tmp_path / "run"))
    snaps = [e for e in events if e["event"] == "metrics"]
    assert len(snaps) == 2 and snaps[0]["step"] == 2
    assert snaps[-1]["final"] is True
    assert snaps[-1]["counters"]["resilience.rollback"] == 1
    # the Prometheus textfile is (re)written by snapshots
    prom = open(tmp_path / "run" / "metrics.prom").read()
    assert "resilience_rollback 1" in prom


# ---- profiler routing (satellite 1) -----------------------------------------

def test_step_profiler_routes_through_event_stream(tmp_path, monkeypatch):
    import jax

    from cst_captioning_tpu.utils.profiling import StepProfiler

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: calls.append(("start", d)))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append(("stop",)))
    obs.configure(str(tmp_path / "run"), run="t")
    logged = []
    prof = StepProfiler(str(tmp_path / "trace"), steps=2, skip=1,
                        log=lambda ev, **f: logged.append((ev, f)))
    for _ in range(5):
        prof.tick()
    assert calls == [("start", str(tmp_path / "trace")), ("stop",)]
    obs.shutdown()
    # no stderr print: completion is a structured event, to BOTH sinks
    assert logged == [(
        "profiler_trace_written",
        {"dir": str(tmp_path / "trace"), "steps": 2},
    )]
    events = read_events(str(tmp_path / "run"))
    assert [e for e in events if e["event"] == "profiler_trace_written"]
    # the capture window is a span on the profiler virtual track
    (win,) = spans_of(events, "profile.window")
    assert win["track"] == "profiler"


# ---- report -----------------------------------------------------------------

def test_report_aggregates_committed_fixture():
    rep = report_run(FIXTURE_RUN)
    assert rep["run"] == "fixture" and rep["complete"]
    assert rep["wall_s"] == pytest.approx(7.5)
    by_name = {p["phase"]: p for p in rep["phases"]}
    assert by_name["xe.step"]["count"] == 2
    assert by_name["xe.step"]["total_s"] == pytest.approx(0.9)
    assert by_name["xe.step"]["max_s"] == pytest.approx(0.5)
    # totals partition: covered == sum of self times, and the epoch spans
    # contribute only their input-wait self time
    assert rep["covered_s"] == pytest.approx(
        sum(p["self_s"] for p in rep["phases"])
    )
    assert by_name["xe.epoch"]["self_s"] == pytest.approx(1.1)
    assert rep["coverage"] == pytest.approx(6.4 / 7.5)
    # background work is reported but never summed against wall clock
    over = {p["phase"] for p in rep["overlap"]}
    assert over == {"prefetch.stage", "profile.window"}
    r = rep["resilience"]
    assert r["nan_skips"] == 1 and r["divergences"] == 2
    assert r["rollbacks"] == 1 and r["retry_attempts"] == 2
    assert r["ckpt_corrupt_fallbacks"] == 1
    assert r["chaos_faults"] == 3
    assert r["chaos_faults_by_kind"] == {"nan": 2, "io_error": 1}
    assert rep["compile"] == {"count": 4, "seconds": 2.5}
    text = render_report(rep)
    assert "xe.step" in text and "chaos faults injected: 3" in text
    assert "nan=2" in text and "rollbacks: 1" in text


def test_report_mfu_column_and_decode_section():
    """The phase table's mfu column (flops.<phase> counters over run wall x
    device.peak_flops, PR 4) and the decode early-exit section (depth
    histogram vs budget) — from a synthetic event stream."""
    span = lambda ts, name, dur: {  # noqa: E731
        "ts": ts, "event": "span", "name": name, "dur": dur,
        "self_dur": dur, "depth": 0, "thread": "main",
    }
    events = [
        {"ts": 0.0, "event": "run_start", "run": "mfu", "thread": "main"},
        span(1.0, "rl.decode", 4.0),
        span(6.0, "rl.update", 2.0),
        span(8.0, "xe.step", 1.0),
        {
            "ts": 9.0, "event": "metrics",
            "counters": {
                "flops.rl.decode": 4e12,   # / 10s wall / 1e12 peak = 0.4
                "flops.rl.update": 1e12,
                "flops.xe.step": 5e11,
            },
            "gauges": {"device.peak_flops": 1e12,
                       "rl.decode.budget": 30.0},
            "histograms": {
                "rl.decode.depth": {
                    "buckets": [10.0, 20.0, 30.0],
                    # two batches exited at depth 15, one ran the budget
                    "counts": [0, 2, 1, 0],
                    "sum": 60.0, "count": 3, "max": 30.0,
                },
            },
        },
        {"ts": 10.0, "event": "run_end", "run": "mfu"},
    ]
    rep = build_report(events)
    by_name = {p["phase"]: p for p in rep["phases"]}
    assert by_name["rl.decode"]["mfu"] == pytest.approx(0.4)
    assert by_name["rl.update"]["mfu"] == pytest.approx(0.1)
    assert by_name["xe.step"]["mfu"] == pytest.approx(0.05)
    d = rep["decode"]
    assert d["batches"] == 3 and d["budget"] == 30.0
    assert d["depth_mean"] == pytest.approx(20.0)
    assert d["saved_frac"] == pytest.approx(1.0 - 20.0 / 30.0)
    assert d["depth_max"] == 30.0
    text = render_report(rep)
    assert "mfu" in text and "0.4000" in text
    assert "decode early-exit" in text and "33.3% of the scan budget" in text


def test_report_mfu_blank_without_counters():
    """Rows without a flops counter (or with no peak gauge) get mfu=None and
    render blank — the fixture run predates the counters."""
    rep = report_run(FIXTURE_RUN)
    assert all(p["mfu"] is None for p in rep["phases"])
    assert rep["decode"] is None
    render_report(rep)  # renders without error


def test_scst_records_flops_and_depth():
    """An SCST step feeds the flops.rl.decode / flops.rl.update counters and
    (with a recorder installed) the rl.decode.depth histogram the report's
    MFU column and decode section read."""
    import jax
    import jax.numpy as jnp
    import numpy as _np

    from cst_captioning_tpu.config.config import (
        ModelConfig, RLConfig, TrainConfig,
    )
    from cst_captioning_tpu.models import CaptionModel
    from cst_captioning_tpu.rl import SCSTTrainer
    from cst_captioning_tpu.train import create_train_state, make_optimizer

    obs.REGISTRY.reset()

    cfg = ModelConfig(
        vocab_size=20, modalities=(("resnet", 6),), d_embed=8, d_hidden=8,
        d_att=4, encoder="meanpool", dropout=0.0, max_len=5, max_frames=3,
        dtype="float32",
    )
    model = CaptionModel(cfg)
    rng = _np.random.default_rng(0)
    feats = {"resnet": jnp.asarray(rng.normal(size=(4, 3, 6)), jnp.float32)}
    masks = {"resnet": jnp.ones((4, 3), jnp.float32)}
    labels = jnp.asarray(rng.integers(4, 20, size=(4, 5)), jnp.int32)
    tx = make_optimizer(TrainConfig(lr=1e-3, grad_clip=5.0), 10)
    state = create_train_state(model, tx, (feats, masks, labels), seed=1)

    reward = lambda vids, rows: _np.ones(len(rows), _np.float32)  # noqa: E731
    scst = SCSTTrainer(
        model, reward, RLConfig(enabled=True, num_rollouts=2, baseline="greedy")
    )
    state, _ = scst.train_step(
        state, feats, masks, ["v0", "v1", "v2", "v3"], jax.random.key(0)
    )
    snap = obs.snapshot()
    assert snap["counters"]["flops.rl.decode"] > 0
    assert snap["counters"]["flops.rl.update"] > 0
    assert snap["gauges"]["rl.decode.budget"] == 5.0
    # the depth histogram only records when a recorder is installed
    assert "rl.decode.depth" not in snap["histograms"]
    obs.REGISTRY.reset()


def test_report_handles_torn_stream_and_missing_end(tmp_path):
    d = tmp_path / "run"
    d.mkdir()
    lines = [
        json.dumps({"ts": 10.0, "event": "run_start", "run": "torn",
                    "thread": "MainThread"}),
        json.dumps({"ts": 11.0, "event": "span", "name": "xe.step",
                    "dur": 1.0, "self_dur": 1.0, "depth": 0,
                    "thread": "MainThread"}),
        '{"ts": 12.0, "event": "span", "na',  # torn final line (kill -9)
    ]
    (d / "events.jsonl").write_text("\n".join(lines))
    # build_report over hand-parsed events == report_run over the torn file
    assert build_report([json.loads(l) for l in lines[:2]])["wall_s"] == 1.0
    rep = report_run(str(d))
    assert not rep["complete"]
    assert rep["wall_s"] == pytest.approx(1.0)  # first..last parseable ts
    assert rep["phases"][0]["phase"] == "xe.step"
    assert "did not close cleanly" in render_report(rep)


def test_report_missing_dir_errors_cleanly(tmp_path):
    from cst_captioning_tpu.cli.obs_report import main as report_main

    assert report_main([str(tmp_path / "nope")]) == 2
    with pytest.raises(FileNotFoundError):
        report_run(str(tmp_path / "nope"))


def test_obs_report_cli_json(tmp_path, capsys):
    from cst_captioning_tpu.cli.obs_report import main as report_main

    assert report_main([FIXTURE_RUN, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["run"] == "fixture"
    assert {p["phase"] for p in rep["phases"]} >= {"xe.step", "rl.reward"}
    capsys.readouterr()
    assert report_main([FIXTURE_RUN]) == 0
    assert "resilience:" in capsys.readouterr().out


def test_live_roundtrip_report_covers_wall_clock(tmp_path):
    """Recorder -> stream -> report: coverage ~1 for fully spanned runs."""
    obs.configure(str(tmp_path / "run"), run="t")
    with obs.span("xe.epoch"):
        for _ in range(3):
            with obs.span("xe.step"):
                time.sleep(0.01)
    with obs.span("eval"):
        time.sleep(0.02)
    obs.shutdown()
    rep = report_run(str(tmp_path / "run"))
    assert rep["complete"]
    by_name = {p["phase"]: p for p in rep["phases"]}
    assert by_name["xe.step"]["count"] == 3
    # phase totals sum to (nearly) the measured wall clock
    assert rep["coverage"] > 0.9
    assert rep["covered_s"] <= rep["wall_s"] + 1e-6


# ---- chaos-run report (satellite: injected faults are visible) --------------

@pytest.fixture(scope="module")
def chaos_datasets(tmp_path_factory):
    from cst_captioning_tpu.data import CaptionDataset, make_synthetic_dataset

    out = tmp_path_factory.mktemp("obssynth")
    synth = make_synthetic_dataset(
        str(out), num_videos=12, num_topics=3, vocab_words=20,
        modalities={"resnet": 16}, max_frames=4, seed=5,
    )
    train = CaptionDataset(
        synth["info_json"], {"resnet": synth["resnet"]}, "train", 4
    )
    return train


def test_chaos_run_report_shows_injected_faults(chaos_datasets, tmp_path):
    from cst_captioning_tpu.config.config import (
        DataConfig,
        EvalConfig,
        ExperimentConfig,
        ModelConfig,
        RLConfig,
        TrainConfig,
    )
    from cst_captioning_tpu.resilience import Fault, FaultPlan
    from cst_captioning_tpu.train.trainer import Trainer

    train_ds = chaos_datasets
    ckpt = str(tmp_path / "ckpt")
    run_dir = str(tmp_path / "obs")
    cfg = ExperimentConfig(
        name="obs-chaos",
        model=ModelConfig(
            vocab_size=len(train_ds.vocab), modalities=(("resnet", 16),),
            d_embed=16, d_hidden=16, d_att=8, encoder="temporal_attention",
            dropout=0.0, max_len=8, max_frames=4, dtype="float32",
        ),
        data=DataConfig(batch_size=8, seq_per_vid=2),
        train=TrainConfig(
            lr=5e-3, grad_clip=5.0, ckpt_dir=ckpt, seed=0, epochs=1,
            eval_every_epochs=100, log_every_steps=1,
            obs=True, obs_dir=run_dir,
        ),
        rl=RLConfig(enabled=False),
        eval=EvalConfig(beam_size=1, max_len=8),
    )
    tr = Trainer(cfg, train_ds, None, log_path=ckpt + "/ev.jsonl",
                 use_mesh=False)
    plan = FaultPlan([Fault("xe.batch", "nan", at=1)])
    with plan.activate():
        tr.train_xe()
    obs.shutdown()
    assert plan.fired

    rep = report_run(run_dir)
    by_name = {p["phase"]: p for p in rep["phases"]}
    # the instrumented run produced the phase table...
    assert by_name["xe.step"]["count"] == 3
    assert "setup" in by_name and "ckpt.save" in by_name
    assert rep["coverage"] > 0.5
    # ...and the resilience summary shows the injected fault end to end:
    # chaos activation -> device guard nan-skip -> sentinel verdict
    r = rep["resilience"]
    assert r["chaos_faults"] >= 1
    assert r["chaos_faults_by_kind"].get("nan", 0) >= 1
    assert r["nan_skips"] == 1
    assert r["divergences"] == 1
    text = render_report(rep)
    assert "nan-skips: 1" in text


def test_trainer_epoch_events_report_meter_latency(chaos_datasets, tmp_path):
    """Satellite: XE epochs log obs-histogram latency (the StepTimer
    replacement) — identical field names to the RL epoch summary."""
    from cst_captioning_tpu.config.config import (
        DataConfig,
        EvalConfig,
        ExperimentConfig,
        ModelConfig,
        RLConfig,
        TrainConfig,
    )
    from cst_captioning_tpu.train.trainer import Trainer

    train_ds = chaos_datasets
    ckpt = str(tmp_path / "ckpt")
    cfg = ExperimentConfig(
        name="meter",
        model=ModelConfig(
            vocab_size=len(train_ds.vocab), modalities=(("resnet", 16),),
            d_embed=16, d_hidden=16, d_att=8, encoder="meanpool",
            dropout=0.0, max_len=8, max_frames=4, dtype="float32",
        ),
        data=DataConfig(batch_size=8, seq_per_vid=2),
        train=TrainConfig(
            lr=5e-3, ckpt_dir=ckpt, seed=0, epochs=1, eval_every_epochs=100,
        ),
        rl=RLConfig(enabled=True, num_rollouts=2, lr=1e-3, epochs=1,
                    baseline="greedy", pipelined=False),
        eval=EvalConfig(beam_size=1, max_len=8),
    )
    tr = Trainer(cfg, train_ds, None, log_path=ckpt + "/ev.jsonl",
                 use_mesh=False)
    tr.train_xe()
    tr.train_rl()
    events = [json.loads(l) for l in open(ckpt + "/ev.jsonl")]
    (xe,) = [e for e in events if e["event"] == "xe_epoch"]
    (rl,) = [e for e in events if e["event"] == "rl_epoch"]
    keys = {"steps", "clips_per_sec", "step_seconds_p50", "step_seconds_p95"}
    assert keys <= set(xe) and keys <= set(rl)
    assert xe["steps"] == 3.0 - 1.0  # first (compile) step excluded
    assert xe["clips_per_sec"] > 0 and rl["clips_per_sec"] > 0
    assert np.isfinite(xe["step_seconds_p95"])


def test_report_decode_compaction_counters():
    """The decode section surfaces the rl.decode.compaction counter pair
    (lanes stepped vs compacted away) and the renderer prints the ledger."""
    events = [
        {"ts": 0.0, "event": "run_start", "run": "comp", "thread": "main"},
        {
            "ts": 1.0, "event": "metrics",
            "counters": {
                "rl.decode.compaction.lanes_stepped": 300.0,
                "rl.decode.compaction.lanes_skipped": 100.0,
            },
            "gauges": {"rl.decode.budget": 30.0},
            "histograms": {
                "rl.decode.depth": {
                    "buckets": [10.0, 20.0, 30.0],
                    "counts": [0, 1, 0, 0],
                    "sum": 15.0, "count": 1, "max": 15.0,
                },
            },
        },
        {"ts": 2.0, "event": "run_end", "run": "comp"},
    ]
    rep = build_report(events)
    d = rep["decode"]
    assert d["lanes_stepped"] == 300.0 and d["lanes_skipped"] == 100.0
    assert d["compaction_saved_frac"] == pytest.approx(0.25)
    text = render_report(rep)
    assert "decode compaction" in text and "25.0% of lane-steps" in text


def test_scst_records_compaction_counters(tmp_path):
    """With a recorder installed, an SCST step feeds the depth histogram
    AND the compaction counter pair from the decoded tokens (the default
    decode compacts, so both counters exist and sum to G*B*depth)."""
    import jax
    import jax.numpy as jnp
    import numpy as _np

    from cst_captioning_tpu.config.config import (
        ModelConfig, RLConfig, TrainConfig,
    )
    from cst_captioning_tpu.models import CaptionModel
    from cst_captioning_tpu.rl import SCSTTrainer
    from cst_captioning_tpu.train import create_train_state, make_optimizer

    obs.REGISTRY.reset()
    obs.configure(str(tmp_path / "obs"), run="comp")
    try:
        cfg = ModelConfig(
            vocab_size=20, modalities=(("resnet", 6),), d_embed=8,
            d_hidden=8, d_att=4, encoder="meanpool", dropout=0.0, max_len=6,
            max_frames=3, dtype="float32", decode_stride=2,
        )
        model = CaptionModel(cfg)
        rng = _np.random.default_rng(0)
        feats = {
            "resnet": jnp.asarray(rng.normal(size=(4, 3, 6)), jnp.float32)
        }
        masks = {"resnet": jnp.ones((4, 3), jnp.float32)}
        labels = jnp.asarray(rng.integers(4, 20, size=(4, 6)), jnp.int32)
        tx = make_optimizer(TrainConfig(lr=1e-3, grad_clip=5.0), 10)
        state = create_train_state(model, tx, (feats, masks, labels), seed=1)
        reward = lambda vids, rows: _np.ones(  # noqa: E731
            len(rows), _np.float32
        )
        scst = SCSTTrainer(
            model, reward,
            RLConfig(enabled=True, num_rollouts=2, baseline="greedy"),
        )
        state, _ = scst.train_step(
            state, feats, masks, ["v0", "v1", "v2", "v3"], jax.random.key(0)
        )
        snap = obs.snapshot()
        stepped = snap["counters"]["rl.decode.compaction.lanes_stepped"]
        skipped = snap["counters"]["rl.decode.compaction.lanes_skipped"]
        depth = snap["histograms"]["rl.decode.depth"]["sum"]
        assert stepped > 0 and skipped >= 0
        assert stepped + skipped == 3 * 4 * depth  # G * B * depth
    finally:
        obs.shutdown()
        obs.REGISTRY.reset()


def test_observe_device_memory_samples_all_local_devices(monkeypatch):
    """Every local device lands in device<k>.* gauges; the legacy aggregate
    device.* gauges carry the max (the HBM-headroom signal on a balanced
    mesh; ROADMAP obs open item, closed PR 5)."""
    import jax

    from cst_captioning_tpu.obs import metrics as m

    class FakeDev:
        def __init__(self, i, used, peak):
            self.id = i
            self._s = {"bytes_in_use": used, "peak_bytes_in_use": peak,
                       "bytes_limit": 100.0}

        def memory_stats(self):
            return self._s

    reg = m.Registry()
    monkeypatch.setattr(
        jax, "local_devices", lambda: [FakeDev(0, 10.0, 30.0),
                                       FakeDev(1, 20.0, 25.0)]
    )
    assert m.observe_device_memory(reg) is True
    snap = reg.snapshot()["gauges"]
    assert snap["device0.bytes_in_use"] == 10.0
    assert snap["device1.bytes_in_use"] == 20.0
    assert snap["device.bytes_in_use"] == 20.0        # max across devices
    assert snap["device.peak_bytes_in_use"] == 30.0   # device 0's peak
    assert snap["device1.peak_bytes_in_use"] == 25.0


def test_observe_device_memory_statless_backend(monkeypatch):
    """CPU-style backends (memory_stats() -> None) write nothing."""
    import jax

    from cst_captioning_tpu.obs import metrics as m

    class NoStats:
        id = 0

        def memory_stats(self):
            return None

    reg = m.Registry()
    monkeypatch.setattr(jax, "local_devices", lambda: [NoStats()])
    assert m.observe_device_memory(reg) is False
    assert reg.snapshot()["gauges"] == {}


# ---- multihost report merge + health/DCN sections ---------------------------

def _write_stream(path, events):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def _proc_events(t0, t1, phase_self, counters=None, gauges=None,
                 histograms=None):
    return [
        {"ts": t0, "event": "run_start", "run": "multi", "thread": "MainThread"},
        {"ts": t0 + 0.1, "event": "span", "name": "rl.decode",
         "dur": phase_self, "self_dur": phase_self, "thread": "MainThread"},
        {"ts": t1 - 0.01, "event": "metrics",
         "counters": counters or {}, "gauges": gauges or {},
         "histograms": histograms or {}},
        {"ts": t1, "event": "run_end"},
    ]


def test_report_merges_proc_streams_with_skew_attribution(tmp_path):
    """proc<k>/ sub-streams merge into hosts/cluster sections: per-host
    start/end skew names the straggler, counters sum cluster-wide."""
    run = str(tmp_path / "run")
    _write_stream(
        os.path.join(run, "events.jsonl"),
        _proc_events(100.0, 110.0, 5.0,
                     counters={"resilience.chaos_fault": 1,
                               "health.dcn_stall": 1,
                               "health.heartbeats": 7}),
    )
    _write_stream(
        os.path.join(run, "proc1", "events.jsonl"),
        _proc_events(100.5, 113.0, 9.0,
                     counters={"resilience.chaos_fault": 2,
                               "health.peer_lost": 1,
                               "health.heartbeats": 5}),
    )
    rep = report_run(run)
    assert [h["proc"] for h in rep["hosts"]] == [0, 1]
    h0, h1 = rep["hosts"]
    assert h0["start_skew_s"] == pytest.approx(0.0)
    assert h1["start_skew_s"] == pytest.approx(0.5)
    assert h0["end_skew_s"] == pytest.approx(0.0)
    assert h1["end_skew_s"] == pytest.approx(3.0)
    assert h1["top_phase"] == "rl.decode"
    c = rep["cluster"]
    assert c["processes"] == 2 and c["straggler_proc"] == 1
    assert c["max_end_skew_s"] == pytest.approx(3.0)
    assert c["chaos_faults"] == 3
    assert c["dcn_stalls"] == 1 and c["peer_losses"] == 1
    assert c["heartbeats"] == 12
    # rendering includes the cluster table without touching the phase table
    text = render_report(rep)
    assert "cluster: 2 process streams merged" in text
    assert "proc" in text


def test_report_single_stream_has_no_cluster_section(tmp_path):
    run = str(tmp_path / "run")
    _write_stream(os.path.join(run, "events.jsonl"),
                  _proc_events(0.0, 1.0, 0.5))
    rep = report_run(run)
    assert "hosts" not in rep and "cluster" not in rep


def test_report_health_section_surfaces_heartbeats_and_dcn_stalls(tmp_path):
    run = str(tmp_path / "run")
    hist = {"dcn.collective_seconds": {
        "buckets": [0.1, 1.0], "counts": [8, 2], "sum": 2.4, "count": 10,
        "max": 0.9,
    }}
    _write_stream(
        os.path.join(run, "events.jsonl"),
        _proc_events(0.0, 10.0, 1.0,
                     counters={"health.heartbeats": 20,
                               "health.dcn_stall": 2,
                               "health.peer_lost": 1,
                               "resilience.peer_loss_drain": 1,
                               "resilience.degraded_continuation": 1,
                               "resilience.ckpt_enospc": 3,
                               "resilience.prefetch_stall": 4},
                     gauges={"health.peers_alive": 1.0,
                             "health.peer_age_max_s": 0.2},
                     histograms=hist),
    )
    rep = report_run(run)
    h = rep["health"]
    assert h["heartbeats"] == 20 and h["dcn_stalls"] == 2
    assert h["peer_losses"] == 1 and h["peers_alive"] == 1.0
    assert h["collectives"] == 10
    assert 0.0 < h["collective_p95_s"] <= 0.9
    r = rep["resilience"]
    assert r["peer_loss_drains"] == 1
    assert r["degraded_continuations"] == 1
    assert r["ckpt_enospc"] == 3 and r["prefetch_stalls"] == 4
    text = render_report(rep)
    assert "health: 20 heartbeat(s)" in text
    assert "2 stall(s)" in text
    assert "peer-loss drains: 1" in text and "degraded continuations: 1" in text


def test_report_no_health_section_without_signals(tmp_path):
    run = str(tmp_path / "run")
    _write_stream(os.path.join(run, "events.jsonl"),
                  _proc_events(0.0, 1.0, 0.5))
    rep = report_run(run)
    assert rep["health"] is None
    assert "health:" not in render_report(rep)


# ---- XLA HLO cost-analysis backend (obs/flops.compiled_cost) ----------------


def test_compiled_cost_reports_hlo_flops():
    """The compiled-program FLOPs backend: a known matmul's HLO cost is
    exactly 2*m*n*k, and jitted callables are accepted as-is."""
    import jax
    import numpy as np

    from cst_captioning_tpu.obs.flops import compiled_cost

    a = np.ones((32, 48), np.float32)
    b = np.ones((48, 16), np.float32)
    cost = compiled_cost(lambda x, y: x @ y, a, b)
    assert cost is not None
    assert cost["flops"] == 2 * 32 * 48 * 16
    assert cost["bytes_accessed"] > 0
    jitted = jax.jit(lambda x, y: x @ y)
    cost2 = compiled_cost(jitted, a, b)
    assert cost2 is not None and cost2["flops"] == cost["flops"]


def test_compiled_cost_degrades_to_none():
    """Analysis failures degrade to None (the analytic-model fallback), by
    contract — never to a crash."""
    from cst_captioning_tpu.obs.flops import compiled_cost

    # not traceable -> lower() raises inside -> None
    assert compiled_cost(lambda: open("/nonexistent")) is None


def test_report_serving_section_from_synthetic_events(tmp_path):
    """The serving section aggregates the engine's funnel counters + the
    per-request phase histograms (queue-wait / encode / decode / detok)."""
    import os

    from cst_captioning_tpu.obs.report import render_report, report_run

    run = str(tmp_path / "run")
    hist = {}
    for name, p50 in (("queue_wait", 0.01), ("encode", 0.02),
                      ("decode", 0.3), ("detok", 0.001), ("latency", 0.35)):
        hist[f"serving.{name}_seconds"] = {
            "buckets": [0.001, 0.01, 0.1, 1.0],
            "counts": [0, 0, 5, 0], "sum": 5 * p50, "count": 5, "max": p50,
        }
    _write_stream(
        os.path.join(run, "events.jsonl"),
        _proc_events(0.0, 2.0, 0.5,
                     counters={"serving.requests_submitted": 6,
                               "serving.requests_admitted": 5,
                               "serving.requests_completed": 5,
                               "serving.strides": 9,
                               "serving.drains": 1,
                               "serving.admission_blocked_pages": 2},
                     gauges={"serving.pages_in_use": 3.0},
                     histograms=hist),
    )
    rep = report_run(run)
    sv = rep["serving"]
    assert sv["submitted"] == 6 and sv["completed"] == 5
    assert sv["strides"] == 9 and sv["drains"] == 1
    assert sv["admission_blocked_pages"] == 2
    assert set(sv["phases"]) == {"queue_wait", "encode", "decode", "detok"}
    assert sv["phases"]["decode"]["count"] == 5
    assert sv["latency_p95_s"] > 0
    text = render_report(rep)
    assert "serving: 6 submitted, 5 admitted, 5 completed" in text
    assert "queue_wait" in text and "page backpressure" in text


def test_report_serving_paged_bank_section(tmp_path):
    """The serving section surfaces the paged in-kernel attention
    telemetry: page-table occupancy gauges, encode-ahead staging depth,
    and the HBM bytes the killed dense-bank gather would have moved."""
    import os

    from cst_captioning_tpu.obs.report import render_report, report_run

    run = str(tmp_path / "run")
    _write_stream(
        os.path.join(run, "events.jsonl"),
        _proc_events(0.0, 2.0, 0.5,
                     counters={"serving.requests_submitted": 8,
                               "serving.requests_admitted": 8,
                               "serving.requests_completed": 8,
                               "serving.requests_staged": 3,
                               "serving.strides": 12,
                               "serving.gather_bytes_avoided": 6 * 2**20},
                     gauges={"serving.pages.in_use": 10.0,
                             "serving.pages.free": 2.0,
                             "serving.pages.table_rows": 4.0}),
    )
    rep = report_run(run)
    sv = rep["serving"]
    assert sv["pages"] == {"in_use": 10.0, "free": 2.0, "table_rows": 4.0}
    assert sv["staged"] == 3
    assert sv["gather_bytes_avoided"] == 6 * 2**20
    text = render_report(rep)
    assert "page table: 10 in use / 2 free over 4 row(s)" in text
    assert "staged admissions: 3" in text
    assert "gather bytes avoided: 6.0 MiB" in text


def test_report_no_serving_section_without_requests(tmp_path):
    import os

    from cst_captioning_tpu.obs.report import render_report, report_run

    run = str(tmp_path / "run")
    _write_stream(os.path.join(run, "events.jsonl"),
                  _proc_events(0.0, 1.0, 0.5))
    rep = report_run(run)
    assert rep["serving"] is None
    assert "serving:" not in render_report(rep)


# ---- Obs v2: prometheus specials, FLOPs-backend tags, serving SLO -----------


def test_prometheus_nonfinite_and_cumulative_inf_bucket():
    """_prom_num pins: gauges/counters holding NaN/±Inf render the Prometheus
    spellings ("NaN"/"+Inf"/"-Inf" — repr would emit "nan" and break
    scrapers), and the histogram's +Inf cumulative bucket always equals the
    total count even when every observation overflows the bounds."""
    reg = Registry()
    reg.gauge("loss.last").set(float("nan"))
    reg.gauge("burn.fast").set(float("inf"))
    reg.gauge("burn.neg").set(float("-inf"))
    reg.counter("secs").inc(1.5)
    h = reg.histogram("lat", buckets=(0.1,))
    h.observe(5.0)
    h.observe(7.0)  # both overflow: finite buckets stay 0
    lines = reg.to_prometheus().splitlines()
    assert "loss_last NaN" in lines
    assert "burn_fast +Inf" in lines
    assert "burn_neg -Inf" in lines
    assert "secs 1.5" in lines
    assert 'lat_bucket{le="0.1"} 0' in lines
    assert 'lat_bucket{le="+Inf"} 2' in lines
    assert "lat_count 2" in lines


def test_report_mfu_rows_carry_flops_backend(tmp_path):
    """Obs v2 satellite: each phase row labels WHICH FLOPs source its mfu
    reflects (compiled XLA cost vs the analytic model) — in the JSON field
    and as the c/a mark + legend in the rendered table."""
    run = str(tmp_path / "run")
    events = [
        {"ts": 0.0, "event": "run_start", "run": "b", "thread": "MainThread"},
        {"ts": 1.0, "event": "span", "name": "xe.step", "dur": 1.0,
         "self_dur": 1.0, "thread": "MainThread"},
        {"ts": 3.0, "event": "span", "name": "rl.update", "dur": 1.0,
         "self_dur": 1.0, "thread": "MainThread"},
        {"ts": 5.0, "event": "span", "name": "rl.decode", "dur": 1.0,
         "self_dur": 1.0, "thread": "MainThread"},
        {"ts": 9.9, "event": "metrics",
         "counters": {"flops.xe.step": 1e12, "flops.rl.update": 2e12,
                      "flops.rl.decode": 3e12},
         "gauges": {"device.peak_flops": 1e12,
                    "flops.backend.xe.step": 1.0,     # compiled probe hit
                    "flops.backend.rl.update": 0.0}}, # analytic fallback
        {"ts": 10.0, "event": "run_end"},
    ]
    _write_stream(os.path.join(run, "events.jsonl"), events)
    rep = report_run(run)
    by_name = {p["phase"]: p for p in rep["phases"]}
    assert by_name["xe.step"]["flops_backend"] == "compiled"
    assert by_name["rl.update"]["flops_backend"] == "analytic"
    assert by_name["rl.decode"]["flops_backend"] is None  # no gauge: untagged
    text = render_report(rep)
    assert "0.1000c" in text and "0.2000a" in text
    assert "mfu flops source: c = compiled program" in text


def test_report_serving_slo_section(tmp_path):
    """The serving section surfaces the SLO monitor's per-window attainment/
    burn-rate gauges, breach + alert counters, and the target."""
    run = str(tmp_path / "run")
    _write_stream(
        os.path.join(run, "events.jsonl"),
        _proc_events(0.0, 2.0, 0.5,
                     counters={"serving.requests_submitted": 10,
                               "serving.requests_admitted": 10,
                               "serving.requests_completed": 10,
                               "serving.strides": 4,
                               "serving.slo.breaches": 3,
                               "serving.slo.alerts": 1},
                     gauges={"serving.slo.target_s": 0.25,
                             "serving.slo.attainment.60s": 0.7,
                             "serving.slo.burn_rate.60s": 30.0,
                             "serving.slo.attainment.600s": 0.97,
                             "serving.slo.burn_rate.600s": 3.0}),
    )
    rep = report_run(run)
    slo = rep["serving"]["slo"]
    assert slo["target_s"] == 0.25
    assert slo["windows"][60]["attainment"] == pytest.approx(0.7)
    assert slo["windows"][60]["burn_rate"] == pytest.approx(30.0)
    assert slo["windows"][600]["burn_rate"] == pytest.approx(3.0)
    assert slo["breaches"] == 3 and slo["alerts"] == 1
    text = render_report(rep)
    assert "slo (target 0.250s):" in text
    assert "60s: 70.0% (burn 30.0x)" in text
    assert "breaches: 3" in text and "alerts: 1" in text


def test_report_serving_without_slo_has_no_slo_key(tmp_path):
    run = str(tmp_path / "run")
    _write_stream(
        os.path.join(run, "events.jsonl"),
        _proc_events(0.0, 1.0, 0.5,
                     counters={"serving.requests_submitted": 1,
                               "serving.requests_admitted": 1,
                               "serving.requests_completed": 1}),
    )
    rep = report_run(run)
    assert "slo" not in rep["serving"]
    assert "slo" not in render_report(rep)
