"""Golden tests for BLEU and ROUGE-L with formula-derived expected values."""

import math

import numpy as np

from cst_captioning_tpu.metrics.bleu import Bleu
from cst_captioning_tpu.metrics.rouge import RougeL


def toks(s):
    return s.split()


def test_bleu_perfect_match():
    gts = {"v": [toks("a man plays a guitar")]}
    res = {"v": [toks("a man plays a guitar")]}
    corpus, per = Bleu(4).compute_score(gts, res)
    np.testing.assert_allclose(corpus, [1.0, 1.0, 1.0, 1.0], atol=1e-9)


def test_bleu_partial_hand_computed():
    # hyp "the cat" vs ref "the cat sat": p1 = 2/2, p2 = 1/1, p3 undefined (0)
    # brevity = exp(1 - 3/2) = exp(-0.5)
    gts = {"v": [toks("the cat sat")]}
    res = {"v": [toks("the cat")]}
    corpus, _ = Bleu(4).compute_score(gts, res)
    bp = math.exp(1.0 - 3.0 / 2.0)
    np.testing.assert_allclose(corpus[0], bp, atol=1e-9)
    np.testing.assert_allclose(corpus[1], bp, atol=1e-9)  # sqrt(1*1) = 1
    assert corpus[2] == 0.0 and corpus[3] == 0.0


def test_bleu_clipping():
    # hyp repeats "the" 4 times; ref has it twice -> clipped p1 = 2/4
    gts = {"v": [toks("the cat the mat")]}
    res = {"v": [toks("the the the the")]}
    corpus, _ = Bleu(1).compute_score(gts, res)
    np.testing.assert_allclose(corpus[0], 0.5, atol=1e-9)


def test_bleu_closest_ref_length():
    # Two refs lengths 2 and 6; hyp length 2 -> closest is 2 -> bp = 1.
    gts = {"v": [toks("a b"), toks("a b c d e f")]}
    res = {"v": [toks("a b")]}
    corpus, _ = Bleu(1).compute_score(gts, res)
    np.testing.assert_allclose(corpus[0], 1.0, atol=1e-9)


def test_bleu_sentence_smoothing_nonzero():
    # Per-sentence BLEU-4 of a 4-token partial match must be > 0 via +1 smoothing
    b = Bleu(4)
    s = b.sentence_bleu(toks("a man rides horse"), [toks("a man rides a horse")])
    assert s[3] > 0.0
    assert (np.diff(s) <= 1e-12).all()  # orders are non-increasing


def test_bleu_corpus_pools_counts():
    # Corpus BLEU pools match/total over segments (not mean of per-sentence).
    gts = {"a": [toks("x y")], "b": [toks("p q")]}
    res = {"a": [toks("x y")], "b": [toks("z w")]}
    corpus, _ = Bleu(1).compute_score(gts, res)
    np.testing.assert_allclose(corpus[0], 0.5, atol=1e-9)  # 2 of 4 unigrams


def test_rouge_perfect_and_disjoint():
    r = RougeL()
    assert r.sentence_score(toks("a b c"), [toks("a b c")]) == 1.0
    assert r.sentence_score(toks("a b c"), [toks("x y z")]) == 0.0


def test_rouge_hand_computed():
    # hyp "the cat" vs ref "the cat sat": lcs=2, p=1, r=2/3, beta=1.2
    r = RougeL()
    p, rec, b2 = 1.0, 2.0 / 3.0, 1.2**2
    expected = (1 + b2) * p * rec / (rec + b2 * p)
    np.testing.assert_allclose(
        r.sentence_score(toks("the cat"), [toks("the cat sat")]), expected, atol=1e-9
    )


def test_rouge_max_over_refs():
    # p from one ref, r from another: coco-caption takes max of each separately
    r = RougeL()
    hyp = toks("a b")
    refs = [toks("a b c d"), toks("a x")]
    # ref1: lcs 2 -> p=1, rec=0.5 ; ref2: lcs 1 -> p=0.5, rec=0.5
    p, rec, b2 = 1.0, 0.5, 1.44
    expected = (1 + b2) * p * rec / (rec + b2 * p)
    np.testing.assert_allclose(r.sentence_score(hyp, refs), expected, atol=1e-9)


def test_lcs_non_contiguous():
    r = RougeL()
    # hyp "a x b y c" vs ref "a b c": lcs = 3
    s = r.sentence_score(toks("a x b y c"), [toks("a b c")])
    p, rec, b2 = 3.0 / 5.0, 1.0, 1.44
    expected = (1 + b2) * p * rec / (rec + b2 * p)
    np.testing.assert_allclose(s, expected, atol=1e-9)
