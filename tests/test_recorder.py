"""Flight recorder + online anomaly detection (obs/recorder.py, obs/anomaly.py).

Covers the Obs v2 acceptance criteria:

- EWMA z-score / nonfinite / stall detection units (pure stdlib, seeded);
- ring buffering, batched flush, postmortem bundle durability (manifest
  verifies), the per-process dump budget;
- the chaos acceptance run: a NaN fault mid-RL-epoch produces a verifiable
  postmortem bundle whose ring covers the steps before the trip, with the
  diverged step flagged by the anomaly detector;
- degraded-mesh continuation re-probes the compiled FLOPs cost
  (``obs.flops.probes``) and the recorder keeps appending across the mesh
  rebuild without a step gap;
- ``stats=True`` (recorder on) changes metric OUTPUTS only: final params are
  bit-identical to the default ``recorder_steps=0`` run.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os

import jax
import numpy as np
import pytest

from cst_captioning_tpu import obs
from cst_captioning_tpu.config.config import (
    DataConfig,
    EvalConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    RLConfig,
    TrainConfig,
)
from cst_captioning_tpu.data import CaptionDataset, make_synthetic_dataset
from cst_captioning_tpu.obs import recorder
from cst_captioning_tpu.obs.anomaly import AnomalyDetector, Ewma
from cst_captioning_tpu.obs.report import load_postmortem, render_postmortem
from cst_captioning_tpu.resilience import Fault, FaultPlan
from cst_captioning_tpu.train.trainer import Trainer


@pytest.fixture(autouse=True)
def _clean_obs():
    """Recorder + registry are process-global; every test gets fresh ones."""
    recorder.shutdown()
    obs.REGISTRY.reset()
    yield
    recorder.shutdown()
    obs.shutdown()
    obs.REGISTRY.reset()


# ---- anomaly detection units ------------------------------------------------


def test_ewma_warmup_gate_and_z_score():
    ew = Ewma(alpha=0.5, warmup=3)
    assert ew.update(10.0) is None
    assert ew.update(10.0) is None
    assert ew.update(11.0) is None  # third observation: still warming up
    z = ew.update(30.0)             # judged against the PRE-update moments
    assert z is not None and z > 3.0
    # the spike folded in: a level shift re-converges instead of alarming
    for _ in range(50):
        last = ew.update(30.0)
    assert abs(last) < 1.0


def test_ewma_rejects_bad_alpha():
    with pytest.raises(ValueError):
        Ewma(alpha=0.0)
    with pytest.raises(ValueError):
        Ewma(alpha=1.5)


def test_detector_flags_z_spike_and_counts_it():
    det = AnomalyDetector(z_threshold=4.0, alpha=0.1, warmup=4)
    for i in range(10):
        assert det.observe("loss", 2.0 + 0.01 * i, step=i) == []
    kinds = det.observe("loss", 50.0, step=10, phase="xe")
    assert kinds == ["loss_z"]
    snap = obs.REGISTRY.snapshot()
    assert snap["counters"]["obs.anomaly.loss_z"] == 1


def test_detector_nonfinite_short_circuits():
    det = AnomalyDetector(warmup=2)
    det.observe("grad_norm", 1.0)
    assert det.observe("grad_norm", float("nan"), step=3) == ["nonfinite"]
    assert det.observe("grad_norm", float("inf"), step=4) == ["nonfinite"]
    # the poison never entered the moments: healthy values stay healthy
    for _ in range(20):
        assert det.observe("grad_norm", 1.0) == []
    snap = obs.REGISTRY.snapshot()
    assert snap["counters"]["obs.anomaly.nonfinite"] == 2


def test_detector_unknown_stream_is_carried_not_judged():
    det = AnomalyDetector(warmup=0)
    assert det.observe("sample_entropy", float("nan")) == []


def test_detector_stall_on_step_gap():
    det = AnomalyDetector(stall_factor=10.0, gap_window=32)
    for _ in range(10):
        assert det.observe_gap(0.1) == []
    assert det.observe_gap(5.0, step=11, phase="rl") == ["stall"]
    snap = obs.REGISTRY.snapshot()
    assert snap["counters"]["obs.anomaly.stall"] == 1


# ---- recorder ring / flush / postmortem -------------------------------------


def _drive(fr, n, start=1, phase="xe", loss=2.0):
    for i in range(start, start + n):
        fr.record(i, phase, {"loss": loss, "grad_norm": 1.0})


def test_ring_keeps_last_capacity_steps(tmp_path):
    fr = recorder.configure(4, str(tmp_path), run="t")
    _drive(fr, 10)
    fr.flush()
    assert [r["step"] for r in fr.ring] == [7, 8, 9, 10]
    assert all(r["loss"] == 2.0 and r["phase"] == "xe" for r in fr.ring)
    # timestamps are absolute (mapped through the configure-time origin)
    assert all(r["ts"] > 1e9 for r in fr.ring)


def test_flush_reads_device_scalars_in_one_batch(tmp_path):
    import jax.numpy as jnp

    fr = recorder.configure(8, str(tmp_path), run="t")
    fr.record(1, "xe", {"loss": jnp.float32(3.5), "grad_norm": jnp.float32(2.0)})
    fr.record(2, "xe", {"loss": jnp.float32(3.25)})
    fr.flush()
    assert [r["loss"] for r in fr.ring] == [3.5, 3.25]
    fr.flush()  # empty buffer: no-op, ring unchanged
    assert len(fr.ring) == 2


def test_judge_dedupes_same_kind_within_a_step(tmp_path):
    fr = recorder.configure(8, str(tmp_path), run="t",
                            detector=AnomalyDetector(warmup=4))
    _drive(fr, 6)
    fr.flush()
    nan = float("nan")
    fr.record(7, "rl", {"rl_loss": nan, "grad_norm": nan})
    fr.flush()
    last = list(fr.ring)[-1]
    # loss AND grad_norm both nonfinite on one step: ONE verdict
    assert last["anomalies"].count("nonfinite") == 1


def test_postmortem_bundle_verifies_and_renders(tmp_path):
    obs.configure(str(tmp_path), run="t")
    fr = recorder.configure(8, str(tmp_path), run="t",
                            detector=AnomalyDetector(warmup=4),
                            config={"name": "t"})
    _drive(fr, 6)
    fr.flush()
    fr.record(7, "rl", {"rl_loss": float("nan"), "reward_mean": 0.4})
    bundle = fr.postmortem("divergence_nonfinite", phase="rl", step=7,
                           action="skip_batch")
    assert bundle is not None and os.path.isdir(bundle)
    for f in ("ring.jsonl", "registry.json", "events_tail.jsonl",
              "config.json", "meta.json", "manifest.json"):
        assert os.path.exists(os.path.join(bundle, f)), f
    pm = load_postmortem(bundle)
    assert pm["verified"] and pm["problems"] == []
    assert pm["meta"]["reason"] == "divergence_nonfinite"
    assert pm["meta"]["step"] == 7 and pm["meta"]["action"] == "skip_batch"
    # postmortem self-flushed: the diverged step is IN the ring, flagged
    assert [r["step"] for r in pm["ring"]] == [1, 2, 3, 4, 5, 6, 7]
    assert "nonfinite" in pm["ring"][-1]["anomalies"]
    assert math.isnan(pm["ring"][-1]["rl_loss"])
    text = render_postmortem(pm)
    assert "divergence_nonfinite" in text and "nonfinite" in text
    assert "manifest verified" in text


def test_postmortem_tampered_bundle_fails_verification(tmp_path):
    fr = recorder.configure(4, str(tmp_path), run="t")
    _drive(fr, 3)
    bundle = fr.postmortem("tamper_check")
    with open(os.path.join(bundle, "ring.jsonl"), "a") as f:
        f.write('{"step": 999}\n')
    pm = load_postmortem(bundle)
    assert not pm["verified"]
    assert any("ring.jsonl" in p for p in pm["problems"])
    assert "MISMATCH" in render_postmortem(pm).upper() or pm["problems"]


def test_postmortem_dump_budget(tmp_path):
    fr = recorder.configure(4, str(tmp_path), run="t", max_dumps=2)
    _drive(fr, 2)
    assert fr.postmortem("one") is not None
    assert fr.postmortem("two") is not None
    assert fr.postmortem("three") is None  # budget spent: no disk fill
    dumps = [n for n in os.listdir(tmp_path) if n.startswith("postmortem_")]
    assert len(dumps) == 2


def test_meta_schema2_carries_identity_and_anchor_table(tmp_path):
    """Fleet-merge inputs (obs/fleet.py): schema-2 meta stamps the host
    identity and a monotonic-to-wall anchor pair at start + each flush."""
    fr = recorder.configure(8, str(tmp_path), run="t", proc=3, world=5,
                            host="h3")
    _drive(fr, 3)
    fr.flush()
    _drive(fr, 3, start=4)
    fr.flush()
    pm = load_postmortem(fr.postmortem("unit"))
    meta = pm["meta"]
    assert meta["schema"] == 2
    assert (meta["proc"], meta["world"], meta["host"]) == (3, 5, "h3")
    # start anchor + one per flush (the dump's own flush re-stamps the last)
    assert len(meta["anchors"]) >= 3
    offs = [wall - pc for pc, wall in meta["anchors"]]
    assert offs == sorted(offs) or max(offs) - min(offs) < 5.0
    # the render names the process
    assert "proc: 3/5 (h3)" in render_postmortem(pm)


def test_dump_budget_gauge_tracks_remaining(tmp_path):
    fr = recorder.configure(4, str(tmp_path), run="t", max_dumps=2)

    def left():
        return obs.REGISTRY.snapshot()["gauges"]["obs.recorder.dump_budget"]

    assert left() == 2.0  # published at configure time
    _drive(fr, 2)
    assert fr.postmortem("one") is not None and left() == 1.0
    assert fr.postmortem("two") is not None and left() == 0.0
    assert fr.postmortem("three") is None and left() == 0.0


def test_ephemeral_recorder_does_not_clobber_budget_gauge(tmp_path):
    """serving/engine.py drains may dump through a throwaway recorder while
    a global one is live — the gauge tracks the GLOBAL budget only."""
    fr = recorder.configure(4, str(tmp_path), run="t", max_dumps=3)
    _drive(fr, 1)
    fr.postmortem("one")
    eph = recorder.FlightRecorder(2, str(tmp_path / "eph"), run="e",
                                  max_dumps=1)
    eph.record(1, "xe", {"loss": 1.0})
    assert eph.postmortem("drain") is not None
    eph.close()
    snap = obs.REGISTRY.snapshot()["gauges"]
    assert snap["obs.recorder.dump_budget"] == 2.0


def test_postmortem_registry_extra_and_flush_error_render(tmp_path):
    fr = recorder.configure(4, str(tmp_path), run="t")
    _drive(fr, 2)
    bundle = fr.postmortem(
        "serving_drain_test",
        registry_extra={"serving": {"slo": {"target_s": 0.5}}},
    )
    pm = load_postmortem(bundle)
    assert pm["registry"]["serving"]["slo"]["target_s"] == 0.5
    # a flush that died at dump time is called out ahead of the stale ring
    pm["meta"]["flush_error"] = "RuntimeError: boom"
    text = render_postmortem(pm)
    assert "FLUSH FAILED" in text and "boom" in text


def test_module_level_api_is_noop_when_unconfigured():
    recorder.shutdown()
    assert recorder.active() is None
    recorder.record(1, "xe", {"loss": 1.0})
    recorder.flush()
    assert recorder.postmortem("nothing") is None
    recorder.note_fault("xe.step", "nan", visit=0)  # must not raise


# ---- trainer integration: the chaos acceptance run --------------------------


@pytest.fixture(scope="module")
def synth_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("recsynth")
    return make_synthetic_dataset(
        str(out), num_videos=12, num_topics=3, vocab_words=20,
        modalities={"resnet": 16}, max_frames=4, seed=5,
    )


@pytest.fixture(scope="module")
def datasets(synth_dir):
    train = CaptionDataset(
        synth_dir["info_json"], {"resnet": synth_dir["resnet"]}, "train", 4
    )
    return train


def make_cfg(ckpt_dir: str, vocab_size: int, *, pipelined: bool = False,
             batch_size: int = 8, seq_per_vid: int = 2, num_devices: int = 0,
             rl_epochs: int = 2, **train_kw) -> ExperimentConfig:
    train_kw.setdefault("eval_every_epochs", 100)
    train_kw.setdefault("epochs", 2)
    return ExperimentConfig(
        name="flightrec",
        model=ModelConfig(
            vocab_size=vocab_size, modalities=(("resnet", 16),),
            d_embed=16, d_hidden=16, d_att=8, encoder="temporal_attention",
            dropout=0.0, max_len=8, max_frames=4, dtype="float32",
        ),
        data=DataConfig(batch_size=batch_size, seq_per_vid=seq_per_vid),
        train=TrainConfig(
            lr=5e-3, grad_clip=5.0, ckpt_dir=ckpt_dir, seed=0,
            log_every_steps=1, **train_kw,
        ),
        rl=RLConfig(
            enabled=True, num_rollouts=2, lr=1e-3, epochs=rl_epochs,
            baseline="greedy", pipelined=pipelined,
        ),
        eval=EvalConfig(beam_size=1, max_len=8),
        mesh=MeshConfig(num_devices=num_devices),
    )


def test_chaos_nan_mid_rl_produces_verifiable_postmortem(datasets,
                                                         tmp_path_factory):
    """ISSUE acceptance: a seeded chaos run injecting a NaN mid-RL-epoch
    leaves a verifiable postmortem bundle; the ring covers the steps before
    the trip and the divergence step is flagged by the anomaly detector."""
    train_ds = datasets
    d = str(tmp_path_factory.mktemp("chaospm"))
    obs_dir = os.path.join(d, "obs")
    cfg = make_cfg(d, len(train_ds.vocab), epochs=2, rl_epochs=1,
                   obs=True, obs_dir=obs_dir, recorder_steps=8, anomaly=True)
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl",
                 use_mesh=False)
    try:
        tr.train_xe()
        # 12 videos / batch 8 = 2 RL batches/epoch; poison the second
        with FaultPlan([Fault("rl.batch", "nan", at=1)]).activate():
            tr.train_rl()
        assert tr.rl_epochs == 1  # skip_batch: the epoch still completes
    finally:
        tr.close()

    bundles = sorted(
        n for n in os.listdir(obs_dir) if n.startswith("postmortem_")
    )
    # the chaos hook dumps when the fault fires; the sentinel dumps on the
    # divergence it causes — both trips are captured
    reasons = set()
    for b in bundles:
        pm = load_postmortem(os.path.join(obs_dir, b))
        assert pm["verified"], (b, pm["problems"])
        reasons.add(pm["meta"]["reason"])
    assert "chaos_nan" in reasons
    assert "divergence_nonfinite" in reasons

    (div,) = [b for b in bundles if b.endswith("divergence_nonfinite")]
    pm = load_postmortem(os.path.join(obs_dir, div))
    assert pm["meta"]["action"] == "skip_batch"
    trip_step = pm["meta"]["step"]
    # ring coverage: the XE steps before the trip AND the diverged step
    # itself (recorded before sentinel.push, flushed by the dump). The RL
    # step clock restarts at 1 (fresh optimizer state), so order is
    # per-phase, not global.
    xe_steps = [r["step"] for r in pm["ring"] if r["phase"] == "xe"]
    rl_steps = [r["step"] for r in pm["ring"] if r["phase"] == "rl"]
    assert xe_steps == sorted(xe_steps)
    assert rl_steps == sorted(rl_steps)
    assert len(pm["ring"]) >= 4
    assert rl_steps[-1] == trip_step
    diverged = pm["ring"][-1]
    assert diverged["phase"] == "rl"
    assert "nonfinite" in diverged["anomalies"]
    # the run totals agree: the detector counted what the ring flagged
    counters = pm["registry"]["counters"]
    assert counters.get("obs.anomaly.nonfinite", 0) >= 1
    render_postmortem(pm)  # renders without error


def test_degraded_mesh_reprobes_flops_and_ring_has_no_gap(datasets,
                                                          tmp_path_factory):
    """ISSUE satellite: after ``Trainer._continue_degraded`` rebuilds the
    mesh, the compiled-cost probe re-runs (``obs.flops.probes`` ticks again)
    and the flight recorder keeps appending across the rebuild without a
    step gap."""
    train_ds = datasets
    d = str(tmp_path_factory.mktemp("degradedrec"))
    obs_dir = os.path.join(d, "obs")
    cfg = make_cfg(d, len(train_ds.vocab), pipelined=True, batch_size=2,
                   seq_per_vid=1, epochs=1, num_devices=2, health=True,
                   health_sim_hosts=2, elastic="degraded",
                   obs=True, obs_dir=obs_dir, recorder_steps=32)
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl")
    try:
        tr.train_xe()
        probes_xe = obs.REGISTRY.snapshot()["counters"]["obs.flops.probes"]
        assert probes_xe >= 1  # the XE step program was probed once
        # 5 RL batches/epoch; visit 6 = second update of epoch 2 -> the peer
        # loss lands mid-epoch and the run continues on the shrunk mesh
        with FaultPlan(
            [Fault("rl.step", "partial_preempt", at=6, host=1)]
        ).activate():
            tr.train_rl()
        assert tr.rl_epochs == 2
        assert tr.mesh is not None and tr.mesh.devices.size == 1

        # re-probe: the first SCST build probed its update program once; the
        # post-rebuild build probed the recompiled program AGAIN
        probes = obs.REGISTRY.snapshot()["counters"]["obs.flops.probes"]
        assert probes >= probes_xe + 2

        fr = recorder.active()
        assert fr is not None
        fr.flush()
        rl_steps = sorted({r["step"] for r in fr.ring if r["phase"] == "rl"})
        # 2 epochs x 5 steps, appended across the mesh rebuild with no gap
        # (replayed seam steps dedupe to the same step numbers)
        assert rl_steps == list(range(rl_steps[0], rl_steps[0] + 10))
        # the peer-loss drain dumped a bundle before the continuation
        assert any(
            n.startswith("postmortem_") and n.endswith("peer_loss")
            for n in os.listdir(obs_dir)
        )
    finally:
        tr.close()


def test_recorder_stats_do_not_change_trained_params(datasets,
                                                     tmp_path_factory):
    """The recorder's on-device stats are metric OUTPUTS only: a run with
    ``recorder_steps`` on trains bit-identically to the default-off run."""
    train_ds = datasets

    def run(train_kw):
        d = str(tmp_path_factory.mktemp("statspin"))
        cfg = make_cfg(d, len(train_ds.vocab), epochs=1, rl_epochs=1,
                       **train_kw)
        tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl",
                     use_mesh=False)
        try:
            tr.train_xe()
            tr.train_rl()
        finally:
            tr.close()
        return jax.device_get(tr.state.params)

    p_off = run({})
    p_on = run({"obs": True, "obs_dir": "", "recorder_steps": 8,
                "anomaly": True})
    for a, b in zip(
        jax.tree_util.tree_leaves(p_off), jax.tree_util.tree_leaves(p_on)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_records_update_ratios_when_stats_on(datasets,
                                                     tmp_path_factory):
    """stats=True threads through the step factories: ring records carry the
    per-family update-ratio outputs."""
    train_ds = datasets
    d = str(tmp_path_factory.mktemp("updratio"))
    obs_dir = os.path.join(d, "obs")
    cfg = make_cfg(d, len(train_ds.vocab), epochs=1, rl_epochs=1,
                   obs=True, obs_dir=obs_dir, recorder_steps=16)
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl",
                 use_mesh=False)
    try:
        tr.train_xe()
        fr = recorder.active()
        assert fr is not None
        fr.flush()
        recs = list(fr.ring)
        assert recs, "recorder captured no XE steps"
        keys = set(recs[-1])
        assert "upd_ratio/global" in keys
        assert any(k.startswith("upd_ratio/") and k != "upd_ratio/global"
                   for k in keys)
        assert all(math.isfinite(recs[-1][k]) for k in keys
                   if k.startswith("upd_ratio/"))
    finally:
        tr.close()
