"""Golden tests for CIDEr / CIDEr-D.

Expected values are derived in-test straight from the paper formulas
(Vedantam et al. 2015; length penalty exp(-(lh-lr)^2/(2*6^2)) per SURVEY.md §4
item 1) for small hand-traceable cases — an independent oracle, not a copy of
the implementation.
"""

import math
import pickle

import numpy as np
import pytest

from cst_captioning_tpu.metrics.cider import Cider, CiderD, CorpusDF


def toks(s):
    return s.split()


def test_identical_hypothesis_scores_10():
    gts = {"v": [toks("a man plays a guitar")]}
    res = {"v": [toks("a man plays a guitar")]}
    for scorer in (Cider(), CiderD()):
        mean, per = scorer.compute_score(gts, res)
        assert per.shape == (1,)
        np.testing.assert_allclose(mean, 10.0, atol=1e-9)


def test_disjoint_hypothesis_scores_0():
    gts = {
        "v1": [toks("a b c d e")],
        "v2": [toks("p q r s t")],
    }
    res = {"v1": [toks("a b c d e")], "v2": [toks("a b c d e")]}
    mean, per = CiderD().compute_score(gts, res)
    np.testing.assert_allclose(per[0], 10.0, atol=1e-9)
    np.testing.assert_allclose(per[1], 0.0, atol=1e-9)
    np.testing.assert_allclose(mean, 5.0, atol=1e-9)


def test_ciderd_partial_overlap_hand_computed():
    # Single-doc corpus: ndoc=1 -> log_ndoc = log(max(1, e)) = 1; every ngram
    # appears in the one doc, so idf = 1 - log(1) = 1 for all ngrams.
    gts = {"v": [toks("the cat sat")]}
    res = {"v": [toks("the cat")]}
    # 1-gram: hyp vec {the:1, cat:1} |.|=sqrt2; ref {the,cat,sat} |.|=sqrt3;
    #   clipped dot = 2 -> cos = 2/sqrt(6)
    # 2-gram: hyp {(the,cat)} |.|=1; ref 2 bigrams |.|=sqrt2; dot=1 -> 1/sqrt2
    # 3,4-gram: hyp has none -> 0
    # length penalty: exp(-(2-3)^2 / (2*36))
    expected = (
        10.0
        * math.exp(-1.0 / 72.0)
        * (2.0 / math.sqrt(6.0) + 1.0 / math.sqrt(2.0) + 0.0 + 0.0)
        / 4.0
    )
    _, per = CiderD().compute_score(gts, res)
    np.testing.assert_allclose(per[0], expected, atol=1e-9)


def test_cider_partial_overlap_hand_computed():
    # Plain CIDEr: same vectors, plain cosine (same dot here since counts<=1),
    # NO length penalty.
    gts = {"v": [toks("the cat sat")]}
    res = {"v": [toks("the cat")]}
    expected = 10.0 * (2.0 / math.sqrt(6.0) + 1.0 / math.sqrt(2.0)) / 4.0
    _, per = Cider().compute_score(gts, res)
    np.testing.assert_allclose(per[0], expected, atol=1e-9)


def test_ciderd_length_penalty_sigma6():
    # Same n-gram content, padded hypothesis: penalty should be exact gaussian.
    gts = {"v": [toks("a b c d")]}
    res_exact = {"v": [toks("a b c d")]}
    res_long = {"v": [toks("a b c d x y")]}  # delta = 2
    _, per_exact = CiderD().compute_score(gts, res_exact)
    _, per_long = CiderD().compute_score(gts, res_long)
    assert per_long[0] < per_exact[0]
    # the long hyp's 1-gram cosine etc. change too, so only check monotonicity
    # plus the exact penalty on a pure-length case below:
    # hyp with same multiset achieved by repetition is hard; instead verify
    # penalty formula directly on equal-content different-length is covered by
    # test_ciderd_partial_overlap_hand_computed (delta=-1 term).


def test_multiple_refs_average():
    # Score vs 2 refs = mean of per-ref similarity. With one ref identical and
    # one disjoint (all idf>0, ndoc=2 -> log_ndoc=1), expect exactly half of
    # the identical-only score times penalty terms.
    gts = {"v": [toks("a b c d"), toks("p q r s")], "v2": [toks("z z2 z3 z4")]}
    res = {"v": [toks("a b c d")], "v2": [toks("z z2 z3 z4")]}
    _, per = CiderD().compute_score(gts, res)
    np.testing.assert_allclose(per[0], 5.0, atol=1e-9)


def test_precomputed_df_matches_corpus_mode():
    corpus_gts = {
        "v1": [toks("a man rides a horse"), toks("a person rides a horse")],
        "v2": [toks("a cat sits on a mat")],
    }
    res = {"v1": [toks("a man rides a horse")], "v2": [toks("a cat sits")]}
    df = CorpusDF.from_refs([corpus_gts["v1"], corpus_gts["v2"]])
    m_pre, per_pre = CiderD(df=df).compute_score(corpus_gts, res)
    m_cor, per_cor = CiderD(df="corpus").compute_score(corpus_gts, res)
    np.testing.assert_allclose(per_pre, per_cor, atol=1e-12)
    np.testing.assert_allclose(m_pre, m_cor, atol=1e-12)


def test_corpus_df_save_load_roundtrip(tmp_path):
    df = CorpusDF.from_refs([[toks("a b c")], [toks("b c d")]])
    p = str(tmp_path / "df.pkl")
    df.save(p)
    df2 = CorpusDF.load(p)
    assert df2.num_docs == 2
    assert df2.df == df.df
    assert df2.df[("b", "c")] == 2.0


def test_df_counts_documents_not_occurrences():
    # "a" appears twice in doc 1 but df counts docs containing it.
    df = CorpusDF.from_refs([[toks("a a b"), toks("a c")], [toks("a d")]])
    assert df.df[("a",)] == 2.0
    assert df.df[("b",)] == 1.0


def test_reward_vector_ordering_stable():
    # Distinct refs per doc keep idf > 0 (an ngram in every doc has idf = 0).
    gts = {f"v{i}": [toks(f"a{i} b{i} c{i} d{i}")] for i in range(5)}
    res = {
        f"v{i}": [toks(f"a{i} b{i} c{i} d{i}") if i % 2 == 0 else toks("x y z w")]
        for i in range(5)
    }
    _, per = CiderD().compute_score(gts, res)
    np.testing.assert_allclose(per, [10.0, 0.0, 10.0, 0.0, 10.0], atol=1e-9)


def test_idf_zero_for_ubiquitous_ngrams():
    # An n-gram appearing in every document has idf = 0 and contributes nothing.
    gts = {f"v{i}": [toks("a b c d")] for i in range(5)}
    res = {f"v{i}": [toks("a b c d")] for i in range(5)}
    mean, _ = CiderD().compute_score(gts, res)
    np.testing.assert_allclose(mean, 0.0, atol=1e-12)


# ---- native (C++ merge-join kernel) vs Python CiderD parity -----------------
#
# CaptionScorer defaults use_native=True, so eval/validation CIDEr-D — the
# best-checkpoint selection signal — routes through the string-interning /
# df-upload adapter in metrics/native_cider.py by default. These tests pin
# the adapter against the Python oracle; the kernel accumulates per-id
# scores in float32 (documented at NativeCiderD.compute_score), hence the
# ~1e-8 relative tolerance rather than exact equality.

_NATIVE_TOL = dict(rtol=1e-6, atol=1e-7)  # f32 kernel accumulation


def _native(gts, df):
    from cst_captioning_tpu.metrics.native_cider import NativeCiderD

    n = NativeCiderD.build(gts, df)
    if n is None:
        pytest.skip("native creward library unavailable on this host")
    return n


def _parity_case():
    gts = {
        "v1": [toks("a man rides a horse"), toks("a person rides a horse")],
        "v2": [toks("a cat sits on a mat")],
        "v3": [toks("two dogs play in the park")],
    }
    res = {
        "v1": [toks("a man rides a horse")],
        "v2": [toks("a cat sits")],
        "v3": [toks("dogs play fetch")],
    }
    return gts, res


@pytest.mark.parametrize("mode", ["corpus", "corpus_df"])
def test_native_ciderd_matches_python_oracle(mode):
    """Both df modes: df='corpus' (eval semantics — df over the pools
    being scored) and a precomputed CorpusDF forwarded as-is."""
    gts, res = _parity_case()
    if mode == "corpus":
        df = "corpus"
    else:
        df = CorpusDF.from_refs(list(gts.values()))
    native = _native(gts, df)
    got = native.compute_score(res)
    assert got is not None
    mean_n, per_n = got
    mean_p, per_p = CiderD(df=df).compute_score(gts, res)
    np.testing.assert_allclose(per_n, per_p, **_NATIVE_TOL)
    np.testing.assert_allclose(mean_n, mean_p, **_NATIVE_TOL)


def test_native_ciderd_oov_hypothesis_words():
    """Hypothesis words never seen in any reference intern to fresh ids;
    they must contribute zero matches, exactly like the Python scorer
    (and not crash the kernel's merge join)."""
    gts = {
        "v1": [toks("a man rides a horse")],
        "v2": [toks("a cat sits on a mat")],
    }
    res = {
        "v1": [toks("a man rides a zeppelin wombat")],  # OOV tail
        "v2": [toks("qq ww ee rr")],                     # fully OOV
    }
    native = _native(gts, "corpus")
    got = native.compute_score(res)
    assert got is not None
    mean_n, per_n = got
    mean_p, per_p = CiderD(df="corpus").compute_score(gts, res)
    np.testing.assert_allclose(per_n, per_p, **_NATIVE_TOL)
    np.testing.assert_allclose(per_n[1], 0.0, atol=1e-7)
    np.testing.assert_allclose(mean_n, mean_p, **_NATIVE_TOL)


def test_native_ciderd_id_mismatch_falls_back_to_none():
    """compute_score refuses a res pool it was not prepared for (the
    df='corpus' semantics depend on the id set): the scorer then uses the
    Python oracle. Both the subset and superset directions refuse."""
    gts, res = _parity_case()
    native = _native(gts, "corpus")
    subset = {"v1": res["v1"]}
    assert native.compute_score(subset) is None
    superset = dict(res, v9=[toks("new clip")])
    assert native.compute_score(superset) is None
    # covers() is the scorer's cache-reuse predicate: exact pool only
    assert native.covers(gts)
    assert not native.covers({"v1": gts["v1"]})
    # and the prepared pool still scores after the refusals
    assert native.compute_score(res) is not None


def test_native_ciderd_f32_tolerance_is_tight():
    """The documented kernel contract: per-id divergence from the Python
    (float64) oracle stays at f32 accumulation scale (~1e-8 relative for
    O(10) scores) — if this drifts, best-checkpoint selection could flip
    between the native and fallback paths."""
    gts = {f"v{i}": [toks(f"w{i} x{i} y{i} z{i} common")]
           for i in range(8)}
    res = {f"v{i}": [toks(f"w{i} x{i} y{i} z{i} common")]
           for i in range(8)}
    native = _native(gts, "corpus")
    got = native.compute_score(res)
    assert got is not None
    _, per_n = got
    _, per_p = CiderD(df="corpus").compute_score(gts, res)
    # identical hyp/ref: scores are O(10); 1e-6 absolute ≈ 1e-7 relative
    np.testing.assert_allclose(per_n, per_p, rtol=0, atol=1e-5)
    assert np.max(np.abs(per_n - per_p)) < 1e-5
