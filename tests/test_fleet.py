"""Fleet-wide postmortem merge (obs/fleet.py) + the --postmortem run-dir CLI.

Covers the PR 13 acceptance criteria:

- the committed 2-proc fixture (scripts/make_fleet_fixture.py) merges into
  one skew-corrected timeline: +5 s victim clock recovered via the anchor
  tables, trip attributed to the victim's nonfinite step, ``lost=[...]``
  meta naming the victim host, the dcn_stall interleaved;
- skew-attribution edge cases: single-proc pass-through, a missing proc
  yields ``missing_procs`` (degraded merge, survivors still render), a
  tampered bundle is excluded AND reported, an anchor-free legacy bundle
  merges with ``skew="unknown"`` instead of crashing;
- straggler naming from per-step corrected lag on synthetic bundles with a
  known injected offset;
- the chaos acceptance run: a 2-sim-host partial preemption mid-RL-epoch
  leaves per-proc bundles that merge into a fleet timeline naming the
  victim host and the trip step, and the CLI renders it.
"""

from __future__ import annotations

import json
import math
import os

import pytest

from cst_captioning_tpu import obs
from cst_captioning_tpu.cli import obs_report as cli
from cst_captioning_tpu.config.config import (
    DataConfig,
    EvalConfig,
    ExperimentConfig,
    MeshConfig,
    ModelConfig,
    RLConfig,
    TrainConfig,
)
from cst_captioning_tpu.data import CaptionDataset, make_synthetic_dataset
from cst_captioning_tpu.obs import recorder
from cst_captioning_tpu.obs.fleet import (
    discover_bundles,
    list_bundles,
    merge_bundles,
    render_fleet,
    select_latest,
)
from cst_captioning_tpu.obs.report import load_postmortem
from cst_captioning_tpu.resilience import Fault, FaultPlan, durable
from cst_captioning_tpu.train.trainer import Trainer

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "postmortem_fleet")

# a plausible wall-clock epoch for synthetic bundles (anchors make ring ts
# self-describing, so any positive origin works)
T0 = 1.7e9


@pytest.fixture(autouse=True)
def _clean_obs():
    """Recorder + registry are process-global; every test gets fresh ones."""
    recorder.shutdown()
    obs.REGISTRY.reset()
    yield
    recorder.shutdown()
    obs.shutdown()
    obs.REGISTRY.reset()


# ---- synthetic bundle builders ----------------------------------------------


def _ring_row(step, ts, loss=2.0, phase="rl", anomalies=None):
    row = {"step": step, "phase": phase, "ts": ts, "loss": loss,
           "grad_norm": 1.0}
    if anomalies:
        row["anomalies"] = list(anomalies)
    return row


def _meta(proc, world, host, ring, *, reason="unit", wall0=T0,
          anchors="start", **fields):
    """A schema-2 meta dict whose start anchor maps ring ts to itself
    (pc = ts - wall0); ``anchors=None`` strips every schema-2 field to
    simulate a legacy (pre-anchor) bundle."""
    m = {
        "schema": 2,
        "reason": reason,
        "run": "synth",
        "proc": proc,
        "world": world,
        "host": host,
        "capacity": 64,
        "steps": [r["step"] for r in ring],
        "anchors": [[0.0, wall0]] if anchors == "start" else anchors,
        "dumped_ts": wall0 + 999.0,
    }
    if anchors is None:
        for k in ("schema", "anchors", "proc", "world", "host"):
            del m[k]
    m.update(fields)
    return m


def _write_bundle(bdir, ring, meta, *, events=(), registry=None):
    """Write a bundle the way obs/recorder.py does (durable blobs + sha256
    manifest) so ``_verify_bundle`` passes on untampered ones."""
    os.makedirs(bdir)
    blobs = {
        "ring.jsonl": "".join(
            json.dumps(r) + "\n" for r in ring).encode(),
        "registry.json": json.dumps(
            registry or {"counters": {}, "gauges": {}, "histograms": {}}
        ).encode(),
        "events_tail.jsonl": "".join(
            json.dumps(e) + "\n" for e in events).encode(),
        "config.json": b"{}",
        "meta.json": json.dumps(meta).encode(),
    }
    for name, blob in blobs.items():
        durable.write_bytes_durable(os.path.join(bdir, name), blob)
    durable.write_manifest(bdir, blobs)
    return bdir


# ---- committed fixture -------------------------------------------------------


def test_committed_fixture_merges_with_skew_and_trip():
    fleet = merge_bundles(FIXTURE)
    assert fleet["merged_procs"] == [0, 1]
    assert fleet["missing_procs"] == [] and fleet["excluded"] == []
    assert not fleet["degraded"]
    assert fleet["world"] == 2 and fleet["run"] == "fleetfix"

    # proc1's wall clock was skewed +5 s when the fixture was generated;
    # the anchored median-delta model recovers it (ring records are a few
    # tens of ms apart, so the tolerance is generous)
    info = {i["proc"]: i for i in fleet["procs_info"]}
    assert info[0]["skew"] == "anchored" and info[1]["skew"] == "anchored"
    assert info[0]["offset_s"] == 0.0
    assert 4.0 < info[1]["offset_s"] < 6.0

    # trip: the victim's nonfinite loss at rl step 7, flagged in-ring by
    # its anomaly detector
    trip = fleet["trip"]
    assert trip["proc"] == 1 and trip["host"] == "host1"
    assert trip["phase"] == "rl" and trip["step"] == 7
    assert "nonfinite" in trip["kinds"] and trip["source"] == "ring"

    # the survivor's peer-loss meta named the victim
    assert fleet["victim_hosts"] == [1]

    # survivor's dcn_stall made the fleet event stream
    assert any(
        e["event"] == "dcn_stall" and e["proc"] == 0 for e in fleet["events"]
    )

    text = render_fleet(fleet)
    assert "[TRIP]" in text and "dcn_stall" in text
    assert "victim host(s): [1]" in text
    assert "peer_loss" in text and "divergence_nonfinite" in text


def test_committed_fixture_listing():
    rows = list_bundles(FIXTURE)
    assert {r["proc"] for r in rows} == {0, 1}
    assert all(r["verified"] for r in rows)
    by_proc = {r["proc"]: r for r in rows}
    assert by_proc[0]["reason"] == "peer_loss"
    assert by_proc[1]["reason"] == "divergence_nonfinite"
    assert by_proc[1]["step"] == 7 and by_proc[1]["host"] == "host1"


# ---- discovery / selection ---------------------------------------------------


def test_latest_bundle_per_proc_wins(tmp_path):
    d = str(tmp_path)
    ring = [_ring_row(i, T0 + 0.1 * i) for i in range(1, 4)]
    _write_bundle(os.path.join(d, "postmortem_01_chaos_nan"), ring,
                  _meta(0, 1, "h0", ring, reason="chaos_nan"))
    _write_bundle(os.path.join(d, "postmortem_02_peer_loss"), ring,
                  _meta(0, 1, "h0", ring, reason="peer_loss"))
    found = discover_bundles(d)
    assert [os.path.basename(b) for b in found[0]] == [
        "postmortem_01_chaos_nan", "postmortem_02_peer_loss"]
    latest = select_latest(found)
    assert latest[0].endswith("postmortem_02_peer_loss")
    fleet = merge_bundles(d)
    assert fleet["procs_info"][0]["reason"] == "peer_loss"
    # --list still enumerates BOTH dumps
    assert [r["reason"] for r in list_bundles(d)] == [
        "chaos_nan", "peer_loss"]


# ---- skew edge cases ---------------------------------------------------------


def test_single_proc_merge_is_a_passthrough(tmp_path):
    d = str(tmp_path)
    ring = [_ring_row(i, T0 + 0.1 * i) for i in range(1, 6)]
    _write_bundle(os.path.join(d, "postmortem_01_preempt"), ring,
                  _meta(0, 1, "solo", ring, reason="preempt", phase="rl",
                        step=5))
    fleet = merge_bundles(d)
    assert fleet["merged_procs"] == [0] and fleet["world"] == 1
    assert fleet["missing_procs"] == [] and not fleet["degraded"]
    assert [s["step"] for s in fleet["steps"]] == [1, 2, 3, 4, 5]
    # one clock: no cross-host lag model, no straggler
    assert all(s["cells"]["0"]["lag_s"] is None for s in fleet["steps"])
    assert fleet["straggler"] is None
    # a clean ring falls back to the dump meta for the trip story
    assert fleet["trip"]["source"] == "meta"
    assert fleet["trip"]["reason"] == "preempt"
    render_fleet(fleet)


def test_missing_proc_yields_degraded_merge(tmp_path):
    d = str(tmp_path)
    ring = [_ring_row(i, T0 + 0.1 * i) for i in range(1, 4)]
    # the bundle claims world=2 but proc1 never dumped (died pre-flush)
    _write_bundle(os.path.join(d, "postmortem_01_peer_loss"), ring,
                  _meta(0, 2, "h0", ring, reason="peer_loss", lost=[1]))
    fleet = merge_bundles(d)
    assert fleet["world"] == 2
    assert fleet["missing_procs"] == [1]
    assert fleet["degraded"]
    assert fleet["merged_procs"] == [0]
    assert fleet["victim_hosts"] == [1]
    text = render_fleet(fleet)
    assert "DEGRADED MERGE" in text and "MISSING PROCS: [1]" in text


def test_tampered_bundle_is_excluded_and_reported(tmp_path):
    d = str(tmp_path)
    ring0 = [_ring_row(i, T0 + 0.1 * i) for i in range(1, 6)]
    ring1 = [_ring_row(i, T0 + 3.0 + 0.1 * i) for i in range(1, 6)]
    _write_bundle(os.path.join(d, "postmortem_01_peer_loss"), ring0,
                  _meta(0, 2, "h0", ring0, reason="peer_loss"))
    b1 = _write_bundle(
        os.path.join(d, "proc1", "postmortem_01_divergence_spike"), ring1,
        _meta(1, 2, "h1", ring1, reason="divergence_spike", wall0=T0 + 3.0))
    with open(os.path.join(b1, "ring.jsonl"), "a") as f:
        f.write('{"step": 999, "phase": "rl", "ts": 0.0, "loss": 0.0}\n')
    fleet = merge_bundles(d)
    assert fleet["merged_procs"] == [0]
    assert fleet["degraded"] and fleet["missing_procs"] == []
    (ex,) = fleet["excluded"]
    assert ex["proc"] == 1 and ex["problems"]
    assert any("ring.jsonl" in p for p in ex["problems"])
    text = render_fleet(fleet)
    assert "EXCLUDED proc1" in text
    # --list flags the tamper too
    rows = {r["proc"]: r for r in list_bundles(d)}
    assert rows[0]["verified"] and not rows[1]["verified"]


def test_legacy_anchor_free_bundle_merges_with_unknown_skew(tmp_path):
    d = str(tmp_path)
    ring0 = [_ring_row(i, T0 + 0.1 * i) for i in range(1, 6)]
    # proc1 predates schema 2: no anchors, no proc/world/host in meta
    ring1 = [_ring_row(i, T0 + 7.0 + 0.1 * i) for i in range(1, 6)]
    _write_bundle(os.path.join(d, "postmortem_01_peer_loss"), ring0,
                  _meta(0, 2, "h0", ring0, reason="peer_loss"))
    _write_bundle(os.path.join(d, "proc1", "postmortem_01_old"), ring1,
                  _meta(1, 2, "h1", ring1, reason="old", anchors=None))
    fleet = merge_bundles(d)
    assert fleet["merged_procs"] == [0, 1] and not fleet["degraded"]
    info = {i["proc"]: i for i in fleet["procs_info"]}
    assert info[0]["skew"] == "anchored"
    assert info[1]["skew"] == "unknown"
    # an untrusted clock gets no offset model and no lag attribution
    assert info[1]["offset_s"] == 0.0
    assert fleet["straggler"] is None
    for s in fleet["steps"]:
        for cell in s["cells"].values():
            assert cell["lag_s"] is None
    render_fleet(fleet)


def test_injected_offset_recovered_and_straggler_named(tmp_path):
    d = str(tmp_path)
    # proc1's clock runs +5 s ahead; on steps 6-8 it ALSO genuinely trails
    # the fleet by 0.5 s (a straggler, not a clock artifact)
    ring0 = [_ring_row(i, T0 + 0.1 * i) for i in range(1, 9)]
    ring1 = [
        _ring_row(i, T0 + 5.0 + 0.1 * i + (0.5 if i >= 6 else 0.0))
        for i in range(1, 9)
    ]
    ring1[-1]["loss"] = math.nan
    _write_bundle(os.path.join(d, "postmortem_01_peer_loss"), ring0,
                  _meta(0, 2, "h0", ring0, reason="peer_loss"))
    _write_bundle(
        os.path.join(d, "proc1", "postmortem_01_divergence_nonfinite"),
        ring1,
        _meta(1, 2, "h1", ring1, reason="divergence_nonfinite",
              wall0=T0 + 5.0))
    fleet = merge_bundles(d)
    info = {i["proc"]: i for i in fleet["procs_info"]}
    # median delta over 8 shared keys: five 5.0s outvote three 5.5s
    # (tolerances sized for float64 resolution at wall-clock magnitude)
    assert info[1]["offset_s"] == pytest.approx(5.0, abs=1e-5)
    st = fleet["straggler"]
    assert st is not None and st["proc"] == 1 and st["host"] == "h1"
    assert st["max_lag_s"] == pytest.approx(0.5, abs=1e-5)
    # the residual lag shows on the straggling rows only
    by_step = {s["step"]: s for s in fleet["steps"]}
    assert by_step[3]["cells"]["1"]["lag_s"] == pytest.approx(0.0, abs=1e-5)
    assert by_step[7]["cells"]["1"]["lag_s"] == pytest.approx(0.5, abs=1e-5)
    # nonfinite ring loss trips even without a detector verdict
    trip = fleet["trip"]
    assert trip["proc"] == 1 and trip["step"] == 8
    assert trip["kinds"] == ["nonfinite"] and trip["source"] == "ring"
    text = render_fleet(fleet)
    assert "straggler: proc1" in text and "lag+0.500" in text


def test_trip_is_earliest_in_corrected_time_not_raw(tmp_path):
    d = str(tmp_path)
    # proc0 judged at step 8; proc1's clock is +100 s ahead so its raw ts
    # are all LATER, but corrected its step-3 verdict precedes proc0's
    ring0 = [
        _ring_row(i, T0 + 0.1 * i,
                  anomalies=(["loss_z"] if i == 8 else None))
        for i in range(1, 9)
    ]
    ring1 = [
        _ring_row(i, T0 + 100.0 + 0.1 * i,
                  anomalies=(["grad_norm_z"] if i == 3 else None))
        for i in range(1, 9)
    ]
    _write_bundle(os.path.join(d, "postmortem_01_divergence_spike"), ring0,
                  _meta(0, 2, "h0", ring0, reason="divergence_spike"))
    _write_bundle(
        os.path.join(d, "proc1", "postmortem_01_divergence_spike"), ring1,
        _meta(1, 2, "h1", ring1, reason="divergence_spike",
              wall0=T0 + 100.0))
    fleet = merge_bundles(d)
    trip = fleet["trip"]
    assert trip["proc"] == 1 and trip["step"] == 3
    assert trip["kinds"] == ["grad_norm_z"]


def test_events_tail_interleaves_at_corrected_times(tmp_path):
    d = str(tmp_path)
    ring0 = [_ring_row(i, T0 + 1.0 * i) for i in range(1, 5)]
    ring1 = [_ring_row(i, T0 + 50.0 + 1.0 * i) for i in range(1, 5)]
    # proc1's stall happened between its steps 2 and 3 (raw ts T0+52.5);
    # span-stream events are wall-clock, so only the offset applies
    ev = {"event": "dcn_stall", "ts": T0 + 52.5, "op": "allreduce",
          "dur_s": 3.0}
    noise = {"event": "phase", "ts": T0 + 52.6, "name": "rl"}
    _write_bundle(os.path.join(d, "postmortem_01_peer_loss"), ring0,
                  _meta(0, 2, "h0", ring0, reason="peer_loss"))
    _write_bundle(
        os.path.join(d, "proc1", "postmortem_01_peer_loss"), ring1,
        _meta(1, 2, "h1", ring1, reason="peer_loss", wall0=T0 + 50.0),
        events=[ev, noise])
    fleet = merge_bundles(d)
    (got,) = fleet["events"]  # span noise filtered, the stall kept
    assert got["event"] == "dcn_stall" and got["proc"] == 1
    assert got["t_s"] == pytest.approx(1.5, abs=1e-4)  # t0 is step 1
    assert "~ t+1.500s proc1 dcn_stall" in render_fleet(fleet)


def test_merge_bundles_raises_on_empty_dir(tmp_path):
    with pytest.raises(FileNotFoundError):
        merge_bundles(str(tmp_path))


# ---- the CLI -----------------------------------------------------------------


def test_cli_run_dir_renders_fleet_timeline(capsys):
    assert cli.main(["--postmortem", FIXTURE]) == 0
    out = capsys.readouterr().out
    assert "fleet postmortem: fleetfix" in out
    assert "procs merged: 2/2" in out and "[TRIP]" in out


def test_cli_single_bundle_dir_still_renders_per_process(capsys):
    (bundle,) = [
        n for n in sorted(os.listdir(FIXTURE))
        if n.startswith("postmortem_")
    ]
    assert cli.main(["--postmortem", os.path.join(FIXTURE, bundle)]) == 0
    out = capsys.readouterr().out
    assert "manifest verified" in out
    assert "fleet postmortem" not in out


def test_cli_list_mode_and_json(capsys):
    assert cli.main(["--postmortem", FIXTURE, "--list"]) == 0
    out = capsys.readouterr().out
    assert "peer_loss" in out and "divergence_nonfinite" in out
    assert cli.main(["--postmortem", FIXTURE, "--list", "--json"]) == 0
    rows = json.loads(capsys.readouterr().out)
    assert {r["proc"] for r in rows} == {0, 1}


def test_cli_fleet_json_carries_merged_structure(capsys):
    assert cli.main(["--postmortem", FIXTURE, "--json"]) == 0
    fleet = json.loads(capsys.readouterr().out)
    assert fleet["trip"]["proc"] == 1 and fleet["victim_hosts"] == [1]
    assert fleet["steps"] and fleet["procs_info"]


def test_cli_errors(tmp_path, capsys):
    assert cli.main(["--postmortem", str(tmp_path / "nope")]) == 2
    assert cli.main(["--postmortem", str(tmp_path), "--list"]) == 2
    capsys.readouterr()
    with pytest.raises(SystemExit):
        cli.main(["--list"])  # --list needs --postmortem


# ---- chaos acceptance: partial preemption -> fleet forensic ------------------


@pytest.fixture(scope="module")
def synth_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("fleetsynth")
    return make_synthetic_dataset(
        str(out), num_videos=12, num_topics=3, vocab_words=20,
        modalities={"resnet": 16}, max_frames=4, seed=5,
    )


@pytest.fixture(scope="module")
def datasets(synth_dir):
    return CaptionDataset(
        synth_dir["info_json"], {"resnet": synth_dir["resnet"]}, "train", 4
    )


def make_cfg(ckpt_dir: str, vocab_size: int, **train_kw) -> ExperimentConfig:
    train_kw.setdefault("eval_every_epochs", 100)
    return ExperimentConfig(
        name="fleet",
        model=ModelConfig(
            vocab_size=vocab_size, modalities=(("resnet", 16),),
            d_embed=16, d_hidden=16, d_att=8, encoder="temporal_attention",
            dropout=0.0, max_len=8, max_frames=4, dtype="float32",
        ),
        data=DataConfig(batch_size=2, seq_per_vid=1),
        train=TrainConfig(
            lr=5e-3, grad_clip=5.0, ckpt_dir=ckpt_dir, seed=0,
            log_every_steps=1, epochs=1, **train_kw,
        ),
        rl=RLConfig(
            enabled=True, num_rollouts=2, lr=1e-3, epochs=2,
            baseline="greedy", pipelined=True,
        ),
        eval=EvalConfig(beam_size=1, max_len=8),
        mesh=MeshConfig(num_devices=2),
    )


def test_chaos_partial_preempt_merges_into_fleet_timeline(datasets,
                                                          tmp_path_factory):
    """ISSUE acceptance: a 2-sim-host run losing host 1 mid-RL-epoch leaves
    per-proc bundles that ``merge_bundles`` turns into one fleet timeline
    naming the victim host and the trip step, and the CLI renders it."""
    train_ds = datasets
    d = str(tmp_path_factory.mktemp("fleetchaos"))
    obs_dir = os.path.join(d, "obs")
    cfg = make_cfg(d, len(train_ds.vocab), health=True, health_sim_hosts=2,
                   elastic="degraded", obs=True, obs_dir=obs_dir,
                   recorder_steps=32)
    tr = Trainer(cfg, train_ds, None, log_path=d + "/ev.jsonl")
    try:
        tr.train_xe()
        # 5 RL batches/epoch; visit 6 = second update of epoch 2 -> the
        # peer loss lands mid-epoch and the run continues on 1 device
        with FaultPlan(
            [Fault("rl.step", "partial_preempt", at=6, host=1)]
        ).activate():
            tr.train_rl()
        assert tr.rl_epochs == 2
    finally:
        tr.close()

    # the surviving process dumped the chaos hook's bundle AND the
    # peer-loss drain's bundle; the drain one is its latest
    latest = select_latest(discover_bundles(obs_dir))
    assert latest[0].endswith("peer_loss")
    pm0 = load_postmortem(latest[0])
    assert pm0["verified"]
    assert pm0["meta"]["lost"] == [1]
    rl_ring = [r for r in pm0["ring"] if r["phase"] == "rl"]
    assert rl_ring

    # the victim process died before the drain; reconstruct the bundle a
    # real proc 1 would have dumped (same rl step clock, its last step
    # nonfinite) via a second recorder writing the proc1/ layout. No
    # detector: replayed steps have artificial gaps that would earn bogus
    # stall verdicts — the merge's nonfinite fallback attributes the trip.
    fr1 = recorder.FlightRecorder(
        32, os.path.join(obs_dir, "proc1"), run=pm0["meta"]["run"],
        proc=1, world=2, host="simhost1",
    )
    trip_step = rl_ring[-1]["step"]
    for r in rl_ring:
        loss = (math.nan if r["step"] == trip_step
                else r.get("rl_loss", r.get("loss", 1.0)))
        fr1.record(r["step"], "rl", {"rl_loss": loss, "grad_norm": 1.0})
    assert fr1.postmortem("divergence_nonfinite", phase="rl",
                          step=trip_step) is not None
    fr1.close()

    fleet = merge_bundles(obs_dir)
    assert fleet["merged_procs"] == [0, 1]
    assert fleet["world"] == 2 and not fleet["degraded"]
    assert fleet["victim_hosts"] == [1]
    trip = fleet["trip"]
    assert trip["proc"] == 1 and trip["host"] == "simhost1"
    assert trip["step"] == trip_step and "nonfinite" in trip["kinds"]
    text = render_fleet(fleet)
    assert "[TRIP]" in text and "victim host(s): [1]" in text

    rows = list_bundles(obs_dir)
    assert {r["reason"] for r in rows} >= {
        "chaos_partial_preempt", "peer_loss", "divergence_nonfinite"}
    assert cli.main(["--postmortem", obs_dir]) == 0
